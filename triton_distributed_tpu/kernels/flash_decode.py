"""Distributed GQA flash-decode: split-KV attention + LSE-combine (SP/CP).

Reference: python/triton_dist/kernels/nvidia/flash_decode.py —
``kernel_gqa_fwd_batch_decode_split_kv`` (:130-280, online-softmax partial
attention over KV splits), intra-rank combine (:393-451), inter-rank
combine merging per-rank (out, lse) partials (:482-566), host entries
``gqa_fwd_batch_decode{,_intra_rank}`` (:763-930); the SP layer
sp_flash_decode_layer.py:78-184 shards the KV cache over ranks.

TPU re-design:

* The reference splits KV across SMs and re-combines to fill the GPU.
  On TPU one core runs the grid sequentially with VMEM-resident
  accumulators, so "split-KV + intra-rank combine" collapses into a
  single Pallas kernel whose innermost grid dimension walks KV blocks,
  carrying (m, l, acc) online-softmax state in scratch — the classic
  TPU flash-attention schedule. No intra-rank combine kernel is needed;
  the hardware pipeline plays the role of the split scheduler.
* What remains distributed is exactly the reference's inter-rank stage:
  each rank decodes over its local KV shard producing (out, lse), the
  partials are all-gathered (small payload — the LL-allgather regime),
  and a combine re-normalizes with the global LSE. Numerically this is
  the ring-attention / blockwise-softmax merge, done once over ranks
  (≡ kernel_inter_rank_gqa_fwd_batch_decode_combine_kv).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.config import interp_key, local_interpret
from triton_distributed_tpu.lang.launch import shmem_call
from triton_distributed_tpu.utils.testing import chaos_delay

NEG_INF = -1.0e30  # finite -inf stand-in: exp(NEG_INF - m) == 0 without NaNs


def _n_valid_blocks(kv_len, block_k):
    """ceil(kv_len / block_k), floored at 1 — even an empty row walks one
    block (its scores are fully masked; lse comes back NEG_INF)."""
    return jnp.maximum(jax.lax.div(kv_len + block_k - 1, block_k), 1)


def _decode_kernel(
    scale, soft_cap, block_k, kv_lens_ref, q_ref, k_ref, v_ref,
    out_ref, lse_ref, m_ref, l_ref, acc_ref,
    ks_ref=None, vs_ref=None,
):
    """One (batch, kv_head) group; grid dim 2 walks KV blocks sequentially.

    q_ref: (1, 1, G, D) — the GQA query group of this kv head.
    k_ref/v_ref: (1, block_k, D) — current KV block of this head, read
    directly from the cache viewed as (B, S, Hkv·D) (a free reshape of the
    native layout — no transposed copy; the block DMA slices the head's
    D-column window).
    Carries (m, l, acc) in f32 scratch across the KV walk (the online
    softmax of the reference's split_kv kernel, :207-258).

    This STATIC grid walks the cache CAPACITY: blocks past
    ceil(kv_lens[b]/block_k) skip their COMPUTE (the ``pl.when``
    below) but their DMA still lands — Mosaic's pipeline fetches every
    BlockSpec window, and index-map clamping does not reliably elide
    the copies (measured). Length-proportional HBM traffic lives in
    :func:`_decode_kernel_dyn` (the native-layout default); this
    kernel serves the reference-style bshd view and unaligned
    geometries, where capacity-proportional reads are the price of the
    strided window.

    ``ks_ref``/``vs_ref``: optional (…, 1, block_k) f32 per-row scale
    blocks — int8 KV mode, with the same exact per-column scale folds
    as ``_decode_kernel_dyn``'s quant path.
    """
    b = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = kv_lens_ref[b]

    @pl.when(ki < _n_valid_blocks(kv_len, block_k))
    def _compute():
        q = q_ref[0, 0]                        # (G, D), input dtype
        # KV blocks arrive as (1, block_k, D) [bshd view] or (1, 1,
        # block_k, D) [bhsd]; flatten the unit block dims either way.
        k = k_ref[...].reshape(block_k, q.shape[-1])
        v = v_ref[...].reshape(block_k, q.shape[-1])
        if ks_ref is not None:
            # widen WITHOUT the scale; fold per-column below (exact)
            k = k.astype(jnp.bfloat16)
            v = v.astype(jnp.bfloat16)

        # Inputs stay in their native (bf16) dtype so the MXU runs at
        # full rate; accumulation is f32 via preferred_element_type.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                              # (G, block_k) f32
        if ks_ref is not None:
            s = s * ks_ref[...].reshape(1, block_k)
        if soft_cap > 0.0:
            s = soft_cap * jnp.tanh(s / soft_cap)

        pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = pos < kv_len
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:]                      # (G, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # mask p explicitly: in an ALL-masked block m_new == NEG_INF and
        # exp(s − m_new) degenerates to 1, which would make an empty
        # row's output depend on how many blocks were walked — with the
        # mask, l stays 0 and _finish emits exact zeros + NEG_INF lse
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)   # (G, block_k)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        if vs_ref is not None:
            # fold V's per-row scale into p (rank-1 exactness)
            p = p * vs_ref[...].reshape(1, block_k)
        acc_ref[:] = alpha * acc_ref[:] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        l = l_ref[:]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        out_ref[0, 0] = (acc_ref[:] / safe_l).astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l > 0.0, m_ref[:] + jnp.log(safe_l), jnp.full_like(l, NEG_INF)
        )


def _decode_kernel_dyn(
    scale, soft_cap, block_k, n_bufs, g, d, quant, *refs,
):
    """Dynamic-trip-count decode: grid is (B, Hkv) ONLY; the KV walk is
    an in-kernel ``fori_loop`` over ceil(kv_lens[b]/block_k) blocks with
    manually double-buffered HBM→VMEM DMAs.

    Why not a (B, Hkv, S/block_k) grid with index-map clamping: a grid
    walks the cache CAPACITY — every invalid tail block still costs a
    grid step (measured 0.6–1.4 µs each at serving shapes), and Mosaic's
    revisit-skip does not reliably elide the clamped copies. A dynamic
    loop bound issues exactly ceil(len/block_k) DMAs and zero extra
    steps — HBM reads and overhead both scale with the TRUE lengths
    (≡ the reference kernel's dynamic ``for`` over kv chunks,
    flash_decode.py:207-216; same discipline as the count-bounded MoE
    chunk transport, moe_dispatch.py).

    k_hbm/v_hbm: full (B, Hkv, S, D) refs in ANY space — one (block_k,
    D) contiguous run is DMA'd per loop step into the rotating VMEM
    slots. The pipeline runs ACROSS grid steps: each iteration issues
    the NEXT block's copy — the last iteration of a (b, h) group
    prefetches the next group's block 0 — and ``slot_ref`` (persistent
    SMEM) carries the slot rotation over the group boundary, so the DMA
    engine never drains between groups (without this, a one-block group
    exposes its full copy latency every grid step: measured 2.4 ms vs
    1.5 ms for the whole walk at B=128, Hkv=8, S=2048).

    ``quant``: int8 KV mode — k_hbm/v_hbm are int8 with per-(b, h, s)
    f32 scale planes. The scales fold EXACTLY into the softmax
    (per-column into s before soft-capping, per-column into p before
    the PV dot), so the only extra VPU work is two int8→bf16 widens
    and two (G, block_k)-sized multiplies — the D-sized dequant
    multiply never happens. Halves the KV bytes in HBM and on the DMA
    stream (2× the context per chip). The scale planes arrive as
    PIPELINED (1, 1, 1, S) VMEM blocks — Mosaic's grid pipeline
    prefetches each (b, h) row's whole scale vector (8 KB at S=2048)
    — NOT as per-block manual DMAs: at serving batch sizes the walk is
    DMA-COUNT bound (thousands of 0.1-µs-class issues), and the two
    4 KB scale copies per block doubled the count for 3% of the bytes
    (measured: see docs/PERF.md round-5 serving attention section).
    """
    if quant:
        (kv_lens_ref, q_ref, k_hbm, v_hbm, ks_ref, vs_ref,
         out_ref, lse_ref,
         kbuf, vbuf, sem_k, sem_v, slot_ref, m_ref, l_ref, acc_ref) = refs
    else:
        (kv_lens_ref, q_ref, k_hbm, v_hbm, out_ref, lse_ref,
         kbuf, vbuf, sem_k, sem_v, slot_ref, m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    h = pl.program_id(1)
    nb_total = pl.num_programs(0)
    nh = pl.num_programs(1)
    kv_len = kv_lens_ref[b]
    # clamp at capacity: a caller whose lens overran the cache (e.g.
    # append_kv increments past a full cache) must not DMA past the end
    nb = jnp.minimum(
        _n_valid_blocks(kv_len, block_k),
        k_hbm.shape[2] // block_k,
    )
    q = q_ref[0, 0]                            # (G, D)

    def dma(bb, hh, j, slot):
        win = pl.ds(j * block_k, block_k)
        return [
            pltpu.make_async_copy(
                k_hbm.at[bb, hh, win], kbuf.at[slot], sem_k.at[slot]
            ),
            pltpu.make_async_copy(
                v_hbm.at[bb, hh, win], vbuf.at[slot], sem_v.at[slot]
            ),
        ]

    @pl.when(jnp.logical_and(b == 0, h == 0))
    def _warmup():                             # first block of the run
        slot_ref[0] = 0
        for cp in dma(0, 0, 0, 0):
            cp.start()

    s0 = slot_ref[0]                           # this group's start slot
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(j, _):
        slot = jax.lax.rem(s0 + j, n_bufs)
        nxt = jax.lax.rem(s0 + j + 1, n_bufs)

        # issue the NEXT block's copy BEFORE waiting on this one: the
        # engine queues it behind the in-flight copy and rolls straight
        # into it when that completes — i.e. during this block's
        # compute. Starting after the wait leaves the engine idle for
        # the whole compute phase (measured: per-iter time = DMA +
        # compute instead of max(DMA, compute)).
        @pl.when(j + 1 < nb)
        def _prefetch_in_group():
            for cp in dma(b, h, j + 1, nxt):
                cp.start()

        # group's last block: prefetch the NEXT group's first block so
        # the copy flies while out/lse spill and the grid advances
        @pl.when(
            jnp.logical_and(
                j + 1 == nb,
                jnp.logical_or(h + 1 < nh, b + 1 < nb_total),
            )
        )
        def _prefetch_next_group():
            nb_ = jnp.where(h + 1 < nh, b, b + 1)
            nh_ = jnp.where(h + 1 < nh, h + 1, 0)
            for cp in dma(nb_, nh_, 0, nxt):
                cp.start()

        for cp in dma(b, h, j, slot):
            cp.wait()

        win = pl.ds(j * block_k, block_k)
        if quant:
            # widen WITHOUT the scale (the D-sized multiply is the
            # expensive dequant path) — scales fold per-column below
            k = kbuf[slot].astype(jnp.bfloat16)    # (block_k, D)
            v = vbuf[slot].astype(jnp.bfloat16)
            v_scale = vs_ref[0, 0, :, win]         # (1, block_k)
        else:
            k = kbuf[slot]                         # (block_k, D)
            v = vbuf[slot]
            v_scale = None
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                              # (G, block_k)
        if quant:
            # exact: scale_s is constant along each k column of the dot
            s = s * ks_ref[0, 0, :, win]           # (1, block_k) broadcast
        if soft_cap > 0.0:
            s = soft_cap * jnp.tanh(s / soft_cap)

        def update(s, p_mask):
            m = m_ref[:]
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)             # (G, block_k)
            if p_mask is not None:
                # an all-masked block degenerates exp(s − m) to 1
                p = jnp.where(p_mask, p, 0.0)
            l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
            if v_scale is not None:
                # fold V's per-row scale into p (row r of V scales the
                # whole rank-1 term p[:, r]·v[r]) — exact
                pv = (p * v_scale).astype(v.dtype)
            else:
                pv = p.astype(v.dtype)
            acc_ref[:] = alpha * acc_ref[:] + jnp.dot(
                pv, v, preferred_element_type=jnp.float32
            )
            m_ref[:] = m_new

        # interior blocks (every position valid) skip the mask chain —
        # the iota/compare/select passes over (G, block_k) f32 cost as
        # much VPU time as the whole softmax update (the kernel is
        # compute-bound at bf16 blocks); only the ragged tail pays them
        is_tail = jnp.logical_and(
            j + 1 == nb, (j + 1) * block_k > kv_len
        )

        @pl.when(is_tail)
        def _masked():
            pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            valid = pos < kv_len
            update(jnp.where(valid, s, NEG_INF), valid)

        @pl.when(jnp.logical_not(is_tail))
        def _plain():
            update(s, None)

        return 0

    jax.lax.fori_loop(0, nb, body, 0)
    slot_ref[0] = jax.lax.rem(s0 + nb, n_bufs)  # hand the rotation on
    l = l_ref[:]
    safe_l = jnp.where(l > 0.0, l, 1.0)
    out_ref[0, 0] = (acc_ref[:] / safe_l).astype(out_ref.dtype)
    lse_ref[0, 0] = jnp.where(
        l > 0.0, m_ref[:] + jnp.log(safe_l), jnp.full_like(l, NEG_INF)
    )


def _decode_kernel_dyn_mh(
    scale, soft_cap, block_k, n_bufs, hkv, g, d, *refs,
):
    """MULTIHEAD dynamic-trip INT8 decode: grid (B,) — every KV head of
    a batch row in ONE grid step.

    Round-5 measurement (docs/PERF.md): at serving batch sizes the
    per-(b, h) grid of ``_decode_kernel_dyn`` pays ~0.55 µs of
    per-group overhead (grid step, out/lse spill, q/scale pipeline
    fetch, state re-init) × B·Hkv = 1024 groups — roughly half the
    kernel's time at B=128, while the same kernel at B=4 (32 groups)
    runs at 97% of HBM SOL. Folding the Hkv heads into one step cuts
    the group count 8×: the K/V copies become single strided DMAs
    (Hkv contiguous (block_k, D) runs each), the softmax state blocks
    up to (Hkv·G, ·), and the per-head compute unrolls statically.
    Trip counts are per-ROW (all heads share kv_lens[b]) — which is
    what makes the merge natural.

    Same quant semantics as ``_decode_kernel_dyn``: int8 K/V widened
    without scales, per-column scale folds into s and p, pipelined
    (1, Hkv, 1, S) scale blocks, SMEM slot-rotation carry with
    cross-row prefetch.
    """
    (kv_lens_ref, q_ref, k_hbm, v_hbm, ks_ref, vs_ref,
     out_ref, lse_ref,
     kbuf, vbuf, sem_k, sem_v, slot_ref, m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    nb_total = pl.num_programs(0)
    kv_len = kv_lens_ref[b]
    nb = jnp.minimum(
        _n_valid_blocks(kv_len, block_k),
        k_hbm.shape[2] // block_k,
    )

    def dma(bb, j, slot):
        win = pl.ds(j * block_k, block_k)
        return [
            pltpu.make_async_copy(
                k_hbm.at[bb, :, win], kbuf.at[slot], sem_k.at[slot]
            ),
            pltpu.make_async_copy(
                v_hbm.at[bb, :, win], vbuf.at[slot], sem_v.at[slot]
            ),
        ]

    @pl.when(b == 0)
    def _warmup():
        slot_ref[0] = 0
        for cp in dma(0, 0, 0):
            cp.start()

    s0 = slot_ref[0]
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(j, _):
        slot = jax.lax.rem(s0 + j, n_bufs)
        nxt = jax.lax.rem(s0 + j + 1, n_bufs)

        @pl.when(j + 1 < nb)
        def _prefetch_in_group():
            for cp in dma(b, j + 1, nxt):
                cp.start()

        @pl.when(jnp.logical_and(j + 1 == nb, b + 1 < nb_total))
        def _prefetch_next_group():
            for cp in dma(b + 1, 0, nxt):
                cp.start()

        # chaos hook: widen the slot-rotation window between the
        # prefetch issues and this block's wait (the race-prone carry)
        chaos_delay(site="flash_decode", step=None, me=None, n=None)
        for cp in dma(b, j, slot):
            cp.wait()

        win = pl.ds(j * block_k, block_k)

        def heads(masked):
            if masked:
                pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1
                )
                valid = pos < kv_len               # (1, block_k)
            for h in range(hkv):                   # static unroll
                q = q_ref[0, h]                    # (G, D) bf16
                k = kbuf[slot, h].astype(jnp.bfloat16)
                v = vbuf[slot, h].astype(jnp.bfloat16)
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale                          # (G, block_k)
                s = s * ks_ref[0, h, :, win]
                if soft_cap > 0.0:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                if masked:
                    s = jnp.where(valid, s, NEG_INF)
                lo, hi = h * g, (h + 1) * g
                m = m_ref[lo:hi]
                m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                if masked:
                    p = jnp.where(valid, p, 0.0)
                l_ref[lo:hi] = alpha * l_ref[lo:hi] + jnp.sum(
                    p, axis=1, keepdims=True
                )
                pv = (p * vs_ref[0, h, :, win]).astype(v.dtype)
                acc_ref[lo:hi] = alpha * acc_ref[lo:hi] + jnp.dot(
                    pv, v, preferred_element_type=jnp.float32
                )
                m_ref[lo:hi] = m_new

        is_tail = jnp.logical_and(
            j + 1 == nb, (j + 1) * block_k > kv_len
        )

        @pl.when(is_tail)
        def _masked():
            heads(True)

        @pl.when(jnp.logical_not(is_tail))
        def _plain():
            heads(False)

        return 0

    jax.lax.fori_loop(0, nb, body, 0)
    slot_ref[0] = jax.lax.rem(s0 + nb, n_bufs)     # hand the rotation on
    for h in range(hkv):
        lo, hi = h * g, (h + 1) * g
        l = l_ref[lo:hi]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        out_ref[0, h] = (acc_ref[lo:hi] / safe_l).astype(out_ref.dtype)
        lse_ref[0, h] = jnp.where(
            l > 0.0, m_ref[lo:hi] + jnp.log(safe_l), jnp.full_like(l, NEG_INF)
        )


def _paged_kernel_dyn_mh(
    scale, soft_cap, page, n_bufs, hkv, g, d, *refs,
):
    """MULTIHEAD dynamic-trip INT8 PAGED decode: grid (B,), all heads
    per step, the page walk as in-kernel manual DMAs indexed through
    the SMEM block table (scalar-prefetch — ``table_ref[b, j]`` picks
    the pool slab for row b's j-th page). The paged twin of
    :func:`_decode_kernel_dyn_mh`, for the same reason: the static
    (B, Hkv, pages) grid pays per-group overhead ~B·Hkv× — after the
    contiguous kernel went multihead, the paged serving step measured
    1.39× contiguous (was 1.08× grid-vs-grid, docs/PERF.md r5).

    Scale pools ride as (npages, Hkv, 1, page) ANY refs with their own
    small manual DMAs per page block — a table-indexed fetch can't use
    the grid pipeline (index maps change per grid step, not per inner
    loop iteration)."""
    (table_ref, kv_lens_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
     out_ref, lse_ref,
     kbuf, vbuf, ksbuf, vsbuf, sem_k, sem_v, sem_ks, sem_vs,
     slot_ref, m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    nb_total = pl.num_programs(0)
    npages = k_hbm.shape[0]
    pps = table_ref.shape[1]
    kv_len = kv_lens_ref[b]
    nb = jnp.minimum(_n_valid_blocks(kv_len, page), pps)

    def dma(bb, j, slot):
        # row bb's j-th page; clamp to the valid range so a prefetch
        # into a short row's padding never addresses out of pool
        jc = jnp.minimum(
            j, jnp.maximum(_n_valid_blocks(kv_lens_ref[bb], page) - 1, 0)
        )
        pid = jnp.clip(table_ref[bb, jc], 0, npages - 1)
        return [
            pltpu.make_async_copy(
                k_hbm.at[pid], kbuf.at[slot], sem_k.at[slot]
            ),
            pltpu.make_async_copy(
                v_hbm.at[pid], vbuf.at[slot], sem_v.at[slot]
            ),
            pltpu.make_async_copy(
                ks_hbm.at[pid], ksbuf.at[slot], sem_ks.at[slot]
            ),
            pltpu.make_async_copy(
                vs_hbm.at[pid], vsbuf.at[slot], sem_vs.at[slot]
            ),
        ]

    @pl.when(b == 0)
    def _warmup():
        slot_ref[0] = 0
        for cp in dma(0, 0, 0):
            cp.start()

    s0 = slot_ref[0]
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(j, _):
        slot = jax.lax.rem(s0 + j, n_bufs)
        nxt = jax.lax.rem(s0 + j + 1, n_bufs)

        @pl.when(j + 1 < nb)
        def _prefetch_in_group():
            for cp in dma(b, j + 1, nxt):
                cp.start()

        @pl.when(jnp.logical_and(j + 1 == nb, b + 1 < nb_total))
        def _prefetch_next_group():
            for cp in dma(b + 1, 0, nxt):
                cp.start()

        for cp in dma(b, j, slot):
            cp.wait()

        def heads(masked):
            if masked:
                pos = j * page + jax.lax.broadcasted_iota(
                    jnp.int32, (1, page), 1
                )
                valid = pos < kv_len
            for h in range(hkv):                   # static unroll
                q = q_ref[0, h]                    # (G, D) bf16
                k = kbuf[slot, h].astype(jnp.bfloat16)
                v = vbuf[slot, h].astype(jnp.bfloat16)
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale
                s = s * ksbuf[slot, h]             # (1, page)
                if soft_cap > 0.0:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                if masked:
                    s = jnp.where(valid, s, NEG_INF)
                lo, hi = h * g, (h + 1) * g
                m = m_ref[lo:hi]
                m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                if masked:
                    p = jnp.where(valid, p, 0.0)
                l_ref[lo:hi] = alpha * l_ref[lo:hi] + jnp.sum(
                    p, axis=1, keepdims=True
                )
                pv = (p * vsbuf[slot, h]).astype(v.dtype)
                acc_ref[lo:hi] = alpha * acc_ref[lo:hi] + jnp.dot(
                    pv, v, preferred_element_type=jnp.float32
                )
                m_ref[lo:hi] = m_new

        is_tail = jnp.logical_and(j + 1 == nb, (j + 1) * page > kv_len)

        @pl.when(is_tail)
        def _masked():
            heads(True)

        @pl.when(jnp.logical_not(is_tail))
        def _plain():
            heads(False)

        return 0

    jax.lax.fori_loop(0, nb, body, 0)
    slot_ref[0] = jax.lax.rem(s0 + nb, n_bufs)
    for h in range(hkv):
        lo, hi = h * g, (h + 1) * g
        l = l_ref[lo:hi]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        out_ref[0, h] = (acc_ref[lo:hi] / safe_l).astype(out_ref.dtype)
        lse_ref[0, h] = jnp.where(
            l > 0.0, m_ref[lo:hi] + jnp.log(safe_l), jnp.full_like(l, NEG_INF)
        )


def pick_block_k(s_len: int, requested: int, *, head_dim: int = 128,
                 itemsize: int = 2) -> int:
    """Largest divisor of ``s_len`` ≤ ``requested``, preferring sublane
    multiples (16). Replaces the old hard divisibility assert: SP cache
    slices (S/tp) may not divide the caller's block_k (e.g. capacity 384
    with the default block), and nothing upstream enforces it.

    On real TPU an unaligned *interior* second-minor block is a Mosaic
    lowering error (see ``_divisor_block``'s contract), so strict mode
    applies and a length with no aligned divisor ≤ requested degrades to
    ONE whole-length block (ragged edges are padded, interiors never
    misalign) — not to the old pathological block_k=1. That whole-length
    fallback is CAPPED (ADVICE r3): a long prime-ish cache slice would
    otherwise materialize an (s_len, D) K and V block in VMEM and fail
    at Mosaic compile/run far less legibly — raise here with the fix
    (pad the cache to an aligned capacity) instead."""
    from triton_distributed_tpu.config import compiling_for_tpu
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    b = _divisor_block(s_len, requested, 16, strict=compiling_for_tpu())
    if b:
        return b
    # whole-length fallback: 2 KV blocks (K and V) double-buffered by
    # the pipeline ≈ 4·s_len·D·itemsize of VMEM
    est = 4 * s_len * head_dim * itemsize
    budget = 64 * 1024 * 1024   # leave headroom under the 128 MB v5e VMEM
    if compiling_for_tpu() and est > budget:
        raise ValueError(
            f"flash_decode: cache slice length {s_len} has no 16-aligned "
            f"divisor <= block_k={requested}, and a whole-length KV block "
            f"(~{est >> 20} MB VMEM) exceeds the safe budget — pad the KV "
            "cache capacity to a multiple of 16 (init_cache already does; "
            "custom cache layouts must follow suit)"
        )
    return s_len


@functools.partial(
    jax.jit,
    static_argnames=("scale", "soft_cap", "block_k", "kv_layout", "interpret"),
)
def gqa_fwd_batch_decode(
    q, k_cache, v_cache, kv_lens, *,
    scale: float | None = None, soft_cap: float = 0.0,
    block_k: int | None = 2048, kv_layout: str = "bhsd", interpret=None,
):
    """Local GQA decode over a (sharded or whole) KV cache → (out, lse).

    q: (B, Hq, D); k_cache/v_cache: (B, Hkv, S, D) (``kv_layout="bhsd"``,
    the framework's native decode layout: each KV block is one contiguous
    DMA run — measured 97% of HBM speed-of-light on a v5e vs 87% for the
    strided view at the same block size) or (B, S, Hkv, D) (``"bshd"``,
    the reference-style layout); kv_lens: (B,) int32 valid lengths.
    The layout default is "bhsd" EVERYWHERE in this stack (kernel, XLA
    twin, AOT twin, SP entries, layer, append_kv) — callers holding
    reference-style caches must pass kv_layout="bshd" explicitly. Returns out
    (B, Hq, D) in q.dtype and lse (B, Hq) f32 — the per-shard partials
    the SP combine consumes. ``lse`` is the natural-log sum-exp of
    ``scale * q·k`` over valid positions (≡ gqa_fwd_batch_decode,
    flash_decode.py:763-846, with the intra-rank combine folded into the
    kernel's sequential KV walk).
    """
    batch, hq, d = q.shape
    if kv_layout == "bshd":
        _, s_len, hkv, _ = k_cache.shape
    elif kv_layout == "bhsd":
        _, hkv, s_len, _ = k_cache.shape
    else:
        raise ValueError(f"kv_layout must be 'bshd' or 'bhsd', got {kv_layout!r}")
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if block_k is None:
        # auto: half the capacity, clamped to the measured sweet band
        # (v5e sweeps, docs/PERF.md — smaller blocks lose DMA depth,
        # larger ones lose length granularity against partial fills)
        block_k = min(max(s_len // 2, 1024), 4096)
    block_k = pick_block_k(
        s_len, block_k, head_dim=d, itemsize=k_cache.dtype.itemsize
    )

    qg = q.reshape(batch, hkv, g, d)
    # the manual-DMA path slices (block_k, d) runs out of the raw cache,
    # which needs native tile alignment (lane dim d ≡ 0 mod 128, sublane
    # offset ≡ 0 mod 8); unaligned geometries (tiny test heads) take the
    # static BlockSpec grid below, whose pipeline pads transparently
    if kv_layout == "bhsd" and d % 128 == 0 and block_k % 8 == 0:
        # native layout: dynamic-trip-count kernel — grid (B, Hkv),
        # in-kernel double-buffered KV DMAs, ceil(len/block_k) blocks
        # per row (HBM reads scale with TRUE lengths, not capacity)
        n_bufs = 2
        kernel = functools.partial(
            _decode_kernel_dyn, scale, soft_cap, block_k, n_bufs, g, d, False
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,              # kv_lens → trip counts
            grid=(batch, hkv),
            in_specs=[
                pl.BlockSpec((1, 1, g, d), lambda b, h, lens: (b, h, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, g, d), lambda b, h, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, g, 1), lambda b, h, lens: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((n_bufs, block_k, d), k_cache.dtype),
                pltpu.VMEM((n_bufs, block_k, d), v_cache.dtype),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SMEM((1,), jnp.int32),    # slot rotation carry
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        )
        call = shmem_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((batch, hkv, g, d), q.dtype),
                jax.ShapeDtypeStruct((batch, hkv, g, 1), jnp.float32),
            ],
            collective_id=None,
            interpret=local_interpret() if interpret is None else interpret,
            name="gqa_decode_split_kv_dyn",
            # the slot-rotation carry (SMEM) and cross-step DMA prefetch
            # are only correct under SEQUENTIAL grid execution — pin it
            # so a parallel/Megacore default can't corrupt the pipeline
            dimension_semantics=("arbitrary", "arbitrary"),
        )
        out, lse = call(kv_lens.astype(jnp.int32), qg, k_cache, v_cache)
        return out.reshape(batch, hq, d), lse.reshape(batch, hq)

    # static (B, Hkv, S/block_k) grid: the reference-style bshd layout
    # (whose strided head window precludes the manual contiguous-run
    # DMA above) and unaligned-geometry bhsd fallbacks
    if kv_layout == "bshd":
        kf = k_cache.reshape(batch, s_len, hkv * d)   # free view, no copy
        vf = v_cache.reshape(batch, s_len, hkv * d)
        kv_spec = pl.BlockSpec((1, block_k, d), lambda b, h, k: (b, k, h))
    else:
        kf, vf = k_cache, v_cache
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, k: (b, h, k, 0)
        )
    kernel = functools.partial(_decode_kernel, scale, soft_cap, block_k)
    call = shmem_call(
        kernel,
        grid=(batch, hkv, s_len // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # kv_lens, whole (B,)
            pl.BlockSpec((1, 1, g, d), lambda b, h, k: (b, h, 0, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, g, d), lambda b, h, k: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, g, 1), lambda b, h, k: (b, h, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((batch, hkv, g, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
        collective_id=None,
        interpret=local_interpret() if interpret is None else interpret,
        name="gqa_decode_split_kv",
    )
    out, lse = call(kv_lens.astype(jnp.int32), qg, kf, vf)
    return out.reshape(batch, hq, d), lse.reshape(batch, hq)


def quantize_kv(x):
    """Per-(…, s) row int8 quantization of a (..., S, D) cache tensor:
    each length-D row gets one f32 scale (max-abs / 127). Returns
    (int8 values, f32 scales of shape x.shape[:-1]).

    TPU-first serving extension (the reference quantizes only the
    tokens moving through the MoE wire, low_latency_all_to_all.py:82-90;
    the stationary KV cache is the larger HBM consumer at decode —
    int8 halves both the cache footprint and the attention DMA bytes).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.where(amax > 0.0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(xf / s[..., None]), -127.0, 127.0
    ).astype(jnp.int8)
    return q, s


def _mh_q8_vmem_plan(hkv, s_len, block_k, d, n_bufs, multihead):
    """(n_bufs, vmem_limit, multihead) for the multihead-q8 decode grid.

    The VMEM residents are the int8 KV slot buffers (2 · n_bufs · Hkv ·
    block_k · d) AND the grid-pipelined (1, Hkv, 1, S) f32 scale planes
    — 2 planes (K and V) × 2 Mosaic pipeline buffers × Hkv·S·4 B, which
    grow linearly in per-shard S and previously ate the fixed 8 MB
    headroom silently (ADVICE r5: compilation failures from ~64k
    per-shard S). Budgeting: shallower KV buffering first; then a
    scoped vmem_limit that counts BOTH terms; above the per-shard-S
    threshold where even minimal buffering cannot fit the configured
    budget, fall back to the per-(b, h) grid (multihead=False), whose
    scale blocks are Hkv× smaller."""
    from triton_distributed_tpu.config import fused_vmem_budget

    def kv_bytes(nb):
        return 2 * nb * hkv * block_k * d

    scale_bytes = 2 * 2 * hkv * s_len * 4
    while multihead and n_bufs > 2 and \
            kv_bytes(n_bufs) + scale_bytes > 12 * 1024 * 1024:
        n_bufs -= 1
    vmem_limit = None
    if multihead and kv_bytes(n_bufs) + scale_bytes > 12 * 1024 * 1024:
        vmem_limit = kv_bytes(n_bufs) + scale_bytes + 8 * 1024 * 1024
        if vmem_limit > fused_vmem_budget():
            # per-shard S too large for the multihead grid at any depth
            multihead = False
            vmem_limit = None
    return n_bufs, vmem_limit, multihead


def _q8_auto_block_k(batch, hkv, s_len):
    """Block size for the int8 walk — the r4 heuristic (half capacity
    clamped to [1024, 4096]) re-validated round 5 by a PAIRED sweep at
    the serving headline (B=128, Hkv=8, S=2048, mixed lens U[S/8,
    3S/4], v5e): 1024 best; 512 +20%, 256 +57% (per-block overhead),
    2048 +5% (over-read on partial rows). The walk is bytes/BW bound
    (~0.17 µs/block fixed + ~470-580 GB/s effective on 131-262 KB
    contiguous runs) — NOT DMA-count bound: moving the per-block scale
    copies onto the grid pipeline and deepening n_bufs 2→4 measured
    neutral at 1024 (docs/PERF.md round-5 serving attention)."""
    del batch, hkv
    return min(max(s_len // 2, 1024), 4096)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "soft_cap", "block_k", "n_bufs", "multihead",
                     "interpret"),
)
def gqa_fwd_batch_decode_q8(
    q, k_q, k_scale, v_q, v_scale, kv_lens, *,
    scale: float | None = None, soft_cap: float = 0.0,
    block_k: int | None = None, n_bufs: int = 4, multihead: bool = True,
    interpret=None,
):
    """Local GQA decode over an INT8 KV cache → (out, lse).

    q: (B, Hq, D) bf16/f32; k_q/v_q: (B, Hkv, S, D) int8 [bhsd];
    k_scale/v_scale: (B, Hkv, S) f32 per-token-per-head scales (from
    :func:`quantize_kv`). Same contract as :func:`gqa_fwd_batch_decode`
    — dynamic per-row trip counts, reads scale with TRUE lengths — at
    half the KV bytes; the scales fold exactly into the softmax and
    ride the grid pipeline, not per-block DMAs (see
    ``_decode_kernel_dyn``'s quant mode). ``n_bufs``: KV slot depth —
    4 keeps the DMA engine fed across short (1-2 block) rows where
    double buffering drains at every group boundary. ``multihead``
    (default): grid (B,) with all Hkv heads per step — 8× fewer grid
    groups, the round-5 fix for the per-group overhead that dominated
    the serving shape (``_decode_kernel_dyn_mh``); False keeps the
    per-(b, h) grid (comparison/debug).
    """
    batch, hq, d = q.shape
    _, hkv, s_len, _ = k_q.shape
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if block_k is None:
        block_k = _q8_auto_block_k(batch, hkv, s_len)
    block_k = pick_block_k(s_len, block_k, head_dim=d, itemsize=1)

    if d % 128 != 0 or block_k % 128 != 0:
        # unaligned geometry (the in-kernel scale slice works at lane
        # granules): widen via XLA and take the dense path
        k = (k_q.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
        v = (v_q.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
        return gqa_fwd_batch_decode(
            q, k, v, kv_lens, scale=scale, soft_cap=soft_cap,
            block_k=block_k, kv_layout="bhsd", interpret=interpret,
        )

    qg = q.reshape(batch, hkv, g, d).astype(jnp.bfloat16)
    ks4 = k_scale.astype(jnp.float32).reshape(batch, hkv, 1, s_len)
    vs4 = v_scale.astype(jnp.float32).reshape(batch, hkv, 1, s_len)
    n_bufs, vmem_limit, multihead = _mh_q8_vmem_plan(
        hkv, s_len, block_k, d, n_bufs, multihead
    )
    if multihead:
        kernel = functools.partial(
            _decode_kernel_dyn_mh, scale, soft_cap, block_k, n_bufs,
            hkv, g, d,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch,),
            in_specs=[
                pl.BlockSpec((1, hkv, g, d), lambda b, lens: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                # whole per-row scale planes on the grid pipeline (see
                # _decode_kernel_dyn's quant note)
                pl.BlockSpec(
                    (1, hkv, 1, s_len), lambda b, lens: (b, 0, 0, 0)
                ),
                pl.BlockSpec(
                    (1, hkv, 1, s_len), lambda b, lens: (b, 0, 0, 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec((1, hkv, g, d), lambda b, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, hkv, g, 1), lambda b, lens: (b, 0, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((n_bufs, hkv, block_k, d), jnp.int8),
                pltpu.VMEM((n_bufs, hkv, block_k, d), jnp.int8),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.VMEM((hkv * g, 1), jnp.float32),
                pltpu.VMEM((hkv * g, 1), jnp.float32),
                pltpu.VMEM((hkv * g, d), jnp.float32),
            ],
        )
        dims = ("arbitrary",)
    else:
        kernel = functools.partial(
            _decode_kernel_dyn, scale, soft_cap, block_k, n_bufs, g, d, True
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, hkv),
            in_specs=[
                pl.BlockSpec((1, 1, g, d), lambda b, h, lens: (b, h, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(
                    (1, 1, 1, s_len), lambda b, h, lens: (b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, s_len), lambda b, h, lens: (b, h, 0, 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, g, d), lambda b, h, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, g, 1), lambda b, h, lens: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((n_bufs, block_k, d), jnp.int8),
                pltpu.VMEM((n_bufs, block_k, d), jnp.int8),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SemaphoreType.DMA((n_bufs,)),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        )
        dims = ("arbitrary", "arbitrary")
    call = shmem_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((batch, hkv, g, 1), jnp.float32),
        ],
        collective_id=None,
        vmem_limit_bytes=vmem_limit,
        interpret=local_interpret() if interpret is None else interpret,
        name="gqa_decode_split_kv_q8" + ("_mh" if multihead else ""),
        # slot-rotation carries + cross-step DMA prefetch require
        # SEQUENTIAL grid execution
        dimension_semantics=dims,
    )
    out, lse = call(kv_lens.astype(jnp.int32), qg, k_q, v_q, ks4, vs4)
    return out.reshape(batch, hq, d), lse.reshape(batch, hq)


def gqa_fwd_batch_decode_q8_xla(
    q, k_q, k_scale, v_q, v_scale, kv_lens, *, scale=None, soft_cap=0.0,
):
    """Dense-XLA twin of :func:`gqa_fwd_batch_decode_q8` (correctness
    reference): widen the int8 cache and run the dense reference."""
    k = (k_q.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
    v = (v_q.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    return gqa_fwd_batch_decode_xla(
        q, k, v, kv_lens, scale=scale, soft_cap=soft_cap, kv_layout="bhsd"
    )


def _paged_decode_kernel(
    scale, soft_cap, page, table_ref, kv_lens_ref, q_ref, k_ref, v_ref,
    out_ref, lse_ref, m_ref, l_ref, acc_ref,
):
    """Scalar-prefetch adapter over :func:`_decode_kernel`: the page
    table is consumed by the BlockSpec index maps (which page to DMA
    next), not by the compute body."""
    del table_ref
    _decode_kernel(
        scale, soft_cap, page, kv_lens_ref, q_ref, k_ref, v_ref,
        out_ref, lse_ref, m_ref, l_ref, acc_ref,
    )


@functools.partial(
    jax.jit, static_argnames=("scale", "soft_cap", "interpret")
)
def paged_gqa_fwd_batch_decode(
    q, k_pool, v_pool, kv_lens, block_table, *,
    scale: float | None = None, soft_cap: float = 0.0, interpret=None,
):
    """PAGED GQA decode: the KV cache lives in a shared page pool and
    each batch row walks its own page list (≡ the reference's paged
    entries — gqa_fwd_batch_decode takes (num_pages, page_size, Hkv, D)
    caches + a block_table, flash_decode.py:763-846, and the SP layer
    forwards one, sp_flash_decode_layer.py:78-84).

    q: (B, Hq, D); k_pool/v_pool: (num_pages, Hkv, page_size, D) —
    "phsd", the paged analogue of the bhsd fast layout: one (page,
    head) block is a single contiguous DMA run. block_table:
    (B, pages_per_seq) int32 page ids (entries past the valid length
    may be any in-range id — their scores are masked by ``kv_lens``);
    kv_lens: (B,) valid lengths. Returns (out (B, Hq, D), lse (B, Hq)).

    The page table rides as a scalar-prefetch operand so the KV
    BlockSpec index maps read it directly — the kernel's sequential
    page walk is physically gather-free (the DMA engine fetches page
    ``table[b, j]`` while page ``j-1`` computes), the TPU translation
    of the reference's in-kernel ``tl.load(block_table + ...)``.

    Page-size guidance (measured on a v5e, docs/PERF.md): per-page
    pipeline overhead makes small GPU-style pages slow — use ≥1024-row
    pages (757 GB/s at 2048, matching the contiguous kernel; 149 GB/s
    at 128).
    """
    batch, hq, d = q.shape
    npages, hkv, page, _ = k_pool.shape
    assert v_pool.shape == k_pool.shape, (k_pool.shape, v_pool.shape)
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    qg = q.reshape(batch, hkv, g, d)
    pages_per_seq = block_table.shape[1]
    grid = (batch, hkv, pages_per_seq)

    def kv_map(b, h, j, table_ref, lens_ref):
        # length-aware page skipping (same trick as the dense kernel's
        # block clamp): steps past row b's last valid page revisit it,
        # so Mosaic skips their DMA — reads scale with true lengths.
        # Also doubles as the -1-padding guard: clamped steps never
        # consult the (possibly -1) padded table entries.
        jc = jnp.minimum(j, _n_valid_blocks(lens_ref[b], page) - 1)
        # clamp BOTH ways: padded table entries (-1 padding included)
        # must never address out of pool
        return (jnp.clip(table_ref[b, jc], 0, npages - 1), h, 0, 0)

    kv_spec = pl.BlockSpec((1, 1, page, d), kv_map)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, g, d), lambda b, h, j, t_, l_: (b, h, 0, 0)
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, g, d), lambda b, h, j, t_, l_: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, g, 1), lambda b, h, j, t_, l_: (b, h, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    call = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale, soft_cap, page),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((batch, hkv, g, 1), jnp.float32),
        ],
        interpret=local_interpret() if interpret is None else interpret,
        name="gqa_decode_paged",
    )
    out, lse = call(
        block_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
        qg, k_pool, v_pool,
    )
    return out.reshape(batch, hq, d), lse.reshape(batch, hq)


@functools.partial(
    jax.jit, static_argnames=("scale", "soft_cap", "interpret")
)
def paged_gqa_fwd_batch_decode_q8(
    q, k_pool, k_scale, v_pool, v_scale, kv_lens, block_table, *,
    scale: float | None = None, soft_cap: float = 0.0, interpret=None,
):
    """PAGED GQA decode over an INT8 page pool.

    k_pool/v_pool: (num_pages, Hkv, page, D) int8; k_scale/v_scale:
    (num_pages, Hkv, page) f32 per-row scales (reshaped internally to
    the lane-aligned (num_pages, Hkv, 1, page) DMA layout). Same
    contract as :func:`paged_gqa_fwd_batch_decode` at half the KV pool
    bytes — the int8 composition of the paged and quantized serving
    modes (block-table page walk + exact in-softmax scale folds).
    """
    batch, hq, d = q.shape
    npages, hkv, page, _ = k_pool.shape
    assert v_pool.shape == k_pool.shape, (k_pool.shape, v_pool.shape)
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    if d % 128 != 0 or page % 128 != 0:
        # unaligned geometry (the (…, 1, page) scale windows slice the
        # lane dim at page granules): widen and take the full-precision
        # paged path — the SAME fallback discipline (and precision) as
        # the contiguous q8 entry, so mixed-geometry callers see one
        # numerical behavior across cache layouts
        kp = (k_pool.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
        vp = (v_pool.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
        return paged_gqa_fwd_batch_decode(
            q, kp, vp, kv_lens, block_table, scale=scale,
            soft_cap=soft_cap, interpret=interpret,
        )

    qg = q.reshape(batch, hkv, g, d).astype(jnp.bfloat16)

    # MULTIHEAD page walk (grid (B,), manual table-indexed DMAs): 8×
    # fewer grid groups than the static (B, Hkv, pages) grid — the
    # per-group overhead fix of _decode_kernel_dyn_mh applied to the
    # paged mode (see _paged_kernel_dyn_mh)
    n_bufs = 4
    while n_bufs > 2 and 2 * n_bufs * hkv * page * d > 12 * 1024 * 1024:
        n_bufs -= 1
    vmem_limit = None
    if 2 * n_bufs * hkv * page * d > 12 * 1024 * 1024:
        vmem_limit = 2 * n_bufs * hkv * page * d + 8 * 1024 * 1024
    mh_kernel = functools.partial(
        _paged_kernel_dyn_mh, scale, soft_cap, page, n_bufs, hkv, g, d
    )
    mh_grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # block table, kv_lens
        grid=(batch,),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda b, t_, l_: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda b, t_, l_: (b, 0, 0, 0)),
            pl.BlockSpec((1, hkv, g, 1), lambda b, t_, l_: (b, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((n_bufs, hkv, page, d), jnp.int8),
            pltpu.VMEM((n_bufs, hkv, page, d), jnp.int8),
            pltpu.VMEM((n_bufs, hkv, 1, page), jnp.float32),
            pltpu.VMEM((n_bufs, hkv, 1, page), jnp.float32),
            pltpu.SemaphoreType.DMA((n_bufs,)),
            pltpu.SemaphoreType.DMA((n_bufs,)),
            pltpu.SemaphoreType.DMA((n_bufs,)),
            pltpu.SemaphoreType.DMA((n_bufs,)),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, d), jnp.float32),
        ],
    )
    mh_call = shmem_call(
        mh_kernel,
        grid_spec=mh_grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((batch, hkv, g, 1), jnp.float32),
        ],
        collective_id=None,
        vmem_limit_bytes=vmem_limit,
        interpret=local_interpret() if interpret is None else interpret,
        name="gqa_decode_paged_q8_mh",
        dimension_semantics=("arbitrary",),   # slot carry is sequential
    )
    out, lse = mh_call(
        block_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
        qg, k_pool, v_pool,
        k_scale.astype(jnp.float32).reshape(npages, hkv, 1, page),
        v_scale.astype(jnp.float32).reshape(npages, hkv, 1, page),
    )
    return out.reshape(batch, hq, d), lse.reshape(batch, hq)


def paged_gqa_fwd_batch_decode_q8_xla(
    q, k_pool, k_scale, v_pool, v_scale, kv_lens, block_table, *,
    scale=None, soft_cap=0.0,
):
    """Dense-XLA twin: widen the int8 pools and take the dense paged
    reference."""
    kp = (k_pool.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
    vp = (v_pool.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    return paged_gqa_fwd_batch_decode_xla(
        q, kp, vp, kv_lens, block_table, scale=scale, soft_cap=soft_cap
    )


def paged_gqa_fwd_batch_decode_xla(
    q, k_pool, v_pool, kv_lens, block_table, *, scale=None, soft_cap=0.0,
):
    """Dense-XLA twin of :func:`paged_gqa_fwd_batch_decode`: gather the
    pages into a contiguous bhsd cache and reuse the dense reference."""
    npages, hkv, page, d = k_pool.shape
    safe = jnp.clip(block_table.astype(jnp.int32), 0, npages - 1)
    # (B, P, Hkv, page, D) → (B, Hkv, P·page, D)
    kc = k_pool[safe].transpose(0, 2, 1, 3, 4).reshape(
        block_table.shape[0], hkv, -1, d
    )
    vc = v_pool[safe].transpose(0, 2, 1, 3, 4).reshape(
        block_table.shape[0], hkv, -1, d
    )
    return gqa_fwd_batch_decode_xla(
        q, kc, vc, kv_lens, scale=scale, soft_cap=soft_cap,
        kv_layout="bhsd",
    )


def _local_paged_shard_decode(
    q, k_pool, v_pool, global_kv_lens, block_table, axis, *,
    scale, soft_cap, use_pallas, interpret=None,
):
    """Rank-local PAGED decode over this rank's sequence slice — the ONE
    definition of the per-rank lens/dispatch logic (shared by the device
    body and the jitted SP entry, mirroring _local_shard_decode)."""
    r = jax.lax.axis_index(axis)
    page = k_pool.shape[2]
    s_loc = block_table.shape[1] * page
    local_lens = jnp.clip(
        global_kv_lens - r * s_loc, 0, s_loc
    ).astype(jnp.int32)
    decode = (
        paged_gqa_fwd_batch_decode if use_pallas
        else paged_gqa_fwd_batch_decode_xla
    )
    kwargs = dict(scale=scale, soft_cap=soft_cap)
    if use_pallas:
        kwargs.update(interpret=interpret)
    return decode(q, k_pool, v_pool, local_lens, block_table, **kwargs)


def sp_paged_gqa_fwd_batch_decode_device(
    q, k_pool, v_pool, global_kv_lens, block_table, axis, *,
    scale=None, soft_cap=0.0, use_pallas=True, interpret=None,
):
    """Per-device SP PAGED decode body — callable inside any shard_map.

    Each rank owns a page pool and the page table of ITS contiguous
    sequence slice (≡ "each rank's kv shard's kv_table",
    sp_flash_decode_layer.py:84): local paged decode over the slice,
    then the usual AG(out, lse) + inter-rank combine.
    """
    out, lse = _local_paged_shard_decode(
        q, k_pool, v_pool, global_kv_lens, block_table, axis,
        scale=scale, soft_cap=soft_cap, use_pallas=use_pallas,
        interpret=interpret,
    )
    return _merge_shard_partials(out, lse, axis)


def gqa_fwd_batch_decode_aot(
    *, scale: float | None = None, soft_cap: float = 0.0,
    block_k: int = 2048, kv_layout: str = "bhsd", cache_dir=".aot_cache",
):
    """AOT twin of :func:`gqa_fwd_batch_decode` (≡ the ``*_aot`` entries
    calling pre-compiled kernels, flash_decode.py:1007-1160): returns a
    shape-dispatching artifact library — ``.compile(q, k, v, lens)``
    serializes one shape point, calls reload it without retracing."""
    from triton_distributed_tpu.tools.aot import AotLibrary

    def entry(q, k_cache, v_cache, kv_lens):
        return gqa_fwd_batch_decode(
            q, k_cache, v_cache, kv_lens,
            scale=scale, soft_cap=soft_cap, block_k=block_k,
            kv_layout=kv_layout,
        )

    # hyperparameters are part of the artifact identity — two libraries
    # sharing a cache_dir must never reuse each other's kernels
    name = f"gqa_decode-bk{block_k}-sc{soft_cap}-s{scale}-{kv_layout}"
    return AotLibrary(entry, name=name, cache_dir=cache_dir)


def gqa_fwd_batch_decode_xla(
    q, k_cache, v_cache, kv_lens, *, scale=None, soft_cap=0.0,
    kv_layout: str = "bhsd",
):
    """Dense-XLA twin of :func:`gqa_fwd_batch_decode` (correctness
    reference, ≡ the torch baselines in test_decode_attn.py)."""
    batch, hq, d = q.shape
    if kv_layout == "bshd":
        s_len = k_cache.shape[1]
        kt = k_cache.transpose(0, 2, 1, 3).astype(jnp.float32)  # (B,Hkv,S,D)
        vt = v_cache.transpose(0, 2, 1, 3).astype(jnp.float32)
    else:
        s_len = k_cache.shape[2]
        kt = k_cache.astype(jnp.float32)
        vt = v_cache.astype(jnp.float32)
    hkv = kt.shape[1]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(batch, hkv, g, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhsd->bhgs", qg, kt) * scale
    if soft_cap > 0.0:
        s = soft_cap * jnp.tanh(s / soft_cap)
    mask = jnp.arange(s_len)[None, None, None, :] < kv_lens[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # explicit mask: an empty row has m == NEG_INF and exp degenerates
    # to 1 — mask so l stays 0 and the output is exact zeros (matching
    # the kernel's block-skipping-independent semantics)
    p = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhgs,bhsd->bhgd", p / jnp.maximum(l, 1e-30), vt)
    lse = jnp.where(l[..., 0] > 0, m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)), NEG_INF)
    return out.reshape(batch, hq, d).astype(q.dtype), lse.reshape(batch, hq)


def combine_partials(outs, lses, out_dtype=None):
    """Merge per-shard (out, lse) partials along axis 0.

    outs: (R, B, Hq, D); lses: (R, B, Hq). The blockwise-softmax /
    ring-attention merge (≡ kernel_inter_rank_gqa_fwd_batch_decode_
    combine_kv, flash_decode.py:482-566): weight each shard by
    exp(lse_r − lse_max) and renormalize. Shards with empty KV carry
    lse == NEG_INF and contribute exactly zero.
    """
    out_dtype = out_dtype or outs.dtype
    lses = lses.astype(jnp.float32)
    m = jnp.max(lses, axis=0, keepdims=True)                 # (1, B, Hq)
    w = jnp.exp(lses - m)                                    # (R, B, Hq)
    denom = jnp.maximum(jnp.sum(w, axis=0), 1e-30)           # (B, Hq)
    merged = jnp.einsum("rbh,rbhd->bhd", w, outs.astype(jnp.float32)) / denom[..., None]
    lse = m[0] + jnp.log(denom)
    return merged.astype(out_dtype), lse


def combine_gqa_partials(outs, lses, out_dtype=None):
    """Merge cp-rank partials in the ragged-kernel layout.

    outs: (R, Hkv, TG, D); lses: (R, Hkv, TG) — the (out, lse) pair
    :func:`~triton_distributed_tpu.kernels.ragged_paged_attention.
    ragged_paged_attention` returns, stacked along the cp axis. Same
    softmax merge as :func:`combine_partials`; the explicit where()
    guard keeps rows every shard masked out (all lses at NEG_INF —
    padding tokens, empty shards) at exactly zero weight instead of
    degenerating exp(NEG_INF − NEG_INF) to 1. For a row fully resident
    on one shard the merge is the identity on that shard's out
    (weights 1/1 in f32 — bit-exact through the round trip), which is
    what makes short-request streams byte-identical to the cp-free
    engine.
    """
    out_dtype = out_dtype or outs.dtype
    lses = lses.astype(jnp.float32)
    m = jnp.max(lses, axis=0, keepdims=True)                 # (1, Hkv, TG)
    w = jnp.where(lses > NEG_INF / 2, jnp.exp(lses - m), 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=0), 1e-30)           # (Hkv, TG)
    merged = jnp.einsum(
        "rht,rhtd->htd", w, outs.astype(jnp.float32)
    ) / denom[..., None]
    lse = jnp.where(
        jnp.max(lses, axis=0) > NEG_INF / 2,
        m[0] + jnp.log(denom),
        NEG_INF,
    )
    return merged.astype(out_dtype), lse


def cp_lse_combine_xla(x, mesh, axis: str = "x"):
    """XLA body of the cp-decode LSE-combine — the degradation target
    declared for the ``cp_decode.lse_combine`` lint family.

    ``x``: per-rank (n·m, cols) contribution slabs stacked along
    ``axis`` (rows ``[dst·m, (dst+1)·m)`` = this rank's exp-weighted
    partial for destination shard ``dst``: numerator rows ``w_r·out_r``
    with the additive denominator row ``Σ w_r`` riding in the block —
    the weighting against the pre-agreed running max makes the merge a
    pure add over ranks, cf. :func:`combine_partials`). Returns each
    rank's (m, cols) reduced destination shard — ``psum_scatter``, the
    ring kernel's semantics on the raw f32 wire.
    """
    fn = jax.shard_map(
        lambda s: jax.lax.psum_scatter(
            s.astype(jnp.float32), axis, scatter_dimension=0, tiled=True
        ),
        mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False,
    )
    return jax.jit(fn)(x)


def _local_shard_decode(
    q, k_shard, v_shard, global_kv_lens, axis, *,
    scale, soft_cap, block_k, use_pallas, kv_layout="bhsd", interpret=None,
):
    """Rank-local decode over this rank's contiguous KV slice → (out, lse)."""
    r = jax.lax.axis_index(axis)
    s_loc = k_shard.shape[1 if kv_layout == "bshd" else 2]
    local_lens = jnp.clip(global_kv_lens - r * s_loc, 0, s_loc).astype(jnp.int32)
    decode = gqa_fwd_batch_decode if use_pallas else gqa_fwd_batch_decode_xla
    kwargs = dict(scale=scale, soft_cap=soft_cap, kv_layout=kv_layout)
    if use_pallas:
        kwargs.update(block_k=block_k, interpret=interpret)
    return decode(q, k_shard, v_shard, local_lens, **kwargs)


def _merge_shard_partials(out, lse, axis):
    """AG of per-rank (out, lse) + inter-rank combine, inside shard_map.

    Small payload — the reference uses its LL allgather here
    (low_latency_allgather_layer.py); XLA's all_gather over ICI is the
    TPU fast path for this message size.
    """
    merged, _ = _merge_shard_partials_lse(out, lse, axis)
    return merged


def _merge_shard_partials_lse(out, lse, axis):
    """Like :func:`_merge_shard_partials` but returning (out, lse) —
    callers can merge FURTHER partials (e.g. the current decode step's
    just-produced token, models/transformer.decode_step: the softmax
    merge is associative, so the new token rides as an exact
    single-position partial with lse = its raw score, and the cache
    append no longer feeds the attention kernel)."""
    outs = jax.lax.all_gather(out, axis)
    lses = jax.lax.all_gather(lse, axis)
    return combine_partials(outs, lses, out_dtype=out.dtype)


def sp_gqa_fwd_batch_decode_device(
    q, k_shard, v_shard, global_kv_lens, axis, *,
    scale=None, soft_cap=0.0, block_k=2048, use_pallas=True,
    kv_layout="bhsd", interpret=None,
):
    """Per-device SP decode body — callable inside any shard_map.

    q: (B, Hq, D) replicated across ``axis``; k_shard/v_shard: this
    rank's contiguous slice of the sequence — (B, Hkv, S/R, D) for
    ``kv_layout="bhsd"`` (native, default) or (B, S/R, Hkv, D) for
    ``"bshd"``;
    global_kv_lens: (B,) TOTAL valid lengths. ≡ SpGQAFlashDecodeAttention
    .forward (sp_flash_decode_layer.py:78-184): local decode → AG of
    (out, lse) → inter-rank combine.
    """
    out, lse = _local_shard_decode(
        q, k_shard, v_shard, global_kv_lens, axis,
        scale=scale, soft_cap=soft_cap, block_k=block_k,
        use_pallas=use_pallas, kv_layout=kv_layout, interpret=interpret,
    )
    return _merge_shard_partials(out, lse, axis)


def _sp_specs(axis, batch_axes):
    """(batch-dim spec, rank-stacked partial spec, merged out spec) for
    the SP decode shard_maps. With ``batch_axes`` (e.g. a dp mesh axis)
    the batch dim 0 of q/lens/caches is SHARDED over them — the
    serving layout on a dp×tp mesh: batch over dp, sequence over tp.
    The per-rank partials stack rank-major into dim 0, so the stacked
    dim is sharded over (batch_axes..., axis)."""
    ba = tuple(batch_axes)
    b = ba if ba else None
    return b, ba + (axis,), b


@functools.lru_cache(maxsize=64)
def _sp_decode_fns(mesh, axis, scale, soft_cap, block_k, use_pallas,
                   kv_layout, batch_axes=(), ikey=()):
    """Jitted (local, merge) pair for :func:`sp_gqa_fwd_batch_decode`,
    cached so repeated decode steps don't retrace/recompile. ``ikey``
    is ``config.interp_key()`` — chaos/fault knobs are traced into the
    local decode kernel, so toggling them must rebuild (the same
    convention as every collective builder)."""
    # Two dispatches, not one: on the CPU-interpreter path, mixing the
    # io_callback-driven Pallas simulation and an XLA collective in a single
    # program can starve the collective rendezvous threads (deadlock). On
    # TPU the split costs one extra dispatch on a microseconds-scale op.
    def local(q, k_shard, v_shard, lens):
        return _local_shard_decode(
            q, k_shard, v_shard, lens, axis,
            scale=scale, soft_cap=soft_cap, block_k=block_k,
            use_pallas=use_pallas, kv_layout=kv_layout,
        )

    b, part, out = _sp_specs(axis, batch_axes)
    kv_spec = P(b, axis) if kv_layout == "bshd" else P(b, None, axis)
    local_fn = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(b), kv_spec, kv_spec, P(b)),
            out_specs=(P(part), P(part)),
            check_vma=False,
        )
    )
    merge_fn = jax.jit(
        jax.shard_map(
            functools.partial(_merge_shard_partials_lse, axis=axis),
            mesh=mesh,
            in_specs=(P(part), P(part)),
            out_specs=(P(out), P(out)),
            check_vma=False,
        )
    )
    return local_fn, merge_fn


def sp_gqa_fwd_batch_decode(
    q, k_cache, v_cache, global_kv_lens, mesh, axis="x", *,
    scale=None, soft_cap=0.0, block_k=2048, use_pallas=True,
    kv_layout="bhsd", with_lse=False, batch_axes=(),
):
    """Host entry: sequence-parallel GQA decode on ``mesh``.

    k_cache/v_cache: (B, Hkv, S, D) [bhsd, native default] or
    (B, S, Hkv, D) [bshd] with S sharded over ``axis``; q and
    global_kv_lens replicated. Returns (B, Hq, D) replicated —
    plus the merged (B, Hq) lse with ``with_lse`` (for callers
    merging further partials via :func:`combine_partials`).
    With ``batch_axes`` (dp mesh axes), the batch dim of every
    operand and result is sharded over them instead — the serving
    layout on a dp×tp mesh (batch over dp, sequence over ``axis``).
    """
    local_fn, merge_fn = _sp_decode_fns(
        mesh, axis, scale, soft_cap, block_k, use_pallas, kv_layout,
        tuple(batch_axes), interp_key(),
    )
    out, lse = local_fn(q, k_cache, v_cache, global_kv_lens)
    out, lse = merge_fn(out, lse)
    return (out, lse) if with_lse else out


def _local_shard_decode_q8(
    q, k_q, k_scale, v_q, v_scale, global_kv_lens, axis, *,
    scale, soft_cap, block_k, interpret=None,
):
    """Rank-local INT8 decode over this rank's contiguous KV slice."""
    r = jax.lax.axis_index(axis)
    s_loc = k_q.shape[2]
    local_lens = jnp.clip(
        global_kv_lens - r * s_loc, 0, s_loc
    ).astype(jnp.int32)
    return gqa_fwd_batch_decode_q8(
        q, k_q, k_scale, v_q, v_scale, local_lens,
        scale=scale, soft_cap=soft_cap, block_k=block_k,
        interpret=interpret,
    )


def sp_gqa_fwd_batch_decode_q8_device(
    q, k_q, k_scale, v_q, v_scale, global_kv_lens, axis, *,
    scale=None, soft_cap=0.0, block_k=None, interpret=None,
):
    """Per-device SP decode body over an INT8 KV cache (composable
    inside any shard_map; quantized twin of
    :func:`sp_gqa_fwd_batch_decode_device`)."""
    out, lse = _local_shard_decode_q8(
        q, k_q, k_scale, v_q, v_scale, global_kv_lens, axis,
        scale=scale, soft_cap=soft_cap, block_k=block_k,
        interpret=interpret,
    )
    return _merge_shard_partials(out, lse, axis)


@functools.lru_cache(maxsize=64)
def _sp_q8_fns(mesh, axis, scale, soft_cap, block_k, batch_axes=(), ikey=()):
    """Jitted (local, merge) pair for the INT8 SP decode — split into
    two dispatches for the interpreter-deadlock reason documented at
    :func:`_sp_decode_fns`."""

    def local(q, kq, ks, vq, vs, lens):
        return _local_shard_decode_q8(
            q, kq, ks, vq, vs, lens, axis,
            scale=scale, soft_cap=soft_cap, block_k=block_k,
        )

    b, part, out = _sp_specs(axis, batch_axes)
    kv_spec = P(b, None, axis)                 # (B, Hkv, S[, D]) seq-sharded
    local_fn = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(b), kv_spec, kv_spec, kv_spec, kv_spec, P(b)),
            out_specs=(P(part), P(part)),
            check_vma=False,
        )
    )
    merge_fn = jax.jit(
        jax.shard_map(
            functools.partial(_merge_shard_partials_lse, axis=axis),
            mesh=mesh,
            in_specs=(P(part), P(part)),
            out_specs=(P(out), P(out)),
            check_vma=False,
        )
    )
    return local_fn, merge_fn


def sp_gqa_fwd_batch_decode_q8(
    q, k_q, k_scale, v_q, v_scale, global_kv_lens, mesh, axis="x", *,
    scale=None, soft_cap=0.0, block_k=None, with_lse=False, batch_axes=(),
):
    """Host entry: sequence-parallel GQA decode over an INT8 KV cache.

    k_q/v_q: (B, Hkv, S, D) int8, k_scale/v_scale: (B, Hkv, S) f32 —
    all with S sharded over ``axis``; q and global_kv_lens replicated
    (batch dim sharded over ``batch_axes`` when given — the dp×tp
    serving layout). Returns (B, Hq, D) replicated (+ merged lse with
    ``with_lse``). Half the KV bytes of the bf16 entry both at rest
    and on the attention DMA stream.
    """
    local_fn, merge_fn = _sp_q8_fns(
        mesh, axis, scale, soft_cap, block_k, tuple(batch_axes), interp_key()
    )
    out, lse = local_fn(q, k_q, k_scale, v_q, v_scale, global_kv_lens)
    out, lse = merge_fn(out, lse)
    return (out, lse) if with_lse else out


def _local_paged_shard_decode_q8(
    q, k_pool, k_scale, v_pool, v_scale, global_kv_lens, block_table,
    axis, *, scale, soft_cap, interpret=None,
):
    """Rank-local INT8 paged decode over this rank's sequence slice."""
    r = jax.lax.axis_index(axis)
    page = k_pool.shape[2]
    s_loc = block_table.shape[1] * page
    local_lens = jnp.clip(
        global_kv_lens - r * s_loc, 0, s_loc
    ).astype(jnp.int32)
    return paged_gqa_fwd_batch_decode_q8(
        q, k_pool, k_scale, v_pool, v_scale, local_lens, block_table,
        scale=scale, soft_cap=soft_cap, interpret=interpret,
    )


@functools.lru_cache(maxsize=64)
def _sp_paged_q8_fns(mesh, axis, scale, soft_cap, with_lse=False, ikey=()):
    """Jitted (local, merge) pair for the INT8 paged SP decode."""

    def local(q, kp, ks, vp, vs, lens, table):
        return _local_paged_shard_decode_q8(
            q, kp, ks, vp, vs, lens, table[0], axis,
            scale=scale, soft_cap=soft_cap,
        )

    local_fn = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(),
                      P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )
    merge_fn = jax.jit(
        jax.shard_map(
            functools.partial(
                _merge_shard_partials_lse if with_lse
                else _merge_shard_partials,
                axis=axis,
            ),
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(), P()) if with_lse else P(),
            check_vma=False,
        )
    )
    return local_fn, merge_fn


def sp_paged_gqa_fwd_batch_decode_q8(
    q, k_pool, k_scale, v_pool, v_scale, global_kv_lens, block_table,
    mesh, axis="x", *, scale=None, soft_cap=0.0, with_lse=False,
):
    """Host entry: sequence-parallel INT8 PAGED GQA decode — the same
    per-rank pool/table contract as :func:`sp_paged_gqa_fwd_batch_decode`
    with int8 pools + (R·npages_local, Hkv, page) f32 scale pools, all
    sharded ``P(axis)`` on dim 0. ``with_lse``: also return the merged
    (B, Hq) lse so callers can fold further partials (the paged decode
    step's just-produced token, models/transformer.decode_step)."""
    local_fn, merge_fn = _sp_paged_q8_fns(
        mesh, axis, scale, soft_cap, with_lse, interp_key()
    )
    out, lse = local_fn(
        q, k_pool, k_scale, v_pool, v_scale, global_kv_lens, block_table
    )
    return merge_fn(out, lse)


@functools.lru_cache(maxsize=64)
def _sp_paged_fns(mesh, axis, scale, soft_cap, use_pallas, with_lse=False,
                  ikey=()):
    """Jitted (local, merge) pair for the PAGED SP decode — split into
    two dispatches for the same interpreter-deadlock reason as
    :func:`_sp_decode_fns`."""

    def local(q, kp, vp, lens, table):
        return _local_paged_shard_decode(
            q, kp, vp, lens, table[0], axis,
            scale=scale, soft_cap=soft_cap, use_pallas=use_pallas,
        )

    local_fn = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )
    merge_fn = jax.jit(
        jax.shard_map(
            functools.partial(
                _merge_shard_partials_lse if with_lse
                else _merge_shard_partials,
                axis=axis,
            ),
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(), P()) if with_lse else P(),
            check_vma=False,
        )
    )
    return local_fn, merge_fn


def sp_paged_gqa_fwd_batch_decode(
    q, k_pool, v_pool, global_kv_lens, block_table, mesh, axis="x", *,
    scale=None, soft_cap=0.0, use_pallas=True, with_lse=False,
):
    """Host entry: sequence-parallel PAGED GQA decode on ``mesh``.

    Each rank owns a page pool of its contiguous sequence slice and the
    table addressing it (≡ "each rank's kv shard's kv_table",
    sp_flash_decode_layer.py:78-84):

    * k_pool/v_pool: (R·npages_local, Hkv, page, D) sharded P(axis) on
      dim 0 — rank r's local pool is its shard.
    * block_table: (R, B, pages_per_slice) sharded P(axis), LOCAL page
      ids into each rank's own pool shard.
    * q, global_kv_lens replicated. Returns (B, Hq, D) replicated
      (+ the merged (B, Hq) lse with ``with_lse``).
    """
    local_fn, merge_fn = _sp_paged_fns(
        mesh, axis, scale, soft_cap, use_pallas, with_lse, interp_key()
    )
    out, lse = local_fn(q, k_pool, v_pool, global_kv_lens, block_table)
    return merge_fn(out, lse)
