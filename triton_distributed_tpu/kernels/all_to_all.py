"""Dense AllToAll kernel (equal splits).

Reference: the transport layer under fast_all_to_all
(python/triton_dist/kernels/nvidia/low_latency_all_to_all.py:36-118) —
one block per peer, putmem_nbi of that peer's range, fence, signal. The
MoE splits-aware dispatch/combine built on this lives in
``kernels/moe_all_to_all.py``.

TPU re-design: one kernel per device issues n-1 concurrent RDMAs, slice j
of the local input going to peer j's slot me, then waits for its n-1
arrivals. The recv DMA semaphore plays the role of the reference's
``signal_op/signal_wait_until`` call-count protocol.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import interp_key
from triton_distributed_tpu.utils.testing import chaos_delay


def _a2a_kernel(n, axis, mesh_axes, x_ref, out_ref, send_sem, recv_sem):
    me = lang.my_pe(axis)
    m = x_ref.shape[0] // n

    out_ref[pl.ds(me * m, m)] = x_ref[pl.ds(me * m, m)]
    lang.barrier_all(axis, mesh_axes)

    handles = []
    for i in range(n - 1):
        pi = jax.lax.rem(me + 1 + i, n)
        peer = lang.pe_flat(axis, pi, mesh_axes)
        chaos_delay(site="all_to_all", step=i, me=me, n=n)
        handles.append(
            lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],      # lands in peer's slot `me`
                x_ref.at[pl.ds(pi * m, m)],        # my rows destined to peer
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            )
        )
    lang.quiet(*handles)
    for h in handles:
        h.wait_recv()


@functools.lru_cache(maxsize=256)
def _build_a2a_call(mesh_axes, axis, n, local_shape, dtype, collective_id,
                    chaos=False):
    """Bare per-device Pallas a2a call — usable inside any shard_map over
    a mesh with ``mesh_axes`` (the device variant; ≡ how flash_decode
    exposes sp_gqa_fwd_batch_decode_device for composition)."""
    assert local_shape[0] % n == 0, (
        f"per-device rows {local_shape[0]} not divisible by {n}"
    )
    call = lang.shmem_call(
        functools.partial(_a2a_kernel, n, axis, mesh_axes),
        out_shape=jax.ShapeDtypeStruct(local_shape, dtype),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=collective_id,
        name="a2a_dense",
    )
    return lang.maybe_instrument(
        call, axis=axis, site="all_to_all", collective_id=collective_id, n=n
    )


def all_to_all_device(x_loc, n, axis, mesh_axes, *, collective_id: int = 4):
    """Dense a2a on this device's shard, callable inside shard_map.

    ``x_loc``: (rows, ...) with rows divisible by ``n`` (= size of
    ``axis``). Row block j goes to peer j's block ``me``.
    """
    if n == 1:
        return x_loc
    call = _build_a2a_call(
        tuple(mesh_axes), axis, n, tuple(x_loc.shape),
        jnp.dtype(x_loc.dtype), collective_id, interp_key(),
    )
    return call(x_loc)


@functools.lru_cache(maxsize=256)
def _build_all_to_all(mesh, axis, shape, dtype, collective_id, chaos):
    n = mesh.shape[axis]
    local_shape = (shape[0] // n,) + tuple(shape[1:])
    call = _build_a2a_call(
        mesh.axis_names, axis, n, local_shape, dtype, collective_id, chaos
    )
    fn = jax.shard_map(
        call, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    return jax.jit(fn)


def all_to_all(x, mesh, axis: str = "x", *, collective_id: int = 4):
    """Equal-split AllToAll along dim 0 (row block j of device i → row block
    i of device j). Input/output sharded P(axis) on dim 0."""
    from triton_distributed_tpu.config import pallas_collectives_available

    if not pallas_collectives_available():
        # off-TPU without the TPU-simulation interpreter: XLA-native twin
        return all_to_all_xla(x, mesh, axis)
    n = mesh.shape[axis]
    if n == 1:
        return x
    fn = _build_all_to_all(
        mesh, axis, x.shape, x.dtype, collective_id, interp_key()
    )
    return fn(x)


def all_to_all_xla(x, mesh, axis: str = "x"):
    """lax.all_to_all reference implementation (correctness baseline)."""

    def per_device(xs):
        n = jax.lax.axis_size(axis)
        xs = xs.reshape((n, xs.shape[0] // n) + xs.shape[1:])
        out = jax.lax.all_to_all(xs, axis, split_axis=0, concat_axis=0, tiled=False)
        return out.reshape((-1,) + out.shape[2:])

    fn = jax.shard_map(
        per_device, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    return jax.jit(fn)(x)
