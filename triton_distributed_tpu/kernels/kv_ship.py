"""KV page shipping: prefill slice → decode slice on the quantized wire.

Disaggregated serving separates prefill from decode because their
rooflines differ (prefill is compute-bound, decode bandwidth-bound —
mixing them in one batch makes each steal the other's headroom); the
price is moving every finished request's KV cache between the roles.
This module is that transport:

* **Payload layout** — the pool's NATIVE quantized form travels
  verbatim: int8 page payloads ``(P·page, ·)`` with their per-row f32
  scale planes riding a parallel rail (the ``lang.wire`` paired-rail
  layout with ``chunk_rows == 1`` — the KV cache's own per-row scale
  granularity). No requantization happens anywhere on the path, so an
  int8-KV request decodes TOKEN-EXACTLY as if it had prefilled on the
  decode slice, and the wire moves ~half the bytes a dequantized
  bf16 ship would.

* **XLA-side helpers** (:func:`gather_kv_pages` /
  :func:`scatter_kv_pages`) — the pool↔payload plumbing the serving
  engines jit: gather a request's pages out of every layer's pool,
  scatter arrivals into the decode pool at block-table-assigned slots.
  Shared by every transport (the DCN ``ppermute`` rail, the
  ``device_put`` fallback, and this kernel's launch wrapper), so the
  bytes on every path are identical by construction.

* **The Pallas SHMEM kernel** (:func:`_kv_ship_kernel`) — the
  ICI-role-split transport: when both roles live on one slice (a
  2×(n/2) partition of a single torus), pages move rank→rank by remote
  DMA, each page's payload and scale plane driven as one dual-rail
  handle (the ring machinery's ``_DualDMA`` discipline: the receive
  wait releases only when BOTH rails have landed, so a landed page can
  never be consumed with a half-landed scale plane). Registered as the
  ``kv_ship.pages`` lint family with a pairwise PERMUTE delivery
  contract — every page lands exactly once, at its assigned slot, from
  exactly its partner rank (SL008), with the scale rail paired on its
  own semaphores (SL009) — and preflighted by the Mosaic scan like
  every family.

The role-pair topology: rank ``r`` ships to ``(r + n//2) % n`` — on an
even mesh this is exactly the slice split (prefill ranks [0, n/2) each
feed their head-shard twin decode rank), and it stays a bijection on
the odd lint meshes the analyzer also runs.
"""

from __future__ import annotations

import functools

import numpy as np

from triton_distributed_tpu import lang
from triton_distributed_tpu.lang import wire as wirelib

_SITE = "kv_ship"


# ------------------------------------------------- XLA-side pool plumbing

def gather_kv_pages(layers, pids):
    """Pull pages ``pids`` (P,) out of every layer's K and V pool.

    Returns ``(q_payload, s_payload)``: ``q`` stacked
    ``(L·2, P, Hkv, page, D)`` in the pool dtype (int8 under
    ``kv_quant`` — the wire payload IS the pool bytes), ``s`` the
    matching ``(L·2, P, Hkv, page)`` f32 scale planes, or None for
    unquantized pools (raw wire)."""
    import jax.numpy as jnp

    qs, ss = [], []
    for kp, vp in layers:
        for pool in (kp, vp):
            if isinstance(pool, dict):
                qs.append(pool["q"][pids])
                ss.append(pool["scale"][pids])
            else:
                qs.append(pool[pids])
    q = jnp.stack(qs)
    s = jnp.stack(ss) if ss else None
    return q, s


def scatter_kv_pages(layers, pids, q_payload, s_payload):
    """Inverse of :func:`gather_kv_pages`: land the arrived payload in
    the destination pools at page slots ``pids`` (the decode block
    table's assignment). Meant to be jitted with ``layers`` donated —
    the landing aliases in place like the serving step's append."""
    new, i = [], 0
    for kp, vp in layers:
        pair = []
        for pool in (kp, vp):
            if isinstance(pool, dict):
                pool = {
                    "q": pool["q"].at[pids].set(q_payload[i]),
                    "scale": pool["scale"].at[pids].set(s_payload[i]),
                }
            else:
                pool = pool.at[pids].set(q_payload[i])
            pair.append(pool)
            i += 1
        new.append(tuple(pair))
    return tuple(new)


def ship_wire_bytes(n_pages: int, page: int, hkv: int, d: int,
                    n_layers: int, quant: bool = True) -> int:
    """Bytes one request's KV ship puts on the wire: K and V pages for
    every layer — 1 B/element int8 payload plus the per-row f32 scale
    planes under ``kv_quant``, else the raw 2 B/element pages."""
    per_page = hkv * page * d * (1 if quant else 2)
    if quant:
        per_page += hkv * page * 4          # the per-row scale plane
    return n_layers * 2 * n_pages * per_page


# --------------------------------------------------- the Pallas transport

def _kv_ship_kernel(
    n, axis, mesh_axes, pages, rows,
    dstpg_ref, src_q, src_s, dst_q, dst_s,
    send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """Pairwise page ship: every rank pushes its ``pages`` staged pages
    (each ``rows`` rows of payload + its per-row scale plane) to its
    partner rank's pool at the LANDING slots ``dstpg_ref`` assigned by
    the receiver's block table, one dual-rail DMA pair per page.

    Per-page semaphore slots: page i's arrival can only credit slot i,
    so a wait being satisfied proves THAT page (and its scale plane —
    own rail, own semaphores) landed. After the waits, each landed
    page/scale pair is installed-as-quantized: the pool keeps the int8
    bytes and their scales (the attention kernel folds the scales at
    read time), which :func:`lang.wire.epilogue_consume` records as the
    consume-with-scale provenance edge — leaving a page uninstalled is
    SL008 against the permute contract, installing one without its
    scale plane is SL009."""
    me = lang.my_pe(axis)
    to = lang.pe_flat(axis, (me + n // 2) % n, mesh_axes)

    lang.barrier_all(axis, mesh_axes)

    from jax.experimental import pallas as pl

    handles = []
    for i in range(pages):
        slot = dstpg_ref[i]
        dq = lang.remote_copy(
            src_q.at[pl.ds(i * rows, rows)],
            dst_q.at[pl.ds(slot * rows, rows)],
            send_sem.at[i], recv_sem.at[i], to,
        )
        ds = lang.remote_copy(
            src_s.at[pl.ds(i * rows, rows)],
            dst_s.at[pl.ds(slot * rows, rows)],
            s_send_sem.at[i], s_recv_sem.at[i], to,
        )
        dq.start()
        ds.start()
        handles.append((dq, ds))
    for dq, ds in handles:
        lang.quiet(dq, ds)
    # the n//2-shifted inbound partner ships the same page count with
    # the same landing table, so waiting my own descriptors' recv side
    # releases exactly when MY pool has page i + scales resident
    for dq, ds in handles:
        dq.wait_recv()
        ds.wait_recv()
    for i in range(pages):
        slot = dstpg_ref[i]
        wirelib.epilogue_consume(
            dst_q.at[pl.ds(slot * rows, rows)],
            dst_s.at[pl.ds(slot * rows, rows)],
            None,
        )


#: lint geometry: 4 staged pages of 8 rows × 128 lanes, landing slots a
#: permutation of the whole destination buffer (zero slack, so the
#: permute contract can demand FULL exactly-once coverage).
KV_SHIP_GEOM = dict(pages=4, rows=8, cols=128)


@functools.lru_cache(maxsize=32)
def _build_kv_ship(mesh, axis, pages, rows, cols, collective_id, token=()):
    """Construct the page-ship kernel via ``shmem_call`` (the LaunchSpec
    capture the analyzer and the Mosaic pre-flight read back). The
    dev-box serving engines ride the XLA transports; this is the
    ICI-role-split fast path and the family's analyzable body."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del token
    n = mesh.shape[axis]
    nsem = max(pages, 1)
    return lang.shmem_call(
        functools.partial(
            _kv_ship_kernel, n, axis, mesh.axis_names, pages, rows
        ),
        out_shape=[
            jax.ShapeDtypeStruct((pages * rows, cols), jnp.int8),
            jax.ShapeDtypeStruct(
                (pages * rows, wirelib.SCALE_LANES), jnp.float32
            ),
        ],
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + lang.vmem_specs(2),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),   # scale rail
            pltpu.SemaphoreType.DMA((nsem,)),
        ],
        collective_id=collective_id,
        name="kv_ship_pages",
    )


def build_lint_kernel(mesh, n, token=()):
    """The registry/pre-flight entry: construct the ship kernel at
    :data:`KV_SHIP_GEOM` exactly as production would (the partner
    rotation is baked from the mesh's rank count)."""
    del n                                  # read from the mesh
    g = KV_SHIP_GEOM
    return _build_kv_ship(
        mesh, "x", g["pages"], g["rows"], g["cols"], 14, token,
    )
