"""KV page shipping: prefill slice → decode slice on the quantized wire.

Disaggregated serving separates prefill from decode because their
rooflines differ (prefill is compute-bound, decode bandwidth-bound —
mixing them in one batch makes each steal the other's headroom); the
price is moving every finished request's KV cache between the roles.
This module is that transport:

* **Payload layout** — the pool's NATIVE quantized form travels
  verbatim: int8 page payloads ``(P·page, ·)`` with their per-row f32
  scale planes riding a parallel rail (the ``lang.wire`` paired-rail
  layout with ``chunk_rows == 1`` — the KV cache's own per-row scale
  granularity). No requantization happens anywhere on the path, so an
  int8-KV request decodes TOKEN-EXACTLY as if it had prefilled on the
  decode slice, and the wire moves ~half the bytes a dequantized
  bf16 ship would.

* **XLA-side helpers** (:func:`gather_kv_pages` /
  :func:`scatter_kv_pages`) — the pool↔payload plumbing the serving
  engines jit: gather a request's pages out of every layer's pool,
  scatter arrivals into the decode pool at block-table-assigned slots.
  Shared by every transport (the DCN ``ppermute`` rail, the
  ``device_put`` fallback, and this kernel's launch wrapper), so the
  bytes on every path are identical by construction.

* **The Pallas SHMEM kernel** (:func:`_kv_ship_kernel`) — the
  ICI-role-split transport: when both roles live on one slice (a
  2×(n/2) partition of a single torus), pages move rank→rank by remote
  DMA, each page's payload and scale plane driven as one dual-rail
  handle (the ring machinery's ``_DualDMA`` discipline: the receive
  wait releases only when BOTH rails have landed, so a landed page can
  never be consumed with a half-landed scale plane). Registered as the
  ``kv_ship.pages`` lint family with a pairwise PERMUTE delivery
  contract — every page lands exactly once, at its assigned slot, from
  exactly its partner rank (SL008), with the scale rail paired on its
  own semaphores (SL009) — and preflighted by the Mosaic scan like
  every family.

The role-pair topology: rank ``r`` ships to ``(r + n//2) % n`` — on an
even mesh this is exactly the slice split (prefill ranks [0, n/2) each
feed their head-shard twin decode rank), and it stays a bijection on
the odd lint meshes the analyzer also runs.
"""

from __future__ import annotations

import functools

import numpy as np

from triton_distributed_tpu import lang
from triton_distributed_tpu.lang import wire as wirelib

_SITE = "kv_ship"


# ------------------------------------------------- XLA-side pool plumbing

def gather_kv_pages(layers, pids):
    """Pull pages ``pids`` (P,) out of every layer's K and V pool.

    Returns ``(q_payload, s_payload)``: ``q`` stacked
    ``(L·2, P, Hkv, page, D)`` in the pool dtype (int8 under
    ``kv_quant`` — the wire payload IS the pool bytes), ``s`` the
    matching ``(L·2, P, Hkv, page)`` f32 scale planes, or None for
    unquantized pools (raw wire)."""
    import jax.numpy as jnp

    qs, ss = [], []
    for kp, vp in layers:
        for pool in (kp, vp):
            if isinstance(pool, dict):
                qs.append(pool["q"][pids])
                ss.append(pool["scale"][pids])
            else:
                qs.append(pool[pids])
    q = jnp.stack(qs)
    s = jnp.stack(ss) if ss else None
    return q, s


def scatter_kv_pages(layers, pids, q_payload, s_payload):
    """Inverse of :func:`gather_kv_pages`: land the arrived payload in
    the destination pools at page slots ``pids`` (the decode block
    table's assignment). Meant to be jitted with ``layers`` donated —
    the landing aliases in place like the serving step's append."""
    new, i = [], 0
    for kp, vp in layers:
        pair = []
        for pool in (kp, vp):
            if isinstance(pool, dict):
                pool = {
                    "q": pool["q"].at[pids].set(q_payload[i]),
                    "scale": pool["scale"].at[pids].set(s_payload[i]),
                }
            else:
                pool = pool.at[pids].set(q_payload[i])
            pair.append(pool)
            i += 1
        new.append(tuple(pair))
    return tuple(new)


def ship_wire_bytes(n_pages: int, page: int, hkv: int, d: int,
                    n_layers: int, quant: bool = True) -> int:
    """Bytes one request's KV ship puts on the wire: K and V pages for
    every layer — 1 B/element int8 payload plus the per-row f32 scale
    planes under ``kv_quant``, else the raw 2 B/element pages."""
    per_page = hkv * page * d * (1 if quant else 2)
    if quant:
        per_page += hkv * page * 4          # the per-row scale plane
    return n_layers * 2 * n_pages * per_page


# --------------------------------------------------- the Pallas transport

def _kv_ship_kernel(
    n, axis, mesh_axes, pages, rows, coalesce, rail,
    dstpg_ref, src_q, src_s, dst_q, dst_s,
    send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """Pairwise page ship: every rank pushes its ``pages`` staged pages
    (each ``rows`` rows of payload + its per-row scale plane) to its
    partner rank's pool at the LANDING slots ``dstpg_ref`` assigned by
    the receiver's block table, one dual-rail DMA pair per TICK.

    A tick moves ``coalesce`` consecutive staged pages in one
    descriptor (``coalesce=1`` is the classic per-page ship, byte-
    identical to the pre-schedule kernel); coalescing is only legal
    when the landing table assigns each tick's pages a CONTIGUOUS slot
    run (see :func:`coalesced_landing_ok`) — the caller, not this
    kernel, guarantees that.

    Per-tick semaphore slots: tick i's arrival can only credit slot i,
    so a wait being satisfied proves THAT tick's pages (and their scale
    planes) landed. ``rail`` places the scale plane's DMA:
    ``"paired"`` rides its own semaphores (legal); ``"shared"`` signals
    the payload's semaphores (a payload wait can be released by a scale
    arrival — SL009); ``"drop"`` ships no scales at all (the landed
    pages install as raw quantized bytes — SL009). After the waits,
    each landed page/scale pair is installed-as-quantized: the pool
    keeps the int8 bytes and their scales (the attention kernel folds
    the scales at read time), which :func:`lang.wire.epilogue_consume`
    records as the consume-with-scale provenance edge — leaving a page
    uninstalled is SL008 against the permute contract, installing one
    without its scale plane is SL009."""
    assert pages % coalesce == 0, (pages, coalesce)
    me = lang.my_pe(axis)
    to = lang.pe_flat(axis, (me + n // 2) % n, mesh_axes)

    lang.barrier_all(axis, mesh_axes)

    from jax.experimental import pallas as pl

    span = coalesce * rows
    ticks = pages // coalesce
    handles = []
    for i in range(ticks):
        slot = dstpg_ref[i * coalesce]
        dq = lang.remote_copy(
            src_q.at[pl.ds(i * span, span)],
            dst_q.at[pl.ds(slot * rows, span)],
            send_sem.at[i], recv_sem.at[i], to,
        )
        if rail == "drop":
            dq.start()
            handles.append((dq, None))
            continue
        s_snd = send_sem if rail == "shared" else s_send_sem
        s_rcv = recv_sem if rail == "shared" else s_recv_sem
        ds = lang.remote_copy(
            src_s.at[pl.ds(i * span, span)],
            dst_s.at[pl.ds(slot * rows, span)],
            s_snd.at[i], s_rcv.at[i], to,
        )
        dq.start()
        ds.start()
        handles.append((dq, ds))
    for dq, ds in handles:
        if ds is None:
            lang.quiet(dq)
        else:
            lang.quiet(dq, ds)
    # the n//2-shifted inbound partner ships the same page count with
    # the same landing table, so waiting my own descriptors' recv side
    # releases exactly when MY pool has tick i's pages + scales resident
    for dq, ds in handles:
        dq.wait_recv()
        if ds is not None:
            ds.wait_recv()
    for i in range(ticks):
        slot = dstpg_ref[i * coalesce]
        wirelib.epilogue_consume(
            dst_q.at[pl.ds(slot * rows, span)],
            None if rail == "drop"
            else dst_s.at[pl.ds(slot * rows, span)],
            None,
        )


#: lint geometry: 4 staged pages of 8 rows × 128 lanes, landing slots a
#: permutation of the whole destination buffer (zero slack, so the
#: permute contract can demand FULL exactly-once coverage).
KV_SHIP_GEOM = dict(pages=4, rows=8, cols=128)


def coalesced_landing_table(pages: int, coalesce: int):
    """A landing permutation every coalescing width can legally drive:
    consecutive staged pages within a tick land at CONSECUTIVE slots
    (one descriptor per tick needs one contiguous destination run),
    while tick groups land reversed so the table stays a non-identity
    permutation the contract must actually check. ``coalesce=1``
    reproduces the classic fully-reversed lint table."""
    ticks = pages // coalesce
    return [
        p
        for blk in reversed(range(ticks))
        for p in range(blk * coalesce, (blk + 1) * coalesce)
    ]


def coalesced_landing_ok(table, coalesce: int) -> bool:
    """True when ``table`` assigns each ``coalesce``-page tick a
    contiguous ascending slot run — the host-side legality check a
    production launch must pass before running a coalesced schedule."""
    table = [int(x) for x in table]
    if coalesce <= 1:
        return True
    if len(table) % coalesce:
        return False
    for t in range(0, len(table), coalesce):
        base = table[t]
        if table[t:t + coalesce] != list(range(base, base + coalesce)):
            return False
    return True


@functools.lru_cache(maxsize=32)
def _build_kv_ship(mesh, axis, pages, rows, cols, collective_id, token=(),
                   schedule=None):
    """Construct the page-ship kernel via ``shmem_call`` (the LaunchSpec
    capture the analyzer and the Mosaic pre-flight read back). The
    dev-box serving engines ride the XLA transports; this is the
    ICI-role-split fast path and the family's analyzable body.

    ``schedule``: an optional :class:`tune.schedule.GridSchedule` whose
    ``coalesce`` (pages per tick descriptor) and ``rail`` (scale-plane
    semaphore placement) knobs this builder threads into the kernel;
    None ≡ the default schedule, byte-identical to the pre-schedule
    per-page dual-rail ship."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del token
    coalesce = 1 if schedule is None else int(schedule.coalesce)
    rail = "paired" if schedule is None else str(schedule.rail)
    if pages % coalesce:
        raise ValueError(
            f"kv_ship: coalesce={coalesce} does not divide the staged "
            f"page count {pages}"
        )
    n = mesh.shape[axis]
    nsem = max(pages // coalesce, 1)
    return lang.shmem_call(
        functools.partial(
            _kv_ship_kernel, n, axis, mesh.axis_names, pages, rows,
            coalesce, rail,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((pages * rows, cols), jnp.int8),
            jax.ShapeDtypeStruct(
                (pages * rows, wirelib.SCALE_LANES), jnp.float32
            ),
        ],
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + lang.vmem_specs(2),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),   # scale rail
            pltpu.SemaphoreType.DMA((nsem,)),
        ],
        collective_id=collective_id,
        name="kv_ship_pages",
    )


def build_lint_kernel(mesh, n, token=(), schedule=None):
    """The registry/pre-flight entry: construct the ship kernel at
    :data:`KV_SHIP_GEOM` exactly as production would (the partner
    rotation is baked from the mesh's rank count). ``schedule`` threads
    a grid schedule through to the kernel (see :func:`_build_kv_ship`)."""
    del n                                  # read from the mesh
    g = KV_SHIP_GEOM
    return _build_kv_ship(
        mesh, "x", g["pages"], g["rows"], g["cols"], 14, token,
        schedule=schedule,
    )
