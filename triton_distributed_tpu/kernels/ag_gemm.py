"""AllGather-GEMM: tensor-parallel overlap of activation gather with matmul.

Reference: python/triton_dist/kernels/nvidia/allgather_gemm.py — context
(:407-490), producer copy engines + consumer persistent GEMM waiting
per-tile on shard-arrival barriers (:133-254, dl.wait+consume_token
:224-227), rank-swizzled tile order (:205-219), host entry ``ag_gemm``
(:539) and the multi-stream dispatcher (:586-661).

TPU re-design — no streams, two engines instead:

* ``PALLAS_FUSED``: ONE persistent Pallas kernel per device runs an
  HBM-streaming ring. Operands and the gathered-A workspace live in HBM
  (ANY memory space); the matmul is a tiled ``emit_pipeline`` whose
  (m, n, k) blocks are double-buffered HBM→VMEM DMAs, so the engine has
  no whole-working-set VMEM gate and engages at any shape (the Llama-7B
  TP8 north-star included — the reference's persistent TMA consumer GEMM,
  allgather_gemm.py:133-254, translated to Mosaic's DMA pipeline). At
  ring step ``s`` the kernel (1) waits on the recv DMA semaphore for
  shard ``(me-s)`` — the hardware equivalent of dl.wait+consume_token
  (:224-227) — (2) starts the RDMA forwarding that shard to the right
  neighbor (HBM→HBM over ICI, touching no VMEM), and (3) streams the
  shard through the MXU while the forward is in flight. Each rank starts
  on its own local shard, so the reference's rank-swizzled tile order
  falls out of the ring schedule naturally.
* ``XLA_RING``: shard_map loop of ``ppermute`` + ``jnp.dot`` — XLA's
  async collective-permute overlaps the hop with the matmul. This is the
  DCN path, mirroring the reference's inter-node engine
  (allgather.py:291-468).
* ``XLA_NAIVE``: all_gather → dot (the torch_ag_gemm-style baseline,
  reference test_ag_gemm.py).
"""

from __future__ import annotations

import enum
import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import fused_vmem_budget, interp_key
from triton_distributed_tpu.kernels.ring import AGWireRefs, ag_forward_ring
from triton_distributed_tpu.lang import wire as wirelib
from triton_distributed_tpu.runtime import (
    LinkKind,
    detect_topology,
    mesh_axes_size,
)

logger = logging.getLogger(__name__)
_warned = set()


def _warn_once(key, msg):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


class AGGemmMethod(enum.Enum):
    PALLAS_FUSED = "pallas_fused"
    XLA_RING = "xla_ring"
    XLA_NAIVE = "xla_naive"


# ------------------------------------------------------------- block chooser

#: default tile targets for the streaming matmul pipeline (bm, bk, bn).
#: Swept on a real v5e at the Llama-7B TP8 north-star shard
#: (8192×8192 @ 8192×3584 bf16) with the paired-median methodology:
#: (512, 2048, 1792) → 167 TFLOP/s vs 157 for (512, 512, 1792) and 161-162
#: for the 4096-bk / 1024-bm variants. GEMM-RS carries its own targets
#: (its north-star shape prefers whole-K tiles — see gemm_rs.py).
_TILE_TARGETS = (512, 2048, 1792)


def _divisor_block(dim: int, target: int, mult: int, strict: bool) -> int | None:
    """Largest divisor of ``dim`` ≤ ``target``, preferring multiples of
    ``mult`` (the hardware tile granule). ``strict`` (real-TPU): an
    unaligned *interior* block shape is a Mosaic lowering error, so only a
    multiple-of-mult divisor or the whole dim (single block — ragged
    edges are padded, interiors never misalign) is acceptable; off-TPU the
    interpreter ignores tiling and any divisor works."""
    best = None
    for b in range(min(target, dim), 0, -1):
        if dim % b == 0:
            if b % mult == 0:
                return b
            if best is None:
                best = b
    if strict and best != dim:
        return None
    return best


def pick_mm_blocks(m: int, k: int, n: int, itemsize: int,
                   budget: int | None = None, targets=None):
    """(bm, bk, bn) for the streaming matmul pipeline, or None if the shape
    admits no (TPU-lowerable) divisor blocking. Shrinks targets until the
    double-buffered tile working set fits the VMEM budget."""
    from triton_distributed_tpu.config import compiling_for_tpu

    budget = budget or fused_vmem_budget()
    strict = compiling_for_tpu()
    sublane = 8 * (4 // itemsize)  # (8·packing, 128) native tile
    tm, tk, tn = targets or _TILE_TARGETS
    while True:
        bm = _divisor_block(m, tm, sublane, strict)
        # bk is A's lane dim and B's sublane dim; 128 covers both granules
        bk = _divisor_block(k, tk, 128, strict)
        bn = _divisor_block(n, tn, 128, strict)
        if bm is None or bk is None or bn is None:
            return None
        # 2 A-tiles + 2 B-tiles + 2 out-tiles + 1 f32 accumulator
        work = 2 * (bm * bk + bk * bn) * itemsize + 2 * bm * bn * itemsize + 4 * bm * bn
        if work <= budget:
            return bm, bk, bn
        if tm <= 64 and tk <= 128 and tn <= 128:
            return None  # pathological budget
        tm, tk, tn = max(tm // 2, 64), max(tk // 2, 128), max(tn // 2, 128)


def mm_pipeline(mb, nb, kb, bm, bk, bn, acc_ref, *, m_off=0, n_off=0, out_m_off=None):
    """Tiled (m, n, k) matmul pipeline over HBM refs: C[out_m_off:, n_off:]
    = A[m_off:, :] @ B[:, n_off:] for one (mb·bm, kb·bk)×(kb·bk, nb·bn)
    slab. Offsets are *block* offsets (may be traced), so callers address
    shard windows without slicing the HBM refs (index arithmetic replaces
    the reference's rank-swizzled tile-id remap, allgather_gemm.py:205-219).
    ``out_m_off`` defaults to ``m_off`` (in-place shard layout); pass 0 to
    write a compact (mb·bm)-row slab (the GEMM-RS work buffers)."""
    if out_m_off is None:
        out_m_off = m_off

    def inner(a_ref, b_ref, o_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jnp.dot(
            a_ref[...], b_ref[...], preferred_element_type=jnp.float32
        )

        @pl.when(pl.program_id(2) == kb - 1)
        def _():
            o_ref[...] = acc_ref[...].astype(o_ref.dtype)

    return pltpu.emit_pipeline(
        inner,
        grid=(mb, nb, kb),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (m_off + i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, n_off + j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (out_m_off + i, n_off + j))
        ],
    )


def mm_q8_pipeline(mb, nb, kb, bm, bk, bn):
    """Tiled s8×s8 matmul pipeline with the wire scales folded into the
    accumulator epilogue — the dequant-free int8-MXU consumer. Operates
    over pre-sliced HBM refs ``(aq, asc, bq, bsc, out)``: aq the
    (mb·bm, kb·bk) int8 wire slab, asc its (mb, SCALE_LANES) scale
    plane (the int8-mxu wire pins ``chunk_rows == bm`` so row-block i's
    scale is exactly plane row i), bq/bsc the per-out-channel quantized
    weight (lang.wire.quantize_cols). The MXU runs its native s8×s8→s32
    path (2× the bf16 rate on v5e — the W8A8 grouped-GEMM measurement,
    kernels/group_gemm.py) and the rank-1 ``a_scale[chunk]·b_scale[n]``
    correction lands on the s32 accumulator at the last K step — exact,
    both scales are constant over the K reduction, the same epilogue
    shape as group_gemm's dequant epilogue. No per-arrival dequant pass
    runs and no bf16 copy of the slab ever exists."""

    def mk(acc_ref):
        def inner(aq_ref, as_ref, bq_ref, bs_ref, o_ref):
            @pl.when(pl.program_id(2) == 0)
            def _():
                acc_ref[...] = jnp.zeros_like(acc_ref)

            acc_ref[...] += jax.lax.dot_general(
                aq_ref[...], bq_ref[...],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )

            @pl.when(pl.program_id(2) == kb - 1)
            def _():
                # (1,1) chunk scale × (1,bn) channel scales → (1,bn),
                # sublane-broadcast onto the (bm,bn) accumulator (the
                # lane-replicated scale-plane idiom — never a scalar)
                o_ref[...] = (
                    acc_ref[...].astype(jnp.float32)
                    * (as_ref[:, :1] * bs_ref[...])
                ).astype(o_ref.dtype)

        return pltpu.emit_pipeline(
            inner,
            grid=(mb, nb, kb),
            in_specs=[
                pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                pl.BlockSpec(
                    (1, wirelib.SCALE_LANES), lambda i, j, kk: (i, 0)
                ),
                pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
                pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            ],
            out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))],
        )

    def run(acc_ref, aq_hbm, as_hbm, bq_hbm, bs_hbm, out_hbm):
        if wirelib.epilogue_consume(aq_hbm, as_hbm, out_hbm):
            return  # symbolic: the provenance edge replaces the pipeline
        mk(acc_ref)(aq_hbm, as_hbm, bq_hbm, bs_hbm, out_hbm)

    return run


# ----------------------------------------------------------- fused engine


def _fused_kernel(
    n, axis, mesh_axes, blocks, publish_local, schedule,
    x_hbm, b_hbm, out_hbm, ag_hbm, acc_ref, local_sem, send_sem, recv_sem,
):
    """HBM-streaming ring AG-GEMM. Per step: wait shard arrival → start
    forwarding it → stream it through the MXU while the RDMA is in flight
    (the ring protocol lives in kernels/ring.ag_forward_ring)."""
    me = lang.my_pe(axis)
    m = x_hbm.shape[0]  # shard rows
    k = x_hbm.shape[1]
    nl = b_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m // bm, nl // bn, k // bk

    # Publish the local shard into the gathered workspace (HBM→HBM local
    # DMA ≡ local_copy_and_barrier_all, allgather_gemm.py:100-117) — ONLY
    # when the caller wants the gathered activations back: the ring
    # forwards and consumes the local shard straight from x_hbm, so slab
    # ``me`` is otherwise never read and the copy would be dead bandwidth
    # on the overlap-critical step 0.
    if publish_local:
        cp = pltpu.make_async_copy(x_hbm, ag_hbm.at[pl.ds(me * m, m)], local_sem)
        cp.start()

    def consume(s, src, a_hbm, a_row_off):
        # Stream this shard through the MXU while the forward is in flight.
        mm_pipeline(
            mb, nb, kb, bm, bk, bn, acc_ref,
            m_off=a_row_off // bm, out_m_off=src * mb,
        )(a_hbm, b_hbm, out_hbm)

    ag_forward_ring(
        n, axis, mesh_axes, x_hbm, ag_hbm, m, send_sem, recv_sem, consume,
        site="ag_gemm", schedule=schedule,
    )
    if publish_local:
        cp.wait()


def _fused_kernel_w(
    n, axis, mesh_axes, blocks, publish_local, fmt, schedule,
    x_hbm, xq_hbm, xs_hbm, b_hbm,
    out_hbm, ag_hbm, agq_hbm, ags_hbm,
    acc_ref, local_sem, send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`_fused_kernel`: the ring moves the
    host-quantized slab (xq/xs, lang.wire layout) plus its scale plane
    and dequantizes each arrival into the bf16 ``ag_hbm`` workspace
    before the matmul pipeline consumes it. The local shard never
    crosses the wire, so it is consumed exact from ``x_hbm``."""
    me = lang.my_pe(axis)
    m = x_hbm.shape[0]
    k = x_hbm.shape[1]
    nl = b_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m // bm, nl // bn, k // bk

    if publish_local:
        # gathered-A contract: slab ``me`` is the EXACT local slab (it
        # never rode the wire), same as the raw-wire engine
        cp = pltpu.make_async_copy(x_hbm, ag_hbm.at[pl.ds(me * m, m)], local_sem)
        cp.start()

    def consume(s, src, a_hbm, a_row_off):
        mm_pipeline(
            mb, nb, kb, bm, bk, bn, acc_ref,
            m_off=a_row_off // bm, out_m_off=src * mb,
        )(a_hbm, b_hbm, out_hbm)

    wire = AGWireRefs(
        fmt=fmt, local_q=xq_hbm, local_s=xs_hbm, agq=agq_hbm, ags=ags_hbm,
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        dequant=wirelib.dequant_pipeline(m, k, fmt),
    )
    ag_forward_ring(
        n, axis, mesh_axes, x_hbm, ag_hbm, m, send_sem, recv_sem, consume,
        site="ag_gemm", wire=wire, schedule=schedule,
    )
    if publish_local:
        cp.wait()


def _fused_kernel_mx(
    n, axis, mesh_axes, blocks, fmt, schedule,
    xq_hbm, xs_hbm, bq_hbm, bs_hbm,
    out_hbm, agq_hbm, ags_hbm,
    acc_ref, send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """int8→MXU twin of :func:`_fused_kernel_w`: the ring moves the
    host-quantized slab + scale plane exactly like the int8 wire, but
    the wire ends AT THE MXU — every slab (the local one included, for
    uniform numerics against the per-channel-quantized weight) streams
    through the s8×s8 pipeline with the chunk scale folded into the
    accumulator epilogue. There is no per-arrival dequant pass, no bf16
    gathered workspace, and arrival traffic through VMEM is halved
    (1-byte A tiles)."""
    m = xq_hbm.shape[0]
    k = xq_hbm.shape[1]
    nl = bq_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m // bm, nl // bn, k // bk
    pipe = mm_q8_pipeline(mb, nb, kb, bm, bk, bn)

    def consume(s, src, a_hbm, a_row_off):
        del a_hbm, a_row_off  # int8 wire refs replace the bf16 workspace
        if s == 0:
            q_slab, s_rows = xq_hbm, xs_hbm
        else:
            q_slab = agq_hbm.at[pl.ds(src * m, m)]
            s_rows = ags_hbm.at[pl.ds(src * mb, mb)]
        pipe(acc_ref, q_slab, s_rows, bq_hbm, bs_hbm,
             out_hbm.at[pl.ds(src * m, m)])

    wire = AGWireRefs(
        fmt=fmt, local_q=xq_hbm, local_s=xs_hbm, agq=agq_hbm, ags=ags_hbm,
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        dequant=None,   # the epilogue IS the dequant
    )
    ag_forward_ring(
        n, axis, mesh_axes, xq_hbm, agq_hbm, m, send_sem, recv_sem, consume,
        site="ag_gemm", wire=wire, schedule=schedule,
    )


def _specs(axis, batch_axes, dcn_axis=None):
    """(in_specs, out_specs) for AG-GEMM under shard_map over the full mesh.

    Activation rows may additionally be sharded over ``batch_axes`` (data
    parallelism): the kernel then gathers only the ``axis`` (sequence/TP)
    factor of the rows inside each DP group. Hierarchical (``dcn_axis``):
    the TP factor spans (axis, dcn_axis) with axis-MAJOR row order, so
    the rail-gathered rows per ring slab are contiguous."""
    ba = tuple(batch_axes)
    # a 1-tuple of axis names is equivalent to the bare name for both
    # PartitionSpec and lax collectives, so no flat/hier branching
    tp_axes = (axis,) if dcn_axis is None else (axis, dcn_axis)
    a_spec = P(ba + tp_axes, None)
    b_spec = P(None, tp_axes)
    out_spec = P(ba if ba else None, tp_axes)
    return (a_spec, b_spec), out_spec


@functools.lru_cache(maxsize=256)
def _build_fused(
    mesh, axis, batch_axes, a_shape, b_shape, dtype, out_dtype, collective_id,
    chaos, return_gathered=True, dcn_axis=None, wire=None,
    b_prequant=False, schedule=None,
):
    """Fused engine. ``dcn_axis`` set = the hierarchical decomposition
    (≡ the reference's inter-node AG-GEMM, allgather.py:291-375): the
    DCN rail leg feeds the SAME fused Pallas ring, which runs
    intra-slice over ``axis``. Row layout is axis-major — rows sharded
    P((axis, dcn_axis)) — so railed rows stay slab-contiguous.

    Round 4 (VERDICT r3 #5): the rail is CHUNKED for overlap — instead
    of one serial ``all_gather`` completing before the ring starts, the
    other slices' rows arrive as nd−1 INDEPENDENT ``ppermute`` fetches
    issued up front, and the fused ring runs once per slice chunk
    (local slice first, railed chunks as they land). Nothing in the
    chunk-s ring depends on chunk s+1's fetch, so XLA's async collective
    machinery can fly the DCN legs under the Mosaic calls (≡ the
    reference running inter-node puts concurrently with intra-node
    copies and the consumer GEMM, allgather.py:291-375). Falls back to
    the serial rail when the per-slice slab admits no blocking."""
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    k = a_shape[1]
    n_local = b_shape[1] // (n * nd)
    dp = mesh_axes_size(mesh, batch_axes)
    m_gathered = a_shape[0] // dp  # rows per device after the full AG
    slab_rows = m_gathered // n    # rows per ring step (nd shards railed)
    blocks = pick_mm_blocks(slab_rows, k, n_local, dtype.itemsize)
    if blocks is None:
        raise ValueError(
            f"ag_gemm PALLAS_FUSED: no divisor blocking for shard "
            f"({slab_rows}, {k}) @ ({k}, {n_local}); use XLA_RING"
        )
    if n == 1:
        # degenerate ring: ag_forward_ring early-returns without touching
        # the barrier semaphore, and Mosaic rejects a collective_id on a
        # kernel that never does (same convention as gemm_rs)
        collective_id = None
    fmt = None
    rail_fmt = None
    mx = wire == "int8-mxu"
    if b_prequant and not (mx and dcn_axis is None):
        raise ValueError(
            "b_prequant (weight-resident B) requires wire='int8-mxu' "
            "on a flat mesh"
        )
    m_dev = m_gathered // (n * nd)
    if wire is not None and dcn_axis is not None:
        # hierarchical: the wire rides the DCN RAIL legs (XLA-side
        # quant/dequant around the ppermute fetches / serial gather —
        # Mosaic cast support is irrelevant there); the intra-slice
        # Pallas rings stay on the raw wire. int8-mxu demotes to its
        # int8 payload: the rail dequantizes before any ring consumes.
        rail_fmt = wirelib.make_wire_format(
            wirelib.wire_payload(wire), m_dev, strict=False
        )
        mx = False
    elif mx:
        wirelib.require_mxu("ag_gemm")
        # one scale row per mm row-block: the epilogue's (1, 128) scale
        # operand then indexes plane row i for A row-block i directly
        fmt = wirelib.WireFormat(quant="int8", chunk_rows=blocks[0])
    elif wire is not None:
        from triton_distributed_tpu.config import compiling_for_tpu

        wirelib.require_inkernel(wire, "ag_gemm")
        fmt = wirelib.make_wire_format(
            wire, slab_rows, strict=compiling_for_tpu()
        )
        if fmt is None:
            raise ValueError(
                f"ag_gemm wire={wire!r}: slab of {slab_rows} rows admits "
                "no legal scale chunking; use the bf16 wire"
            )

    def mk_call(m_g, blk, cid):
        if mx:
            nsem = (max(n - 1, 1),)
            return lang.shmem_call(
                functools.partial(
                    _fused_kernel_mx, n, axis, mesh.axis_names, blk, fmt,
                    schedule,
                ),
                out_shape=[
                    jax.ShapeDtypeStruct((m_g, n_local), out_dtype),
                    # the wire workspace IS the gathered representation:
                    # no bf16 twin exists — arrival HBM/VMEM is halved
                    jax.ShapeDtypeStruct((m_g, k), fmt.wire_dtype),
                    jax.ShapeDtypeStruct(
                        (m_g // blk[0], wirelib.SCALE_LANES), jnp.float32
                    ),
                ],
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
                out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
                scratch_shapes=[
                    pltpu.VMEM((blk[0], blk[2]), jnp.int32),  # s32 acc
                    pltpu.SemaphoreType.DMA(nsem),
                    pltpu.SemaphoreType.DMA(nsem),
                    pltpu.SemaphoreType.DMA(nsem),   # scale rail
                    pltpu.SemaphoreType.DMA(nsem),
                ],
                collective_id=cid,
                vmem_limit_bytes=fused_vmem_budget(),
                name="ag_gemm_fused_int8mxw",
            )
        if fmt is not None:
            nsem = (max(n - 1, 1),)
            return lang.shmem_call(
                functools.partial(
                    _fused_kernel_w, n, axis, mesh.axis_names, blk,
                    return_gathered, fmt, schedule,
                ),
                out_shape=[
                    jax.ShapeDtypeStruct((m_g, n_local), out_dtype),
                    jax.ShapeDtypeStruct((m_g, k), dtype),      # gathered A
                    # wire workspaces: quantized slabs + scale planes
                    jax.ShapeDtypeStruct((m_g, k), fmt.wire_dtype),
                    jax.ShapeDtypeStruct(
                        (fmt.chunks(m_g), wirelib.SCALE_LANES), jnp.float32
                    ),
                ],
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
                out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
                scratch_shapes=[
                    pltpu.VMEM((blk[0], blk[2]), jnp.float32),
                    pltpu.SemaphoreType.DMA,
                    pltpu.SemaphoreType.DMA(nsem),
                    pltpu.SemaphoreType.DMA(nsem),
                    pltpu.SemaphoreType.DMA(nsem),   # scale rail
                    pltpu.SemaphoreType.DMA(nsem),
                ],
                collective_id=cid,
                vmem_limit_bytes=fused_vmem_budget(),
                name=f"ag_gemm_fused_{wire}w",
            )
        return lang.shmem_call(
            functools.partial(
                _fused_kernel, n, axis, mesh.axis_names, blk,
                return_gathered, schedule,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((m_g, n_local), out_dtype),
                jax.ShapeDtypeStruct((m_g, k), dtype),  # gathered A
            ],
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            scratch_shapes=[
                pltpu.VMEM((blk[0], blk[2]), jnp.float32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
                pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            ],
            collective_id=cid,
            vmem_limit_bytes=fused_vmem_budget(),
            name="ag_gemm_fused",
        )

    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)
    ba = tuple(batch_axes)
    ag_spec = P(ba if ba else None, None)
    chunk_blocks = (
        pick_mm_blocks(m_dev, k, n_local, dtype.itemsize)
        if dcn_axis is not None and nd > 1 else None
    )
    if dcn_axis is None:
        call = lang.maybe_instrument(
            mk_call(m_gathered, blocks, collective_id),
            axis=axis, site="ag_gemm", collective_id=collective_id, n=n,
        )
        if fmt is None:
            body = call
        elif mx and b_prequant:
            def body(a_loc, bq_loc, bs_loc):
                # weight-RESIDENT int8-mxu: B's (bq, bs) pair arrives
                # pre-quantized (quantize_grouped_weights convention) —
                # only the moving A slab quantizes per call
                aq, asc = wirelib.quantize_slab(a_loc, fmt)
                out, agq, ags = call(aq, asc, bq_loc, bs_loc)
                if not return_gathered:
                    return out, agq
                g = wirelib.dequantize_slab(agq, ags, fmt, dtype)
                me = jax.lax.axis_index(axis)
                return out, jax.lax.dynamic_update_slice(
                    g, a_loc, (me * slab_rows, 0)
                )
        elif mx:
            def body(a_loc, b_loc):
                # both operands quantized ONCE in XLA (fuse with their
                # producers); the kernel consumes wire bytes end to end
                aq, asc = wirelib.quantize_slab(a_loc, fmt)
                bq, bsc = wirelib.quantize_cols(b_loc)
                out, agq, ags = call(aq, asc, bq, bsc)
                if not return_gathered:
                    # the gathered output is dead to the caller — hand
                    # back the wire workspace untouched (no dequant ever)
                    return out, agq
                g = wirelib.dequantize_slab(agq, ags, fmt, dtype)
                me = jax.lax.axis_index(axis)
                return out, jax.lax.dynamic_update_slice(
                    g, a_loc, (me * slab_rows, 0)
                )
        else:
            def body(a_loc, b_loc):
                # quantize the local slab ONCE in XLA (fuses with the
                # producer); the ring forwards these exact wire bytes
                aq, asc = wirelib.quantize_slab(a_loc, fmt)
                out = call(a_loc, aq, asc, b_loc)
                return out[0], out[1]
    elif chunk_blocks is None:
        call = mk_call(m_gathered, blocks, collective_id)

        def body(a_loc, b_loc):
            # serial rail fallback: gather my axis-position's rows across
            # slices (axis-major rows → the railed slab is contiguous),
            # over the quantized rail when the wire is on
            if rail_fmt is None:
                ag = jax.lax.all_gather(a_loc, dcn_axis, tiled=True)
            else:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_all_gather,
                )

                ag = dcn_wire_all_gather(a_loc, dcn_axis, rail_fmt)
            return call(ag, b_loc)
    else:
        # distinct collective_ids per chunk ring: strict per-chunk
        # rendezvous on the barrier semaphore (a skewed neighbor's
        # chunk-s+1 signal must not satisfy a chunk-s wait); the offset
        # range is reserved in the registry's rail ledger (checked
        # disjoint from every other chunked family)
        from triton_distributed_tpu.kernels.registry import rail_collective_id

        chunk_calls = [
            mk_call(
                n * m_dev, chunk_blocks,
                rail_collective_id("ag_gemm.dcn_chunks", collective_id, s),
            )
            for s in range(nd)
        ]

        def body(a_loc, b_loc):
            my = jax.lax.axis_index(dcn_axis)
            # nd−1 independent rail fetches, all issued before any ring:
            # chunk s holds slice (my − s)'s rows. With the rail wire on,
            # each fetch moves the once-quantized payload + scale plane
            # (≈2× fewer DCN bytes) and dequantizes on arrival.
            if rail_fmt is not None:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_fetches,
                )

                chunks = dcn_wire_fetches(a_loc, dcn_axis, nd, rail_fmt)
            else:
                chunks = [a_loc] + [
                    jax.lax.ppermute(
                        a_loc, dcn_axis,
                        [(i, (i + s) % nd) for i in range(nd)],
                    )
                    for s in range(1, nd)
                ]
            pieces = [
                chunk_calls[s](chunks[s], b_loc) for s in range(nd)
            ]
            o = jnp.stack([p[0] for p in pieces])   # (nd, n·m_dev, n_local)
            g = jnp.stack([p[1] for p in pieces])   # (nd, n·m_dev, k)
            order = jnp.mod(my - jnp.arange(nd), nd)  # chunk idx per slice

            def reorder(x):
                # chunk-major → the axis-major global row order the
                # out_specs promise: [axis pos][slice][m_dev]
                x = jnp.take(x, order, axis=0)
                x = x.reshape(nd, n, m_dev, x.shape[-1])
                return jnp.transpose(x, (1, 0, 2, 3)).reshape(
                    n * nd * m_dev, x.shape[-1]
                )

            if not return_gathered:
                # the gathered-A output is dead to the caller — a flat
                # reshape satisfies the shape without paying a ~full-A
                # gather+transpose copy per step
                return reorder(o), g.reshape(n * nd * m_dev, k)
            return reorder(o), reorder(g)
    if b_prequant:
        # (a, bq, bs): the scale row shards like B's columns
        in_specs = tuple(in_specs) + (in_specs[1],)
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(out_specs, ag_spec),
        check_vma=False,
    )
    return jax.jit(fn)


def ag_gemm_device(a_loc, b_loc, axis, *, out_dtype=None, wire=None,
                   b_quant=None):
    """Per-device XLA-ring AG-GEMM body — usable inside any shard_map.

    ppermute hops overlap the next step's dot via XLA async collective
    permute (the reference's comm-stream/GEMM-stream overlap, expressed
    through the XLA scheduler instead of streams).

    ``wire`` ('fp8'/'int8'): the hops carry the ONCE-quantized slab +
    per-chunk scales (lang.wire layout — the same bytes the fused wire
    ring ships) and each arrival is dequantized before its dot; the own
    shard never crosses the wire and is consumed exact.

    ``wire='int8-mxu'``: the standalone AG→matmul twin of the fused
    int8→MXU engine — identical rails, but every arriving slab (and the
    local one, for uniform numerics) feeds an s8×s8→s32 dot against the
    per-out-channel-quantized B with the chunk·channel scale product
    folded onto the accumulator; no dequantized copy of A ever exists.

    ``b_quant``: a PRE-QUANTIZED ``(bq (K, N) int8, bs (1, N) f32)``
    pair for the int8-mxu consumer (weight-residency: serving layers
    already holding ``quantize_grouped_weights``-style dicts pass the
    pair through instead of paying a per-call ``quantize_cols`` of B —
    the ROADMAP carried-forward item the engine's steady-state decode
    loop makes measurable). Only consumed when ``wire='int8-mxu'`` and
    the slab admits the wire layout; ``b_loc`` may then be None."""
    n = jax.lax.axis_size(axis)
    m_local = a_loc.shape[0]
    out_dtype = out_dtype or a_loc.dtype
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    mx = wire == "int8-mxu"
    fmt = None
    if wire is not None:
        from triton_distributed_tpu.config import compiling_for_tpu

        fmt = wirelib.make_wire_format(
            wire, m_local, strict=compiling_for_tpu()
        )
    if mx and fmt is not None:
        if b_quant is not None:
            bq, bs = b_quant          # resident pair: no per-call quant
        else:
            bq, bs = wirelib.quantize_cols(b_loc)
        q, sc = wirelib.quantize_slab(a_loc, fmt)
        # per-row expand of the lane-replicated chunk scales (XLA side —
        # the fused kernel instead pins chunk_rows == block_m)
        row_scale = jnp.repeat(sc[:, :1], fmt.chunk_rows, axis=0)

        def s8_tile(q_cur, rs_cur):
            acc = jax.lax.dot_general(
                q_cur, bq, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return (acc.astype(jnp.float32) * rs_cur * bs).astype(out_dtype)

        out = jnp.zeros((n * m_local, bq.shape[1]), out_dtype)
        out = jax.lax.dynamic_update_slice(
            out, s8_tile(q, row_scale), (me * m_local, 0)
        )

        def step_mx(s, carry):
            q_cur, sc_cur, out = carry
            q_cur = jax.lax.ppermute(q_cur, axis, perm=perm)
            sc_cur = jax.lax.ppermute(sc_cur, axis, perm=perm)
            src = jax.lax.rem(me + n - s, n)
            rs_cur = jnp.repeat(sc_cur[:, :1], fmt.chunk_rows, axis=0)
            out = jax.lax.dynamic_update_slice(
                out, s8_tile(q_cur, rs_cur), (src * m_local, 0)
            )
            return q_cur, sc_cur, out

        _, _, out = jax.lax.fori_loop(1, n, step_mx, (q, sc, out))
        return out
    if mx:
        if b_loc is None:
            # a resident pair whose slab admits no wire layout: widen
            # ONCE here (the degradation twin of the dequant-free path)
            bq, bs = b_quant
            b_loc = (bq.astype(jnp.float32) * bs).astype(a_loc.dtype)
        fmt = None  # no legal chunking: stay on the exact wire

    out = jnp.zeros((n * m_local, b_loc.shape[1]), out_dtype)
    if fmt is None:
        def step(s, carry):
            a_cur, out = carry
            src = jax.lax.rem(me + n - s, n)
            tile = jnp.dot(a_cur, b_loc, preferred_element_type=jnp.float32)
            out = jax.lax.dynamic_update_slice(
                out, tile.astype(out_dtype), (src * m_local, 0)
            )
            a_next = jax.lax.ppermute(a_cur, axis, perm=perm)
            return a_next, out

        a_cur, out = jax.lax.fori_loop(0, n - 1, step, (a_loc, out))
        src = jax.lax.rem(me + 1, n)  # after n-1 hops I hold shard me+1
        tile = jnp.dot(a_cur, b_loc, preferred_element_type=jnp.float32)
        return jax.lax.dynamic_update_slice(
            out, tile.astype(out_dtype), (src * m_local, 0)
        )

    # quantized wire: own shard exact, remote shards dequantized from the
    # once-quantized payload + scale plane riding the permute hops
    tile = jnp.dot(a_loc, b_loc, preferred_element_type=jnp.float32)
    out = jax.lax.dynamic_update_slice(
        out, tile.astype(out_dtype), (me * m_local, 0)
    )
    q, sc = wirelib.quantize_slab(a_loc, fmt)

    def step_w(s, carry):
        q_cur, sc_cur, out = carry
        q_cur = jax.lax.ppermute(q_cur, axis, perm=perm)
        sc_cur = jax.lax.ppermute(sc_cur, axis, perm=perm)
        src = jax.lax.rem(me + n - s, n)
        a_cur = wirelib.dequantize_slab(q_cur, sc_cur, fmt, a_loc.dtype)
        tile = jnp.dot(a_cur, b_loc, preferred_element_type=jnp.float32)
        out = jax.lax.dynamic_update_slice(
            out, tile.astype(out_dtype), (src * m_local, 0)
        )
        return q_cur, sc_cur, out

    _, _, out = jax.lax.fori_loop(1, n, step_w, (q, sc, out))
    return out


@functools.lru_cache(maxsize=256)
def _build_xla_ring(mesh, axis, batch_axes, out_dtype, dcn_axis=None,
                    wire=None, b_prequant=False):
    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)
    if b_prequant:
        # resident int8-mxu weights: body takes (a, bq, bs) — no
        # per-call quantize_cols of B (flat mesh only; the host entry
        # widens for hierarchical calls)
        assert dcn_axis is None and wire == "int8-mxu"
        (a_spec, b_spec), _ = (in_specs, out_specs)

        def body_q(a_loc, bq_loc, bs_loc):
            return ag_gemm_device(
                a_loc, None, axis, out_dtype=out_dtype, wire=wire,
                b_quant=(bq_loc, bs_loc),
            )

        return jax.jit(jax.shard_map(
            body_q, mesh=mesh, in_specs=(a_spec, b_spec, b_spec),
            out_specs=out_specs, check_vma=False,
        ))

    def body(a_loc, b_loc):
        if dcn_axis is not None:
            # same rail/ring split as the fused engine: DCN leg via
            # lax, ppermute ring intra-slice over nd× slabs — with the
            # wire on, the rail leg ships the quantized payload too
            w_rail = wirelib.wire_payload(wire)
            rail_fmt = (
                wirelib.make_wire_format(w_rail, a_loc.shape[0],
                                         strict=False)
                if w_rail is not None else None
            )
            if rail_fmt is not None:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_all_gather,
                )

                a_loc = dcn_wire_all_gather(a_loc, dcn_axis, rail_fmt)
            else:
                a_loc = jax.lax.all_gather(a_loc, dcn_axis, tiled=True)
        return ag_gemm_device(
            a_loc, b_loc, axis, out_dtype=out_dtype, wire=wire
        )

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_gather(mesh, axis, batch_axes, dcn_axis=None):
    """Standalone row-gather used when ``return_gathered=True`` rides an
    XLA engine (the fused engine produces the gathered A for free)."""
    ba = tuple(batch_axes)
    tp_axes = (axis,) if dcn_axis is None else (axis, dcn_axis)
    fn = jax.shard_map(
        lambda x: jax.lax.all_gather(x, tp_axes, tiled=True),
        mesh=mesh,
        in_specs=_specs(axis, batch_axes, dcn_axis)[0][0],
        out_specs=P(ba if ba else None, None),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_xla_naive(mesh, axis, batch_axes, out_dtype, dcn_axis=None):
    tp_axes = (axis,) if dcn_axis is None else (axis, dcn_axis)

    def body(a_loc, b_loc):
        a_full = jax.lax.all_gather(a_loc, tp_axes, tiled=True)
        return jnp.dot(a_full, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )

    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _engine_tuner(mesh, axis, batch_axes, out_dtype, collective_id,
                  return_gathered, dcn_axis=None, wire=None):
    """Measured engine selection for ``method=None`` (≡ wrapping the op
    in contextual_autotune, reference autotuner.py:97): every engine is
    benchmarked end to end per input shape, the winner persists on disk,
    and the MAX consensus keeps multi-process meshes aligned. Engines
    that cannot build for a shape (e.g. unblockable PALLAS_FUSED) fail
    to +inf and lose. out_dtype/collective_id/wire are part of the tuner
    name (and so the cache key): a winner for one out_dtype or wire
    format must not be applied to another it might not even build for."""
    from triton_distributed_tpu.tune.autotuner import method_tuner

    def run(a, b, *, method):
        return ag_gemm(
            a, b, mesh, axis, batch_axes=batch_axes,
            method=AGGemmMethod(method), out_dtype=out_dtype,
            collective_id=collective_id, return_gathered=return_gathered,
            dcn_axis=dcn_axis, wire_dtype=wire,
        )

    return method_tuner(
        f"ag_gemm[{dict(mesh.shape)}|{axis}|{batch_axes}|{out_dtype}|"
        f"{collective_id}|rg{int(return_gathered)}|{dcn_axis}|w{wire}]",
        run, AGGemmMethod,
    )


@functools.lru_cache(maxsize=64)
def _wire_tuner(mesh, axis, batch_axes, out_dtype, collective_id,
                return_gathered, dcn_axis=None, wq=None):
    """Measured wire-dtype selection for ``wire_dtype='auto'``: the
    bf16 wire and the fp8 wire are benchmarked end to end and the
    winner persists (the same thunk-level contract as the engine
    tuners — a wire format is just another config of the whole op).
    ``wq='int8'`` adds the dequant-free 'int8-mxu' candidate (the
    caller's weight intent is what makes its numerics acceptable) and
    is part of the tuner name, so winners never leak across intents."""
    from triton_distributed_tpu.tune.autotuner import wire_tuner

    def run(a, b, *, wire_dtype):
        # engine pinned to the static heuristic: the wire sweep must
        # compare wire formats on ONE engine, not recurse into the
        # engine tuner's own benching mid-measurement
        dp = mesh_axes_size(mesh, tuple(batch_axes))
        method = auto_ag_gemm_method(
            mesh, axis, a, b, dp=dp, dcn_axis=dcn_axis
        )
        return ag_gemm(
            a, b, mesh, axis, batch_axes=batch_axes, method=method,
            out_dtype=out_dtype, collective_id=collective_id,
            return_gathered=return_gathered, dcn_axis=dcn_axis,
            wire_dtype=wire_dtype,
        )

    return wire_tuner(
        f"ag_gemm_wire[{dict(mesh.shape)}|{axis}|{batch_axes}|{out_dtype}|"
        f"{collective_id}|rg{int(return_gathered)}|{dcn_axis}|wq{wq}]",
        run, mxu=(wq == "int8"),
    )


def auto_ag_gemm_method(mesh, axis, a, b, dp: int = 1,
                        dcn_axis: str | None = None) -> AGGemmMethod:
    """≡ reference method auto-selection (allgather.py:54-69): topology +
    shape blockability decide the engine. The streaming fused engine has no
    working-set VMEM gate; it is skipped only when the intra-slice ``axis``
    itself crosses DCN (no Pallas remote DMA across slices — declare the
    cross-slice factor as ``dcn_axis`` for the hierarchical engine) or on
    shapes with no divisor blocking — and the fallback is *logged* so
    nobody silently benchmarks XLA believing it is the fused kernel."""
    from triton_distributed_tpu.config import pallas_collectives_available

    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    if not pallas_collectives_available():
        _warn_once(
            ("ag_gemm", "nosim"),
            "ag_gemm: Pallas collectives unavailable off-TPU (jax lacks "
            "the TPU-simulation interpreter); using XLA_RING engine",
        )
        return AGGemmMethod.XLA_RING
    topo = detect_topology(mesh, axis)
    if topo.link_kind == LinkKind.DCN:
        _warn_once(
            ("ag_gemm", "dcn", axis),
            f"ag_gemm: axis {axis!r} crosses DCN; using XLA_RING engine "
            "(pass the cross-slice factor as dcn_axis= to keep the fused "
            "engine intra-slice)",
        )
        return AGGemmMethod.XLA_RING
    slab_rows = a.shape[0] // (dp * n)
    blocks = pick_mm_blocks(
        slab_rows, a.shape[1], b.shape[1] // (n * nd), a.dtype.itemsize
    )
    if blocks is None:
        _warn_once(
            ("ag_gemm", "blocks", a.shape, b.shape),
            f"ag_gemm: shard ({slab_rows}, {a.shape[1]}) @ "
            f"({a.shape[1]}, {b.shape[1] // (n * nd)}) admits no divisor "
            "blocking; falling back to XLA_RING",
        )
        return AGGemmMethod.XLA_RING
    return AGGemmMethod.PALLAS_FUSED


def resolve_ag_gemm_wire(
    mesh, axis, a, b, *, batch_axes=(), method=None, wire_dtype=None,
    dcn_axis: str | None = None, dp: int | None = None,
    wq: str | None = None,
) -> str | None:
    """The wire format :func:`ag_gemm` will ACTUALLY ship for these
    arguments: None (raw bf16 wire) unless a ring engine runs and the
    slab admits the lang.wire layout. ``'auto'`` consults the measured
    wire tuner (when tuning is enabled and args are concrete), else the
    perf model's comm-bound test — compressed exactly when the bf16
    ring transfer, not the shard matmul, is the per-step critical path,
    and picking the dequant-free ``'int8-mxu'`` consumer wire there
    when the caller declared an int8 weight intent (``wq='int8'``).

    Hierarchical (``dcn_axis``) calls resolve the wire for the DCN RAIL
    legs (the payload format the ppermute fetches ship; 'int8-mxu'
    demotes to its 'int8' payload — the rail dequantizes before any MXU
    sees it)."""
    from triton_distributed_tpu.config import compiling_for_tpu

    w = wirelib.normalize_wire(wire_dtype)
    if w is None:
        return None
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    if dp is None:
        dp = mesh_axes_size(mesh, tuple(batch_axes))
    if n * nd == 1:
        return None
    if method == AGGemmMethod.XLA_NAIVE:
        return None  # no ring — nothing to compress
    k = a.shape[1]
    if dcn_axis is not None:
        # the DCN rail wire: XLA-side quant/dequant around the rail legs
        # — runs on any backend, so only payload-layout eligibility gates
        m_dev = a.shape[0] // (dp * n * nd)
        if w == "auto":
            if not wirelib.wire_blockable(m_dev, k, "fp8", False):
                return None
            from triton_distributed_tpu.runtime.topology import (
                auto_allgather_wire,
            )

            # a DCN leg is always comm-bound relative to ICI; compress
            # whenever the payload clears the fixed-cost threshold
            return auto_allgather_wire(m_dev * k * a.dtype.itemsize)
        payload = wirelib.wire_payload(w)
        if not wirelib.wire_blockable(m_dev, k, payload, False):
            raise ValueError(
                f"ag_gemm wire_dtype={w!r}: DCN rail slab ({m_dev}, {k}) "
                "admits no legal wire chunking (a pinned wire format is "
                "a contract); use wire_dtype='auto' or the bf16 wire"
            )
        return payload
    slab_rows = a.shape[0] // (dp * n)
    strict = compiling_for_tpu()
    # in-kernel wire consumption happens only on the fused engine; XLA
    # engines carry fp8 / s8 dots natively regardless of Mosaic support
    inkernel = method == AGGemmMethod.PALLAS_FUSED
    if w == "auto":
        if not wirelib.wire_blockable(slab_rows, k, "fp8", strict):
            return None
        from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

        tuned = tuned_method_or_none(
            lambda: _wire_tuner(
                mesh, axis, tuple(batch_axes), jnp.dtype(a.dtype), 5,
                False, dcn_axis, wq,
            ),
            a, b, key="wire_dtype",
        )
        if tuned is not None:
            w = wirelib.normalize_wire(tuned)
        else:
            from triton_distributed_tpu.tune.perf_model import (
                auto_wire_dtype,
            )

            n_local = b.shape[1] // n
            w = wirelib.normalize_wire(auto_wire_dtype(
                slab_rows, k, n_local, a.dtype.itemsize, consumer_wq=wq,
            ))
        if w == "int8-mxu" and inkernel and not wirelib.inkernel_s8_dot_ok():
            # the caller already declared int8 numerics (wq='int8'), so
            # demoting to the dequant-then-matmul int8 wire is not a
            # silent numerics-class switch — only the MXU feed changes
            w = "int8"
        if w == "fp8" and inkernel and not wirelib.inkernel_wire_ok("fp8"):
            # no silent numerics switch to int8: auto keeps the exact
            # wire where the toolchain cannot carry fp8 in-kernel
            return None
        return w
    if inkernel:
        if w == "int8-mxu":
            wirelib.require_mxu("ag_gemm")
        else:
            wirelib.require_inkernel(w, "ag_gemm")
    if not wirelib.wire_blockable(slab_rows, k, w, strict):
        raise ValueError(
            f"ag_gemm wire_dtype={w!r}: slab ({slab_rows}, {k}) admits no "
            "legal wire chunking/blocking (a pinned wire format is a "
            "contract); use wire_dtype='auto' or the bf16 wire"
        )
    return w


def resolve_ag_gemm_method(
    a_mesh, axis, a, b, *, batch_axes=(), method=None, out_dtype=None,
    collective_id: int = 5, return_gathered: bool = False,
    dcn_axis: str | None = None, wire_dtype=None,
) -> AGGemmMethod:
    """The engine :func:`ag_gemm` will ACTUALLY run for these arguments:
    the explicit ``method``, else the tuned winner (when tuning is
    enabled and the args are concrete), else the topology/blockability
    heuristic — with the safety recheck demoting a fused winner that is
    not buildable in this environment. Exposed so callers that must act
    on the resolved engine (ops.overlap's save_gathered residual gate)
    agree with the entry instead of re-guessing."""
    if method is not None:
        return method
    from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(a_mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    m = tuned_method_or_none(
        lambda: _engine_tuner(
            a_mesh, axis, batch_axes, jnp.dtype(out_dtype), collective_id,
            return_gathered, dcn_axis, wirelib.normalize_wire(wire_dtype),
        ),
        a, b,
    )
    auto = functools.partial(
        auto_ag_gemm_method, a_mesh, axis, a, b, dp=dp, dcn_axis=dcn_axis
    )
    method = AGGemmMethod(m) if m else auto()
    if method == AGGemmMethod.PALLAS_FUSED and auto() != method:
        # a persisted winner from another environment (bigger VMEM
        # budget, non-DCN mesh) may no longer be buildable here; the
        # heuristic encodes exactly those safety constraints
        method = auto()
    return method


def ag_gemm(
    a,
    b,
    mesh,
    axis: str = "x",
    *,
    batch_axes: tuple = (),
    method: AGGemmMethod | None = None,
    out_dtype=None,
    collective_id: int = 5,
    return_gathered: bool = False,
    dcn_axis: str | None = None,
    wire_dtype=None,
    wq: str | None = None,
    b_quant=None,
    schedule=None,
):
    """Fused AllGather(A) @ B for column-parallel TP.

    ``wire_dtype``: what the ring ships (docs/PERF.md "Quantized wire").
    None/'bf16' — the raw compute dtype (default, today's numerics);
    'fp8'/'int8' — 1-byte payload + per-chunk f32 scales (lang.wire),
    quantized once at the source, dequantized on receive before the MXU
    (own shard consumed exact); 'int8-mxu' — the DEQUANT-FREE consumer
    wire: identical int8 rails, but every slab (local included) feeds
    the MXU's native s8×s8→s32 path against the per-out-channel
    quantized B, with the chunk·channel scale product folded into the
    accumulator epilogue — no per-arrival dequant pass, half the
    arrival VMEM, 2× the MXU rate; 'auto' — the measured wire tuner,
    else the perf model picks the compressed wire exactly when the bf16
    ring transfer is the per-step critical path (comm-bound shapes),
    preferring 'int8-mxu' there when ``wq='int8'``. With a compressed
    wire the gathered-A output (``return_gathered``) holds the
    dequantized remote slabs — inference-grade, like the MoE wire.

    ``wq``: the caller's weight-quantization intent ('int8' or None).
    It does not change B's storage here; it licenses the auto selector
    to pick 'int8-mxu', whose epilogue quantizes B per out-channel.

    ``b_quant``: PRE-QUANTIZED weight residency (ROADMAP carried-
    forward, closed by the serving engine's steady-state loop): a
    ``(bq (K, N) int8, bs per-out-channel f32)`` pair — or pass ``b``
    itself as a ``{"q", "scale"}`` dict (the
    ``quantize_grouped_weights`` convention) — and the int8-mxu
    consumers feed it straight to the s8×s8 epilogue with NO per-call
    ``quantize_cols`` of B. When the int8-mxu wire is not eligible
    (1-device mesh, hierarchical call, pinned other wire, slab without
    a wire layout), B is widened ONCE per call and the ordinary engine
    runs — the same degradation discipline as every other knob.

    ``a``: (M, K) with rows sharded over ``(*batch_axes, axis)`` — each
    device holds an M/(dp·n) row shard; the kernel gathers the ``axis``
    factor within each DP group (Megatron sequence-parallel layout).
    ``b``: (K, N) sharded P(None, axis) — column-parallel weight.
    Returns (M, N) with rows sharded over ``batch_axes``, cols over ``axis``.

    ``dcn_axis``: hierarchical TP spanning slices (≡ the reference's
    inter-node AG-GEMM, allgather.py:291-375). The TP factor is
    (axis, dcn_axis) with AXIS-MAJOR ordering — rows P((axis, dcn_axis)),
    weight cols likewise: the other slices' rows cross DCN as nd−1
    independent ``ppermute`` fetches feeding per-slice fused rings
    (local slice first), so the DCN legs fly under the Mosaic calls;
    a serial ``lax.all_gather`` rail feeding one nd×-slab ring is the
    fallback when the per-slice slab admits no blocking (see
    _build_fused and docs/PERF.md's DCN-overlap section).

    ``return_gathered=True`` additionally returns the gathered activations
    (the reference exposes them in its symmetric workspace; callers reuse
    them for subsequent ops). Only the fused engine produces them for free;
    other engines re-gather via ``lax.all_gather``.

    Host entry ≡ reference ``ag_gemm`` (allgather_gemm.py:539) +
    ``rowise_ag_gemm_dispatcher`` (:586-661).
    """
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    if isinstance(b, dict):
        # quantized-dict weight (the serving layers' storage): implies
        # the resident int8-mxu consumer
        b_quant = (b["q"], b["scale"])
        b = None
    if b_quant is not None:
        bq = b_quant[0]
        bs = jnp.asarray(b_quant[1], jnp.float32).reshape(1, -1)
        assert a.shape[1] == bq.shape[0], (
            f"contract dim mismatch {a.shape} @ {bq.shape}"
        )
        slab_rows = a.shape[0] // (dp * n * nd)
        eligible = (
            n * nd > 1 and dcn_axis is None
            and wirelib.normalize_wire(wire_dtype) in (None, "int8-mxu",
                                                       "auto")
            and wirelib.make_wire_format(
                "int8-mxu", slab_rows * nd, strict=False
            ) is not None
        )
        if eligible:
            proxy = jax.ShapeDtypeStruct(bq.shape, a.dtype)
            try:
                method = resolve_ag_gemm_method(
                    mesh, axis, a, proxy, batch_axes=batch_axes,
                    method=method, out_dtype=out_dtype,
                    collective_id=collective_id,
                    return_gathered=return_gathered,
                    wire_dtype="int8-mxu",
                )
            except Exception:
                method = AGGemmMethod.XLA_RING
            if method == AGGemmMethod.PALLAS_FUSED:
                try:
                    from triton_distributed_tpu.tune.schedule import (
                        resolve_schedule,
                    )

                    fn = _build_fused(
                        mesh, axis, batch_axes, a.shape, bq.shape,
                        a.dtype, jnp.dtype(out_dtype), collective_id,
                        interp_key(), return_gathered, None, "int8-mxu",
                        True,
                        resolve_schedule(
                            "ag_gemm.fused", a.shape, (n * nd,),
                            "int8-mxu", schedule,
                        ),
                    )
                    out, gathered = fn(a, bq, bs)
                    return (out, gathered) if return_gathered else out
                except ValueError:
                    pass                       # unblockable: XLA ring
            fn = _build_xla_ring(
                mesh, axis, batch_axes, jnp.dtype(out_dtype), None,
                "int8-mxu", True,
            )
            out = fn(a, bq, bs)
            if return_gathered:
                return out, _build_gather(mesh, axis, batch_axes, None)(a)
            return out
        # ineligible for the resident consumer: widen ONCE per call and
        # run the ordinary engine (documented degradation)
        b = (bq.astype(jnp.float32) * bs).astype(a.dtype)
    assert a.shape[0] % (n * nd * dp) == 0 and b.shape[1] % (n * nd) == 0
    assert a.shape[1] == b.shape[0], f"contract dim mismatch {a.shape} @ {b.shape}"
    if n * nd == 1:
        out = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
        return (out, a) if return_gathered else out
    method = resolve_ag_gemm_method(
        mesh, axis, a, b, batch_axes=batch_axes, method=method,
        out_dtype=out_dtype, collective_id=collective_id,
        return_gathered=return_gathered, dcn_axis=dcn_axis,
        wire_dtype=wire_dtype,
    )
    wire = resolve_ag_gemm_wire(
        mesh, axis, a, b, batch_axes=batch_axes, method=method,
        wire_dtype=wire_dtype, dcn_axis=dcn_axis, dp=dp, wq=wq,
    )
    if method == AGGemmMethod.PALLAS_FUSED:
        from triton_distributed_tpu.tune.schedule import resolve_schedule

        sched = resolve_schedule(
            "ag_gemm.fused", a.shape, (n * nd,), wire, schedule
        )
        if (
            sched is not None and sched.dequant == "epilogue"
            and wire == "int8" and dcn_axis is None
            and wirelib.inkernel_s8_dot_ok()
        ):
            # a searched epilogue-dequant schedule means the winner was
            # gated on the MXU-consumer kernel twin: the int8 payload is
            # consumed straight by the s8×s8 epilogue, no dequant pass
            wire = "int8-mxu"
        fn = _build_fused(
            mesh, axis, batch_axes, a.shape, b.shape, a.dtype, out_dtype,
            collective_id, interp_key(), return_gathered, dcn_axis, wire,
            False, sched,
        )
        out, gathered = fn(a, b)
        return (out, gathered) if return_gathered else out
    if method == AGGemmMethod.XLA_RING:
        fn = _build_xla_ring(
            mesh, axis, batch_axes, out_dtype, dcn_axis, wire
        )
    else:
        fn = _build_xla_naive(mesh, axis, batch_axes, out_dtype, dcn_axis)
    out = fn(a, b)
    if return_gathered:
        return out, _build_gather(mesh, axis, batch_axes, dcn_axis)(a)
    return out
