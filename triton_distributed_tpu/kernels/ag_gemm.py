"""AllGather-GEMM: tensor-parallel overlap of activation gather with matmul.

Reference: python/triton_dist/kernels/nvidia/allgather_gemm.py — context
(:407-490), producer copy engines + consumer persistent GEMM waiting
per-tile on shard-arrival barriers (:133-254, dl.wait+consume_token
:224-227), rank-swizzled tile order (:205-219), host entry ``ag_gemm``
(:539) and the multi-stream dispatcher (:586-661).

TPU re-design — no streams, two engines instead:

* ``PALLAS_FUSED``: ONE Pallas kernel per device runs a shard-granular
  ring: at step ``s`` it computes the MXU matmul for shard ``(me-s)``
  while the RDMA forwarding that same shard to the right neighbor is in
  flight. The DMA recv semaphore *is* the reference's per-tile barrier
  (dl.wait ≡ ``wait_recv``; consume_token is unnecessary because the
  semaphore wait orders the subsequent VMEM reads). Each rank starts on
  its own local shard — the reference's rank-swizzled tile order falls
  out of the ring schedule naturally.
* ``XLA_RING``: shard_map loop of ``ppermute`` + ``jnp.dot`` —
  XLA's async collective-permute overlaps the hop with the matmul. Works
  for any size (shards stream through HBM, not VMEM); this is the DCN /
  large-shape path, mirroring the reference's inter-node engine
  (allgather.py:291-468).
* ``XLA_NAIVE``: all_gather → dot (the torch_ag_gemm-style baseline,
  reference test_ag_gemm.py).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import config, fused_vmem_budget, on_tpu
from triton_distributed_tpu.runtime import (
    LinkKind,
    detect_topology,
    mesh_axes_size,
    ring_neighbors,
)
from triton_distributed_tpu.utils.testing import chaos_delay


class AGGemmMethod(enum.Enum):
    PALLAS_FUSED = "pallas_fused"
    XLA_RING = "xla_ring"
    XLA_NAIVE = "xla_naive"


def _fused_kernel(n, axis, mesh_axes, x_ref, b_ref, out_ref, ag_ref, send_sem, recv_sem):
    """Ring AG-GEMM. Per step: wait shard arrival → start forwarding it →
    matmul it against the local B shard while the RDMA is in flight."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)

    ag_ref[pl.ds(me * m, m)] = x_ref[:]
    lang.neighbor_barrier(axis, left, right)

    dmas = []
    for s in range(n):
        src = jax.lax.rem(me + n - s, n) if s > 0 else me
        if s > 0:
            # Shard ``src`` was sent by the left neighbor at its step s-1
            # and lands with a credit on recv_sem[s-1]. The descriptor we
            # wait on is our *outgoing* step s-1 copy — byte counts are
            # identical for every shard, so the recv wait releases exactly
            # when the incoming shard's payload is resident (the dl.wait +
            # consume_token of allgather_gemm.py:224-227, done by hardware).
            dmas[s - 1].wait_recv()
        if s < n - 1:
            chaos_delay()
            dma = lang.remote_copy(
                ag_ref.at[pl.ds(src * m, m)],
                ag_ref.at[pl.ds(src * m, m)],
                send_sem.at[s],
                recv_sem.at[s],
                right,
            )
            dma.start()
            dmas.append(dma)
        # MXU matmul for this shard, overlapped with the in-flight forward.
        out_ref[pl.ds(src * m, m)] = jnp.dot(
            ag_ref[pl.ds(src * m, m)], b_ref[:], preferred_element_type=jnp.float32
        ).astype(out_ref.dtype)
    for dma in dmas:
        dma.wait_send()


def _specs(axis, batch_axes):
    """(in_specs, out_specs) for AG-GEMM under shard_map over the full mesh.

    Activation rows may additionally be sharded over ``batch_axes`` (data
    parallelism): the kernel then gathers only the ``axis`` (sequence/TP)
    factor of the rows inside each DP group."""
    ba = tuple(batch_axes)
    row = ba + (axis,) if ba else axis
    a_spec = P(row, None)
    b_spec = P(None, axis)
    out_spec = P(ba if ba else None, axis)
    return (a_spec, b_spec), out_spec


@functools.lru_cache(maxsize=256)
def _build_fused(
    mesh, axis, batch_axes, a_shape, b_shape, dtype, out_dtype, collective_id, chaos
):
    n = mesh.shape[axis]
    k = a_shape[1]
    n_local = b_shape[1] // n
    dp = mesh_axes_size(mesh, batch_axes)
    m_gathered = a_shape[0] // dp  # rows per device after the AG over `axis`

    call = lang.shmem_call(
        functools.partial(_fused_kernel, n, axis, mesh.axis_names),
        out_shape=jax.ShapeDtypeStruct((m_gathered, n_local), out_dtype),
        in_specs=lang.vmem_specs(2),
        scratch_shapes=[
            pltpu.VMEM((m_gathered, k), dtype),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=collective_id,
        name="ag_gemm_fused",
    )
    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        call, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


def ag_gemm_device(a_loc, b_loc, axis, *, out_dtype=None):
    """Per-device XLA-ring AG-GEMM body — usable inside any shard_map.

    ppermute hops overlap the next step's dot via XLA async collective
    permute (the reference's comm-stream/GEMM-stream overlap, expressed
    through the XLA scheduler instead of streams)."""
    n = jax.lax.axis_size(axis)
    m_local = a_loc.shape[0]
    out_dtype = out_dtype or a_loc.dtype
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        a_cur, out = carry
        src = jax.lax.rem(me + n - s, n)
        tile = jnp.dot(a_cur, b_loc, preferred_element_type=jnp.float32)
        out = jax.lax.dynamic_update_slice(
            out, tile.astype(out_dtype), (src * m_local, 0)
        )
        a_next = jax.lax.ppermute(a_cur, axis, perm=perm)
        return a_next, out

    out = jnp.zeros((n * m_local, b_loc.shape[1]), out_dtype)
    a_cur, out = jax.lax.fori_loop(0, n - 1, step, (a_loc, out))
    src = jax.lax.rem(me + 1, n)  # after n-1 hops I hold shard me+1
    tile = jnp.dot(a_cur, b_loc, preferred_element_type=jnp.float32)
    return jax.lax.dynamic_update_slice(out, tile.astype(out_dtype), (src * m_local, 0))


@functools.lru_cache(maxsize=256)
def _build_xla_ring(mesh, axis, batch_axes, out_dtype):
    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        functools.partial(ag_gemm_device, axis=axis, out_dtype=out_dtype),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_xla_naive(mesh, axis, batch_axes, out_dtype):
    def body(a_loc, b_loc):
        a_full = jax.lax.all_gather(a_loc, axis, tiled=True)
        return jnp.dot(a_full, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )

    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


def _fused_fits(n, m, k, n_local, itemsize) -> bool:
    work = (m * k + k * n_local + m * n_local) * itemsize
    return work <= fused_vmem_budget()


def auto_ag_gemm_method(mesh, axis, a, b, dp: int = 1) -> AGGemmMethod:
    """≡ reference method auto-selection (allgather.py:54-69): topology +
    working-set size decide the engine."""
    n = mesh.shape[axis]
    topo = detect_topology(mesh, axis)
    fits = _fused_fits(n, a.shape[0] // dp, a.shape[1], b.shape[1] // n, a.dtype.itemsize)
    if topo.link_kind == LinkKind.DCN:
        return AGGemmMethod.XLA_RING
    if fits and (topo.link_kind == LinkKind.ICI or not on_tpu()):
        return AGGemmMethod.PALLAS_FUSED
    return AGGemmMethod.XLA_RING


def ag_gemm(
    a,
    b,
    mesh,
    axis: str = "x",
    *,
    batch_axes: tuple = (),
    method: AGGemmMethod | None = None,
    out_dtype=None,
    collective_id: int = 5,
):
    """Fused AllGather(A) @ B for column-parallel TP.

    ``a``: (M, K) with rows sharded over ``(*batch_axes, axis)`` — each
    device holds an M/(dp·n) row shard; the kernel gathers the ``axis``
    factor within each DP group (Megatron sequence-parallel layout).
    ``b``: (K, N) sharded P(None, axis) — column-parallel weight.
    Returns (M, N) with rows sharded over ``batch_axes``, cols over ``axis``.

    Host entry ≡ reference ``ag_gemm`` (allgather_gemm.py:539) +
    ``rowise_ag_gemm_dispatcher`` (:586-661).
    """
    n = mesh.shape[axis]
    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    assert a.shape[0] % (n * dp) == 0 and b.shape[1] % n == 0
    assert a.shape[1] == b.shape[0], f"contract dim mismatch {a.shape} @ {b.shape}"
    if n == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
    if method is None:
        method = auto_ag_gemm_method(mesh, axis, a, b, dp=dp)
    if method == AGGemmMethod.PALLAS_FUSED:
        fn = _build_fused(
            mesh, axis, batch_axes, a.shape, b.shape, a.dtype, out_dtype,
            collective_id, config.chaos_delay,
        )
    elif method == AGGemmMethod.XLA_RING:
        fn = _build_xla_ring(mesh, axis, batch_axes, out_dtype)
    else:
        fn = _build_xla_naive(mesh, axis, batch_axes, out_dtype)
    return fn(a, b)
