"""AllGather engines (TPU-native re-design of the reference AG family).

Reference: python/triton_dist/kernels/nvidia/allgather.py — copy-engine
full-mesh push/pull (:79-135), 1D ring push (:138), NUMA-aware 2D ring
(:194), inter-node NVSHMEM variants (:291-468), with ``AllGatherMethod``
auto-selection (:44-69); low-latency variants in low_latency_allgather.py.

TPU re-design: the torus makes rings the bandwidth-optimal method over
ICI, so the workhorses are a unidirectional ring and a bidirectional ring
(each direction carries half of every shard → 2× bandwidth). For small
messages a direct all-to-all push minimizes hops (the role the reference's
LL-packed protocol plays; TPU needs no flag packing because the RDMA recv
semaphore is ordered after payload arrival). DCN / no-Pallas paths fall
back to ``jax.lax.all_gather``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import interp_key
from triton_distributed_tpu.lang import wire as wirelib
from triton_distributed_tpu.runtime import (
    AllGatherMethod,
    auto_allgather_method,
    detect_topology,
    ring_neighbors,
)
from triton_distributed_tpu.runtime import faults as _faults
from triton_distributed_tpu.utils.testing import chaos_delay

_SITE = "allgather"     # fault-plan / watchdog site for every AG engine


def _ring_ag_kernel(
    n, axis, mesh_axes, schedule, x_ref, out_ref, send_sem, recv_sem
):
    """Unidirectional ring: at step s forward shard (me-s) to the right
    neighbor; after n-1 steps everyone holds everything. The traversal
    (direction, chunk order) is the :class:`RingSchedule`'s to choose;
    ``schedule=None`` is the canonical forward ring, byte-identical to
    the pre-schedule kernel."""
    direction = "fwd" if schedule is None else schedule.direction
    order = "ring" if schedule is None else schedule.chunk_order
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    left, right = ring_neighbors(me, n)
    left, right = lang.pe_flat(axis, left, mesh_axes), lang.pe_flat(axis, right, mesh_axes)
    to = right if direction == "fwd" else left

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    # payload-corruption hook: the local slab is both what the ring
    # forwards and what lands in the result, so a corrupted word here
    # propagates exactly like a corrupted wire payload would
    _faults.maybe_corrupt(out_ref, _SITE, me, n, row_off=me * m)
    lang.neighbor_barrier(axis, left, right, site=_SITE, me=me, n=n)

    # One semaphore slot per step: a slot's credit can then only come from
    # that step's DMA, so a wait being satisfied proves that *specific*
    # transfer landed (slot reuse would let a later step's credit release an
    # earlier wait while its data is still in flight).
    last = n - 1 if order != "skip_last" else n - 2
    for s in range(last):
        if direction == "fwd":
            src = jax.lax.rem(me + n - s, n) if s > 0 else me
        else:
            src = jax.lax.rem(me + s, n)
        chaos_delay(site=_SITE, step=s, me=me, n=n)
        dma = lang.remote_copy(
            out_ref.at[pl.ds(src * m, m)],
            out_ref.at[pl.ds(src * m, m)],
            send_sem.at[s],
            recv_sem.at[s],
            to,
        )
        dma.start()
        dma.wait()  # drains send + the symmetric incoming recv


def _ring_ag_kernel_w(
    n, axis, mesh_axes, schedule,
    x_ref, xq_ref, xs_ref, out_ref, outq_ref, outs_ref,
    send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`_ring_ag_kernel`: the ring forwards
    the host-quantized slab (1 byte/elem) plus a per-ROW f32 scale plane
    (lang.wire with chunk_rows=1 — the VMEM-resident engines afford
    row-granular scales), dequantizing each arrival into ``out_ref``.
    The own slab is written exact from ``x_ref`` (it never crosses the
    wire), matching the fused engines' wire contract."""
    direction = "fwd" if schedule is None else schedule.direction
    order = "ring" if schedule is None else schedule.chunk_order
    rail = "own" if schedule is None else schedule.scale_rail
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)
    to = right if direction == "fwd" else left
    sr_sem = s_recv_sem if rail == "own" else recv_sem

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    outq_ref[pl.ds(me * m, m)] = xq_ref[:]
    outs_ref[pl.ds(me * m, m)] = xs_ref[:]
    _faults.maybe_corrupt(out_ref, _SITE, me, n, row_off=me * m)
    lang.neighbor_barrier(axis, left, right, site=_SITE, me=me, n=n)

    last = n - 1 if order != "skip_last" else n - 2
    for s in range(last):
        if direction == "fwd":
            src = jax.lax.rem(me + n - s, n) if s > 0 else me
        else:
            src = jax.lax.rem(me + s, n)
        chaos_delay(site=_SITE, step=s, me=me, n=n)
        dma_q = lang.remote_copy(
            outq_ref.at[pl.ds(src * m, m)],
            outq_ref.at[pl.ds(src * m, m)],
            send_sem.at[s], recv_sem.at[s], to,
        )
        dma_s = lang.remote_copy(
            outs_ref.at[pl.ds(src * m, m)],
            outs_ref.at[pl.ds(src * m, m)],
            s_send_sem.at[s], sr_sem.at[s], to,
        )
        dma_q.start()
        dma_s.start()
        dma_q.wait()   # drains send + the symmetric incoming recv
        dma_s.wait()
        # the slab that just LANDED came from the upstream neighbor:
        # its step-s source — shard (me∓1∓s) — dequantize it for the
        # caller (the wire copy stays resident for the next forward)
        if direction == "fwd":
            arr = jax.lax.rem(me + 2 * n - 1 - s, n)
        else:
            arr = jax.lax.rem(me + 1 + s, n)
        wirelib.dequant_rows_into(
            out_ref.at[pl.ds(arr * m, m)],
            outq_ref.at[pl.ds(arr * m, m)],
            outs_ref.at[pl.ds(arr * m, m)],
        )


def _ring_bidir_ag_kernel(
    n, axis, mesh_axes, schedule, x_ref, out_ref, send_sem, recv_sem
):
    """Bidirectional ring: clockwise carries the left split8/8 columns of
    every shard, counter-clockwise the rest → each link moves a fraction
    of the bytes, halving AG time on a torus at the default even split."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    k = x_ref.shape[1]
    if schedule is None:
        kh = k // 2
    else:
        # lane-align the split point so both column slices stay Mosaic-
        # friendly; at split8=4 on lane-multiple widths this is k // 2
        kh = (k * int(schedule.split8)) // 8
        if k >= 256:
            kh = max(128, min(k - 128, (kh // 128) * 128))
    left, right = ring_neighbors(me, n)
    left, right = lang.pe_flat(axis, left, mesh_axes), lang.pe_flat(axis, right, mesh_axes)

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    lang.neighbor_barrier(axis, left, right, site=_SITE, me=me, n=n)

    # Per-step distinct semaphore slots (see _ring_ag_kernel): cw uses
    # slots [0, n-1), ccw uses [n-1, 2(n-1)).
    for s in range(n - 1):
        cw_src = jax.lax.rem(me + n - s, n)   # shard forwarded clockwise
        ccw_src = jax.lax.rem(me + s, n)      # shard forwarded counter-clockwise
        chaos_delay(site=_SITE, step=s, me=me, n=n)
        cw = lang.remote_copy(
            out_ref.at[pl.ds(cw_src * m, m), pl.ds(0, kh)],
            out_ref.at[pl.ds(cw_src * m, m), pl.ds(0, kh)],
            send_sem.at[s],
            recv_sem.at[s],
            right,
        )
        ccw = lang.remote_copy(
            out_ref.at[pl.ds(ccw_src * m, m), pl.ds(kh, k - kh)],
            out_ref.at[pl.ds(ccw_src * m, m), pl.ds(kh, k - kh)],
            send_sem.at[n - 1 + s],
            recv_sem.at[n - 1 + s],
            left,
        )
        cw.start()
        ccw.start()
        cw.wait()
        ccw.wait()


def _ll_push_ag_kernel(n, axis, mesh_axes, x_ref, out_ref, send_sem, recv_sem):
    """Small-message path: push the local shard straight to every peer
    (one hop, n-1 concurrent RDMAs), then wait for the n-1 arrivals.
    ≡ the role of the reference's LL/multimem fast-allgather
    (low_latency_allgather.py:532-624) — flag packing is unnecessary
    because TPU recv semaphores fire after payload arrival."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    _faults.maybe_corrupt(out_ref, _SITE, me, n, row_off=me * m)
    lang.barrier_all(axis, mesh_axes)

    handles = []
    for i in range(n - 1):
        peer = lang.pe_flat(axis, jax.lax.rem(me + 1 + i, n), mesh_axes)
        chaos_delay(site=_SITE, step=i, me=me, n=n)
        handles.append(
            lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],
                out_ref.at[pl.ds(me * m, m)],
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            )
        )
    lang.quiet(*handles)
    # wait for the n-1 incoming shards (equal-size, any order)
    for i, h in enumerate(handles):
        h.wait_recv()


def _ll_persist_kernel(
    n, axis, mesh_axes, parity_ref, x_ref, ws_in, out_ref, ws_out,
    send_sem, recv_sem, local_sem,
):
    """Barrier-free small-message AG over a PERSISTENT double-buffered
    workspace (≡ the reference's LL protocol: persistent symmetric
    buffers + call_count double buffering, low_latency_allgather.py:
    532-569 — no entry barrier at all).

    Why no barrier is needed: a rank finishes call N only after
    receiving every peer's call-N push, so inter-rank skew is bounded
    by ONE call. Writes for call N land in parity window N%2; the only
    other traffic a lagging peer can have outstanding is for call N-1
    in window (N-1)%2 — disjoint. The workspace aliases input→output
    (pallas input_output_aliases + jit donation), so the SAME physical
    buffer carries every call; the per-call recv DMA semaphore (n-1
    credits) replaces the reference's packed flag words.

    Semaphores are PER-PARITY rows (2, n-1): Mosaic reuses the same
    physical semaphores across calls of a kernel, so a skewed peer's
    call-N+1 credit must not be able to satisfy my call-N wait — with
    parity rows it lands in the other row, and a same-parity mix-up
    (call N vs N+2) is impossible because skew > 1 contradicts the
    recv dependency. This is the counting-semaphore translation of the
    reference's exact-value ``signal_wait_until(EQ, call_count)``.

    parity_ref: SMEM (1,) = call_idx % 2; ws_in/ws_out: the aliased
    (2·n·m, k) persistent workspace; out_ref: (n·m, k) fresh output
    (the parity window is drained into it — the window is overwritten
    two calls later)."""
    del ws_in  # aliased with ws_out — one buffer, two names
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    parity = parity_ref[0]
    base = parity * (n * m)

    # my own slot: local VMEM→HBM copy into the window (the drain below
    # reads the whole window, mine included)
    cp_self = pltpu.make_async_copy(
        x_ref, ws_out.at[pl.ds(base + me * m, m)], local_sem
    )
    cp_self.start()

    handles = []
    for i in range(n - 1):
        peer = lang.pe_flat(axis, jax.lax.rem(me + 1 + i, n), mesh_axes)
        chaos_delay(site=_SITE, step=i, me=me, n=n)
        handles.append(
            lang.putmem_signal_nbi_block(
                ws_out.at[pl.ds(base + me * m, m)],   # peer's slot `me`
                x_ref,
                send_sem.at[parity, i],
                recv_sem.at[parity, i],
                peer,
            )
        )
    lang.quiet(*handles)
    for h in handles:
        h.wait_recv()
    cp_self.wait()
    drain = pltpu.make_async_copy(
        ws_out.at[pl.ds(base, n * m)], out_ref, local_sem
    )
    drain.start()
    drain.wait()


_KERNELS = {
    # (kernel, number of semaphore slots as fn of n)
    AllGatherMethod.RING_1D: (_ring_ag_kernel, lambda n: n - 1),
    AllGatherMethod.RING_BIDIR: (_ring_bidir_ag_kernel, lambda n: 2 * (n - 1)),
    AllGatherMethod.LL_SMALL: (_ll_push_ag_kernel, lambda n: n - 1),
}


@functools.lru_cache(maxsize=256)
def _build_all_gather(mesh, axis, method, shape, dtype, collective_id, chaos,
                      wire=None, schedule=None):
    """Compile-once factory: the jitted collective for one (mesh, shape)
    configuration. lru_cache gives call-site reuse — without it every
    invocation would rebuild pallas_call+shard_map+jit and retrace.

    ``wire`` ('fp8'/'int8'): quantized ring wire (lang.wire, per-row
    scales). Supported on RING_1D (the Pallas wire kernel) and
    XLA_FALLBACK (quantize → gather payload+scales → dequantize, the
    numerics twin that also genuinely halves DCN bytes); the entry
    demotes other methods to the raw wire."""
    n = mesh.shape[axis]
    m = shape[0] // n
    fmt = (
        wirelib.WireFormat(quant=wire, chunk_rows=1)
        if wire is not None else None
    )
    if method == AllGatherMethod.XLA_FALLBACK:
        if fmt is None:
            inner = lambda s: jax.lax.all_gather(s, axis, tiled=True)  # noqa: E731
        else:
            def inner(s):
                q, sc = wirelib.quantize_slab(s, fmt)
                qg = jax.lax.all_gather(q, axis, tiled=True)
                sg = jax.lax.all_gather(sc, axis, tiled=True)
                out = wirelib.dequantize_slab(qg, sg, fmt, s.dtype)
                # own slab exact, like the ring wire kernels
                me = jax.lax.axis_index(axis)
                return jax.lax.dynamic_update_slice(
                    out, s, (me * m,) + (0,) * (s.ndim - 1)
                )
        # instrumented like the Pallas engines: an XLA collective can
        # wedge too (DCN partner loss), and the watchdog/stall hooks are
        # pure host callbacks — no Pallas machinery needed
        body = lang.maybe_instrument(
            inner,
            axis=axis, site=_SITE, collective_id=collective_id, n=n,
        )
        fn = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(None),
            check_vma=False,
        )
        return jax.jit(fn)

    if fmt is not None:
        assert method == AllGatherMethod.RING_1D, method
        wirelib.require_inkernel(wire, "all_gather")
        nsem = max(n - 1, 1)
        call = lang.shmem_call(
            functools.partial(
                _ring_ag_kernel_w, n, axis, mesh.axis_names, schedule
            ),
            out_shape=[
                jax.ShapeDtypeStruct(shape, dtype),
                jax.ShapeDtypeStruct(shape, fmt.wire_dtype),
                jax.ShapeDtypeStruct(
                    (shape[0], wirelib.SCALE_LANES), jnp.float32
                ),
            ],
            in_specs=lang.vmem_specs(3),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((nsem,)),
                pltpu.SemaphoreType.DMA((nsem,)),
                pltpu.SemaphoreType.DMA((nsem,)),   # scale rail
                pltpu.SemaphoreType.DMA((nsem,)),
            ],
            collective_id=collective_id,
            name=f"ag_ring_1d_{wire}w",
        )
        call = lang.maybe_instrument(
            call, axis=axis, site=_SITE, collective_id=collective_id, n=n
        )

        def body(x_loc):
            q, sc = wirelib.quantize_slab(x_loc, fmt)
            return call(x_loc, q, sc)[0]

        fn = jax.shard_map(
            body, mesh=mesh, in_specs=P(axis), out_specs=P(None),
            check_vma=False,
        )
        return jax.jit(fn)

    kernel_fn, nsem_fn = _KERNELS[method]
    nsem = max(nsem_fn(n), 1)
    if method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR):
        kernel = functools.partial(
            kernel_fn, n, axis, mesh.axis_names, schedule
        )
    else:
        kernel = functools.partial(kernel_fn, n, axis, mesh.axis_names)
    call = lang.shmem_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),
        ],
        collective_id=collective_id,
        name=f"ag_{method.value}",
    )
    call = lang.maybe_instrument(
        call, axis=axis, site=_SITE, collective_id=collective_id, n=n
    )
    fn = jax.shard_map(
        call, mesh=mesh, in_specs=P(axis), out_specs=P(None), check_vma=False
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _build_ll_persist(mesh, axis, m_local, k, dtype, collective_id, chaos,
                      instance=0):
    """Jitted barrier-free LL AG: (parity, x, ws) → (gathered, ws') with
    the workspace donated/aliased straight through.

    ``instance`` keys the build per PersistentLLAllGather INSTANCE: two
    live contexts with identical configs must not share one compiled
    kernel — its physical per-parity DMA semaphores would be shared too,
    and interleaved calls could satisfy each other's waits while the
    data sits in the *other* instance's workspace."""
    n = mesh.shape[axis]
    call = lang.shmem_call(
        functools.partial(_ll_persist_kernel, n, axis, mesh.axis_names),
        out_shape=[
            jax.ShapeDtypeStruct((n * m_local, k), dtype),
            jax.ShapeDtypeStruct((2 * n * m_local, k), dtype),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={2: 1},
        # barrier-FREE by design: the kernel never touches the barrier
        # semaphore, and Mosaic rejects a collective_id on one that
        # doesn't (collective_id arg kept for the state cache key only)
        collective_id=None,
        name="ag_ll_persist",
    )
    call = lang.maybe_instrument(
        call, axis=axis, site=_SITE, collective_id=collective_id, n=n
    )
    fn = jax.shard_map(
        call,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(None), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(2,))


class PersistentLLAllGather:
    """Context-owned barrier-free LL allgather (≡ the reference's
    ``AllGatherLayer`` owning persistent symmetric buffers with per-call
    signal bookkeeping, low_latency_allgather_layer.py:31-195).

    Owns the double-buffered workspace and the call counter; each call
    runs the barrier-free kernel (no ``barrier_all`` before the pushes —
    for the small-message regime that barrier IS the latency). Stateful
    by design: use it where the reference layer is used (decode-step
    loops), not inside a larger jit trace.
    """

    _next_instance = [0]

    def __init__(self, mesh, axis, shard_shape, dtype=jnp.bfloat16,
                 collective_id: int = 12):
        from jax.sharding import NamedSharding

        m, k = shard_shape
        self.mesh, self.axis = mesh, axis
        self.n = mesh.shape[axis]
        self.m, self.k = m, k
        self.dtype = jnp.dtype(dtype)
        self.collective_id = collective_id
        self.call_idx = 0
        # per-instance kernel identity — see _build_ll_persist
        self.instance = PersistentLLAllGather._next_instance[0]
        PersistentLLAllGather._next_instance[0] += 1
        self.ws = jax.device_put(
            jnp.zeros((self.n * 2 * self.n * m, k), self.dtype),
            NamedSharding(mesh, P(axis)),
        )

    def __call__(self, x):
        """x: (n·m, k) sharded P(axis) → (n·m, k) replicated gathered."""
        fn = _build_ll_persist(
            self.mesh, self.axis, self.m, self.k, self.dtype,
            self.collective_id, interp_key(), self.instance,
        )
        parity = jnp.full((1,), self.call_idx % 2, jnp.int32)
        out, self.ws = fn(parity, x, self.ws)
        self.call_idx += 1
        return out


@functools.lru_cache(maxsize=64)
def _engine_tuner(mesh, axis, collective_id):
    """Measured engine selection for ``method=None`` — replaces the
    static 64 KiB LL threshold with a per-shape measurement (the
    reference's contextual_autotune wrapping, autotuner.py:97); winners
    persist on disk and the MAX consensus aligns processes."""
    from triton_distributed_tpu.tune.autotuner import method_tuner

    def run(x, *, method):
        return all_gather(
            x, mesh, axis, method=AllGatherMethod(method),
            collective_id=collective_id,
        )

    # LL_PERSIST is excluded: inside jit traces all_gather silently
    # demotes it to LL_SMALL (the persistent workspace is module state),
    # so a persisted 'll_persist' winner would not be the engine that
    # actually runs at traced call sites — the measured winner must
    # always match the executed engine (ADVICE r3). Callers wanting the
    # barrier-free protocol opt in explicitly (method=LL_PERSIST eager,
    # or PersistentLLAllGather / the MoE LL transport in jitted loops).
    candidates = [
        m for m in AllGatherMethod if m != AllGatherMethod.LL_PERSIST
    ]
    return method_tuner(
        f"all_gather[{dict(mesh.shape)}|{axis}|{collective_id}]",
        run, candidates,
    )


def _resolve_ag_wire(wire_dtype, method, x, n):
    """The wire :func:`all_gather` will actually ship: None unless the
    payload is 2-D, the method carries a wire (RING_1D / XLA_FALLBACK),
    and the per-row scale plane actually saves bytes. 'auto' defers to
    :func:`runtime.topology.auto_allgather_wire`; an explicit 'fp8' /
    'int8' on an ineligible payload raises (pinned = contract)."""
    w = wirelib.normalize_wire(wire_dtype)
    if w is None:
        return None
    cols = x.shape[-1] if x.ndim == 2 else 0
    eligible = (
        x.ndim == 2
        and method in (AllGatherMethod.RING_1D, AllGatherMethod.XLA_FALLBACK)
        and x.shape[0] % n == 0
        and cols * x.dtype.itemsize > cols + wirelib.SCALE_LANES * 4
    )
    inkernel = method == AllGatherMethod.RING_1D
    if w == "auto":
        if not eligible:
            return None
        if inkernel and not wirelib.inkernel_wire_ok("fp8"):
            return None  # Mosaic lacks in-kernel f8 casts; stay exact
        from triton_distributed_tpu.runtime.topology import (
            auto_allgather_wire,
        )

        shard_bytes = (x.size // n) * x.dtype.itemsize
        return auto_allgather_wire(shard_bytes)
    if inkernel:
        wirelib.require_inkernel(w, "all_gather")
    if not eligible:
        raise ValueError(
            f"all_gather wire_dtype={w!r} needs a 2-D payload with "
            f"cols·itemsize > cols + {wirelib.SCALE_LANES * 4} on a "
            "ring_1d/xla method (a pinned wire format is a contract); "
            f"got shape {x.shape} {x.dtype} on {method}"
        )
    return w


def all_gather(
    x,
    mesh,
    axis: str = "x",
    *,
    method: AllGatherMethod | None = None,
    collective_id: int = 2,
    wire_dtype=None,
    schedule=None,
):
    """AllGather ``x`` (sharded on dim 0 along ``axis``) → replicated full array.

    Host entry ≡ reference ``fast_allgather`` dispatcher
    (low_latency_allgather.py:971) + method auto-selection (allgather.py:54-69).

    ``wire_dtype``: quantized ring wire ('fp8'/'int8' — 1-byte payload +
    per-row f32 scales, own slab exact; 'auto' — compressed above the
    topology helper's byte threshold). Carried by the RING_1D and
    XLA_FALLBACK engines; with an explicit compressed wire a bidir/LL
    method resolution is demoted to RING_1D so the wire request wins.
    """
    n = mesh.shape[axis]
    if n == 1:
        return x
    if method is None:
        from triton_distributed_tpu.config import pallas_collectives_available
        from triton_distributed_tpu.runtime.topology import LinkKind
        from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

        if not pallas_collectives_available():
            # off-TPU on a jax without the TPU-simulation interpreter:
            # the Pallas engines cannot execute — degrade to XLA
            method = AllGatherMethod.XLA_FALLBACK
            fn = _build_all_gather(
                mesh, axis, method, x.shape, x.dtype, collective_id,
                interp_key(),
                wire=_resolve_ag_wire(wire_dtype, method, x, n),
            )
            return fn(x)
        topo = detect_topology(mesh, axis)
        if topo.link_kind == LinkKind.DCN:
            # Pallas remote DMA cannot cross DCN: never bench Pallas
            # candidates here (a failure may hang, not raise) and never
            # apply a disk winner persisted on an ICI mesh — the same
            # environment re-validation ag_gemm/gemm_rs do before using
            # a tuned method.
            method = AllGatherMethod.XLA_FALLBACK
        else:
            m = tuned_method_or_none(
                lambda: _engine_tuner(mesh, axis, collective_id), x
            )
            if m is not None:
                method = AllGatherMethod(m)
            else:
                shard_bytes = (x.size // n) * x.dtype.itemsize
                method = auto_allgather_method(topo, shard_bytes)
    if method == AllGatherMethod.RING_BIDIR and (x.ndim < 2 or x.shape[1] < 2):
        # bidir splits dim 1 between the two directions — impossible on
        # rank-1 / single-column inputs; fall back to the plain ring.
        method = AllGatherMethod.RING_1D
    if wirelib.normalize_wire(wire_dtype) in ("fp8", "int8") and method in (
        AllGatherMethod.RING_BIDIR, AllGatherMethod.LL_SMALL,
        AllGatherMethod.LL_PERSIST,
    ):
        # an explicit compressed wire outranks the method heuristic —
        # only the plain ring (and the XLA fallback) carry the wire
        method = AllGatherMethod.RING_1D
    if method == AllGatherMethod.LL_PERSIST:
        if isinstance(x, jax.core.Tracer) or x.ndim != 2:
            # the persistent workspace is module state — unreachable from
            # inside a trace (and the context is 2-D); the barrier'd LL
            # push is the stateless equivalent
            method = AllGatherMethod.LL_SMALL
        else:
            return _persist_state(
                mesh, axis, (x.shape[0] // n, x.shape[1]), x.dtype,
                collective_id,
            )(x)
    wire = _resolve_ag_wire(wire_dtype, method, x, n)
    if method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR):
        from triton_distributed_tpu.tune.schedule import resolve_schedule

        family = (
            "allgather.ring_1d"
            if method == AllGatherMethod.RING_1D
            else "allgather.ring_bidir"
        )
        schedule = resolve_schedule(family, x.shape, (n,), wire, schedule)
    else:
        schedule = None
    fn = _build_all_gather(
        mesh, axis, method, x.shape, x.dtype, collective_id, interp_key(),
        wire=wire, schedule=schedule,
    )
    return fn(x)


from collections import OrderedDict

_PERSIST_STATES: OrderedDict = OrderedDict()
_PERSIST_STATES_MAX = 8   # each entry PINS a 2× gathered-array HBM
                          # workspace per device — keep the LRU small


def _persist_state(mesh, axis, shard_shape, dtype, collective_id):
    """Module-owned PersistentLLAllGather per configuration — the
    context the reference keeps in its AllGatherLayer, surfaced through
    the stateless ``all_gather(method=LL_PERSIST)`` entry so the engine
    tuner can bench it like any other method. LRU-bounded: evicting an
    entry only frees its workspace (the protocol carries no cross-call
    obligations beyond the buffer — a fresh context restarts at call 0).
    """
    key = (mesh, axis, tuple(shard_shape), jnp.dtype(dtype), collective_id)
    st = _PERSIST_STATES.get(key)
    if st is None:
        st = _PERSIST_STATES[key] = PersistentLLAllGather(
            mesh, axis, shard_shape, dtype, collective_id
        )
        while len(_PERSIST_STATES) > _PERSIST_STATES_MAX:
            _PERSIST_STATES.popitem(last=False)
    else:
        _PERSIST_STATES.move_to_end(key)
    return st
