"""AllGather engines (TPU-native re-design of the reference AG family).

Reference: python/triton_dist/kernels/nvidia/allgather.py — copy-engine
full-mesh push/pull (:79-135), 1D ring push (:138), NUMA-aware 2D ring
(:194), inter-node NVSHMEM variants (:291-468), with ``AllGatherMethod``
auto-selection (:44-69); low-latency variants in low_latency_allgather.py.

TPU re-design: the torus makes rings the bandwidth-optimal method over
ICI, so the workhorses are a unidirectional ring and a bidirectional ring
(each direction carries half of every shard → 2× bandwidth). For small
messages a direct all-to-all push minimizes hops (the role the reference's
LL-packed protocol plays; TPU needs no flag packing because the RDMA recv
semaphore is ordered after payload arrival). DCN / no-Pallas paths fall
back to ``jax.lax.all_gather``.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import interp_key
from triton_distributed_tpu.runtime import (
    AllGatherMethod,
    auto_allgather_method,
    detect_topology,
    ring_neighbors,
)
from triton_distributed_tpu.utils.testing import chaos_delay


def _ring_ag_kernel(n, axis, mesh_axes, x_ref, out_ref, send_sem, recv_sem):
    """Unidirectional ring: at step s forward shard (me-s) to the right
    neighbor; after n-1 steps everyone holds everything."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    left, right = ring_neighbors(me, n)
    left, right = lang.pe_flat(axis, left, mesh_axes), lang.pe_flat(axis, right, mesh_axes)

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    lang.neighbor_barrier(axis, left, right)

    # One semaphore slot per step: a slot's credit can then only come from
    # that step's DMA, so a wait being satisfied proves that *specific*
    # transfer landed (slot reuse would let a later step's credit release an
    # earlier wait while its data is still in flight).
    for s in range(n - 1):
        src = jax.lax.rem(me + n - s, n) if s > 0 else me
        chaos_delay()
        dma = lang.remote_copy(
            out_ref.at[pl.ds(src * m, m)],
            out_ref.at[pl.ds(src * m, m)],
            send_sem.at[s],
            recv_sem.at[s],
            right,
        )
        dma.start()
        dma.wait()  # drains send + the symmetric incoming recv


def _ring_bidir_ag_kernel(n, axis, mesh_axes, x_ref, out_ref, send_sem, recv_sem):
    """Bidirectional ring: clockwise carries the left half-columns of every
    shard, counter-clockwise the right half → each link moves half the
    bytes, halving AG time on a torus."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]
    k = x_ref.shape[1]
    kh = k // 2
    left, right = ring_neighbors(me, n)
    left, right = lang.pe_flat(axis, left, mesh_axes), lang.pe_flat(axis, right, mesh_axes)

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    lang.neighbor_barrier(axis, left, right)

    # Per-step distinct semaphore slots (see _ring_ag_kernel): cw uses
    # slots [0, n-1), ccw uses [n-1, 2(n-1)).
    for s in range(n - 1):
        cw_src = jax.lax.rem(me + n - s, n)   # shard forwarded clockwise
        ccw_src = jax.lax.rem(me + s, n)      # shard forwarded counter-clockwise
        chaos_delay()
        cw = lang.remote_copy(
            out_ref.at[pl.ds(cw_src * m, m), pl.ds(0, kh)],
            out_ref.at[pl.ds(cw_src * m, m), pl.ds(0, kh)],
            send_sem.at[s],
            recv_sem.at[s],
            right,
        )
        ccw = lang.remote_copy(
            out_ref.at[pl.ds(ccw_src * m, m), pl.ds(kh, k - kh)],
            out_ref.at[pl.ds(ccw_src * m, m), pl.ds(kh, k - kh)],
            send_sem.at[n - 1 + s],
            recv_sem.at[n - 1 + s],
            left,
        )
        cw.start()
        ccw.start()
        cw.wait()
        ccw.wait()


def _ll_push_ag_kernel(n, axis, mesh_axes, x_ref, out_ref, send_sem, recv_sem):
    """Small-message path: push the local shard straight to every peer
    (one hop, n-1 concurrent RDMAs), then wait for the n-1 arrivals.
    ≡ the role of the reference's LL/multimem fast-allgather
    (low_latency_allgather.py:532-624) — flag packing is unnecessary
    because TPU recv semaphores fire after payload arrival."""
    me = lang.my_pe(axis)
    m = x_ref.shape[0]

    out_ref[pl.ds(me * m, m)] = x_ref[:]
    lang.barrier_all(axis, mesh_axes)

    handles = []
    for i in range(n - 1):
        peer = lang.pe_flat(axis, jax.lax.rem(me + 1 + i, n), mesh_axes)
        chaos_delay()
        handles.append(
            lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],
                out_ref.at[pl.ds(me * m, m)],
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            )
        )
    lang.quiet(*handles)
    # wait for the n-1 incoming shards (equal-size, any order)
    for i, h in enumerate(handles):
        h.wait_recv()


_KERNELS = {
    # (kernel, number of semaphore slots as fn of n)
    AllGatherMethod.RING_1D: (_ring_ag_kernel, lambda n: n - 1),
    AllGatherMethod.RING_BIDIR: (_ring_bidir_ag_kernel, lambda n: 2 * (n - 1)),
    AllGatherMethod.LL_SMALL: (_ll_push_ag_kernel, lambda n: n - 1),
}


@functools.lru_cache(maxsize=256)
def _build_all_gather(mesh, axis, method, shape, dtype, collective_id, chaos):
    """Compile-once factory: the jitted collective for one (mesh, shape)
    configuration. lru_cache gives call-site reuse — without it every
    invocation would rebuild pallas_call+shard_map+jit and retrace."""
    n = mesh.shape[axis]
    if method == AllGatherMethod.XLA_FALLBACK:
        fn = jax.shard_map(
            lambda s: jax.lax.all_gather(s, axis, tiled=True),
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(None),
            check_vma=False,
        )
        return jax.jit(fn)

    kernel_fn, nsem_fn = _KERNELS[method]
    nsem = max(nsem_fn(n), 1)
    call = lang.shmem_call(
        functools.partial(kernel_fn, n, axis, mesh.axis_names),
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((nsem,)),
            pltpu.SemaphoreType.DMA((nsem,)),
        ],
        collective_id=collective_id,
        name=f"ag_{method.value}",
    )
    fn = jax.shard_map(
        call, mesh=mesh, in_specs=P(axis), out_specs=P(None), check_vma=False
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _engine_tuner(mesh, axis, collective_id):
    """Measured engine selection for ``method=None`` — replaces the
    static 64 KiB LL threshold with a per-shape measurement (the
    reference's contextual_autotune wrapping, autotuner.py:97); winners
    persist on disk and the MAX consensus aligns processes."""
    from triton_distributed_tpu.tune.autotuner import method_tuner

    def run(x, *, method):
        return all_gather(
            x, mesh, axis, method=AllGatherMethod(method),
            collective_id=collective_id,
        )

    return method_tuner(
        f"all_gather[{dict(mesh.shape)}|{axis}|{collective_id}]",
        run, AllGatherMethod,
    )


def all_gather(
    x,
    mesh,
    axis: str = "x",
    *,
    method: AllGatherMethod | None = None,
    collective_id: int = 2,
):
    """AllGather ``x`` (sharded on dim 0 along ``axis``) → replicated full array.

    Host entry ≡ reference ``fast_allgather`` dispatcher
    (low_latency_allgather.py:971) + method auto-selection (allgather.py:54-69).
    """
    n = mesh.shape[axis]
    if n == 1:
        return x
    if method is None:
        from triton_distributed_tpu.runtime.topology import LinkKind
        from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

        topo = detect_topology(mesh, axis)
        if topo.link_kind == LinkKind.DCN:
            # Pallas remote DMA cannot cross DCN: never bench Pallas
            # candidates here (a failure may hang, not raise) and never
            # apply a disk winner persisted on an ICI mesh — the same
            # environment re-validation ag_gemm/gemm_rs do before using
            # a tuned method.
            method = AllGatherMethod.XLA_FALLBACK
        else:
            m = tuned_method_or_none(
                lambda: _engine_tuner(mesh, axis, collective_id), x
            )
            if m is not None:
                method = AllGatherMethod(m)
            else:
                shard_bytes = (x.size // n) * x.dtype.itemsize
                method = auto_allgather_method(topo, shard_bytes)
    if method == AllGatherMethod.RING_BIDIR and (x.ndim < 2 or x.shape[1] < 2):
        # bidir splits dim 1 between the two directions — impossible on
        # rank-1 / single-column inputs; fall back to the plain ring.
        method = AllGatherMethod.RING_1D
    fn = _build_all_gather(
        mesh, axis, method, x.shape, x.dtype, collective_id, interp_key()
    )
    return fn(x)
