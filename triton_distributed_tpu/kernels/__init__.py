"""Kernel library (L4): collective and compute-communication-overlap kernels.

Reference: python/triton_dist/kernels/nvidia/ (see SURVEY.md §2.3).
"""

from triton_distributed_tpu.kernels.ag_gemm import (
    AGGemmMethod,
    ag_gemm,
    resolve_ag_gemm_wire,
)
from triton_distributed_tpu.kernels.all_to_all import all_to_all, all_to_all_xla
from triton_distributed_tpu.kernels.allgather import (
    PersistentLLAllGather,
    all_gather,
)
from triton_distributed_tpu.kernels.flash_decode import (
    combine_partials,
    gqa_fwd_batch_decode,
    gqa_fwd_batch_decode_q8,
    gqa_fwd_batch_decode_q8_xla,
    gqa_fwd_batch_decode_xla,
    paged_gqa_fwd_batch_decode,
    paged_gqa_fwd_batch_decode_q8,
    paged_gqa_fwd_batch_decode_q8_xla,
    paged_gqa_fwd_batch_decode_xla,
    quantize_kv,
    sp_gqa_fwd_batch_decode,
    sp_gqa_fwd_batch_decode_device,
    sp_gqa_fwd_batch_decode_q8,
    sp_gqa_fwd_batch_decode_q8_device,
    sp_paged_gqa_fwd_batch_decode,
    sp_paged_gqa_fwd_batch_decode_device,
    sp_paged_gqa_fwd_batch_decode_q8,
)
from triton_distributed_tpu.kernels.gemm_rs import (
    GemmRSMethod,
    gemm_rs,
    resolve_gemm_rs_wire,
)
from triton_distributed_tpu.kernels.group_gemm import (
    grouped_matmul,
    grouped_matmul_xla,
)
from triton_distributed_tpu.kernels.moe_all_to_all import (
    MoEAllToAllContext,
    create_all_to_all_context,
    fast_all_to_all,
)
from triton_distributed_tpu.kernels.moe_utils import (
    moe_align_block_size,
    select_experts,
)
from triton_distributed_tpu.kernels.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from triton_distributed_tpu.kernels.reduce_scatter import (
    reduce_scatter,
    reduce_scatter_xla,
)

__all__ = [
    "PersistentLLAllGather",
    "all_gather",
    "reduce_scatter",
    "reduce_scatter_xla",
    "all_to_all",
    "all_to_all_xla",
    "ag_gemm",
    "AGGemmMethod",
    "resolve_ag_gemm_wire",
    "gemm_rs",
    "GemmRSMethod",
    "resolve_gemm_rs_wire",
    "gqa_fwd_batch_decode",
    "gqa_fwd_batch_decode_xla",
    "paged_gqa_fwd_batch_decode",
    "paged_gqa_fwd_batch_decode_xla",
    "sp_gqa_fwd_batch_decode",
    "sp_gqa_fwd_batch_decode_device",
    "sp_paged_gqa_fwd_batch_decode",
    "sp_paged_gqa_fwd_batch_decode_device",
    "combine_partials",
    "select_experts",
    "moe_align_block_size",
    "grouped_matmul",
    "grouped_matmul_xla",
    "MoEAllToAllContext",
    "create_all_to_all_context",
    "fast_all_to_all",
    "ring_attention",
    "ulysses_attention",
]
