"""Grouped (per-expert) GEMM over block-aligned sorted tokens.

Reference: the consumer grouped-GEMM kernels
``kernel_consumer_m_parallel_scatter_group_gemm`` (python/triton_dist/
kernels/nvidia/allgather_group_gemm.py:420-498) and the producer grouped
GEMM of moe_reduce_rs.py:362-467 — tiles walk the block-aligned sorted
token list, each M-block owned by exactly one expert whose weight matrix
it multiplies.

TPU re-design: the expert-id-per-block indirection becomes a Mosaic
scalar-prefetch index map — ``block_expert`` rides in SMEM and the
weight BlockSpec selects expert ``be[m]``'s (K, N) matrix per M-block
(the canonical TPU grouped-matmul / Megablocks schedule). MXU does the
FLOPs in bf16 with f32 accumulation in VMEM scratch. The XLA twin is
``jax.lax.ragged_dot`` over the same layout (group_sizes = padded
per-expert counts), used as the correctness baseline and as the
fallback where a shape falls off the kernel's alignment.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.config import local_interpret


def _ggemm_kernel(nsteps_k, be_ref, x_ref, w_ref, o_ref, acc_ref):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nsteps_k - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _ggemm_q_kernel(nsteps_k, xdt, be_ref, x_ref, w_ref, s_ref, o_ref,
                    acc_ref):
    """Weight-only-quantized variant: W rides HBM in its 1-byte wire
    dtype (int8 / fp8) and is widened tile-by-tile in VMEM; the
    per-(expert, out-channel) scale multiplies the f32 accumulator once
    at the final K step (dequantization is linear over the K reduction,
    so folding it into the epilogue is exact)."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], w_ref[0].astype(xdt),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nsteps_k - 1)
    def _store():
        o_ref[:] = (acc_ref[:] * s_ref[0, 0][None, :]).astype(o_ref.dtype)


def _ggemm_q8a_kernel(nsteps_k, be_ref, x_ref, w_ref, xs_ref, ws_ref,
                      o_ref, acc_ref):
    """W8A8 variant: BOTH operands ride int8 and the MXU runs its
    native s8×s8→s32 path (measured 320–350 TOP/s on a v5e — 2× the
    bf16 rate), with the rank-1 scale correction
    ``x_scale[m] · w_scale[e, n]`` applied to the s32 accumulator at
    the last K step (exact: both scales are constant over the K
    reduction)."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )

    @pl.when(kk == nsteps_k - 1)
    def _store():
        o_ref[:] = (
            acc_ref[:].astype(jnp.float32)
            * xs_ref[:]                        # (block_m, 1)
            * ws_ref[0, 0][None, :]            # (block_n,)
        ).astype(o_ref.dtype)


def quantize_act_rows(x):
    """Per-row symmetric int8 activation quantization: (M, K) →
    ((M, K) int8, (M, 1) f32 scales). The activation-side half of the
    W8A8 decode path (weights come from :func:`quantize_grouped_weights`)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / s), -127.0, 127.0).astype(jnp.int8)
    return q, s.astype(jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "block_n", "block_k", "vmem_limit_bytes",
                     "interpret", "out_dtype"),
)
def grouped_matmul(
    x_sorted, w, block_expert, *,
    w_scale=None, x_scale=None,
    block_m: int = 512, block_n: int = 2048, block_k: int = 512,
    vmem_limit_bytes: int | None = None,
    interpret=None,
    out_dtype=None,
):
    """x_sorted (cap, K) @ w (E, K, N) → (cap, N), expert per M-block.

    ``cap`` must be a multiple of ``block_m`` and ``block_expert`` have
    ``cap // block_m`` entries (from moe_utils.moe_align_block_size).
    Defaults swept on a real v5e (8 experts, 1024 rows/expert,
    4096×2048 bf16): (512, 2048, 512) → 168 TFLOP/s (MFU 0.85) vs 121
    for the old (256, 512, 512). Smaller block_m trades MXU efficiency
    for less routing padding — contexts keep their own defaults.

    WEIGHT-RESIDENT mode (decode sizes): ``block_n``/``block_k`` ≥ the
    whole N/K dims (pass e.g. 1<<30; rounded down to the dims) keep an
    expert's ENTIRE weight matrix in VMEM — the W BlockSpec index
    (be[m], 0, 0) is unchanged across that expert's consecutive sorted
    M-blocks, so Mosaic's pipeline skips the re-fetch and weight
    traffic drops from #blocks× to #expert-runs× the matrix. That lets
    ``block_m`` shrink (less alignment padding → fewer padded-row
    FLOPs) without the weight re-streaming penalty that otherwise
    punishes small blocks — measured 1235 → 1130 µs on the serving
    decode pair at (64, whole, whole) vs (256, 2048, 512), docs/PERF.md.
    Whole-dim tiles exceed Mosaic's 16 MB default scoped VMEM — pass
    ``vmem_limit_bytes`` (the contexts use config.fused_vmem_budget()).

    WEIGHT-ONLY QUANTIZATION (serving decode, where weight HBM reads
    dominate): pass ``w`` in a 1-byte dtype (int8 / float8_e4m3fn) plus
    ``w_scale`` (E, N) f32 per-(expert, out-channel) scales (from
    :func:`quantize_grouped_weights`). The kernel widens W tiles in
    VMEM and folds the scale into the f32 accumulator at the last K
    step — HBM weight traffic halves vs bf16 while the MXU still runs
    the bf16 pipeline. Composes with the weight-resident schedule.

    ``out_dtype`` (default: x's dtype): the store casts the f32
    accumulator directly to this — pass f32 for logits-grade outputs
    (a post-hoc ``.astype`` after a bf16 store would re-widen
    already-rounded values).

    W8A8 (``x_scale`` given too, x int8 from :func:`quantize_act_rows`):
    the MXU runs its native s8×s8→s32 path at 2× the bf16 rate and the
    rank-1 ``x_scale[m]·w_scale[e, n]`` correction lands on the s32
    accumulator in the epilogue. Decode-size grouped GEMMs at bm=64
    are MXU-bound (the weight-resident schedule already minimized the
    HBM reads), so doubling the MXU rate is the remaining lever.
    ``out_dtype`` defaults to bf16 here (int8 out makes no sense).
    """
    from triton_distributed_tpu.config import compiling_for_tpu
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    cap, kdim = x_sorted.shape
    e, _, ndim = w.shape
    assert cap % block_m == 0, f"cap={cap} not divisible by block_m={block_m}"
    # round the requested blocks DOWN to divisors (TPU-aligned when
    # possible): the sweep-tuned defaults must not assert on shapes like
    # N=3584 that 512 divides but 2048 does not
    block_n = _divisor_block(ndim, min(block_n, ndim), 128, compiling_for_tpu()) or ndim
    block_k = _divisor_block(kdim, min(block_k, kdim), 128, compiling_for_tpu()) or kdim
    nsteps_k = kdim // block_k

    in_specs = [
        pl.BlockSpec((block_m, block_k), lambda m, n, k, be: (m, k)),
        pl.BlockSpec(
            (1, block_k, block_n), lambda m, n, k, be: (be[m], k, n)
        ),
    ]
    acc_dtype = jnp.float32
    if w_scale is None:
        assert x_scale is None, "x_scale requires w_scale (W8A8 mode)"
        kernel = functools.partial(_ggemm_kernel, nsteps_k)
        args = (block_expert, x_sorted, w)
    else:
        assert w.dtype.itemsize == 1, (
            f"w_scale given but w dtype {w.dtype} is not a 1-byte wire "
            "dtype (int8 / float8_e4m3fn)"
        )
        assert w_scale.shape == (e, ndim), (w_scale.shape, (e, ndim))
        # (E, 1, N): the unit sublane dim equals the array dim, which
        # Mosaic accepts where a (1, block_n) slice of (E, N) is rejected
        ws3 = w_scale.astype(jnp.float32)[:, None, :]
        ws_spec = pl.BlockSpec(
            (1, 1, block_n), lambda m, n, k, be: (be[m], 0, n)
        )
        if x_scale is None:
            in_specs.append(ws_spec)
            kernel = functools.partial(
                _ggemm_q_kernel, nsteps_k, x_sorted.dtype
            )
            args = (block_expert, x_sorted, w, ws3)
        else:
            assert x_sorted.dtype == jnp.int8, (
                f"W8A8 needs int8 activations, got {x_sorted.dtype}"
            )
            assert x_scale.shape == (cap, 1), (x_scale.shape, (cap, 1))
            in_specs.append(
                pl.BlockSpec((block_m, 1), lambda m, n, k, be: (m, 0))
            )
            in_specs.append(ws_spec)
            kernel = functools.partial(_ggemm_q8a_kernel, nsteps_k)
            args = (
                block_expert, x_sorted, w,
                x_scale.astype(jnp.float32), ws3,
            )
            acc_dtype = jnp.int32
            if out_dtype is None:
                out_dtype = jnp.bfloat16
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(cap // block_m, ndim // block_n, nsteps_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_m, block_n), lambda m, n, k, be: (m, n)),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), acc_dtype)],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (cap, ndim), out_dtype or x_sorted.dtype
        ),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit_bytes
        ),
        interpret=local_interpret() if interpret is None else interpret,
    )
    return call(*args)


def grouped_matmul_xla(x_sorted, w, splits_padded):
    """``jax.lax.ragged_dot`` twin: group sizes are the block-aligned
    per-expert counts (they sum to cap; padding rows are zero)."""
    return jax.lax.ragged_dot(
        x_sorted, w, splits_padded.astype(jnp.int32)
    ).astype(x_sorted.dtype)


def quantize_grouped_weights(w, mode: str = "int8"):
    """(E, K, N) weights → ((E, K, N) wire-dtype, (E, N) f32 scales).

    Symmetric per-(expert, out-channel) weight-only quantization for the
    serving decode path (the grouped GEMM there is weight-HBM-bound, so
    1-byte weights halve its floor). Same scale convention as the token
    wire quant (kernels/moe_all_to_all.quantize_rows — ≡ the reference's
    WITH_SCALE fp8 transport, low_latency_all_to_all.py:82-90), applied
    to the stationary operand instead of the moving one.
    """
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1)        # (E, N)
    if mode == "int8":
        scale = jnp.maximum(amax, 1e-30) / 127.0
        q = jnp.round(w.astype(jnp.float32) / scale[:, None, :])
        return jnp.clip(q, -127, 127).astype(jnp.int8), scale
    if mode == "fp8":
        scale = jnp.maximum(amax, 1e-30) / 448.0                  # e4m3 max
        return (
            (w.astype(jnp.float32) / scale[:, None, :]).astype(
                jnp.float8_e4m3fn
            ),
            scale,
        )
    raise ValueError(f"weight quant mode must be int8|fp8, got {mode!r}")


def resident_weight_itemsize(mode: str | None, dtype) -> int:
    """VMEM bytes/elem a weight-resident ``grouped_matmul`` schedule
    must budget per weight element — the kernel-lowering cost model the
    model layer's residency gate consumes (kept HERE so it tracks this
    kernel). int8 tiles are consumed at wire width; fp8 has no native
    v5e MXU form, so Mosaic materializes the widened copy (budget wire
    + f32 temp — measured: whole-dim fp8 tiles blow scoped VMEM where
    int8 fits, docs/PERF.md); None = the unquantized compute dtype."""
    if mode == "int8":
        return 1
    if mode == "fp8":
        return 5
    assert mode is None, f"unknown weight-quant mode {mode!r}"
    return jnp.dtype(dtype).itemsize


def dequantize_grouped_weights(q, scale, dtype=jnp.bfloat16):
    """Widen (E, K, N) wire-dtype weights back with their (E, N) scales
    — the XLA-twin path (ragged_dot has no quantized form) and the
    correctness reference for the in-kernel epilogue dequant."""
    return (q.astype(jnp.float32) * scale[:, None, :]).astype(dtype)


def padded_splits(splits, block_m: int, cap: int):
    """Block-aligned per-expert counts with the tail slack folded into the
    last group so the sizes sum to ``cap`` (ragged_dot requires it)."""
    from triton_distributed_tpu.kernels.moe_utils import round_up_to_block

    padded = round_up_to_block(splits, block_m)
    slack = cap - jnp.sum(padded)
    return padded.at[-1].add(slack)
