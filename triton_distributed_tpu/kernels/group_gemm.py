"""Grouped (per-expert) GEMM over block-aligned sorted tokens.

Reference: the consumer grouped-GEMM kernels
``kernel_consumer_m_parallel_scatter_group_gemm`` (python/triton_dist/
kernels/nvidia/allgather_group_gemm.py:420-498) and the producer grouped
GEMM of moe_reduce_rs.py:362-467 — tiles walk the block-aligned sorted
token list, each M-block owned by exactly one expert whose weight matrix
it multiplies.

TPU re-design: the expert-id-per-block indirection becomes a Mosaic
scalar-prefetch index map — ``block_expert`` rides in SMEM and the
weight BlockSpec selects expert ``be[m]``'s (K, N) matrix per M-block
(the canonical TPU grouped-matmul / Megablocks schedule). MXU does the
FLOPs in bf16 with f32 accumulation in VMEM scratch. The XLA twin is
``jax.lax.ragged_dot`` over the same layout (group_sizes = padded
per-expert counts), used as the correctness baseline and as the
fallback where a shape falls off the kernel's alignment.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.config import local_interpret


def _ggemm_kernel(nsteps_k, be_ref, x_ref, w_ref, o_ref, acc_ref):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nsteps_k - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "block_n", "block_k", "vmem_limit_bytes",
                     "interpret"),
)
def grouped_matmul(
    x_sorted, w, block_expert, *,
    block_m: int = 512, block_n: int = 2048, block_k: int = 512,
    vmem_limit_bytes: int | None = None,
    interpret=None,
):
    """x_sorted (cap, K) @ w (E, K, N) → (cap, N), expert per M-block.

    ``cap`` must be a multiple of ``block_m`` and ``block_expert`` have
    ``cap // block_m`` entries (from moe_utils.moe_align_block_size).
    Defaults swept on a real v5e (8 experts, 1024 rows/expert,
    4096×2048 bf16): (512, 2048, 512) → 168 TFLOP/s (MFU 0.85) vs 121
    for the old (256, 512, 512). Smaller block_m trades MXU efficiency
    for less routing padding — contexts keep their own defaults.

    WEIGHT-RESIDENT mode (decode sizes): ``block_n``/``block_k`` ≥ the
    whole N/K dims (pass e.g. 1<<30; rounded down to the dims) keep an
    expert's ENTIRE weight matrix in VMEM — the W BlockSpec index
    (be[m], 0, 0) is unchanged across that expert's consecutive sorted
    M-blocks, so Mosaic's pipeline skips the re-fetch and weight
    traffic drops from #blocks× to #expert-runs× the matrix. That lets
    ``block_m`` shrink (less alignment padding → fewer padded-row
    FLOPs) without the weight re-streaming penalty that otherwise
    punishes small blocks — measured 1235 → 1130 µs on the serving
    decode pair at (64, whole, whole) vs (256, 2048, 512), docs/PERF.md.
    Whole-dim tiles exceed Mosaic's 16 MB default scoped VMEM — pass
    ``vmem_limit_bytes`` (the contexts use config.fused_vmem_budget()).
    """
    from triton_distributed_tpu.config import compiling_for_tpu
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    cap, kdim = x_sorted.shape
    e, _, ndim = w.shape
    assert cap % block_m == 0, f"cap={cap} not divisible by block_m={block_m}"
    # round the requested blocks DOWN to divisors (TPU-aligned when
    # possible): the sweep-tuned defaults must not assert on shapes like
    # N=3584 that 512 divides but 2048 does not
    block_n = _divisor_block(ndim, min(block_n, ndim), 128, compiling_for_tpu()) or ndim
    block_k = _divisor_block(kdim, min(block_k, kdim), 128, compiling_for_tpu()) or kdim
    nsteps_k = kdim // block_k

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(cap // block_m, ndim // block_n, nsteps_k),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda m, n, k, be: (m, k)),
            pl.BlockSpec(
                (1, block_k, block_n), lambda m, n, k, be: (be[m], k, n)
            ),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda m, n, k, be: (m, n)),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
    )
    call = pl.pallas_call(
        functools.partial(_ggemm_kernel, nsteps_k),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((cap, ndim), x_sorted.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit_bytes
        ),
        interpret=local_interpret() if interpret is None else interpret,
    )
    return call(block_expert, x_sorted, w)


def grouped_matmul_xla(x_sorted, w, splits_padded):
    """``jax.lax.ragged_dot`` twin: group sizes are the block-aligned
    per-expert counts (they sum to cap; padding rows are zero)."""
    return jax.lax.ragged_dot(
        x_sorted, w, splits_padded.astype(jnp.int32)
    ).astype(x_sorted.dtype)


def padded_splits(splits, block_m: int, cap: int):
    """Block-aligned per-expert counts with the tail slack folded into the
    last group so the sizes sum to ``cap`` (ragged_dot requires it)."""
    from triton_distributed_tpu.kernels.moe_utils import round_up_to_block

    padded = round_up_to_block(splits, block_m)
    slack = cap - jnp.sum(padded)
    return padded.at[-1].add(slack)
