"""MoE low-latency AllToAll: splits-aware dispatch/combine for EP.

Reference: python/triton_dist/kernels/nvidia/low_latency_all_to_all.py —
``all_to_all_kernel`` (:36-118, one block per peer: putmem_nbi of the
peer's token range + splits, fence, signal_op/signal_wait_until on a
call-count), ``AllToAllContext`` (:125-187, symmetric buffers padded to
``max_m`` because token counts are runtime values), host entries
``fast_all_to_all`` (:189-248) and ``all_to_all_post_process`` (:251-269);
the EP layer ep_a2a_layer.py:40-240 drives dispatch → expert → combine.

TPU re-design:

* XLA is static-shape, so the reference's ``max_m`` padding is not an
  implementation detail here but the core of the design: tokens ride in
  per-peer slots of fixed capacity ``max_m`` rows, and the true counts
  ride IN THE SAME payload as trailing rows (the NCCL-LL trick of
  packing flag next to payload, applied to metadata). The transport
  array is int32 — tokens are bitcast into int lanes, counts are native
  ints — because TPU float units flush subnormals, so int32 COUNT bits
  must never transit float lanes (a count of 6 bitcast to bf16 is a
  denormal and silently becomes 0). Int lanes are flush-free for
  arbitrary bits in both directions. One RDMA per peer moves data +
  counts, and the recv DMA semaphore subsumes the reference's
  call-count signal protocol (payload-then-flag ordering is a hardware
  guarantee on TPU, so no separate flag write and no ``call_count % 2``
  double buffering).
* The transport is therefore exactly the dense AllToAll kernel
  (kernels/all_to_all.py) over ``max_m + splits_rows`` rows per slot.
* The runtime-value work the reference does on the GPU (per-expert
  ranges from a splits cumsum) happens in XLA gather/scatter around the
  kernel: ``dispatch_stage`` packs expert-sorted tokens into per-peer
  slots, ``combine_unstage`` scatters processed tokens back into sorted
  order. Both fuse into neighbouring ops under jit.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from triton_distributed_tpu.kernels.all_to_all import all_to_all, all_to_all_xla
from triton_distributed_tpu.kernels.moe_utils import exclusive_cumsum


@dataclass(frozen=True)
class MoEAllToAllContext:
    """Static geometry of the EP exchange (≡ AllToAllContext,
    low_latency_all_to_all.py:125-165 — minus the symmetric buffers,
    which on TPU are ordinary sharded arrays owned by the caller).

    ``max_m``: per-peer token-slot capacity. Like the reference, a peer's
    token count is TRUNCATED at ``max_m``: overflow tokens are dropped
    (they come back as zero rows from the combine, and the receiver sees
    clamped splits) — size it to the worst case (``num_tokens * topk``
    for a pathological router).
    """

    mesh: jax.sharding.Mesh
    axis: str
    max_m: int
    hidden: int
    experts_per_rank: int
    dtype: jnp.dtype
    collective_id: int = 10
    # Total EP ranks when the exchange is hierarchical (DCN×ICI, see
    # ops/moe.py) — slot geometry then spans all ranks, not just the
    # ``axis`` line. None → flat exchange over ``axis``.
    num_ranks: int | None = None
    # Quantized wire format: "fp8" (e4m3) or "int8" ships tokens at 1
    # byte/elem with one f32 scale per token packed IN-SLOT next to the
    # payload (≡ the WITH_SCALE putmem_signal of the reference's
    # headline fp8 dispatch, low_latency_all_to_all.py:43-107). None →
    # tokens ride in ``dtype``.
    quant: str | None = None
    # Chunk granule (rows) of the fused count-bounded transport
    # (kernels/moe_dispatch): wire bytes per peer are
    # ceil(count/chunk)·chunk rows, so the chunk bounds the per-peer
    # slack (≡ the reference shipping exact per-expert ranges,
    # low_latency_all_to_all.py:62-90 — here rounded up to one DMA
    # granule). Must be a multiple of the wire dtype's sublane tile;
    # None → max(tile, 64) (≈0.5 MB DMAs at hidden 7168).
    chunk_m: int | None = None

    @property
    def n(self) -> int:
        return self.num_ranks or self.mesh.shape[self.axis]

    @property
    def num_experts(self) -> int:
        return self.n * self.experts_per_rank

    @property
    def wire_dtype(self):
        if self.quant is None:
            return jnp.dtype(self.dtype)
        if self.quant == "fp8":
            return jnp.dtype(jnp.float8_e4m3fn)
        if self.quant == "int8":
            return jnp.dtype(jnp.int8)
        raise ValueError(f"quant must be None|'fp8'|'int8', got {self.quant!r}")

    @property
    def quant_max(self) -> float:
        return 448.0 if self.quant == "fp8" else 127.0

    @property
    def ints_per_row(self) -> int:
        return self.hidden * self.wire_dtype.itemsize // 4

    @property
    def scale_rows(self) -> int:
        """Rows per slot carrying the bitcast f32 per-token scales."""
        if self.quant is None:
            return 0
        return -(-self.max_m // self.ints_per_row)

    @property
    def splits_rows(self) -> int:
        """Trailing rows per slot carrying the bitcast int32 splits."""
        return -(-self.experts_per_rank // self.ints_per_row)

    @property
    def slot_rows(self) -> int:
        return self.max_m + self.scale_rows + self.splits_rows


def create_all_to_all_context(
    mesh, axis, *, max_m, hidden, experts_per_rank,
    dtype=jnp.bfloat16, collective_id: int = 10, num_ranks: int | None = None,
    quant: str | None = None, chunk_m: int | None = None,
) -> MoEAllToAllContext:
    """≡ create_all_to_all_context (low_latency_all_to_all.py:168-187)."""
    dtype = jnp.dtype(dtype)
    ctx = MoEAllToAllContext(
        mesh=mesh, axis=axis, max_m=max_m, hidden=hidden,
        experts_per_rank=experts_per_rank, dtype=dtype,
        collective_id=collective_id, num_ranks=num_ranks, quant=quant,
        chunk_m=chunk_m,
    )
    assert (hidden * ctx.wire_dtype.itemsize) % 4 == 0, (
        f"hidden={hidden} row of {ctx.wire_dtype} not a whole number of int32s"
    )
    return ctx


def _pack_splits(ctx: MoEAllToAllContext, spl):
    """(n, epr) int32 → (n, splits_rows, ints_per_row) int32 rows."""
    pad = ctx.splits_rows * ctx.ints_per_row - ctx.experts_per_rank
    spl = jnp.pad(spl, ((0, 0), (0, pad)))
    return spl.reshape(ctx.n, ctx.splits_rows, ctx.ints_per_row)


def _toks_to_ints(ctx: MoEAllToAllContext, toks):
    """(..., H) wire dtype → (..., ints_per_row) int32, pure bitcast."""
    lead = toks.shape[:-1]
    itemsize = ctx.wire_dtype.itemsize
    if itemsize < 4:
        toks = toks.reshape(*lead, ctx.ints_per_row, 4 // itemsize)
    return jax.lax.bitcast_convert_type(toks, jnp.int32).reshape(
        *lead, ctx.ints_per_row
    )


def _ints_to_toks(ctx: MoEAllToAllContext, ints):
    """(..., ints_per_row) int32 → (..., H) wire dtype, pure bitcast."""
    rows = jax.lax.bitcast_convert_type(ints, ctx.wire_dtype)
    return rows.reshape(*ints.shape[:-1], ctx.hidden)


def quantize_rows(ctx: MoEAllToAllContext, toks):
    """(..., H) → ((..., H) wire dtype, (...,) f32 per-token scales).

    Symmetric per-token quantization: scale = amax/QMAX (≡ the per-token
    scales the reference ships WITH_SCALE, low_latency_all_to_all.py:43).
    """
    f = toks.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / ctx.quant_max
    q = f / scale[..., None]
    if ctx.quant == "int8":
        q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    else:
        q = q.astype(jnp.float8_e4m3fn)
    return q, scale


def dequantize_rows(ctx: MoEAllToAllContext, q, scale):
    """Inverse of :func:`quantize_rows`, back to ctx.dtype."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(ctx.dtype)


def _pack_scales(ctx: MoEAllToAllContext, scale):
    """(n, max_m) f32 scales → (n, scale_rows, ints_per_row) int32 rows."""
    ints = jax.lax.bitcast_convert_type(scale.astype(jnp.float32), jnp.int32)
    pad = ctx.scale_rows * ctx.ints_per_row - ctx.max_m
    ints = jnp.pad(ints, ((0, 0), (0, pad)))
    return ints.reshape(ctx.n, ctx.scale_rows, ctx.ints_per_row)


def _unpack_scales(ctx: MoEAllToAllContext, rows):
    """(n, scale_rows, ints_per_row) int32 → (n, max_m) f32 scales."""
    flat = rows.reshape(ctx.n, -1)[:, : ctx.max_m]
    return jax.lax.bitcast_convert_type(flat, jnp.float32)


def peer_offsets(ctx: MoEAllToAllContext, splits):
    """(counts, exclusive offsets) of this device's tokens per peer.

    splits: (num_experts,) int32 — my token count per GLOBAL expert
    (experts [j*epr, (j+1)*epr) live on peer j).
    """
    counts = splits.reshape(ctx.n, ctx.experts_per_rank).sum(axis=1)
    return counts.astype(jnp.int32), exclusive_cumsum(counts)


def dispatch_stage(ctx: MoEAllToAllContext, tokens, splits):
    """Stage expert-sorted tokens into per-peer padded slots.

    tokens: (M, H) sorted by global expert id; splits: (num_experts,).
    Returns (toks (n, max_m, H) ctx.dtype, spl (n, epr) int32) — pass
    through :func:`pack_slots` for the single-payload Pallas transport,
    or exchange the pair directly with two ``lax.all_to_all`` calls
    (the differentiable path: no bitcast touches the float tokens).
    ≡ the send_buf staging at low_latency_all_to_all.py:213-215.
    """
    m_total = tokens.shape[0]
    counts, offs = peer_offsets(ctx, splits)
    pos = jnp.arange(ctx.max_m, dtype=jnp.int32)
    idx = offs[:, None] + pos[None, :]                       # (n, max_m)
    valid = pos[None, :] < counts[:, None]
    gathered = tokens[jnp.clip(idx, 0, m_total - 1)]         # (n, max_m, H)
    toks = jnp.where(valid[..., None], gathered, 0).astype(ctx.dtype)
    spl = splits.reshape(ctx.n, ctx.experts_per_rank).astype(jnp.int32)
    return toks, spl


def pack_slots(ctx: MoEAllToAllContext, toks, spl):
    """(toks (n, max_m, H), spl (n, epr)) → one int32 payload
    (n * slot_rows, ints_per_row) for :func:`fast_all_to_all`. With
    ``ctx.quant`` set, tokens are quantized and their per-token f32
    scales ride in-slot between payload and splits (one RDMA still moves
    data + scales + counts). The bitcast is gradient-opaque — inference
    transport only.

    Note (measured dead end): quantizing BEFORE the slot gather — to
    halve staging traffic — is 33% SLOWER on a v5e (233 µs vs 175 µs at
    the DeepSeek headline config): 1-byte-element gathers/selects lower
    poorly on the VPU, and XLA already fuses this gather→mask→quantize
    chain tightly. Keep the gather in the compute dtype."""
    parts = []
    if ctx.quant is None:
        parts.append(_toks_to_ints(ctx, toks.astype(ctx.dtype)))
    else:
        q, scale = quantize_rows(ctx, toks)
        parts.append(_toks_to_ints(ctx, q))
        parts.append(_pack_scales(ctx, scale))
    parts.append(_pack_splits(ctx, spl))
    slots = jnp.concatenate(parts, axis=1)
    return slots.reshape(ctx.n * ctx.slot_rows, ctx.ints_per_row)


def clamp_recv_splits(ctx: MoEAllToAllContext, spl):
    """Clamp receiver splits to what actually fit in the slot: a sender
    whose per-peer total exceeded ``max_m`` shipped only the first
    ``max_m`` rows (in expert order), so the clamped cumulative counts
    name exactly the rows that arrived."""
    cum = jnp.minimum(jnp.cumsum(spl, axis=1), ctx.max_m)
    return jnp.diff(cum, axis=1, prepend=0)


def fast_all_to_all(ctx: MoEAllToAllContext, send, *, use_xla: bool = False):
    """Padded-slot exchange: slot j of device i → slot i of device j
    (≡ fast_all_to_all, low_latency_all_to_all.py:189-248). ``send`` is
    the global int32 (n² · slot_rows, ints_per_row) array sharded
    P(axis) on dim 0.
    """
    if use_xla:
        return all_to_all_xla(send, ctx.mesh, ctx.axis)
    return all_to_all(
        send, ctx.mesh, ctx.axis, collective_id=ctx.collective_id
    )


def recv_tokens_view(ctx: MoEAllToAllContext, recv):
    """Per-device slice → ((n, max_m, H) ctx.dtype tokens, (n, epr) int32
    splits). Quantized transports are dequantized here with the in-slot
    per-token scales.

    Row i of the splits = source rank i's counts for MY experts
    (≡ all_to_all_post_process, low_latency_all_to_all.py:251-269).
    Splits are clamped via :func:`clamp_recv_splits`.
    """
    slots = recv.reshape(ctx.n, ctx.slot_rows, ctx.ints_per_row)
    toks = _ints_to_toks(ctx, slots[:, : ctx.max_m])
    if ctx.quant is not None:
        scales = _unpack_scales(
            ctx, slots[:, ctx.max_m : ctx.max_m + ctx.scale_rows]
        )
        toks = dequantize_rows(ctx, toks, scales)
    spl = slots[:, ctx.max_m + ctx.scale_rows :].reshape(ctx.n, -1)[
        :, : ctx.experts_per_rank
    ]
    return toks, clamp_recv_splits(ctx, spl)


def combine_stage(ctx: MoEAllToAllContext, toks):
    """(n, max_m, H) processed tokens → int32 slots for the Pallas
    return transport. The splits rows are zero-filled; the combiner
    already knows its own original splits."""
    return pack_slots(
        ctx, toks, jnp.zeros((ctx.n, ctx.experts_per_rank), jnp.int32)
    )


def combine_unpack(ctx: MoEAllToAllContext, comb):
    """Int32 return-leg payload → (n, max_m, H) ctx.dtype token slots
    (dequantized with the in-slot scales when the wire is quantized)."""
    slots = comb.reshape(ctx.n, ctx.slot_rows, ctx.ints_per_row)
    toks = _ints_to_toks(ctx, slots[:, : ctx.max_m])
    if ctx.quant is not None:
        scales = _unpack_scales(
            ctx, slots[:, ctx.max_m : ctx.max_m + ctx.scale_rows]
        )
        toks = dequantize_rows(ctx, toks, scales)
    return toks


def combine_unstage(ctx: MoEAllToAllContext, toks, splits, m_total: int):
    """Scatter combined per-peer slots back into expert-sorted order.

    toks: (n, max_m, H) return-leg token slots (from
    :func:`combine_unpack` on the Pallas path, or directly from a
    ``lax.all_to_all`` on the differentiable path) — slot j holds MY
    tokens as processed by peer j; splits: this device's ORIGINAL
    dispatch splits. Returns (m_total, H) in the original sorted order.
    """
    toks = toks.reshape(ctx.n * ctx.max_m, ctx.hidden)
    counts, offs = peer_offsets(ctx, splits)
    ends = jnp.cumsum(counts)
    t = jnp.arange(m_total, dtype=jnp.int32)
    j = jnp.searchsorted(ends, t, side="right").astype(jnp.int32)
    j = jnp.clip(j, 0, ctx.n - 1)
    pos = t - offs[j]
    flat = j * ctx.max_m + jnp.clip(pos, 0, ctx.max_m - 1)
    out = toks[flat]
    # overflow tokens (pos >= max_m) were never shipped — zero, not
    # duplicates of the last slot row
    valid = (t < ends[-1]) & (pos < ctx.max_m)
    return jnp.where(valid[:, None], out, 0)
