"""Shared streaming-ring harnesses for the fused overlap kernels.

Two protocols, each used by two kernels (keep ONE implementation of the
deadlock-prone concurrency logic):

* :func:`ag_forward_ring` — the AllGather forward ring of
  ag_gemm._fused_kernel and moe_tp_fused.ag_group_gemm_kernel: shard
  ``(me-s) mod n`` is forwarded to the right neighbor while the caller's
  ``consume`` streams it through the MXU. Step 0 forwards/consumes the
  caller's local slab directly (no dependence on the workspace publish).
* :func:`reduce_ring` — the compute-into-the-ring reduce of
  gemm_rs._fused_kernel and moe_tp_fused.moe_reduce_rs_kernel:
  double-buffered work/recv slabs flowing leftward with ack-credit flow
  control (a sender may not rewrite a slot its receiver hasn't folded —
  semaphore credits count arrivals, not consumption; see
  reduce_scatter.ring_reduce_core for the original reasoning).

Both the forward descriptor and the wait-side descriptor are rebuilt
from identical arguments: DMA waits are on the slot semaphore and byte
counts match for every shard, so a reconstructed descriptor's
``wait_recv`` releases exactly when the incoming payload is resident
(the dl.wait + consume_token of allgather_gemm.py:224-227, done by
hardware).

Both harnesses optionally run a QUANTIZED wire (``wire=`` —
:class:`AGWireRefs` / :class:`RSWireRefs`, layout in ``lang.wire``):
the payload slab ships as fp8/int8 with a per-chunk f32 scale plane on
a parallel DMA rail, halving wire bytes on comm-bound shapes. The AG
ring quantizes once at the source and forwards the quantized bytes
unchanged (receivers dequantize before consuming); the reduce ring
re-quantizes each hop's fresh partial and dequant-accumulates in f32.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.runtime import ring_neighbors
from triton_distributed_tpu.utils.testing import chaos_delay


@dataclass
class AGWireRefs:
    """Quantized-wire rail of :func:`ag_forward_ring` (lang.wire layout:
    fp8/int8 payload slabs + per-chunk f32 scale planes, each moved by
    its own RDMA so the receive wait covers payload AND scales).

    The ring then forwards the QUANTIZED bytes unchanged (quantize once
    at the source — no per-hop requantization on the AG side) and
    dequantizes each arrival into the bf16 workspace before the caller's
    ``consume`` streams it through the MXU."""

    fmt: object          # lang.wire.WireFormat
    local_q: object      # (slab_rows, k) wire-dtype local slab (input)
    local_s: object      # (chunks, 128) f32 local scales (input)
    agq: object          # (n·slab_rows, k) wire workspace
    ags: object          # (n·chunks, 128) f32 scale workspace
    s_send_sem: object   # (n-1,) DMA sems, scale rail
    s_recv_sem: object
    #: callable(q_hbm, s_hbm, dst_hbm) — lang.wire — or None for the
    #: int8-MXU consumers: the caller's ``consume`` feeds the arrived
    #: quantized slab straight to the MXU and folds the scale in its
    #: accumulator epilogue, so there is no per-arrival dequant pass
    #: (and no bf16 workspace write) at all.
    dequant: object


@dataclass
class RSWireRefs:
    """Quantized-wire rail of :func:`reduce_ring`. Unlike the AG side,
    every hop's payload is a NEW partial sum, so the sender quantizes
    its work slab per hop and the receiver dequant-accumulates in f32
    (error is one rounding per hop, bounded, not compounding)."""

    fmt: object          # lang.wire.WireFormat
    wq: tuple            # double-buffered quantized work slabs
    ws: tuple            # their scale planes
    rq: tuple            # double-buffered quantized recv slabs
    rs: tuple            # their scale planes
    s_send_sem: object   # (2,) DMA sems, scale rail
    s_recv_sem: object
    quantize: object     # callable(src_hbm, q_hbm, s_hbm) — lang.wire
    dequant_add: object  # callable(a_hbm, q_hbm, s_hbm, dst_hbm)


class _DualDMA:
    """A payload RDMA and its scale-rail twin driven as one handle."""

    def __init__(self, payload, scales):
        self._h = (payload, scales)

    def start(self):
        for h in self._h:
            h.start()
        return self

    def wait_recv(self):
        for h in self._h:
            h.wait_recv()

    def wait_send(self):
        for h in self._h:
            h.wait_send()


def ag_forward_ring(
    n, axis, mesh_axes, local_hbm, ag_hbm, slab_rows, send_sem, recv_sem,
    consume, *, site=None, wire: AGWireRefs | None = None,
):
    """Run the AG forward ring; ``consume(s, src, a_hbm, a_row_off)``
    computes over shard ``src`` (rows ``[a_row_off, a_row_off+slab_rows)``
    of ``a_hbm``) at step ``s`` while the next transfer is in flight.

    ``local_hbm``: this device's (slab_rows, ·) slab; ``ag_hbm``: the
    (n·slab_rows, ·) gathered workspace (slab ``me`` is NOT written by
    this harness — publish it yourself if the gathered result is part of
    your contract, cf. ag_gemm's ``return_gathered``).
    """
    if n == 1:
        # single-rank degenerate ring: no barrier (self-signal semantics
        # would otherwise be load-bearing — cf. reduce_ring's early
        # return and gemm_rs nulling collective_id at n==1)
        consume(0, 0, local_hbm, 0)
        return

    me = lang.my_pe(axis)
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)

    lang.neighbor_barrier(axis, left, right, site=site, me=me, n=n)

    if wire is None:
        def fwd(src, slot, from_local):
            src_ref = local_hbm if from_local else ag_hbm.at[
                pl.ds(src * slab_rows, slab_rows)
            ]
            return lang.remote_copy(
                src_ref,
                ag_hbm.at[pl.ds(src * slab_rows, slab_rows)],
                send_sem.at[slot],
                recv_sem.at[slot],
                right,
            )
    else:
        ch = wire.fmt.chunks(slab_rows)

        def fwd(src, slot, from_local):
            # two rails, one handle: the quantized payload slab and its
            # scale plane — the receive wait releases only when BOTH
            # have landed, so dequant/forward never read torn wire data
            q_src = wire.local_q if from_local else wire.agq.at[
                pl.ds(src * slab_rows, slab_rows)
            ]
            s_src = wire.local_s if from_local else wire.ags.at[
                pl.ds(src * ch, ch)
            ]
            return _DualDMA(
                lang.remote_copy(
                    q_src,
                    wire.agq.at[pl.ds(src * slab_rows, slab_rows)],
                    send_sem.at[slot], recv_sem.at[slot], right,
                ),
                lang.remote_copy(
                    s_src,
                    wire.ags.at[pl.ds(src * ch, ch)],
                    wire.s_send_sem.at[slot], wire.s_recv_sem.at[slot],
                    right,
                ),
            )

    for s in range(n):
        src = jax.lax.rem(me + n - s, n) if s > 0 else me
        if s > 0:
            fwd(src, s - 1, s == 1).wait_recv()
        if s < n - 1:
            chaos_delay(site=site, step=s, me=me, n=n)
            fwd(src, s, s == 0).start()
        if s == 0:
            consume(s, src, local_hbm, 0)
        else:
            if wire is not None and wire.dequant is not None:
                # arrived wire slab → bf16 workspace, then the MXU
                # consumes it exactly like the raw-wire path (the
                # forward above already moved the quantized bytes on).
                # dequant=None = the int8-MXU wire: consume reads the
                # quantized slab directly and the scale fold happens in
                # its accumulator epilogue — the dequant pass is GONE.
                ch = wire.fmt.chunks(slab_rows)
                wire.dequant(
                    wire.agq.at[pl.ds(src * slab_rows, slab_rows)],
                    wire.ags.at[pl.ds(src * ch, ch)],
                    ag_hbm.at[pl.ds(src * slab_rows, slab_rows)],
                )
            consume(s, src, ag_hbm, src * slab_rows)
    for s in range(n - 1):
        src = jax.lax.rem(me + n - s, n) if s > 0 else me
        fwd(src, s, s == 0).wait_send()


def reduce_ring(
    n, axis, mesh_axes, out_hbm, work, recv, send_sem, recv_sem, ack_sem,
    partial_into, fold, *, site=None, wire: RSWireRefs | None = None,
):
    """Run the compute-into-the-ring reduce.

    ``partial_into(dst, dst_ref)`` produces this device's contribution to
    destination shard ``dst`` — invoked between a ring DMA's start and
    its recv wait so the transfer hides under it. ``fold(a, b, dst_ref)``
    writes ``a + b`` (streamed). ``work``/``recv``: pairs of
    double-buffered HBM slabs. Destination order me+1…me is the
    rank-swizzle of gemm_reduce_scatter.py:205-219.
    """
    me = lang.my_pe(axis)
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)

    if n == 1:
        partial_into(0, out_hbm)
        return

    if wire is None:
        def ring_dma(slot):
            return lang.remote_copy(
                work[slot], recv[slot], send_sem.at[slot], recv_sem.at[slot],
                left,
            )
    else:
        def ring_dma(slot):
            return _DualDMA(
                lang.remote_copy(
                    wire.wq[slot], wire.rq[slot],
                    send_sem.at[slot], recv_sem.at[slot], left,
                ),
                lang.remote_copy(
                    wire.ws[slot], wire.rs[slot],
                    wire.s_send_sem.at[slot], wire.s_recv_sem.at[slot],
                    left,
                ),
            )

    lang.neighbor_barrier(axis, left, right, site=site, me=me, n=n)
    # my contribution to shard (me+1), the first one I forward
    partial_into(jax.lax.rem(me + 1, n), work[0])

    for s in range(n - 1):
        slot = s % 2
        chaos_delay(site=site, step=s, me=me, n=n)
        if s >= 2:
            # left must have folded my slot (s-2) before I rewrite it
            pltpu.semaphore_wait(ack_sem, 1)
        if wire is not None:
            # fresh partial → wire format; the wait_send at step s-1 (or
            # the ack above) already freed wq/ws[slot] for rewriting
            wire.quantize(work[slot], wire.wq[slot], wire.ws[slot])
        dma = ring_dma(slot)
        dma.start()
        # produce my contribution to the next destination while the
        # accumulator is in flight
        nxt = jax.lax.rem(me + 2 + s, n)
        if s >= 1:
            ring_dma(1 - slot).wait_send()  # slot reusable
        partial_into(nxt, work[1 - slot])
        dma.wait_recv()
        # received: partial sum of shard (me+2+s) accumulated so far by
        # the ring to my right; fold in my own contribution.
        dst = out_hbm if s == n - 2 else work[1 - slot]
        if wire is None:
            fold(work[1 - slot], recv[slot], dst)
        else:
            wire.dequant_add(
                work[1 - slot], wire.rq[slot], wire.rs[slot], dst
            )
        lang.signal_op(ack_sem, 1, pe=right, site=site, me=me, n=n)

    ring_dma((n - 2) % 2).wait_send()
    # drain leftover acks: n-1 received, max(n-3, 0) consumed in-loop
    pltpu.semaphore_wait(ack_sem, min(2, n - 1))
