"""Shared streaming-ring harnesses for the fused overlap kernels.

Two protocols, each used by two kernels (keep ONE implementation of the
deadlock-prone concurrency logic):

* :func:`ag_forward_ring` — the AllGather forward ring of
  ag_gemm._fused_kernel and moe_tp_fused.ag_group_gemm_kernel: shard
  ``(me-s) mod n`` is forwarded to the right neighbor while the caller's
  ``consume`` streams it through the MXU. Step 0 forwards/consumes the
  caller's local slab directly (no dependence on the workspace publish).
* :func:`reduce_ring` — the compute-into-the-ring reduce of
  gemm_rs._fused_kernel and moe_tp_fused.moe_reduce_rs_kernel:
  double-buffered work/recv slabs flowing leftward with ack-credit flow
  control (a sender may not rewrite a slot its receiver hasn't folded —
  semaphore credits count arrivals, not consumption; see
  reduce_scatter.ring_reduce_core for the original reasoning).

Both the forward descriptor and the wait-side descriptor are rebuilt
from identical arguments: DMA waits are on the slot semaphore and byte
counts match for every shard, so a reconstructed descriptor's
``wait_recv`` releases exactly when the incoming payload is resident
(the dl.wait + consume_token of allgather_gemm.py:224-227, done by
hardware).

Both harnesses optionally run a QUANTIZED wire (``wire=`` —
:class:`AGWireRefs` / :class:`RSWireRefs`, layout in ``lang.wire``):
the payload slab ships as fp8/int8 with a per-chunk f32 scale plane on
a parallel DMA rail, halving wire bytes on comm-bound shapes. The AG
ring quantizes once at the source and forwards the quantized bytes
unchanged (receivers dequantize before consuming); the reduce ring
re-quantizes each hop's fresh partial and dequant-accumulates in f32.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.runtime import ring_neighbors
from triton_distributed_tpu.utils.testing import chaos_delay


@dataclass
class AGWireRefs:
    """Quantized-wire rail of :func:`ag_forward_ring` (lang.wire layout:
    fp8/int8 payload slabs + per-chunk f32 scale planes, each moved by
    its own RDMA so the receive wait covers payload AND scales).

    The ring then forwards the QUANTIZED bytes unchanged (quantize once
    at the source — no per-hop requantization on the AG side) and
    dequantizes each arrival into the bf16 workspace before the caller's
    ``consume`` streams it through the MXU."""

    fmt: object          # lang.wire.WireFormat
    local_q: object      # (slab_rows, k) wire-dtype local slab (input)
    local_s: object      # (chunks, 128) f32 local scales (input)
    agq: object          # (n·slab_rows, k) wire workspace
    ags: object          # (n·chunks, 128) f32 scale workspace
    s_send_sem: object   # (n-1,) DMA sems, scale rail
    s_recv_sem: object
    #: callable(q_hbm, s_hbm, dst_hbm) — lang.wire — or None for the
    #: int8-MXU consumers: the caller's ``consume`` feeds the arrived
    #: quantized slab straight to the MXU and folds the scale in its
    #: accumulator epilogue, so there is no per-arrival dequant pass
    #: (and no bf16 workspace write) at all.
    dequant: object


@dataclass
class RSWireRefs:
    """Quantized-wire rail of :func:`reduce_ring`. Unlike the AG side,
    every hop's payload is a NEW partial sum, so the sender quantizes
    its work slab per hop and the receiver dequant-accumulates in f32
    (error is one rounding per hop, bounded, not compounding)."""

    fmt: object          # lang.wire.WireFormat
    wq: tuple            # double-buffered quantized work slabs
    ws: tuple            # their scale planes
    rq: tuple            # double-buffered quantized recv slabs
    rs: tuple            # their scale planes
    s_send_sem: object   # (2,) DMA sems, scale rail
    s_recv_sem: object
    #: callable(src_hbm, q_hbm, s_hbm) — lang.wire — or None when the
    #: producer (``partial_into``) quantizes straight off its accumulator
    #: epilogue into wq/ws (the gemm_rs int8-MXU producer): the ring
    #: then ships those bytes without a separate read-back pass.
    quantize: object
    dequant_add: object  # callable(a_hbm, q_hbm, s_hbm, dst_hbm)


class _DualDMA:
    """A payload RDMA and its scale-rail twin driven as one handle."""

    def __init__(self, payload, scales):
        self._h = (payload, scales)

    def start(self):
        for h in self._h:
            h.start()
        return self

    def wait_recv(self):
        for h in self._h:
            h.wait_recv()

    def wait_send(self):
        for h in self._h:
            h.wait_send()


def ag_forward_ring(
    n, axis, mesh_axes, local_hbm, ag_hbm, slab_rows, send_sem, recv_sem,
    consume, *, site=None, wire: AGWireRefs | None = None, schedule=None,
):
    """Run the AG forward ring; ``consume(s, src, a_hbm, a_row_off)``
    computes over shard ``src`` (rows ``[a_row_off, a_row_off+slab_rows)``
    of ``a_hbm``) at step ``s`` while the next transfer is in flight.

    ``local_hbm``: this device's (slab_rows, ·) slab; ``ag_hbm``: the
    (n·slab_rows, ·) gathered workspace (slab ``me`` is NOT written by
    this harness — publish it yourself if the gathered result is part of
    your contract, cf. ag_gemm's ``return_gathered``).

    ``schedule``: an optional ``tune.schedule.RingSchedule`` the harness
    EXECUTES — traversal direction, hop set and scale-rail assignment
    are schedule data, not code. ``None`` runs the canonical default
    (forward ring, every hop, scale rail on its own semaphores), byte-
    identical to the pre-schedule harness. Mutated schedules may be
    deliberately illegal (a skipped hop, a scale rail on the payload's
    semaphore): the harness executes what it is told and shmemlint is
    the oracle that rejects the candidate (SL008/SL009).
    """
    direction = "fwd" if schedule is None else schedule.direction
    order = "ring" if schedule is None else schedule.chunk_order
    rail = "own" if schedule is None else schedule.scale_rail

    if n == 1:
        # single-rank degenerate ring: no barrier (self-signal semantics
        # would otherwise be load-bearing — cf. reduce_ring's early
        # return and gemm_rs nulling collective_id at n==1)
        consume(0, 0, local_hbm, 0)
        return

    me = lang.my_pe(axis)
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)
    # "rev" flips nothing about the protocol — chunks flow leftward and
    # the consumed source walks (me+s) instead of (me-s)
    to = right if direction == "fwd" else left

    def src_at(s):
        if s == 0:
            return me
        if direction == "fwd":
            return jax.lax.rem(me + n - s, n)
        return jax.lax.rem(me + s, n)

    lang.neighbor_barrier(axis, left, right, site=site, me=me, n=n)

    if wire is None:
        def fwd(src, slot, from_local):
            src_ref = local_hbm if from_local else ag_hbm.at[
                pl.ds(src * slab_rows, slab_rows)
            ]
            return lang.remote_copy(
                src_ref,
                ag_hbm.at[pl.ds(src * slab_rows, slab_rows)],
                send_sem.at[slot],
                recv_sem.at[slot],
                to,
            )
    else:
        ch = wire.fmt.chunks(slab_rows)
        s_recv = recv_sem if rail == "payload" else wire.s_recv_sem

        def fwd(src, slot, from_local):
            # two rails, one handle: the quantized payload slab and its
            # scale plane — the receive wait releases only when BOTH
            # have landed, so dequant/forward never read torn wire data
            q_src = wire.local_q if from_local else wire.agq.at[
                pl.ds(src * slab_rows, slab_rows)
            ]
            s_src = wire.local_s if from_local else wire.ags.at[
                pl.ds(src * ch, ch)
            ]
            return _DualDMA(
                lang.remote_copy(
                    q_src,
                    wire.agq.at[pl.ds(src * slab_rows, slab_rows)],
                    send_sem.at[slot], recv_sem.at[slot], to,
                ),
                lang.remote_copy(
                    s_src,
                    wire.ags.at[pl.ds(src * ch, ch)],
                    wire.s_send_sem.at[slot], s_recv.at[slot],
                    to,
                ),
            )

    # the mutated "skip_last" order drops the final hop entirely —
    # start, wait AND consume — so every semaphore still balances and
    # only the delivery contract (SL008) can see the hole
    last = n - 1 if order != "skip_last" else n - 2
    for s in range(last + 1):
        src = src_at(s)
        if s > 0:
            fwd(src, s - 1, s == 1).wait_recv()
        if s < last:
            chaos_delay(site=site, step=s, me=me, n=n)
            fwd(src, s, s == 0).start()
        if s == 0:
            consume(s, src, local_hbm, 0)
        else:
            if wire is not None and wire.dequant is not None:
                # arrived wire slab → bf16 workspace, then the MXU
                # consumes it exactly like the raw-wire path (the
                # forward above already moved the quantized bytes on).
                # dequant=None = the int8-MXU wire: consume reads the
                # quantized slab directly and the scale fold happens in
                # its accumulator epilogue — the dequant pass is GONE.
                ch = wire.fmt.chunks(slab_rows)
                wire.dequant(
                    wire.agq.at[pl.ds(src * slab_rows, slab_rows)],
                    wire.ags.at[pl.ds(src * ch, ch)],
                    ag_hbm.at[pl.ds(src * slab_rows, slab_rows)],
                )
            consume(s, src, ag_hbm, src * slab_rows)
    for s in range(last):
        src = src_at(s)
        fwd(src, s, s == 0).wait_send()


def reduce_ring(
    n, axis, mesh_axes, out_hbm, work, recv, send_sem, recv_sem, ack_sem,
    partial_into, fold, *, site=None, wire: RSWireRefs | None = None,
    schedule=None,
):
    """Run the compute-into-the-ring reduce.

    ``partial_into(dst, dst_ref)`` produces this device's contribution to
    destination shard ``dst`` — invoked between a ring DMA's start and
    its recv wait so the transfer hides under it. ``fold(a, b, dst_ref)``
    writes ``a + b`` (streamed). ``work``/``recv``: ``depth``-buffered
    HBM slab tuples. Destination order me+1…me is the rank-swizzle of
    gemm_reduce_scatter.py:205-219.

    ``schedule``: an optional ``tune.schedule.RingSchedule``; ``None``
    runs the canonical default (depth 2, scale rail on its own
    semaphores), byte-identical to the pre-schedule harness. The buffer
    depth d generalizes the double-buffer protocol: slot ``s % d``, ack
    credit waited from ``s >= d`` (the receiver must have folded the
    slot before it is rewritten), in-loop send drain from ``s >= d-1``,
    and ``min(d-1, n-1)`` sends / ``min(d, n-1)`` acks drained at exit.
    """
    d = 2 if schedule is None else int(schedule.depth)
    rail = "own" if schedule is None else schedule.scale_rail
    assert len(work) >= d and len(recv) >= d, (len(work), len(recv), d)

    me = lang.my_pe(axis)
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)

    if n == 1:
        partial_into(0, out_hbm)
        return

    if wire is None:
        def ring_dma(slot):
            return lang.remote_copy(
                work[slot], recv[slot], send_sem.at[slot], recv_sem.at[slot],
                left,
            )
    else:
        s_recv = recv_sem if rail == "payload" else wire.s_recv_sem

        def ring_dma(slot):
            return _DualDMA(
                lang.remote_copy(
                    wire.wq[slot], wire.rq[slot],
                    send_sem.at[slot], recv_sem.at[slot], left,
                ),
                lang.remote_copy(
                    wire.ws[slot], wire.rs[slot],
                    wire.s_send_sem.at[slot], s_recv.at[slot],
                    left,
                ),
            )

    lang.neighbor_barrier(axis, left, right, site=site, me=me, n=n)
    # my contribution to shard (me+1), the first one I forward
    partial_into(jax.lax.rem(me + 1, n), work[0])

    for s in range(n - 1):
        slot = s % d
        nxt_slot = (s + 1) % d
        chaos_delay(site=site, step=s, me=me, n=n)
        if s >= d:
            # left must have folded my slot (s-d) before I rewrite it
            pltpu.semaphore_wait(ack_sem, 1)
        if wire is not None and wire.quantize is not None:
            # fresh partial → wire format; the wait_send at step s-d+1
            # (or the ack above) already freed wq/ws[slot] for rewriting.
            # quantize=None = producer-quantized wire (gemm_rs int8-MXU):
            # partial_into already wrote wq/ws straight off its
            # accumulator epilogue, so the read-back pass is gone.
            wire.quantize(work[slot], wire.wq[slot], wire.ws[slot])
        dma = ring_dma(slot)
        dma.start()
        # produce my contribution to the next destination while the
        # accumulator is in flight
        nxt = jax.lax.rem(me + 2 + s, n)
        if s >= d - 1:
            ring_dma(nxt_slot).wait_send()  # slot reusable
        partial_into(nxt, work[nxt_slot])
        dma.wait_recv()
        # received: partial sum of shard (me+2+s) accumulated so far by
        # the ring to my right; fold in my own contribution.
        dst = out_hbm if s == n - 2 else work[nxt_slot]
        if wire is None:
            fold(work[nxt_slot], recv[slot], dst)
        else:
            wire.dequant_add(
                work[nxt_slot], wire.rq[slot], wire.rs[slot], dst
            )
        lang.signal_op(ack_sem, 1, pe=right, site=site, me=me, n=n)

    # drain the last min(d-1, n-1) sends the in-loop waits never reached
    for i in range(min(d - 1, n - 1)):
        ring_dma((n - 2 - i) % d).wait_send()
    # drain leftover acks: n-1 received, max(n-1-d, 0) consumed in-loop
    pltpu.semaphore_wait(ack_sem, min(d, n - 1))
