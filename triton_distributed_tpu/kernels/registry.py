"""Registry of SHMEM kernel families for static analysis (shmemlint).

Each :class:`KernelFamily` names one protocol the kernel library ships
and knows how to *construct* it through the real builder (so the
analyzer sees the exact kernel partial, scratch semaphores,
collective_id and VMEM limits production uses — captured by the
``lang.launch.shmem_call`` hook) plus the per-device input shapes the
capture cannot know. Shapes are small lint shapes: the protocol under
analysis (signal/wait structure, slot indexing, barrier usage) is
shape-generic; only the region arithmetic needs concrete numbers.

Builders are lru-cached, so every build call gets a fresh
``("shmemlint", token)`` in an unused key argument — guaranteeing the
captured LaunchSpec was produced by THIS build, not a stale cache hit
from another configuration.

Central collective-id ledger: the ids below are the ones the op entries
default to. ``analysis.lint`` cross-checks uniqueness across families
(rule SL005) — a new family colliding with an existing id fails lint
instead of deadlocking a rendezvous at runtime (ADVICE r5: gemm_rs's
+96 chunk rail vs ag_gemm's +64 rail).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class KernelFamily:
    """One analyzable kernel family.

    ``build(mesh, n, token)`` constructs the kernel via its real
    builder (mesh may be a ``jax.sharding.AbstractMesh`` — nothing is
    executed); ``launch_name`` is the ``shmem_call`` name to read the
    captured :class:`~triton_distributed_tpu.lang.launch.LaunchSpec`
    back under; ``in_shapes(n)`` gives per-device input (shape, dtype)
    pairs; ``init(n)`` optionally seeds ref contents by name or
    positional index (count-carrying protocols need representative
    values to steer their receive loops).
    """

    name: str
    site: str | None
    launch_name: str
    build: callable
    in_shapes: callable
    init: callable = None
    axis: str = "x"
    mesh_axes: tuple = ("x",)


_F32 = np.dtype(np.float32)
_I32 = np.dtype(np.int32)


# ----------------------------------------------------------------- builders

def _ag(method):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.allgather import (
            _build_all_gather,
        )

        _build_all_gather(
            mesh, "x", method, (8 * n, 128), jnp.dtype(jnp.float32), 2,
            token,
        )

    return build


def _ag_ll_persist(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.allgather import _build_ll_persist

    _build_ll_persist(
        mesh, "x", 8, 128, jnp.dtype(jnp.float32), 12, token,
    )


def _rs_ring(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_reduce_scatter,
    )

    _build_reduce_scatter(
        mesh, "x", (8 * n, 128), jnp.dtype(jnp.float32), False, 3, token
    )


def _rs_stream(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream,
    )

    _build_rs_stream(
        mesh, "x", 8 * n, 128, jnp.dtype(jnp.float32), False, 3, token
    )


def _a2a(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.all_to_all import _build_a2a_call

    _build_a2a_call(
        ("x",), "x", n, (8 * n, 128), jnp.dtype(jnp.float32), 4, token
    )


def _ag_gemm(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.ag_gemm import _build_fused

    _build_fused(
        mesh, "x", (), (16 * n, 128), (128, 64 * n),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 5, token,
        return_gathered=True,
    )


def _gemm_rs(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    _build_fused(
        mesh, "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6, token,
    )


#: lint geometry for the chunked MoE a2a: 8-row alignment tiles, 1 chunk
#: of 8 rows per peer, 2-chunk slots, a 1-row meta block whose chunk
#: count sits at (row 0, lane 1).
_MOE_GEOM = dict(a=8, chunk_u=1, slot_u=2, mr=1, nck_row=0, nck_lane=1,
                 kmax=2, cap=16, hidden=128)


def _moe_a2a(know_recv, collective_id):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.moe_dispatch import (
            _build_chunked_a2a,
        )

        g = _MOE_GEOM
        _build_chunked_a2a(
            ("x",), "x", n, g["a"], g["chunk_u"], g["slot_u"], g["mr"],
            g["nck_row"], g["nck_lane"], g["kmax"], g["cap"], g["hidden"],
            jnp.dtype(jnp.float32), know_recv, collective_id, token,
        )

    return build


def _moe_in_shapes(n):
    g = _MOE_GEOM
    return [
        ((1,), _I32),                       # parity
        ((n,), _I32),                       # offs (a-units)
        ((n,), _I32),                       # sendk
        ((n,), _I32),                       # recvk
        ((n * g["slot_u"] * g["a"], g["hidden"]), _F32),   # payload
        ((n * g["mr"], 128), _I32),         # meta
    ]


def _moe_init(know_recv):
    def init(n):
        g = _MOE_GEOM
        seed = {
            "offs_ref": np.arange(n, dtype=np.int32) * g["slot_u"],
            "sendk_ref": np.ones((n,), np.int32),
            "recvk_ref": np.ones((n,), np.int32),
        }
        if not know_recv:
            # the dispatch leg reads incoming chunk counts from the
            # landed metadata head; per-rank symbolic execution has no
            # peer memory, so seed the receive metadata with the counts
            # a symmetric peer would send (1 chunk each)
            meta = np.zeros((n * g["mr"], 128), np.int32)
            meta[:, g["nck_lane"]] = 1
            seed[6 + 1] = meta              # output ref: dst_meta
            src = np.zeros((n * g["mr"], 128), np.int32)
            src[:, g["nck_lane"]] = 1
            seed["meta_hbm"] = src
        return seed

    return init


#: every analyzable kernel family, keyed by registry name.
def families() -> dict:
    from triton_distributed_tpu.runtime import AllGatherMethod

    fams = [
        KernelFamily(
            "allgather.ring_1d", "allgather", "ag_ring_1d",
            _ag(AllGatherMethod.RING_1D),
            lambda n: [((8, 128), _F32)],
        ),
        KernelFamily(
            "allgather.ring_bidir", "allgather", "ag_ring_bidir",
            _ag(AllGatherMethod.RING_BIDIR),
            lambda n: [((8, 128), _F32)],
        ),
        KernelFamily(
            "allgather.ll_small", "allgather", "ag_ll_small",
            _ag(AllGatherMethod.LL_SMALL),
            lambda n: [((8, 128), _F32)],
        ),
        KernelFamily(
            "allgather.ll_persist", "allgather", "ag_ll_persist",
            _ag_ll_persist,
            lambda n: [((1,), _I32), ((8, 128), _F32),
                       ((2 * n * 8, 128), _F32)],
        ),
        KernelFamily(
            "reduce_scatter.ring", "reduce_scatter", "rs_ring",
            _rs_ring,
            lambda n: [((8 * n, 128), _F32)],
        ),
        KernelFamily(
            "reduce_scatter.stream", "reduce_scatter", "rs_ring_stream",
            _rs_stream,
            lambda n: [((8 * n, 128), _F32)],
        ),
        KernelFamily(
            "all_to_all.dense", "all_to_all", "a2a_dense",
            _a2a,
            lambda n: [((8 * n, 128), _F32)],
        ),
        KernelFamily(
            "ag_gemm.fused", "ag_gemm", "ag_gemm_fused",
            _ag_gemm,
            lambda n: [((16, 128), _F32), ((128, 64), _F32)],
        ),
        KernelFamily(
            "gemm_rs.fused", "gemm_rs", "gemm_rs_fused",
            _gemm_rs,
            # A rows are unsharded (each device holds all M rows of its
            # K-column shard); B is row-sharded
            lambda n: [((16 * n, 128), _F32), ((128, 64), _F32)],
        ),
        KernelFamily(
            "moe_dispatch.a2a", "moe_dispatch", "moe_chunked_a2a",
            _moe_a2a(False, 10),
            _moe_in_shapes,
            init=_moe_init(False),
        ),
        KernelFamily(
            "moe_combine.a2a", "moe_dispatch", "moe_chunked_a2a",
            _moe_a2a(True, 11),
            _moe_in_shapes,
            init=_moe_init(True),
        ),
    ]
    return {f.name: f for f in fams}
