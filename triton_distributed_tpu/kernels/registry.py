"""Registry of SHMEM kernel families for static analysis (shmemlint).

Each :class:`KernelFamily` names one protocol the kernel library ships
and knows how to *construct* it through the real builder (so the
analyzer sees the exact kernel partial, scratch semaphores,
collective_id and VMEM limits production uses — captured by the
``lang.launch.shmem_call`` hook) plus the per-device input shapes the
capture cannot know. Shapes are small lint shapes: the protocol under
analysis (signal/wait structure, slot indexing, barrier usage) is
shape-generic; only the region arithmetic needs concrete numbers.

Builders are lru-cached, so every build call gets a fresh
``("shmemlint", token)`` in an unused key argument — guaranteeing the
captured LaunchSpec was produced by THIS build, not a stale cache hit
from another configuration.

Central collective-id ledger: the ids below are the ones the op entries
default to. ``analysis.lint`` cross-checks uniqueness across families
(rule SL005) — a new family colliding with an existing id fails lint
instead of deadlocking a rendezvous at runtime (ADVICE r5: gemm_rs's
+96 chunk rail vs ag_gemm's +64 rail).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


# ------------------------------------------------- collective-id offset rails
#
# Chunked engines (ag_gemm's DCN rail, gemm_rs's column chunks) need one
# DISTINCT collective_id per chunk ring — a skewed neighbor's chunk-s+1
# barrier signal must not satisfy a chunk-s wait. Offsets used to be
# allocated ad hoc (+64 here, +96 there) with disjointness maintained by
# comment (ADVICE r5); this ledger makes it a checked invariant: every
# rail reserves an [offset, offset+length) range at import, overlapping
# reservations raise immediately, and the id arithmetic goes through
# :func:`rail_collective_id` so no call site can silently stray outside
# its reservation.

_RAILS: dict = {}


def reserve_collective_rail(name: str, offset: int, length: int) -> None:
    """Reserve the offset range [offset, offset+length) for ``name``'s
    per-chunk collective ids. Overlap with any existing reservation is a
    programming error and raises at import time — the static twin of the
    SL005 runtime-collision rule."""
    assert length > 0
    for other, (off, ln) in _RAILS.items():
        if other == name:
            continue
        if offset < off + ln and off < offset + length:
            raise ValueError(
                f"collective-id rail {name!r} [{offset}, {offset + length}) "
                f"overlaps {other!r} [{off}, {off + ln}) — chunk barriers "
                "of the two families would satisfy each other's rendezvous"
            )
    prev = _RAILS.get(name)
    if prev is not None and prev != (offset, length):
        raise ValueError(
            f"collective-id rail {name!r} re-reserved with a different "
            f"range: {prev} vs {(offset, length)}"
        )
    _RAILS[name] = (offset, length)


def rail_collective_id(name: str, collective_id, chunk: int):
    """The collective_id of chunk ring ``chunk`` on rail ``name``
    (None passes through — the degenerate no-barrier path)."""
    off, length = _RAILS[name]
    if not 0 <= chunk < length:
        raise ValueError(
            f"rail {name!r}: chunk {chunk} outside the reserved length "
            f"{length} — widen the reservation, don't improvise offsets"
        )
    return None if collective_id is None else collective_id + off + chunk


def reserved_rails() -> dict:
    """Snapshot of the ledger (name → (offset, length)), for tests."""
    return dict(_RAILS)


#: the rails the fused engines ship with. Bases are the op entries'
#: default collective_ids (single digits), so offsets start high enough
#: that base ids can never land inside a rail.
reserve_collective_rail("ag_gemm.dcn_chunks", 64, 32)
reserve_collective_rail("gemm_rs.dcn_chunks", 96, 32)


@dataclass(frozen=True)
class KernelFamily:
    """One analyzable kernel family.

    ``build(mesh, n, token)`` constructs the kernel via its real
    builder (mesh may be a ``jax.sharding.AbstractMesh`` — nothing is
    executed); ``launch_name`` is the ``shmem_call`` name to read the
    captured :class:`~triton_distributed_tpu.lang.launch.LaunchSpec`
    back under; ``in_shapes(n)`` gives per-device input (shape, dtype)
    pairs; ``init(n)`` optionally seeds ref contents by name or
    positional index (count-carrying protocols need representative
    values to steer their receive loops).

    ``contract`` declares the family's DELIVERY contract (gather /
    reduce / all-to-all permutation — see ``analysis.dataflow.
    DeliveryContract``): what the destination buffer must provably hold
    at termination. The SL008 pass is driven entirely by this table —
    a family with no contract still gets the protocol and wire-rail
    passes, but delivery completeness is only as strong as what is
    declared here.
    """

    name: str
    site: str | None
    launch_name: str
    build: callable
    in_shapes: callable
    init: callable = None
    axis: str = "x"
    mesh_axes: tuple = ("x",)
    contract: object = None
    # dotted path of the XLA twin this family degrades onto (the
    # with_fallback / health-probation target). Filled from
    # DEGRADATION_TARGETS in families(); a registered family without
    # one is a silent-gap lint error (bench.py --lint).
    degrades_to: str | None = None


#: family name → dotted path of its declared degradation target. Every
#: registered family MUST appear here (or set degrades_to directly):
#: ``bench.py --lint`` fails on a family whose degraded path is
#: undeclared or unresolvable — the silent-gap class where a fused
#: engine has no tested place to fall when the health ledger demotes it.
DEGRADATION_TARGETS = {
    "allgather.ring_1d": "jax.lax.all_gather",
    "allgather.ring_bidir": "jax.lax.all_gather",
    "allgather.ll_small": "jax.lax.all_gather",
    "allgather.ll_persist": "jax.lax.all_gather",
    "allgather.ring_1d_fp8w": "jax.lax.all_gather",
    "reduce_scatter.ring": "jax.lax.psum_scatter",
    "reduce_scatter.stream": "jax.lax.psum_scatter",
    "reduce_scatter.ring_fp8w": "jax.lax.psum_scatter",
    "reduce_scatter.stream_int8w": "jax.lax.psum_scatter",
    "all_to_all.dense": "jax.lax.all_to_all",
    "ag_gemm.fused": "triton_distributed_tpu.tools.native.xla_ag_gemm",
    "ag_gemm.fused_fp8w": "triton_distributed_tpu.tools.native.xla_ag_gemm",
    "ag_gemm.fused_int8mxw":
        "triton_distributed_tpu.tools.native.xla_ag_gemm",
    "gemm_rs.fused": "triton_distributed_tpu.tools.native.xla_gemm_rs",
    "gemm_rs.fused_fp8w": "triton_distributed_tpu.tools.native.xla_gemm_rs",
    "moe_tp.ag_group_gemm":
        "triton_distributed_tpu.kernels.group_gemm.grouped_matmul_xla",
    "moe_tp.ag_group_gemm_fp8w":
        "triton_distributed_tpu.kernels.group_gemm.grouped_matmul_xla",
    "moe_tp.ag_group_gemm_int8mxw":
        "triton_distributed_tpu.kernels.group_gemm.grouped_matmul_xla",
    "moe_tp.reduce_rs":
        "triton_distributed_tpu.kernels.group_gemm.grouped_matmul_xla",
    "moe_tp.reduce_rs_fp8w":
        "triton_distributed_tpu.kernels.group_gemm.grouped_matmul_xla",
    "flash_decode.ragged_paged":
        "triton_distributed_tpu.kernels.ragged_paged_attention."
        "ragged_paged_attention_xla",
    "kv_ship.pages": "triton_distributed_tpu.tools.native.xla_kv_ship",
    "moe_dispatch.a2a": "jax.lax.all_to_all",
    "moe_combine.a2a": "jax.lax.all_to_all",
    # training: both CP schemes degrade onto dense attention (gather KV,
    # attend locally — exact, no ring to deadlock); the grad ring onto
    # the plain-psum all-reduce (exact bf16 wire, no quantization)
    "cp.ring_attention":
        "triton_distributed_tpu.kernels.ring_attention."
        "dense_attention_reference",
    "cp.ulysses":
        "triton_distributed_tpu.kernels.ring_attention."
        "dense_attention_reference",
    "grad_ring.stream_int8w":
        "triton_distributed_tpu.train.grad_wire.grad_allreduce_xla",
    "cp_decode.lse_combine":
        "triton_distributed_tpu.kernels.flash_decode.cp_lse_combine_xla",
}


def resolve_degradation_target(path: str):
    """Import the object behind a DEGRADATION_TARGETS dotted path (or
    raise) — the lint gate's existence proof that the declared fallback
    is real, not a typo."""
    import importlib

    mod_name, _, attr = path.rpartition(".")
    mod = importlib.import_module(mod_name)
    return getattr(mod, attr)


def missing_degradation_targets() -> tuple:
    """(family, problem) pairs for every registered family whose
    degradation target is undeclared or fails to import. Empty means
    the bidirectional degradation matrix (docs/ROBUSTNESS.md) has no
    silent gaps; ``bench.py --lint`` and ci/fast.sh enforce empty."""
    out = []
    for name, fam in families().items():
        if not fam.degrades_to:
            out.append((name, "no declared degradation target"))
            continue
        try:
            resolve_degradation_target(fam.degrades_to)
        except Exception as e:  # noqa: BLE001 — report, don't crash lint
            out.append(
                (name, f"target {fam.degrades_to!r} unresolvable: {e}"))
    return tuple(out)


_F32 = np.dtype(np.float32)
_I32 = np.dtype(np.int32)
_I8 = np.dtype(np.int8)


def _f8():
    import ml_dtypes

    return np.dtype(ml_dtypes.float8_e4m3fn)


# ----------------------------------------------------------------- builders

def _ag(method):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.allgather import (
            _build_all_gather,
        )

        _build_all_gather(
            mesh, "x", method, (8 * n, 128), jnp.dtype(jnp.float32), 2,
            token,
        )

    return build


def _ag_ll_persist(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.allgather import _build_ll_persist

    _build_ll_persist(
        mesh, "x", 8, 128, jnp.dtype(jnp.float32), 12, token,
    )


def _rs_ring(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_reduce_scatter,
    )

    _build_reduce_scatter(
        mesh, "x", (8 * n, 128), jnp.dtype(jnp.float32), False, 3, token
    )


def _rs_stream(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream,
    )

    _build_rs_stream(
        mesh, "x", 8 * n, 128, jnp.dtype(jnp.float32), False, 3, token
    )


def _rs_stream_w(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream_w,
    )

    # wide lint columns: the streaming wire's per-chunk scale planes
    # only compress when the chunk payload dwarfs them (entry gate)
    _build_rs_stream_w(
        mesh, "x", 8 * n, 2048, jnp.dtype(jnp.float32), False, 3, token,
        "int8",
    )


def _a2a(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.all_to_all import _build_a2a_call

    _build_a2a_call(
        ("x",), "x", n, (8 * n, 128), jnp.dtype(jnp.float32), 4, token
    )


def _ag_gemm(mesh, n, token, wire=None):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.ag_gemm import _build_fused

    _build_fused(
        mesh, "x", (), (16 * n, 128), (128, 64 * n),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 5, token,
        return_gathered=True, wire=wire,
    )


def _gemm_rs(mesh, n, token, wire=None):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    _build_fused(
        mesh, "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6, token,
        wire=wire,
    )


def _ag_ring_w(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    # wider lint columns than the raw twin: the standalone rings carry
    # PER-ROW scale planes (512 B/row), which only compress when the
    # row payload dwarfs them — exactly the entry's eligibility gate
    _build_all_gather(
        mesh, "x", AllGatherMethod.RING_1D, (8 * n, 2048),
        jnp.dtype(jnp.float32), 2, token, wire="fp8",
    )


def _rs_ring_w(mesh, n, token):
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_reduce_scatter_w,
    )

    _build_reduce_scatter_w(
        mesh, "x", (8 * n, 2048), jnp.dtype(jnp.float32), False, 3, token,
        "fp8",
    )


#: lint geometry for the moe_tp fused pair: 8-row routing blocks, 16-row
#: per-shard sorted slabs, tiny K/N/F/H of 128, 2 experts per rank.
_MOE_TP_GEOM = dict(bm=8, cap=16, k=128, nl=128, fl=128, h=128, e=2)


def _moe_tp_blocks():
    from triton_distributed_tpu.kernels.moe_tp_fused import pick_gg_blocks

    g = _MOE_TP_GEOM
    return pick_gg_blocks(g["bm"], g["cap"], g["k"], g["nl"], 4)


def _moe_ag_gg(wire):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.moe_tp_fused import (
            build_ag_group_gemm_call,
        )

        g = _MOE_TP_GEOM
        build_ag_group_gemm_call(
            n, ("x",), "x", g["cap"], g["k"], g["nl"], g["e"],
            _moe_tp_blocks(), jnp.dtype(jnp.float32), 13, wire=wire,
        )
        _capture_token(token)

    return build


def _moe_rs(wire):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.moe_tp_fused import (
            build_moe_reduce_rs_call,
        )

        g = _MOE_TP_GEOM
        build_moe_reduce_rs_call(
            n, ("x",), "x", g["cap"], g["fl"], g["h"], g["e"],
            _moe_tp_blocks(), jnp.dtype(jnp.float32), 12, wire=wire,
        )
        _capture_token(token)

    return build


def _capture_token(token):
    """The moe_tp builders are not lru-cached (shmem_call is constructed
    directly), so the freshness token is consumed here only to keep the
    build signature uniform."""
    del token


def _moe_ag_gg_shapes(wire):
    def in_shapes(n):
        g = _MOE_TP_GEOM
        if wire == "int8-mxu":
            # no bf16 slab at all: quantized tokens + per-routing-block
            # scale plane + per-(expert, out-channel) quantized weights
            return [
                ((n, g["cap"] // g["bm"]), _I32),      # be (SMEM)
                ((g["cap"], g["k"]), _I8),             # quantized slab
                ((g["cap"] // g["bm"], 128), _F32),    # scale plane
                ((g["e"], g["k"], g["nl"]), _I8),      # quantized weights
                ((g["e"], 1, g["nl"]), _F32),          # weight scales
            ]
        shapes = [
            ((n, g["cap"] // g["bm"]), _I32),          # be (SMEM)
            ((g["cap"], g["k"]), _F32),                # sorted slab
        ]
        if wire:
            shapes += [
                ((g["cap"], g["k"]), _f8()),           # quantized slab
                ((1, 128), _F32),                      # scale plane
            ]
        shapes.append(((g["e"], g["k"], g["nl"]), _F32))   # expert weights
        return shapes

    return in_shapes


def _moe_rs_shapes(n):
    g = _MOE_TP_GEOM
    return [
        ((n, g["cap"] // g["bm"]), _I32),              # be (SMEM)
        ((n * g["cap"], g["fl"]), _F32),               # per-shard sorted y
        ((g["e"], g["fl"], g["h"]), _F32),             # expert weights
    ]


def _kv_ship(mesh, n, token):
    """The disaggregated-serving KV page ship (kernels/kv_ship.py):
    pairwise prefill→decode page transfers on the quantized wire —
    int8 page payloads + per-row f32 scale planes as dual DMA rails,
    landing at the receiver's block-table-assigned slots."""
    from triton_distributed_tpu.kernels.kv_ship import build_lint_kernel

    build_lint_kernel(mesh, n, token=(token, n))


def _kv_ship_in_shapes(n):
    from triton_distributed_tpu.kernels.kv_ship import KV_SHIP_GEOM as g

    del n
    rows = g["pages"] * g["rows"]
    return [
        ((g["pages"],), _I32),               # landing page table (SMEM)
        ((rows, g["cols"]), _I8),            # staged page payload
        ((rows, 128), _F32),                 # per-row scale planes
    ]


def _kv_ship_init(n):
    from triton_distributed_tpu.kernels.kv_ship import KV_SHIP_GEOM as g

    del n
    # landing slots: a permutation of the destination pool (zero slack,
    # so the permute contract demands full exactly-once coverage) —
    # identical on every rank, as the reserve→ship handshake guarantees
    return {0: np.asarray(
        list(reversed(range(g["pages"]))), np.int32
    )}


def _kv_ship_elems() -> int:
    """Elements ONE partner rank delivers into a pool: the whole staged
    page set (pages · rows · cols)."""
    from triton_distributed_tpu.kernels.kv_ship import KV_SHIP_GEOM as g

    return g["pages"] * g["rows"] * g["cols"]


def _cp_kv_rotate(mesh, n, token):
    """The ring-attention KV-rotation ring (kernels/cp_ring.py): the
    training CP transport's Pallas twin on the shared AG forward-ring
    harness, schedule-threaded so PR 9's search applies."""
    from triton_distributed_tpu.kernels.cp_ring import build_kv_rotate_lint

    build_kv_rotate_lint(mesh, n, token=(token, n))


def _cp_ulysses(mesh, n, token):
    """The Ulysses head-scatter a2a (kernels/cp_ring.py)."""
    from triton_distributed_tpu.kernels.cp_ring import build_ulysses_lint

    build_ulysses_lint(mesh, n, token=(token, n))


def _grad_ring(mesh, n, token):
    """The wire-quantized gradient ring (kernels/cp_ring.py): streaming
    reduce ring on the int8 wire — the Pallas protocol twin of
    ``train.grad_wire``'s EF reduce-scatter."""
    from triton_distributed_tpu.kernels.cp_ring import build_grad_ring_lint

    build_grad_ring_lint(mesh, n, token=(token, n))


def _cp_lse_combine(mesh, n, token):
    """The long-context decode merge (kernels/cp_ring.py): cross-rank
    LSE-combine as an f32 add-reduce ring — the Pallas protocol twin of
    ``flash_decode.cp_lse_combine_xla``."""
    from triton_distributed_tpu.kernels.cp_ring import (
        build_cp_lse_combine_lint,
    )

    build_cp_lse_combine_lint(mesh, n, token=(token, n))


def _ragged_paged(mesh, n, token):
    """The ragged paged-attention family is LOCAL (no remote DMA): the
    serving state shards pools over the KV-head dim, so each rank runs
    the same kernel on its head slice. Built at the kernel module's
    LINT_GEOM (zero-slack packing → the `local` contract can demand
    FULL own-write coverage of the out buffer)."""
    del mesh
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        build_lint_kernel,
    )

    build_lint_kernel(token=(token, n))


def _ragged_in_shapes(n):
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM as g,
    )

    del n
    pool = (g["npages"], g["hkv"], g["page"], g["d"])
    return [
        ((g["r"], g["pps"]), _I32),                   # block table
        ((g["r"],), _I32),                            # kv_lens
        ((g["r"],), _I32),                            # q_lens
        ((g["r"],), _I32),                            # q_starts
        ((g["r"], 2 + 2 * g["topo_w"]), _I32),        # topologies
        ((g["hkv"], g["t"] * g["g"], g["d"]), _F32),  # packed q
        (pool, _I8),                                  # k pool
        (pool, _I8),                                  # v pool
        ((g["npages"], g["hkv"], 1, g["page"]), _F32),  # k scales
        ((g["npages"], g["hkv"], 1, g["page"]), _F32),  # v scales
    ]


def _ragged_init(n):
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM as g,
    )

    del n
    # two active rows, zero-slack packing: row 0 walks 2 pages (len 12
    # over 8-row pages), row 1 walks 1; both contribute 8 tokens at
    # 8-aligned starts tiling the whole (t, g) out span
    return {
        0: np.arange(g["r"] * g["pps"], dtype=np.int32).reshape(
            g["r"], g["pps"]
        ),
        1: np.asarray([12, 8], np.int32),             # kv_lens
        2: np.asarray([8, 8], np.int32),              # q_lens
        3: np.asarray([0, 8], np.int32),              # q_starts
        4: np.zeros((g["r"], 2 + 2 * g["topo_w"]), np.int32),  # CAUSAL
    }


#: lint geometry for the chunked MoE a2a: 8-row alignment tiles, 1 chunk
#: of 8 rows per peer, 2-chunk slots, a 1-row meta block whose chunk
#: count sits at (row 0, lane 1).
_MOE_GEOM = dict(a=8, chunk_u=1, slot_u=2, mr=1, nck_row=0, nck_lane=1,
                 kmax=2, cap=16, hidden=128)


def _moe_a2a(know_recv, collective_id):
    def build(mesh, n, token):
        import jax.numpy as jnp

        from triton_distributed_tpu.kernels.moe_dispatch import (
            _build_chunked_a2a,
        )

        g = _MOE_GEOM
        _build_chunked_a2a(
            ("x",), "x", n, g["a"], g["chunk_u"], g["slot_u"], g["mr"],
            g["nck_row"], g["nck_lane"], g["kmax"], g["cap"], g["hidden"],
            jnp.dtype(jnp.float32), know_recv, collective_id, token,
        )

    return build


def _moe_in_shapes(n):
    g = _MOE_GEOM
    return [
        ((1,), _I32),                       # parity
        ((n,), _I32),                       # offs (a-units)
        ((n,), _I32),                       # sendk
        ((n,), _I32),                       # recvk
        ((n * g["slot_u"] * g["a"], g["hidden"]), _F32),   # payload
        ((n * g["mr"], 128), _I32),         # meta
    ]


def _moe_init(know_recv):
    def init(n):
        g = _MOE_GEOM
        seed = {
            "offs_ref": np.arange(n, dtype=np.int32) * g["slot_u"],
            "sendk_ref": np.ones((n,), np.int32),
            "recvk_ref": np.ones((n,), np.int32),
        }
        if not know_recv:
            # the dispatch leg reads incoming chunk counts from the
            # landed metadata head; per-rank symbolic execution has no
            # peer memory, so seed the receive metadata with the counts
            # a symmetric peer would send (1 chunk each)
            meta = np.zeros((n * g["mr"], 128), np.int32)
            meta[:, g["nck_lane"]] = 1
            seed[6 + 1] = meta              # output ref: dst_meta
            src = np.zeros((n * g["mr"], 128), np.int32)
            src[:, g["nck_lane"]] = 1
            seed["meta_hbm"] = src
        return seed

    return init


#: every analyzable kernel family, keyed by registry name. Each family
#: declares its DELIVERY contract (the SL008 table): what the kernel
#: must provably have delivered when every semaphore has balanced.
def families() -> dict:
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.runtime import AllGatherMethod

    def gather(dst, **kw):
        return DeliveryContract(kind="gather", dst=dst, **kw)

    def reduce(dst, **kw):
        return DeliveryContract(kind="reduce", dst=dst, **kw)

    #: the chunked MoE a2a is capacity-padded: with the seeded routing
    #: (1 chunk per peer) each source delivers exactly one chunk of
    #: chunk_u·a rows into its slot; the rest of the slot stays empty.
    _g = _MOE_GEOM
    moe_contract = DeliveryContract(
        kind="permute", dst=6,        # dst_tok, behind the *refs splat
        payload_per_src=lambda n: _g["chunk_u"] * _g["a"] * _g["hidden"],
        full=False,
    )

    fams = [
        KernelFamily(
            "allgather.ring_1d", "allgather", "ag_ring_1d",
            _ag(AllGatherMethod.RING_1D),
            lambda n: [((8, 128), _F32)],
            contract=gather("out_ref"),
        ),
        KernelFamily(
            "allgather.ring_bidir", "allgather", "ag_ring_bidir",
            _ag(AllGatherMethod.RING_BIDIR),
            lambda n: [((8, 128), _F32)],
            contract=gather("out_ref"),
        ),
        KernelFamily(
            "allgather.ll_small", "allgather", "ag_ll_small",
            _ag(AllGatherMethod.LL_SMALL),
            lambda n: [((8, 128), _F32)],
            contract=gather("out_ref"),
        ),
        KernelFamily(
            "allgather.ll_persist", "allgather", "ag_ll_persist",
            _ag_ll_persist,
            lambda n: [((1,), _I32), ((8, 128), _F32),
                       ((2 * n * 8, 128), _F32)],
            contract=gather("out_ref"),
        ),
        KernelFamily(
            "reduce_scatter.ring", "reduce_scatter", "rs_ring",
            _rs_ring,
            lambda n: [((8 * n, 128), _F32)],
            contract=reduce("out_ref"),
        ),
        KernelFamily(
            "reduce_scatter.stream", "reduce_scatter", "rs_ring_stream",
            _rs_stream,
            lambda n: [((8 * n, 128), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "all_to_all.dense", "all_to_all", "a2a_dense",
            _a2a,
            lambda n: [((8 * n, 128), _F32)],
            contract=DeliveryContract(kind="permute", dst="out_ref"),
        ),
        KernelFamily(
            "ag_gemm.fused", "ag_gemm", "ag_gemm_fused",
            _ag_gemm,
            lambda n: [((16, 128), _F32), ((128, 64), _F32)],
            contract=gather("ag_hbm"),
        ),
        KernelFamily(
            # quantized-wire twin: payload rides as fp8 + a per-chunk f32
            # scale plane; shmemlint checks the changed byte counts and
            # the scale rail's semaphore protocol alongside the original
            "ag_gemm.fused_fp8w", "ag_gemm", "ag_gemm_fused_fp8w",
            lambda mesh, n, token: _ag_gemm(mesh, n, token, wire="fp8"),
            lambda n: [((16, 128), _F32), ((16, 128), _f8()),
                       ((1, 128), _F32), ((128, 64), _F32)],
            contract=gather("ag_hbm"),
        ),
        KernelFamily(
            # dequant-free int8→MXU twin: identical int8 rails, but the
            # contract destination is the WIRE workspace itself — every
            # arriving slab must be epilogue-consumed (the provenance
            # edge lang.wire.epilogue_consume emits flips it to
            # dequantized; raw bytes left over are SL008, a consume
            # without the scale fold is SL009)
            "ag_gemm.fused_int8mxw", "ag_gemm", "ag_gemm_fused_int8mxw",
            lambda mesh, n, token: _ag_gemm(mesh, n, token,
                                            wire="int8-mxu"),
            lambda n: [((16, 128), _I8), ((1, 128), _F32),
                       ((128, 64), _I8), ((1, 64), _F32)],
            # no local-slab publish: the local slab is consumed straight
            # from the quantized input and never enters the workspace
            contract=gather("agq_hbm", own_absent_ok=True),
        ),
        KernelFamily(
            "gemm_rs.fused", "gemm_rs", "gemm_rs_fused",
            _gemm_rs,
            # A rows are unsharded (each device holds all M rows of its
            # K-column shard); B is row-sharded
            lambda n: [((16 * n, 128), _F32), ((128, 64), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "gemm_rs.fused_fp8w", "gemm_rs", "gemm_rs_fused_fp8w",
            lambda mesh, n, token: _gemm_rs(mesh, n, token, wire="fp8"),
            lambda n: [((16 * n, 128), _F32), ((128, 64), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "allgather.ring_1d_fp8w", "allgather", "ag_ring_1d_fp8w",
            _ag_ring_w,
            lambda n: [((8, 2048), _F32), ((8, 2048), _f8()),
                       ((8, 128), _F32)],
            contract=gather("out_ref"),
        ),
        KernelFamily(
            "reduce_scatter.ring_fp8w", "reduce_scatter", "rs_ring_fp8w",
            _rs_ring_w,
            lambda n: [((8 * n, 2048), _F32)],
            contract=reduce("out_ref"),
        ),
        KernelFamily(
            # the HBM-streaming RS's quantized wire (the last bf16 leg
            # of the standalone RS family): per-hop quant pipelines +
            # scale rail, dequant-accumulate in f32 — the fused gemm_rs
            # wire protocol on the streaming engine
            "reduce_scatter.stream_int8w", "reduce_scatter",
            "rs_ring_stream_int8w",
            _rs_stream_w,
            lambda n: [((8 * n, 2048), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "moe_tp.ag_group_gemm", "moe_tp", "ag_group_gemm_fused",
            _moe_ag_gg(None),
            _moe_ag_gg_shapes(None),
            # no local-slab publish: slab `me` is consumed straight from
            # the sorted input and legitimately absent from the workspace
            contract=gather("ag_hbm", own_absent_ok=True),
        ),
        KernelFamily(
            "moe_tp.ag_group_gemm_fp8w", "moe_tp", "ag_group_gemm_fused_fp8w",
            _moe_ag_gg("fp8"),
            _moe_ag_gg_shapes("fp8"),
            contract=gather("ag_hbm", own_absent_ok=True),
        ),
        KernelFamily(
            # dequant-free int8→MXU grouped twin: sorted int8 slabs feed
            # the s8×s8 grouped GEMM against per-(expert, out-channel)
            # quantized weights; the wire workspace is the contract dst
            "moe_tp.ag_group_gemm_int8mxw", "moe_tp",
            "ag_group_gemm_fused_int8mxw",
            _moe_ag_gg("int8-mxu"),
            _moe_ag_gg_shapes("int8-mxu"),
            contract=gather("agq_hbm", own_absent_ok=True),
        ),
        KernelFamily(
            "moe_tp.reduce_rs", "moe_tp", "moe_reduce_rs_fused",
            _moe_rs(None),
            _moe_rs_shapes,
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "moe_tp.reduce_rs_fp8w", "moe_tp", "moe_reduce_rs_fused_fp8w",
            _moe_rs("fp8"),
            _moe_rs_shapes,
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            # the serving engine's mixed prefill/decode attention — a
            # LOCAL kernel (head-sharded pools, no cross-rank merge):
            # the contract demands every out element be the rank's own
            # computed write (full coverage, no holes, no raw
            # quantized bytes surviving the scale folds)
            "flash_decode.ragged_paged", "ragged_paged",
            "ragged_paged_attention_q8",
            _ragged_paged,
            _ragged_in_shapes,
            init=_ragged_init,
            contract=DeliveryContract(
                kind="local", dst=10,
                topo={"ref": 4, "kv_lens": 1, "q_lens": 2, "width": 8},
            ),
        ),
        KernelFamily(
            # the disaggregated-serving page ship: a PAIRWISE permute —
            # each decode rank's pool must hold exactly its partner
            # prefill rank's pages, each exactly once at its assigned
            # slot (src_only pins the topology; a skipped or doubled
            # page is SL008), with the scale rail paired per page on
            # its own semaphores (SL009) and the landed pair recorded
            # installed-as-quantized (epilogue_consume — the pool keeps
            # int8+scales, the attention kernel folds at read time)
            "kv_ship.pages", "kv_ship", "kv_ship_pages",
            _kv_ship,
            _kv_ship_in_shapes,
            init=_kv_ship_init,
            contract=DeliveryContract(
                kind="permute", dst="dst_q",
                payload_per_src=lambda n: (
                    _kv_ship_elems()
                ),
                src_only=lambda rank, n: {(rank - n // 2) % n},
            ),
        ),
        KernelFamily(
            # training CP: the KV-rotation ring under ring attention.
            # The local KV block is consumed at step 0 straight from
            # the input (the XLA body's peeled step 0) and never enters
            # the workspace — own_absent_ok, like the int8-MXU gathers.
            # A skip_last schedule mutation drops one block entirely;
            # only this gather contract (SL008) can see the hole.
            "cp.ring_attention", "cp_ring", "cp_ring_kv_rotate",
            _cp_kv_rotate,
            lambda n: [((8, 128), _F32)],
            contract=gather("ag_ref", own_absent_ok=True),
        ),
        KernelFamily(
            # training CP: the Ulysses seq→heads re-shard's dense a2a
            "cp.ulysses", "cp_ring", "cp_ulysses_a2a",
            _cp_ulysses,
            lambda n: [((8 * n, 128), _F32)],
            contract=DeliveryContract(kind="permute", dst="out_ref"),
        ),
        KernelFamily(
            # the gradient ring: streaming reduce on the int8 wire (wide
            # lint columns — scale planes only compress when the stripe
            # payload dwarfs them). The EF/stochastic-rounding numerics
            # live in train.grad_wire; this twin pins the PROTOCOL
            # (slot/ack discipline, paired scale rail → SL009).
            "grad_ring.stream_int8w", "grad_ring", "grad_ring_stream_int8w",
            _grad_ring,
            lambda n: [((8 * n, 2048), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            # long-context serving: each cp rank's paged-attention
            # partial rides as exp-weighted numerator rows + an
            # additive denominator row, so the softmax merge is a pure
            # add-reduce and the ring stays on the raw f32 wire (a
            # quantized denominator would drift the final normalize).
            # The reduce contract (SL008) is what sees a dropped or
            # double-folded rank — a token decoded against a silently
            # missing KV shard.
            "cp_decode.lse_combine", "cp_decode", "cp_decode_lse_combine",
            _cp_lse_combine,
            lambda n: [((8 * n, 128), _F32)],
            contract=reduce("out_hbm"),
        ),
        KernelFamily(
            "moe_dispatch.a2a", "moe_dispatch", "moe_chunked_a2a",
            _moe_a2a(False, 10),
            _moe_in_shapes,
            init=_moe_init(False),
            contract=moe_contract,
        ),
        KernelFamily(
            "moe_combine.a2a", "moe_dispatch", "moe_chunked_a2a",
            _moe_a2a(True, 11),
            _moe_in_shapes,
            init=_moe_init(True),
            contract=moe_contract,
        ),
    ]
    from dataclasses import replace as _replace

    out = {
        f.name: (
            f if f.degrades_to
            else _replace(f, degrades_to=DEGRADATION_TARGETS.get(f.name))
        )
        for f in fams
    }
    _strict_verify_contracts()
    return out


#: one-shot flag for the TDTPU_LINT_STRICT registration gate: None =
#: not yet run, True = verified clean. A failure leaves it None so a
#: fixed environment can re-verify.
_STRICT_VERIFIED = None


def _strict_verify_contracts():
    """Under ``TDTPU_LINT_STRICT=1``, re-verify every hand-declared
    delivery contract against the one inferred from its XLA twin at
    registration time (mesh 4, memoized — one pass per process). Any
    SL012 drift raises: a declaration that would make SL008 check the
    wrong obligation must not register."""
    import os

    global _STRICT_VERIFIED
    if _STRICT_VERIFIED or os.environ.get("TDTPU_LINT_STRICT") != "1":
        return
    # mark before running: verification itself calls families()
    _STRICT_VERIFIED = True
    try:
        from triton_distributed_tpu.analysis import contract_infer
        from triton_distributed_tpu.analysis.findings import Severity

        findings = contract_infer.verify_declared_contracts(n=4)
        errs = [f for f in findings if f.severity >= Severity.ERROR]
        if errs:
            raise RuntimeError(
                "TDTPU_LINT_STRICT: declared delivery contracts drift "
                "from the twin-inferred obligations:\n"
                + "\n".join(f.format() for f in errs)
            )
    except BaseException:
        _STRICT_VERIFIED = None
        raise
