"""Single-kernel overlapped MoE-TP engines: AG⊕GroupGEMM and GroupGEMM⊕RS.

Reference: python/triton_dist/kernels/nvidia/allgather_group_gemm.py —
``kernel_consumer_m_parallel_scatter_group_gemm`` waits per-tile on the
producer AG barrier before consuming gathered tokens (:420-498) — and
moe_reduce_rs.py — the producer grouped GEMM signals per-rank tile
counters into a consumer reduce-scatter pipeline (:362-545).

TPU re-design (the key restructuring): tokens ride the ring **pre-sorted
per shard**. Each device expert-sorts its own token rows locally (cheap
XLA gather) and the ring ships those padded sorted slabs, so every
arriving shard is immediately a contiguous grouped-GEMM operand — no
in-kernel gather, fully static shapes. Consequences:

* The overlap structure collapses into the ag_gemm/gemm_rs streaming
  rings: at step ``s`` the grouped-GEMM pipeline for the shard that just
  arrived runs on the MXU while the next shard's RDMA is in flight. The
  per-tile ``dl.wait`` of the reference becomes the per-shard recv-DMA
  semaphore wait, with expert-id block indexing via an SMEM table
  (the scalar-prefetch idiom of kernels/group_gemm.py).
* The sorted layout is **per-shard**: outputs are (tp·cap_s, ·) where
  slab ``s`` holds shard ``s``'s tokens in its own expert-sorted order.
  The topk combine happens after the reduce ring, on each destination's
  own rows only — which is exactly the locality that makes the reduce
  ring a plain ring over sorted slabs.
* Wire bytes are topk× the raw-token AG (sorted rows duplicate each
  token topk times). Compute scales by the same topk, so the
  compute-to-comm ratio — what overlap depends on — is unchanged, and
  the transfers stay hidden under the MXU at north-star shapes. The
  trade buys contiguous DMAs and no dynamic in-kernel addressing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import fused_vmem_budget
from triton_distributed_tpu.kernels.ag_gemm import _divisor_block
from triton_distributed_tpu.kernels.gemm_rs import ew_add_pipeline
from triton_distributed_tpu.kernels.ring import (
    AGWireRefs,
    RSWireRefs,
    ag_forward_ring,
    reduce_ring,
)
from triton_distributed_tpu.lang import wire as wirelib


def pick_gg_blocks(block_m: int, cap: int, k: int, nl: int, itemsize: int):
    """(bm, bk, bn) for the grouped pipelines. bm is pinned to the routing
    ``block_m`` (one expert per A-block is the grouped-GEMM contract);
    bk/bn stream K and the output columns."""
    from triton_distributed_tpu.config import compiling_for_tpu

    strict = compiling_for_tpu()
    if cap % block_m:
        return None
    if strict and block_m % (8 * (4 // itemsize)):
        return None  # sublane-misaligned routing block on real hardware
    bk = _divisor_block(k, 512, 128, strict)
    bn = _divisor_block(nl, 1792, 128, strict)
    if bk is None or bn is None:
        return None
    work = 2 * (block_m * bk + bk * bn) * itemsize \
        + 2 * block_m * bn * itemsize + 4 * block_m * bn
    if work > fused_vmem_budget():
        return None
    return block_m, bk, bn


def gmm_pipeline(mb, nb, kb, blocks, acc_ref, expert_of_block, *,
                 a_m_off=0, out_m_off=0):
    """Tiled grouped-matmul pipeline over HBM refs: for each A row-block
    ``i``, C[out_m_off+i, j] = A[a_m_off+i, :] @ W[expert_of_block(i)].
    ``expert_of_block`` reads the SMEM block→expert table (the
    scalar-prefetch indexing of kernels/group_gemm.py:74-85, here inside
    ``emit_pipeline`` index maps)."""
    bm, bk, bn = blocks

    def inner(a_ref, b_ref, o_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jnp.dot(
            a_ref[...], b_ref[0], preferred_element_type=jnp.float32
        )

        @pl.when(pl.program_id(2) == kb - 1)
        def _():
            o_ref[...] = acc_ref[...].astype(o_ref.dtype)

    return pltpu.emit_pipeline(
        inner,
        grid=(mb, nb, kb),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (a_m_off + i, kk)),
            pl.BlockSpec(
                (1, bk, bn), lambda i, j, kk: (expert_of_block(i), kk, j)
            ),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (out_m_off + i, j))
        ],
    )


def gmm_q8_pipeline(mb, nb, kb, blocks, expert_of_block):
    """s8×s8 grouped-matmul pipeline with the wire scales folded into
    the epilogue — the grouped twin of ag_gemm.mm_q8_pipeline, which is
    itself the exact epilogue shape of group_gemm._ggemm_q8a_kernel:
    the arriving int8 token slab multiplies the per-(expert,
    out-channel) quantized weight on the MXU's native s8×s8→s32 path,
    and the rank-1 ``chunk_scale[m]·w_scale[e, n]`` correction lands on
    the s32 accumulator at the last K step. Operates over pre-sliced
    HBM refs (aq, asc, wq, wsc, out); the int8-mxu wire pins
    ``chunk_rows == bm`` so A row-block i's scale is plane row i."""
    bm, bk, bn = blocks

    def mk(acc_ref):
        def inner(aq_ref, as_ref, wq_ref, ws_ref, o_ref):
            @pl.when(pl.program_id(2) == 0)
            def _():
                acc_ref[...] = jnp.zeros_like(acc_ref)

            acc_ref[...] += jax.lax.dot_general(
                aq_ref[...], wq_ref[0],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )

            @pl.when(pl.program_id(2) == kb - 1)
            def _():
                o_ref[...] = (
                    acc_ref[...].astype(jnp.float32)
                    * (as_ref[:, :1] * ws_ref[0])
                ).astype(o_ref.dtype)

        return pltpu.emit_pipeline(
            inner,
            grid=(mb, nb, kb),
            in_specs=[
                pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                pl.BlockSpec(
                    (1, wirelib.SCALE_LANES), lambda i, j, kk: (i, 0)
                ),
                pl.BlockSpec(
                    (1, bk, bn),
                    lambda i, j, kk: (expert_of_block(i), kk, j),
                ),
                pl.BlockSpec(
                    (1, 1, bn), lambda i, j, kk: (expert_of_block(i), 0, j)
                ),
            ],
            out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))],
        )

    def run(acc_ref, aq_hbm, as_hbm, wq_hbm, ws_hbm, out_hbm):
        if wirelib.epilogue_consume(aq_hbm, as_hbm, out_hbm):
            return  # symbolic: the provenance edge replaces the pipeline
        mk(acc_ref)(aq_hbm, as_hbm, wq_hbm, ws_hbm, out_hbm)

    return run


def ag_group_gemm_kernel(
    n, axis, mesh_axes, blocks,
    be_ref, xs_hbm, w_hbm, out_hbm, ag_hbm,
    acc_ref, send_sem, recv_sem,
):
    """Streaming ring AG ⊕ grouped GEMM (≡ the producer AG + per-tile-
    waiting consumer grouped GEMM of allgather_group_gemm.py:420-498).

    xs_hbm: (cap_s, K) this device's pre-sorted padded token slab;
    w_hbm: (E, K, NL) expert weight columns; be_ref: (n, cap_s/bm) SMEM
    block→expert table for every shard; out_hbm: (n·cap_s, NL) per-shard
    sorted outputs; ag_hbm: (n·cap_s, K) gathered-slab workspace.
    """
    cap = xs_hbm.shape[0]
    k = xs_hbm.shape[1]
    nl = w_hbm.shape[2]
    bm, bk, bn = blocks
    mb, nb, kb = cap // bm, nl // bn, k // bk

    # No local-slab publish (unlike ag_gemm): the gathered workspace is
    # internal here, the local shard is computed and forwarded straight
    # from xs_hbm, and slab ``me`` is never read by anyone.
    def consume(s, src, a_hbm, a_row_off):
        gmm_pipeline(
            mb, nb, kb, blocks, acc_ref,
            lambda i, src=src: be_ref[src, i],
            a_m_off=a_row_off // bm,
            out_m_off=src * mb,
        )(a_hbm, w_hbm, out_hbm)

    ag_forward_ring(
        n, axis, mesh_axes, xs_hbm, ag_hbm, cap, send_sem, recv_sem, consume,
        site="moe_tp",
    )


def ag_group_gemm_kernel_w(
    n, axis, mesh_axes, blocks, fmt,
    be_ref, xs_hbm, xq_hbm, xsc_hbm, w_hbm,
    out_hbm, ag_hbm, agq_hbm, ags_hbm,
    acc_ref, send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`ag_group_gemm_kernel`: the sorted
    token slabs ride the ring as host-quantized fp8/int8 + per-chunk
    scales (lang.wire) and each arrival is dequantized into the bf16
    workspace before its grouped-GEMM pipeline (local slab exact)."""
    cap = xs_hbm.shape[0]
    k = xs_hbm.shape[1]
    nl = w_hbm.shape[2]
    bm, bk, bn = blocks
    mb, nb, kb = cap // bm, nl // bn, k // bk

    def consume(s, src, a_hbm, a_row_off):
        gmm_pipeline(
            mb, nb, kb, blocks, acc_ref,
            lambda i, src=src: be_ref[src, i],
            a_m_off=a_row_off // bm,
            out_m_off=src * mb,
        )(a_hbm, w_hbm, out_hbm)

    wire = AGWireRefs(
        fmt=fmt, local_q=xq_hbm, local_s=xsc_hbm, agq=agq_hbm, ags=ags_hbm,
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        dequant=wirelib.dequant_pipeline(cap, k, fmt),
    )
    ag_forward_ring(
        n, axis, mesh_axes, xs_hbm, ag_hbm, cap, send_sem, recv_sem, consume,
        site="moe_tp", wire=wire,
    )


def ag_group_gemm_kernel_mx(
    n, axis, mesh_axes, blocks, fmt,
    be_ref, xq_hbm, xsc_hbm, wq_hbm, wsc_hbm,
    out_hbm, agq_hbm, ags_hbm,
    acc_ref, send_sem, recv_sem, s_send_sem, s_recv_sem,
):
    """int8→MXU twin of :func:`ag_group_gemm_kernel_w`: the sorted token
    slabs ride the ring as int8 + per-chunk scales and every arriving
    slab (the local one included) streams straight through the s8×s8
    grouped-GEMM pipeline against the per-(expert, out-channel)
    quantized weights — the per-arrival dequant pass and the bf16
    gathered workspace are gone; scales fold in the accumulator
    epilogue (group_gemm's W8A8 shape)."""
    cap = xq_hbm.shape[0]
    k = xq_hbm.shape[1]
    nl = wq_hbm.shape[2]
    bm, bk, bn = blocks
    mb, nb, kb = cap // bm, nl // bn, k // bk

    def consume(s, src, a_hbm, a_row_off):
        del a_hbm, a_row_off
        if s == 0:
            q_slab, s_rows = xq_hbm, xsc_hbm
        else:
            q_slab = agq_hbm.at[pl.ds(src * cap, cap)]
            s_rows = ags_hbm.at[pl.ds(src * mb, mb)]
        gmm_q8_pipeline(
            mb, nb, kb, blocks, lambda i, src=src: be_ref[src, i]
        )(acc_ref, q_slab, s_rows, wq_hbm, wsc_hbm,
          out_hbm.at[pl.ds(src * cap, cap)])

    wire = AGWireRefs(
        fmt=fmt, local_q=xq_hbm, local_s=xsc_hbm, agq=agq_hbm, ags=ags_hbm,
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        dequant=None,   # the grouped-GEMM epilogue IS the dequant
    )
    ag_forward_ring(
        n, axis, mesh_axes, xq_hbm, agq_hbm, cap, send_sem, recv_sem,
        consume, site="moe_tp", wire=wire,
    )


def moe_reduce_rs_kernel(
    n, axis, mesh_axes, blocks,
    be_ref, y_hbm, w_hbm, out_hbm, w0, w1, r0, r1,
    acc_ref, send_sem, recv_sem, ack_sem,
):
    """Grouped GEMM ⊕ reduce ring over per-shard sorted slabs (≡ the
    producer grouped GEMM signalling the consumer topk-reduce-RS,
    moe_reduce_rs.py:362-545; flow control from reduce_scatter.py's
    ring ack protocol).

    y_hbm: (n·cap_s, FL) per-shard sorted up-projection outputs (FL =
    F/tp columns — each rank's grouped GEMM yields a PARTIAL (cap_s, H)
    per destination); w_hbm: (E, FL, H); out_hbm: (cap_s, H) — this
    rank's fully-reduced sorted rows, still awaiting the local topk
    combine (done in XLA on the destination's own rows).
    """
    cap = out_hbm.shape[0]
    h = out_hbm.shape[1]
    fl = y_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = cap // bm, h // bn, fl // bk

    def partial_into(dst, dst_ref):
        gmm_pipeline(
            mb, nb, kb, blocks, acc_ref,
            lambda i, dst=dst: be_ref[dst, i],
            a_m_off=dst * mb,
        )(y_hbm, w_hbm, dst_ref)

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (r0, r1),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(cap, h, out_hbm.dtype.itemsize),
        site="moe_tp",
    )


def moe_reduce_rs_kernel_w(
    n, axis, mesh_axes, blocks, fmt,
    be_ref, y_hbm, w_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    acc_ref, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`moe_reduce_rs_kernel` (same per-hop
    quantize / f32 dequant-accumulate contract as gemm_rs's wire)."""
    cap = out_hbm.shape[0]
    h = out_hbm.shape[1]
    fl = y_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = cap // bm, h // bn, fl // bk

    def partial_into(dst, dst_ref):
        gmm_pipeline(
            mb, nb, kb, blocks, acc_ref,
            lambda i, dst=dst: be_ref[dst, i],
            a_m_off=dst * mb,
        )(y_hbm, w_hbm, dst_ref)

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1), ws=(ws0, ws1), rq=(rq0, rq1), rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(cap, h, fmt),
        dequant_add=wirelib.dequant_add_pipeline(cap, h, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="moe_tp", wire=wire,
    )


def _wire_fmt(wire, rows, block_m=None):
    if wire is None:
        return None
    from triton_distributed_tpu.config import compiling_for_tpu

    if wire == "int8-mxu":
        # the epilogue consumer pins one scale row per routing block so
        # the grouped pipeline's scale operand indexes plane row i for
        # A row-block i (block_m always divides cap_s)
        wirelib.require_mxu("moe_tp")
        assert block_m is not None and rows % block_m == 0
        return wirelib.WireFormat(quant="int8", chunk_rows=block_m)
    wirelib.require_inkernel(wire, "moe_tp")
    fmt = wirelib.make_wire_format(wire, rows, strict=compiling_for_tpu())
    if fmt is None:
        raise ValueError(
            f"moe_tp wire={wire!r}: slab of {rows} rows admits no legal "
            "scale chunking; use the bf16 wire"
        )
    return fmt


def build_ag_group_gemm_call(
    n, mesh_axes, axis, cap, k, nl, e, blocks, dtype, collective_id,
    wire=None,
):
    """pallas_call for :func:`ag_group_gemm_kernel` (per-device, for use
    inside shard_map). ``wire``: 'fp8'/'int8' switches to the
    quantized-wire kernel — the caller then passes the host-quantized
    (xq, xsc) pair after the sorted slab; 'int8-mxu' to the
    dequant-free epilogue consumer — the caller passes (xq, xsc) plus
    the per-(expert, out-channel) quantized weight pair (wq, wsc) and
    NO bf16 slab at all."""
    fmt = _wire_fmt(wire, cap, blocks[0])
    if wire == "int8-mxu":
        nsem = (max(n - 1, 1),)
        mb = cap // blocks[0]
        return lang.shmem_call(
            functools.partial(
                ag_group_gemm_kernel_mx, n, axis, mesh_axes, blocks, fmt
            ),
            out_shape=[
                jax.ShapeDtypeStruct((n * cap, nl), dtype),
                # the int8 wire workspace IS the gathered representation
                jax.ShapeDtypeStruct((n * cap, k), fmt.wire_dtype),
                jax.ShapeDtypeStruct(
                    (n * mb, wirelib.SCALE_LANES), jnp.float32
                ),
            ],
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            scratch_shapes=[
                pltpu.VMEM((blocks[0], blocks[2]), jnp.int32),  # s32 acc
                pltpu.SemaphoreType.DMA(nsem),
                pltpu.SemaphoreType.DMA(nsem),
                pltpu.SemaphoreType.DMA(nsem),   # scale rail
                pltpu.SemaphoreType.DMA(nsem),
            ],
            collective_id=None if n == 1 else collective_id,
            vmem_limit_bytes=fused_vmem_budget(),
            name="ag_group_gemm_fused_int8mxw",
        )
    if fmt is not None:
        nsem = (max(n - 1, 1),)
        return lang.shmem_call(
            functools.partial(
                ag_group_gemm_kernel_w, n, axis, mesh_axes, blocks, fmt
            ),
            out_shape=[
                jax.ShapeDtypeStruct((n * cap, nl), dtype),
                jax.ShapeDtypeStruct((n * cap, k), dtype),   # bf16 workspace
                jax.ShapeDtypeStruct((n * cap, k), fmt.wire_dtype),
                jax.ShapeDtypeStruct(
                    (n * fmt.chunks(cap), wirelib.SCALE_LANES), jnp.float32
                ),
            ],
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
            scratch_shapes=[
                pltpu.VMEM((blocks[0], blocks[2]), jnp.float32),
                pltpu.SemaphoreType.DMA(nsem),
                pltpu.SemaphoreType.DMA(nsem),
                pltpu.SemaphoreType.DMA(nsem),   # scale rail
                pltpu.SemaphoreType.DMA(nsem),
            ],
            collective_id=None if n == 1 else collective_id,
            vmem_limit_bytes=fused_vmem_budget(),
            name=f"ag_group_gemm_fused_{wire}w",
        )
    return lang.shmem_call(
        functools.partial(ag_group_gemm_kernel, n, axis, mesh_axes, blocks),
        out_shape=[
            jax.ShapeDtypeStruct((n * cap, nl), dtype),
            jax.ShapeDtypeStruct((n * cap, k), dtype),  # ring workspace
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        scratch_shapes=[
            pltpu.VMEM((blocks[0], blocks[2]), jnp.float32),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        # n==1: ag_forward_ring early-returns without touching the
        # barrier semaphore, and Mosaic rejects an unused collective_id
        collective_id=None if n == 1 else collective_id,
        vmem_limit_bytes=fused_vmem_budget(),
        name="ag_group_gemm_fused",
    )


def build_moe_reduce_rs_call(
    n, mesh_axes, axis, cap, fl, h, e, blocks, dtype, collective_id,
    wire=None,
):
    """pallas_call for :func:`moe_reduce_rs_kernel` (per-device).
    ``wire``: 'fp8'/'int8' switches to the quantized-wire reduce ring
    ('int8-mxu' carries its int8 payload — a reduce ring has no MXU
    consumer to fold scales into)."""
    slab = jax.ShapeDtypeStruct((cap, h), dtype)
    fmt = _wire_fmt(wirelib.wire_payload(wire), cap)
    if fmt is not None:
        qslab = jax.ShapeDtypeStruct((cap, h), fmt.wire_dtype)
        sslab = jax.ShapeDtypeStruct(
            (fmt.chunks(cap), wirelib.SCALE_LANES), jnp.float32
        )
        return lang.shmem_call(
            functools.partial(
                moe_reduce_rs_kernel_w, n, axis, mesh_axes, blocks, fmt
            ),
            out_shape=[slab, slab, slab,
                       qslab, qslab, sslab, sslab,
                       qslab, qslab, sslab, sslab],
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 11,
            scratch_shapes=[
                pltpu.VMEM((blocks[0], blocks[2]), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
                pltpu.SemaphoreType.DMA((2,)),   # scale rail
                pltpu.SemaphoreType.DMA((2,)),
            ],
            collective_id=None if n == 1 else collective_id,
            vmem_limit_bytes=fused_vmem_budget(),
            name=f"moe_reduce_rs_fused_{wirelib.wire_payload(wire)}w",
        )
    return lang.shmem_call(
        functools.partial(moe_reduce_rs_kernel, n, axis, mesh_axes, blocks),
        out_shape=[slab, slab, slab, slab, slab],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
        scratch_shapes=[
            pltpu.VMEM((blocks[0], blocks[2]), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=None if n == 1 else collective_id,
        vmem_limit_bytes=fused_vmem_budget(),
        name="moe_reduce_rs_fused",
    )
