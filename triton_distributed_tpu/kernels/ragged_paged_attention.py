"""Ragged paged attention: mixed prefill-chunk + decode rows, ONE launch.

The continuous-batching serving engine (serving/engine.py) assembles a
per-step batch in which some requests contribute ONE decode token and
others contribute a CHUNK of prompt tokens — the "Ragged Paged
Attention" TPU kernel shape (arXiv:2604.15464, PAPERS.md): one kernel,
per-row ``(kv_len, q_len)`` metadata, per-request block tables into a
shared page pool, and NO rectangle padding — each row's KV walk is
``ceil(kv_len/page)`` pages of ITS true length, and each row's query
block is its true chunk (rounded to the sublane granule), packed into
one ragged token array.

Why not reuse the decode kernels (flash_decode.py): those are
one-query-per-row machines — ``q: (B, Hq, D)`` — so a prefill chunk
would need its own rectangle launch per step, which is exactly the
fixed-batch regime the engine exists to kill. This kernel walks BOTH
kinds of rows in one grid, so a step's cost is proportional to the
step's true token/KV volume regardless of the prefill/decode mix.

Layout contract (the "GQA-rows" packing):

* ``q``/``out``: ``(Hkv, T·G, D)`` — head-major, then token-major with
  the G query heads of one token adjacent. Row ``r``'s tokens occupy
  rows ``[q_starts[r]·G, (q_starts[r]+q_lens[r])·G)`` of dim 1. This
  makes each row's per-head query block ONE contiguous
  ``(block_q·G, D)`` DMA run — no in-kernel reshape that changes the
  lane dim (a construct this toolchain's Mosaic rejects; deny rule
  MC005). ``pack_gqa_rows`` / ``unpack_gqa_rows`` convert from/to the
  natural ``(T, Hq, D)``.
* ``q_starts`` must be 8-aligned token offsets (the engine packs rows
  at 8-token granularity — ragged, not rectangular: the pad between
  rows is < 8 tokens, not ``S - len``).
* KV pools: ``(npages, Hkv, page, D)`` ["phsd"], int8 with
  ``(npages, Hkv, page)`` f32 scales (the serving default) or bf16;
  ``block_table``: ``(R, pages_per_seq)`` pool page ids; ``kv_lens``:
  per-row TOTAL lengths INCLUDING this step's tokens (append-then-
  attend — the engine scatters the step's K/V into the pool first, so
  a chunk's tokens attend each other causally through the pool).
* Causality: token ``t`` of row ``r`` sits at global position
  ``kv_lens[r] - q_lens[r] + t`` and attends positions
  ``<= kv_lens[r] - q_lens[r] + t``. Decode rows (``q_lens[r] == 1``)
  degenerate to the flash-decode mask. Only FRONTIER pages (those
  crossing ``kv_len - q_len + 1``) pay the mask chain — interior pages
  run the unmasked fast path, the ``is_tail`` discipline of
  ``flash_decode._decode_kernel_dyn``.
* The ``block_q`` query block is a STATIC per-launch bound on
  ``max(q_lens)``; rows shorter than it over-read into the NEXT row's
  tokens and over-write garbage outputs there, which the ascending
  sequential grid self-heals (row r+1 re-writes its own rows after
  row r; the final row's tail needs ``q_starts[-1] + block_q <= T``
  of slack in the packed array — the engine reserves it). Out-DMAs
  are waited before the grid step ends so the self-heal ordering is
  real, not racy.

The kernel is LOCAL (no remote DMA): under tensor parallelism the
serving state shards the pools over the KV-HEAD dim (heads are
independent in GQA attention — no cross-rank LSE merge needed, unlike
the sequence-sharded decode path), so each rank runs this kernel on
its own head slice. It is registered in the kernel registry as the
``flash_decode.ragged_paged`` family with a ``local`` delivery
contract (every output element covered by locally computed writes, no
raw quantized bytes left) and covered by the Mosaic pre-flight.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.config import local_interpret
from triton_distributed_tpu.lang.launch import shmem_call
from triton_distributed_tpu.utils.testing import chaos_delay

NEG_INF = -1.0e30

# ------------------------------------------------------- attention topology
#
# Per-row mask descriptor over the q×kv tile grid — the 5th scalar-
# prefetch operand (``topologies``). Row layout, ``W`` = descriptor
# width (ancestor-bitmask positions):
#
#   [kind, aux, anc[0..W-1], parent[0..W-1]]        (2 + 2·W) int32
#
# * ``kind``: TOPO_CAUSAL (today's causal-frontier mask — the default,
#   byte-identical outputs), TOPO_TREE (tree-speculation verify row:
#   ``anc[t]`` is the packed ancestor bitmask of q position ``t`` over
#   the row's speculative region ``[kv_len - q_len, kv_len)``, bit 0 =
#   the frontier token, self bit included — sibling branches never
#   attend each other), or TOPO_SHARED_PREFIX (positions below
#   ``aux = split`` tokens are a prefix page run ALIASED across rows'
#   block tables; the mask itself stays causal — the aliasing is a
#   table-level fact the engine's PagePool refcounts make safe), or
#   TOPO_CP (context-parallel KV shard: this row's pool walk covers one
#   cp rank's CONTIGUOUS slice of a longer global sequence, and the
#   causal frontier is shifted RIGHT by ``aux`` tokens — local position
#   ``p`` is visible to q token ``t`` iff
#   ``p < kv_len - q_len + t + 1 + aux`` AND ``p < kv_len``. The owner
#   shard (the one holding the frontier) runs ``aux = 0`` ≡ causal;
#   earlier, fully-covered shards run ``aux >= q_len`` and attend their
#   whole slice; a shard past the data runs ``kv_len = 0`` and masks
#   everything, so its LSE comes back NEG_INF and the cross-rank
#   LSE-combine weighs it zero).
# * ``aux``: TREE → occupied q positions (1 + draft nodes);
#   SHARED_PREFIX → the shared-prefix split in tokens; CP → the
#   frontier shift ``(global_kv - r·slice) - kv_len`` in tokens.
# * ``parent[t]``: q position of t's tree parent (-1 for the frontier)
#   — NOT read by the kernel (the anc bitmask is self-contained); it is
#   the analysis cross-check the masked-coverage SL008 facet validates
#   ``anc[t] == anc[parent[t]] | (1 << t)`` against, so a descriptor
#   that lets a TREE row attend a sibling branch cannot hide.
#
# The bitmask is int32, so a tree verify row carries at most
# TOPO_MAX_NODES q positions (bits 0..30 — bit 31 would overflow the
# signed lane).

TOPO_CAUSAL = 0
TOPO_TREE = 1
TOPO_SHARED_PREFIX = 2
TOPO_CP = 3
TOPO_MAX_NODES = 31


def topo_width(block_q: int) -> int:
    """Descriptor width for a ``block_q`` launch: one ancestor-bitmask
    slot per q position, capped at the int32 bitmask bound."""
    return min(int(block_q), TOPO_MAX_NODES)


def causal_topologies(r: int, width: int):
    """(R, 2+2W) all-CAUSAL descriptor block — the identity operand."""
    return np.zeros((r, 2 + 2 * width), np.int32)


def tree_topology_row(parents, width: int):
    """One TREE descriptor row from per-node parent indices.

    ``parents[i]`` is the parent DRAFT NODE of draft node ``i`` (-1 =
    the frontier token). q position 0 is the frontier; node ``i`` sits
    at q position ``i + 1``."""
    n = len(parents)
    if n + 1 > width:
        raise ValueError(
            f"tree of {n} nodes needs width >= {n + 1}, got {width}")
    row = np.zeros((2 + 2 * width,), np.int32)
    row[0] = TOPO_TREE
    row[1] = n + 1
    anc = np.zeros((width,), np.int64)
    par = np.full((width,), -1, np.int64)
    anc[0] = 1
    for i, p in enumerate(parents):
        t = i + 1
        pt = int(p) + 1
        if not 0 <= pt < t:
            raise ValueError(
                f"node {i}: parent {p} must be an earlier node or -1")
        anc[t] = anc[pt] | (np.int64(1) << t)
        par[t] = pt
    row[2:2 + width] = anc.astype(np.int32)
    row[2 + width:2 + 2 * width] = par.astype(np.int32)
    return row


def shared_prefix_topology_row(split: int, width: int):
    """One SHARED_PREFIX descriptor row (``split`` in tokens)."""
    row = np.zeros((2 + 2 * width,), np.int32)
    row[0] = TOPO_SHARED_PREFIX
    row[1] = int(split)
    return row


def cp_topology_row(shift: int, width: int):
    """One CP descriptor row. ``shift`` is the frontier shift in
    tokens: for cp rank r over a slice of ``s_loc`` positions serving a
    row at global length G, ``shift = max((G - r·s_loc) - kv_len, 0)``
    where ``kv_len = clip(G - r·s_loc, 0, s_loc)`` is the rank's local
    length — 0 on the shard that owns the frontier (pure causal),
    ``>= q_len`` on fully-covered earlier shards."""
    if shift < 0:
        raise ValueError(f"cp frontier shift must be >= 0, got {shift}")
    row = np.zeros((2 + 2 * width,), np.int32)
    row[0] = TOPO_CP
    row[1] = int(shift)
    return row


def _n_valid_pages(kv_len, page):
    """ceil(kv_len / page) floored at 1 (an empty row still walks one
    page; its scores are fully masked)."""
    return jnp.maximum(jax.lax.div(kv_len + page - 1, page), 1)


def pack_gqa_rows(q, hkv):
    """(T, Hq, D) → (Hkv, T·G, D): the kernel's GQA-rows layout — one
    contiguous (q_len·G, D) run per (row, kv-head)."""
    t, hq, d = q.shape
    g = hq // hkv
    return q.reshape(t, hkv, g, d).transpose(1, 0, 2, 3).reshape(
        hkv, t * g, d
    )


def unpack_gqa_rows(o, hq):
    """(Hkv, T·G, D) → (T, Hq, D): inverse of :func:`pack_gqa_rows`."""
    hkv, tg, d = o.shape
    g = hq // hkv
    t = tg // g
    return o.reshape(hkv, t, g, d).transpose(1, 0, 2, 3).reshape(t, hq, d)


def _ragged_kernel(
    scale, soft_cap, page, n_bufs, hkv, g, d, block_q, quant, topo_w,
    *refs,
):
    """Grid (R,): one request row per step; all local KV heads unrolled.

    Per row: a dynamic ``fori_loop`` over ``ceil(kv_len/page)`` pages
    with double-buffered table-indexed pool DMAs (the
    ``_paged_kernel_dyn_mh`` machinery), a per-row query block of
    ``block_q`` tokens DMA'd once (double-buffered across rows), and
    an online softmax whose state spans the row's ``block_q·G`` query
    rows per head. Slot rotation and the row-ahead prefetch ride an
    SMEM carry — SEQUENTIAL grid execution required (pinned via
    dimension_semantics).

    ``topo_w`` (static): 0 keeps the pre-topology kernel bit-for-bit
    (four scalar operands, every row causal, every row active); > 0
    adds the 5th scalar-prefetch topology operand of that descriptor
    width, the TREE ancestor-bitmask mask, and the ``q_len == 0`` row
    skip — inactive rows are hopped over by the cross-row q-prefetch
    (the prefetch targets the NEXT ACTIVE row, not ``r + 1``) and
    leave carries, buffers, and their stale out spans untouched."""
    if quant:
        if topo_w:
            (table_ref, kv_lens_ref, q_lens_ref, q_starts_ref, topo_ref,
             q_hbm, k_hbm, v_hbm, ks_hbm, vs_hbm,
             out_hbm, lse_hbm,
             qbuf, kbuf, vbuf, ksbuf, vsbuf, obuf, lbuf,
             sem_q, sem_k, sem_v, sem_ks, sem_vs, sem_o,
             slot_ref, m_ref, l_ref, acc_ref) = refs
        else:
            (table_ref, kv_lens_ref, q_lens_ref, q_starts_ref,
             q_hbm, k_hbm, v_hbm, ks_hbm, vs_hbm,
             out_hbm, lse_hbm,
             qbuf, kbuf, vbuf, ksbuf, vsbuf, obuf, lbuf,
             sem_q, sem_k, sem_v, sem_ks, sem_vs, sem_o,
             slot_ref, m_ref, l_ref, acc_ref) = refs
    else:
        if topo_w:
            (table_ref, kv_lens_ref, q_lens_ref, q_starts_ref, topo_ref,
             q_hbm, k_hbm, v_hbm,
             out_hbm, lse_hbm,
             qbuf, kbuf, vbuf, obuf, lbuf,
             sem_q, sem_k, sem_v, sem_o,
             slot_ref, m_ref, l_ref, acc_ref) = refs
        else:
            (table_ref, kv_lens_ref, q_lens_ref, q_starts_ref,
             q_hbm, k_hbm, v_hbm,
             out_hbm, lse_hbm,
             qbuf, kbuf, vbuf, obuf, lbuf,
             sem_q, sem_k, sem_v, sem_o,
             slot_ref, m_ref, l_ref, acc_ref) = refs
    r = pl.program_id(0)
    nr = pl.num_programs(0)
    npages = k_hbm.shape[0]
    pps = table_ref.shape[1]
    nrows = table_ref.shape[0]
    rows = block_q * g

    kv_len = kv_lens_ref[r]
    q_len = q_lens_ref[r]
    nb = jnp.minimum(_n_valid_pages(kv_len, page), pps)

    def dma(rr, j, slot):
        # row rr's j-th page; clamp so a prefetch into a short row's
        # padding never addresses out of pool (table pad entries incl.
        # -1 are clamped too)
        jc = jnp.minimum(
            j, jnp.maximum(_n_valid_pages(kv_lens_ref[rr], page) - 1, 0)
        )
        pid = jnp.clip(table_ref[rr, jc], 0, npages - 1)
        cps = [
            pltpu.make_async_copy(
                k_hbm.at[pid], kbuf.at[slot], sem_k.at[slot]
            ),
            pltpu.make_async_copy(
                v_hbm.at[pid], vbuf.at[slot], sem_v.at[slot]
            ),
        ]
        if quant:
            cps += [
                pltpu.make_async_copy(
                    ks_hbm.at[pid], ksbuf.at[slot], sem_ks.at[slot]
                ),
                pltpu.make_async_copy(
                    vs_hbm.at[pid], vsbuf.at[slot], sem_vs.at[slot]
                ),
            ]
        return cps

    def qdma(rr, qslot):
        # the row's whole query block, every local head, one strided
        # copy (hkv contiguous (rows, d) runs)
        start = q_starts_ref[rr] * g
        return pltpu.make_async_copy(
            q_hbm.at[:, pl.ds(start, rows)], qbuf.at[qslot],
            sem_q.at[qslot],
        )

    if topo_w:
        # ---- q_len == 0 skip: the cross-row prefetch hop protocol ----
        # next_active(a): smallest active row index >= a (static unroll
        # over the R-sized scalar operand; nrows when none). The warmup
        # and the end-of-row prefetch both target the next ACTIVE row,
        # and an inactive row's entire body is skipped — its carries
        # pass through untouched, so the rotation the last active row
        # handed on still matches the buffers in flight.
        def next_active(after):
            na = jnp.int32(nrows)
            for rr in range(nrows - 1, -1, -1):
                na = jnp.where(
                    jnp.logical_and(rr >= after, q_lens_ref[rr] > 0),
                    jnp.int32(rr), na,
                )
            return na

        first_active = next_active(0)
        nxt_active = next_active(r + 1)
        nxt_clamped = jnp.minimum(nxt_active, nrows - 1)

        @pl.when(r == 0)
        def _warmup():
            slot_ref[0] = 0                   # KV slot rotation carry
            slot_ref[1] = 0                   # q double-buffer parity

            @pl.when(first_active < nr)
            def _start_first():
                fa = jnp.minimum(first_active, nrows - 1)
                qdma(fa, 0).start()
                for cp in dma(fa, 0, 0):
                    cp.start()
    else:
        @pl.when(r == 0)
        def _warmup():
            slot_ref[0] = 0                   # KV slot rotation carry
            slot_ref[1] = 0                   # q double-buffer parity
            qdma(0, 0).start()
            for cp in dma(0, 0, 0):
                cp.start()

    def row_body():
        s0 = slot_ref[0]
        qslot = slot_ref[1]
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)
        qdma(r, qslot).wait()                 # warmed by the previous row

        # per-query-row causal limit: token t = row // g sits at global
        # position kv_len - q_len + t and may attend positions < limit =
        # that + 1. Rows past q_len (block padding) get limit > kv_len —
        # they attend whatever the pool holds and produce garbage the
        # packing contract discards (see module docstring).
        base = kv_len - q_len
        row_tok = jax.lax.div(
            jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0), g
        )
        limit = base + row_tok + 1            # (rows, 1)

        if topo_w:
            # the row's descriptor, materialized as per-query-row
            # columns via STATIC selects over the descriptor width —
            # the vector-indexed gather Mosaic rejects (MC006) is
            # exactly what this unroll avoids.
            kind = topo_ref[r, 0]
            aux = topo_ref[r, 1]
            anc_col = jnp.zeros((rows, 1), jnp.int32)
            for t in range(min(topo_w, block_q)):
                anc_col = jnp.where(
                    row_tok == t, topo_ref[r, 2 + t], anc_col
                )

        def body(j, _):
            slot = jax.lax.rem(s0 + j, n_bufs)
            nxt = jax.lax.rem(s0 + j + 1, n_bufs)

            @pl.when(j + 1 < nb)
            def _prefetch_in_row():
                for cp in dma(r, j + 1, nxt):
                    cp.start()

            if topo_w:
                @pl.when(jnp.logical_and(j + 1 == nb, nxt_active < nr))
                def _prefetch_next_row():
                    qdma(nxt_clamped, 1 - qslot).start()
                    for cp in dma(nxt_clamped, 0, nxt):
                        cp.start()
            else:
                @pl.when(jnp.logical_and(j + 1 == nb, r + 1 < nr))
                def _prefetch_next_row():
                    qdma(r + 1, 1 - qslot).start()
                    for cp in dma(r + 1, 0, nxt):
                        cp.start()

            # chaos hook: widens the slot-rotation window between the
            # prefetch issues and this page's wait (the race-prone carry)
            chaos_delay(site="ragged_paged", step=None, me=None, n=None)
            for cp in dma(r, j, slot):
                cp.wait()

            # only pages crossing the causal frontier (or the length
            # tail) pay the mask chain; interior pages take the plain
            # path. TREE rows: the speculative region [base, kv_len) is
            # entirely frontier pages, so interior pages stay fast.
            is_frontier = (j + 1) * page > base + 1

            def heads(masked):
                if masked:
                    pos = j * page + jax.lax.broadcasted_iota(
                        jnp.int32, (1, page), 1
                    )
                    valid = pos < limit       # (rows, page)
                    if topo_w:
                        # TREE: position base+t is visible to query row
                        # t' iff bit t of anc[t'] is set; everything
                        # below base stays causal-visible, everything
                        # past kv_len masked. SHARED_PREFIX masks as
                        # causal (the aliasing is table-level).
                        rel = pos - base      # (rows, page)
                        bit = jax.lax.shift_right_logical(
                            anc_col, jnp.clip(rel, 0, 31)
                        ) & 1
                        tree_valid = jnp.logical_and(
                            pos < kv_len,
                            jnp.logical_or(rel < 0, bit > 0),
                        )
                        valid = jnp.where(
                            kind == TOPO_TREE, tree_valid, valid
                        )
                        # CP: this rank's slice sits ``aux`` tokens to
                        # the LEFT of the causal frontier, so the limit
                        # shifts right by aux; the ``pos < kv_len``
                        # conjunct is load-bearing — on fully-covered
                        # shards limit + aux exceeds kv_len and padding
                        # rows must not read past the slice.
                        cp_valid = jnp.logical_and(
                            pos < kv_len, pos < limit + aux
                        )
                        valid = jnp.where(
                            kind == TOPO_CP, cp_valid, valid
                        )
                for h in range(hkv):          # static unroll
                    q = qbuf[qslot, h]        # (rows, d)
                    k = kbuf[slot, h]
                    v = vbuf[slot, h]
                    if quant:
                        k = k.astype(jnp.bfloat16)
                        v = v.astype(jnp.bfloat16)
                    s = jax.lax.dot_general(
                        q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * scale                 # (rows, page) f32
                    if quant:
                        s = s * ksbuf[slot, h]   # (1, page) — exact fold
                    if soft_cap > 0.0:
                        s = soft_cap * jnp.tanh(s / soft_cap)
                    if masked:
                        s = jnp.where(valid, s, NEG_INF)
                    lo, hi = h * rows, (h + 1) * rows
                    m = m_ref[lo:hi]
                    m_new = jnp.maximum(
                        m, jnp.max(s, axis=1, keepdims=True)
                    )
                    alpha = jnp.exp(m - m_new)
                    p = jnp.exp(s - m_new)
                    if masked:
                        # an all-masked row degenerates exp(s - m) to 1
                        p = jnp.where(valid, p, 0.0)
                    l_ref[lo:hi] = alpha * l_ref[lo:hi] + jnp.sum(
                        p, axis=1, keepdims=True
                    )
                    if quant:
                        pv = (p * vsbuf[slot, h]).astype(v.dtype)
                    else:
                        pv = p.astype(v.dtype)
                    acc_ref[lo:hi] = alpha * acc_ref[lo:hi] + jnp.dot(
                        pv, v, preferred_element_type=jnp.float32
                    )
                    m_ref[lo:hi] = m_new

            @pl.when(is_frontier)
            def _masked():
                heads(True)

            @pl.when(jnp.logical_not(is_frontier))
            def _plain():
                heads(False)

            return 0

        jax.lax.fori_loop(0, nb, body, 0)
        slot_ref[0] = jax.lax.rem(s0 + nb, n_bufs)  # hand the rotation on
        if topo_w:
            slot_ref[1] = jnp.where(nxt_active < nr, 1 - qslot, qslot)
        else:
            slot_ref[1] = jnp.where(r + 1 < nr, 1 - qslot, qslot)

        for h in range(hkv):
            lo, hi = h * rows, (h + 1) * rows
            l = l_ref[lo:hi]
            safe_l = jnp.where(l > 0.0, l, 1.0)
            obuf[h] = (acc_ref[lo:hi] / safe_l).astype(obuf.dtype)
            lbuf[h] = jnp.where(
                l > 0.0, m_ref[lo:hi] + jnp.log(safe_l),
                jnp.full_like(l, NEG_INF)
            )
        start = q_starts_ref[r] * g
        o_cp = pltpu.make_async_copy(
            obuf, out_hbm.at[:, pl.ds(start, rows)], sem_o.at[0]
        )
        l_cp = pltpu.make_async_copy(
            lbuf, lse_hbm.at[:, pl.ds(start, rows)], sem_o.at[1]
        )
        o_cp.start()
        l_cp.start()
        # wait BEFORE the grid advances: overlapping rows' out regions
        # self-heal by write order, which async completions would break
        o_cp.wait()
        l_cp.wait()

    if topo_w:
        @pl.when(q_len > 0)
        def _active_row():
            row_body()
    else:
        row_body()


@functools.lru_cache(maxsize=64)
def _build_ragged(
    r, pps, npages, t_tokens, hkv, g, d, page, block_q, q_dtype,
    quant, scale, soft_cap, n_bufs, interpret, token=(), topo_w=0,
):
    """Construct the ragged-paged-attention pallas_call (lru-cached on
    the full static geometry; ``token`` busts the cache for lint/
    preflight builds). Returns the call taking
    ``(table, kv_lens, q_lens, q_starts[, topologies], q, k_pool,
    v_pool [, k_scale, v_scale])`` — the topology operand present iff
    ``topo_w > 0`` (its descriptor width; 0 = the pre-topology
    launch, bit-for-bit)."""
    del token
    q_dtype = jnp.dtype(q_dtype)
    rows = block_q * g
    kernel = functools.partial(
        _ragged_kernel, scale, soft_cap, page, n_bufs, hkv, g, d,
        block_q, quant, topo_w,
    )
    pool_dt = jnp.dtype(jnp.int8) if quant else q_dtype
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),    # q (head-major packed)
        pl.BlockSpec(memory_space=pl.ANY),    # k pool
        pl.BlockSpec(memory_space=pl.ANY),    # v pool
    ]
    scratch = [
        pltpu.VMEM((2, hkv, rows, d), q_dtype),          # qbuf
        pltpu.VMEM((n_bufs, hkv, page, d), pool_dt),     # kbuf
        pltpu.VMEM((n_bufs, hkv, page, d), pool_dt),     # vbuf
    ]
    sems = [
        pltpu.SemaphoreType.DMA((2,)),        # sem_q
        pltpu.SemaphoreType.DMA((n_bufs,)),   # sem_k
        pltpu.SemaphoreType.DMA((n_bufs,)),   # sem_v
    ]
    if quant:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),   # k scales
            pl.BlockSpec(memory_space=pl.ANY),   # v scales
        ]
        scratch += [
            pltpu.VMEM((n_bufs, hkv, 1, page), jnp.float32),  # ksbuf
            pltpu.VMEM((n_bufs, hkv, 1, page), jnp.float32),  # vsbuf
        ]
        sems += [
            pltpu.SemaphoreType.DMA((n_bufs,)),  # sem_ks
            pltpu.SemaphoreType.DMA((n_bufs,)),  # sem_vs
        ]
    scratch += [
        pltpu.VMEM((hkv, rows, d), q_dtype),             # obuf
        pltpu.VMEM((hkv, rows, 1), jnp.float32),         # lbuf
    ]
    sems += [pltpu.SemaphoreType.DMA((2,))]   # sem_o (out, lse)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        # table, kv_lens, q_lens, starts [+ per-row topology]
        num_scalar_prefetch=5 if topo_w else 4,
        grid=(r,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),           # out
            pl.BlockSpec(memory_space=pl.ANY),           # lse
        ],
        scratch_shapes=scratch + sems + [
            pltpu.SMEM((2,), jnp.int32),                 # slot carries
            pltpu.VMEM((hkv * rows, 1), jnp.float32),    # m
            pltpu.VMEM((hkv * rows, 1), jnp.float32),    # l
            pltpu.VMEM((hkv * rows, d), jnp.float32),    # acc
        ],
    )
    # VMEM working set: the kv slot buffers + scale planes + q/out
    # blocks + softmax state, with pipeline headroom
    kv_bytes = 2 * n_bufs * hkv * page * d * pool_dt.itemsize
    sc_bytes = 2 * n_bufs * hkv * page * 4 if quant else 0
    q_bytes = 3 * hkv * rows * d * q_dtype.itemsize
    st_bytes = hkv * rows * (d + 2) * 4
    vmem_limit = None
    total = kv_bytes + sc_bytes + q_bytes + st_bytes
    if total > 12 * 1024 * 1024:
        vmem_limit = total + 8 * 1024 * 1024
    call = shmem_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hkv, t_tokens * g, d), q_dtype),
            jax.ShapeDtypeStruct((hkv, t_tokens * g, 1), jnp.float32),
        ],
        collective_id=None,                   # purely local kernel
        vmem_limit_bytes=vmem_limit,
        interpret=local_interpret() if interpret is None else interpret,
        name="ragged_paged_attention" + ("_q8" if quant else ""),
        # slot-rotation carries + cross-row prefetch + out self-heal
        # all require SEQUENTIAL grid execution
        dimension_semantics=("arbitrary",),
    )
    return call


def auto_block_q(max_q_len: int, g: int) -> int:
    """Smallest block from the {8, 16, 32, 64, 128, ...} ladder covering
    ``max_q_len`` whose GQA row count (block·G) is sublane-aligned —
    keeping the jit/kernel cache bounded while decode-dominated steps
    don't pay a prefill-sized MXU block."""
    b = 8
    while b < max_q_len:
        b *= 2
    while (b * g) % 8:
        b *= 2
    return b


@functools.partial(
    jax.jit,
    static_argnames=("group", "scale", "soft_cap", "block_q", "n_bufs",
                     "interpret"),
)
def ragged_paged_attention(
    q, k_pool, v_pool, kv_lens, q_lens, q_starts, block_table, *,
    group: int, topologies=None, k_scale=None, v_scale=None,
    scale: float | None = None, soft_cap: float = 0.0, block_q: int = 8,
    n_bufs: int = 2, interpret=None,
):
    """Mixed prefill-chunk/decode attention over a shared page pool.

    q: (Hkv, T·G, D) packed GQA rows (:func:`pack_gqa_rows`) with
    ``group`` = G = Hq // Hkv (not recoverable from the packed shape);
    k_pool/v_pool: (npages, Hkv, page, D) — int8 when ``k_scale``/
    ``v_scale`` ((npages, Hkv, page) f32) are given, else q.dtype;
    kv_lens/q_lens/q_starts: (R,) int32 per-row metadata (lengths
    INCLUDE this step's tokens; starts are 8-aligned token offsets
    with ``q_starts[r] + block_q <= T`` slack for every row);
    block_table: (R, pages_per_seq) int32 pool page ids. ``block_q``:
    static bound on max(q_lens) (see :func:`auto_block_q`).

    ``topologies``: optional (R, 2+2W) int32 per-row attention-topology
    descriptors (see the module-level layout notes) — None keeps the
    pre-topology launch bit-for-bit. When given, TREE rows mask by
    ancestor bitmask, SHARED_PREFIX rows read aliased prefix pages
    through their (deduplicated) block tables, and ``q_len == 0`` rows
    are skipped by the cross-row prefetch hop.

    Returns (out (Hkv, T·G, D) in q.dtype, lse (Hkv, T·G) f32). Rows
    of dim 1 outside the per-row valid spans hold garbage (the packing
    contract; see the module docstring).
    """
    hkv, tg, d = q.shape
    g = group
    npages, _, page, _ = k_pool.shape
    assert v_pool.shape == k_pool.shape, (k_pool.shape, v_pool.shape)
    assert tg % g == 0, (tg, g)
    t_tokens = tg // g
    r, pps = block_table.shape
    quant = k_scale is not None
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if (block_q * g) % 8:
        raise ValueError(
            f"ragged_paged_attention: block_q·G = {block_q * g} must be "
            "sublane-aligned (multiple of 8) — pick block_q via "
            "auto_block_q"
        )
    topo_w = 0
    if topologies is not None:
        tr, tw = topologies.shape
        topo_w = (tw - 2) // 2
        if tr != r or tw != 2 + 2 * topo_w or not (
            1 <= topo_w <= TOPO_MAX_NODES
        ):
            raise ValueError(
                f"ragged_paged_attention: topologies shape {(tr, tw)} "
                f"must be (R={r}, 2+2·W) with 1 <= W <= {TOPO_MAX_NODES}"
            )
    call = _build_ragged(
        r, pps, npages, t_tokens, hkv, g, d, page, block_q,
        jnp.dtype(q.dtype).name, quant, float(scale), float(soft_cap),
        n_bufs, interpret, (), topo_w,
    )
    args = [
        block_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
        q_lens.astype(jnp.int32), q_starts.astype(jnp.int32),
    ]
    if topo_w:
        args.append(topologies.astype(jnp.int32))
    args += [q, k_pool, v_pool]
    if quant:
        args += [
            k_scale.astype(jnp.float32).reshape(npages, hkv, 1, page),
            v_scale.astype(jnp.float32).reshape(npages, hkv, 1, page),
        ]
    out, lse = call(*args)
    return out, lse.reshape(hkv, tg)


def ragged_paged_attention_xla(
    q, k_pool, v_pool, kv_lens, q_lens, q_starts, block_table, *,
    group: int, topologies=None, k_scale=None, v_scale=None, scale=None,
    soft_cap=0.0,
):
    """Dense-XLA twin (correctness reference + degradation target):
    gather each row's pages into a contiguous cache and run the masked
    dense attention with the same causal-frontier semantics — including
    the per-row topology operand (TREE ancestor-bitmask masks; CAUSAL
    and SHARED_PREFIX rows mask causally). Same signature/garbage-rows
    contract as :func:`ragged_paged_attention`.
    """
    hkv, tg, d = q.shape
    g = group
    t_tokens = tg // g
    npages, _, page, _ = k_pool.shape
    r, pps = block_table.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if k_scale is not None:
        k_pool = (k_pool.astype(jnp.float32)
                  * k_scale[..., None]).astype(q.dtype)
        v_pool = (v_pool.astype(jnp.float32)
                  * v_scale[..., None]).astype(q.dtype)
    safe = jnp.clip(block_table.astype(jnp.int32), 0, npages - 1)
    # (R, pps, Hkv, page, D) → (R, Hkv, pps·page, D)
    kc = k_pool[safe].transpose(0, 2, 1, 3, 4).reshape(r, hkv, -1, d)
    vc = v_pool[safe].transpose(0, 2, 1, 3, 4).reshape(r, hkv, -1, d)
    s_cap = pps * page

    # token t of the packed array belongs to row rt with position
    # pt = kv_len[rt] - q_len[rt] + (t - q_start[rt]); tokens outside
    # every row's span keep row -1 (their outputs are garbage anyway —
    # compute them against row 0 with a full mask)
    tok = jnp.arange(t_tokens)
    row_of = jnp.full((t_tokens,), -1, jnp.int32)
    for rr in range(r):
        inside = (tok >= q_starts[rr]) & (tok < q_starts[rr] + q_lens[rr])
        row_of = jnp.where(inside, rr, row_of)
    row_c = jnp.clip(row_of, 0, r - 1)
    t_in_row = tok - q_starts[row_c]
    limit = jnp.where(
        row_of >= 0,
        kv_lens[row_c] - q_lens[row_c] + t_in_row + 1,
        0,
    )                                          # (T,)

    qg = q.reshape(hkv, t_tokens, g, d).astype(jnp.float32)
    kt = kc[row_c].astype(jnp.float32)         # (T, Hkv, S, D)
    vt = vc[row_c].astype(jnp.float32)
    s = jnp.einsum("htgd,thsd->htgs", qg, kt) * scale
    if soft_cap > 0.0:
        s = soft_cap * jnp.tanh(s / soft_cap)
    pos_s = jnp.arange(s_cap)
    ok = pos_s[None, :] < limit[:, None]       # (T, S) causal
    if topologies is not None:
        topologies = jnp.asarray(topologies, jnp.int32)
        w = (topologies.shape[1] - 2) // 2
        kind_t = topologies[row_c, 0]          # (T,)
        anc_t = topologies[row_c, 2 + jnp.clip(t_in_row, 0, w - 1)]
        base_t = kv_lens[row_c] - q_lens[row_c]
        rel = pos_s[None, :] - base_t[:, None]             # (T, S)
        bit = jnp.right_shift(anc_t[:, None], jnp.clip(rel, 0, 31)) & 1
        tree_ok = (pos_s[None, :] < kv_lens[row_c][:, None]) & (
            (rel < 0) | (bit > 0)
        )
        ok = jnp.where(
            ((kind_t == TOPO_TREE) & (row_of >= 0))[:, None],
            tree_ok, ok,
        )
        aux_t = topologies[row_c, 1]           # (T,) cp frontier shift
        cp_ok = (pos_s[None, :] < kv_lens[row_c][:, None]) & (
            pos_s[None, :] < (limit + aux_t)[:, None]
        )
        ok = jnp.where(
            ((kind_t == TOPO_CP) & (row_of >= 0))[:, None],
            cp_ok, ok,
        )
    mask = ok[None, :, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("htgs,thsd->htgd", p / jnp.maximum(l, 1e-30), vt)
    lse = jnp.where(
        l[..., 0] > 0,
        m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)),
        NEG_INF,
    )
    return (
        out.reshape(hkv, tg, d).astype(q.dtype),
        lse.reshape(hkv, tg),
    )


# ------------------------------------------------------------ lint geometry
#
# The registry family builds the kernel at this small fixed geometry:
# 2 rows, 1-page walks, G=1, 8-token blocks packed with ZERO slack
# (q_starts = (0, 8), T = 16) so the `local` delivery contract can
# require FULL coverage of the out buffer by locally computed writes.

LINT_GEOM = dict(r=2, pps=2, npages=4, t=16, hkv=2, g=1, d=128, page=8,
                 block_q=8, topo_w=8)

#: parking-zone slack the GRID lint geometry reserves past each row's
#: packed span — the widest block_q a legal candidate may write into it.
#: A schedule whose block overruns even this slack spills into the next
#: row's delivered span (OOB on the zero-slack gate buffer → SL008).
GRID_BLOCK_CAP = 16


def grid_lint_geom(schedule=None) -> dict:
    """The :data:`LINT_GEOM`-shaped geometry a grid schedule gates at:
    the packing granularity ``pack_rows`` sets the per-row span, the
    schedule's ``block_q`` (0 = the :func:`auto_block_q` ladder) sets
    the query block, and the packed width reserves exactly
    ``min(block_q, GRID_BLOCK_CAP)`` tokens of tail slack — so the
    default schedule reproduces :data:`LINT_GEOM` exactly (byte-
    identity pin) while an over-wide block has nowhere legal to park
    its writes."""
    g = 1
    pack = 8 if schedule is None else int(schedule.pack_rows)
    bq = 0 if schedule is None else int(schedule.block_q)
    bq = bq or auto_block_q(pack, g)
    page = 8
    t = pack + min(bq, GRID_BLOCK_CAP)
    kv0 = pack + 4                        # row 0 crosses a page boundary
    pps = -(-kv0 // page)
    topo_w = topo_width(max(bq, 8))
    topo = causal_topologies(2, topo_w)
    tree_pack = 0 if schedule is None else int(
        getattr(schedule, "tree_pack", 0)
    )
    if tree_pack > 0:
        # exercise the TREE mask path at the gate: row 1 carries a
        # branchy verify tree (trunk chain + one sibling branch off the
        # frontier) of min(tree_pack, pack) nodes
        nd = max(min(tree_pack, pack) - 1, 1)
        parents = [-1] + list(range(nd - 1))
        if nd >= 3:
            parents[2] = -1               # sibling branch off the root
        topo[1] = tree_topology_row(parents[:nd], topo_w)
    return dict(
        r=2, pps=pps, npages=2 * pps, t=t, hkv=2, g=g, d=128, page=page,
        block_q=bq, n_bufs=2 if schedule is None else int(schedule.n_bufs),
        kv_lens=(kv0, pack), q_lens=(pack, pack), q_starts=(0, pack),
        topo_w=topo_w, topo=topo,
    )


def build_grid_lint_kernel(token=(), schedule=None, quant=True):
    """Grid-schedule gate entry: construct the ragged kernel at
    :func:`grid_lint_geom` with the schedule's ``block_q``/``n_bufs``
    threaded through the production builder. Returns the geometry dict
    so the gate can derive matching input shapes and scalar-prefetch
    init values."""
    gm = grid_lint_geom(schedule)
    _build_ragged(
        gm["r"], gm["pps"], gm["npages"], gm["t"], gm["hkv"], gm["g"],
        gm["d"], gm["page"], gm["block_q"], "float32", quant,
        1.0 / math.sqrt(gm["d"]), 0.0, gm["n_bufs"], False, token,
        gm["topo_w"],
    )
    return gm


def build_lint_kernel(token=(), quant=True):
    """Construct the ragged kernel exactly as production would (via
    shmem_call, so the LaunchSpec is captured under the family's
    launch name) at :data:`LINT_GEOM`. Used by the kernel registry and
    the Mosaic pre-flight."""
    gm = LINT_GEOM
    return _build_ragged(
        gm["r"], gm["pps"], gm["npages"], gm["t"], gm["hkv"], gm["g"],
        gm["d"], gm["page"], gm["block_q"], "float32", quant,
        1.0 / math.sqrt(gm["d"]), 0.0, 2, False, token, gm["topo_w"],
    )
