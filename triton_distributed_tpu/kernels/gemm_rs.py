"""GEMM-ReduceScatter: row-parallel TP overlap of matmul with reduction.

Reference: python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py —
producer GEMM in rank-swizzled tile order signalling per-destination
barriers (:124-235), consumer ``reduce_scatter_2d_op`` on separate
streams (reduce_scatter.py:863), host entries ``gemm_rs_op``/``gemm_rs``
(:498-560).

TPU re-design: a reduce ring in which each step's contribution is
*computed into the ring* by the MXU while the previous partial is in
flight — the matmul for the next destination shard overlaps the RDMA of
the current accumulator, replacing the reference's GEMM-stream /
RS-stream pair with single-kernel software pipelining. Tile order is
rank-swizzled by construction: device ``me`` computes destination shards
``me+1, me+2, …, me`` so every shard's partial flows leftward and ends
fully reduced on its owner.

Engines: ``PALLAS_FUSED`` (VMEM-resident, ICI), ``XLA_RING``
(ppermute+dot loop, any size / DCN), ``XLA_NAIVE`` (dot → psum_scatter
baseline, ≡ the torch reference impl in test_gemm_rs.py).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import config, fused_vmem_budget, on_tpu
from triton_distributed_tpu.kernels.reduce_scatter import ring_reduce_core
from triton_distributed_tpu.runtime import LinkKind, detect_topology, mesh_axes_size


class GemmRSMethod(enum.Enum):
    PALLAS_FUSED = "pallas_fused"
    XLA_RING = "xla_ring"
    XLA_NAIVE = "xla_naive"


def _fused_kernel(
    n, axis, mesh_axes, a_ref, b_ref, out_ref, acc_ref, recv_ref, send_sem, recv_sem, ack_sem
):
    """Compute-into-the-ring GEMM-RS: the shared ring-reduce core
    (kernels/reduce_scatter.py:ring_reduce_core) with the per-destination
    contribution produced by the MXU. ``make_partial`` runs between a
    slot DMA's start and wait, so each destination's matmul overlaps the
    in-flight accumulator (the producer/consumer stream overlap of the
    reference, collapsed into one kernel). Destination order me+1…me is
    the rank-swizzle of gemm_reduce_scatter.py:205-219."""
    m = out_ref.shape[0]

    def make_partial(dst):
        return jnp.dot(
            a_ref[pl.ds(dst * m, m)], b_ref[:], preferred_element_type=jnp.float32
        ).astype(acc_ref.dtype)

    ring_reduce_core(
        n, axis, mesh_axes, make_partial,
        out_ref, acc_ref, recv_ref, send_sem, recv_sem, ack_sem,
    )


def _specs(axis, batch_axes):
    """(in_specs, out_specs) for GEMM-RS under shard_map over the full mesh.

    Activation rows may additionally be sharded over ``batch_axes`` (DP);
    the reduce-scatter then runs over ``axis`` within each DP group and the
    output rows end up sharded over (*batch_axes, axis) — the Megatron
    sequence-parallel layout, the exact inverse of ag_gemm's."""
    ba = tuple(batch_axes)
    a_spec = P(ba if ba else None, axis)
    b_spec = P(axis, None)
    out_spec = P(ba + (axis,) if ba else axis, None)
    return (a_spec, b_spec), out_spec


@functools.lru_cache(maxsize=256)
def _build_fused(
    mesh, axis, batch_axes, a_shape, b_shape, dtype, out_dtype, collective_id, chaos
):
    n = mesh.shape[axis]
    dp = mesh_axes_size(mesh, batch_axes)
    m_local = a_shape[0] // (dp * n)
    n_out = b_shape[1]

    call = lang.shmem_call(
        functools.partial(_fused_kernel, n, axis, mesh.axis_names),
        out_shape=jax.ShapeDtypeStruct((m_local, n_out), out_dtype),
        in_specs=lang.vmem_specs(2),
        scratch_shapes=[
            pltpu.VMEM((m_local, n_out), out_dtype),
            pltpu.VMEM((2, m_local, n_out), out_dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=collective_id,
        name="gemm_rs_fused",
    )
    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        call, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


def gemm_rs_device(a_loc, b_loc, axis, *, out_dtype=None):
    """Per-device XLA-ring GEMM-RS body — usable inside any shard_map.

    The accumulator flows leftward around the ring while the next
    destination's partial matmul runs, overlapped by XLA async permute."""
    n = jax.lax.axis_size(axis)
    out_dtype = out_dtype or a_loc.dtype
    m_local = a_loc.shape[0] // n
    me = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % n) for i in range(n)]

    def partial(dst):
        rows = jax.lax.dynamic_slice(
            a_loc, (dst * m_local, 0), (m_local, a_loc.shape[1])
        )
        return jnp.dot(rows, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )

    def step(s, acc):
        acc = jax.lax.ppermute(acc, axis, perm=perm)
        return acc + partial(jax.lax.rem(me + 2 + s, n))

    acc = partial(jax.lax.rem(me + 1, n))
    return jax.lax.fori_loop(0, n - 1, step, acc)


@functools.lru_cache(maxsize=256)
def _build_xla_ring(mesh, axis, batch_axes, out_dtype):
    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        functools.partial(gemm_rs_device, axis=axis, out_dtype=out_dtype),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_xla_naive(mesh, axis, batch_axes, out_dtype):
    def body(a_loc, b_loc):
        full = jnp.dot(a_loc, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
        return jax.lax.psum_scatter(full, axis, scatter_dimension=0, tiled=True)

    in_specs, out_specs = _specs(axis, batch_axes)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


def _fused_fits(n, m, k_local, n_out, itemsize) -> bool:
    m_local = m // n
    work = (m * k_local + k_local * n_out + 4 * m_local * n_out) * itemsize
    return work <= fused_vmem_budget()


def auto_gemm_rs_method(mesh, axis, a, b, dp: int = 1) -> GemmRSMethod:
    n = mesh.shape[axis]
    topo = detect_topology(mesh, axis)
    fits = _fused_fits(n, a.shape[0] // dp, a.shape[1] // n, b.shape[1], a.dtype.itemsize)
    if topo.link_kind == LinkKind.DCN:
        return GemmRSMethod.XLA_RING
    if fits and (topo.link_kind == LinkKind.ICI or not on_tpu()):
        return GemmRSMethod.PALLAS_FUSED
    return GemmRSMethod.XLA_RING


def gemm_rs(
    a,
    b,
    mesh,
    axis: str = "x",
    *,
    batch_axes: tuple = (),
    method: GemmRSMethod | None = None,
    out_dtype=None,
    collective_id: int = 6,
):
    """Fused (A @ B) → ReduceScatter for row-parallel TP.

    ``a``: (M, K) with rows sharded over ``batch_axes`` (DP) and cols
    P(axis) — each device holds a K/n column shard. ``b``: (K, N) sharded
    P(axis, None) — row-parallel weight. Returns (M, N) with rows sharded
    over ``(*batch_axes, axis)``: within each DP group device i owns
    fully-reduced row shard i (sequence-parallel layout).

    Host entry ≡ reference ``gemm_rs`` (gemm_reduce_scatter.py:547).
    """
    n = mesh.shape[axis]
    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    assert a.shape[0] % (dp * n) == 0 and a.shape[1] % n == 0 and b.shape[0] % n == 0
    assert a.shape[1] == b.shape[0], f"contract dim mismatch {a.shape} @ {b.shape}"
    if n == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
    if method is None:
        method = auto_gemm_rs_method(mesh, axis, a, b, dp=dp)
    if method == GemmRSMethod.PALLAS_FUSED:
        fn = _build_fused(
            mesh, axis, batch_axes, a.shape, b.shape, a.dtype, out_dtype,
            collective_id, config.chaos_delay,
        )
    elif method == GemmRSMethod.XLA_RING:
        fn = _build_xla_ring(mesh, axis, batch_axes, out_dtype)
    else:
        fn = _build_xla_naive(mesh, axis, batch_axes, out_dtype)
    return fn(a, b)
