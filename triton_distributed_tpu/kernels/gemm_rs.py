"""GEMM-ReduceScatter: row-parallel TP overlap of matmul with reduction.

Reference: python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py —
producer GEMM in rank-swizzled tile order signalling per-destination
barriers (:124-235), consumer ``reduce_scatter_2d_op`` on separate
streams (reduce_scatter.py:863), host entries ``gemm_rs_op``/``gemm_rs``
(:498-560).

TPU re-design: a reduce ring in which each step's contribution is
*computed into the ring* by the MXU while the previous partial is in
flight — the matmul for the next destination shard overlaps the RDMA of
the current accumulator, replacing the reference's GEMM-stream /
RS-stream pair with single-kernel software pipelining. Tile order is
rank-swizzled by construction: device ``me`` computes destination shards
``me+1, me+2, …, me`` so every shard's partial flows leftward and ends
fully reduced on its owner.

The fused engine is HBM-streaming: operands and the ring slabs live in
HBM (ANY memory space); the per-destination matmul and the fold-in add
are tiled ``emit_pipeline`` loops whose blocks are double-buffered
HBM→VMEM DMAs. There is no whole-working-set VMEM gate — the engine
engages at the north-star shapes (the whole point of the reference's
persistent producer GEMM, gemm_reduce_scatter.py:124-235).

Engines: ``PALLAS_FUSED`` (streaming ring, ICI), ``XLA_RING``
(ppermute+dot loop, DCN path), ``XLA_NAIVE`` (dot → psum_scatter
baseline, ≡ the torch reference impl in test_gemm_rs.py).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import fused_vmem_budget, interp_key
from triton_distributed_tpu.kernels.ag_gemm import (
    _divisor_block,
    _warn_once,
    mm_pipeline,
    pick_mm_blocks,
)
from triton_distributed_tpu.kernels.ring import RSWireRefs, reduce_ring
from triton_distributed_tpu.lang import wire as wirelib
from triton_distributed_tpu.runtime import (
    LinkKind,
    detect_topology,
    mesh_axes_size,
)


#: GEMM-RS tile targets, swept on a v5e at the Llama-7B down-projection
#: north-star shard (8192×3584 @ 3584×8192 bf16): (512, whole-K, 1024) →
#: 167 TFLOP/s vs 147 for the shared ag_gemm targets. The 4096 bk target
#: yields whole-K for K-shards ≤ 4096 and shrinks under the VMEM budget
#: elsewhere.
_RS_TILE_TARGETS = (512, 4096, 1024)


class GemmRSMethod(enum.Enum):
    PALLAS_FUSED = "pallas_fused"
    XLA_RING = "xla_ring"
    XLA_NAIVE = "xla_naive"


def ew_add_pipeline(m, n, itemsize):
    """Tiled elementwise-add pipeline over HBM refs: dst = a + b.
    Blocks stream through VMEM double-buffered; used to fold a received
    ring partial into the locally computed one. Under an active
    shmemlint recorder the fold is recorded as an AddEvent — the
    provenance edge the SL008 reduce-contract pass accumulates — and
    the value-level pipeline is skipped (evaluator pipelines only ever
    recorded access hulls)."""
    from triton_distributed_tpu.config import compiling_for_tpu

    bm = _divisor_block(m, 512, 8 * (4 // itemsize), compiling_for_tpu())
    bn = _divisor_block(n, 2048, 128, compiling_for_tpu())

    def inner(a_ref, b_ref, o_ref):
        o_ref[...] = a_ref[...] + b_ref[...]

    spec = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    pipe = pltpu.emit_pipeline(
        inner, grid=(m // bm, n // bn), in_specs=[spec, spec], out_specs=[spec]
    )

    def run(a_hbm, b_hbm, o_hbm):
        from triton_distributed_tpu.analysis import events

        rec = events.active_recorder()
        if rec is not None:
            rec.emit(events.AddEvent(
                a_region=a_hbm.region(), b_region=b_hbm.region(),
                dst_region=o_hbm.region(),
            ))
            return
        pipe(a_hbm, b_hbm, o_hbm)

    return run


def mm_q8_rs_pipeline(mb, nb, kb, bm, bk, bn, fmt, acc_ref, *, m_off=0):
    """s8×s8→s32 producer for the wire reduce ring: the partial runs on
    the MXU's native int8 path (int8 weights + activations) and is
    quantized for the wire STRAIGHT OFF THE ACCUMULATOR — the epilogue
    (mm_q8_pipeline's ``as·bs`` rescale shape) writes the f32-rescaled
    partial slab AND its wire copy (int8 payload + per-chunk scale row)
    in one pass, so the separate quant_pipeline read-back over HBM is
    gone. Requires ``nb == 1`` (the out tile spans every column, so a
    row block IS a scale chunk: ``fmt.chunk_rows == bm``)."""
    assert nb == 1 and fmt.chunk_rows == bm, (nb, fmt.chunk_rows, bm)
    qmax = fmt.qmax

    def inner(aq_ref, as_ref, bq_ref, bs_ref, o_ref, q_ref, s_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jax.lax.dot_general(
            aq_ref[...], bq_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

        @pl.when(pl.program_id(2) == kb - 1)
        def _():
            # rank-1 a_scale[chunk]·b_scale[n] rescale on the s32
            # accumulator (mm_q8_pipeline's epilogue shape) → the f32
            # partial tile; its wire quantization happens HERE, off the
            # same accumulator values, before the tile leaves VMEM
            t = acc_ref[...].astype(jnp.float32) * (
                as_ref[:, :1] * bs_ref[...]
            )
            o_ref[...] = t.astype(o_ref.dtype)
            row = jnp.max(jnp.abs(t), axis=1, keepdims=True)
            chunk = jnp.max(row, axis=0, keepdims=True)
            scale = jnp.maximum(chunk, 1e-12) / qmax
            s_ref[...] = jnp.broadcast_to(
                scale, (1, wirelib.SCALE_LANES)
            ).astype(jnp.float32)
            y = t / scale
            if fmt.quant == "int8":
                y = jnp.clip(jnp.round(y), -127, 127)
            q_ref[...] = y.astype(q_ref.dtype)

    pipe = pltpu.emit_pipeline(
        inner,
        grid=(mb, nb, kb),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (m_off + i, kk)),
            pl.BlockSpec(
                (1, wirelib.SCALE_LANES), lambda i, j, kk: (m_off + i, 0)
            ),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            pl.BlockSpec(
                (1, wirelib.SCALE_LANES), lambda i, j, kk: (i, 0)
            ),
        ],
    )

    def run(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_hbm, wq_hbm, ws_hbm):
        from triton_distributed_tpu.analysis import events

        rec = events.active_recorder()
        if rec is not None:
            # symbolic twin: the product is locally-owned data in the
            # work slab, immediately re-quantized into the wire rails —
            # the same Write+Quant provenance mm_pipeline+quant_pipeline
            # would leave, minus the value-level HBM read-back
            rec.emit(events.WriteEvent(region=dst_hbm.region()))
            rec.emit(events.QuantEvent(
                src_region=dst_hbm.region(), q_region=wq_hbm.region(),
                s_region=ws_hbm.region(), chunk_rows=fmt.chunk_rows,
            ))
            return
        pipe(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_hbm, wq_hbm, ws_hbm)

    return run


def mm_q8_partial_pipeline(mb, nb, kb, bm, bk, bn, acc_ref, *, m_off=0):
    """s8×s8→s32 producer WITHOUT the fused wire epilogue: the rescaled
    f32 partial lands in the destination slab only, and the ring
    harness's separate ``quant_pipeline`` read-back pass makes the wire
    copy afterwards (the ``GridSchedule.epilogue="readback"`` placement
    — one extra HBM round-trip per hop, but no ``nb == 1`` /
    chunk-geometry constraint on the out tiling)."""

    def inner(aq_ref, as_ref, bq_ref, bs_ref, o_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jax.lax.dot_general(
            aq_ref[...], bq_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

        @pl.when(pl.program_id(2) == kb - 1)
        def _():
            o_ref[...] = (
                acc_ref[...].astype(jnp.float32)
                * (as_ref[:, :1] * bs_ref[...])
            ).astype(o_ref.dtype)

    pipe = pltpu.emit_pipeline(
        inner,
        grid=(mb, nb, kb),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (m_off + i, kk)),
            pl.BlockSpec(
                (1, wirelib.SCALE_LANES), lambda i, j, kk: (m_off + i, 0)
            ),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        ],
    )

    def run(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_hbm):
        from triton_distributed_tpu.analysis import events

        rec = events.active_recorder()
        if rec is not None:
            # symbolic twin: a locally computed partial in the work slab
            # (the wire quantization is the harness's read-back pass)
            rec.emit(events.WriteEvent(region=dst_hbm.region()))
            return
        pipe(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_hbm)

    return run


def _fused_kernel(
    n, axis, mesh_axes, blocks, schedule,
    a_hbm, b_hbm, out_hbm, w0, w1, r0, r1, acc_ref, send_sem, recv_sem, ack_sem,
):
    """HBM-streaming compute-into-the-ring GEMM-RS.

    Step ``s`` (destination shard ``me+1+s``): the matmul pipeline for the
    *next* destination runs between a ring DMA's start and its recv wait,
    so each transfer hides under a full shard matmul. Double-buffered work
    and recv slabs with the ack-based flow control of
    kernels/reduce_scatter.py:ring_reduce_core (a sender may not rewrite a
    slot its receiver hasn't folded in — semaphore credits count arrivals,
    not consumption)."""
    m_local = out_hbm.shape[0]
    n_out = out_hbm.shape[1]
    k = a_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m_local // bm, n_out // bn, k // bk

    def partial_into(dst, dst_ref):
        # dst_ref = A[dst·m_local : (dst+1)·m_local, :] @ B   (streamed)
        mm_pipeline(mb, nb, kb, bm, bk, bn, acc_ref, m_off=dst * mb, out_m_off=0)(
            a_hbm, b_hbm, dst_ref
        )

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (r0, r1),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(m_local, n_out, out_hbm.dtype.itemsize),
        site="gemm_rs", schedule=schedule,
    )


def _fused_kernel_w(
    n, axis, mesh_axes, blocks, fmt, schedule,
    a_hbm, b_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    acc_ref, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`_fused_kernel`: each hop's freshly
    computed partial is quantized to the lang.wire layout before its
    RDMA, and the receive side dequant-accumulates in f32 (one rounding
    per hop — the RS-side contract that keeps reduction error bounded).
    The bf16 recv slabs of the raw engine are gone; the wire lands in
    the 1-byte rq slabs + rs scale planes."""
    m_local = out_hbm.shape[0]
    n_out = out_hbm.shape[1]
    k = a_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m_local // bm, n_out // bn, k // bk

    def partial_into(dst, dst_ref):
        mm_pipeline(mb, nb, kb, bm, bk, bn, acc_ref, m_off=dst * mb, out_m_off=0)(
            a_hbm, b_hbm, dst_ref
        )

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1), ws=(ws0, ws1), rq=(rq0, rq1), rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m_local, n_out, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m_local, n_out, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="gemm_rs", wire=wire, schedule=schedule,
    )


def _fused_kernel_mxw(
    n, axis, mesh_axes, blocks, fmt, schedule,
    aq_hbm, as_hbm, bq_hbm, bs_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    acc_ref, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """int8-MXU-producer twin of :func:`_fused_kernel_w` (carried-forward
    ROADMAP item): with int8 weights + activations the producer matmul
    runs the MXU's native s8×s8→s32 path, and the wire is quantized off
    an ACCUMULATOR at both places a hop's payload is born —
    ``RSWireRefs.quantize=None`` tells the ring harness the read-back
    quantize pass is gone:

    * the FIRST send (a pure local partial) quantizes straight off the
      producer's s32 accumulator (:func:`mm_q8_rs_pipeline`'s fused
      epilogue into wq/ws slot 0);
    * every later send must ship the FOLDED running sum, not the local
      partial — the fold itself re-quantizes off its f32 accumulator
      into the next send's rail pair
      (:func:`lang.wire.dequant_add_requant_pipeline`). Shipping the
      raw local partial here loses every upstream contribution — the
      delivery contract (SL008: one fold per rank) is what catches
      that, which is exactly why this family gates through shmemlint.
    """
    m_local = out_hbm.shape[0]
    n_out = out_hbm.shape[1]
    k = aq_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m_local // bm, n_out // bn, k // bk
    wq, ws = (wq0, wq1), (ws0, ws1)
    produced = [0]
    folded = [0]

    def partial_into(dst, dst_ref):
        i = produced[0]
        produced[0] += 1
        if i == 0:
            # the hop-0 payload: local partial, wire-quantized off the
            # producer accumulator into send slot 0
            mm_q8_rs_pipeline(
                mb, nb, kb, bm, bk, bn, fmt, acc_ref, m_off=dst * mb
            )(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_ref, wq[0], ws[0])
        else:
            # later partials only feed the fold; their wire copy is the
            # fold's requantize (writing a rail here would be dead work)
            mm_q8_partial_pipeline(
                mb, nb, kb, bm, bk, bn, acc_ref, m_off=dst * mb
            )(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_ref)

    deq_req = wirelib.dequant_add_requant_pipeline(m_local, n_out, fmt)
    deq = wirelib.dequant_add_pipeline(m_local, n_out, fmt)

    def dequant_add(a_hbm, q_hbm, s_hbm, dst_hbm):
        s = folded[0]
        folded[0] += 1
        if s < n - 2:
            # fold step s feeds send step s+1 (slot (s+1) % 2): requant
            # the accumulated sum into that slot's rail pair
            slot = (s + 1) % 2
            deq_req(a_hbm, q_hbm, s_hbm, dst_hbm, wq[slot], ws[slot])
        else:
            # final fold lands in out_hbm; nothing ships after it
            deq(a_hbm, q_hbm, s_hbm, dst_hbm)

    wire = RSWireRefs(
        fmt=fmt, wq=wq, ws=ws, rq=(rq0, rq1), rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=None,   # producer/fold-quantized: the rails are written
        dequant_add=dequant_add,
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="gemm_rs", wire=wire, schedule=schedule,
    )


def _fused_kernel_mxr(
    n, axis, mesh_axes, blocks, fmt, schedule,
    aq_hbm, as_hbm, bq_hbm, bs_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    acc_ref, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """The READBACK epilogue placement of the int8-MXU producer (the
    ``GridSchedule.epilogue="readback"`` alternative to
    :func:`_fused_kernel_mxw`): the s8×s8→s32 producer writes only the
    f32 partial, and the ring harness's ``quant_pipeline`` read-back
    pass makes each hop's wire copy — the pre-fusion pipeline shape,
    kept searchable so the grid schedule search prices the fused
    epilogue AGAINST it instead of assuming it."""
    m_local = out_hbm.shape[0]
    n_out = out_hbm.shape[1]
    k = aq_hbm.shape[1]
    bm, bk, bn = blocks
    mb, nb, kb = m_local // bm, n_out // bn, k // bk

    def partial_into(dst, dst_ref):
        mm_q8_partial_pipeline(
            mb, nb, kb, bm, bk, bn, acc_ref, m_off=dst * mb
        )(aq_hbm, as_hbm, bq_hbm, bs_hbm, dst_ref)

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1), ws=(ws0, ws1), rq=(rq0, rq1), rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m_local, n_out, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m_local, n_out, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="gemm_rs", wire=wire, schedule=schedule,
    )


def _specs(axis, batch_axes, dcn_axis=None):
    """(in_specs, out_specs) for GEMM-RS under shard_map over the full mesh.

    Activation rows may additionally be sharded over ``batch_axes`` (DP);
    the reduce-scatter then runs over ``axis`` within each DP group and the
    output rows end up sharded over (*batch_axes, axis) — the Megatron
    sequence-parallel layout, the exact inverse of ag_gemm's.
    Hierarchical (``dcn_axis``): the TP factor spans (axis, dcn_axis)
    axis-MAJOR (matching ag_gemm's hierarchical layout): K cols and
    output rows sharded P((axis, dcn_axis))."""
    ba = tuple(batch_axes)
    # a 1-tuple of axis names is equivalent to the bare name for both
    # PartitionSpec and lax collectives, so no flat/hier branching
    tp_axes = (axis,) if dcn_axis is None else (axis, dcn_axis)
    a_spec = P(ba if ba else None, tp_axes)
    b_spec = P(tp_axes, None)
    out_spec = P(ba + tp_axes, None)
    return (a_spec, b_spec), out_spec


@functools.lru_cache(maxsize=256)
def _build_fused(
    mesh, axis, batch_axes, a_shape, b_shape, dtype, out_dtype, collective_id,
    chaos, dcn_axis=None, wire=None, schedule=None,
):
    """Fused engine. ``dcn_axis`` set = hierarchical (≡ the reference's
    inter-node GEMM-RS, reduce_scatter.py:524-545): the fused ring
    reduces intra-slice over ``axis`` (each slice sums its own K
    stripe), then a ``lax.psum_scatter`` leg crosses DCN — adding the
    other slices' stripes and scattering rows axis-major.

    Round 5 (VERDICT r4 #5): the DCN leg is CHUNKED for overlap — the
    fused ring runs once per N-column chunk, and since chunk c's
    ``psum_scatter`` depends only on chunk c's ring while chunk c+1's
    ring has no dependency on it at all, XLA's async collective
    machinery flies each chunk's DCN transfer under the NEXT chunk's
    Mosaic call (the mirror of ag_gemm's chunked rail; ≡ the reference
    overlapping the inter-node p2p stage of RS on its own stream,
    reduce_scatter.py:524-545). Exposed DCN time drops from the whole
    leg to ~1/C of it. Falls back to the serial leg when the column
    chunk admits no divisor blocking."""
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    dp = mesh_axes_size(mesh, batch_axes)
    m_local = a_shape[0] // (dp * n)
    k_local = a_shape[1] // (n * nd)
    n_out = b_shape[1]
    blocks = pick_mm_blocks(
        m_local, k_local, n_out, dtype.itemsize, targets=_RS_TILE_TARGETS
    )
    if blocks is None:
        raise ValueError(
            f"gemm_rs PALLAS_FUSED: no divisor blocking for shard "
            f"({m_local}, {k_local}) @ ({k_local}, {n_out}); use XLA_RING"
        )

    if n == 1:
        collective_id = None  # degenerate path uses no barrier semaphore
    fmt = None
    rail_fmt = None
    # the grid schedule (tune.schedule.GridSchedule) governs the MXU
    # producer's epilogue placement and demotion policy; its rail knob
    # maps onto the inner reduce ring's scale-rail assignment. A plain
    # RingSchedule (or None) leaves today's behavior byte-identical.
    # Duck-typed on the classes' `kind` tag, not isinstance — the tune
    # module may be loaded twice (its CLI runs it as __main__), and two
    # copies of GridSchedule must still dispatch here.
    from triton_distributed_tpu.tune.schedule import RingSchedule

    epilogue, demote = "accumulator", "auto"
    if getattr(schedule, "kind", "ring") == "grid":
        epilogue, demote = schedule.epilogue, schedule.demote
        schedule = (
            RingSchedule(scale_rail="payload")
            if schedule.rail == "shared" else None
        )
    mx = wire == "int8-mxu" and dcn_axis is None
    if mx and (n_out // blocks[2] != 1 or m_local % blocks[0]):
        # the accumulator-epilogue quantizer needs the out tile to span
        # every column (a row block IS a scale chunk); otherwise run the
        # ordinary int8 wire with its separate quantize pass
        if demote == "strict":
            raise ValueError(
                f"gemm_rs int8-mxu: shard ({m_local}, {k_local}) @ "
                f"({k_local}, {n_out}) blocks to {blocks} — the "
                "accumulator epilogue needs a full-width out tile and "
                "chunk-aligned rows, and the schedule pins "
                "demote='strict'"
            )
        mx = False
        wire = "int8"
    if mx:
        wirelib.require_mxu("gemm_rs")
        fmt = wirelib.WireFormat(quant="int8", chunk_rows=blocks[0])
    elif wire is not None and dcn_axis is not None:
        # hierarchical: the wire rides the DCN LEG (the quantized
        # ppermute reduce ring replacing psum_scatter — XLA-side
        # quant/dequant, any backend); intra-slice rings stay raw.
        # The rail reduces (m_local, ·) partials in nd stripes of
        # m_local/nd rows each.
        if m_local % nd == 0:
            rail_fmt = wirelib.make_wire_format(
                wirelib.wire_payload(wire), m_local // nd, strict=False
            )
    elif wire is not None:
        from triton_distributed_tpu.config import compiling_for_tpu

        wirelib.require_inkernel(
            wirelib.wire_payload(wire), "gemm_rs"
        )
        fmt = wirelib.make_wire_format(
            wirelib.wire_payload(wire), m_local, strict=compiling_for_tpu()
        )
        if fmt is None:
            raise ValueError(
                f"gemm_rs wire={wire!r}: slab of {m_local} rows admits no "
                "legal scale chunking; use the bf16 wire"
            )

    def mk_call(n_cols, blk, cid):
        slab = jax.ShapeDtypeStruct((m_local, n_cols), out_dtype)
        if mx:
            qslab = jax.ShapeDtypeStruct((m_local, n_cols), fmt.wire_dtype)
            sslab = jax.ShapeDtypeStruct(
                (fmt.chunks(m_local), wirelib.SCALE_LANES), jnp.float32
            )
            mx_kernel = (
                _fused_kernel_mxr if epilogue == "readback"
                else _fused_kernel_mxw
            )
            return lang.shmem_call(
                functools.partial(
                    mx_kernel, n, axis, mesh.axis_names, blk, fmt,
                    schedule,
                ),
                out_shape=[slab, slab, slab,
                           qslab, qslab, sslab, sslab,
                           qslab, qslab, sslab, sslab],
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
                out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 11,
                scratch_shapes=[
                    pltpu.VMEM((blk[0], blk[2]), jnp.int32),  # s32 acc
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.REGULAR,
                    pltpu.SemaphoreType.DMA((2,)),   # scale rail
                    pltpu.SemaphoreType.DMA((2,)),
                ],
                collective_id=cid,
                vmem_limit_bytes=fused_vmem_budget(),
                name="gemm_rs_fused_int8mxw",
            )
        if fmt is not None:
            qslab = jax.ShapeDtypeStruct((m_local, n_cols), fmt.wire_dtype)
            sslab = jax.ShapeDtypeStruct(
                (fmt.chunks(m_local), wirelib.SCALE_LANES), jnp.float32
            )
            return lang.shmem_call(
                functools.partial(
                    _fused_kernel_w, n, axis, mesh.axis_names, blk, fmt,
                    schedule,
                ),
                # out + bf16 work pair + quantized work/scale pairs +
                # quantized recv/scale pairs (HBM workspaces as outputs)
                out_shape=[slab, slab, slab,
                           qslab, qslab, sslab, sslab,
                           qslab, qslab, sslab, sslab],
                in_specs=[
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY),
                ],
                out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 11,
                scratch_shapes=[
                    pltpu.VMEM((blk[0], blk[2]), jnp.float32),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.REGULAR,
                    pltpu.SemaphoreType.DMA((2,)),   # scale rail
                    pltpu.SemaphoreType.DMA((2,)),
                ],
                collective_id=cid,
                vmem_limit_bytes=fused_vmem_budget(),
                name=f"gemm_rs_fused_{wirelib.wire_payload(wire)}w",
            )
        return lang.shmem_call(
            functools.partial(
                _fused_kernel, n, axis, mesh.axis_names, blk, schedule
            ),
            # work/recv ring slabs are HBM workspaces (Mosaic supports
            # scratch only in vmem/smem/semaphore space, so they ride as
            # extra outputs — the symmetric-workspace pattern of the
            # reference's ctx).
            out_shape=[slab, slab, slab, slab, slab],
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
            scratch_shapes=[
                pltpu.VMEM((blk[0], blk[2]), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            collective_id=cid,
            vmem_limit_bytes=fused_vmem_budget(),
            name="gemm_rs_fused",
        )

    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)

    n_chunks = 1
    chunk_blocks = None
    if dcn_axis is not None and nd > 1:
        for c in (4, 2):
            if n_out % c:
                continue
            chunk_blocks = pick_mm_blocks(
                m_local, k_local, n_out // c, dtype.itemsize,
                targets=_RS_TILE_TARGETS,
            )
            if chunk_blocks is not None:
                n_chunks = c
                break

    if dcn_axis is None:
        call = lang.maybe_instrument(
            mk_call(n_out, blocks, collective_id),
            axis=axis, site="gemm_rs", collective_id=collective_id, n=n,
        )

        if mx:
            def body(a, b):
                # quantize both operands in XLA; the kernel's MXU path
                # consumes s8×s8→s32 and quantizes the wire partial
                # straight off the accumulator epilogue
                aq, asc = wirelib.quantize_slab(a, fmt)
                bq, bsc = wirelib.quantize_cols(b)
                return call(aq, asc, bq, bsc)[0]
        else:
            def body(a, b):
                return call(a, b)[0]
    elif n_chunks == 1:
        call = mk_call(n_out, blocks, collective_id)

        def body(a, b):
            # serial DCN leg fallback (no admissible column chunking) —
            # quantized rail when the wire is on
            part = call(a, b)[0]
            if rail_fmt is not None:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_reduce_scatter,
                )

                return dcn_wire_reduce_scatter(
                    part, dcn_axis, nd, rail_fmt
                )
            return jax.lax.psum_scatter(
                part, dcn_axis, scatter_dimension=0, tiled=True
            )
    else:
        nc = n_out // n_chunks
        # distinct collective_ids per chunk ring: strict per-chunk
        # rendezvous (a skewed neighbor's chunk-c+1 signal must not
        # satisfy a chunk-c wait); the offset range is reserved in the
        # registry's rail ledger, so disjointness from every other
        # chunked family is checked, not maintained by comment
        from triton_distributed_tpu.kernels.registry import rail_collective_id

        chunk_calls = [
            mk_call(
                nc, chunk_blocks,
                rail_collective_id("gemm_rs.dcn_chunks", collective_id, s),
            )
            for s in range(n_chunks)
        ]

        def dcn_rs(part):
            # manual reduce-scatter as a ppermute ring (the
            # gemm_rs_device stripe pattern over dcn_axis): XLA
            # async-converts collective-permute — a sync psum_scatter
            # would serialize the whole leg (verified in the compiled
            # schedule), while these hops get start/done windows the
            # next chunk's Mosaic call slots into. With the rail wire
            # on, each hop moves the per-hop-quantized partial + scale
            # plane (~2× fewer DCN bytes, f32 dequant-accumulate).
            if rail_fmt is not None:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_reduce_scatter,
                )

                return dcn_wire_reduce_scatter(
                    part, dcn_axis, nd, rail_fmt
                )
            me = jax.lax.axis_index(dcn_axis)
            m_s = part.shape[0] // nd
            perm = [(i, (i - 1) % nd) for i in range(nd)]

            def stripe(i):
                return jax.lax.dynamic_slice(
                    part, (i * m_s, 0), (m_s, part.shape[1])
                )

            acc = stripe(jax.lax.rem(me + 1, nd))
            for s in range(nd - 1):
                acc = jax.lax.ppermute(acc, dcn_axis, perm=perm)
                acc = acc + stripe(jax.lax.rem(me + 2 + s, nd))
            return acc

        def body(a, b):
            scattered = []
            for s in range(n_chunks):
                part = chunk_calls[s](a, b[:, s * nc:(s + 1) * nc])[0]
                # this chunk's DCN ring has no consumer until the final
                # concat — its hops fly under chunk s+1's Mosaic ring
                scattered.append(dcn_rs(part))
            return jnp.concatenate(scattered, axis=1)

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


def gemm_rs_device(a_loc, b_loc, axis, *, out_dtype=None, wire=None):
    """Per-device XLA-ring GEMM-RS body — usable inside any shard_map.

    The accumulator flows leftward around the ring while the next
    destination's partial matmul runs, overlapped by XLA async permute.

    ``wire`` ('fp8'/'int8'): each hop's partial sum is quantized to the
    lang.wire layout before its permute and dequant-accumulated in f32
    on arrival — the same per-hop requantization semantics (and byte
    counts) as the fused wire ring."""
    n = jax.lax.axis_size(axis)
    out_dtype = out_dtype or a_loc.dtype
    m_local = a_loc.shape[0] // n
    me = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % n) for i in range(n)]
    fmt = None
    if wire is not None:
        from triton_distributed_tpu.config import compiling_for_tpu

        fmt = wirelib.make_wire_format(
            wire, m_local, strict=compiling_for_tpu()
        )

    def partial(dst):
        rows = jax.lax.dynamic_slice(
            a_loc, (dst * m_local, 0), (m_local, a_loc.shape[1])
        )
        return jnp.dot(rows, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )

    if fmt is None:
        def step(s, acc):
            acc = jax.lax.ppermute(acc, axis, perm=perm)
            return acc + partial(jax.lax.rem(me + 2 + s, n))

        acc = partial(jax.lax.rem(me + 1, n))
        return jax.lax.fori_loop(0, n - 1, step, acc)

    def step_w(s, acc):
        q, sc = wirelib.quantize_slab(acc, fmt)
        q = jax.lax.ppermute(q, axis, perm=perm)
        sc = jax.lax.ppermute(sc, axis, perm=perm)
        arrived = wirelib.dequantize_slab(q, sc, fmt, jnp.float32)
        return (
            arrived + partial(jax.lax.rem(me + 2 + s, n)).astype(jnp.float32)
        ).astype(out_dtype)

    acc = partial(jax.lax.rem(me + 1, n))
    return jax.lax.fori_loop(0, n - 1, step_w, acc)


@functools.lru_cache(maxsize=256)
def _build_xla_ring(mesh, axis, batch_axes, out_dtype, dcn_axis=None,
                    wire=None):
    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)

    def body(a_loc, b_loc):
        part = gemm_rs_device(
            a_loc, b_loc, axis, out_dtype=out_dtype,
            wire=wirelib.wire_payload(wire),
        )
        if dcn_axis is not None:
            nd = jax.lax.axis_size(dcn_axis)
            w_rail = wirelib.wire_payload(wire)
            rail_fmt = (
                wirelib.make_wire_format(
                    w_rail, part.shape[0] // nd, strict=False
                )
                if w_rail is not None and part.shape[0] % nd == 0
                else None
            )
            if rail_fmt is not None:
                from triton_distributed_tpu.runtime.multislice import (
                    dcn_wire_reduce_scatter,
                )

                part = dcn_wire_reduce_scatter(
                    part, dcn_axis, nd, rail_fmt
                )
            else:
                part = jax.lax.psum_scatter(
                    part, dcn_axis, scatter_dimension=0, tiled=True
                )
        return part

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_xla_naive(mesh, axis, batch_axes, out_dtype, dcn_axis=None):
    tp_axes = (axis,) if dcn_axis is None else (axis, dcn_axis)

    def body(a_loc, b_loc):
        full = jnp.dot(a_loc, b_loc, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
        return jax.lax.psum_scatter(full, tp_axes, scatter_dimension=0, tiled=True)

    in_specs, out_specs = _specs(axis, batch_axes, dcn_axis)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _engine_tuner(mesh, axis, batch_axes, out_dtype, collective_id,
                  dcn_axis=None, wire=None):
    """Measured engine selection for ``method=None`` (see
    ag_gemm._engine_tuner for the contract incl. why out_dtype,
    collective_id and wire belong in the name/key)."""
    from triton_distributed_tpu.tune.autotuner import method_tuner

    def run(a, b, *, method):
        return gemm_rs(
            a, b, mesh, axis, batch_axes=batch_axes,
            method=GemmRSMethod(method), out_dtype=out_dtype,
            collective_id=collective_id, dcn_axis=dcn_axis, wire_dtype=wire,
        )

    return method_tuner(
        f"gemm_rs[{dict(mesh.shape)}|{axis}|{batch_axes}|{out_dtype}|"
        f"{collective_id}|{dcn_axis}|w{wire}]",
        run, GemmRSMethod,
    )


@functools.lru_cache(maxsize=64)
def _wire_tuner(mesh, axis, batch_axes, out_dtype, collective_id,
                dcn_axis=None):
    """Measured wire-dtype selection for ``wire_dtype='auto'`` (see
    ag_gemm._wire_tuner)."""
    from triton_distributed_tpu.tune.autotuner import wire_tuner

    def run(a, b, *, wire_dtype):
        dp = mesh_axes_size(mesh, tuple(batch_axes))
        method = auto_gemm_rs_method(
            mesh, axis, a, b, dp=dp, dcn_axis=dcn_axis
        )
        return gemm_rs(
            a, b, mesh, axis, batch_axes=batch_axes, method=method,
            out_dtype=out_dtype, collective_id=collective_id,
            dcn_axis=dcn_axis, wire_dtype=wire_dtype,
        )

    return wire_tuner(
        f"gemm_rs_wire[{dict(mesh.shape)}|{axis}|{batch_axes}|{out_dtype}|"
        f"{collective_id}|{dcn_axis}]",
        run,
    )


def auto_gemm_rs_method(mesh, axis, a, b, dp: int = 1,
                        dcn_axis: str | None = None) -> GemmRSMethod:
    """Topology + shape blockability decide the engine; fallbacks are
    logged (nobody should benchmark XLA believing it is the fused kernel).
    A cross-slice TP factor declared as ``dcn_axis`` keeps the fused
    engine intra-slice; only ``axis`` itself crossing DCN forces XLA."""
    from triton_distributed_tpu.config import pallas_collectives_available

    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    if not pallas_collectives_available():
        _warn_once(
            ("gemm_rs", "nosim"),
            "gemm_rs: Pallas collectives unavailable off-TPU (jax lacks "
            "the TPU-simulation interpreter); using XLA_RING engine",
        )
        return GemmRSMethod.XLA_RING
    topo = detect_topology(mesh, axis)
    if topo.link_kind == LinkKind.DCN:
        _warn_once(
            ("gemm_rs", "dcn", axis),
            f"gemm_rs: axis {axis!r} crosses DCN; using XLA_RING engine "
            "(pass the cross-slice factor as dcn_axis= to keep the fused "
            "engine intra-slice)",
        )
        return GemmRSMethod.XLA_RING
    m_local = a.shape[0] // (dp * n)
    blocks = pick_mm_blocks(
        m_local, a.shape[1] // (n * nd), b.shape[1], a.dtype.itemsize,
        targets=_RS_TILE_TARGETS,
    )
    if blocks is None:
        _warn_once(
            ("gemm_rs", "blocks", a.shape, b.shape),
            f"gemm_rs: shard ({m_local}, {a.shape[1] // (n * nd)}) @ "
            f"({a.shape[1] // (n * nd)}, {b.shape[1]}) admits no divisor "
            "blocking; falling back to XLA_RING",
        )
        return GemmRSMethod.XLA_RING
    return GemmRSMethod.PALLAS_FUSED


def resolve_gemm_rs_wire(
    mesh, axis, a, b, *, batch_axes=(), method=None, wire_dtype=None,
    out_dtype=None, dcn_axis: str | None = None, dp: int | None = None,
) -> str | None:
    """The wire format :func:`gemm_rs` will ACTUALLY ship (mirror of
    ag_gemm.resolve_ag_gemm_wire): None unless a ring engine runs and
    the OUTPUT slab — what the reduce ring moves — admits the lang.wire
    layout; 'auto' consults the measured wire tuner, else the perf
    model's comm-bound test at the per-step shapes."""
    from triton_distributed_tpu.config import compiling_for_tpu

    # a reduce ring accumulates — 'int8-mxu' has no MXU consumer here
    # and resolves to its int8 payload wire
    w = wirelib.wire_payload(wirelib.normalize_wire(wire_dtype))
    if w is None:
        return None
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    if dp is None:
        dp = mesh_axes_size(mesh, tuple(batch_axes))
    if n * nd == 1:
        return None
    if method == GemmRSMethod.XLA_NAIVE:
        return None  # psum_scatter — no ring to compress
    if dcn_axis is not None:
        # the DCN rail wire: the quantized ppermute reduce ring replaces
        # psum_scatter on the leg (XLA-side — any backend); intra-slice
        # Pallas rings stay raw
        m_s = a.shape[0] // (dp * n * nd * nd)
        n_out = b.shape[1]
        if a.shape[0] % (dp * n * nd * nd) or not wirelib.wire_blockable(
            max(m_s, 1), n_out, "fp8", False
        ):
            if w == "auto":
                return None
            raise ValueError(
                f"gemm_rs wire_dtype={w!r}: DCN rail stripe admits no "
                "legal wire chunking (a pinned wire format is a "
                "contract); use wire_dtype='auto' or the bf16 wire"
            )
        if w == "auto":
            from triton_distributed_tpu.runtime.topology import (
                auto_allgather_wire,
            )

            out_itemsize = jnp.dtype(out_dtype or a.dtype).itemsize
            return auto_allgather_wire(m_s * n_out * out_itemsize)
        return w
    m_local = a.shape[0] // (dp * n)
    k_local = a.shape[1] // n
    n_out = b.shape[1]
    out_itemsize = jnp.dtype(out_dtype or a.dtype).itemsize
    strict = compiling_for_tpu()
    inkernel = method == GemmRSMethod.PALLAS_FUSED
    if w == "auto":
        if not wirelib.wire_blockable(m_local, n_out, "fp8", strict):
            return None
        if inkernel and not wirelib.inkernel_wire_ok("fp8"):
            return None  # no silent fp8→int8 numerics switch
        from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

        tuned = tuned_method_or_none(
            lambda: _wire_tuner(
                mesh, axis, tuple(batch_axes), jnp.dtype(a.dtype), 6,
                dcn_axis,
            ),
            a, b, key="wire_dtype",
        )
        if tuned is not None:
            return wirelib.normalize_wire(tuned)
        from triton_distributed_tpu.tune.perf_model import auto_wire_dtype

        return wirelib.normalize_wire(auto_wire_dtype(
            m_local, k_local, n_out, out_itemsize,
            slab_bytes=m_local * n_out * out_itemsize,
        ))
    if inkernel:
        wirelib.require_inkernel(w, "gemm_rs")
    if not wirelib.wire_blockable(m_local, n_out, w, strict):
        raise ValueError(
            f"gemm_rs wire_dtype={w!r}: slab ({m_local}, {n_out}) admits "
            "no legal wire chunking/blocking (a pinned wire format is a "
            "contract); use wire_dtype='auto' or the bf16 wire"
        )
    return w


def resolve_gemm_rs_method(
    a_mesh, axis, a, b, *, batch_axes=(), method=None, out_dtype=None,
    collective_id: int = 6, dcn_axis: str | None = None, wire_dtype=None,
) -> GemmRSMethod:
    """The engine :func:`gemm_rs` will ACTUALLY run for these arguments
    (mirror of ag_gemm.resolve_ag_gemm_method): explicit ``method``,
    else the tuned winner, else the heuristic — with the safety recheck
    demoting a fused winner that is not buildable in this environment."""
    if method is not None:
        return method
    from triton_distributed_tpu.tune.autotuner import tuned_method_or_none

    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(a_mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    m = tuned_method_or_none(
        lambda: _engine_tuner(
            a_mesh, axis, batch_axes, jnp.dtype(out_dtype), collective_id,
            dcn_axis, wirelib.normalize_wire(wire_dtype),
        ),
        a, b,
    )
    auto = functools.partial(
        auto_gemm_rs_method, a_mesh, axis, a, b, dp=dp, dcn_axis=dcn_axis
    )
    method = GemmRSMethod(m) if m else auto()
    if method == GemmRSMethod.PALLAS_FUSED and auto() != method:
        # persisted winner may not be buildable in this environment
        method = auto()
    return method


def gemm_rs(
    a,
    b,
    mesh,
    axis: str = "x",
    *,
    batch_axes: tuple = (),
    method: GemmRSMethod | None = None,
    out_dtype=None,
    collective_id: int = 6,
    dcn_axis: str | None = None,
    wire_dtype=None,
    schedule=None,
):
    """Fused (A @ B) → ReduceScatter for row-parallel TP.

    ``wire_dtype``: what the reduce ring ships (docs/PERF.md "Quantized
    wire"). None/'bf16' — the raw partials (default, today's numerics);
    'fp8'/'int8' — each hop's partial quantized to a 1-byte payload +
    per-chunk f32 scales (lang.wire), dequant-accumulated in f32 on
    receive so reduction error is one bounded rounding per hop;
    'int8-mxu' — additionally run the producer GEMM itself on s8×s8→s32
    and quantize each hop's wire partial straight off the accumulator
    epilogue (no separate read-back quantize pass); 'auto' — the
    measured wire tuner, else the perf model picks the compressed wire
    exactly on comm-bound shapes. Inference-grade transport.

    ``schedule``: an explicit :class:`tune.schedule.RingSchedule` for
    the fused reduce ring (scale-rail assignment, buffer depth). None
    resolves a persisted schedule-search winner for this
    (shape, mesh, wire) key, falling back to the canonical default —
    byte-identical to the pre-schedule kernel.

    ``a``: (M, K) with rows sharded over ``batch_axes`` (DP) and cols
    P(axis) — each device holds a K/n column shard. ``b``: (K, N) sharded
    P(axis, None) — row-parallel weight. Returns (M, N) with rows sharded
    over ``(*batch_axes, axis)``: within each DP group device i owns
    fully-reduced row shard i (sequence-parallel layout).

    ``dcn_axis``: hierarchical TP spanning slices (≡ the reference's
    inter-node GEMM-RS, reduce_scatter.py:524-545): K cols and output
    rows sharded P((axis, dcn_axis)) axis-major; the fused Pallas ring
    reduces intra-slice, a psum_scatter leg crosses DCN.

    Host entry ≡ reference ``gemm_rs`` (gemm_reduce_scatter.py:547).
    """
    n = mesh.shape[axis]
    nd = mesh.shape[dcn_axis] if dcn_axis else 1
    batch_axes = tuple(batch_axes)
    dp = mesh_axes_size(mesh, batch_axes)
    out_dtype = out_dtype or a.dtype
    assert (
        a.shape[0] % (dp * n * nd) == 0
        and a.shape[1] % (n * nd) == 0
        and b.shape[0] % (n * nd) == 0
    )
    assert a.shape[1] == b.shape[0], f"contract dim mismatch {a.shape} @ {b.shape}"
    if n * nd == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
    method = resolve_gemm_rs_method(
        mesh, axis, a, b, batch_axes=batch_axes, method=method,
        out_dtype=out_dtype, collective_id=collective_id, dcn_axis=dcn_axis,
        wire_dtype=wire_dtype,
    )
    wire = resolve_gemm_rs_wire(
        mesh, axis, a, b, batch_axes=batch_axes, method=method,
        wire_dtype=wire_dtype, out_dtype=out_dtype, dcn_axis=dcn_axis, dp=dp,
    )
    if method == GemmRSMethod.PALLAS_FUSED:
        from triton_distributed_tpu.tune.schedule import resolve_schedule

        if (wirelib.normalize_wire(wire_dtype) == "int8-mxu"
                and wire == "int8" and dcn_axis is None
                and wirelib.inkernel_s8_dot_ok()):
            # the caller asked for the MXU consumer; resolve_gemm_rs_wire
            # reports the payload ('int8') since that is what the ring
            # ships — re-upgrade for the builder
            wire = "int8-mxu"
        # the MXU-producer wire resolves the GRID family (epilogue
        # placement / demotion policy, tune.schedule.GridSchedule); the
        # plain wires resolve the ring family as before
        fam = (
            "gemm_rs.mx_epilogue" if wire == "int8-mxu"
            else "gemm_rs.fused"
        )
        sched = resolve_schedule(fam, a.shape, (n * nd,), wire, schedule)
        fn = _build_fused(
            mesh, axis, batch_axes, a.shape, b.shape, a.dtype, out_dtype,
            collective_id, interp_key(), dcn_axis, wire, sched,
        )
    elif method == GemmRSMethod.XLA_RING:
        fn = _build_xla_ring(
            mesh, axis, batch_axes, out_dtype, dcn_axis, wire
        )
    else:
        fn = _build_xla_naive(mesh, axis, batch_axes, out_dtype, dcn_axis)
    return fn(a, b)
