"""Lint-family kernels for the training collectives (CP + grad ring).

The training path's collectives run as XLA programs off-TPU
(``kernels.ring_attention``'s ppermute/a2a bodies, ``train.grad_wire``'s
quantized rings) — but the wire/lint/schedule investment only pays if
those protocols are ANALYZABLE like every serving family. This module
is the Pallas twin of each training collective, built through
``lang.shmem_call`` so shmemlint and the Mosaic pre-flight see the real
launch (the ``kv_ship`` precedent: lint/preflight evidence and the
on-TPU fast path; production dev-box steps ride the XLA bodies):

* ``cp.ring_attention`` (collective id 15) — the KV-rotation ring:
  each hop forwards the current KV block to the ring neighbor while
  the attention partial consumes it. Runs on the shared
  :func:`~triton_distributed_tpu.kernels.ring.ag_forward_ring`
  harness, so ``RingSchedule`` traversal freedoms (direction) execute
  and the mutated ``skip_last`` candidate drops a block on the floor —
  visible ONLY to the gather delivery contract (SL008): one attention
  step silently never sees one sequence block.
* ``cp.ulysses`` (collective id 16) — the head-scatter all-to-all
  (dense, equal splits), the Ulysses re-shard's transport.
* ``grad_ring.stream_int8w`` (collective id 17) — the gradient ring:
  HBM-streaming reduce ring on the int8 wire (per-hop quant pipelines
  + scale rail, f32 dequant-accumulate), the Pallas shape of
  ``train.grad_wire``'s EF reduce-scatter. Schedule depth 2/3 executes;
  the mutated ``scale_rail="payload"`` candidate ships scales on the
  payload's semaphore — the SL009 torn-scale hazard.
* ``cp_decode.lse_combine`` (collective id 18) — the long-context
  decode merge: each cp rank's paged-attention partial rides the ring
  as exp-weighted numerator rows (``w_r·out_r``) plus an additive
  denominator row (``Σ w_r`` under the pre-agreed running max), so the
  cross-rank softmax merge of ``flash_decode.combine_partials`` is
  EXACTLY an add-reduce over ranks and the hop protocol is
  :func:`~triton_distributed_tpu.kernels.ring.reduce_ring` on the raw
  f32 wire (no quantization — the denominator row must fold exactly or
  the normalize at the owner rank drifts). The XLA body serving
  actually runs is ``flash_decode.cp_lse_combine_xla``; this twin puts
  the reduce PROTOCOL under lint with a fold-class delivery contract.

The collective ids are shared with the XLA bodies' heartbeat
instrumentation (``ring_attention.RING_ATTENTION_COLLECTIVE_ID`` etc.)
so a watchdog trip report and the lint evidence name the same launch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.lang import wire as wirelib

#: lint geometry: KV blocks of 8 rows × 128 lanes (ring + a2a), wide
#: 2048-lane grad stripes (the streaming wire's scale planes only
#: compress when the stripe payload dwarfs them — same reasoning as
#: reduce_scatter.stream_int8w's lint columns).
CP_RING_GEOM = dict(rows=8, cols=128, grad_cols=2048)

CP_RING_COLLECTIVE_ID = 15
CP_ULYSSES_COLLECTIVE_ID = 16
GRAD_RING_COLLECTIVE_ID = 17
CP_DECODE_COMBINE_COLLECTIVE_ID = 18


# ------------------------------------------------ cp.ring_attention (15)

def _kv_rotate_kernel(n, axis, mesh_axes, schedule,
                      kv_ref, ag_ref, send_sem, recv_sem):
    """KV-rotation ring: forward the (rows, cols) KV block around the
    ring while each step's arrival is consumed by the attention partial.
    The local block is consumed at step 0 straight from the input and
    never enters the workspace (``own_absent_ok`` in the contract) —
    exactly the XLA body's peeled step 0."""
    from triton_distributed_tpu.kernels.ring import ag_forward_ring

    rows = kv_ref.shape[0]

    def consume(s, src, a_hbm, row_off):
        # the attention partial: pure local compute over the arrived
        # block — no provenance the delivery contract needs to see
        del s, src, a_hbm, row_off

    ag_forward_ring(
        n, axis, mesh_axes, kv_ref, ag_ref, rows, send_sem, recv_sem,
        consume, site="cp_ring", schedule=schedule,
    )


@functools.lru_cache(maxsize=64)
def _build_kv_rotate(mesh, axis, rows, cols, collective_id, token=(),
                     schedule=None):
    del token
    n = mesh.shape[axis]
    return lang.shmem_call(
        functools.partial(
            _kv_rotate_kernel, n, axis, mesh.axis_names, schedule
        ),
        # the rotated-KV workspace rides as an ANY output (no HBM scratch)
        out_shape=[jax.ShapeDtypeStruct((n * rows, cols), jnp.float32)],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=collective_id,
        name="cp_ring_kv_rotate",
    )


def build_kv_rotate_lint(mesh, n, token=(), schedule=None):
    """Registry/pre-flight entry for ``cp.ring_attention``."""
    del n
    g = CP_RING_GEOM
    return _build_kv_rotate(
        mesh, "x", g["rows"], g["cols"], CP_RING_COLLECTIVE_ID,
        token, schedule,
    )


# ----------------------------------------------------- cp.ulysses (16)

def _ulysses_a2a_kernel(n, axis, mesh_axes, x_ref, out_ref,
                        send_sem, recv_sem):
    """Head-scatter a2a: slice j of the local (n·rows, cols) slab goes
    to peer j's slot ``me`` — the dense equal-split transport under the
    Ulysses seq→heads re-shard (the XLA body's lax.all_to_all)."""
    from triton_distributed_tpu.utils.testing import chaos_delay

    me = lang.my_pe(axis)
    m = x_ref.shape[0] // n

    out_ref[pl.ds(me * m, m)] = x_ref[pl.ds(me * m, m)]
    lang.barrier_all(axis, mesh_axes)

    handles = []
    for i in range(n - 1):
        pi = jax.lax.rem(me + 1 + i, n)
        peer = lang.pe_flat(axis, pi, mesh_axes)
        chaos_delay(site="cp_ring", step=i, me=me, n=n)
        handles.append(
            lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],
                x_ref.at[pl.ds(pi * m, m)],
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            )
        )
    lang.quiet(*handles)
    for h in handles:
        h.wait_recv()


@functools.lru_cache(maxsize=64)
def _build_ulysses(mesh, axis, rows, cols, collective_id, token=()):
    del token
    n = mesh.shape[axis]
    return lang.shmem_call(
        functools.partial(_ulysses_a2a_kernel, n, axis, mesh.axis_names),
        out_shape=jax.ShapeDtypeStruct((n * rows, cols), jnp.float32),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=collective_id,
        name="cp_ulysses_a2a",
    )


def build_ulysses_lint(mesh, n, token=()):
    """Registry/pre-flight entry for ``cp.ulysses``."""
    del n
    g = CP_RING_GEOM
    return _build_ulysses(
        mesh, "x", g["rows"], g["cols"], CP_ULYSSES_COLLECTIVE_ID, token,
    )


# --------------------------------------------- grad_ring.stream_int8w (17)

def _grad_ring_kernel_w(
    n, axis, mesh_axes, fmt, schedule,
    x_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    copy_sem, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """The gradient ring's Pallas shape: HBM-streaming reduce ring on
    the quantized wire (per-hop quant into the wq/ws rails, f32
    dequant-accumulate on receive) — protocol kernels/ring.py, wire
    layout lang.wire. The EF residual/stochastic-rounding numerics live
    in the XLA body (``train.grad_wire``); the PROTOCOL (slot indexing,
    ack credits, paired scale rail) is what this twin puts under lint."""
    from triton_distributed_tpu.kernels.ring import RSWireRefs, reduce_ring

    m = out_hbm.shape[0]
    cols = out_hbm.shape[1]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1), ws=(ws0, ws1), rq=(rq0, rq1),
        rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m, cols, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m, cols, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="grad_ring", wire=wire, schedule=schedule,
    )


def _grad_ring_kernel_w3(
    n, axis, mesh_axes, fmt, schedule,
    x_hbm, out_hbm, w0, w1, w2,
    wq0, wq1, wq2, ws0, ws1, ws2, rq0, rq1, rq2, rs0, rs1, rs2,
    copy_sem, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """Depth-3 twin of :func:`_grad_ring_kernel_w` (schedule depth 3)."""
    from triton_distributed_tpu.kernels.ring import RSWireRefs, reduce_ring

    m = out_hbm.shape[0]
    cols = out_hbm.shape[1]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1, wq2), ws=(ws0, ws1, ws2),
        rq=(rq0, rq1, rq2), rs=(rs0, rs1, rs2),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m, cols, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m, cols, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1, w2), (None, None, None),
        send_sem, recv_sem, ack_sem, partial_into, None,
        site="grad_ring", wire=wire, schedule=schedule,
    )


@functools.lru_cache(maxsize=64)
def _build_grad_ring_w(mesh, axis, rows, cols, collective_id, wire,
                       token=(), schedule=None):
    del token
    n = mesh.shape[axis]
    m_local = rows // n
    d = 2 if schedule is None else int(schedule.depth)
    fmt = wirelib.make_wire_format(wire, m_local)
    assert fmt is not None, (wire, m_local)
    slab = jax.ShapeDtypeStruct((m_local, cols), jnp.float32)
    qslab = jax.ShapeDtypeStruct((m_local, cols), fmt.wire_dtype)
    sslab = jax.ShapeDtypeStruct(
        (fmt.chunks(m_local), wirelib.SCALE_LANES), jnp.float32
    )
    kernel = _grad_ring_kernel_w if d == 2 else _grad_ring_kernel_w3
    return lang.shmem_call(
        functools.partial(kernel, n, axis, mesh.axis_names, fmt, schedule),
        # out + bf16 work slots + quantized work/scale + recv/scale slots
        # (HBM workspaces ride as ANY outputs — Mosaic has no HBM scratch)
        out_shape=[slab] + [slab] * d
                  + [qslab] * d + [sslab] * d
                  + [qslab] * d + [sslab] * d,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (1 + 5 * d),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.REGULAR,
            pltpu.SemaphoreType.DMA((d,)),   # scale rail
            pltpu.SemaphoreType.DMA((d,)),
        ],
        collective_id=collective_id,
        name=f"grad_ring_stream_{wire}w",
    )


def build_grad_ring_lint(mesh, n, token=(), schedule=None):
    """Registry/pre-flight entry for ``grad_ring.stream_int8w``."""
    g = CP_RING_GEOM
    return _build_grad_ring_w(
        mesh, "x", g["rows"] * n, g["grad_cols"], GRAD_RING_COLLECTIVE_ID,
        "int8", token, schedule,
    )


# --------------------------------------------- cp_decode.lse_combine (18)

def _cp_lse_combine_kernel(
    n, axis, mesh_axes, schedule,
    x_hbm, out_hbm, w0, w1, r0, r1,
    copy_sem, send_sem, recv_sem, ack_sem,
):
    """Cross-rank LSE-combine as an HBM-streaming add-reduce ring.

    ``x_hbm`` rows ``[dst·m, (dst+1)·m)`` are this rank's exp-weighted
    contribution to destination shard ``dst`` — numerator rows
    ``w_r·out_r`` with the denominator row ``Σ w_r`` riding as the last
    row of each block (the softmax merge is associative once every rank
    weights against the pre-agreed max, so the ring core is a plain
    add). The wire stays f32: a quantized denominator row would drift
    the owner rank's final normalize. Fold provenance is the streamed
    two-operand add (``ew_add_pipeline``) — the evidence SL008 replays."""
    from triton_distributed_tpu.kernels.gemm_rs import ew_add_pipeline
    from triton_distributed_tpu.kernels.ring import reduce_ring

    m = out_hbm.shape[0]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (r0, r1),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(m, out_hbm.shape[1], out_hbm.dtype.itemsize),
        site="cp_decode", schedule=schedule,
    )


def _cp_lse_combine_kernel3(
    n, axis, mesh_axes, schedule,
    x_hbm, out_hbm, w0, w1, w2, r0, r1, r2,
    copy_sem, send_sem, recv_sem, ack_sem,
):
    """Depth-3 twin of :func:`_cp_lse_combine_kernel` (schedule depth 3)."""
    from triton_distributed_tpu.kernels.gemm_rs import ew_add_pipeline
    from triton_distributed_tpu.kernels.ring import reduce_ring

    m = out_hbm.shape[0]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1, w2), (r0, r1, r2),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(m, out_hbm.shape[1], out_hbm.dtype.itemsize),
        site="cp_decode", schedule=schedule,
    )


@functools.lru_cache(maxsize=64)
def _build_cp_lse_combine(mesh, axis, rows, cols, collective_id, token=(),
                          schedule=None):
    del token
    n = mesh.shape[axis]
    d = 2 if schedule is None else int(schedule.depth)
    slab = jax.ShapeDtypeStruct((rows // n, cols), jnp.float32)
    kernel = _cp_lse_combine_kernel if d == 2 else _cp_lse_combine_kernel3
    return lang.shmem_call(
        functools.partial(kernel, n, axis, mesh.axis_names, schedule),
        # ring slabs ride as extra ANY outputs (Mosaic has no HBM scratch)
        out_shape=[slab] * (1 + 2 * d),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (1 + 2 * d),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=collective_id,
        name="cp_decode_lse_combine",
    )


def build_cp_lse_combine_lint(mesh, n, token=(), schedule=None):
    """Registry/pre-flight entry for ``cp_decode.lse_combine``."""
    g = CP_RING_GEOM
    return _build_cp_lse_combine(
        mesh, "x", g["rows"] * n, g["cols"],
        CP_DECODE_COMBINE_COLLECTIVE_ID, token, schedule,
    )
