"""MoE routing utilities: expert selection, token sort, block alignment.

Reference: ``select_experts`` (python/triton_dist/kernels/nvidia/
moe_reduce_rs.py:180-213, softmax+topk routing), ``full_moe_align_block_
size`` (moe_reduce_rs.py:87-179) and the CUDA ``moe_ag_scatter_align_
block_size`` (csrc/lib/moe_utils.cu:61-356): sort the (token, expert)
pairs by expert and pad each expert's segment to a GEMM block boundary so
a grouped GEMM can walk whole blocks with a single expert id per block.

TPU re-design: the alignment is a handful of cumsums/scatters over a few
thousand int32s — XLA fuses it into the surrounding program, so it stays
jnp (no custom kernel needed; the reference needed CUDA because torch ops
for this were the bottleneck at sub-microsecond latencies). Shapes are
static: the padded capacity is the worst case ``M·k`` rounded up plus one
partial block per expert, and unused slots carry a sentinel row id that
gathers a zero row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_up_to_block(x, block: int):
    """Round ``x`` (int or int array) up to a multiple of ``block``."""
    return ((x + block - 1) // block) * block


def exclusive_cumsum(x):
    """[0, x0, x0+x1, ...] — segment start offsets from segment sizes."""
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(x)[:-1].astype(jnp.int32)]
    )


def select_experts(gate_logits, topk: int, *, renormalize: bool = True):
    """Softmax router → (weights (M, k) f32, expert ids (M, k) int32).

    ≡ select_experts (moe_reduce_rs.py:180-213).
    """
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(probs, topk)
    if renormalize:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


def aligned_capacity(total: int, num_experts: int, block_m: int) -> int:
    """Static worst-case padded length: every expert wastes < block_m."""
    return round_up_to_block(total + num_experts * (block_m - 1), block_m)


def moe_align_block_size(topk_ids, num_experts: int, block_m: int):
    """Sort (token, slot) pairs by expert and pad segments to block_m.

    topk_ids: (M, k) int32. Returns:
      sorted_token_ids: (cap,) int32 — flat source index ``row*k + slot``
        per padded position, sentinel ``M*k`` for padding (gather a zero
        row there);
      block_expert: (cap//block_m,) int32 — owning expert of each block;
      splits: (num_experts,) int32 — true token count per expert.
    ≡ moe_ag_scatter_align_block_size (csrc/lib/moe_utils.cu:61-356).
    """
    m, k = topk_ids.shape
    total = m * k
    cap = aligned_capacity(total, num_experts, block_m)
    flat = topk_ids.reshape(-1).astype(jnp.int32)

    splits = jnp.zeros((num_experts,), jnp.int32).at[flat].add(1)
    padded = round_up_to_block(splits, block_m)
    padded_offs = exclusive_cumsum(padded)
    offs = exclusive_cumsum(splits)

    order = jnp.argsort(flat, stable=True).astype(jnp.int32)   # (total,)
    sorted_experts = flat[order]
    rank_in_expert = jnp.arange(total, dtype=jnp.int32) - offs[sorted_experts]
    dest = padded_offs[sorted_experts] + rank_in_expert

    sorted_token_ids = jnp.full((cap,), total, jnp.int32).at[dest].set(order)

    nblocks = cap // block_m
    block_start = jnp.arange(nblocks, dtype=jnp.int32) * block_m
    block_expert = jnp.searchsorted(
        jnp.cumsum(padded), block_start, side="right"
    ).astype(jnp.int32)
    block_expert = jnp.clip(block_expert, 0, num_experts - 1)
    return sorted_token_ids, block_expert, splits


def gather_sorted(x, sorted_token_ids, topk: int):
    """Rows of ``x`` (M, H) in padded-sorted order, zeros at padding.

    ``sorted_token_ids`` indexes the flattened (M·k) token-slot space;
    the row is ``id // k``.
    """
    total = x.shape[0] * topk
    rows = jnp.clip(sorted_token_ids // topk, 0, x.shape[0] - 1)
    valid = sorted_token_ids < total
    return jnp.where(valid[:, None], x[rows], 0)


def scatter_combine(y_sorted, sorted_token_ids, weights, m: int):
    """Weighted scatter-add of expert outputs back to token order.

    y_sorted: (cap, H) grouped-GEMM output in padded-sorted order;
    weights: (M, k) router weights. Returns (M, H) — each token is the
    weighted sum of its k expert outputs (≡ the topk-reduce stage of
    moe_reduce_rs.py:468-545).
    """
    k = weights.shape[1]
    total = m * k
    valid = sorted_token_ids < total
    safe = jnp.where(valid, sorted_token_ids, 0)
    w = weights.reshape(-1)[safe] * valid                      # (cap,)
    rows = jnp.where(valid, safe // k, m)                      # sentinel → m
    out = jnp.zeros((m + 1, y_sorted.shape[1]), jnp.float32)
    out = out.at[rows].add(y_sorted.astype(jnp.float32) * w[:, None])
    return out[:m]
