"""Context-parallel attention for training: ring and Ulysses.

Reference scope: the reference's sequence parallelism is decode-side
only (KV-sharded flash-decode, flash_decode.py:482-566; SURVEY.md §5
"no training-time ring attention or Ulysses"). Long-context TRAINING
is first-class here, so this module adds both standard CP schemes over
the same mesh axes the rest of the framework uses:

* **Ring attention** (blockwise causal): Q stays put; KV blocks rotate
  around the ring via ``ppermute`` while each step's partial attention
  folds into carried online-softmax state (m, l, acc) — the classic
  blockwise-parallel formulation. Communication overlaps compute via
  XLA's async collective-permute, and every op has a transpose rule so
  ``jax.grad`` works through the whole ring (the backward rotates the
  opposite direction automatically).
* **Ulysses** (all-to-all head scatter): re-shard seq→heads with one
  a2a, run plain local attention on full sequences of the local head
  subset, a2a back. Cheaper at moderate sequence lengths; needs
  heads % cp == 0.

Both consume (B, S, H, D) with S sharded over ``axis`` and are
numerically the same computation as dense causal attention.

Robustness: the host entries wrap the per-device bodies in
``lang.maybe_instrument`` heartbeats (site ``"cp_ring"``) — CP rings
were the last collectives that could wedge silently. A chaos
``Stall(site="cp_ring")`` under an armed watchdog trips with the ring's
collective id in the report, and the lint-family twins in
``kernels.cp_ring`` carry the same ids so evidence lines up. The
degradation target is :func:`dense_attention_reference` (gather KV,
attend densely — exact, no ring to deadlock).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1.0e30

#: collective ids of the CP rings (shared with the ``kernels.cp_ring``
#: lint families so watchdog reports and lint evidence name the same
#: launch): ring KV-rotation = 15, Ulysses head-scatter a2a = 16.
RING_ATTENTION_COLLECTIVE_ID = 15
ULYSSES_COLLECTIVE_ID = 16


def _block_attn(q, k, v, scale, mask):
    """One blockwise partial: returns (scores_max, exp-sums, weighted V)
    in f32. q: (B, Sq, Hkv, G, D); k/v: (B, Skv, Hkv, D); mask
    broadcastable to (B, Sq, Hkv, G, Skv)."""
    s = jnp.einsum(
        "bqhgd,bkhd->bqhgk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    s = jnp.where(mask, s, NEG_INF)
    # the max is a pure numerical stabilizer: it must be a constant to
    # autodiff everywhere (exponent AND the cross-block combine factors),
    # or the blockwise gradients pick up spurious max-subgradient terms
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bqhgk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention_device(q, k, v, axis, *, causal: bool = True, scale=None):
    """Per-device ring attention body (callable inside shard_map).

    q/k/v: (B, S_loc, H, D) — this rank's sequence block; H is Hq for q
    and Hkv for k/v (GQA supported, Hq % Hkv == 0). Returns
    (B, S_loc, Hq, D) in q.dtype.
    """
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, s_loc, hkv, g, d)
    pos_q = me * s_loc + jnp.arange(s_loc)                    # global q rows

    def block_mask(src):
        if not causal:
            return jnp.ones((1, 1, 1, 1, s_loc), bool)
        pos_k = src * s_loc + jnp.arange(s_loc)
        return (pos_q[:, None] >= pos_k[None, :])[None, :, None, None, :]

    def combine(acc, blk):
        m_acc, l_acc, o_acc = acc
        m_blk, l_blk, o_blk = blk
        m_new = jnp.maximum(m_acc, m_blk)
        a_old = jnp.exp(m_acc - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        return (m_new, a_old * l_acc + a_blk * l_blk,
                a_old * o_acc + a_blk * o_blk)

    # step 0 peeled: the local block needs no rotation, so the scan does
    # exactly n-1 ppermute pairs (no discarded final rotation)
    acc = _block_attn(qg, k, v, scale, block_mask(me))

    def step(carry, i):
        k_blk, v_blk, acc = carry
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        src = jax.lax.rem(me - i + n, n)                      # block owner
        blk = _block_attn(qg, k_blk, v_blk, scale, block_mask(src))
        return (k_blk, v_blk, combine(acc, blk)), None

    (_, _, (_, l_f, o_f)), _ = jax.lax.scan(
        step, (k, v, acc), jnp.arange(1, n)
    )
    out = o_f / jnp.maximum(l_f, 1e-30)
    return out.reshape(b, s_loc, hq, d).astype(q.dtype)


def ulysses_attention_device(q, k, v, axis, *, causal: bool = True, scale=None):
    """Per-device Ulysses body: a2a seq→heads, local attention over the
    FULL sequence on H/cp local heads, a2a back.

    q/k/v: (B, S_loc, H, D), S sharded over ``axis``; needs
    Hq % cp == 0 and Hkv % cp == 0.
    """
    n = jax.lax.axis_size(axis)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    assert hq % n == 0, f"Ulysses needs Hq % cp == 0, got {hq} % {n}"
    if hkv % n != 0:
        # GQA with fewer KV heads than the CP degree: replicate KV heads
        # so each rank gets a whole head (the standard Ulysses-GQA trick;
        # replicated heads attend identically, numerics unchanged)
        assert n % hkv == 0, f"need Hkv % cp == 0 or cp % Hkv == 0 ({hkv}, {n})"
        rep = n // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        hkv = n

    def scatter_heads(x):
        # (B, S_loc, H, D) → (B, n*S_loc, H/n, D): head chunk i goes to
        # rank i; received seq blocks stack in source order (global seq)
        return jax.lax.all_to_all(
            x, axis, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        # inverse: (B, S, H/n, D) → (B, S_loc, H, D); received head
        # chunks stack in source order (global head = src·H/n + local)
        return jax.lax.all_to_all(
            x, axis, split_axis=1, concat_axis=2, tiled=True
        )

    out = dense_attention_reference(
        scatter_heads(q), scatter_heads(k), scatter_heads(v),
        causal=causal, scale=scale,
    )
    return gather_heads(out)


@functools.lru_cache(maxsize=64)
def _build(mesh, axis, kind, causal, batch_axes, ikey=None):
    # ikey: config.interp_key() — folds faults.trace_key, so arming the
    # watchdog / activating a fault plan rebuilds with heartbeats on
    from triton_distributed_tpu import lang

    body = {
        "ring": ring_attention_device,
        "ulysses": ulysses_attention_device,
    }[kind]
    cid = {
        "ring": RING_ATTENTION_COLLECTIVE_ID,
        "ulysses": ULYSSES_COLLECTIVE_ID,
    }[kind]
    mapped = lang.maybe_instrument(
        functools.partial(body, axis=axis, causal=causal),
        axis=axis, site="cp_ring", collective_id=cid,
        n=mesh.shape[axis],
    )
    spec = P(tuple(batch_axes) if batch_axes else None, axis)
    fn = jax.shard_map(
        mapped,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)


def _ikey():
    from triton_distributed_tpu.config import interp_key

    return interp_key()


def ring_attention(q, k, v, mesh, axis="x", *, causal: bool = True,
                   batch_axes: tuple = ()):
    """Host entry: (B, S, H, D) with S sharded over ``axis`` (and B over
    ``batch_axes``, if given)."""
    return _build(
        mesh, axis, "ring", causal, tuple(batch_axes), _ikey()
    )(q, k, v)


def ulysses_attention(q, k, v, mesh, axis="x", *, causal: bool = True,
                      batch_axes: tuple = ()):
    """Host entry: (B, S, H, D) with S sharded over ``axis`` (and B over
    ``batch_axes``, if given)."""
    return _build(
        mesh, axis, "ulysses", causal, tuple(batch_axes), _ikey()
    )(q, k, v)


def dense_attention_reference(q, k, v, *, causal: bool = True, scale=None):
    """Unsharded causal GQA attention — the correctness baseline and the
    local body of Ulysses (full sequence, local head subset)."""
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, s, hkv, g, d)
    if causal:
        pos = jnp.arange(s)
        mask = (pos[:, None] >= pos[None, :])[None, :, None, None, :]
    else:
        mask = jnp.ones((1, 1, 1, 1, s), bool)
    m, l, o = _block_attn(qg, k, v, scale, mask)
    return (o / jnp.maximum(l, 1e-30)).reshape(b, s, hq, d).astype(q.dtype)
