"""Fused MoE dispatch/combine: count-bounded chunked per-peer DMAs.

Reference: the single-kernel DeepEP-style dispatch
(python/triton_dist/kernels/nvidia/low_latency_all_to_all.py:36-118) —
one block per peer computes that peer's token range from the splits
cumsum and ``putmem_nbi``s EXACTLY those bytes, barrier-free behind a
call-count signal protocol (:97-118). This module is that protocol's
TPU translation, third design iteration:

* r2 staged padded slots in XLA and bitcast everything to int32 —
  199 µs of staging before any wire traffic.
* r3 DMAed fixed ``max_pad``-row windows per peer straight out of the
  aligned expert-sorted payload — fast staging (83.5 µs measured), but
  the wire moved the worst-case window regardless of true counts (≈n×
  the necessary ICI bytes at n>1) behind a per-leg ``barrier_all``
  (VERDICT r3 missing #1/#2).
* r4 (this file): COUNT-BOUNDED chunked transport. Tokens are staged
  once into aligned expert-sorted per-peer segments (as r3); the
  kernel then ships each peer ceil(count/chunk) chunk DMAs — wire
  bytes track the true counts to within one chunk granule per peer,
  the TPU expression of the reference's exact per-expert ranges
  (Mosaic DMA shapes are static, so the granule is the price of
  static shapes; offsets ride SMEM in tile units so Mosaic can prove
  alignment). Receivers learn the incoming chunk count from a small
  metadata block (counts + chunk count + checksum) that lands before
  the payload wait — the splits-ride-with-payload trick of the
  reference — and wait for exactly that many chunk arrivals.

Two transport modes share the kernel body:

* **barrier mode** (stateless): fresh receive buffers per call, entry
  ``barrier_all`` (a fresh launch's buffers are only addressable once
  every peer has entered the kernel). Used by one-shot/prefill calls.
* **LL mode** (barrier-free): persistent double-buffered workspaces
  owned by the caller and threaded through every call (aliased
  input→output), per-parity semaphore rows, NO barrier — the
  ``_ll_persist_kernel`` protocol (kernels/allgather.py:138-203)
  applied to the a2a, in the functional carry form
  ``(payload, ws, parity) → ws'`` so fully-jitted decode loops can
  roll the parity across steps (≡ the reference's call_count double
  buffering, low_latency_all_to_all.py:97-118).

Safety of LL mode (no barrier):

1. *No overwrite before read*: call N's pushes land in parity window
   N%2. A rank finishes call N only after receiving every peer's
   call-N traffic, so inter-rank skew is bounded by ONE call; window
   N%2 is re-written at call N+2, by which point every consumer read
   of call N (which precedes the local issue of call N+1, which
   precedes any peer's entry into call N+2) has completed.
2. *No credit confusion*: semaphores are per-(parity, sender) slots,
   so a one-call-skewed peer's credits land in the other parity row.
   Across DIFFERENT call sites (dispatch vs combine, layer i vs j) —
   where physical semaphore allocations are outside our control — the
   protocol stays safe because every (src, dst) pair's sequence of
   credited byte counts equals, in issue order, the receiver's
   sequence of waited byte counts (TPU RDMA between a fixed pair is
   delivered in issue order, see lang.fence), so counting waits
   consume matched credits even if sites were to share semaphores.

The token payload rides in its NATIVE wire dtype (fp8/int8/bf16):
DMAs move bytes, so quantized bits are safe in flight; a measured
~290 µs bitcast-to-int32 of the r2 design is avoided. Metadata
(int32 counts, f32 scale bits) rides a separate small int32 array so
count bits never transit float lanes.

Layout summary:

* sender payload: (m_cap, hidden) wire dtype — aligned expert-sorted
  segments (segment starts are multiples of the dtype's sublane tile).
* sender meta: (n, meta_rows, 128) int32 — [epr counts, n_chunks,
  checksum][per-row f32 scale bits for that peer's window].
* receiver (barrier mode): tokens (n·slot_pad, hidden) wire dtype +
  meta (n·meta_rows, 128) int32; rows past the shipped chunks are
  unwritten (stale), masked by the counts exactly like the reference
  masks by splits. LL mode: the same layout ×2 parity windows inside
  the persistent workspace.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import config, interp_key
from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.kernels.moe_utils import exclusive_cumsum
from triton_distributed_tpu.utils.testing import chaos_delay

META_W = 128  # metadata lane width (one native int32 tile)


def _cnt_rows(ctx) -> int:
    """Leading metadata rows holding [epr counts, n_chunks, checksum] —
    the ONE definition every packer/parser must share (a mismatch
    silently shifts the scale rows)."""
    return -(-(ctx.experts_per_rank + 2) // META_W)


def align(ctx: ma.MoEAllToAllContext) -> int:
    """Segment-start granule: the wire dtype's sublane tile (8·packing —
    32 rows for 1-byte wire, 16 for bf16, 8 for f32). Mosaic requires
    DMA slice offsets AND shapes aligned to it."""
    return 8 * (4 // ctx.wire_dtype.itemsize)


def chunk_rows(ctx: ma.MoEAllToAllContext) -> int:
    """Wire DMA granule (rows): per-peer wire bytes are
    ceil(count/chunk)·chunk rows, so this bounds the slack vs the true
    count (≤ chunk−1 rows/peer). Default max(tile, 64) ≈ 0.5 MB DMAs at
    hidden 7168 — big enough to amortize DMA issue, small next to the
    per-peer payload."""
    a = align(ctx)
    if ctx.chunk_m is not None:
        if ctx.chunk_m % a or ctx.chunk_m <= 0:
            raise ValueError(
                f"chunk_m={ctx.chunk_m} must be a positive multiple of the "
                f"wire sublane tile {a}"
            )
        return ctx.chunk_m
    if ctx.max_m < 64:
        return a
    return max(a, 64)


def n_chunks_max(ctx: ma.MoEAllToAllContext) -> int:
    return -(-ctx.max_m // chunk_rows(ctx))


def slot_pad(ctx: ma.MoEAllToAllContext) -> int:
    """Per-peer receive-slot capacity (rows): worst case all ``max_m``
    assignments route to one peer, rounded to whole chunks."""
    return n_chunks_max(ctx) * chunk_rows(ctx)


def meta_rows(ctx: ma.MoEAllToAllContext) -> int:
    """Per-slot int32 metadata rows: [counts, n_chunks, checksum]
    [scales], padded to the int32 sublane granule (8)."""
    sc_rows = 0 if ctx.quant is None else -(-slot_pad(ctx) // META_W)
    return -(-(_cnt_rows(ctx) + sc_rows) // 8) * 8


def m_cap(ctx: ma.MoEAllToAllContext) -> int:
    """Sender payload rows. A peer's chunks cover
    [offs_al, offs_al + ceil(count/chunk)·chunk): segment alignment
    wastes < align per peer and the last chunk overshoots by < chunk,
    so aligned-total + n·align + chunk rows always contain every read
    (the overhang rows carry neighbouring-segment bytes, masked by the
    receiver's counts)."""
    a = align(ctx)
    return -(-ctx.max_m // a) * a + a * ctx.n + chunk_rows(ctx)


def send_plan(ctx: ma.MoEAllToAllContext, splits):
    """(counts (n,), dense offs (n,), aligned offs (n,), sendk (n,))
    per peer: aligned segment starts and the chunk count each peer's
    transfer needs — the cumsum→range computation of the reference's
    kernel (low_latency_all_to_all.py:62-80), done once in XLA."""
    a = align(ctx)
    counts, offs = ma.peer_offsets(ctx, splits)
    offs_al = exclusive_cumsum(-(-counts // a) * a)
    sendk = -(-counts // chunk_rows(ctx))
    return counts, offs, offs_al, sendk.astype(jnp.int32)


def assignment_dest(ctx: ma.MoEAllToAllContext, sorted_experts, offs, offs_al):
    """(peer (T,), dest (T,)): target rank and aligned payload row for
    each expert-sorted assignment.

    ``sorted_experts``: (T,) global expert id per sorted assignment;
    position t within its peer's dense segment is t - offs[peer]."""
    t = jnp.arange(sorted_experts.shape[0], dtype=jnp.int32)
    peer = (sorted_experts // ctx.experts_per_rank).astype(jnp.int32)
    peer = jnp.clip(peer, 0, ctx.n - 1)
    return peer, offs_al[peer] + (t - offs[peer])


def stage_aligned(ctx: ma.MoEAllToAllContext, x, src_row, dest, n_valid):
    """One-pass staging: gather rows of ``x`` into the aligned layout in
    the native wire dtype → ((m_cap, hidden) tokens, (m_cap,) f32 scales
    or None).

    ``src_row``: (T,) source row of x per assignment (T = M·topk);
    ``dest``: (T,) aligned payload row per assignment (from
    :func:`assignment_dest`); ``n_valid``: valid assignment count
    (assignments ≥ n_valid were clipped — none at standard routing).
    """
    cap = m_cap(ctx)
    inv = jnp.full((cap,), -1, jnp.int32).at[dest].set(
        jnp.where(jnp.arange(src_row.shape[0]) < n_valid, src_row, -1)
    )
    ok = inv >= 0
    rows = jnp.where(
        ok[:, None], x[jnp.clip(inv, 0, x.shape[0] - 1)], 0
    )
    if ctx.quant is None:
        return rows.astype(ctx.dtype), None
    q, scale = ma.quantize_rows(ctx, rows)
    return q, scale.astype(jnp.float32)


def _pack_scale_rows(ctx, scale2d):
    """(n, slot_pad) f32 → (n, ceil(sp/128), 128) bitcast int32."""
    sp = slot_pad(ctx)
    pad = -(-sp // META_W) * META_W - sp
    return jax.lax.bitcast_convert_type(
        jnp.pad(scale2d.astype(jnp.float32), ((0, 0), (0, pad))), jnp.int32
    ).reshape(ctx.n, -1, META_W)


def _head_checksum(head):
    """(n, epr+1) int32 [counts, n_chunks] → (n,) int32 mix. Cheap FNV
    -style word mix in uint32 (wrapping); the debug-mode integrity
    check — a packer/parser drift or corrupted meta row flips it."""
    v = head.astype(jnp.uint32)
    i = jnp.arange(v.shape[1], dtype=jnp.uint32)
    h = jnp.sum((v ^ (i * jnp.uint32(0x9E3779B9))) * jnp.uint32(0x85EBCA6B),
                axis=1)
    h = h ^ (h >> 15)
    return h.astype(jnp.int32)


def _pack_meta(ctx, head, scale2d):
    """Shared meta packer: ``head`` (n, epr+1) int32 [counts, n_chunks]
    gets its checksum appended and is padded into the leading cnt_rows;
    ``scale2d`` (n, slot_pad) f32 or None fills the scale rows; zeros
    pad to meta_rows. The ONE head/scale layout definition — its dual is
    :func:`_parse_meta` (a drift between them silently shifts rows,
    which is what the checksum surfaces)."""
    cnt_rows = _cnt_rows(ctx)
    head = jnp.concatenate([head, _head_checksum(head)[:, None]], axis=1)
    pad = cnt_rows * META_W - head.shape[1]
    parts = [jnp.pad(head, ((0, 0), (0, pad))).reshape(ctx.n, cnt_rows, META_W)]
    if scale2d is not None:
        parts.append(_pack_scale_rows(ctx, scale2d))
    used = sum(p.shape[1] for p in parts)
    tail = meta_rows(ctx) - used
    if tail:
        parts.append(jnp.zeros((ctx.n, tail, META_W), jnp.int32))
    return jnp.concatenate(parts, axis=1)


def meta_payload(ctx: ma.MoEAllToAllContext, splits, scales, offs_al, sendk):
    """(n, meta_rows, 128) int32 per-peer wire metadata:
    [epr counts, n_chunks, checksum][f32 scale bits for that peer's
    window rows]. ``n_chunks`` drives the receiver's payload wait trip
    count; the checksum guards the whole head row (verified by
    :func:`_parse_meta` under ``config.debug_checksum``)."""
    spl = splits.reshape(ctx.n, ctx.experts_per_rank).astype(jnp.int32)
    head = jnp.concatenate([spl, sendk[:, None]], axis=1)
    scale2d = None
    if ctx.quant is not None:
        sp = slot_pad(ctx)
        j = jnp.arange(sp, dtype=jnp.int32)
        idx = offs_al[:, None] + j[None, :]       # window rows
        scale2d = scales[jnp.clip(idx, 0, scales.shape[0] - 1)]
    return _pack_meta(ctx, head, scale2d)


def _parse_meta(ctx: ma.MoEAllToAllContext, meta):
    """(n·meta_rows, 128) int32 → ((n, epr) clamped counts, (n,) ok
    flags, (n, slot_pad) f32 scales or None). ``ok`` is all-True unless
    ``config.debug_checksum`` is on and a head row fails its checksum
    (consumers poison those slots with NaN — loud, not silently zero)."""
    mr = meta_rows(ctx)
    slots = meta.reshape(ctx.n, mr, META_W)
    cnt_rows = _cnt_rows(ctx)
    flat = slots[:, :cnt_rows].reshape(ctx.n, -1)
    epr = ctx.experts_per_rank
    rspl = ma.clamp_recv_splits(ctx, flat[:, :epr])
    if config.debug_checksum:
        ok = _head_checksum(flat[:, : epr + 1]) == flat[:, epr + 1]
    else:
        ok = jnp.ones((ctx.n,), bool)
    scales = None
    if ctx.quant is not None:
        sp = slot_pad(ctx)
        sc = slots[:, cnt_rows:].reshape(ctx.n, -1)[:, :sp]
        scales = jax.lax.bitcast_convert_type(sc, jnp.float32)
    return rspl, ok, scales


def recv_view(ctx: ma.MoEAllToAllContext, recv_tok, recv_meta):
    """Receiver unpack: ((n, slot_pad, H) dequantized ctx.dtype tokens,
    (n, epr) clamped counts). Slot p's valid rows are [0, counts[p]
    .sum()); rows past the shipped chunks are unwritten garbage, masked
    by the counts (≡ the reference masking by splits)."""
    rspl, ok, scales = _parse_meta(ctx, recv_meta)
    toks = recv_tok.reshape(ctx.n, slot_pad(ctx), ctx.hidden)
    if ctx.quant is not None:
        toks = ma.dequantize_rows(ctx, toks, scales)
    toks = toks.astype(ctx.dtype)
    if config.debug_checksum:
        toks = jnp.where(ok[:, None, None], toks, jnp.nan)
    return toks, rspl


def stage_return(ctx: ma.MoEAllToAllContext, y):
    """(n, slot_pad, H) processed slot rows → ((n·slot_pad, H) wire-
    dtype tokens, (n, meta_rows, 128) int32 scale metadata) for the
    combine leg (quantized symmetrically with dispatch)."""
    sp = slot_pad(ctx)
    # zero head (the combiner ships no counts back) with a VALID
    # checksum, so a future debug-checksum pass over combine meta
    # doesn't false-positive
    zero_head = jnp.zeros((ctx.n, ctx.experts_per_rank + 1), jnp.int32)
    if ctx.quant is None:
        toks = y.astype(ctx.dtype).reshape(ctx.n * sp, ctx.hidden)
        return toks, _pack_meta(ctx, zero_head, None)
    q, scale = ma.quantize_rows(ctx, y)            # scale: (n, sp)
    return (
        q.reshape(ctx.n * sp, ctx.hidden),
        _pack_meta(ctx, zero_head, scale),
    )


def combine_view(ctx: ma.MoEAllToAllContext, comb_tok, comb_meta, peer, dest,
                 offs_al, n_valid):
    """Combine-leg unpack → (T, H) per-assignment rows in the original
    sorted order (dequantized), zeros for clipped assignments.

    Slot-regular: processed slot ``p`` returns whole to source ``p``,
    so assignment ``t`` (dispatched to peer ``p`` at aligned payload
    row ``dest[t]``, which landed at window row ``dest[t] - offs_al[p]``
    on the receiver) sits at combine slot ``p`` that same row."""
    sp = slot_pad(ctx)
    _, _, scales = _parse_meta(ctx, comb_meta)
    toks = comb_tok.reshape(ctx.n, sp, ctx.hidden)
    if ctx.quant is not None:
        toks = ma.dequantize_rows(ctx, toks, scales)
    toks = toks.reshape(ctx.n * sp, ctx.hidden).astype(ctx.dtype)
    t = jnp.arange(dest.shape[0])
    row = peer * sp + dest - offs_al[peer]
    rows = toks[jnp.clip(row, 0, toks.shape[0] - 1)]
    return jnp.where((t < n_valid)[:, None], rows, 0)


# ------------------------------------------------------------- the kernel


def _chunked_a2a_kernel(
    n, axis, mesh_axes, a, chunk_u, slot_u, mr, nck_row, nck_lane, kmax,
    know_recv, ll,
    parity_ref, offs_ref, sendk_ref, recvk_ref, payload_hbm, meta_hbm,
    *refs,
):
    """Count-bounded chunked per-peer push (both transport modes).

    Peer ``p`` receives my ``sendk[p]`` payload chunks from aligned
    segment offset ``offs[p]`` plus my metadata row-block, landing in
    slot ``me`` of its receive arrays (parity window in LL mode). The
    receiver waits one fixed-size meta DMA per peer, reads the incoming
    chunk count from the landed meta head (``know_recv=False``, the
    dispatch leg — counts are runtime data only the sender had) or from
    ``recvk_ref`` (``know_recv=True``, the combine leg — the original
    source knows how many rows it dispatched), then waits exactly that
    many chunk arrivals. Serves dispatch (dynamic aligned segment
    offsets) and combine (static slot offsets).

    All offsets ride SMEM in units of ``a`` (the wire dtype's sublane
    tile); the in-kernel multiply lets Mosaic PROVE every dynamic DMA
    slice start is tile-aligned.
    """
    if ll:
        ws_tok_in, ws_meta_in, dst_tok, dst_meta = refs[:4]
        sems = refs[4:]
        del ws_tok_in, ws_meta_in  # aliased with dst_* — one buffer
        par = parity_ref[0]
    else:
        dst_tok, dst_meta = refs[:2]
        sems = refs[2:]
        par = 0
    (send_sem, recv_sem, msend_sem, mrecv_sem, local_sem, smem_sem,
     smem_meta) = sems
    me = lang.my_pe(axis)
    chunk = chunk_u * a
    tbase = par * (n * slot_u)     # parity window base, in a-units
    mbase = par * n                # parity meta base, in mr-blocks

    # --- self slot: local chunked copies (no peer dependency)
    def self_start(c, _):
        pltpu.make_async_copy(
            payload_hbm.at[pl.ds((offs_ref[me] + c * chunk_u) * a, chunk)],
            dst_tok.at[pl.ds((tbase + me * slot_u + c * chunk_u) * a, chunk)],
            local_sem,
        ).start()
        return 0

    jax.lax.fori_loop(0, sendk_ref[me], self_start, 0)
    cpm = pltpu.make_async_copy(
        meta_hbm.at[pl.ds(me * mr, mr)],
        dst_meta.at[pl.ds((mbase + me) * mr, mr)],
        local_sem,
    )
    cpm.start()

    if not ll and n > 1:
        # fresh per-call receive buffers: no RDMA into a peer that has
        # not entered this launch yet (LL mode's persistent workspace
        # removes exactly this barrier)
        lang.barrier_all(axis, mesh_axes)

    # --- sends: one meta DMA + sendk[p] chunk DMAs per peer
    for i in range(n - 1):
        pi = jax.lax.rem(me + 1 + i, n)
        peer = lang.pe_flat(axis, pi, mesh_axes)
        chaos_delay(site="moe_dispatch", step=i, me=me, n=n)
        lang.remote_copy(
            meta_hbm.at[pl.ds(pi * mr, mr)],
            dst_meta.at[pl.ds((mbase + me) * mr, mr)],   # peer slot `me`
            msend_sem.at[par, pi],
            mrecv_sem.at[par, me],
            peer,
        ).start()

        def send_body(c, _, pi=pi, peer=peer):
            lang.remote_copy(
                payload_hbm.at[pl.ds((offs_ref[pi] + c * chunk_u) * a, chunk)],
                dst_tok.at[
                    pl.ds((tbase + me * slot_u + c * chunk_u) * a, chunk)
                ],
                send_sem.at[par, pi],
                recv_sem.at[par, me],                    # peer's slot `me`
                peer,
            ).start()
            return 0

        jax.lax.fori_loop(0, sendk_ref[pi], send_body, 0)

    # --- receives: per peer, meta → chunk count → chunk waits
    for i in range(n - 1):
        q = jax.lax.rem(me + 1 + i, n)
        msl = dst_meta.at[pl.ds((mbase + q) * mr, mr)]
        pltpu.make_async_copy(msl, msl, mrecv_sem.at[par, q]).wait()
        if know_recv:
            kq = recvk_ref[q]
        else:
            # DEDICATED semaphore: local_sem still carries the in-flight
            # self-slot copies here, and a DMA-sem wait is satisfied by
            # byte count — a completed self chunk's credit would release
            # this wait while smem_meta is still unwritten (garbage kq)
            cp = pltpu.make_async_copy(
                dst_meta.at[pl.ds((mbase + q) * mr + nck_row, 1)],
                smem_meta, smem_sem,
            )
            cp.start()
            cp.wait()
            # clamp: a corrupted count must not drive an out-of-bounds
            # wait (the data is already garbage; debug_checksum surfaces
            # it loudly on the host side)
            kq = jnp.clip(smem_meta[0, nck_lane], 0, kmax)

        def recv_body(c, _, q=q):
            sl = dst_tok.at[
                pl.ds((tbase + q * slot_u + c * chunk_u) * a, chunk)
            ]
            pltpu.make_async_copy(sl, sl, recv_sem.at[par, q]).wait()
            return 0

        jax.lax.fori_loop(0, kq, recv_body, 0)

    # --- drain: local completion of my own sends + self copies
    for i in range(n - 1):
        pi = jax.lax.rem(me + 1 + i, n)
        peer = lang.pe_flat(axis, pi, mesh_axes)

        def send_wait(c, _, pi=pi, peer=peer):
            lang.remote_copy(
                payload_hbm.at[pl.ds((offs_ref[pi] + c * chunk_u) * a, chunk)],
                dst_tok.at[
                    pl.ds((tbase + me * slot_u + c * chunk_u) * a, chunk)
                ],
                send_sem.at[par, pi],
                recv_sem.at[par, me],
                peer,
            ).wait_send()
            return 0

        jax.lax.fori_loop(0, sendk_ref[pi], send_wait, 0)
        lang.remote_copy(
            meta_hbm.at[pl.ds(pi * mr, mr)],
            dst_meta.at[pl.ds((mbase + me) * mr, mr)],
            msend_sem.at[par, pi],
            mrecv_sem.at[par, me],
            peer,
        ).wait_send()

    def self_wait(c, _):
        pltpu.make_async_copy(
            payload_hbm.at[pl.ds((offs_ref[me] + c * chunk_u) * a, chunk)],
            dst_tok.at[pl.ds((tbase + me * slot_u + c * chunk_u) * a, chunk)],
            local_sem,
        ).wait()
        return 0

    jax.lax.fori_loop(0, sendk_ref[me], self_wait, 0)
    cpm.wait()


def _kernel_geometry(ctx: ma.MoEAllToAllContext):
    """Static kernel parameters shared by both builders."""
    a = align(ctx)
    ck = chunk_rows(ctx)
    epr = ctx.experts_per_rank
    return dict(
        a=a,
        chunk_u=ck // a,
        slot_u=slot_pad(ctx) // a,
        mr=meta_rows(ctx),
        nck_row=epr // META_W,
        nck_lane=epr % META_W,
        kmax=n_chunks_max(ctx),
    )


def _sem_scratch(n):
    return [
        pltpu.SemaphoreType.DMA((2, max(n, 1))),   # send
        pltpu.SemaphoreType.DMA((2, max(n, 1))),   # recv
        pltpu.SemaphoreType.DMA((2, max(n, 1))),   # meta send
        pltpu.SemaphoreType.DMA((2, max(n, 1))),   # meta recv
        pltpu.SemaphoreType.DMA,                   # local copies
        pltpu.SemaphoreType.DMA,                   # SMEM meta-head reads
        pltpu.SMEM((1, META_W), jnp.int32),        # meta head scratch
    ]


_SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)
_ANY_SPEC = pl.BlockSpec(memory_space=pl.ANY)


@functools.lru_cache(maxsize=64)
def _build_chunked_a2a(mesh_axes, axis, n, a, chunk_u, slot_u, mr, nck_row,
                       nck_lane, kmax, cap, hidden, wire_dtype, know_recv,
                       collective_id, ikey):
    """Barrier-mode build: fresh receive outputs, entry barrier.
    Composable inside any shard_map (like all_to_all.all_to_all_device).
    """
    return lang.shmem_call(
        functools.partial(
            _chunked_a2a_kernel, n, axis, mesh_axes, a, chunk_u, slot_u,
            mr, nck_row, nck_lane, kmax, know_recv, False,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n * slot_u * a, hidden), wire_dtype),
            jax.ShapeDtypeStruct((n * mr, META_W), jnp.int32),
        ],
        in_specs=[_SMEM_SPEC] * 4 + [_ANY_SPEC] * 2,
        out_specs=[_ANY_SPEC] * 2,
        scratch_shapes=_sem_scratch(n),
        # n==1 skips barrier_all; Mosaic rejects an unused collective_id
        collective_id=collective_id if n > 1 else None,
        name="moe_chunked_a2a",
    )


@functools.lru_cache(maxsize=64)
def _build_chunked_a2a_ll(mesh_axes, axis, n, a, chunk_u, slot_u, mr,
                          nck_row, nck_lane, kmax, cap, hidden, wire_dtype,
                          know_recv, instance, ikey):
    """LL-mode build: barrier-free, persistent aliased workspace.

    ``instance`` keys the build per EPMoEState instance: two live
    states with identical configs must not share one compiled kernel —
    its physical per-parity DMA semaphores would be shared too (same
    ruling as allgather._build_ll_persist)."""
    return lang.shmem_call(
        functools.partial(
            _chunked_a2a_kernel, n, axis, mesh_axes, a, chunk_u, slot_u,
            mr, nck_row, nck_lane, kmax, know_recv, True,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((2 * n * slot_u * a, hidden), wire_dtype),
            jax.ShapeDtypeStruct((2 * n * mr, META_W), jnp.int32),
        ],
        in_specs=[_SMEM_SPEC] * 4 + [_ANY_SPEC] * 4,
        out_specs=[_ANY_SPEC] * 2,
        scratch_shapes=_sem_scratch(n),
        input_output_aliases={6: 0, 7: 1},
        # barrier-FREE by design (Mosaic rejects a collective_id on a
        # kernel that never touches the barrier semaphore)
        collective_id=None,
        name="moe_chunked_a2a_ll",
    )


def _geom_args(ctx):
    g = _kernel_geometry(ctx)
    return (
        ctx.mesh.axis_names, ctx.axis, ctx.n, g["a"], g["chunk_u"],
        g["slot_u"], g["mr"], g["nck_row"], g["nck_lane"], g["kmax"],
        m_cap(ctx), ctx.hidden, ctx.wire_dtype,
    )


def _zero_n(ctx):
    return jnp.zeros((ctx.n,), jnp.int32)


def dispatch_device(ctx: ma.MoEAllToAllContext, payload, offs_al, sendk,
                    meta_pl):
    """Per-device fused dispatch (inside any shard_map over ctx.mesh),
    barrier mode: ``payload`` (m_cap, hidden) wire-dtype aligned
    segments; ``offs_al``/``sendk`` (n,) int32 from :func:`send_plan`;
    ``meta_pl`` (n, meta_rows, 128) int32 from :func:`meta_payload`.
    Returns (recv_tok (n·slot_pad, hidden), recv_meta (n·meta_rows,
    128)) for :func:`recv_view`."""
    a = align(ctx)
    call = _build_chunked_a2a(
        *_geom_args(ctx), False, ctx.collective_id, interp_key()
    )
    return call(
        jnp.zeros((1,), jnp.int32),
        (offs_al // a).astype(jnp.int32),
        sendk.astype(jnp.int32),
        _zero_n(ctx),
        payload,
        meta_pl.reshape(ctx.n * meta_rows(ctx), META_W),
    )


def combine_device(ctx: ma.MoEAllToAllContext, y_tok, y_meta, retk, expk):
    """Per-device combine, barrier mode: the same kernel with STATIC
    slot offsets (slot p returns whole to source p, ``retk[p]`` chunks)
    and known receive counts (``expk[p]`` = the chunk count this rank
    dispatched to peer p — the source knows what must come back).
    ``y_tok`` (n·slot_pad, hidden) wire dtype; ``y_meta``
    (n, meta_rows, 128)."""
    a = align(ctx)
    call = _build_chunked_a2a(
        *_geom_args(ctx), True, ctx.collective_id + 1, interp_key()
    )
    slot_offs = (jnp.arange(ctx.n, dtype=jnp.int32) * slot_pad(ctx)) // a
    return call(
        jnp.zeros((1,), jnp.int32),
        slot_offs,
        retk.astype(jnp.int32),
        expk.astype(jnp.int32),
        y_tok,
        y_meta.reshape(ctx.n * meta_rows(ctx), META_W),
    )


def dispatch_ll_device(ctx: ma.MoEAllToAllContext, payload, offs_al, sendk,
                       meta_pl, parity, ws_tok, ws_meta, instance: int):
    """Barrier-free dispatch: functional carry form. ``parity`` (1,)
    int32 = call index % 2; ``ws_tok`` (2·n·slot_pad, hidden) /
    ``ws_meta`` (2·n·meta_rows, 128) persistent workspaces (aliased
    through — pass the returned arrays to the next call). Returns
    (ws_tok', ws_meta'); read the received window with
    :func:`ll_window`."""
    a = align(ctx)
    call = _build_chunked_a2a_ll(
        *_geom_args(ctx), False, instance, interp_key()
    )
    return call(
        parity.astype(jnp.int32),
        (offs_al // a).astype(jnp.int32),
        sendk.astype(jnp.int32),
        _zero_n(ctx),
        payload,
        meta_pl.reshape(ctx.n * meta_rows(ctx), META_W),
        ws_tok,
        ws_meta,
    )


def combine_ll_device(ctx: ma.MoEAllToAllContext, y_tok, y_meta, retk, expk,
                      parity, ws_tok, ws_meta, instance: int):
    """Barrier-free combine: static slot offsets + known receive
    counts, persistent workspace carry (see :func:`combine_device` /
    :func:`dispatch_ll_device`)."""
    a = align(ctx)
    call = _build_chunked_a2a_ll(
        *_geom_args(ctx), True, instance, interp_key()
    )
    slot_offs = (jnp.arange(ctx.n, dtype=jnp.int32) * slot_pad(ctx)) // a
    return call(
        parity.astype(jnp.int32),
        slot_offs,
        retk.astype(jnp.int32),
        expk.astype(jnp.int32),
        y_tok,
        y_meta.reshape(ctx.n * meta_rows(ctx), META_W),
        ws_tok,
        ws_meta,
    )


def ll_window(ctx: ma.MoEAllToAllContext, ws_tok, ws_meta, parity):
    """Slice the just-received parity window out of the LL workspaces →
    (recv_tok (n·slot_pad, H), recv_meta (n·meta_rows, 128)). A pure
    XLA dynamic-slice: it fuses into the downstream unpack, so the
    window is read in place — no drain copy of the padded window (the
    LL allgather's drain would cost ~2× the true payload bytes here)."""
    sp = slot_pad(ctx)
    mr = meta_rows(ctx)
    p = parity.reshape(())
    tok = jax.lax.dynamic_slice(
        ws_tok, (p * (ctx.n * sp), 0), (ctx.n * sp, ws_tok.shape[1])
    )
    meta = jax.lax.dynamic_slice(
        ws_meta, (p * (ctx.n * mr), 0), (ctx.n * mr, META_W)
    )
    return tok, meta


def ll_workspace_shapes(ctx: ma.MoEAllToAllContext):
    """Per-device LL workspace shapes: ((2·n·slot_pad, hidden) wire,
    (2·n·meta_rows, 128) int32)."""
    return (
        ((2 * ctx.n * slot_pad(ctx), ctx.hidden), ctx.wire_dtype),
        ((2 * ctx.n * meta_rows(ctx), META_W), jnp.dtype(jnp.int32)),
    )


def wire_rows(ctx: ma.MoEAllToAllContext, splits):
    """Accounting: (n,) payload rows this rank puts on the wire PER
    PEER, for each leg (dispatch and combine ship the same chunked row
    ranges in opposite directions). Callers exclude the self slot and
    compare against true counts — the wire-byte scaling test mirrors
    TestRailDedup's accounting."""
    _, _, _, sendk = send_plan(ctx, splits)
    return sendk * chunk_rows(ctx)
