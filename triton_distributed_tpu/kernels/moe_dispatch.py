"""Fused MoE dispatch/combine: in-kernel per-peer window DMAs.

Reference: the single-kernel DeepEP-style dispatch
(python/triton_dist/kernels/nvidia/low_latency_all_to_all.py:36-118) —
one block per peer computes that peer's token range from the splits
cumsum and ``putmem_nbi``s it straight out of the send buffer. The
first TPU design (kernels/moe_all_to_all.py) kept the transport dumb
and did the per-peer range work in XLA: gather tokens into (n, max_m)
padded slots, quantize, bitcast into one int32 payload, concat — that
staging dominated the measured dispatch latency (BENCH_r02: 199 µs with
no wire at all, VERDICT r2 weak #1).

This module is the TPU translation of the reference's on-device range
computation, with two measured design rules:

* Tokens are expert-sorted ONCE into per-peer contiguous, DMA-ALIGNED
  segments (the same single row-gather the dense path already pays) and
  the transport kernel DMAs each peer's
  ``payload[offs_al[p] : offs_al[p]+max_pad]`` window directly —
  scalar-prefetched offsets, no slot inflation, no concat.
* The token payload rides in its NATIVE wire dtype (fp8/int8/bf16).
  DMAs move bytes, so quantized bits are safe in flight; only the
  metadata (int32 counts, f32 scales) must avoid float token lanes, and
  it rides in a separate small int32 array. The previous design bitcast
  the whole payload to int32 "for safety" — measured on a v5e, that
  byte-repack alone cost ~290 µs at the headline config, 4× the rest of
  the staging combined.

The combine leg reuses the SAME kernel with static slot offsets
(``offs = [0, mp, 2mp, …]``): processed slots return whole to their
sources — slot-regular, so no offset exchange, and no overlapping
return windows (a windowed write-back into the aligned segments would
clobber neighbouring segments whose true counts are below max_pad).

Layout summary:

* sender payload: (m_cap, hidden) wire dtype — aligned expert-sorted
  segments (segment starts are multiples of the dtype's sublane tile).
* sender meta: (n, meta_rows, 128) int32 — [epr counts][per-token f32
  scale bits for that peer's window] (~4 B/token vs the 7 KB payload).
* receiver: tokens (n·max_pad, hidden) wire dtype + meta
  (n·meta_rows, 128) int32; rows past the counts are neighbouring-
  segment garbage, masked by the counts exactly like the reference
  masks by splits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import interp_key
from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.kernels.moe_utils import exclusive_cumsum
from triton_distributed_tpu.utils.testing import chaos_delay

META_W = 128  # metadata lane width (one native int32 tile)


def _cnt_rows(ctx) -> int:
    """Leading metadata rows holding [epr counts, row shift] — the ONE
    definition every packer/parser must share (a mismatch silently
    shifts the scale rows)."""
    return -(-(ctx.experts_per_rank + 1) // META_W)


def align(ctx: ma.MoEAllToAllContext) -> int:
    """Segment-start / window-row granule: the wire dtype's sublane tile
    (8·packing — 32 rows for 1-byte wire, 16 for bf16, 8 for f32).
    Mosaic requires DMA slice offsets AND shapes aligned to it."""
    return 8 * (4 // ctx.wire_dtype.itemsize)


def max_pad(ctx: ma.MoEAllToAllContext) -> int:
    """Per-peer window rows: worst-case per-peer token count, aligned."""
    a = align(ctx)
    return -(-ctx.max_m // a) * a


def meta_rows(ctx: ma.MoEAllToAllContext) -> int:
    """Per-slot int32 metadata rows: [counts, shift][scales], padded to
    the int32 sublane granule (8)."""
    sc_rows = 0 if ctx.quant is None else -(-max_pad(ctx) // META_W)
    return -(-(_cnt_rows(ctx) + sc_rows) // 8) * 8


def m_cap(ctx: ma.MoEAllToAllContext) -> int:
    """Sender payload rows: the aligned segments only. Windows are
    max_pad rows regardless of the true count, so a late window could
    read past the end — the kernel CLAMPS window starts to
    ``m_cap - max_pad`` and ships the resulting per-slot row shift in
    the metadata instead of over-allocating (the overhang rows would
    otherwise ride the staging gather+quantize for nothing: at the
    n=1 headline config they doubled the staged rows)."""
    return -(-ctx.max_m // align(ctx)) * align(ctx) + align(ctx) * ctx.n


def aligned_offsets(ctx: ma.MoEAllToAllContext, splits):
    """(counts (n,), dense offs (n,), aligned offs (n,), window offs
    (n,)) per peer. Window offsets are the segment offsets clamped so a
    max_pad-row window never reads past m_cap. The clamp is the COMMON
    case, not a corner: m_cap - max_pad ≈ align·n, so under uniform
    routing most peers' windows start below their segment and carry a
    nonzero row ``shift``, shipped in the metadata — the shift handling
    is live on most slots of every step."""
    a = align(ctx)
    counts, offs = ma.peer_offsets(ctx, splits)
    offs_al = exclusive_cumsum(-(-counts // a) * a)
    offs_w = jnp.minimum(offs_al, m_cap(ctx) - max_pad(ctx))
    return counts, offs, offs_al, offs_w


def assignment_dest(ctx: ma.MoEAllToAllContext, sorted_experts, offs, offs_al):
    """(peer (T,), dest (T,)): target rank and aligned payload row for
    each expert-sorted assignment.

    ``sorted_experts``: (T,) global expert id per sorted assignment;
    position t within its peer's dense segment is t - offs[peer]."""
    t = jnp.arange(sorted_experts.shape[0], dtype=jnp.int32)
    peer = (sorted_experts // ctx.experts_per_rank).astype(jnp.int32)
    peer = jnp.clip(peer, 0, ctx.n - 1)
    return peer, offs_al[peer] + (t - offs[peer])


def stage_aligned(ctx: ma.MoEAllToAllContext, x, src_row, dest, n_valid):
    """One-pass staging: gather rows of ``x`` into the aligned layout in
    the native wire dtype → ((m_cap, hidden) tokens, (m_cap,) f32 scales
    or None).

    ``src_row``: (T,) source row of x per assignment (T = M·topk);
    ``dest``: (T,) aligned payload row per assignment (from
    :func:`assignment_dest`); ``n_valid``: valid assignment count
    (assignments ≥ n_valid were clipped — none at standard routing).
    """
    cap = m_cap(ctx)
    inv = jnp.full((cap,), -1, jnp.int32).at[dest].set(
        jnp.where(jnp.arange(src_row.shape[0]) < n_valid, src_row, -1)
    )
    ok = inv >= 0
    rows = jnp.where(
        ok[:, None], x[jnp.clip(inv, 0, x.shape[0] - 1)], 0
    )
    if ctx.quant is None:
        return rows.astype(ctx.dtype), None
    q, scale = ma.quantize_rows(ctx, rows)
    return q, scale.astype(jnp.float32)


def _pack_scale_rows(ctx, scale2d):
    """(n, max_pad) f32 → (n, ceil(mp/128), 128) bitcast int32."""
    mp = max_pad(ctx)
    pad = -(-mp // META_W) * META_W - mp
    return jax.lax.bitcast_convert_type(
        jnp.pad(scale2d.astype(jnp.float32), ((0, 0), (0, pad))), jnp.int32
    ).reshape(ctx.n, -1, META_W)


def meta_payload(ctx: ma.MoEAllToAllContext, splits, scales, offs_al, offs_w):
    """(n, meta_rows, 128) int32 per-peer wire metadata:
    [epr counts, row shift][f32 scale bits for that peer's WINDOW rows].

    The shift (= offs_al - offs_w, nonzero for most peers under uniform
    routing — see aligned_offsets) tells the receiver where its segment
    begins inside the window; counts and shift share the first row
    block (epr + 1 ≤ 128·cnt_rows)."""
    spl = splits.reshape(ctx.n, ctx.experts_per_rank).astype(jnp.int32)
    cnt_rows = _cnt_rows(ctx)
    head = jnp.concatenate([spl, (offs_al - offs_w)[:, None]], axis=1)
    pad = cnt_rows * META_W - head.shape[1]
    parts = [jnp.pad(head, ((0, 0), (0, pad))).reshape(ctx.n, cnt_rows, META_W)]
    if ctx.quant is not None:
        mp = max_pad(ctx)
        j = jnp.arange(mp, dtype=jnp.int32)
        idx = offs_w[:, None] + j[None, :]       # window rows, not segment
        vals = scales[jnp.clip(idx, 0, scales.shape[0] - 1)]
        parts.append(_pack_scale_rows(ctx, vals))
    used = sum(p.shape[1] for p in parts)
    tail = meta_rows(ctx) - used
    if tail:
        parts.append(jnp.zeros((ctx.n, tail, META_W), jnp.int32))
    return jnp.concatenate(parts, axis=1)


def _parse_meta(ctx: ma.MoEAllToAllContext, meta):
    """(n·meta_rows, 128) int32 → ((n, epr) clamped counts, (n,) row
    shifts, (n, max_pad) f32 scales or None)."""
    mr = meta_rows(ctx)
    slots = meta.reshape(ctx.n, mr, META_W)
    cnt_rows = _cnt_rows(ctx)
    flat = slots[:, :cnt_rows].reshape(ctx.n, -1)
    rspl = ma.clamp_recv_splits(ctx, flat[:, : ctx.experts_per_rank])
    shift = flat[:, ctx.experts_per_rank]
    scales = None
    if ctx.quant is not None:
        mp = max_pad(ctx)
        sc = slots[:, cnt_rows:].reshape(ctx.n, -1)[:, :mp]
        scales = jax.lax.bitcast_convert_type(sc, jnp.float32)
    return rspl, shift, scales


def recv_view(ctx: ma.MoEAllToAllContext, recv_tok, recv_meta):
    """Receiver unpack: ((n, max_pad, H) dequantized ctx.dtype tokens,
    (n, epr) clamped counts, (n,) row shifts). Slot p's valid rows are
    [shift[p], shift[p] + counts[p].sum()) — senders clamp window
    starts routinely (see aligned_offsets), so shifts are the norm."""
    rspl, shift, scales = _parse_meta(ctx, recv_meta)
    toks = recv_tok.reshape(ctx.n, max_pad(ctx), ctx.hidden)
    if ctx.quant is not None:
        toks = ma.dequantize_rows(ctx, toks, scales)
    return toks.astype(ctx.dtype), rspl, shift


def stage_return(ctx: ma.MoEAllToAllContext, y):
    """(n, max_pad, H) processed slot rows → ((n·max_pad, H) wire-dtype
    tokens, (n, meta_rows, 128) int32 scale metadata) for the combine
    leg (quantized symmetrically with dispatch)."""
    mp = max_pad(ctx)
    if ctx.quant is None:
        toks = y.astype(ctx.dtype).reshape(ctx.n * mp, ctx.hidden)
        meta = jnp.zeros((ctx.n, meta_rows(ctx), META_W), jnp.int32)
        return toks, meta
    q, scale = ma.quantize_rows(ctx, y)            # scale: (n, mp)
    parts = [
        jnp.zeros((ctx.n, _cnt_rows(ctx), META_W), jnp.int32),
        _pack_scale_rows(ctx, scale),
    ]
    tail = meta_rows(ctx) - sum(p.shape[1] for p in parts)
    if tail:
        parts.append(jnp.zeros((ctx.n, tail, META_W), jnp.int32))
    return (
        q.reshape(ctx.n * mp, ctx.hidden),
        jnp.concatenate(parts, axis=1),
    )


def combine_view(ctx: ma.MoEAllToAllContext, comb_tok, comb_meta, peer, dest,
                 offs_w, n_valid):
    """Combine-leg unpack → (T, H) per-assignment rows in the original
    sorted order (dequantized), zeros for clipped assignments.

    Slot-regular: processed slot ``p`` comes back whole as slot ``p``,
    so assignment ``t`` (sent to peer ``p`` at WINDOW row
    ``dest[t] - offs_w[p]``) sits at slot ``p`` row
    ``dest[t] - offs_w[p]``."""
    mp = max_pad(ctx)
    _, _, scales = _parse_meta(ctx, comb_meta)
    toks = comb_tok.reshape(ctx.n, mp, ctx.hidden)
    if ctx.quant is not None:
        toks = ma.dequantize_rows(ctx, toks, scales)
    toks = toks.reshape(ctx.n * mp, ctx.hidden).astype(ctx.dtype)
    t = jnp.arange(dest.shape[0])
    row = peer * mp + dest - offs_w[peer]
    rows = toks[jnp.clip(row, 0, toks.shape[0] - 1)]
    return jnp.where((t < n_valid)[:, None], rows, 0)


# ------------------------------------------------------------- the kernel


def _window_a2a_kernel(
    n, axis, mesh_axes, a, mp, mr,
    offs_ref, payload_hbm, meta_hbm, recv_tok_hbm, recv_meta_hbm,
    send_sem, recv_sem, meta_send_sem, meta_recv_sem, local_sem,
):
    """Per-peer window push: peer ``p`` receives my payload window
    ``[offs[p]·a, offs[p]·a + mp)`` plus my metadata row-block for it,
    landing in its slot ``me`` of the two receive arrays. Serves both
    legs: dispatch (dynamic aligned segment offsets) and combine (static
    slot offsets). The recv DMA semaphores subsume the reference's
    call-count signal protocol (payload-then-flag ordering is a
    hardware guarantee).

    ``offs_ref`` holds offsets in units of ``a`` (the wire dtype's
    sublane tile): the multiply inside lets Mosaic PROVE the dynamic
    slice start is tile-aligned."""
    me = lang.my_pe(axis)

    # self-slot: plain local HBM→HBM copies (no peer dependency)
    cp = pltpu.make_async_copy(
        payload_hbm.at[pl.ds(offs_ref[me] * a, mp)],
        recv_tok_hbm.at[pl.ds(me * mp, mp)],
        local_sem,
    )
    cp.start()
    cpm = pltpu.make_async_copy(
        meta_hbm.at[pl.ds(me * mr, mr)],
        recv_meta_hbm.at[pl.ds(me * mr, mr)],
        local_sem,
    )
    cpm.start()

    if n > 1:
        lang.barrier_all(axis, mesh_axes)

    handles = []
    for i in range(n - 1):
        pi = jax.lax.rem(me + 1 + i, n)
        peer = lang.pe_flat(axis, pi, mesh_axes)
        chaos_delay()
        handles.append(lang.putmem_signal_nbi_block(
            recv_tok_hbm.at[pl.ds(me * mp, mp)],          # peer slot `me`
            payload_hbm.at[pl.ds(offs_ref[pi] * a, mp)],  # my window for pi
            send_sem.at[i],
            recv_sem.at[i],
            peer,
        ))
        handles.append(lang.putmem_signal_nbi_block(
            recv_meta_hbm.at[pl.ds(me * mr, mr)],
            meta_hbm.at[pl.ds(pi * mr, mr)],
            meta_send_sem.at[i],
            meta_recv_sem.at[i],
            peer,
        ))
    lang.quiet(*handles)
    for h in handles:
        h.wait_recv()
    cp.wait()
    cpm.wait()


@functools.lru_cache(maxsize=64)
def _build_window_a2a_call(mesh_axes, axis, n, a, mp, mr, cap, hidden,
                           wire_dtype, collective_id, ikey):
    """Bare per-device window-a2a pallas_call (composable inside any
    shard_map, like all_to_all.all_to_all_device)."""
    return lang.shmem_call(
        functools.partial(
            _window_a2a_kernel, n, axis, mesh_axes, a, mp, mr
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n * mp, hidden), wire_dtype),
            jax.ShapeDtypeStruct((n * mr, META_W), jnp.int32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA,
        ],
        # n==1 skips barrier_all; Mosaic rejects an unused collective_id
        collective_id=collective_id if n > 1 else None,
        name="moe_window_a2a",
    )


def dispatch_device(ctx: ma.MoEAllToAllContext, payload, offs_w, meta_pl):
    """Per-device fused dispatch (inside any shard_map over ctx.mesh):
    ``payload`` (m_cap, hidden) wire dtype aligned segments; ``offs_w``
    (n,) int32 clamped WINDOW offsets (from :func:`aligned_offsets`);
    ``meta_pl`` (n, meta_rows, 128) int32 from :func:`meta_payload`.
    Returns (recv_tok (n·max_pad, hidden), recv_meta (n·meta_rows, 128))
    for :func:`recv_view`."""
    a = align(ctx)
    call = _build_window_a2a_call(
        ctx.mesh.axis_names, ctx.axis, ctx.n, a, max_pad(ctx),
        meta_rows(ctx), m_cap(ctx), ctx.hidden, ctx.wire_dtype,
        ctx.collective_id, interp_key(),
    )
    return call(
        (offs_w // a).astype(jnp.int32),
        payload,
        meta_pl.reshape(ctx.n * meta_rows(ctx), META_W),
    )


def combine_device(ctx: ma.MoEAllToAllContext, y_tok, y_meta):
    """Per-device combine: the same window kernel with STATIC slot
    offsets (slot p returns whole to source p). ``y_tok``
    (n·max_pad, hidden) wire dtype; ``y_meta`` (n, meta_rows, 128)."""
    a = align(ctx)
    mp = max_pad(ctx)
    call = _build_window_a2a_call(
        ctx.mesh.axis_names, ctx.axis, ctx.n, a, mp, meta_rows(ctx),
        ctx.n * mp, ctx.hidden, ctx.wire_dtype,
        ctx.collective_id + 1, interp_key(),
    )
    slot_offs = (jnp.arange(ctx.n, dtype=jnp.int32) * mp) // a
    return call(
        slot_offs, y_tok, y_meta.reshape(ctx.n * meta_rows(ctx), META_W)
    )
