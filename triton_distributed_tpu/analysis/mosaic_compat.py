"""Mosaic-compat pre-flight: seconds-fast compile-shaped coverage.

The only static check that the SHMEM kernels actually *lower* on this
toolchain used to be ``tests/test_aot_topology.py`` — a full XLA+Mosaic
compile against an unattached v5e topology whose module fixture alone
cost ~8 minutes of the tier-1 budget (it is ``slow``-marked since
round 6, leaving tier-1 with zero Mosaic-lowering coverage). This
module restores a cheap approximation: every registry family is built
exactly as it would be FOR HARDWARE (``config.force_compile`` — the
strict divisor/blocking paths, the in-kernel wire contracts), its
``pallas_call`` is traced to a kernel jaxpr on CPU (tracing runs no
platform code — an abstract mesh suffices), and the jaxpr is scanned
for the constructs this toolchain's Mosaic backend is KNOWN to reject:

* **MC001** — f8 casts inside the kernel (``arith.extf f8E4M3FN →
  f32``: "Only 16-bit to 32-bit extensions supported"; the finding the
  AOT suite catches at minute 8, here at second 2);
* **MC002** — collapsing a loaded ``(1, 1)`` float vector to a scalar
  (the ``vector.shape_cast 1x1 → scalar`` Mosaic rejects — the reason
  lang.wire keeps lane-replicated ``(1, 128)`` scale rows);
* **MC003** — broadcasting a sub-byte (4-bit) vector;
* **MC004** — a dot over 1-byte operands with an unsupported
  accumulator form. The int8→MXU consumers (ag_gemm/moe_tp
  ``wire_dtype='int8-mxu'``) ride the NATIVE s8×s8→s32 path — proven
  on this toolchain by the W8A8 grouped GEMM running on chip
  (kernels/group_gemm, round 5) and re-verified by this pre-flight's
  force-compile scan of those families; what Mosaic rejects is asking
  the MXU for a FLOAT accumulate of int8 operands, or any fp8 dot
  (no f8 MXU form here, see MC001). A family whose builder refuses
  cleanly under ``lang.wire.require_mxu`` (TDTPU_WIRE_INT8_MXU=0) is a
  pass — the contract fires before Mosaic ever would, mirroring the
  MC001 fp8 handling;
* **MC006** — a gather with traced (runtime) indices: no dynamic
  vector-indexed gather lowering here — the reason the ragged
  kernel's tree-topology mask is a STATIC per-position
  ancestor-bitmask unroll rather than an ``anc[par]`` index chase.

A family whose builder REFUSES cleanly under the hardware contract
(``require_inkernel`` raising for a pinned fp8 wire) is a pass: the
contract fires before Mosaic ever would, which is the designed
behavior. What this does NOT prove: full backend legality (layouts,
alignment, semaphore rules) — that remains the nightly/slow AOT
suite's job. The scan is a deny-list of known-rejected constructs, not
an emulation of the Mosaic verifier.

CLI::

    python -m triton_distributed_tpu.analysis.mosaic_compat
        [--mesh 8] [--kernel SUBSTR] [--json]
"""

from __future__ import annotations

import contextlib
import itertools

from triton_distributed_tpu.analysis.findings import Finding

_TOKENS = itertools.count()

#: substrings of the canonical clean-refusal diagnostics
#: (lang.wire.require_inkernel / require_mxu) — a build that raises one
#: never reaches Mosaic, so there is nothing to scan and nothing to
#: flag.
_CLEAN_REFUSALS = ("in-kernel f8", "in-kernel s8")


@contextlib.contextmanager
def _force_compile():
    """Build for HARDWARE (strict Mosaic paths) from this CPU process.
    Builders key their caches on explicit tokens here, so flipping the
    knob cannot leak stale builds into other callers."""
    from triton_distributed_tpu.config import config

    old = config.force_compile
    config.force_compile = True
    try:
        yield
    finally:
        config.force_compile = old


def _is_f8(dtype) -> bool:
    return "float8" in str(dtype)


def _is_subbyte(dtype) -> bool:
    s = str(dtype)
    return ("int4" in s) or ("float4" in s) or ("int2" in s)


def _walk_jaxprs(jaxpr):
    """Yield every eqn of a jaxpr and (recursively) of the sub-jaxprs
    carried in eqn params (scan/while/cond bodies, pipeline loops)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is None and hasattr(v, "eqns"):
                inner = v
            if inner is not None and not hasattr(inner, "eqns"):
                inner = getattr(inner, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield from _walk_jaxprs(inner)


def _kernel_jaxprs(jaxpr):
    """The pallas_call kernel jaxprs reachable from an outer jaxpr —
    the scan looks ONLY inside them (host-side XLA ops may legally use
    every construct Mosaic lacks, e.g. the XLA-side fp8 quantize)."""
    out = []
    for eqn in _walk_jaxprs(jaxpr):
        if eqn.primitive.name == "pallas_call":
            kj = eqn.params.get("jaxpr")
            if kj is not None:
                out.append(kj)
    return out


def scan_kernel_jaxpr(kjaxpr, kernel_name, site=None) -> list:
    """MC001–MC006 over one kernel jaxpr."""
    findings = []
    seen = set()

    def add(rule, msg):
        if (rule, msg) not in seen:
            seen.add((rule, msg))
            findings.append(Finding(rule, kernel_name, msg, site=site))

    for eqn in _walk_jaxprs(kjaxpr):
        name = eqn.primitive.name
        if name == "convert_element_type" and eqn.invars and eqn.outvars:
            src = getattr(eqn.invars[0].aval, "dtype", None)
            dst = getattr(eqn.outvars[0].aval, "dtype", None)
            if src is not None and (_is_f8(src) or _is_f8(dst)):
                add("MC001",
                    f"in-kernel cast {src} -> {dst}: this Mosaic rejects "
                    "f8 extensions ('Only 16-bit to 32-bit extensions "
                    "supported') — carry int8 in-kernel or keep fp8 on "
                    "the XLA engines (lang.wire.inkernel_wire_ok)")
        elif name in ("reshape", "squeeze") and eqn.invars and eqn.outvars:
            ia = eqn.invars[0].aval
            oa = eqn.outvars[0].aval
            ishape = getattr(ia, "shape", None)
            oshape = getattr(oa, "shape", None)
            if (
                ishape and len(ishape) >= 2 and all(d == 1 for d in ishape)
                and oshape == ()
                and "float" in str(getattr(ia, "dtype", ""))
            ):
                add("MC002",
                    f"{tuple(ishape)} float vector collapsed to a scalar "
                    "in-kernel: Mosaic rejects the vector<1x1> -> scalar "
                    "shape_cast — keep a (1, lanes) row and broadcast "
                    "(the lang.wire scale-plane idiom)")
            elif (
                ishape is not None and oshape is not None
                and len(ishape) >= 2 and len(oshape) >= 2
                and ishape[-1] != oshape[-1]
                and ishape[-1] > 1 and oshape[-1] > 1
            ):
                # MC005: a reshape that CHANGES the lane (minor)
                # dimension between two >1-lane vectors — this
                # Mosaic's vector shape_cast cannot re-lay lanes (the
                # construct a naive (T, G·D) → (T·G, D) GQA-row
                # flatten produces; the ragged kernel's head-major
                # packing exists to avoid it). Unit-collapse reshapes
                # (lane dim kept) are the supported form and pass.
                add("MC005",
                    f"in-kernel reshape {tuple(ishape)} -> "
                    f"{tuple(oshape)} changes the lane (minor) "
                    "dimension: this Mosaic's vector shape_cast cannot "
                    "re-lay lanes — restructure the buffer so the lane "
                    "dim survives (e.g. the head-major (Hkv, T*G, D) "
                    "GQA-rows packing of kernels/"
                    "ragged_paged_attention) or reshape on the XLA "
                    "side")
        elif name == "broadcast_in_dim" and eqn.outvars:
            dt = getattr(eqn.outvars[0].aval, "dtype", None)
            if dt is not None and _is_subbyte(dt):
                add("MC003",
                    f"in-kernel broadcast of sub-byte dtype {dt}: this "
                    "Mosaic backend has no sub-byte broadcast layout — "
                    "widen to int8 first")
        elif name == "dot_general" and len(eqn.invars) >= 2 and eqn.outvars:
            dts = [getattr(v.aval, "dtype", None) for v in eqn.invars[:2]]
            out_dt = getattr(eqn.outvars[0].aval, "dtype", None)
            onebyte = [
                d for d in dts
                if d is not None and getattr(d, "itemsize", 0) == 1
            ]
            if len(onebyte) == 2:
                if any(_is_f8(d) for d in onebyte):
                    add("MC004",
                        f"in-kernel dot over fp8 operands ({dts[0]} x "
                        f"{dts[1]}): this Mosaic has no f8 MXU form — "
                        "carry int8 (the s8*s8->s32 path) or keep fp8 "
                        "on the XLA engines")
                elif "int32" not in str(out_dt):
                    add("MC004",
                        f"in-kernel s8 dot accumulating to {out_dt}: "
                        "Mosaic lowers int8 dots only on the native "
                        "s8*s8->s32 path — set preferred_element_type="
                        "int32 and fold the scales on the accumulator "
                        "in the epilogue (the lang.wire int8-mxu "
                        "contract)")
        elif name == "gather" and len(eqn.invars) >= 2:
            # MC006: a gather whose index operand is a TRACED value
            # (a Var, not a Literal constant) — dynamic vector-indexed
            # gathers have no lowering on this Mosaic backend. The
            # construct a naive topology-mask build produces
            # (anc[par[q]] with runtime par): the ragged kernel's
            # static per-position ancestor-bitmask unroll exists to
            # avoid it. Constant-index gathers fold at trace time and
            # pass.
            idx = eqn.invars[1]
            if not hasattr(idx, "val"):        # jax.core.Literal has .val
                ishape = getattr(idx.aval, "shape", ())
                add("MC006",
                    f"in-kernel gather with traced indices (index "
                    f"shape {tuple(ishape)}): this Mosaic has no "
                    "dynamic vector-indexed gather lowering — unroll "
                    "over the index set with static masks (the ragged "
                    "kernel's ancestor-bitmask unroll) or gather on "
                    "the XLA side")
        elif name == "dynamic_slice" and len(eqn.invars) >= 2:
            # MC007: a dynamic_slice whose start index on the SUBLANE
            # (second-minor) dimension is a TRACED value while the
            # slice is proper on that dimension — this Mosaic can only
            # fold dynamic sublane offsets that are compile-time
            # constants (traced LANE offsets and full-size sublane
            # "slices" at a traced zero both lower fine). Promoted
            # from the nightly slow run's jaxpr signature so the
            # 8-minute finding is a 2-second one.
            op = eqn.invars[0]
            oshape = getattr(op.aval, "shape", ())
            sizes = tuple(eqn.params.get("slice_sizes", ()))
            if (len(oshape) >= 2
                    and len(eqn.invars) == 1 + len(oshape)
                    and len(sizes) == len(oshape)
                    and sizes[-2] != oshape[-2]):
                sub = eqn.invars[1 + len(oshape) - 2]
                if not hasattr(sub, "val"):   # Literal has .val
                    add("MC007",
                        f"in-kernel dynamic_slice of {tuple(oshape)} "
                        f"with a traced start index on the sublane "
                        f"(second-minor) dimension (slice_sizes="
                        f"{sizes}): this Mosaic only folds constant "
                        "sublane offsets — unroll over the candidate "
                        "offsets with static masks or hoist the slice "
                        "to the XLA side")
    return findings


def i8_to_float_casts(kjaxpr) -> list:
    """Every in-kernel ``convert_element_type`` that widens an int8
    array to a float type — the signature of a per-arrival DEQUANT
    pass. The int8→MXU acceptance check (tests/test_wire.py) asserts
    this list is EMPTY for the ``*_int8mxw`` families' traced kernels:
    their wire ends at the s8×s8 dot, whose only float conversion is
    the s32 accumulator's epilogue widening."""
    out = []
    for eqn in _walk_jaxprs(kjaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        if not (eqn.invars and eqn.outvars):
            continue
        src = getattr(eqn.invars[0].aval, "dtype", None)
        dst = getattr(eqn.outvars[0].aval, "dtype", None)
        if (src is not None and dst is not None
                and "int8" in str(src) and "float" in str(dst)):
            out.append((str(src), str(dst),
                        tuple(getattr(eqn.invars[0].aval, "shape", ()))))
    return out


# ------------------------------------------------------------------ tracing

def trace_spec(spec, in_shapes, n, *, mesh=None, axis="x"):
    """Trace one LaunchSpec's pallas_call to a jaxpr on an abstract
    n-rank mesh. Nothing executes and no TPU platform code runs —
    tracing only stages the kernel body out, which is exactly the input
    of the Python-side Mosaic lowering."""
    import jax
    from jax.experimental import pallas as pl
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.analysis.lint import lint_mesh

    mesh = mesh if mesh is not None else lint_mesh(n, axis)
    kw = {}
    scratch = list(spec.scratch_shapes)
    if getattr(spec, "grid_spec", None) is not None:
        # scalar-prefetch families (PrefetchScalarGridSpec): re-invoke
        # with the captured spec object — it already carries the
        # scratch (the capture mirrors it into spec.scratch_shapes for
        # the abstract evaluator), and in_shapes lists the scalar-
        # prefetch operands FIRST, exactly the call convention
        kw["grid_spec"] = spec.grid_spec
        scratch = []
    else:
        if spec.grid is not None:
            kw["grid"] = spec.grid
        if spec.in_specs is not None:
            kw["in_specs"] = spec.in_specs
        if spec.out_specs is not None:
            kw["out_specs"] = spec.out_specs
    call = pl.pallas_call(
        spec.kernel,
        out_shape=spec.out_shape,
        scratch_shapes=scratch,
        interpret=False,
        **kw,
    )
    nout = len(jax.tree.leaves(jax.eval_shape(lambda: spec.out_shape)))
    avals = [jax.ShapeDtypeStruct(s, d) for s, d in in_shapes]
    wrapped = jax.shard_map(
        lambda *a: jax.tree.leaves(call(*a)),
        mesh=mesh,
        in_specs=tuple(P() for _ in avals),
        out_specs=[P()] * nout,
        check_vma=False,
    )
    return jax.make_jaxpr(wrapped)(*avals)


def preflight_spec(spec, in_shapes, n, *, kernel_name, site=None,
                   axis="x") -> list:
    """Trace one spec under the hardware config and scan it."""
    with _force_compile():
        jaxpr = trace_spec(spec, in_shapes, n, axis=axis)
    findings = []
    for kj in _kernel_jaxprs(jaxpr.jaxpr):
        findings += scan_kernel_jaxpr(kj, kernel_name, site=site)
    return findings


def trace_family_kernels(fam, n: int = 8) -> list:
    """Build one registry family FOR HARDWARE and return its traced
    kernel jaxprs — the raw material of the deny-list scan, and of
    ad-hoc jaxpr assertions in tests (e.g. the int8→MXU acceptance
    check that no per-arrival dequant pass exists in the traced
    kernel). Raises the builder's clean-refusal ValueError through."""
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.analysis.lint import lint_mesh

    with _force_compile():
        mesh = lint_mesh(n, fam.axis)
        fam.build(mesh, n, ("mosaic_compat", next(_TOKENS)))
        spec = captured_launch(fam.launch_name)
        if spec is None:
            raise RuntimeError(
                f"family {fam.name!r}: builder did not construct a "
                f"shmem_call named {fam.launch_name!r}"
            )
        jaxpr = trace_spec(spec, fam.in_shapes(n), n, mesh=mesh,
                           axis=fam.axis)
    return _kernel_jaxprs(jaxpr.jaxpr)


def preflight_family(fam, n: int = 8):
    """Build one registry family FOR HARDWARE and scan its kernel.
    Returns (status, findings): status 'scanned', or 'refused' when the
    builder raised a canonical pinned-wire contract error (a pass —
    the contract fires before Mosaic ever would)."""
    try:
        kernel_jaxprs = trace_family_kernels(fam, n)
    except ValueError as e:
        if any(s in str(e) for s in _CLEAN_REFUSALS):
            return "refused", []
        raise
    findings = []
    for kj in kernel_jaxprs:
        findings += scan_kernel_jaxpr(kj, fam.name, site=fam.site)
    return "scanned", findings


def preflight_all(n: int = 8, kernels=None):
    """Pre-flight every registry family (optionally filtered by name
    substrings). Returns (findings, report) where report maps
    'scanned'/'refused' to the family-name lists."""
    from triton_distributed_tpu.kernels.registry import families

    fams = families()
    if kernels:
        fams = {
            name: f for name, f in fams.items()
            if any(k in name for k in kernels)
        }
        if not fams:
            raise ValueError(f"no registered kernel matches {kernels}")
    findings = []
    report = {"scanned": [], "refused": []}
    for name in sorted(fams):
        status, f = preflight_family(fams[name], n)
        report[status].append(name)
        findings += f
    return findings, report


# ---------------------------------------------------------------------- CLI

def main(argv=None) -> int:
    import argparse
    import json
    import sys

    from triton_distributed_tpu.analysis.findings import (
        SCHEMA_VERSION,
        Severity,
        rule_counts,
    )

    ap = argparse.ArgumentParser(
        prog="python -m triton_distributed_tpu.analysis.mosaic_compat",
        description="Mosaic-compat pre-flight: trace each registered "
        "kernel family's jaxpr (built for hardware) and scan for "
        "constructs this toolchain's Mosaic backend rejects "
        "(MC001-MC004)",
    )
    ap.add_argument("--mesh", type=int, default=8, metavar="N")
    ap.add_argument("--kernel", action="append", default=None,
                    metavar="SUBSTR")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.mesh < 2:
        ap.error("--mesh must be >= 2")

    findings, report = preflight_all(n=args.mesh, kernels=args.kernel)
    errs = sum(f.severity >= Severity.ERROR for f in findings)
    if args.json:
        print(json.dumps({
            "schema_version": SCHEMA_VERSION, "mesh": args.mesh,
            "scanned": report["scanned"], "refused": report["refused"],
        }))
        for f in findings:
            print(json.dumps(f.to_json()))
        print(json.dumps({
            "schema_version": SCHEMA_VERSION,
            "rule_counts": rule_counts(findings), "errors": errs,
        }))
    else:
        for f in findings:
            print(f.format())
        print(
            f"mosaic-compat: {len(report['scanned'])} kernel families "
            f"scanned, {len(report['refused'])} refused cleanly under "
            f"the hardware wire contract: {errs} error(s)",
            file=sys.stderr,
        )
    return 1 if errs else 0


if __name__ == "__main__":
    raise SystemExit(main())
