"""Deliberately broken SHMEM kernels — one per shmemlint rule.

These exist so every rule is pinned by a real kernel body forever, and
specifically to close the caveat ``tests/test_races.py`` documents: the
TPU interpreter's dynamic race detector has MISSED a deliberately
removed wait under ``dma_execution_mode="on_wait"``. The
:func:`missing_wait` fixture is exactly that bug, and
``tests/test_analysis.py`` asserts shmemlint flags it (SL001) with
rank + semaphore diagnostics — statically, on any jax, no interpreter
required.

Each fixture returns a hand-built
:class:`~triton_distributed_tpu.lang.launch.LaunchSpec` plus the
per-device input shapes, ready for
:func:`triton_distributed_tpu.analysis.lint.analyze_spec`.
"""

from __future__ import annotations

import numpy as np

from triton_distributed_tpu import lang
from triton_distributed_tpu.lang import wire as wirelib
from triton_distributed_tpu.lang.launch import LaunchSpec

_F32 = np.dtype(np.float32)


def _f8():
    import ml_dtypes

    return np.dtype(ml_dtypes.float8_e4m3fn)


def _spec(kernel, name, out_shapes=(), scratch=(), collective_id=None,
          vmem_limit_bytes=None):
    import jax

    return LaunchSpec(
        name=name,
        kernel=kernel,
        out_shape=[jax.ShapeDtypeStruct(s, d) for s, d in out_shapes],
        in_specs=None,
        out_specs=None,
        scratch_shapes=tuple(scratch),
        collective_id=collective_id,
        vmem_limit_bytes=vmem_limit_bytes,
    )


def _sems(*shapes):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.SemaphoreType.DMA(s) if s else pltpu.SemaphoreType.REGULAR(())
            for s in shapes]


def missing_wait(axis="x"):
    """The test_races caveat, seeded: every rank pushes its shard to
    every peer and signals arrival, but the consuming
    ``signal_wait_until`` was "forgotten" — the kernel reads the
    gathered buffer with nothing ordering the landings. Dynamically
    this is a probabilistic wrong-answer; statically it is SL001
    (unconsumed flag credits) + SL004 (unordered landing vs the read).
    """

    def kernel(x_ref, out_ref, chk_ref, send_sem, recv_sem, flag_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        handles = []
        for i in range(n - 1):
            peer = (me + 1 + i) % n
            handles.append(lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],
                x_ref,
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            ))
            lang.signal_op(flag_sem, 1, pe=peer, site="fixture")
        lang.quiet(*handles)
        # BUG: no `for i in range(n-1): lang.signal_wait_until(flag_sem, 1)`
        # and no recv waits — the landings are unordered with this read:
        chk_ref[0, 0] = jnp.sum(out_ref[:])

    return (
        _spec(
            kernel, "fixture_missing_wait",
            out_shapes=[((8 * 8, 128), _F32), ((1, 1), _F32)],
            scratch=_sems((8,), (8,), None),
            collective_id=40,
        ),
        lambda n: [((8, 128), _F32)],
    )


def credit_imbalance(axis="x"):
    """Off-by-one credit accounting: each rank sends ONE barrier credit
    (to its right neighbor) but waits for TWO — the classic symptom
    that today only shows up as a hang the watchdog must catch. SL002.
    """

    def kernel(x_ref, out_ref, sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        lang.signal_op(sem, 1, pe=(me + 1) % n, site="fixture")
        lang.signal_wait_until(sem, 2)     # BUG: only 1 credit ever comes
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_credit_imbalance",
            out_shapes=[((8, 128), _F32)],
            scratch=_sems(None),
            collective_id=41,
        ),
        lambda n: [((8, 128), _F32)],
    )


def deadlock(axis="x"):
    """Wait-before-signal around the ring: every rank parks in a wait
    whose credit is behind the next rank's identical wait. SL003 with
    the full rank cycle."""

    def kernel(x_ref, out_ref, sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        lang.signal_wait_until(sem, 1)     # BUG: nobody signals first
        lang.signal_op(sem, 1, pe=(me + 1) % n, site="fixture")
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_deadlock",
            out_shapes=[((8, 128), _F32)],
            scratch=_sems(None),
            collective_id=42,
        ),
        lambda n: [((8, 128), _F32)],
    )


def barrier_mismatch(axis="x"):
    """Rank 0 runs an extra ``barrier_all`` the other ranks don't —
    diverging collective sequences across ranks. SL005 (and the missing
    peers make the extra barrier an SL002 hang)."""

    def kernel(x_ref, out_ref):
        me = lang.my_pe(axis)
        lang.barrier_all(axis)
        if me == 0:                        # BUG: rank-dependent barrier
            lang.barrier_all(axis)
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_barrier_mismatch",
            out_shapes=[((8, 128), _F32)],
            collective_id=43,
        ),
        lambda n: [((8, 128), _F32)],
    )


def undrained_dma(axis="x"):
    """Puts whose local completion is never drained (missing ``quiet``/
    ``wait_send``) — the kernel can exit with transfers in flight.
    SL007."""

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]
        from jax.experimental import pallas as pl

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        handles = []
        for i in range(n - 1):
            peer = (me + 1 + i) % n
            handles.append(lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)], x_ref,
                send_sem.at[i], recv_sem.at[i], peer,
            ))
        for h in handles:
            h.wait_recv()
        # BUG: no lang.quiet(*handles) — send semaphores never drained

    return (
        _spec(
            kernel, "fixture_undrained_dma",
            out_shapes=[((8 * 8, 128), _F32)],
            scratch=_sems((8,), (8,)),
            collective_id=44,
        ),
        lambda n: [((8, 128), _F32)],
    )


def vmem_overcommit(axis="x"):
    """VMEM working set exceeding the launch's declared budget. SL006."""

    def kernel(x_ref, out_ref, big_ref, sem):
        out_ref[:] = x_ref[:]
        lang.signal_op(sem, 1, site="fixture")
        lang.signal_wait_until(sem, 1)

    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    return (
        _spec(
            kernel, "fixture_vmem_overcommit",
            out_shapes=[((8, 128), _F32)],
            scratch=[pltpu.VMEM((64, 128), jnp.float32)] + _sems(None),
            collective_id=None,
            vmem_limit_bytes=16 * 1024,   # 16 KiB budget vs ~40 KiB set
        ),
        lambda n: [((8, 128), _F32)],
    )


def skipped_chunk(axis="x"):
    """An AG ring one hop SHORT (``range(n - 2)`` instead of
    ``n - 1``): every semaphore balances — each step is a matched
    start/wait pair — but each rank terminates missing exactly one
    source's chunk. Undetectable by the protocol rules by construction;
    SL008 against the declared gather contract."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        import jax
        from jax.experimental import pallas as pl

        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        for s in range(n - 2):             # BUG: one ring hop short
            src = jax.lax.rem(me + n - s, n) if s > 0 else me
            dma = lang.remote_copy(
                out_ref.at[pl.ds(src * m, m)],
                out_ref.at[pl.ds(src * m, m)],
                send_sem.at[s], recv_sem.at[s], (me + 1) % n,
            )
            dma.start()
            dma.wait()

    return (
        _spec(
            kernel, "fixture_skipped_chunk",
            out_shapes=[((8 * 8, 128), _F32)],
            scratch=_sems((8,), (8,)),
            collective_id=46,
        ),
        lambda n: [((8, 128), _F32)],
        DeliveryContract(kind="gather", dst="out_ref"),
    )


def dup_chunk(axis="x"):
    """A correct LL-push allgather followed by rank 0 RE-delivering its
    shard into slot 1 on every peer — the duplicate overwrites source
    1's chunk. Every semaphore balances (the dup arrivals are waited),
    every landing is barrier-ordered; the data is still wrong: source 0
    held twice, source 1 lost. SL008."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract

    def kernel(x_ref, out_ref, send_sem, recv_sem, dsend_sem, drecv_sem):
        from jax.experimental import pallas as pl

        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        handles = []
        for i in range(n - 1):
            peer = (me + 1 + i) % n
            handles.append(lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)], x_ref,
                send_sem.at[i], recv_sem.at[i], peer,
            ))
        lang.quiet(*handles)
        for h in handles:
            h.wait_recv()
        lang.barrier_all(axis)
        if me == 0:
            # BUG: shard 0 delivered AGAIN, into slot 1, on every peer
            dups = [
                lang.putmem_signal_nbi_block(
                    out_ref.at[pl.ds(1 * m, m)], x_ref,
                    dsend_sem.at[i], drecv_sem.at[i], i + 1,
                )
                for i in range(n - 1)
            ]
            lang.quiet(*dups)
        else:
            lang.signal_wait_until(drecv_sem.at[me - 1], 1)

    return (
        _spec(
            kernel, "fixture_dup_chunk",
            out_shapes=[((8 * 8, 128), _F32)],
            scratch=_sems((8,), (8,), (8,), (8,)),
            collective_id=47,
        ),
        lambda n: [((8, 128), _F32)],
        DeliveryContract(kind="gather", dst="out_ref"),
    )


def scale_on_payload_sem(axis="x"):
    """A quantized one-hop wire whose scale rail is signaled on the
    PAYLOAD's recv semaphore. The credits balance (the receiver waits
    twice), but credits count — they don't tag: the payload wait can be
    released by the scale arrival while the 1-byte slab is still in
    flight. SL009."""

    def kernel(x_ref, xq_ref, xs_ref, out_ref, outq_ref, outs_ref,
               send_sem, recv_sem, s_send_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)

        lang.barrier_all(axis)
        peer = (me + 1) % n
        dq = lang.remote_copy(
            xq_ref, outq_ref, send_sem.at[0], recv_sem.at[0], peer
        )
        # BUG: the scale rail rides the payload's recv semaphore
        dsc = lang.remote_copy(
            xs_ref, outs_ref, s_send_sem.at[0], recv_sem.at[0], peer
        )
        dq.start()
        dsc.start()
        dq.wait()
        dsc.wait_send()
        lang.signal_wait_until(recv_sem.at[0], 1)   # the second credit
        wirelib.dequant_rows_into(out_ref, outq_ref, outs_ref)

    return (
        _spec(
            kernel, "fixture_scale_on_payload_sem",
            out_shapes=[((8, 2048), _F32), ((8, 2048), _f8()),
                        ((8, 128), _F32)],
            scratch=_sems((1,), (1,), (1,)),
            collective_id=48,
        ),
        lambda n: [((8, 2048), _F32), ((8, 2048), _f8()),
                   ((8, 128), _F32)],
        None,
    )


def stale_scale(axis="x"):
    """Two correctly-railed quantized hops into a double-buffered
    workspace; the receiver then dequantizes slot 0's payload with slot
    1's scale plane. Protocol-clean, rails paired, values silently
    wrong. SL010."""

    def kernel(x_ref, out_ref, qbuf_ref, sbuf_ref, recvq_ref, recvs_ref,
               send_sem, recv_sem, s_send_sem, s_recv_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)

        lang.barrier_all(axis)
        peer = (me + 1) % n
        for slot in range(2):
            wirelib.quant_rows_into(
                qbuf_ref.at[slot], sbuf_ref.at[slot], x_ref, "fp8"
            )
            dq = lang.remote_copy(
                qbuf_ref.at[slot], recvq_ref.at[slot],
                send_sem.at[slot], recv_sem.at[slot], peer,
            )
            dsc = lang.remote_copy(
                sbuf_ref.at[slot], recvs_ref.at[slot],
                s_send_sem.at[slot], s_recv_sem.at[slot], peer,
            )
            dq.start()
            dsc.start()
            dq.wait()
            dsc.wait()
        # BUG: slot 0's bytes, slot 1's scales
        wirelib.dequant_rows_into(
            out_ref, recvq_ref.at[0], recvs_ref.at[1]
        )

    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp
    import ml_dtypes

    f8 = jnp.dtype(ml_dtypes.float8_e4m3fn)
    return (
        _spec(
            kernel, "fixture_stale_scale",
            out_shapes=[((8, 2048), _F32)],
            scratch=[
                pltpu.VMEM((2, 8, 2048), f8),            # qbuf
                pltpu.VMEM((2, 8, 128), jnp.float32),    # sbuf
                pltpu.VMEM((2, 8, 2048), f8),            # recvq
                pltpu.VMEM((2, 8, 128), jnp.float32),    # recvs
            ] + _sems((2,), (2,), (2,), (2,)),
            collective_id=49,
        ),
        lambda n: [((8, 2048), _F32)],
        None,
    )


def scale_fold_omitted(axis="x"):
    """An int8→MXU consumer whose epilogue NEVER folds the scale: the
    rails are correctly paired (payload + scale plane on their own
    semaphores — the SL009 structural legs stay silent), every
    semaphore balances, but the arriving s8 slab is fed to the MXU and
    stored without its chunk-scale rescale. The values are silently off
    by the quantization scale. SL009 (scale-fold omitted), with rank +
    site diagnostics."""

    def kernel(xq_ref, xs_ref, out_ref, outq_ref, outs_ref,
               send_sem, recv_sem, s_send_sem, s_recv_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)

        lang.barrier_all(axis)
        peer = (me + 1) % n
        dq = lang.remote_copy(
            xq_ref, outq_ref, send_sem.at[0], recv_sem.at[0], peer
        )
        dsc = lang.remote_copy(
            xs_ref, outs_ref, s_send_sem.at[0], s_recv_sem.at[0], peer
        )
        dq.start()
        dsc.start()
        dq.wait()
        dsc.wait()
        # BUG: the s8×s8 pipeline consumes the payload with NO scale
        # plane — the epilogue stores the unrescaled accumulator
        wirelib.epilogue_consume(outq_ref, None, out_ref)

    return (
        _spec(
            kernel, "fixture_scale_fold_omitted",
            out_shapes=[((8, 128), _F32), ((8, 2048), np.dtype(np.int8)),
                        ((8, 128), _F32)],
            scratch=_sems((1,), (1,), (1,), (1,)),
            collective_id=50,
        ),
        lambda n: [((8, 2048), np.dtype(np.int8)), ((8, 128), _F32)],
        None,
    )


def serialized_ring(axis="x"):
    """A gather ring that runs ``n`` hops instead of ``n-1`` — every
    chunk is still delivered exactly once everywhere (the extra lap
    re-delivers each rank's OWN shard on top of its already-correct
    local copy), every semaphore balances, SL008 is clean... but the
    deepest delivery chain is now ``n`` sequential hops. The hop
    counters the replay tracks expose the detour and the perf model
    prices it: SL011."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        import jax
        from jax.experimental import pallas as pl

        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        for s in range(n):                 # BUG: one lap too many
            src = jax.lax.rem(me + n - s, n) if s > 0 else me
            dma = lang.remote_copy(
                out_ref.at[pl.ds(src * m, m)],
                out_ref.at[pl.ds(src * m, m)],
                send_sem.at[s], recv_sem.at[s], (me + 1) % n,
            )
            dma.start()
            dma.wait()

    return (
        _spec(
            kernel, "fixture_serialized_ring",
            out_shapes=[((8 * 8, 128), _F32)],
            scratch=_sems((8,), (8,)),
            collective_id=51,
        ),
        lambda n: [((8, 128), _F32)],
        DeliveryContract(kind="gather", dst="out_ref"),
    )


_SCHED_TOKENS = None


def _schedule_token():
    global _SCHED_TOKENS
    if _SCHED_TOKENS is None:
        import itertools

        _SCHED_TOKENS = itertools.count()
    return ("fixture-schedule", next(_SCHED_TOKENS))


def schedule_skipped_chunk(axis="x"):
    """A schedule-search MUTATION executed by the REAL ring kernel (not
    a hand-written replica): ``chunk_order='skip_last'`` threaded
    through the production allgather builder drops the final hop's
    start+wait+consume — every remaining semaphore balances, the rails
    stay paired, but each rank terminates one source short. SL008 is
    the only rule that can see it, which is exactly why the schedule
    enumerator's legality gate is shmemlint."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import lint
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.runtime import AllGatherMethod
    from triton_distributed_tpu.tune.schedule import RingSchedule

    n = 8
    _build_all_gather(
        lint.lint_mesh(n), axis, AllGatherMethod.RING_1D, (8 * n, 2048),
        jnp.dtype(jnp.float32), 53, _schedule_token(), wire="int8",
        schedule=RingSchedule(chunk_order="skip_last"),
    )
    spec = captured_launch("ag_ring_1d_int8w")
    return (
        spec,
        lambda _n: [((8, 2048), _F32), ((8, 2048), np.dtype(np.int8)),
                    ((8, 128), _F32)],
        DeliveryContract(kind="gather", dst="out_ref"),
    )


def schedule_scale_on_payload(axis="x"):
    """The other mutation family: ``scale_rail='payload'`` threaded
    through the production streaming-RS builder signals the quantized
    wire's scale arrivals on the PAYLOAD's recv semaphore. Credits
    balance (reduce_ring still waits the right totals) — only the SL009
    rail-pairing replay can reject it."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import lint
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream_w,
    )
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import RingSchedule

    n = 8
    _build_rs_stream_w(
        lint.lint_mesh(n), axis, 8 * n, 2048, jnp.dtype(jnp.float32),
        False, 54, _schedule_token(), "int8",
        schedule=RingSchedule(scale_rail="payload"),
    )
    spec = captured_launch("rs_ring_stream_int8w")
    return (
        spec,
        lambda _n: [((8 * n, 2048), _F32)],
        DeliveryContract(kind="reduce", dst="out_hbm"),
    )


def kv_ship_skipped_page(axis="x"):
    """The KV page ship one page SHORT: the sender's loop walks
    ``range(pages - 1)``, so the last staged page never leaves the
    prefill pool — every semaphore balances (each started rail pair is
    waited), the rails stay paired, but the decode pool terminates with
    that page's slot unwritten and its source's delivered element count
    short. SL008 against the pairwise permute contract (the bug a
    protocol pass cannot see: an admission gate reading kv_lens would
    happily walk the hole)."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.kv_ship import (
        KV_SHIP_GEOM,
        _kv_ship_kernel,
    )
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.kernels.kv_ship import build_lint_kernel
    from triton_distributed_tpu.analysis.lint import lint_mesh

    g = KV_SHIP_GEOM
    n = 8
    build_lint_kernel(lint_mesh(n, axis), n,
                      token=("fixture_kv_ship_skip",))
    real = captured_launch("kv_ship_pages")
    import functools as _ft

    short = _ft.partial(
        _kv_ship_kernel, n, axis, (axis,),
        g["pages"] - 1,                      # BUG: one page never ships
        g["rows"], 1, "paired",
    )

    def kernel(dstpg_ref, src_q, src_s, dst_q, dst_s,
               send_sem, recv_sem, s_send_sem, s_recv_sem):
        dstpg_ref[...] = np.asarray(
            list(reversed(range(g["pages"]))), np.int32
        )
        short(dstpg_ref, src_q, src_s, dst_q, dst_s,
              send_sem, recv_sem, s_send_sem, s_recv_sem)

    def in_shapes(n):
        del n
        rows = g["pages"] * g["rows"]
        return [
            ((g["pages"],), np.dtype(np.int32)),
            ((rows, g["cols"]), np.dtype(np.int8)),
            ((rows, 128), _F32),
        ]

    return (
        replace(real, kernel=kernel, name="fixture_kv_ship_skipped_page"),
        in_shapes,
        DeliveryContract(
            kind="permute", dst="dst_q",
            payload_per_src=lambda n: g["pages"] * g["rows"] * g["cols"],
            src_only=lambda rank, n: {(rank - n // 2) % n},
        ),
    )


def kv_ship_unpaired_scale(axis="x"):
    """A KV page ship whose SCALE RAIL was dropped: the int8 page
    payloads fly and land at their assigned slots (the permute contract
    is satisfied — every page exactly once), but no per-row scale plane
    accompanies them and the landing is installed without a scale fold.
    The decode pool now holds int8 bytes whose scales are whatever the
    pool's scale plane last held — silently wrong logits. SL009 (no
    paired scale-plane RDMA before the next wait, and the
    scale-fold-omitted consume)."""

    from triton_distributed_tpu.kernels.kv_ship import KV_SHIP_GEOM

    g = KV_SHIP_GEOM
    pages, rows = g["pages"], g["rows"]

    def kernel(dstpg_ref, src_q, src_s, dst_q, dst_s,
               send_sem, recv_sem, s_send_sem, s_recv_sem):
        from jax.experimental import pallas as pl

        dstpg_ref[...] = np.asarray(
            list(reversed(range(pages))), np.int32
        )
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        to = (me + n // 2) % n

        lang.barrier_all(axis)
        handles = []
        for i in range(pages):
            slot = dstpg_ref[i]
            dq = lang.remote_copy(
                src_q.at[pl.ds(i * rows, rows)],
                dst_q.at[pl.ds(slot * rows, rows)],
                send_sem.at[i], recv_sem.at[i], to,
            )
            # BUG: the scale plane never ships — payload rail only
            dq.start()
            handles.append(dq)
        lang.quiet(*handles)
        for dq in handles:
            dq.wait_recv()
        for i in range(pages):
            slot = dstpg_ref[i]
            # BUG: installed with NO scale fold (s=None)
            wirelib.epilogue_consume(
                dst_q.at[pl.ds(slot * rows, rows)], None, None
            )

    total = pages * rows
    return (
        _spec(
            kernel, "fixture_kv_ship_unpaired_scale",
            out_shapes=[((total, g["cols"]), np.dtype(np.int8)),
                        ((total, 128), _F32)],
            scratch=_sems((pages,), (pages,), (pages,), (pages,)),
            collective_id=52,
        ),
        lambda n: [
            ((pages,), np.dtype(np.int32)),
            ((total, g["cols"]), np.dtype(np.int8)),
            ((total, 128), _F32),
        ],
        None,
    )


def grid_ragged_overwide_block(axis="x"):
    """GRID-schedule MUTATION through the production ragged builder:
    ``block_q=32`` against the gate geometry's 16-token parking cap.
    The packed buffer reserves exactly ``min(block_q, GRID_BLOCK_CAP)``
    tokens of tail slack, so a 32-wide query block's q-window reads and
    out-DMA writes overrun the buffer — the evaluator's OOB events and
    the zero-slack local contract both land on SL008. Every semaphore
    balances and the page walk is protocol-clean: only the dataflow
    pass can reject this candidate, which is why it sits in the
    schedule enumerator's mutation set."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        build_grid_lint_kernel,
    )
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import GridSchedule

    g = build_grid_lint_kernel(
        token=_schedule_token(), schedule=GridSchedule(block_q=32)
    )
    real = captured_launch("ragged_paged_attention_q8")

    def kernel(*refs):
        table, kv_lens, q_lens, q_starts, topo = refs[:5]
        table[...] = np.arange(
            g["r"] * g["pps"], dtype=np.int32
        ).reshape(g["r"], g["pps"])
        kv_lens[...] = np.asarray(g["kv_lens"], np.int32)
        q_lens[...] = np.asarray(g["q_lens"], np.int32)
        q_starts[...] = np.asarray(g["q_starts"], np.int32)
        topo[...] = np.asarray(g["topo"], np.int32)
        real.kernel(*refs)

    def in_shapes(n):
        del n
        pool = (g["npages"], g["hkv"], g["page"], g["d"])
        return [
            ((g["r"], g["pps"]), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"], 2 + 2 * g["topo_w"]), np.dtype(np.int32)),
            ((g["hkv"], g["t"] * g["g"], g["d"]), _F32),
            (pool, np.dtype(np.int8)),
            (pool, np.dtype(np.int8)),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
        ]

    return (
        replace(real, kernel=kernel,
                name="fixture_grid_ragged_overwide_block"),
        in_shapes,
        DeliveryContract(kind="local", dst=10),
    )


def grid_kv_ship_dropped_scale(axis="x"):
    """GRID-schedule MUTATION through the production kv_ship builder:
    a 2-page coalesced tick whose scale rail is DROPPED
    (``coalesce=2, rail='drop'``). The int8 page payloads fly and land
    coalesced (the permute is still exact), but no per-row scale plane
    ships and the landing installs with no scale fold — SL009, the
    same silent-wrong-logits bug as :func:`kv_ship_unpaired_scale`,
    produced by the real builder under a mutated schedule instead of a
    hand-written replica."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.kv_ship import (
        KV_SHIP_GEOM,
        build_lint_kernel,
        coalesced_landing_table,
    )
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import GridSchedule

    g = KV_SHIP_GEOM
    n = 8
    build_lint_kernel(
        lint_mesh(n, axis), n, token=_schedule_token(),
        schedule=GridSchedule(coalesce=2, rail="drop"),
    )
    real = captured_launch("kv_ship_pages")
    table = np.asarray(coalesced_landing_table(g["pages"], 2), np.int32)

    def kernel(dstpg_ref, *refs):
        dstpg_ref[...] = table
        real.kernel(dstpg_ref, *refs)

    def in_shapes(n):
        del n
        rows = g["pages"] * g["rows"]
        return [
            ((g["pages"],), np.dtype(np.int32)),
            ((rows, g["cols"]), np.dtype(np.int8)),
            ((rows, 128), _F32),
        ]

    # contract=None (the kv_ship_unpaired_scale precedent): the rail
    # pairing is the bug under test, so the pin is EXACTLY ["SL009"] —
    # the permute contract would add its own SL008 for the missing
    # scale-plane deliveries and blur the rule pin
    return (
        replace(real, kernel=kernel,
                name="fixture_grid_kv_ship_dropped_scale"),
        in_shapes,
        None,
    )


def grid_gemm_rs_shared_rail(axis="x"):
    """GRID-schedule MUTATION through the production fused GEMM-RS
    builder on the int8-MXU wire: ``rail='shared'`` signals the scale
    plane's arrival on the PAYLOAD's recv semaphore. Credits balance —
    the reduce ring waits the right totals — but a rank can fold a
    stale scale against a fresh payload; only the SL009 rail-pairing
    replay rejects it."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused
    from triton_distributed_tpu.lang import wire as wirelib
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import GridSchedule

    n = 8
    _build_fused(
        lint_mesh(n, axis), axis, (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6,
        _schedule_token(), wire="int8-mxu",
        schedule=GridSchedule(rail="shared"),
    )
    spec = captured_launch("gemm_rs_fused_int8mxw")

    def in_shapes(n):
        return [((16 * n, 128), np.dtype(np.int8)),
                ((n, wirelib.SCALE_LANES), _F32),
                ((128, 64), np.dtype(np.int8)),
                ((1, 64), _F32)]

    return (
        spec,
        in_shapes,
        DeliveryContract(kind="reduce", dst="out_hbm"),
    )


# ------------------------------------------------ Mosaic-compat fixtures
#
# These are consumed by analysis.mosaic_compat.preflight_spec (real jax
# tracing, not the abstract evaluator): each kernel contains exactly one
# construct this toolchain's Mosaic backend rejects.

def f8_inkernel_cast(axis="x"):
    """arith.extf f8E4M3FN → f32 inside the kernel ('Only 16-bit to
    32-bit extensions supported'). MC001."""

    def kernel(xq_ref, out_ref):
        import jax.numpy as jnp

        out_ref[...] = xq_ref[...].astype(jnp.float32) * 2.0

    return (
        _spec(kernel, "fixture_f8_cast", out_shapes=[((8, 128), _F32)]),
        lambda n: [((8, 128), _f8())],
    )


def scalar_shape_cast(axis="x"):
    """A loaded (1, 1) float block collapsed to a scalar — the
    vector<1x1> → scalar shape_cast Mosaic rejects. MC002."""

    def kernel(x_ref, out_ref):
        import jax.numpy as jnp

        blk = x_ref[...]
        s = jnp.reshape(blk[0:1, 0:1], ())    # BUG: scalar shape_cast
        out_ref[...] = blk * s

    return (
        _spec(kernel, "fixture_scalar_cast", out_shapes=[((8, 128), _F32)]),
        lambda n: [((8, 128), _F32)],
    )


def subbyte_broadcast(axis="x"):
    """An int4 vector broadcast — no sub-byte broadcast layout in this
    Mosaic backend. MC003."""

    def kernel(x_ref, out_ref):
        import jax.numpy as jnp

        nib = jnp.broadcast_to(
            jnp.zeros((1, 1), jnp.int4), x_ref.shape
        )
        out_ref[...] = x_ref[...] + nib.astype(jnp.float32)

    return (
        _spec(kernel, "fixture_subbyte", out_shapes=[((8, 128), _F32)]),
        lambda n: [((8, 128), _F32)],
    )


def duplicate_collective_id(axis="x"):
    """TWO kernel families at DIFFERENT sites sharing one
    collective_id — their barrier rendezvous collide when both are
    launched in a program (the ad-hoc id-rail hazard ADVICE.md flagged
    on gemm_rs's +96 range). The cross-family SL005 check catches it;
    returns both (spec, in_shapes) pairs."""

    def mk(name, site):
        def kernel(x_ref, out_ref):
            lang.barrier_all(axis)
            out_ref[:] = x_ref[:]

        return _spec(
            kernel, name,
            out_shapes=[((8, 128), _F32)],
            collective_id=45,              # BUG: shared across sites
        )

    return (
        (mk("fixture_dup_cid_a", "site_a"), lambda n: [((8, 128), _F32)]),
        (mk("fixture_dup_cid_b", "site_b"), lambda n: [((8, 128), _F32)]),
    )


def ragged_hole(axis="x"):
    """The REAL ragged paged-attention kernel with mis-addressed row
    packing: both rows' ``q_starts`` park at 0, so the second row's
    out-DMA overwrites the first row's span and rows [8:16) of the
    packed output are never written — every semaphore balances, the
    page walk is protocol-clean, but the `local` delivery contract
    terminates with a hole. SL008 (kind='local')."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM,
        build_lint_kernel,
    )
    from triton_distributed_tpu.lang.launch import captured_launch

    g = LINT_GEOM
    build_lint_kernel(token=("fixture_ragged_hole",))
    real = captured_launch("ragged_paged_attention_q8")

    def kernel(*refs):
        table, kv_lens, q_lens, q_starts = refs[:4]
        table[...] = np.arange(
            g["r"] * g["pps"], dtype=np.int32
        ).reshape(g["r"], g["pps"])
        kv_lens[...] = np.asarray([12, 8], np.int32)
        q_lens[...] = np.asarray([8, 8], np.int32)
        q_starts[...] = np.asarray([0, 0], np.int32)   # BUG: both park at 0
        real.kernel(*refs)

    def in_shapes(n):
        del n
        pool = (g["npages"], g["hkv"], g["page"], g["d"])
        return [
            ((g["r"], g["pps"]), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"], 2 + 2 * g["topo_w"]), np.dtype(np.int32)),
            ((g["hkv"], g["t"] * g["g"], g["d"]), _F32),
            (pool, np.dtype(np.int8)),
            (pool, np.dtype(np.int8)),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
        ]

    return (
        replace(real, kernel=kernel, name="fixture_ragged_hole"),
        in_shapes,
        DeliveryContract(kind="local", dst=10),
    )


def ragged_tree_sibling(axis="x"):
    """The REAL ragged kernel fed a MALFORMED tree descriptor: row 1's
    node at q position 2 carries an ancestry bitmask that includes its
    SIBLING branch (bit 1) — the bitmasks are not closed under the
    packed parent pointers, so that node's scores admit keys from a
    path it does not descend from and the verify walk samples from a
    contaminated distribution. Coverage is perfect (every out element
    is the rank's own write), so only the contract's masked-coverage
    facet can reject it. SL008 (kind='local', value-level)."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM,
        TOPO_TREE,
        build_lint_kernel,
        causal_topologies,
    )
    from triton_distributed_tpu.lang.launch import captured_launch

    g = LINT_GEOM
    w = g["topo_w"]
    build_lint_kernel(token=("fixture_ragged_tree_sibling",))
    real = captured_launch("ragged_paged_attention_q8")

    topo = causal_topologies(g["r"], w)
    # row 1: frontier + 7 nodes filling the packed span; q1 and q2 are
    # SIBLING branches off the frontier, q3..q7 chain off q2. A
    # well-formed q2 mask is {0, 2}; this one smuggles in bit 1 (its
    # sibling q1) — and every descendant inherits the leak, but the
    # closure breaks exactly at q2, the graft point.
    topo[1, 0] = TOPO_TREE
    topo[1, 1] = 8
    anc = [1, 3, 7, 15, 31, 63, 127, 255]   # anc[2] holds bit 1: BUG
    par = [-1, 0, 0, 2, 3, 4, 5, 6]
    topo[1, 2:2 + 8] = anc
    topo[1, 2 + w:2 + w + 8] = par

    def in_shapes(n):
        del n
        pool = (g["npages"], g["hkv"], g["page"], g["d"])
        return [
            ((g["r"], g["pps"]), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"],), np.dtype(np.int32)),
            ((g["r"], 2 + 2 * w), np.dtype(np.int32)),
            ((g["hkv"], g["t"] * g["g"], g["d"]), _F32),
            (pool, np.dtype(np.int8)),
            (pool, np.dtype(np.int8)),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
            ((g["npages"], g["hkv"], 1, g["page"]), _F32),
        ]

    init = {
        0: np.arange(g["r"] * g["pps"], dtype=np.int32).reshape(
            g["r"], g["pps"]),
        1: np.asarray([12, 8], np.int32),
        2: np.asarray([8, 8], np.int32),
        3: np.asarray([0, 8], np.int32),
        4: topo,
    }
    return (
        replace(real, kernel=real.kernel,
                name="fixture_ragged_tree_sibling"),
        in_shapes,
        DeliveryContract(
            kind="local", dst=10,
            topo={"ref": 4, "kv_lens": 1, "q_lens": 2, "width": w},
        ),
        init,
    )


def lane_reshape(axis="x"):
    """An in-kernel reshape that CHANGES the lane (minor) dimension —
    (8, 256) → (16, 128) — the vector shape_cast this Mosaic cannot
    re-lay (the naive GQA-row flatten the ragged kernel's head-major
    packing exists to avoid). MC005."""

    def kernel(x_ref, out_ref):
        import jax.numpy as jnp

        blk = x_ref[...]                       # (8, 256)
        out_ref[...] = jnp.reshape(blk, (16, 128))   # BUG: lane change

    return (
        _spec(kernel, "fixture_lane_reshape",
              out_shapes=[((16, 128), _F32)]),
        lambda n: [((8, 256), _F32)],
    )


def dynamic_gather(axis="x"):
    """An in-kernel gather with TRACED indices — the ``anc[par]``
    index chase a naive tree-topology mask build would produce
    (``jnp.take`` over a runtime int vector). This Mosaic has no
    dynamic vector-indexed gather lowering; the ragged kernel's
    static ancestor-bitmask unroll exists to avoid it. MC006."""

    def kernel(idx_ref, x_ref, out_ref):
        import jax.numpy as jnp

        idx = idx_ref[...]                     # (8,) traced int32
        out_ref[...] = jnp.take(x_ref[...], idx, axis=0)   # BUG

    return (
        _spec(kernel, "fixture_dynamic_gather",
              out_shapes=[((8, 128), _F32)]),
        lambda n: [((8,), np.dtype(np.int32)), ((8, 128), _F32)],
    )


def sublane_dynamic_slice(axis="x"):
    """An in-kernel ``dynamic_slice`` whose SUBLANE (second-minor)
    start index is a traced runtime value — the jaxpr signature the
    nightly slow run surfaced (a KV-window slice ``x[start:start+8]``
    with a per-step ``start``). This Mosaic only folds constant
    sublane offsets; traced LANE offsets are fine. MC007."""

    def kernel(idx_ref, x_ref, out_ref):
        import jax.lax as lax

        i = idx_ref[0]                         # traced scalar int32
        out_ref[...] = lax.dynamic_slice(
            x_ref[...], (i, 0), (8, 128))      # BUG: traced sublane start

    return (
        _spec(kernel, "fixture_sublane_dynamic_slice",
              out_shapes=[((8, 128), _F32)]),
        lambda n: [((1,), np.dtype(np.int32)), ((16, 128), _F32)],
    )


def cp_ring_skipped_block(axis="x"):
    """The context-parallel KV rotation ring one BLOCK short: the
    schedule mutation ``chunk_order='skip_last'`` threaded through the
    production cp.ring_attention builder drops the final hop's
    start+wait+consume, so each rank's rotated-KV workspace terminates
    one source block short — an attention output silently missing one
    rank's keys/values. Semaphores balance, rails stay paired; only the
    SL008 delivery replay against the gather contract can reject it
    (``own_absent_ok``: the harness never copies the local block, ring
    attention consumes it straight from the operand)."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.cp_ring import build_kv_rotate_lint
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import RingSchedule

    n = 8
    build_kv_rotate_lint(
        lint_mesh(n, axis), n, token=_schedule_token(),
        schedule=RingSchedule(chunk_order="skip_last"),
    )
    spec = captured_launch("cp_ring_kv_rotate")
    return (
        replace(spec, name="fixture_cp_ring_skipped_block"),
        lambda _n: [((8, 128), _F32)],
        DeliveryContract(kind="gather", dst="ag_ref", own_absent_ok=True),
    )


def grad_ring_unpaired_scale(axis="x"):
    """The gradient ring's quantized wire with the scale rail riding the
    PAYLOAD semaphore: ``scale_rail='payload'`` threaded through the
    production grad_ring.stream_int8w builder signals scale arrivals on
    the payload's recv semaphore. Credits balance (reduce_ring waits the
    right totals) — a gradient can dequantize against a scale from the
    WRONG hop; only the SL009 rail-pairing replay can reject it."""
    from dataclasses import replace

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.cp_ring import build_grad_ring_lint
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.tune.schedule import RingSchedule

    n = 8
    build_grad_ring_lint(
        lint_mesh(n, axis), n, token=_schedule_token(),
        schedule=RingSchedule(scale_rail="payload"),
    )
    spec = captured_launch("grad_ring_stream_int8w")
    return (
        replace(spec, name="fixture_grad_ring_unpaired_scale"),
        lambda _n: [((8 * _n, 2048), _F32)],
        DeliveryContract(kind="reduce", dst="out_hbm"),
    )


def contract_declares_gather_actually_reduces(axis="x"):
    """A seeded SL012 true-positive for contract inference: the REAL
    reduce-scatter ring kernel registered with a hand-written contract
    that declares ``kind='gather'``. Every semaphore balances and the
    kernel genuinely delivers — but it FOLDS (every output element sums
    a contribution from all ranks) while the declaration promises
    single-sourced chunks, so plain SL008 would check the wrong shape
    and judge a correct reduction 'incomplete' (or a broken gather
    complete). Only the twin diff (``jax.lax.psum_scatter`` delivers
    class 'fold', the declared kind is class 'single') can name the
    declaration itself as the bug. Returns (spec, in_shapes, declared
    contract, degrades_to path)."""
    from dataclasses import replace

    import jax.numpy as jnp

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_reduce_scatter,
    )
    from triton_distributed_tpu.lang.launch import captured_launch

    n = 8
    _build_reduce_scatter(
        lint_mesh(n, axis), axis, (8 * n, 128), jnp.dtype(jnp.float32),
        False, 55, _schedule_token(),
    )
    spec = captured_launch("rs_ring")
    return (
        replace(spec, name="fixture_contract_gather_actually_reduces"),
        lambda _n: [((8 * _n, 128), _F32)],
        DeliveryContract(kind="gather", dst="out_ref"),
        "jax.lax.psum_scatter",
    )


def contract_overdeclared_payload(axis="x"):
    """A seeded SL012 true-positive for contract inference: the REAL
    1-D all-gather ring with a declared ``payload_per_src`` of TWICE
    what the twin (and the kernel) actually deliver per source. The
    kind and dst are right, so the drift is purely quantitative — a
    declaration like this would make SL008 flag every correct run as
    half-delivered (and, declared the other way, bless a half-delivered
    one). The inference pass measures the modal per-source landing off
    the replay's provenance nibbles and names the over-declaration.
    Returns (spec, in_shapes, declared contract, degrades_to path)."""
    from dataclasses import replace

    import jax.numpy as jnp

    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.analysis.lint import lint_mesh
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.lang.launch import captured_launch
    from triton_distributed_tpu.runtime import AllGatherMethod

    n = 8
    _build_all_gather(
        lint_mesh(n, axis), axis, AllGatherMethod.RING_1D, (8 * n, 128),
        jnp.dtype(jnp.float32), 56, _schedule_token(),
    )
    spec = captured_launch("ag_ring_1d")
    return (
        replace(spec, name="fixture_contract_overdeclared_payload"),
        lambda _n: [((8, 128), _F32)],
        DeliveryContract(
            kind="gather", dst="out_ref",
            payload_per_src=lambda _n: 2 * 8 * 128,
        ),
        "jax.lax.all_gather",
    )
