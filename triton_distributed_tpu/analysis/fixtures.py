"""Deliberately broken SHMEM kernels — one per shmemlint rule.

These exist so every rule is pinned by a real kernel body forever, and
specifically to close the caveat ``tests/test_races.py`` documents: the
TPU interpreter's dynamic race detector has MISSED a deliberately
removed wait under ``dma_execution_mode="on_wait"``. The
:func:`missing_wait` fixture is exactly that bug, and
``tests/test_analysis.py`` asserts shmemlint flags it (SL001) with
rank + semaphore diagnostics — statically, on any jax, no interpreter
required.

Each fixture returns a hand-built
:class:`~triton_distributed_tpu.lang.launch.LaunchSpec` plus the
per-device input shapes, ready for
:func:`triton_distributed_tpu.analysis.lint.analyze_spec`.
"""

from __future__ import annotations

import numpy as np

from triton_distributed_tpu import lang
from triton_distributed_tpu.lang.launch import LaunchSpec

_F32 = np.dtype(np.float32)


def _spec(kernel, name, out_shapes=(), scratch=(), collective_id=None,
          vmem_limit_bytes=None):
    import jax

    return LaunchSpec(
        name=name,
        kernel=kernel,
        out_shape=[jax.ShapeDtypeStruct(s, d) for s, d in out_shapes],
        in_specs=None,
        out_specs=None,
        scratch_shapes=tuple(scratch),
        collective_id=collective_id,
        vmem_limit_bytes=vmem_limit_bytes,
    )


def _sems(*shapes):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.SemaphoreType.DMA(s) if s else pltpu.SemaphoreType.REGULAR(())
            for s in shapes]


def missing_wait(axis="x"):
    """The test_races caveat, seeded: every rank pushes its shard to
    every peer and signals arrival, but the consuming
    ``signal_wait_until`` was "forgotten" — the kernel reads the
    gathered buffer with nothing ordering the landings. Dynamically
    this is a probabilistic wrong-answer; statically it is SL001
    (unconsumed flag credits) + SL004 (unordered landing vs the read).
    """

    def kernel(x_ref, out_ref, chk_ref, send_sem, recv_sem, flag_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        handles = []
        for i in range(n - 1):
            peer = (me + 1 + i) % n
            handles.append(lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)],
                x_ref,
                send_sem.at[i],
                recv_sem.at[i],
                peer,
            ))
            lang.signal_op(flag_sem, 1, pe=peer, site="fixture")
        lang.quiet(*handles)
        # BUG: no `for i in range(n-1): lang.signal_wait_until(flag_sem, 1)`
        # and no recv waits — the landings are unordered with this read:
        chk_ref[0, 0] = jnp.sum(out_ref[:])

    return (
        _spec(
            kernel, "fixture_missing_wait",
            out_shapes=[((8 * 8, 128), _F32), ((1, 1), _F32)],
            scratch=_sems((8,), (8,), None),
            collective_id=40,
        ),
        lambda n: [((8, 128), _F32)],
    )


def credit_imbalance(axis="x"):
    """Off-by-one credit accounting: each rank sends ONE barrier credit
    (to its right neighbor) but waits for TWO — the classic symptom
    that today only shows up as a hang the watchdog must catch. SL002.
    """

    def kernel(x_ref, out_ref, sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        lang.signal_op(sem, 1, pe=(me + 1) % n, site="fixture")
        lang.signal_wait_until(sem, 2)     # BUG: only 1 credit ever comes
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_credit_imbalance",
            out_shapes=[((8, 128), _F32)],
            scratch=_sems(None),
            collective_id=41,
        ),
        lambda n: [((8, 128), _F32)],
    )


def deadlock(axis="x"):
    """Wait-before-signal around the ring: every rank parks in a wait
    whose credit is behind the next rank's identical wait. SL003 with
    the full rank cycle."""

    def kernel(x_ref, out_ref, sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        lang.signal_wait_until(sem, 1)     # BUG: nobody signals first
        lang.signal_op(sem, 1, pe=(me + 1) % n, site="fixture")
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_deadlock",
            out_shapes=[((8, 128), _F32)],
            scratch=_sems(None),
            collective_id=42,
        ),
        lambda n: [((8, 128), _F32)],
    )


def barrier_mismatch(axis="x"):
    """Rank 0 runs an extra ``barrier_all`` the other ranks don't —
    diverging collective sequences across ranks. SL005 (and the missing
    peers make the extra barrier an SL002 hang)."""

    def kernel(x_ref, out_ref):
        me = lang.my_pe(axis)
        lang.barrier_all(axis)
        if me == 0:                        # BUG: rank-dependent barrier
            lang.barrier_all(axis)
        out_ref[:] = x_ref[:]

    return (
        _spec(
            kernel, "fixture_barrier_mismatch",
            out_shapes=[((8, 128), _F32)],
            collective_id=43,
        ),
        lambda n: [((8, 128), _F32)],
    )


def undrained_dma(axis="x"):
    """Puts whose local completion is never drained (missing ``quiet``/
    ``wait_send``) — the kernel can exit with transfers in flight.
    SL007."""

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        me = lang.my_pe(axis)
        n = lang.n_pes(axis)
        m = x_ref.shape[0]
        from jax.experimental import pallas as pl

        out_ref[pl.ds(me * m, m)] = x_ref[:]
        lang.barrier_all(axis)
        handles = []
        for i in range(n - 1):
            peer = (me + 1 + i) % n
            handles.append(lang.putmem_signal_nbi_block(
                out_ref.at[pl.ds(me * m, m)], x_ref,
                send_sem.at[i], recv_sem.at[i], peer,
            ))
        for h in handles:
            h.wait_recv()
        # BUG: no lang.quiet(*handles) — send semaphores never drained

    return (
        _spec(
            kernel, "fixture_undrained_dma",
            out_shapes=[((8 * 8, 128), _F32)],
            scratch=_sems((8,), (8,)),
            collective_id=44,
        ),
        lambda n: [((8, 128), _F32)],
    )


def vmem_overcommit(axis="x"):
    """VMEM working set exceeding the launch's declared budget. SL006."""

    def kernel(x_ref, out_ref, big_ref, sem):
        out_ref[:] = x_ref[:]
        lang.signal_op(sem, 1, site="fixture")
        lang.signal_wait_until(sem, 1)

    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    return (
        _spec(
            kernel, "fixture_vmem_overcommit",
            out_shapes=[((8, 128), _F32)],
            scratch=[pltpu.VMEM((64, 128), jnp.float32)] + _sems(None),
            collective_id=None,
            vmem_limit_bytes=16 * 1024,   # 16 KiB budget vs ~40 KiB set
        ),
        lambda n: [((8, 128), _F32)],
    )


def duplicate_collective_id(axis="x"):
    """TWO kernel families at DIFFERENT sites sharing one
    collective_id — their barrier rendezvous collide when both are
    launched in a program (the ad-hoc id-rail hazard ADVICE.md flagged
    on gemm_rs's +96 range). The cross-family SL005 check catches it;
    returns both (spec, in_shapes) pairs."""

    def mk(name, site):
        def kernel(x_ref, out_ref):
            lang.barrier_all(axis)
            out_ref[:] = x_ref[:]

        return _spec(
            kernel, name,
            out_shapes=[((8, 128), _F32)],
            collective_id=45,              # BUG: shared across sites
        )

    return (
        (mk("fixture_dup_cid_a", "site_a"), lambda n: [((8, 128), _F32)]),
        (mk("fixture_dup_cid_b", "site_b"), lambda n: [((8, 128), _F32)]),
    )
