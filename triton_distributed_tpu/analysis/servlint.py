"""servlint: small-scope model checking of the serving/fleet protocol.

shmemlint verifies the DEVICE protocol (semaphores, delivery
contracts); this module verifies the HOST protocol one layer up — page
refcounts, transactional reserve/land/commit KV ships, drain/migrate/
failover, preemption and speculative rollback. The invariants it
checks (no lost request, no leaked or double-freed page, no page freed
mid-ship) were previously pinned only by example traces; the same
"semaphore-clean != data-correct" lesson applies, and TLA+-style
bounded exhaustive interleaving over a tiny fleet finds the races
chaos seeds can only sample.

The checker does NOT re-implement the protocol. Every transition runs
*the production code's own transition functions* through the
:class:`~triton_distributed_tpu.serving.protocol.ProtocolOps` seam —
the exact ``admit``/``evict_one``/``preempt_for``/``ensure_pages``/
``advance_cursor``/``rollback_draft``/``reserve_shipped``/
``ship_commit``/``ship_abort``/``failover_requeue``/``drain_requeue``
objects the engines delegate to — driven over an abstract 2-replica
fleet small enough to explore exhaustively:

    2 replicas x <= 3 requests x <= 8 pages (4 per replica pool),
    BFS over all interleavings of {route, admit, step, spec-rollback,
    evict, preempt, launch_ship, commit_ship, transport-fail,
    ReplicaDeath, drain} with state-hash memoization.

BFS makes the first counterexample *minimal*: the finding's printed
repro interleaving is a shortest path to the violation.

Rules (stable IDs, catalogued in analysis/findings.py and
docs/LINT.md):

* **SV001** page leak — a page neither referenced by any block table
  nor on the free/reclaim lists (or refcounted with no referent).
* **SV002** double-free / negative refcount — the PagePool asserts
  (``release`` of a freed page, ``alloc`` of a live one) or a block
  table referencing a freed page.
* **SV003** page freed while a ship/migration holds it — an in-flight
  ship record whose pinned source or reserved destination pages lost
  their refcount or table entry.
* **SV004** request lost or duplicated — conservation of the request
  multiset across failover/drain/preemption (an in-flight ship
  legitimately appears at both endpoints; anything else is a bug).
* **SV005** cursor regression — a request resident in the same slot
  whose cursor moved backwards across a transition (production only
  rewinds via off-slot requeue at cursor 0, or speculative rollback
  to at least the pre-row cursor + 1).
* **SV006** non-transactional ship — dst commit observable before the
  source released its pinned pages, or a transport-exhausted ship
  leaving its destination reservation occupied.
* **SV007** unroutable livelock — backlog nonempty, no resident work
  anywhere, and routing + admission on every routable replica admits
  nothing (nothing can ever change).

Model boundary: no revive/grow (a death is final), a single engine
role per replica, token values are synthetic (scheduling never reads
them), and device work (gather/land) is stubbed — every checked
invariant is pure host bookkeeping, which is exactly what makes the
exploration affordable.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from triton_distributed_tpu.analysis.findings import Finding
from triton_distributed_tpu.serving.engine import (
    EngineConfig,
    EngineStats,
    Request,
    ServingEngine,
)
from triton_distributed_tpu.serving.protocol import ProtocolOps
from triton_distributed_tpu.serving.state import PagePool

#: the abstract fleet's per-replica geometry (2 replicas => 8 pages)
_CFG = EngineConfig(slots=2, token_budget=8, chunk=4, page=4, npages=4)
_PAGES_PER_SEQ = 4


class _StateStub:
    """The two fields of the device ServingState the host verbs read."""

    pages_per_seq = _PAGES_PER_SEQ
    capacity = _PAGES_PER_SEQ * _CFG.page


class _HostShell(ServingEngine):
    """A ServingEngine reduced to its HOST half: the exact fields and
    helper methods the ProtocolOps verbs touch, none of the device
    state (model, params, jits, pools-on-device). The verbs therefore
    run bit-identically to production — same admission sort, same
    eviction ranking, same refcount discipline — at model-checking
    speed."""

    def __init__(self, ops: ProtocolOps):
        # deliberately does NOT call ServingEngine.__init__ (no model)
        self.cfg = _CFG
        self.ops = ops
        self.state = _StateStub()
        self.table = np.full((_CFG.slots, _PAGES_PER_SEQ), -1, np.int32)
        # cp-shard facet: ops carrying ``cp = k`` (CpProtocolOps, the
        # SV001cp fixture) run the SAME verbs over a cp-sharded pool —
        # same total pages, same table width, so the explored state
        # space stays comparable while every alloc/release/lookup now
        # exercises the shard-ownership routing
        cp = int(getattr(ops, "cp", 1))
        if cp > 1:
            from triton_distributed_tpu.serving.state import CpPagePool

            self.pool = CpPagePool(
                cp, _CFG.npages // cp, _CFG.page,
                _PAGES_PER_SEQ // cp, prefix_cache=_CFG.prefix_cache)
        else:
            self.pool = PagePool(_CFG.npages, _CFG.page,
                                 prefix_cache=_CFG.prefix_cache)
        self.slot_req = [None] * _CFG.slots
        self.pending: deque = deque()
        self.waiting: deque = deque()
        self.stats = EngineStats()
        self.step_count = 0
        self.tenants = {}
        self.aging_ticks = 0
        self.throttled_tiers = frozenset()
        self.on_complete = None
        self.on_preempt = None

    # device work is out of model: the payload is its page-id list
    def gather_pages(self, pids):
        return tuple(pids), None

    def land_pages(self, pids, q_payload, s_payload):
        return None

    def clone(self, reqs: dict) -> "_HostShell":
        c = _HostShell.__new__(_HostShell)
        c.cfg = self.cfg
        c.ops = self.ops
        c.state = self.state
        c.table = self.table.copy()
        c.pool = self.pool.clone()
        c.slot_req = [None if r is None else reqs[r.rid]
                      for r in self.slot_req]
        c.pending = deque(reqs[r.rid] for r in self.pending)
        c.waiting = deque(reqs[r.rid] for r in self.waiting)
        c.stats = EngineStats()
        c.step_count = self.step_count
        c.tenants = self.tenants
        c.aging_ticks = self.aging_ticks
        c.throttled_tiers = self.throttled_tiers
        c.on_complete = None
        c.on_preempt = None
        return c


class _Ship:
    """One in-flight KV ship/migration: the reservation-to-commit
    window the transactional discipline protects. ``src_pids`` are the
    source's pinned pages, ``dpids`` the destination's reserved landing
    pages — SV003 demands both stay held until the record resolves."""

    __slots__ = ("rid", "src", "pslot", "dst", "dslot", "dpids",
                 "src_pids")

    def __init__(self, rid, src, pslot, dst, dslot, dpids, src_pids):
        self.rid = rid
        self.src = src
        self.pslot = pslot
        self.dst = dst
        self.dslot = dslot
        self.dpids = tuple(dpids)
        self.src_pids = tuple(src_pids)

    def key(self):
        return (self.rid, self.src, self.pslot, self.dst, self.dslot,
                self.dpids, self.src_pids)


def _universe():
    """The <=3-request workload: mixed tiers (so admission exercises
    preempt_for), page-crossing prompts (so eviction/rollback move real
    pages), single-token completions (bounded lifecycle)."""
    return [
        Request(rid=0, prompt=np.arange(6, dtype=np.int32),
                max_new=2, arrival=0.0),
        Request(rid=1, prompt=np.arange(6, dtype=np.int32) + 1,
                max_new=1, arrival=0.0, priority="batch"),
        Request(rid=2, prompt=np.arange(10, dtype=np.int32) + 2,
                max_new=1, arrival=0.0, priority="background"),
    ]


class _World:
    """One explored fleet state: 2 host shells, the fleet queue, the
    in-flight ship records, the dead/draining sets, and the transition
    trace that reached it (the minimal repro when a rule fires)."""

    def __init__(self, ops: ProtocolOps):
        self.ops = ops
        self.engines = [_HostShell(ops), _HostShell(ops)]
        self.requests = {r.rid: r for r in _universe()}
        self.queue: deque = deque(self.requests.values())
        self.ships: list = []
        self.dead: set = set()
        self.draining: set = set()
        self.trace: tuple = ()

    def clone(self) -> "_World":
        w = _World.__new__(_World)
        w.ops = self.ops
        reqs = {}
        for rid, r in self.requests.items():
            c = Request(rid=r.rid, prompt=r.prompt, max_new=r.max_new,
                        arrival=r.arrival, tenant=r.tenant,
                        priority=r.priority)
            c.generated = list(r.generated)
            c.cursor = r.cursor
            c.slot = r.slot
            c.evictions = r.evictions
            c.done = r.done
            c.parked = r.parked
            reqs[rid] = c
        w.requests = reqs
        w.engines = [e.clone(reqs) for e in self.engines]
        w.queue = deque(reqs[r.rid] for r in self.queue)
        w.ships = [_Ship(*s.key()) for s in self.ships]
        w.dead = set(self.dead)
        w.draining = set(self.draining)
        w.trace = self.trace
        return w

    def alive(self):
        return [k for k in range(len(self.engines))
                if k not in self.dead]

    def routable(self):
        return [k for k in self.alive() if k not in self.draining]

    def _page_renames(self):
        """Per-engine canonical page renaming — the symmetry reduction
        that makes the uncapped nightly exploration terminate. Page ids
        are opaque handles: every verb is equivariant under a per-pool
        relabeling (a CpPagePool relabeling must additionally preserve
        each page's SHARD, since ownership routing reads
        ``pg // npages_shard``), so states identical up to renaming
        have isomorphic futures and may share one key. The map assigns
        ids in first-appearance order over a deterministic traversal —
        block-table rows, in-flight ship pins (sorted by their
        id-independent coordinates), the free list, then any leaked
        straggler by original id — restarting the numbering at each
        shard base so the relabeling is shard-preserving."""
        maps = []
        for k, e in enumerate(self.engines):
            pool = e.pool
            nps = getattr(pool, "npages_shard", pool.npages)
            ren: dict = {}
            nxt: dict = {}

            def visit(pg, ren=ren, nxt=nxt, nps=nps):
                pg = int(pg)
                if pg < 0 or pg in ren:
                    return
                sh = pg // nps
                ren[pg] = sh * nps + nxt.get(sh, 0)
                nxt[sh] = nxt.get(sh, 0) + 1

            for pg in e.table.flat:
                visit(pg)
            for s in sorted(self.ships, key=lambda s: (
                    s.rid, s.src, s.pslot, s.dst, s.dslot)):
                if s.src == k:
                    for pg in s.src_pids:
                        visit(pg)
                if s.dst == k:
                    for pg in s.dpids:
                        visit(pg)
            for pg in pool.free:
                visit(pg)
            for pg in range(pool.npages):
                visit(pg)
            maps.append(ren)
        return maps

    def key(self):
        """Canonical hashable state (counters/stats excluded — they
        grow without bound and never feed a scheduling decision; page
        ids canonicalized via :meth:`_page_renames`)."""
        reqs = tuple(
            (rid, r.cursor, len(r.generated), r.parked, r.done)
            for rid, r in sorted(self.requests.items()))
        maps = self._page_renames()
        engs = []
        for k, e in enumerate(self.engines):
            if k in self.dead:
                engs.append("dead")
                continue
            ren = maps[k]
            inv = {v: o for o, v in ren.items()}
            engs.append((
                k in self.draining,
                tuple(None if r is None else r.rid
                      for r in e.slot_req),
                tuple(ren[int(p)] if int(p) >= 0 else -1
                      for p in e.table.flat),
                tuple(ren[int(p)] for p in e.pool.free),
                tuple(int(e.pool.refs[inv[i]])
                      for i in range(e.pool.npages)),
                tuple(sorted(ren[int(p)] for p in e.pool._reclaim)),
                tuple(sorted((ren[int(p)], h)
                             for p, h in e.pool._hash_of.items())),
                tuple(r.rid for r in e.waiting),
                tuple(r.rid for r in e.pending),
            ))
        ships = tuple(sorted(
            (s.rid, s.src, s.pslot, s.dst, s.dslot,
             tuple(maps[s.dst][int(p)] for p in s.dpids),
             tuple(maps[s.src][int(p)] for p in s.src_pids))
            for s in self.ships))
        return (reqs, tuple(engs),
                tuple(r.rid for r in self.queue), ships)


# ------------------------------------------------------------ transitions


def _resident_rows(world, k):
    eng = world.engines[k]
    return [(s, r) for s, r in enumerate(eng.slot_req)
            if r is not None and not r.parked and not r.done]


def _tok(req) -> int:
    """Synthetic deterministic token — scheduling never reads values,
    only lengths, so any pure function of (rid, position) works."""
    return (req.rid * 31 + len(req.generated)) % 97


def _enabled(world):
    """Labels of every transition enabled in ``world``. A label is
    (kind, args...); :func:`_apply` executes it through the seam."""
    out = []
    for k in world.routable():
        if world.queue:
            out.append(("route", k))
    for k in world.alive():
        # a draining replica admits no ROUTED work but its engine still
        # runs local admission over what it already holds
        eng = world.engines[k]
        if eng.waiting or eng.pending:
            out.append(("admit", k))
        if eng.waiting:
            head = eng.waiting[0]
            if any(r is not None and not r.parked and not r.done
                   and eng._eff_rank(r) > eng._eff_rank(head)
                   for r in eng.slot_req):
                out.append(("preempt", k))
    for k in world.alive():
        rows = _resident_rows(world, k)
        if rows:
            out.append(("evict", k))
        for s, r in rows:
            out.append(("step", k, s))
            if r.cursor > 0 and len(r.seq) - r.cursor >= 2:
                out.append(("spec", k, s))
            if r.cursor > 0 and not any(
                    sh.rid == r.rid for sh in world.ships):
                for j in world.alive():
                    if j != k:
                        out.append(("ship", k, s, j))
    for i, sh in enumerate(world.ships):
        out.append(("commit", i))
        out.append(("xfail", i))
    if len(world.alive()) == 2:
        for k in world.alive():
            out.append(("kill", k))
        for k in world.routable():
            if len(world.routable()) == 2:
                out.append(("drain", k))
    return out


def _label(world, t) -> str:
    kind = t[0]
    if kind in ("route", "admit", "preempt", "evict", "kill", "drain"):
        return f"{kind}@{t[1]}"
    if kind in ("step", "spec"):
        r = world.engines[t[1]].slot_req[t[2]]
        return f"{kind}(r{r.rid}@{t[1]})"
    if kind == "ship":
        r = world.engines[t[1]].slot_req[t[2]]
        return f"ship(r{r.rid}:{t[1]}->{t[3]})"
    sh = world.ships[t[1]]
    return f"{kind}(r{sh.rid})"


def _apply(world, t) -> None:
    """Execute one transition on ``world`` IN PLACE, through the
    production seam verbs."""
    kind = t[0]
    ops = world.ops
    if kind == "route":
        world.engines[t[1]].waiting.append(world.queue.popleft())
    elif kind == "admit":
        ops.admit(world.engines[t[1]])
    elif kind == "preempt":
        eng = world.engines[t[1]]
        if eng.waiting:
            ops.preempt_for(eng, eng.waiting[0])
    elif kind == "evict":
        ops.evict_one(world.engines[t[1]], set())
    elif kind == "step":
        _, k, s = t
        eng = world.engines[k]
        req = eng.slot_req[s]
        take = min(eng._chunk_for(req), len(req.seq) - req.cursor)
        held = eng._pages_held(req.cursor)
        need = eng._pages_held(req.cursor + take)
        if not ops.ensure_pages(eng, s, held, need, {s}):
            return                     # deferred (evictions may have run)
        ops.advance_cursor(eng, s, req, take)
        if req.cursor == len(req.seq):
            req.generated.append(_tok(req))
            ops.complete(eng, req, s)
    elif kind == "spec":
        # one all-rejected verify row: the frontier draw emits, every
        # draft rolls back — the production rollback_draft discipline
        _, k, s = t
        eng = world.engines[k]
        req = eng.slot_req[s]
        take = min(eng._chunk_for(req), len(req.seq) - req.cursor)
        held = eng._pages_held(req.cursor)
        need = eng._pages_held(req.cursor + take)
        if not ops.ensure_pages(eng, s, held, need, {s}):
            return
        old_cursor = req.cursor
        req.generated.append(_tok(req))
        ops.rollback_draft(eng, s, req, old_cursor, take, 0)
        ops.complete(eng, req, s)
    elif kind == "ship":
        _, k, s, j = t
        eng, dst = world.engines[k], world.engines[j]
        req = eng.slot_req[s]
        npg = eng._pages_held(req.cursor)
        req.parked = True              # source pins its pages
        got = ops.reserve_shipped(dst, req)
        if got is None:
            req.parked = False         # no reservation: unwind the pin
            req.slot = s
            return
        dslot, dpids = got
        src_pids = [int(p) for p in eng.table[s, :npg]]
        world.ships.append(_Ship(req.rid, k, s, j, dslot, dpids,
                                 src_pids))
    elif kind == "commit":
        sh = world.ships.pop(t[1])
        ops.ship_commit(world.engines[sh.src], sh.pslot,
                        world.engines[sh.dst],
                        world.requests[sh.rid])
    elif kind == "xfail":
        sh = world.ships.pop(t[1])
        ops.ship_abort(world.engines[sh.dst], sh.dslot,
                       world.requests[sh.rid], sh.pslot)
        world._last_xfail = sh         # checked by _check_state (SV006)
    elif kind == "kill":
        _kill(world, t[1])
    elif kind == "drain":
        k = t[1]
        world.draining.add(k)
        ops.drain_requeue(world.engines[k], world.queue)
    else:                              # pragma: no cover
        raise ValueError(kind)


def _kill(world, k: int) -> None:
    """ReplicaDeath, mirroring ServingFleet._kill + the
    DisaggregatedEngine._fail_over ship discipline: resolve in-flight
    ships first (dst death unparks the row in place at the source; src
    death force-commits at the destination), then the seam's
    failover_requeue drains everything the dead replica held, then the
    survivors' drains are cancelled if the death left no routable
    replica (the SV007 counterexample fix)."""
    ops = world.ops
    for sh in [s for s in world.ships if s.src == k or s.dst == k]:
        world.ships.remove(sh)
        req = world.requests[sh.rid]
        if sh.dst == k:
            # destination died: the source keeps the row, unparked in
            # place (the _fail_over decode-death path); the dead
            # reservation vanishes with the destination's pool
            world.engines[k].slot_req[sh.dslot] = None
            req.slot = sh.pslot
            req.parked = False
        else:
            # source died: force-commit at the destination without a
            # source release (the pages died with the pool)
            world.engines[k].slot_req[sh.pslot] = None
            ops.commit_shipped(world.engines[sh.dst], req)
    world.dead.add(k)
    world.draining.discard(k)
    eng = world.engines[k]
    held, seen = [], set()
    for r in (list(eng.slot_req) + list(eng.waiting)
              + list(eng.pending)):
        if r is not None and not r.done and id(r) not in seen:
            seen.add(id(r))
            held.append(r)
    ops.failover_requeue(held, world.queue, None)
    eng.slot_req = [None] * eng.cfg.slots
    eng.table[:] = -1
    eng.waiting.clear()
    eng.pending.clear()
    if not world.routable() and world.draining:
        # a drain that can no longer hand off must cancel, or the
        # backlog is unroutable forever (ServingFleet._kill does the
        # same since this checker first flagged it)
        world.draining.clear()


# ------------------------------------------------------------------ checks


def _repro(world, label=None) -> str:
    steps = world.trace + ((label,) if label else ())
    return " -> ".join(steps) if steps else "<initial state>"


def _finding(rule, msg, world, label=None) -> Finding:
    return Finding(
        rule=rule, kernel="serving-protocol", site="servlint",
        message=f"{msg}; repro: {_repro(world, label)}")


def _check_pages(world) -> Finding | None:
    """SV001/SV002 static halves: every page of every alive pool is
    exactly one of free / reclaimable-cached / table-referenced."""
    for k in world.alive():
        eng = world.engines[k]
        pool = eng.pool
        intable = {}
        for p in eng.table.flat:
            if p >= 0:
                intable[int(p)] = intable.get(int(p), 0) + 1
        free = set(pool.free)
        for pg in range(pool.npages):
            r = int(pool.refs[pg])
            if r == 0 and intable.get(pg):
                return _finding(
                    "SV002",
                    f"replica {k} block table references freed page "
                    f"{pg} (refcount 0)", world)
            if r == 0 and pg not in free and pg not in pool._reclaim:
                return _finding(
                    "SV001",
                    f"replica {k} page {pg} is unreachable: refcount "
                    f"0 but on neither the free list nor the reclaim "
                    f"cache", world)
            if r > 0 and not intable.get(pg):
                return _finding(
                    "SV001",
                    f"replica {k} page {pg} leaked: refcount {r} but "
                    f"no block-table row references it", world)
            if pg in free and r != 0:
                return _finding(
                    "SV002",
                    f"replica {k} page {pg} is on the free list with "
                    f"refcount {r}", world)
    return None


def _check_ships(world) -> Finding | None:
    """SV003: an in-flight ship's pinned source pages and reserved
    destination pages must stay held until the record resolves."""
    for sh in world.ships:
        src, dst = world.engines[sh.src], world.engines[sh.dst]
        for pg in sh.src_pids:
            if int(src.pool.refs[pg]) < 1:
                return _finding(
                    "SV003",
                    f"source page {pg} of in-flight ship of r{sh.rid} "
                    f"({sh.src}->{sh.dst}) was freed mid-flight",
                    world)
        for pg in sh.dpids:
            if int(dst.pool.refs[pg]) < 1:
                return _finding(
                    "SV003",
                    f"destination landing page {pg} reserved for "
                    f"r{sh.rid} ({sh.src}->{sh.dst}) was freed before "
                    f"the transfer resolved", world)
    return None


def _check_requests(world) -> Finding | None:
    """SV004: conservation of the request multiset."""
    shipping = {sh.rid for sh in world.ships}
    for rid, req in sorted(world.requests.items()):
        n = sum(1 for r in world.queue if r.rid == rid)
        for k in world.alive():
            eng = world.engines[k]
            n += sum(1 for r in eng.waiting if r.rid == rid)
            n += sum(1 for r in eng.pending if r.rid == rid)
            n += sum(1 for r in eng.slot_req
                     if r is not None and r.rid == rid)
        want = 0 if req.done else (2 if rid in shipping else 1)
        if n != want:
            what = "lost" if n < want else "duplicated"
            return _finding(
                "SV004",
                f"request r{rid} {what}: found {n} live copies, "
                f"expected {want} (done={req.done}, "
                f"shipping={rid in shipping})", world)
    return None


def _check_xfail(world) -> Finding | None:
    """SV006 (leak half): after a transport-exhausted ship, the
    destination reservation must be fully rolled back."""
    sh = getattr(world, "_last_xfail", None)
    if sh is None or sh.dst in world.dead:
        return None
    dst = world.engines[sh.dst]
    holder = dst.slot_req[sh.dslot]
    if holder is not None and holder.rid == sh.rid:
        return _finding(
            "SV006",
            f"transport-exhausted ship of r{sh.rid} leaked its "
            f"destination reservation: slot {sh.dslot} on replica "
            f"{sh.dst} is still occupied", world)
    for pg in sh.dpids:
        if int(dst.pool.refs[pg]) > 0 and not (dst.table == pg).any():
            return _finding(
                "SV006",
                f"transport-exhausted ship of r{sh.rid} leaked "
                f"reserved landing page {pg} on replica {sh.dst}",
                world)
    return None


def _check_cursor(pre, world, label) -> Finding | None:
    """SV005: a request resident in the same slot across a transition
    must not move its cursor backwards (legal rewinds go off-slot at
    cursor 0, or through rollback_draft which lands at >= old+1)."""
    for rid, old in pre.items():
        k, s, cursor = old
        if k in world.dead:
            continue
        req = world.engines[k].slot_req[s]
        if req is None or req.rid != rid:
            continue
        if req.cursor < cursor and not (req.cursor == 0
                                        and req.slot is None):
            return _finding(
                "SV005",
                f"request r{rid} cursor regressed {cursor} -> "
                f"{req.cursor} while resident in slot {s} of replica "
                f"{k} — committed-prefix tokens would re-emit", world,
                label)
    return None


def _check_livelock(world) -> Finding | None:
    """SV007: backlog nonempty, nothing resident, no ship in flight,
    and routing + admitting the whole backlog on every routable
    replica admits nothing — no transition can ever make progress."""
    if world.ships:
        return None
    for k in world.alive():
        if any(r is not None for r in world.engines[k].slot_req):
            return None
    backlog = len(world.queue) + sum(
        len(world.engines[k].waiting) + len(world.engines[k].pending)
        for k in world.alive())
    if backlog == 0:
        return None
    probe = world.clone()
    routable = probe.routable()
    for k in probe.alive():
        eng = probe.engines[k]
        if k in routable:
            while probe.queue:
                eng.waiting.append(probe.queue.popleft())
        try:
            probe.ops.admit(eng)
        except Exception:
            pass
        if any(r is not None for r in eng.slot_req):
            return None
    return _finding(
        "SV007",
        f"unroutable livelock: {backlog} request(s) backlogged, no "
        f"replica resident work, and admission on every routable "
        f"replica admits nothing", world)


def _check_state(pre_cursors, world, label) -> Finding | None:
    for check in (_check_pages, _check_ships, _check_requests,
                  _check_xfail):
        f = check(world)
        if f is not None:
            return f
    f = _check_cursor(pre_cursors, world, label)
    if f is not None:
        return f
    return _check_livelock(world)


def _cursors(world) -> dict:
    out = {}
    for k in world.alive():
        for s, r in enumerate(world.engines[k].slot_req):
            if r is not None:
                out[r.rid] = (k, s, r.cursor)
    return out


# ---------------------------------------------------------------- explorer


def explore(ops: ProtocolOps | None = None, *,
            max_states: int = 20_000) -> tuple:
    """Exhaustive bounded BFS over the abstract fleet driven by
    ``ops`` (production :class:`ProtocolOps` by default). Stops at the
    FIRST finding (BFS order makes its repro interleaving minimal) or
    when the reachable graph — capped at ``max_states``; pass
    ``max_states <= 0`` for an uncapped (truly exhaustive) run — is
    exhausted. Returns ``(findings, stats)`` where stats carries
    ``states`` (distinct states visited), ``transitions`` (edges
    executed) and ``complete`` (True when the full reachable graph fit
    under the cap)."""
    ops = ops if ops is not None else ProtocolOps()
    if max_states <= 0:                     # 0 = uncapped (nightly CI)
        max_states = float("inf")
    root = _World(ops)
    f = _check_state({}, root, None)
    if f is not None:
        return [f], {"states": 1, "transitions": 0, "complete": True}
    seen = {root.key()}
    frontier = deque([root])
    states, edges, truncated = 1, 0, False
    while frontier:
        world = frontier.popleft()
        pre = _cursors(world)
        for t in _enabled(world):
            label = _label(world, t)
            succ = world.clone()
            edges += 1
            try:
                _apply(succ, t)
            except AssertionError as exc:
                rule = "SV006" if t[0] in ("ship", "commit",
                                           "xfail") else "SV002"
                why = ("ship handshake violated the pool/parking "
                       "discipline" if rule == "SV006"
                       else "PagePool refcount assertion tripped "
                            "(double free / alloc of a live page)")
                return ([_finding(rule, f"{why}: {exc}", world,
                                  label)],
                        {"states": states, "transitions": edges,
                         "complete": False})
            succ.trace = world.trace + (label,)
            key = succ.key()
            if key in seen:
                continue
            if states >= max_states:
                truncated = True
                continue
            seen.add(key)
            states += 1
            f = _check_state(pre, succ, label)
            if f is not None:
                return [f], {"states": states, "transitions": edges,
                             "complete": False}
            frontier.append(succ)
    return [], {"states": states, "transitions": edges,
                "complete": not truncated}


# ---------------------------------------------------------------- fixtures

# One deliberately-broken ProtocolOps per rule — each mutation is built
# THROUGH the production seam (a subclass overriding exactly one verb),
# so the checker proves it would catch that bug in the real engines.


class _LeakOnFree(ProtocolOps):
    """SV001: free_slot drops the table without releasing refcounts."""

    seeds_rule = "SV001"

    def free_slot(self, eng, slot):
        eng.table[slot] = -1           # BUG: pages stay refcounted
        eng.slot_req[slot] = None


class _DoubleFree(ProtocolOps):
    """SV002: free_slot releases every page twice."""

    seeds_rule = "SV002"

    def free_slot(self, eng, slot):
        for pg in eng.table[slot]:
            if pg >= 0:
                eng.pool.release(int(pg))
                eng.pool.release(int(pg))   # BUG
        eng.table[slot] = -1
        eng.slot_req[slot] = None


class _EvictParked(ProtocolOps):
    """SV003: evict_one ignores the parked (pages-pinned) guard."""

    seeds_rule = "SV003"

    def evict_one(self, eng, batched):
        victims = [
            (eng._rank(req), req.arrival, s)
            for s, req in enumerate(eng.slot_req)
            if req is not None and s not in batched
            and not req.done           # BUG: parked rows are victims
        ]
        if not victims:
            return False
        _, _, s = max(victims)
        req = eng.slot_req[s]
        req.cursor = 0
        req.evictions += 1
        req.slot = None
        self.free_slot(eng, s)
        eng.waiting.appendleft(req)
        eng.stats.evictions += 1
        return True


class _DropOnKill(ProtocolOps):
    """SV004: failover_requeue silently drops the newest request."""

    seeds_rule = "SV004"

    def failover_requeue(self, held, queue, stats=None):
        drained = sorted(held, key=lambda r: r.arrival)
        return super().failover_requeue(drained[:-1], queue,
                                        stats)   # BUG


class _DeepRollback(ProtocolOps):
    """SV005: speculative rollback rewinds past the committed
    frontier token."""

    seeds_rule = "SV005"

    def rollback_draft(self, eng, s, req, old_cursor, take, accepted):
        req.cursor = max(0, old_cursor - 1)      # BUG: not old+1+acc
        keep = eng._pages_held(req.cursor)
        got = eng._pages_held(old_cursor + take)
        for pg in range(keep, got):
            if eng.table[s, pg] >= 0:
                eng.pool.release(int(eng.table[s, pg]))
                eng.table[s, pg] = -1
        if eng.pool.prefix_cache:
            eng._register_frozen(req, s, old_cursor)


class _EagerCommit(ProtocolOps):
    """SV006: destination commit observable before source release."""

    seeds_rule = "SV006"

    def ship_commit(self, src_eng, pslot, dst_eng, req):
        self.commit_shipped(dst_eng, req)        # BUG: dst first
        self.release_parked(src_eng, pslot)


class CpProtocolOps(ProtocolOps):
    """Production verbs over a cp=2-sharded page pool — the cp-shard
    ownership facet's CLEAN half. Every alloc routes by logical page
    index and every release/register by global page id; the bounded
    exploration proves the routing keeps SV001/SV002 across shards
    (no page stranded on, or double-freed from, the wrong shard)."""

    cp = 2


class _CpWrongShardFree(CpProtocolOps):
    """cp facet true positive (SV001): ``free_slot`` releases only the
    pages the FIRST cp shard owns — the bug a cp port keeps when its
    teardown loop still iterates the single-pool id space. A long
    row's cross-shard tail (the pages the sharded pool parked on
    shard 1) keeps its refcount with no table referent."""

    seeds_rule = "SV001"

    def free_slot(self, eng, slot):
        pool = eng.pool
        for pg in eng.table[slot]:
            if pg >= 0 and pool.shard_of(int(pg)) == 0:  # BUG: shard 0 only
                pool.release(int(pg))
        eng.table[slot] = -1
        eng.slot_req[slot] = None


class _NeverAdmit(ProtocolOps):
    """SV007: admission sorts the queue and admits nothing."""

    seeds_rule = "SV007"

    def admit(self, eng):
        while eng.pending and eng.pending[0].arrival <= eng.step_count:
            eng.waiting.append(eng.pending.popleft())
        if not eng.waiting:
            return
        eng.waiting = deque(sorted(                # BUG: sort-only
            eng.waiting,
            key=lambda r: (eng._eff_rank(r), r.arrival, r.rid)))


#: rule id -> mutated-ops factory (the seeded true positives). Keys
#: are the seeded RULE with an optional facet suffix: ``SV001cp`` is
#: the cp-shard ownership facet, caught by SV001 over a cp=2-sharded
#: pool (its clean twin is :class:`CpProtocolOps`).
FIXTURES = {
    "SV001": _LeakOnFree,
    "SV001cp": _CpWrongShardFree,
    "SV002": _DoubleFree,
    "SV003": _EvictParked,
    "SV004": _DropOnKill,
    "SV005": _DeepRollback,
    "SV006": _EagerCommit,
    "SV007": _NeverAdmit,
}


def lint_serving(ops: ProtocolOps | None = None, *,
                 fixture: str | None = None,
                 max_states: int = 20_000) -> tuple:
    """Model-check the serving protocol. ``fixture`` selects a seeded
    mutated-ops true positive from :data:`FIXTURES` instead of the
    production ops. Returns ``(findings, stats)``."""
    if fixture is not None:
        if fixture not in FIXTURES:
            raise ValueError(
                f"unknown servlint fixture {fixture!r} (want one of "
                f"{sorted(FIXTURES)})")
        ops = FIXTURES[fixture]()
    return explore(ops, max_states=max_states)
