"""Event/trace model for shmemlint and the active-recorder registry.

The ``lang.shmem`` primitives and the abstract evaluator's patched
Pallas environment feed a :class:`Recorder` while a kernel body is
symbolically executed once per rank. The result is one straight-line
event list per rank; :mod:`checks` replays all of them together as a
cross-rank schedule.

Events are deliberately low-level — every cross-rank interaction is
expressed as semaphore credits and consuming waits, exactly the TPU
semantics the kernels are written against:

* a remote put delivers one credit to the *sender's* send semaphore
  (local drain) and one to the *receiver's* recv semaphore (arrival,
  ordered after the payload lands);
* ``signal_op`` delivers ``inc`` credits to the target rank's
  semaphore;
* a wait for value ``v`` blocks until ``v`` credits are available and
  consumes them (TPU consuming-wait semantics).

Barrier/fence events ride along as markers for the hygiene checks and
phase attribution.
"""

from __future__ import annotations

from dataclasses import dataclass, field


# ------------------------------------------------------------------ regions

@dataclass(frozen=True)
class Region:
    """A rectangular element region of a named ref: per-dim half-open
    ``[lo, hi)`` intervals. Ref names are SPMD — the same name on two
    ranks denotes each rank's own instance of the symmetric buffer."""

    ref: str
    lo: tuple
    hi: tuple

    def overlaps(self, other: "Region") -> bool:
        if self.ref != other.ref:
            return False
        ndim = min(len(self.lo), len(other.lo))
        for d in range(ndim):
            if self.hi[d] <= other.lo[d] or other.hi[d] <= self.lo[d]:
                return False
        return True

    def __str__(self):
        spans = ",".join(
            f"{lo}:{hi}" for lo, hi in zip(self.lo, self.hi)
        )
        return f"{self.ref}[{spans}]"


# ------------------------------------------------------------------- events

@dataclass
class Event:
    rank: int = -1      # assigned by the recorder
    idx: int = -1       # position in the rank's trace
    phase: int = 0      # number of barrier_all calls passed on this rank


@dataclass
class ReadEvent(Event):
    region: Region = None


@dataclass
class WriteEvent(Event):
    """A local store. ``copy_src`` is set when the stored value was read
    verbatim out of another ref region on the same rank (the evaluator's
    tagged reads detect ``dst[...] = src[...]``); ``add_srcs`` when it
    was the elementwise sum of two such reads (the VMEM ring fold).
    Either gives the dataflow pass (SL008) a provenance edge; a plain
    write is locally computed data."""

    region: Region = None
    copy_src: Region = None
    add_srcs: tuple = None      # (Region, Region) for dst = a + b


@dataclass
class PutEvent(Event):
    """A started DMA. ``dst_rank == rank`` with ``local=True`` is a
    local async copy (single completion semaphore ``send_key``)."""

    src_region: Region = None
    dst_region: Region = None
    dst_rank: int = -1
    send_key: tuple = None      # (sem_name, slot) on the issuing rank
    recv_key: tuple = None      # (sem_name, slot) on the dst rank
    local: bool = False


@dataclass
class SignalEvent(Event):
    key: tuple = None
    target: int = -1
    inc: int = 1
    site: str | None = None


@dataclass
class WaitEvent(Event):
    key: tuple = None
    value: int = 1


@dataclass
class QuantEvent(Event):
    """A wire quantization: ``src`` → 1-byte payload ``q`` + f32 scale
    plane ``s`` (lang.wire layout). Each QuantEvent is its own scale
    group; the dataflow pass tags the q and s regions with the event's
    identity so a later dequant can be checked for pairing (SL010)."""

    src_region: Region = None
    q_region: Region = None
    s_region: Region = None
    chunk_rows: int = 1


@dataclass
class DequantEvent(Event):
    """A wire dequantization (``add_region`` None) or fused
    dequant-accumulate (``dst = add + q·s``): the provenance of ``q``
    flows to ``dst`` and the scale group held by ``s`` must match the
    group ``q`` was quantized under (SL010).

    ``epilogue=True`` is the int8→MXU consumption edge: the payload is
    fed to the MXU AS int8 and its scale is folded into the f32/s32
    accumulator epilogue — the bytes in ``q`` stay physically quantized
    but are vouched-consumed, so the dataflow pass marks them
    dequantized in place (and ``s_region=None`` on an epilogue event is
    the scale-fold-omitted bug, SL009)."""

    q_region: Region = None
    s_region: Region = None
    dst_region: Region = None
    add_region: Region = None
    epilogue: bool = False


@dataclass
class OobEvent(Event):
    """A ref index that extends past the buffer's extent. numpy slicing
    silently CLIPS out-of-range windows, so without this marker an
    over-wide access would be analyzed as its clipped shadow and pass
    every check; the evaluator records the REQUESTED region here and the
    dataflow pass surfaces it as a contract violation (SL008 — e.g. a
    grid kernel's out-DMA overrunning the parking zone)."""

    region: Region = None


@dataclass
class AddEvent(Event):
    """A streamed elementwise fold ``dst = a + b`` (the HBM ring folds'
    ew_add_pipeline). Provenance of both operands accumulates into
    ``dst`` — the edge the reduce-contract check (SL008) rides."""

    a_region: Region = None
    b_region: Region = None
    dst_region: Region = None


@dataclass
class BarrierEvent(Event):
    collective_id: object = None


@dataclass
class FenceEvent(Event):
    pass


# ----------------------------------------------------------------- recorder

@dataclass(frozen=True)
class RefMeta:
    """Static facts about one root buffer, captured at ref construction
    (abstract.build_refs): the dataflow pass needs shapes to materialize
    provenance state and dtypes to recognize wire payload rails."""

    shape: tuple
    dtype: object           # np.dtype (None for semaphores)
    space: str
    is_input: bool
    index: int              # position in the kernel's ref list


@dataclass
class LaunchInfo:
    """Static launch facts the checks need alongside the traces."""

    kernel: str = "?"
    site: str | None = None
    collective_id: object = None
    vmem_limit_bytes: int | None = None
    vmem_bytes: int = 0                 # VMEM-resident working set
    vmem_breakdown: tuple = ()


class Recorder:
    """Per-kernel-family trace recorder. ``me`` is the rank currently
    being symbolically executed; hooks consult :func:`active_recorder`
    and append to ``traces[me]``."""

    def __init__(self, n: int, axis: str, mesh_axes=None,
                 info: LaunchInfo | None = None):
        self.n = int(n)
        self.axis = axis
        self.mesh_axes = tuple(mesh_axes) if mesh_axes else (axis,)
        self.me: int | None = None
        self.info = info or LaunchInfo()
        self.traces: list[list[Event]] = [[] for _ in range(self.n)]
        self._phase = 0
        self.barrier_sem_used = False
        #: root ref name -> RefMeta, in kernel-signature order (filled by
        #: abstract.build_refs; identical across ranks by SPMD symmetry)
        self.ref_meta: dict = {}
        #: input ref index -> initial ndarray (filled by
        #: abstract.build_refs). Value-level contract facets — e.g. the
        #: ragged family's attention-topology descriptor — read the
        #: OPERANDS, not just the traces, so the replay keeps them.
        self.input_values: dict = {}

    def emit(self, ev: Event) -> Event:
        assert self.me is not None, "recorder has no current rank"
        ev.rank = self.me
        ev.idx = len(self.traces[self.me])
        ev.phase = self._phase
        if isinstance(ev, BarrierEvent):
            self._phase += 1
        self.traces[self.me].append(ev)
        return ev

    def start_rank(self, me: int) -> None:
        self.me = int(me)
        self._phase = 0

    # convenience used by checks/tests
    def events(self, kind=None):
        for r in range(self.n):
            for ev in self.traces[r]:
                if kind is None or isinstance(ev, kind):
                    yield ev


_ACTIVE: Recorder | None = None


def active_recorder() -> Recorder | None:
    """The recorder the ``lang.shmem`` hook layer feeds, or None when no
    symbolic execution is in progress (the common case — every hook
    call site checks this first and falls through to real Pallas)."""
    return _ACTIVE


def set_recorder(rec: Recorder | None) -> Recorder | None:
    global _ACTIVE
    old = _ACTIVE
    _ACTIVE = rec
    return old
