"""Abstract evaluator: run SHMEM kernel bodies symbolically, per rank.

A kernel body is an ordinary Python function over Pallas refs. Under
the evaluator it runs *eagerly* with:

* concrete rank values — ``lang.my_pe`` returns the rank currently
  being executed (the ``lang.shmem`` hook layer consults
  :func:`events.active_recorder`);
* :class:`AbsRef` stand-ins for refs — real numpy storage, so index
  arithmetic and compute (``jnp.dot`` on loaded blocks, fold-in adds)
  execute concretely, while every read/write is recorded with its
  element region;
* :class:`AbsSem`/:class:`AbsDMA` stand-ins for semaphores and DMA
  descriptors — starts, waits and signals become trace events instead
  of hardware ops;
* a patched Pallas/lax environment (:func:`patched_pallas`):
  ``pl.when`` evaluates its concrete predicate, ``lax.fori_loop``
  becomes a Python loop, ``emit_pipeline`` records the hull of its
  block accesses, delays are no-ops.

One execution per rank yields the per-rank event traces
(:class:`events.Recorder`) that :mod:`checks` replays cross-rank.

Heuristic, documented: a remote put also copies its source values into
the *local* instance of the destination buffer. Per-rank execution has
no peer memory; for rank-symmetric inputs (the registry's lint shapes)
this models "the peer sends what I would send", which is what
count-carrying protocols (the MoE metadata heads) need to steer their
receive loops correctly.
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import itertools

import numpy as np

from triton_distributed_tpu.analysis import events as ev


def _as_int(x) -> int:
    """Concretize an index/count that may be a 0-d jax array."""
    return int(x)


# ------------------------------------------------------------------- refs

class TaggedArray(np.ndarray):
    """Value read out of an :class:`AbsRef`, remembering *where* it was
    read from so a subsequent store can be recognized as a copy
    (``dst[...] = src[...]``) or a two-operand fold
    (``dst[...] = a[...] + b[...]``) — the provenance edges the SL008
    delivery pass follows. Any other arithmetic strips the tag: the
    result is then locally computed data, which is exactly what the
    dataflow model wants."""

    def __array_finalize__(self, obj):
        # never inherit a tag through views/copies/astype — a tag is
        # only valid on the exact array a read returned
        self.src_region = None
        self.add_srcs = None

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        tags = [getattr(i, "src_region", None) for i in inputs]
        plain = tuple(
            i.view(np.ndarray) if isinstance(i, TaggedArray) else i
            for i in inputs
        )
        out = getattr(ufunc, method)(*plain, **kwargs)
        if (
            ufunc is np.add and method == "__call__" and len(inputs) == 2
            and all(t is not None for t in tags)
            and isinstance(out, np.ndarray)
        ):
            out = out.view(TaggedArray)
            out.add_srcs = (tags[0], tags[1])
        return out


class AbsRef:
    """Ref stand-in with numpy storage. Views (``.at[...]`` and the
    evaluator's slicing) share the parent storage and keep ROOT-buffer
    coordinates: ``origin`` spans every root dim (including ones a
    scalar index dropped) and ``dims`` maps each remaining data dim to
    its root dim, so recorded regions always index the root buffer."""

    def __init__(self, name, data, space="vmem", rec=None, origin=None,
                 root=None, dims=None):
        self.name = name
        self.data = data                      # np.ndarray (possibly a view)
        self.space = space
        self.rec = rec
        self.origin = tuple(origin or (0,) * data.ndim)
        self.root = root or name
        self.dims = tuple(range(data.ndim)) if dims is None else tuple(dims)

    # -- python surface the kernels use ------------------------------------
    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def at(self):
        return _AtIndexer(self)

    def __getitem__(self, idx):
        view = self._slice(idx)
        if self.rec is not None:
            self.rec.emit(ev.ReadEvent(region=view.region()))
        out = np.array(view.data).view(TaggedArray)  # copy — refs mutable
        out.src_region = view.region()
        return out

    def __setitem__(self, idx, value):
        view = self._slice(idx)
        copy_src = getattr(value, "src_region", None)
        add_srcs = getattr(value, "add_srcs", None)
        if np.shape(value) != view.data.shape:
            copy_src = add_srcs = None    # broadcast/partial store: no edge
        if self.rec is not None:
            self.rec.emit(ev.WriteEvent(
                region=view.region(), copy_src=copy_src, add_srcs=add_srcs,
            ))
        view.data[...] = np.broadcast_to(
            np.asarray(value, dtype=self.data.dtype), view.data.shape
        )

    # -- internals ---------------------------------------------------------
    def _slice(self, idx) -> "AbsRef":
        if not isinstance(idx, tuple):
            idx = (idx,)
        np_idx, origin, dims, squeeze = [], list(self.origin), [], []
        req_ext, oob = {}, False
        for d in range(self.ndim):
            rd = self.dims[d]
            dim = self.data.shape[d]
            i = idx[d] if d < len(idx) else slice(None)
            if i is Ellipsis:
                i = slice(None)
            if isinstance(i, slice):
                start = 0 if i.start is None else _as_int(i.start)
                stop = dim if i.stop is None else _as_int(i.stop)
            elif hasattr(i, "start") and hasattr(i, "size"):  # pl.Slice
                start = _as_int(i.start)
                stop = start + _as_int(i.size)
            else:                        # scalar index: slice + squeeze so
                start = _as_int(i)       # the result stays a writable VIEW
                stop = start + 1
                origin[rd] += start
                np_idx.append(slice(start, stop))
                squeeze.append(d)
                continue
            if start < 0 or stop > dim:
                oob = True                # numpy will clip silently —
            req_ext[rd] = stop - start    # remember the REQUESTED window
            np_idx.append(slice(start, stop))
            origin[rd] += start
            dims.append(rd)
        sub = self.data[tuple(np_idx)]
        if squeeze:
            sub = np.squeeze(sub, axis=tuple(squeeze))
        res = AbsRef(
            self.name, sub, self.space, self.rec,
            origin=origin, root=self.root, dims=dims,
        )
        if oob and self.rec is not None:
            lo = tuple(res.origin)
            hi = tuple(
                o + req_ext.get(rd, 1)
                for rd, o in enumerate(res.origin)
            )
            self.rec.emit(ev.OobEvent(region=ev.Region(self.root, lo, hi)))
        return res

    def region(self) -> ev.Region:
        extent = {rd: s for rd, s in zip(self.dims, self.data.shape)}
        lo = tuple(self.origin)
        hi = tuple(
            o + extent.get(rd, 1) for rd, o in enumerate(self.origin)
        )
        return ev.Region(self.root, lo, hi)

    def set_values(self, values) -> None:
        """Raw store WITHOUT a Write event (used by the evaluator's
        local data-propagation for puts — the write is carried by the
        PutEvent itself)."""
        self.data[...] = np.broadcast_to(
            np.asarray(values, dtype=self.data.dtype), self.data.shape
        )

    def __repr__(self):
        return f"AbsRef({self.root}{list(self.origin)}, {self.data.shape})"


class _AtIndexer:
    def __init__(self, ref):
        self._ref = ref

    def __getitem__(self, idx):
        return self._ref._slice(idx)


class AbsSem:
    """Semaphore stand-in. ``.at[idx]`` selects a slot; the (name, slot)
    pair is the identity credits and waits are matched on."""

    def __init__(self, name, shape=(), slot=()):
        self.name = name
        self.shape = tuple(shape)
        self.slot = tuple(slot)

    @property
    def at(self):
        return _SemIndexer(self)

    @property
    def key(self):
        return (self.name, self.slot)

    def __repr__(self):
        return f"AbsSem({self.name}{list(self.slot)})"


class _SemIndexer:
    def __init__(self, sem):
        self._sem = sem

    def __getitem__(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        slot = tuple(_as_int(i) for i in idx)
        return AbsSem(self._sem.name, self._sem.shape, self._sem.slot + slot)


class AbsDMA:
    """DMA-descriptor stand-in. ``start`` emits a PutEvent and locally
    propagates source values into the destination view (see module
    docstring). Wait methods emit consuming waits on the matching
    semaphore slots — including the Pallas idiom of rebuilding a
    descriptor (or a dummy local copy) purely to wait on its semaphore,
    which is why waits do not require a preceding ``start``."""

    def __init__(self, rec, src, dst, send_sem, recv_sem=None, dst_rank=None,
                 local=False):
        self.rec = rec
        self.src, self.dst = src, dst
        self.send_sem, self.recv_sem = send_sem, recv_sem
        self.dst_rank = rec.me if dst_rank is None else _as_int(dst_rank)
        self.local = local

    def start(self):
        self.rec.emit(ev.PutEvent(
            src_region=self.src.region(),
            dst_region=self.dst.region(),
            dst_rank=self.dst_rank,
            send_key=self.send_sem.key,
            recv_key=self.recv_sem.key if self.recv_sem else None,
            local=self.local,
        ))
        if self.src.data.shape == self.dst.data.shape:
            self.dst.set_values(self.src.data)
        return self

    def wait_send(self):
        self.rec.emit(ev.WaitEvent(key=self.send_sem.key, value=1))

    def wait_recv(self):
        key = (self.recv_sem or self.send_sem).key
        self.rec.emit(ev.WaitEvent(key=key, value=1))

    def wait(self):
        if self.local:
            self.rec.emit(ev.WaitEvent(key=self.send_sem.key, value=1))
        else:
            self.wait_send()
            self.wait_recv()


# --------------------------------------------------------- patched pallas

_BARRIER_SEM = "barrier_sem"


def _space_str(ms) -> str:
    s = str(ms).lower()
    for known in ("vmem", "smem", "semaphore", "any"):
        if known in s:
            return "semaphore" if known == "semaphore" else known
    return "any"


@contextlib.contextmanager
def patched_pallas(rec: ev.Recorder):
    """Swap the Pallas/lax entry points kernels actually use for
    evaluator equivalents, for the dynamic extent of one symbolic
    execution. Single-threaded by design (lint runs are not concurrent
    with tracing)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def mk_remote(src_ref, dst_ref, send_sem, recv_sem, device_id,
                  device_id_type=None, **kw):
        return AbsDMA(rec, src_ref, dst_ref, send_sem, recv_sem,
                      dst_rank=device_id)

    def mk_local(src_ref, dst_ref, sem, **kw):
        return AbsDMA(rec, src_ref, dst_ref, sem, None, local=True)

    def sem_signal(sem, inc=1, device_id=None, device_id_type=None, **kw):
        target = rec.me if device_id is None else _as_int(device_id)
        rec.emit(ev.SignalEvent(key=sem.key, target=target,
                                inc=_as_int(inc)))

    def sem_wait(sem, value=1):
        rec.emit(ev.WaitEvent(key=sem.key, value=_as_int(value)))

    def barrier_sem():
        rec.barrier_sem_used = True
        return AbsSem(_BARRIER_SEM)

    def when(pred):
        def deco(fn):
            if bool(pred):
                fn()
            return fn
        return deco

    def fori_loop(lo, hi, body, init, **kw):
        carry = init
        for i in range(_as_int(lo), _as_int(hi)):
            carry = body(i, carry)
        return carry

    def emit_pipeline(body, *, grid, in_specs=None, out_specs=None, **kw):
        in_specs = list(in_specs or [])
        out_specs = list(out_specs or [])

        def hull(spec, ref):
            bs = tuple(_as_int(b) for b in spec.block_shape)
            dims = tuple(_as_int(g) for g in grid)
            pts = itertools.product(*(range(g) for g in dims))
            if int(np.prod(dims)) > 4096:   # affine maps: corners suffice
                pts = itertools.product(*({0, g - 1} for g in dims))
            lo = [None] * len(bs)
            hi = [None] * len(bs)
            for pt in pts:
                blk = spec.index_map(*pt)
                if not isinstance(blk, tuple):
                    blk = (blk,)
                for d, b in enumerate(blk):
                    b = _as_int(b)
                    lo[d] = b * bs[d] if lo[d] is None else min(lo[d], b * bs[d])
                    hi[d] = max(hi[d] or 0, (b + 1) * bs[d])
            hi = [min(h, s) for h, s in zip(hi, ref.data.shape)]
            return ref._slice(tuple(slice(l, h) for l, h in zip(lo, hi)))

        def run(*refs):
            ins, outs = refs[: len(in_specs)], refs[len(in_specs):]
            for spec, ref in zip(in_specs, ins):
                rec.emit(ev.ReadEvent(region=hull(spec, ref).region()))
            for spec, ref in zip(out_specs, outs):
                rec.emit(ev.WriteEvent(region=hull(spec, ref).region()))

        return run

    # mutated by run_symbolic's grid walk (one kernel execution per
    # grid point, ids advancing row-major — the sequential-grid
    # semantics every registered grid kernel pins)
    grid_env = {"ids": (0,) * 8, "dims": (1,) * 8}

    patches = [
        (pltpu, "make_async_remote_copy", mk_remote),
        (pltpu, "make_async_copy", mk_local),
        (pltpu, "semaphore_signal", sem_signal),
        (pltpu, "semaphore_wait", sem_wait),
        (pltpu, "get_barrier_semaphore", barrier_sem),
        (pltpu, "emit_pipeline", emit_pipeline),
        (pl, "when", when),
        (pl, "delay", lambda cycles: None),
        (pl, "program_id", lambda d: grid_env["ids"][d]),
        (pl, "num_programs", lambda d: grid_env["dims"][d]),
        (jax.lax, "fori_loop", fori_loop),
    ]
    saved = []
    for mod, attr, repl in patches:
        saved.append((mod, attr, getattr(mod, attr, None)))
        setattr(mod, attr, repl)
    try:
        yield grid_env
    finally:
        for mod, attr, orig in reversed(saved):
            if orig is None:
                try:
                    delattr(mod, attr)
                except AttributeError:
                    pass
            else:
                setattr(mod, attr, orig)


# ------------------------------------------------------ ref construction

def _ref_names(kernel, count) -> list:
    """Best-effort ref names from the kernel callable's signature (the
    params left unbound by functools.partial), for readable findings."""
    fn, bound = kernel, 0
    while isinstance(fn, functools.partial):
        bound += len(fn.args)
        fn = fn.func
    try:
        params = [
            p for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                          p.VAR_POSITIONAL)
        ]
    except (TypeError, ValueError):
        params = []
    names, i = [], 0
    for p in params[bound:]:
        if p.kind == p.VAR_POSITIONAL:
            break
        names.append(p.name)
    while len(names) < count:
        names.append(f"ref{len(names)}")
    return names[:count]


def build_refs(launch, in_shapes, rec: ev.Recorder, init=None):
    """Materialize the abstract refs for one captured launch:
    ``in_shapes`` — per-device input (shape, dtype) pairs (the one thing
    the capture cannot know); outputs and scratch come from the captured
    ``out_shape``/``scratch_shapes``. ``init`` maps ref NAME -> initial
    ndarray (default zeros). Returns the positional ref list and tallies
    the VMEM working set into ``rec.info``."""
    import jax

    init = dict(init or {})
    specs: list[tuple] = []                  # (kind, shape, dtype, space)
    in_specs = launch.in_specs or []
    for i, (shape, dtype) in enumerate(in_shapes):
        space = _space_str(
            getattr(in_specs[i], "memory_space", "vmem")
        ) if i < len(in_specs) else "vmem"
        specs.append(("ref", shape, np.dtype(dtype), space))
    out_shape = launch.out_shape
    if isinstance(out_shape, (jax.ShapeDtypeStruct,)):
        out_shape = [out_shape]
    out_specs = launch.out_specs
    if out_specs is not None and not isinstance(out_specs, (list, tuple)):
        out_specs = [out_specs]
    for i, o in enumerate(out_shape):
        space = _space_str(
            getattr(out_specs[i], "memory_space", "vmem")
        ) if out_specs and i < len(out_specs) else "vmem"
        specs.append(("ref", tuple(o.shape), np.dtype(o.dtype), space))
    for s in launch.scratch_shapes or ():
        space = _space_str(getattr(s, "memory_space", ""))
        if space == "semaphore" or "SemaphoreType" in type(s).__name__:
            specs.append(("sem", tuple(getattr(s, "shape", ()) or ()),
                          None, "semaphore"))
        else:
            specs.append(("ref", tuple(s.shape), np.dtype(s.dtype), space))

    names = _ref_names(launch.kernel, len(specs))
    n_in = len(in_shapes)
    refs, vmem, breakdown = [], 0, []
    for i, (name, (kind, shape, dtype, space)) in enumerate(
        zip(names, specs)
    ):
        rec.ref_meta.setdefault(name, ev.RefMeta(
            shape=tuple(shape), dtype=dtype, space=space,
            is_input=(kind == "ref" and i < n_in), index=i,
        ))
        if kind == "sem":
            refs.append(AbsSem(name, shape))
            continue
        data = init.get(name, init.get(i))
        data = (np.zeros(shape, dtype) if data is None
                else np.array(data, dtype).reshape(shape))
        if kind == "ref" and i < n_in:
            # value-level contract facets (the ragged topology check)
            # read input OPERANDS at replay time
            rec.input_values.setdefault(i, np.array(data, copy=True))
        refs.append(AbsRef(name, data, space, rec))
        if space in ("vmem", "smem"):
            vmem += data.nbytes
            breakdown.append((name, data.nbytes))
    rec.info.vmem_bytes = vmem
    rec.info.vmem_breakdown = tuple(breakdown)
    return refs


def run_symbolic(launch, in_shapes, n: int, *, axis="x", mesh_axes=None,
                 init=None, kernel_name=None, site=None) -> ev.Recorder:
    """Symbolically execute ``launch.kernel`` once per rank on an
    abstract ``n``-rank mesh; returns the filled recorder."""
    info = ev.LaunchInfo(
        kernel=kernel_name or launch.name or "?",
        site=site,
        collective_id=launch.collective_id,
        vmem_limit_bytes=launch.vmem_limit_bytes,
    )
    rec = ev.Recorder(n, axis, mesh_axes, info)
    # grid kernels (the ragged serving family) execute once PER GRID
    # POINT, row-major, with persistent refs/scratch across steps —
    # the sequential-grid semantics their SMEM slot carries and
    # cross-step DMA prefetches rely on. Gridless launches (every
    # collective family) run exactly once, as before.
    grid = launch.grid
    gs = getattr(launch, "grid_spec", None)
    if grid is None and gs is not None:
        grid = getattr(gs, "grid", None)
    points = (
        list(itertools.product(*(range(int(d)) for d in grid)))
        if grid else [()]
    )
    for me in range(n):
        refs = build_refs(launch, in_shapes, rec, init=init)
        rec.start_rank(me)
        old = ev.set_recorder(rec)
        try:
            with patched_pallas(rec) as grid_env:
                if grid:
                    grid_env["dims"] = tuple(int(d) for d in grid) + (
                        (1,) * (8 - len(grid))
                    )
                for ids in points:
                    if ids:
                        grid_env["ids"] = tuple(ids) + (0,) * (8 - len(ids))
                    launch.kernel(*refs)
        finally:
            ev.set_recorder(old)
    rec.me = None
    return rec
