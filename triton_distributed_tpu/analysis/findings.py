"""Finding model and the SL rule catalog.

Rule IDs are STABLE — tests, suppression annotations and docs refer to
them by name (docs/ANALYSIS.md is the human-facing catalog). Adding a
rule appends; renumbering is a breaking change.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

#: version of the machine-readable finding schema (``--json`` output and
#: :meth:`Finding.to_json`). Bump when a field is added/renamed so
#: downstream consumers (CI dashboards, bench parsers) can dispatch.
SCHEMA_VERSION = 3


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2


#: rule id -> (slug, default severity, one-line description)
RULES = {
    "SL001": (
        "credit-imbalance",
        Severity.ERROR,
        "semaphore credits left unconsumed at kernel exit (signals/DMA "
        "arrivals exceed waits) — the next launch reusing the semaphore "
        "inherits stale credits and releases a wait early",
    ),
    "SL002": (
        "unsatisfiable-wait",
        Severity.ERROR,
        "a semaphore wait whose required credits never arrive on any "
        "rank — at runtime this is a silent hang the watchdog must catch",
    ),
    "SL003": (
        "deadlock-cycle",
        Severity.ERROR,
        "cross-rank wait-for cycle: every rank in the chain is parked in "
        "a wait whose credit is behind another parked rank's wait",
    ),
    "SL004": (
        "unsynchronized-buffer-write",
        Severity.ERROR,
        "a remote DMA lands in a symmetric-buffer region that a local "
        "access also touches, with no wait/fence ordering the two "
        "(write-after-read / write-after-write over RDMA)",
    ),
    "SL005": (
        "barrier-hygiene",
        Severity.ERROR,
        "collective_id misuse: duplicate id across kernel families, "
        "barrier-semaphore use without a collective_id, or ranks "
        "disagreeing on the barrier sequence",
    ),
    "SL006": (
        "vmem-overcommit",
        Severity.ERROR,
        "the kernel's VMEM-resident working set (inputs + outputs + "
        "scratch) exceeds the per-core VMEM budget",
    ),
    "SL007": (
        "undrained-dma",
        Severity.WARNING,
        "a started DMA whose send (local completion) semaphore is never "
        "waited — the kernel can exit with the transfer in flight "
        "(missing quiet()/wait_send())",
    ),
    "SL008": (
        "delivery-incompleteness",
        Severity.ERROR,
        "the kernel terminates without satisfying its declared delivery "
        "contract: a gather/permute destination missing a source chunk "
        "or holding one twice, a reduction folding a rank's contribution "
        "zero or multiple times, or raw quantized wire bytes left in the "
        "output — caught even when every semaphore balances",
    ),
    "SL009": (
        "wire-rail-divergence",
        Severity.ERROR,
        "the quantized payload rail and its scale-plane rail diverge: a "
        "payload RDMA with no paired scale RDMA, the two rails guarded "
        "by the same semaphore credits (a scale arrival can release the "
        "payload wait), a scale plane whose layout drifts from the "
        "lang.wire contract, or a scale plane consumed before its "
        "arrival is ordered",
    ),
    "SL010": (
        "stale-scale-read",
        Severity.ERROR,
        "a dequantize consumes a scale plane from a different "
        "quantization than its payload slab (e.g. hop h's bytes "
        "dequantized with hop h-1's scales in a double-buffered "
        "workspace) — silently wrong values, no protocol violation",
    ),
    "SL011": (
        "hop-critical-path",
        Severity.ERROR,
        "the deepest delivery chain into the contract destination rides "
        "more remote hops than the ring-optimal n-1 — the schedule "
        "serializes or detours transfers; the replay's per-element hop "
        "counters are fed to tune.perf_model.hop_critical_path_ms to "
        "project the wall-clock regression before any hardware run",
    ),
    "SL012": (
        "contract-drift",
        Severity.ERROR,
        "the hand-declared DeliveryContract disagrees with the one "
        "inferred from the family's XLA twin + replay provenance: wrong "
        "kind class (gather/permute vs reduce vs local), a dst root "
        "that never exhibits the twin's delivery pattern, "
        "over/under-declared payload_per_src, missing or stray source "
        "ranks, or full/own-absent drift — the declaration would make "
        "SL008 check the wrong obligation",
    ),
    "SL013": (
        "undeclared-contract",
        Severity.WARNING,
        "a registered family carries no declared DeliveryContract; "
        "contract inference derived one from the XLA twin so the SL008 "
        "completeness pass still runs, but the gap should be closed by "
        "declaring the contract in kernels/registry.py",
    ),
    "MC001": (
        "mosaic-f8-cast",
        Severity.ERROR,
        "the kernel body casts to/from an 8-bit float inside the Pallas "
        "kernel; this toolchain's Mosaic backend rejects f8 extensions "
        "('Only 16-bit to 32-bit extensions supported') — carry int8 "
        "in-kernel or dequantize on the XLA side",
    ),
    "MC002": (
        "mosaic-scalar-shape-cast",
        Severity.ERROR,
        "the kernel body collapses a loaded (1, 1) float vector to a "
        "scalar (jnp.reshape(x, ()) / x[0, 0] on a loaded block); "
        "Mosaic rejects the vector<1x1> -> scalar shape_cast — keep a "
        "(1, lanes) row and broadcast instead (the scale-plane idiom)",
    ),
    "MC003": (
        "mosaic-subbyte-broadcast",
        Severity.ERROR,
        "the kernel body broadcasts a sub-byte (4-bit) vector; this "
        "Mosaic backend has no layout for sub-byte broadcasts — widen "
        "to int8 before broadcasting",
    ),
    "MC004": (
        "mosaic-s8-dot-accumulator",
        Severity.ERROR,
        "an in-kernel dot over 1-byte operands with an unsupported "
        "accumulator form: int8 dots must run the native s8*s8->s32 "
        "path (preferred_element_type=int32, scales folded on the "
        "accumulator afterwards), and fp8 operands have no MXU form on "
        "this toolchain at all — quantize the scale fold into the "
        "epilogue, don't ask the MXU for a float accumulate of int8",
    ),
    "MC005": (
        "mosaic-lane-reshape",
        Severity.ERROR,
        "an in-kernel reshape changes the lane (minor) dimension "
        "between two >1-lane vectors; this Mosaic's vector shape_cast "
        "cannot re-lay lanes — restructure the buffer so the lane dim "
        "survives (the ragged kernel's head-major GQA-rows packing) "
        "or reshape on the XLA side",
    ),
    "MC006": (
        "mosaic-dynamic-gather",
        Severity.ERROR,
        "an in-kernel gather with TRACED (runtime) indices; this "
        "Mosaic backend has no dynamic vector-indexed gather lowering "
        "— unroll over the index set with static masks (the ragged "
        "kernel's per-position ancestor-bitmask unroll) or gather on "
        "the XLA side",
    ),
    "MC007": (
        "mosaic-sublane-dynamic-slice",
        Severity.ERROR,
        "an in-kernel dynamic_slice with a TRACED start index on the "
        "sublane (second-minor) dimension of a >=2-D vector; this "
        "Mosaic backend can only fold dynamic sublane offsets that are "
        "compile-time constants — slice the sublane dim with a static "
        "offset (unroll over the candidate offsets with masks) or hoist "
        "the slice to the XLA side",
    ),
    "SV001": (
        "serving-page-leak",
        Severity.ERROR,
        "a reachable serving state holds a page that no slot table, "
        "ship reservation, or prefix-cache entry references and that is "
        "not on the pool free list — the pool permanently shrinks and "
        "admission eventually wedges",
    ),
    "SV002": (
        "serving-double-free",
        Severity.ERROR,
        "a protocol transition releases a page more times than it was "
        "retained (negative refcount) or allocates a page whose "
        "refcount is still live — two rows now share KV that one of "
        "them will overwrite",
    ),
    "SV003": (
        "serving-freed-while-shipped",
        Severity.ERROR,
        "a page pinned by an in-flight KV ship or live migration was "
        "freed (eviction/preemption of a parked row, or source release "
        "before the transport resolved) — the transfer lands into (or "
        "reads from) reallocated pages",
    ),
    "SV004": (
        "serving-request-conservation",
        Severity.ERROR,
        "a request was lost or duplicated across "
        "failover/drain/preemption: the multiset of live requests "
        "(queued + resident + parked + shipped + completed) no longer "
        "matches the admitted set",
    ),
    "SV005": (
        "serving-cursor-regression",
        Severity.ERROR,
        "a resident request's cursor moved backwards past a committed "
        "prefix without the recompute-eviction discipline (cursor reset "
        "to 0 off-slot) — the stream-exactness precondition breaks and "
        "re-emitted tokens diverge",
    ),
    "SV006": (
        "serving-nontransactional-ship",
        Severity.ERROR,
        "a KV ship/migration violated the transactional discipline: "
        "the destination commit became observable before the source "
        "released its pinned pages, or a transport-exhausted ship "
        "leaked its destination reservation instead of rolling back",
    ),
    "SV007": (
        "serving-unroutable-livelock",
        Severity.ERROR,
        "a reachable state with a nonempty backlog from which no "
        "sequence of transitions ever admits a request (no replica can "
        "free the pages/slots it would need) — the fleet livelocks "
        "with work queued",
    ),
}


@dataclass
class Finding:
    """One lint finding, with enough coordinates to act on it:
    ``kernel`` (registry family), ``site`` (fault-plan site name),
    ``ranks`` involved, the semaphore ``sem`` (name + slot), and the
    barrier-``phase`` index the event sat in (number of ``barrier_all``
    calls the rank had passed)."""

    rule: str
    kernel: str
    message: str
    site: str | None = None
    ranks: tuple = ()
    sem: str | None = None
    phase: int | None = None
    severity: Severity = field(default=None)  # type: ignore[assignment]

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown rule id: {self.rule}")
        if self.severity is None:
            self.severity = RULES[self.rule][1]

    @property
    def slug(self) -> str:
        return RULES[self.rule][0]

    def format(self) -> str:
        loc = self.kernel + (f" [site={self.site}]" if self.site else "")
        bits = []
        if self.ranks:
            bits.append(f"ranks={list(self.ranks)}")
        if self.sem:
            bits.append(f"sem={self.sem}")
        if self.phase is not None:
            bits.append(f"phase={self.phase}")
        tail = (" (" + ", ".join(bits) + ")") if bits else ""
        return (
            f"{self.rule} {self.severity.name.lower()} {self.slug} "
            f"@ {loc}: {self.message}{tail}"
        )

    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "rule": self.rule,
            "slug": self.slug,
            "severity": self.severity.name.lower(),
            "kernel": self.kernel,
            "site": self.site,
            "ranks": list(self.ranks),
            "sem": self.sem,
            "phase": self.phase,
            "message": self.message,
        }


def rule_counts(findings) -> dict:
    """Per-rule finding counts (every catalog rule, zero included) —
    the ``--json`` summary object's payload."""
    counts = {rule: 0 for rule in RULES}
    for f in findings:
        counts[f.rule] += 1
    return counts


def worst(findings) -> Severity | None:
    sevs = [f.severity for f in findings]
    return max(sevs) if sevs else None


def has_errors(findings) -> bool:
    return any(f.severity >= Severity.ERROR for f in findings)
