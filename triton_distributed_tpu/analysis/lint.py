"""shmemlint public API and CLI.

API::

    from triton_distributed_tpu import analysis
    findings = analysis.lint_all(n=8)                  # whole registry
    findings = analysis.lint_family("ag_gemm.fused", n=8)

CLI (exits nonzero when any ERROR-severity finding survives)::

    python -m triton_distributed_tpu.analysis.lint [--mesh 8]
        [--kernel ag_gemm] [--json] [--list]

No devices are required: kernel builders are constructed over a
``jax.sharding.AbstractMesh`` (nothing executes — the analyzer runs the
kernel *bodies* symbolically), so the lint pass runs identically on a
dev laptop, a CI runner and a TPU host, including on a jax without the
TPU-simulation interpreter where the dynamic race/chaos suites cannot
run at all.

Suppressing an intentional violation: pass ``allow={"SL007", ...}`` to
the API (or ``--allow SL007`` on the CLI) — the finding is still
printed, demoted to INFO. See docs/ANALYSIS.md for the rule catalog.
"""

from __future__ import annotations

import itertools

from triton_distributed_tpu.analysis import abstract, checks
from triton_distributed_tpu.analysis.findings import (
    Finding,
    Severity,
    has_errors,
)

_TOKENS = itertools.count()


def lint_mesh(n: int = 8, axis: str = "x"):
    """An abstract n-device 1D mesh for kernel construction. Builders
    only read ``shape``/``axis_names`` at build time, so no physical
    devices back it."""
    import jax

    return jax.sharding.AbstractMesh(((axis, int(n)),))


def analyze_spec(spec, in_shapes, n, *, kernel_name, site=None, init=None,
                 axis="x", mesh_axes=("x",), contract=None):
    """Symbolically execute one captured/hand-built LaunchSpec and run
    the checker passes — protocol (SL001–SL007), wire-rail consistency
    (SL009/SL010), and, when a ``contract`` is given, delivery
    completeness (SL008). Returns (recorder, findings)."""
    rec = abstract.run_symbolic(
        spec, in_shapes, n, axis=axis, mesh_axes=mesh_axes, init=init,
        kernel_name=kernel_name, site=site,
    )
    return rec, checks.check_family(rec, contract=contract)


def analyze_family(fam, n: int = 8, mesh=None, *, infer_contracts=False):
    """Build one registry family over an abstract mesh, read back the
    captured LaunchSpec, and analyze it (the family's declared delivery
    contract drives the SL008 pass). Returns (recorder, findings).

    ``infer_contracts=True`` additionally derives the family's delivery
    obligation from its XLA twin (:mod:`.contract_infer`): declared
    contracts are diffed against the inferred one (SL012 on drift), and
    a family with ``contract=None`` gets the inferred contract as the
    SL008 fallback plus an SL013 surfacing the gap."""
    from triton_distributed_tpu.lang.launch import captured_launch

    mesh = mesh if mesh is not None else lint_mesh(n, fam.axis)
    fam.build(mesh, n, ("shmemlint", next(_TOKENS)))
    spec = captured_launch(fam.launch_name)
    if spec is None:
        raise RuntimeError(
            f"family {fam.name!r}: builder did not construct a "
            f"shmem_call named {fam.launch_name!r}"
        )
    rec = abstract.run_symbolic(
        spec, fam.in_shapes(n), n,
        axis=fam.axis, mesh_axes=fam.mesh_axes,
        init=fam.init(n) if fam.init else None,
        kernel_name=fam.name, site=fam.site,
    )
    fallback, inferred = None, []
    if infer_contracts and fam.degrades_to:
        from triton_distributed_tpu.analysis import contract_infer

        result = contract_infer.infer_spec(
            rec, degrades_to=fam.degrades_to, declared=fam.contract)
        inferred = result.findings
        fallback = result.contract
    findings = checks.check_family(
        rec, contract=fam.contract, fallback_contract=fallback)
    return rec, findings + inferred


def _apply_allow(findings, allow):
    allow = set(allow or ())
    for f in findings:
        if f.rule in allow:
            f.severity = Severity.INFO
    return findings


def lint_family(name: str, n: int = 8, mesh=None, allow=None,
                infer_contracts=False):
    """Lint one registry family by name; returns the findings."""
    from triton_distributed_tpu.kernels.registry import families

    fam = families()[name]
    _, findings = analyze_family(fam, n, mesh,
                                 infer_contracts=infer_contracts)
    return _apply_allow(findings, allow)


def _cross_family_checks(recorders) -> list:
    """SL005 across the registry: two DIFFERENT-site families sharing a
    collective_id share one barrier-semaphore rendezvous — interleaved
    launches would satisfy each other's barriers. Engine variants of
    one op entry (same fault-plan site) deliberately share their op's
    default id: only one of them runs per call."""
    findings = []
    by_id: dict = {}
    for rec in recorders:
        cid = rec.info.collective_id
        if cid is None or not rec.barrier_sem_used:
            continue
        by_id.setdefault(cid, {}).setdefault(
            rec.info.site, []).append(rec.info.kernel)
    for cid, sites in sorted(by_id.items(), key=lambda kv: str(kv[0])):
        if len(sites) > 1:
            kernels = sorted(k for ks in sites.values() for k in ks)
            findings.append(Finding(
                "SL005", "+".join(kernels),
                f"collective_id {cid!r} is shared by kernel families of "
                f"different sites {sorted(map(str, sites))} "
                f"({kernels}) — their barrier rendezvous collide when "
                "launched in one program",
            ))
    return findings


def lint_all(n: int = 8, mesh=None, kernels=None, allow=None,
             infer_contracts=False):
    """Lint every registered kernel family (optionally filtered by the
    ``kernels`` substring list) plus the cross-family hygiene checks.
    Returns the combined findings list."""
    from triton_distributed_tpu.kernels.registry import families

    fams = families()
    if kernels:
        fams = {
            name: f for name, f in fams.items()
            if any(k in name for k in kernels)
        }
        if not fams:
            raise ValueError(f"no registered kernel matches {kernels}")
    findings, recorders = [], []
    for name in sorted(fams):
        rec, f = analyze_family(fams[name], n, mesh,
                                infer_contracts=infer_contracts)
        recorders.append(rec)
        findings += f
    findings += _cross_family_checks(recorders)
    return _apply_allow(findings, allow)


# ---------------------------------------------------------------------- CLI

def _main_serving(args, json, sys) -> int:
    """The ``--serving`` mode: servlint's bounded model check of the
    serving/fleet protocol (SV001–SV007). Exits 2 on any error finding
    — the bench/CI abort convention — 0 when the exploration is
    clean."""
    from triton_distributed_tpu.analysis import servlint
    from triton_distributed_tpu.analysis.findings import (
        SCHEMA_VERSION,
        rule_counts,
    )

    findings, stats = servlint.lint_serving(
        fixture=args.serving_fixture, max_states=args.serving_states)
    _apply_allow(findings, args.allow)
    errs = sum(f.severity >= Severity.ERROR for f in findings)
    warns = sum(f.severity == Severity.WARNING for f in findings)
    if args.json:
        print(json.dumps({
            "schema_version": SCHEMA_VERSION, "mode": "serving",
            "fixture": args.serving_fixture,
            "states": stats["states"],
            "transitions": stats["transitions"],
            "complete": stats["complete"],
        }))
        for f in findings:
            print(json.dumps(f.to_json()))
        print(json.dumps({
            "schema_version": SCHEMA_VERSION,
            "rule_counts": rule_counts(findings),
            "errors": errs, "warnings": warns,
        }))
    else:
        for f in findings:
            print(f.format())
        kind = "exhaustive" if stats["complete"] else "state-capped"
        print(
            f"servlint: {stats['states']} states, "
            f"{stats['transitions']} transitions ({kind}): "
            f"{errs} error(s), {warns} warning(s)",
            file=sys.stderr)
    return 2 if has_errors(findings) else 0


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m triton_distributed_tpu.analysis.lint",
        description="shmemlint: static semaphore-protocol and deadlock "
        "analysis over the registered SHMEM kernel families",
    )
    ap.add_argument("--mesh", type=int, default=8, metavar="N",
                    help="abstract mesh size to analyze on (default 8)")
    ap.add_argument("--kernel", action="append", default=None,
                    metavar="SUBSTR",
                    help="only families whose name contains SUBSTR "
                    "(repeatable); e.g. --kernel ag_gemm")
    ap.add_argument("--allow", action="append", default=None,
                    metavar="RULE",
                    help="demote RULE (e.g. SL007) to info severity")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per line on stdout: a "
                    "schema_version header, each finding, and a "
                    "rule_counts summary")
    ap.add_argument("--infer-contracts", action="store_true",
                    help="derive each family's delivery contract from "
                    "its XLA twin and diff it against the declared one "
                    "(SL012 on drift, SL013 on a missing declaration; "
                    "SL008 runs on the inferred contract when none is "
                    "declared)")
    ap.add_argument("--mosaic", action="store_true",
                    help="also run the Mosaic-compat pre-flight (rules "
                    "MC001-MC004: trace each family's kernel jaxpr and "
                    "scan for constructs this toolchain's Mosaic "
                    "rejects)")
    ap.add_argument("--serving", action="store_true",
                    help="model-check the serving/fleet protocol "
                    "instead of the kernel families (rules SV001-SV007: "
                    "bounded exhaustive interleaving over a 2-replica "
                    "abstract fleet driven by the production ProtocolOps "
                    "seam); exits 2 on any error finding")
    ap.add_argument("--serving-fixture", default=None, metavar="RULE",
                    help="run the --serving exploration against the "
                    "seeded mutated-ops fixture for RULE (e.g. SV003) "
                    "instead of the production ops")
    ap.add_argument("--serving-states", type=int, default=6000,
                    metavar="N",
                    help="distinct-state cap for the --serving "
                    "exploration (default 6000; 0 = uncapped, the "
                    "nightly exhaustive run — the human label and "
                    "--json 'complete' field then report whether the "
                    "full reachable graph was walked)")
    ap.add_argument("--list", action="store_true",
                    help="list registered kernel families and exit")
    args = ap.parse_args(argv)

    if args.mesh < 2:
        ap.error("--mesh must be >= 2 (a 1-rank mesh has no protocol)")

    if args.serving or args.serving_fixture:
        return _main_serving(args, json, sys)

    from triton_distributed_tpu.kernels.registry import families

    if args.list:
        for name, fam in sorted(families().items()):
            print(f"{name:24s} site={fam.site} launch={fam.launch_name}")
        return 0

    findings = lint_all(n=args.mesh, kernels=args.kernel, allow=args.allow,
                        infer_contracts=args.infer_contracts)
    if args.mosaic:
        from triton_distributed_tpu.analysis import mosaic_compat

        mc, report = mosaic_compat.preflight_all(
            n=args.mesh, kernels=args.kernel
        )
        findings += _apply_allow(mc, args.allow)
        if not args.json:
            print(
                "mosaic-compat: "
                f"{len(report['scanned'])} scanned, "
                f"{len(report['refused'])} refused cleanly "
                f"({sorted(report['refused'])})",
                file=sys.stderr,
            )
    checked = sorted(
        name for name in families()
        if not args.kernel or any(k in name for k in args.kernel)
    )
    errs = sum(f.severity >= Severity.ERROR for f in findings)
    warns = sum(f.severity == Severity.WARNING for f in findings)
    if args.json:
        from triton_distributed_tpu.analysis.findings import (
            SCHEMA_VERSION,
            rule_counts,
        )

        print(json.dumps({
            "schema_version": SCHEMA_VERSION, "mesh": args.mesh,
            "families": checked,
        }))
        for f in findings:
            print(json.dumps(f.to_json()))
        print(json.dumps({
            "schema_version": SCHEMA_VERSION,
            "rule_counts": rule_counts(findings),
            "errors": errs, "warnings": warns,
        }))
    else:
        for f in sorted(findings, key=lambda f: -f.severity):
            print(f.format())
        print(
            f"shmemlint: {len(checked)} kernel families on a "
            f"{args.mesh}-rank mesh: {errs} error(s), {warns} warning(s)",
            file=sys.stderr,
        )
    return 1 if has_errors(findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
