"""Symbolic payload-provenance dataflow over the replayed traces.

The protocol passes (checks.py) prove the *semaphore* story; a ring
schedule can pass every credit check and still deliver the wrong bytes
— skip a chunk with an off-by-one hop count, land one chunk twice,
fold a contribution into a reduction zero or two times, or dequantize
hop h's slab with hop h-1's scale plane. This pass replays the same
cross-rank schedule the simulator produced and tracks, per element of
every root buffer, a symbolic provenance tuple:

* ``contrib`` — a nibble-packed count of contributions per SOURCE rank
  (int64, 4 bits per rank: copies move it, folds add it, computed
  writes reset it to the writing rank's own marker);
* ``wire`` — raw / quantized / dequantized;
* ``scale`` — the quantization group id of quantized bytes, and of the
  group a scale plane currently holds (every QuantEvent and every
  quantized input pair is its own group);
* ``hop`` — how many remote DMAs the bytes have ridden.

At quiescence the declared :class:`DeliveryContract` (the registry is
the table that drives this) is checked against the destination buffer:

* **gather/permute** — every rank holds every source's payload exactly
  once (duplicates and omissions are both SL008, even when all
  semaphores balance);
* **reduce** — every output element is the multiset-reduction of ONE
  contribution per rank (a missing or double-folded rank is SL008);
* any raw quantized bytes surviving in the destination are SL008.

Independent of the contract, quantized wire rails are checked for
payload/scale consistency: every 1-byte payload RDMA must be paired
with a lang.wire-shaped scale-plane RDMA to the same peer on its OWN
semaphores (SL009), scale planes must be consumed only under a wait
that vouches for their arrival (SL009), and a dequant must consume the
scale group its payload was quantized under (SL010).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from triton_distributed_tpu.analysis import events as ev
from triton_distributed_tpu.analysis.findings import Finding

#: wire states
RAW, QUANTIZED, DEQUANTIZED = 0, 1, 2

#: contribution-count nibble width: 4 bits per source rank in an int64
#: bounds the analyzable mesh (16 ranks, far above the lint meshes)
_NIBBLE = 4
MAX_RANKS = 64 // _NIBBLE


@dataclass(frozen=True)
class DeliveryContract:
    """What a kernel family promises to have delivered at termination.

    ``kind``: 'gather' (AG family — every rank ends holding every
    source chunk), 'reduce' (RS family — each output element is one
    contribution per rank, folded exactly once), 'permute'
    (all-to-all — each source's designated chunk lands exactly once),
    or 'local' (a per-rank kernel, e.g. the ragged paged-attention
    family: every dst element must be covered by the rank's OWN
    locally computed writes — holes and foreign/mixed provenance are
    violations, and the shared raw-quantized-bytes check still
    applies).
    ``dst``: the destination root buffer, by kernel-parameter name or
    positional ref index. ``payload_per_src``: elements each source
    must deliver into dst (callable of the mesh size; default
    ``dst_elems // n``). ``full``: every dst element must be covered
    (False for capacity-padded transports like the MoE a2a, where
    unused slot rows legitimately stay unwritten).
    ``own_absent_ok``: a gather destination may legitimately omit the
    local rank's own chunk (kernels that consume it straight from the
    input and never publish it, e.g. the moe_tp AG workspace).
    ``src_only``: callable ``(rank, n) -> collection of source ranks``
    restricting WHICH sources must deliver into ``rank``'s destination
    (every other source's expected payload is zero — a stray delivery
    from outside the set is flagged as a duplicate). The pairwise
    transports (kv_ship: each decode rank receives exactly its partner
    prefill rank's pages) declare their topology with this; None keeps
    the all-sources default of the all-to-all/gather families.
    ``topo``: masked-coverage facet for LOCAL kernels that carry a
    per-row attention-topology operand (the ragged paged family):
    ``{"ref": <input index of the (R, 2+2W) descriptor>, "kv_lens": i,
    "q_lens": i, "width": W}``. The replay then VALUE-checks each
    descriptor row — kind in {CAUSAL, TREE, SHARED_PREFIX}, a TREE
    row's ancestry bitmasks closed under the packed parent pointers
    (``anc[t] == anc[parent[t]] | 1<<t`` — a row violating closure
    lets a node attend a sibling branch), a SHARED_PREFIX split inside
    the row's prefix span — and flags violations as SL008.
    """

    kind: str
    dst: object
    payload_per_src: object = None
    full: bool = True
    own_absent_ok: bool = False
    src_only: object = None
    topo: object = None


# ------------------------------------------------------------- replay state

class _State:
    """Per-(rank, root) provenance arrays, lazily materialized."""

    def __init__(self, rec):
        self.rec = rec
        self._arr: dict = {}

    def get(self, rank, root):
        key = (rank, root)
        st = self._arr.get(key)
        if st is None:
            meta = self.rec.ref_meta.get(root)
            if meta is None or meta.dtype is None:
                return None
            shape = meta.shape
            st = self._arr[key] = {
                "contrib": np.zeros(shape, np.int64),
                "wire": np.zeros(shape, np.int8),
                "scale": np.zeros(shape, np.int32),
                "hop": np.zeros(shape, np.int16),
                "last_put": np.full(shape, -1, np.int32),
            }
        return st

    def seed_inputs(self):
        """Inputs are the provenance sources: rank r's input payload is
        marked as r's contribution. 1-byte inputs are pre-quantized wire
        payloads; each (q, s) input pair forms its own per-rank scale
        group (token), so cross-rank or cross-pair dequants mismatch."""
        token = [0]
        tokens = {}
        for rank in range(self.rec.n):
            for root, meta in self.rec.ref_meta.items():
                if not meta.is_input or meta.dtype is None:
                    continue
                st = self.get(rank, root)
                st["contrib"][...] = np.int64(1) << (_NIBBLE * rank)
                if meta.dtype.itemsize == 1:
                    token[0] += 1
                    tokens[(rank, root)] = token[0]
                    st["wire"][...] = QUANTIZED
                    st["scale"][...] = token[0]
        # the scale plane paired with a quantized input is, by the
        # lang.wire calling convention, the f32 input that follows it
        order = [r for r, m in self.rec.ref_meta.items() if m.is_input]
        for rank in range(self.rec.n):
            for i, root in enumerate(order):
                tok = tokens.get((rank, root))
                if tok is None or i + 1 >= len(order):
                    continue
                nxt = self.rec.ref_meta[order[i + 1]]
                if nxt.dtype is not None and nxt.dtype == np.dtype(np.float32):
                    self.get(rank, order[i + 1])["scale"][...] = tok
        self._next_token = token[0]

    def fresh_token(self) -> int:
        self._next_token += 1
        return self._next_token


def _slices(region: ev.Region):
    return tuple(slice(lo, hi) for lo, hi in zip(region.lo, region.hi))


def _region_elems(region: ev.Region) -> int:
    n = 1
    for lo, hi in zip(region.lo, region.hi):
        n *= hi - lo
    return n


def _copy(dst_st, dst_region, src_st, src_region, *, hop_inc=0,
          put_id=None):
    if dst_st is None or src_st is None:
        return
    ds, ss = _slices(dst_region), _slices(src_region)
    for k in ("contrib", "wire", "scale", "hop"):
        src = src_st[k][ss]
        dst = dst_st[k][ds]
        if src.size != dst.size:
            # one side was a numpy-CLIPPED out-of-bounds window (the
            # evaluator emitted an OobEvent — SL008 reports the overrun
            # itself); provenance transfer for the phantom region is
            # undefined, so drop the copy instead of crashing the replay
            return
        if src.shape != dst.shape:
            src = src.reshape(dst.shape)
        dst_st[k][ds] = src
    if hop_inc:
        dst_st["hop"][ds] += hop_inc
    if put_id is not None:
        dst_st["last_put"][ds] = put_id


def _own(st, region, rank):
    if st is None:
        return
    s = _slices(region)
    st["contrib"][s] = np.int64(1) << (_NIBBLE * rank)
    st["wire"][s] = RAW
    st["scale"][s] = 0
    st["hop"][s] = 0
    st["last_put"][s] = -1


def _uniq_scale(st, region):
    vals = np.unique(st["scale"][_slices(region)])
    return [int(v) for v in vals if v != 0]


# ------------------------------------------------------------------ replay

def _replay(rec, sim, state: _State):
    """Apply provenance transfer along the simulator's schedule.

    The mid-replay checks live here because they need the *at-the-time*
    state: SL010 compares the scale group a dequant consumes against
    the group its payload was quantized under, and the SL009 ordering
    leg asks whether the scale plane's most recent landing was vouched
    for by a completed wait BEFORE the dequant — both answers change as
    double-buffered slots are reused."""
    kernel, site = rec.info.kernel, rec.info.site
    findings: list = []
    puts: list = []
    reported = set()

    def check_scale_ordering(rank, e, s_st):
        ids = np.unique(s_st["last_put"][_slices(e.s_region)])
        for pid in (int(v) for v in ids if v >= 0):
            put = puts[pid]
            g = sim.guarantee.get((put.rank, put.idx))
            if g is not None and g[0] == rank and g[1] < e.idx:
                continue
            sig = ("SL009-unordered", e.s_region.ref, rank)
            if sig in reported:
                continue
            reported.add(sig)
            findings.append(Finding(
                "SL009", kernel,
                f"rank {rank} consumes the scale plane {e.s_region} "
                f"(landed by rank {put.rank}'s RDMA) with no completed "
                "wait vouching for the scale rail's arrival — the "
                "dequant can read a half-landed plane",
                site=site, ranks=(rank, put.rank),
                sem=_fmt_key(put.recv_key) if put.recv_key else None,
                phase=e.phase,
            ))

    for rank, e in sim.schedule:
        if isinstance(e, ev.WriteEvent):
            st = state.get(rank, e.region.ref)
            if e.copy_src is not None:
                _copy(st, e.region, state.get(rank, e.copy_src.ref),
                      e.copy_src)
            elif e.add_srcs is not None:
                _fold(state, rank, e.region, e.add_srcs[0], e.add_srcs[1])
            else:
                _own(st, e.region, rank)
        elif isinstance(e, ev.PutEvent):
            put_id = len(puts)
            puts.append(e)
            _copy(
                state.get(e.dst_rank, e.dst_region.ref), e.dst_region,
                state.get(rank, e.src_region.ref), e.src_region,
                hop_inc=0 if e.local else 1,
                put_id=None if e.local else put_id,
            )
        elif isinstance(e, ev.QuantEvent):
            tok = state.fresh_token()
            src_st = state.get(rank, e.src_region.ref)
            q_st = state.get(rank, e.q_region.ref)
            s_st = state.get(rank, e.s_region.ref)
            if q_st is not None and src_st is not None:
                _copy(q_st, e.q_region, src_st, e.src_region)
                qs = _slices(e.q_region)
                q_st["wire"][qs] = QUANTIZED
                q_st["scale"][qs] = tok
            if s_st is not None:
                ss = _slices(e.s_region)
                _own(s_st, e.s_region, rank)
                s_st["scale"][ss] = tok
        elif isinstance(e, ev.DequantEvent):
            q_st = state.get(rank, e.q_region.ref)
            if e.s_region is None:
                # an epilogue consume that never folds a scale: the
                # s8×s8 product is stored unrescaled — wire-rail
                # divergence on the consumer side (the payload stays
                # QUANTIZED, so the contract pass also sees raw bytes)
                sig = ("SL009-nofold", e.q_region.ref, rank)
                if sig not in reported:
                    reported.add(sig)
                    findings.append(Finding(
                        "SL009", kernel,
                        f"rank {rank} consumes the quantized payload "
                        f"{e.q_region} in an MXU accumulator epilogue "
                        "with NO scale folded — the s8×s8 product is "
                        "never rescaled by its chunk scale and the "
                        "stored values are off by the quantization "
                        "scale (scale-fold omitted)",
                        site=site, ranks=(rank,), phase=e.phase,
                    ))
                continue
            s_st = state.get(rank, e.s_region.ref)
            dst_st = (
                state.get(rank, e.dst_region.ref)
                if e.dst_region is not None else None
            )
            if s_st is not None:
                check_scale_ordering(rank, e, s_st)
            needed = _uniq_scale(q_st, e.q_region) if q_st else []
            held = _uniq_scale(s_st, e.s_region) if s_st else []
            if (sorted(needed) != sorted(held) or len(needed) > 1) and (
                ("SL010", e.q_region.ref, e.idx) not in reported
            ):
                reported.add(("SL010", e.q_region.ref, e.idx))
                findings.append(Finding(
                    "SL010", kernel,
                    f"rank {rank} dequantizes {e.q_region} (scale group"
                    f"{'s' if len(needed) != 1 else ''} {needed or '?'})"
                    f" with the scale plane {e.s_region} holding group"
                    f"{'s' if len(held) != 1 else ''} {held or '?'} — "
                    "payload and scales come from different "
                    "quantizations (a stale double-buffer slot or a "
                    "mispaired rail); the dequantized values are "
                    "silently wrong",
                    site=site, ranks=(rank,), phase=e.phase,
                ))
            if e.epilogue:
                # int8→MXU consumption: the payload bytes stay
                # physically quantized where they are, but the scale
                # fold in the accumulator epilogue IS their dequant —
                # mark the consumed region dequantized IN PLACE so the
                # contract pass (SL008 raw-bytes leg) treats the
                # delivery as complete; the matmul output is locally
                # computed data.
                if q_st is not None:
                    qs = _slices(e.q_region)
                    w = q_st["wire"][qs]
                    q_st["wire"][qs] = np.where(
                        w == QUANTIZED, DEQUANTIZED, w
                    )
                if dst_st is not None:
                    _own(dst_st, e.dst_region, rank)
                continue
            if e.add_region is not None and dst_st is not None:
                _fold(state, rank, e.dst_region, e.q_region, e.add_region)
            elif dst_st is not None and q_st is not None:
                _copy(dst_st, e.dst_region, q_st, e.q_region)
            if dst_st is not None:
                ds = _slices(e.dst_region)
                w = dst_st["wire"][ds]
                dst_st["wire"][ds] = np.where(w == QUANTIZED, DEQUANTIZED, w)
                dst_st["scale"][ds] = 0
        elif isinstance(e, ev.AddEvent):
            _fold(state, rank, e.dst_region, e.a_region, e.b_region)
    return puts, findings


def _fold(state: _State, rank, dst_region, a_region, b_region):
    """dst = a + b: contribution nibbles ADD (that is how double-folds
    become visible); a quantized operand stays quantized in the result
    (folding raw wire bytes without a dequant is itself a bug the
    contract check then surfaces)."""
    dst_st = state.get(rank, dst_region.ref)
    a_st = state.get(rank, a_region.ref)
    b_st = state.get(rank, b_region.ref)
    if dst_st is None or a_st is None or b_st is None:
        return
    ds = _slices(dst_region)
    shape = dst_st["contrib"][ds].shape

    def pick(st, region, k):
        v = st[k][_slices(region)]
        return v.reshape(shape) if v.shape != shape else v

    dst_st["contrib"][ds] = (
        pick(a_st, a_region, "contrib") + pick(b_st, b_region, "contrib")
    )
    aw, bw = pick(a_st, a_region, "wire"), pick(b_st, b_region, "wire")
    dst_st["wire"][ds] = np.where(
        (aw == QUANTIZED) | (bw == QUANTIZED), QUANTIZED, np.maximum(aw, bw)
    )
    dst_st["scale"][ds] = 0
    dst_st["hop"][ds] = np.maximum(
        pick(a_st, a_region, "hop"), pick(b_st, b_region, "hop")
    )
    dst_st["last_put"][ds] = -1


# --------------------------------------------------------------- SL009 rails

def _check_rail_pairing(rec) -> list:
    """Structural payload/scale rail pairing (SL009): every non-local
    1-byte-payload RDMA must be immediately followed (before any wait —
    the _DualDMA discipline) by a lang.wire-shaped f32 scale-plane RDMA
    to the same peer, on its OWN semaphores."""
    from triton_distributed_tpu.lang import wire as wirelib

    findings: list = []
    kernel, site = rec.info.kernel, rec.info.site
    reported = set()

    def itemsize(region):
        meta = rec.ref_meta.get(region.ref)
        return meta.dtype.itemsize if meta and meta.dtype is not None else 0

    def report(rule_sig, f):
        if rule_sig not in reported:
            reported.add(rule_sig)
            findings.append(f)

    for r in range(rec.n):
        trace = rec.traces[r]
        for i, e in enumerate(trace):
            if not (isinstance(e, ev.PutEvent) and not e.local
                    and itemsize(e.src_region) == 1):
                continue
            partner = None
            for e2 in trace[i + 1:]:
                if isinstance(e2, ev.WaitEvent):
                    break
                if (isinstance(e2, ev.PutEvent) and not e2.local
                        and e2.dst_rank == e.dst_rank
                        and itemsize(e2.src_region) == 4):
                    partner = e2
                    break
            if partner is None:
                report(("nopair", e.src_region.ref, e.dst_rank), Finding(
                    "SL009", kernel,
                    f"rank {r} forwards the quantized payload "
                    f"{e.src_region} to rank {e.dst_rank} with no paired "
                    "scale-plane RDMA before the next wait — the "
                    "receiver has bytes it cannot dequantize",
                    site=site, ranks=(r, e.dst_rank),
                    sem=_fmt_key(e.recv_key) if e.recv_key else None,
                    phase=e.phase,
                ))
                continue
            if (e.recv_key is not None and e.recv_key == partner.recv_key) \
                    or (e.send_key == partner.send_key):
                report(("sharedsem", e.src_region.ref), Finding(
                    "SL009", kernel,
                    f"rank {r}'s scale rail ({partner.src_region}) is "
                    "signaled on the payload rail's semaphore "
                    f"({_fmt_key(e.recv_key or e.send_key)}): credits "
                    "count, they don't tag — a scale arrival can "
                    "release the payload wait (or vice versa) while the "
                    "other rail is still in flight",
                    site=site, ranks=(r, e.dst_rank),
                    sem=_fmt_key(e.recv_key or e.send_key), phase=e.phase,
                ))
            q_shape = _plane_shape(e.src_region)
            s_shape = _plane_shape(partner.src_region)
            q_rows = 1
            for d in q_shape[:-1]:
                q_rows *= d
            if not wirelib.paired_scale_ok(q_rows, s_shape):
                report(("layout", e.src_region.ref), Finding(
                    "SL009", kernel,
                    f"scale plane {partner.src_region} paired with "
                    f"payload {e.src_region} drifts from the lang.wire "
                    f"layout contract ({q_rows} payload rows need a "
                    f"(rows/chunk_rows, {wirelib.SCALE_LANES}) f32 "
                    "plane whose rows divide them)",
                    site=site, ranks=(r,), phase=e.phase,
                ))
    return findings


def _fmt_key(key) -> str:
    name, slot = key
    return name + (str(list(slot)) if slot else "")


def _plane_shape(region: ev.Region) -> tuple:
    """Region extents with leading unit dims squeezed (a scalar-indexed
    slot of a double-buffered root keeps the root's rank; the wire
    layout contract is over the 2-D slab it selects)."""
    dims = [hi - lo for lo, hi in zip(region.lo, region.hi)]
    while len(dims) > 2 and dims[0] == 1:
        dims.pop(0)
    return tuple(dims)


# ----------------------------------------------------------- SL008 contract

def _resolve_dst(rec, dst):
    if isinstance(dst, int):
        for root, meta in rec.ref_meta.items():
            if meta.index == dst:
                return root
        raise KeyError(f"no ref at position {dst}")
    if dst not in rec.ref_meta:
        raise KeyError(
            f"contract dst {dst!r} is not a ref of kernel "
            f"{rec.info.kernel!r} (refs: {list(rec.ref_meta)})"
        )
    return dst


def _bbox(mask) -> str:
    idx = np.argwhere(mask)
    lo, hi = idx.min(axis=0), idx.max(axis=0) + 1
    return "[" + ",".join(f"{a}:{b}" for a, b in zip(lo, hi)) + "]"


def _check_topology(rec, contract: DeliveryContract) -> list:
    """Masked-coverage facet of the LOCAL contract: value-check the
    per-row attention-topology descriptor operand. The provenance
    arrays prove every out element was the rank's own write; THIS
    check proves the mask those writes were computed under is
    well-formed — a TREE row whose ancestry bitmasks are not closed
    under its parent pointers lets a draft node attend a SIBLING
    branch (contaminating the path-conditioned logits the verify walk
    samples from), which coverage alone can never see."""
    findings: list = []
    kernel, site = rec.info.kernel, rec.info.site
    t = contract.topo
    vals = getattr(rec, "input_values", {})
    topo = vals.get(t["ref"])
    if topo is None:
        return [Finding(
            "SL008", kernel,
            f"contract declares a topology operand at input {t['ref']} "
            "but the replay captured no value for it",
            site=site,
        )]
    topo = np.asarray(topo)
    w = (topo.shape[-1] - 2) // 2
    if w != int(t.get("width", w)):
        findings.append(Finding(
            "SL008", kernel,
            f"topology operand width {w} drifted from the contract's "
            f"declared width {t['width']}",
            site=site,
        ))
    kv_lens = vals.get(t.get("kv_lens"))
    q_lens = vals.get(t.get("q_lens"))
    for r in range(topo.shape[0]):
        kind = int(topo[r, 0])
        aux = int(topo[r, 1])
        if kind not in (0, 1, 2):            # CAUSAL / TREE / SHARED_PREFIX
            findings.append(Finding(
                "SL008", kernel,
                f"row {r}'s topology kind {kind} is not a known "
                "descriptor (CAUSAL=0, TREE=1, SHARED_PREFIX=2)",
                site=site,
            ))
            continue
        if kind == 1:                        # TREE: ancestry closure
            anc = topo[r, 2:2 + w].astype(np.int64)
            par = topo[r, 2 + w:2 + 2 * w]
            if not 1 <= aux <= w:
                findings.append(Finding(
                    "SL008", kernel,
                    f"TREE row {r} packs {aux} positions, outside the "
                    f"descriptor width {w}",
                    site=site,
                ))
                continue
            if anc[0] & 1 == 0:
                findings.append(Finding(
                    "SL008", kernel,
                    f"TREE row {r}'s frontier (q position 0) is not its "
                    "own ancestor — anc[0] must carry bit 0",
                    site=site,
                ))
            for q in range(1, aux):
                pt = int(par[q])
                want = (anc[pt] | (np.int64(1) << q)) if 0 <= pt < q \
                    else None
                if want is None or int(anc[q]) != int(want):
                    findings.append(Finding(
                        "SL008", kernel,
                        f"TREE row {r}'s ancestry is not closed under "
                        f"its parent pointers at q position {q} "
                        f"(anc={int(anc[q]):#x}, parent={pt}) — the "
                        "node's visible set is not exactly its "
                        "root-to-node path, so it can attend a sibling "
                        "branch",
                        site=site,
                    ))
        elif kind == 2:                      # SHARED_PREFIX: split bound
            if kv_lens is not None and q_lens is not None:
                prefix = int(kv_lens[r]) - int(q_lens[r])
                if not 0 <= aux <= prefix:
                    findings.append(Finding(
                        "SL008", kernel,
                        f"SHARED_PREFIX row {r}'s split {aux} falls "
                        f"outside the row's prefix span [0, {prefix}]",
                        site=site,
                    ))
    return findings


def _check_contract(rec, state: _State, contract: DeliveryContract) -> list:
    findings: list = []
    kernel, site = rec.info.kernel, rec.info.site
    n = rec.n
    if contract.kind == "local" and contract.topo:
        findings.extend(_check_topology(rec, contract))
    dst = _resolve_dst(rec, contract.dst)
    meta = rec.ref_meta[dst]
    dst_elems = int(np.prod(meta.shape))
    expect = (
        contract.payload_per_src(n) if contract.payload_per_src
        else dst_elems // n
    )
    full_mask = sum(np.int64(1) << (_NIBBLE * s) for s in range(n))
    for rank in range(n):
        st = state.get(rank, dst)
        c = st["contrib"]
        if (st["wire"] == QUANTIZED).any():
            findings.append(Finding(
                "SL008", kernel,
                f"rank {rank}'s {dst} region "
                f"{dst}{_bbox(st['wire'] == QUANTIZED)} still holds RAW "
                "quantized wire bytes at termination — delivered "
                "without a dequantize",
                site=site, ranks=(rank,),
            ))
        if contract.kind == "local":
            own = np.int64(1) << (_NIBBLE * rank)
            foreign = (c != 0) & (c != own)
            if foreign.any():
                findings.append(Finding(
                    "SL008", kernel,
                    f"rank {rank}'s {dst}{_bbox(foreign)} holds foreign "
                    "or mixed-provenance bytes — a LOCAL kernel's "
                    "output must be its own computed writes only",
                    site=site, ranks=(rank,),
                ))
            if contract.full:
                empty = c == 0
                if empty.any():
                    findings.append(Finding(
                        "SL008", kernel,
                        f"rank {rank}'s {dst}{_bbox(empty)} was never "
                        "written — the per-row output spans terminated "
                        "with a hole (a row's packed span was skipped "
                        "or mis-addressed)",
                        site=site, ranks=(rank,),
                    ))
            continue
        if contract.kind == "reduce":
            bad = c != full_mask
            if bad.any():
                missing, dup = [], []
                for s in range(n):
                    nib = (c >> (_NIBBLE * s)) & 0xF
                    if (nib == 0).any():
                        missing.append(s)
                    if (nib > 1).any():
                        dup.append(s)
                findings.append(Finding(
                    "SL008", kernel,
                    f"rank {rank}'s reduction output {dst}{_bbox(bad)} "
                    "is not the exact one-contribution-per-rank fold: "
                    + (f"rank(s) {missing} never folded in" if missing
                       else "")
                    + ("; " if missing and dup else "")
                    + (f"rank(s) {dup} folded more than once" if dup
                       else ""),
                    site=site, ranks=(rank,),
                ))
            continue
        # gather / permute: every element single-sourced, per-src counts
        single = np.zeros(meta.shape, bool)
        senders = (
            set(contract.src_only(rank, n))
            if contract.src_only is not None else None
        )
        for s in range(n):
            marker = np.int64(1) << (_NIBBLE * s)
            hits = c == marker
            single |= hits
            got = int(hits.sum())
            want = expect if senders is None or s in senders else 0
            if s == rank and contract.own_absent_ok and got == 0:
                continue
            if got != want:
                kind = ("missing" if got < want else "duplicated")
                findings.append(Finding(
                    "SL008", kernel,
                    f"rank {rank} holds {got} element(s) of source rank "
                    f"{s}'s payload in {dst}, expected {want} — chunk "
                    f"{kind} "
                    + (f"(region {dst}{_bbox(hits)})" if got else
                       "(never delivered)"),
                    site=site, ranks=(rank, s),
                ))
        mixed = (c != 0) & ~single
        if mixed.any():
            findings.append(Finding(
                "SL008", kernel,
                f"rank {rank}'s {dst}{_bbox(mixed)} holds elements with "
                "mixed or repeated source contributions — overlapping "
                "deliveries landed in one region",
                site=site, ranks=(rank,),
            ))
        if contract.full:
            empty = c == 0
            if contract.own_absent_ok:
                pass  # per-src counts above already police coverage
            elif empty.any():
                findings.append(Finding(
                    "SL008", kernel,
                    f"rank {rank}'s {dst}{_bbox(empty)} was never "
                    "written by any source — the gather terminated "
                    "with a hole",
                    site=site, ranks=(rank,),
                ))
    return findings


# --------------------------------------------------------- SL011 hop depth

def hop_histogram(rec, state: _State, dst) -> dict:
    """Per-element remote-hop histogram of the contract destination
    across all ranks: {hop_count: elements}. The raw material of the
    critical-path feed-in (tune.perf_model.hop_critical_path_ms)."""
    hist: dict = {}
    for rank in range(rec.n):
        st = state.get(rank, dst)
        if st is None:
            continue
        vals, counts = np.unique(st["hop"], return_counts=True)
        for v, c in zip(vals, counts):
            hist[int(v)] = hist.get(int(v), 0) + int(c)
    return hist


def _check_hop_depth(rec, state: _State, contract) -> list:
    """SL011: the delivery schedule's critical path, measured in remote
    hops, against the ring-optimal depth. A ring of n ranks delivers
    every chunk (and every reduction contribution) in ≤ n-1 sequential
    hops; a schedule whose deepest chain exceeds that has serialized or
    detoured its transfers — the per-element hop counters the replay
    already tracks, fed into the perf model as a pre-hardware wall-clock
    check (ROADMAP PR-4 follow-on)."""
    from triton_distributed_tpu.tune.perf_model import (
        hop_critical_path_ms,
        ring_depth_regression,
    )

    dst = _resolve_dst(rec, contract.dst)
    hist = hop_histogram(rec, state, dst)
    if not hist:
        return []
    max_hop = max(hist)
    meta = rec.ref_meta[dst]
    itemsize = meta.dtype.itemsize if meta.dtype is not None else 4
    hop_bytes = (int(np.prod(meta.shape)) // max(rec.n, 1)) * itemsize
    reg = ring_depth_regression(max_hop, rec.n, hop_bytes)
    if reg is None:
        return []
    excess, excess_ms = reg
    return [Finding(
        "SL011", rec.info.kernel,
        f"the deepest delivery chain into {dst} rides {max_hop} remote "
        f"hops on a {rec.n}-rank mesh (ring-optimal <= {rec.n - 1}): "
        f"{excess} excess sequential hop(s) — the schedule serializes "
        "or detours transfers, projected "
        f"+{excess_ms:.4f} ms critical path per collective at "
        f"{hop_bytes} B/hop (total chain "
        f"{hop_critical_path_ms(max_hop, hop_bytes):.4f} ms, "
        "tune.perf_model.hop_critical_path_ms)",
        site=rec.info.site,
    )]


def _check_oob(rec) -> list:
    """SL008: the abstract evaluator recorded an index that extends past
    a buffer's extent. numpy clips such windows silently, so the clipped
    access already passed every provenance check as its narrower shadow
    — the overrun itself is the bug (a grid kernel's out-DMA spilling
    past the parking zone clobbers a neighbor row's delivered span)."""
    findings, seen = [], set()
    for e in rec.events(ev.OobEvent):
        r = e.region
        shape = None
        meta = rec.ref_meta.get(r.ref)
        if meta is not None:
            shape = tuple(meta.shape)
        key = (r.ref, r.lo, r.hi)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            "SL008", rec.info.kernel,
            f"out-of-bounds access {r}: the requested window extends "
            f"past the buffer extent{'' if shape is None else f' {shape}'}"
            " — the access was silently clipped, so the bytes past the "
            "edge were never read/written (an out-block overrunning the "
            "parking zone violates the delivery contract)",
            site=rec.info.site, ranks=(e.rank,), phase=e.phase,
        ))
    return findings


# ------------------------------------------------------------------- entry

def replay_provenance(rec, sim):
    """Seed per-rank provenance and replay one completed schedule.

    Returns ``(state, puts, findings)`` — the terminal provenance
    ``_State`` (contrib/wire/scale/hop arrays per (rank, root)), the
    put count, and the mid-replay SL009/SL010 wire findings. This is
    the shared substrate of :func:`check_dataflow` and the contract
    inference in :mod:`.contract_infer` (which realizes a
    DeliveryContract *from* the terminal state instead of checking one
    against it)."""
    state = _State(rec)
    state.seed_inputs()
    puts, findings = _replay(rec, sim, state)
    return state, puts, findings


def check_dataflow(rec, sim, contract: DeliveryContract | None) -> list:
    """The SL008/SL009/SL010 data-correctness passes plus the SL011
    hop-critical-path check over one completed replay."""
    if rec.n > MAX_RANKS:
        return []
    findings = _check_oob(rec)
    state, _puts, more = replay_provenance(rec, sim)
    findings += more
    findings += _check_rail_pairing(rec)
    if contract is not None:
        findings += _check_contract(rec, state, contract)
        findings += _check_hop_depth(rec, state, contract)
    return findings
