"""Contract inference: derive SL008 delivery obligations from the twin.

Delivery contracts used to be hand-declared per registry family
(``KernelFamily.contract``) — the one structural hole in the analyzer:
a new family can under-declare and the SL008 completeness pass goes
silently blind, and every machine-generated schedule axis widens that
gap. This module closes it by *deriving* each family's
:class:`~triton_distributed_tpu.analysis.dataflow.DeliveryContract`
from two independent witnesses and diffing the declaration against
them:

1. **The XLA twin** (``degrades_to`` — every family has one,
   lint-enforced). The twin is executed for real on a small CPU mesh
   with rank-tagged inputs: rank ``r``'s payload carries the value
   ``2**r``, identity/ones untagged operands keep the twin linear, so
   every output element's value IS a bitmask of the source ranks that
   contributed to it. Decoding the bitmasks classifies the twin's
   delivery semantics into one of three classes — ``single`` (every
   nonzero element traces to exactly one source: the gather / permute
   shapes), ``fold`` (elements sum contributions from every rank: the
   reduce shapes) or ``local`` (a per-rank function with no mesh
   operand at all). Twins whose public signature is local because the
   transport is composed *around* them in the degraded op path (dense
   attention behind a KV gather, the grouped GEMM behind the MoE token
   all-gather / ahead of the reduce-scatter) are run inside exactly
   that documented composition (ops/moe_tp.py, ops/cp.py) — the class
   measures the degraded data path, not just the inner callable.

2. **The replay's provenance arrays** (``dataflow._State``). Given the
   twin's class, the kernel's own replayed ``contrib`` nibbles
   *realize* the concrete contract: which root buffer exhibits the
   class's delivery pattern (the ``dst``), how many elements each
   source lands per rank (``payload_per_src``), whether every element
   is covered (``full``), whether the local rank's own chunk is
   legitimately absent (``own_absent_ok``), and which sources actually
   deliver into each rank (``src_only`` — only trusted for
   topology-agnostic transports; mesh collectives pin all-sources from
   the twin so a kernel that silently skips a source cannot launder
   the skip into its own inferred topology).

Hand-written contracts become assertions checked against the inferred
ones:

* **SL012** — declared ≠ inferred: wrong kind class, a dst that does
  not exhibit the twin's delivery pattern, over/under-declared
  payload, missing or stray sources, full/own-absent drift.
* **SL013** — a registered family with NO declared contract: inference
  supplies one (so SL008 never goes blind) and surfaces the gap.

Gather and permute intentionally compare as ONE kind class: SL008
checks them with the same branch (every element single-sourced,
per-source counts exact), and sharded twin outputs cannot distinguish
replicated from partitioned landings in general. The inferred
contract's label is chosen from the replay realization and only
affects which (identical) SL008 branch runs.

Twin execution needs ``n`` real (host-platform) devices. When fewer
are available the profile falls back to a static class table keyed by
the twin path — realization and the SL012/SL013 diffs still run, with
``TwinProfile.executed = False`` recorded in every finding's message
so a CI log can tell a measured verdict from a tabled one.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

from triton_distributed_tpu.analysis import checks, dataflow
from triton_distributed_tpu.analysis.dataflow import (
    _NIBBLE,
    DeliveryContract,
)
from triton_distributed_tpu.analysis.findings import Finding

#: twin delivery classes
SINGLE, FOLD, LOCAL = "single", "fold", "local"

#: DeliveryContract.kind → twin class (gather and permute are one
#: class: SL008 checks them with the same branch)
_KIND_CLASS = {
    "gather": SINGLE, "permute": SINGLE, "reduce": FOLD, "local": LOCAL,
}


@dataclass(frozen=True)
class TwinProfile:
    """What the executed twin revealed about the degraded data path.

    ``sources`` is "all" when the twin is a mesh collective over the
    full axis (every rank must deliver — the inferred contract may NOT
    narrow the topology from the replay, or a skipped source would
    launder itself into the inferred ``src_only``); None means the
    transport is topology-agnostic (kv_ship's device_put) and the
    observed sender sets are the contract.
    """

    cls: str                       # single | fold | local
    sources: str | None            # "all" | None
    executed: bool
    detail: str = ""


@dataclass
class InferenceResult:
    """One family's inference: the twin profile, the realized dst root,
    the synthesized contract (usable as the SL008 fallback when the
    family declares none), the SL012/SL013 findings, and the raw
    per-rank observation table for diagnostics."""

    profile: TwinProfile
    dst: str | None
    contract: DeliveryContract | None
    findings: list = field(default_factory=list)
    observed: dict = field(default_factory=dict)


# ------------------------------------------------------------ twin execution

def _mesh(n):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("x",))


def _tags(n):
    """Power-of-two per-rank tags: exact in f32 up to 16 ranks, and a
    sum of any subset is a unique bitmask of the contributing ranks."""
    return 2.0 ** np.arange(n)


def _decode_class(out, n) -> str:
    """Classify a tag-carrying twin output: every nonzero value must be
    an exact subset-sum of the rank tags; one bit set everywhere is
    ``single``, any multi-bit value is ``fold``."""
    v = np.asarray(out, np.float64).ravel()
    iv = np.rint(v).astype(np.int64)
    if not np.allclose(v, iv, atol=1e-6):
        raise ValueError(
            f"twin output is not tag-linear (values {v[:4]}...) — the "
            "provenance decode only holds for linear data movement"
        )
    if (iv < 0).any() or (iv >= (1 << n)).any():
        raise ValueError(
            f"twin output {iv.min()}..{iv.max()} outside the {n}-rank "
            "tag space"
        )
    nz = iv[iv != 0]
    if nz.size == 0:
        raise ValueError("twin output all-zero — tags never arrived")
    multi = (nz & (nz - 1)) != 0
    return FOLD if multi.any() else SINGLE


def _shmap(body, mesh, in_specs, out_specs):
    import jax

    from triton_distributed_tpu.config import ensure_compat

    ensure_compat()
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    ))


def _h_all_gather(twin, n):
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    x = np.repeat(_tags(n), 4)[:, None] * np.ones((1, 128), np.float32)
    out = _shmap(lambda a: twin(a, "x", tiled=True),
                 mesh, P("x"), P("x"))(x.astype(np.float32))
    cls = _decode_class(out, n)
    if cls != SINGLE:
        raise ValueError(f"all_gather twin decoded as {cls}")
    return TwinProfile(SINGLE, "all", True,
                       "tags replicate, one source per element")


def _h_psum_scatter(twin, n):
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    # rank r's whole slab carries tag r; the scatter's output elements
    # must decode to the full-mesh bitmask (one fold per rank)
    x = (_tags(n)[:, None, None]
         * np.ones((1, 4 * n, 128), np.float32)).astype(np.float32)
    out = _shmap(
        lambda a: twin(a[0], "x", scatter_dimension=0, tiled=True),
        mesh, P("x"), P("x"),
    )(x)
    cls = _decode_class(out, n)
    if cls != FOLD:
        raise ValueError(f"psum_scatter twin decoded as {cls}")
    if not np.allclose(np.asarray(out), _tags(n).sum()):
        raise ValueError("psum_scatter twin missed a contribution")
    return TwinProfile(FOLD, "all", True, "full-mesh fold per element")


def _h_all_to_all(twin, n):
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    x = (_tags(n)[:, None, None]
         * np.ones((1, 4 * n, 128), np.float32)).astype(np.float32)
    out = _shmap(
        lambda a: twin(a[0], "x", split_axis=0, concat_axis=0,
                       tiled=True),
        mesh, P("x"), P("x"),
    )(x)
    cls = _decode_class(out, n)
    if cls != SINGLE:
        raise ValueError(f"all_to_all twin decoded as {cls}")
    return TwinProfile(SINGLE, "all", True,
                       "one block per source redistributed")


def _h_ag_gemm(twin, n):
    # B = identity passes the row tags straight through the GEMM: the
    # output provenance is the gathered-A workspace's provenance
    mesh = _mesh(n)
    k = 8
    a = np.repeat(_tags(n), 2)[:, None] * np.ones((1, k), np.float32)
    b = np.eye(k, dtype=np.float32)
    out = twin(a.astype(np.float32), b, mesh, "x")
    cls = _decode_class(out, n)
    if cls != SINGLE:
        raise ValueError(f"ag_gemm twin decoded as {cls}")
    return TwinProfile(SINGLE, "all", True,
                       "row tags survive B=I; gathered-A provenance")


def _h_gemm_rs(twin, n):
    # A's K-columns carry the owner rank's tag, B = ones/(K/n): each
    # rank's partial is exactly its tag, the scatter folds all of them
    mesh = _mesh(n)
    kc, m, nn = 2, 2 * n, 8
    a = np.repeat(_tags(n), kc)[None, :] * np.ones((m, 1), np.float32)
    b = np.full((n * kc, nn), 1.0 / kc, np.float32)
    out = twin(a.astype(np.float32), b, mesh, "x")
    if not np.allclose(np.asarray(out), _tags(n).sum()):
        raise ValueError("gemm_rs twin is not the exact sum of tags")
    return TwinProfile(FOLD, "all", True,
                       "partial per rank = tag, scatter folds all")


def _h_kv_ship(twin, n):
    # topology-agnostic device_put tree: values pass through unchanged
    # (single-source by construction); WHICH pairs ship is the caller's
    # placement choice, so the topology comes from the replay
    payload = {"q": (_tags(n)[:, None]
                     * np.ones((1, 8), np.float32)).astype(np.float32)}
    out = twin(payload, {"q": None})
    if not np.allclose(out["q"], payload["q"]):
        raise ValueError("kv_ship twin altered the payload")
    return TwinProfile(SINGLE, None, True,
                       "pass-through transport; topology from replay")


def _h_grouped_ag(twin, n):
    # the degraded MoE dispatch path (ops/moe_tp.ag_group_gemm_device):
    # all_gather the sorted token slab, then the grouped GEMM locally —
    # W = one identity expert keeps the gathered tags intact
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    k, rows = 8, 2
    x = np.repeat(_tags(n), rows)[:, None] * np.ones((1, k), np.float32)
    w = np.eye(k, dtype=np.float32)[None]
    splits = np.asarray([rows * n], np.int32)

    def body(a):
        g = jax.lax.all_gather(a, "x", tiled=True)
        return twin(g, w, splits)

    out = _shmap(body, mesh, P("x"), P("x"))(x.astype(np.float32))
    cls = _decode_class(out, n)
    if cls != SINGLE:
        raise ValueError(f"grouped AG twin decoded as {cls}")
    return TwinProfile(SINGLE, "all", True,
                       "gather-then-grouped-GEMM (degraded dispatch)")


def _h_grouped_rs(twin, n):
    # the degraded MoE combine path: grouped GEMM on the local partial,
    # then the reduce-scatter folds one contribution per rank
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    k, rows = 8, 2 * n
    x = (_tags(n)[:, None, None]
         * np.ones((1, rows, k), np.float32)).astype(np.float32)
    w = np.eye(k, dtype=np.float32)[None]
    splits = np.asarray([rows], np.int32)

    def body(a):
        y = twin(a[0], w, splits)
        return jax.lax.psum_scatter(y, "x", scatter_dimension=0,
                                    tiled=True)

    out = _shmap(body, mesh, P("x"), P("x"))(x)
    if not np.allclose(np.asarray(out), _tags(n).sum()):
        raise ValueError("grouped RS twin is not the exact sum of tags")
    return TwinProfile(FOLD, "all", True,
                       "grouped-GEMM-then-scatter (degraded combine)")


def _h_cp_attention(twin, n):
    # both CP schemes degrade onto dense attention over GATHERED kv
    # (registry: "gather KV, attend locally") — the transport leg is
    # the all_gather; the attention itself must run and stay finite
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)
    s, d = 4, 8
    kv = (_tags(n)[:, None, None, None]
          * np.ones((1, s, 1, d), np.float32)).astype(np.float32)
    q = np.ones((1, n * s, 1, d), np.float32)

    def body(k_loc):
        k_full = jax.lax.all_gather(k_loc, "x", axis=1, tiled=True)
        o = twin(q, k_full, k_full, causal=True)
        return k_full, o

    k_full, o = _shmap(body, mesh, P("x", None, None, None),
                       (P("x"), P("x")))(kv)
    cls = _decode_class(k_full, n)
    if cls != SINGLE or not np.isfinite(np.asarray(o)).all():
        raise ValueError("cp twin's gathered-KV leg failed to decode")
    return TwinProfile(SINGLE, "all", True,
                       "KV gathered, attended locally (degraded CP)")


def _h_grad_ring(twin, n):
    # grad_allreduce_xla takes a REPLICATED operand (in_specs P()), so
    # per-rank tags cannot ride through it; the fold class is proved by
    # the exact ×n psum of a replicated unit slab instead
    mesh = _mesh(n)
    out = twin(np.ones((8, 128), np.float32), mesh, "x")
    if not np.allclose(np.asarray(out), float(n)):
        raise ValueError("grad ring twin is not the exact n-way psum")
    return TwinProfile(FOLD, "all", True,
                       "replicated psum = exact x n fold")


def _h_cp_decode(twin, n):
    # cp_lse_combine_xla shards its stacked slab operand over the cp
    # axis (in_specs P(axis)); rank r's whole contribution slab carries
    # tag r, so every reduced destination element must decode to the
    # full-mesh fold — a dropped rank is a token decoded against a
    # silently missing KV shard
    mesh = _mesh(n)
    m = 8
    x = (np.repeat(_tags(n), n * m)[:, None]
         * np.ones((1, 128), np.float32)).astype(np.float32)
    out = twin(x, mesh, "x")
    cls = _decode_class(out, n)
    if cls != FOLD:
        raise ValueError(f"cp decode combine twin decoded as {cls}")
    if not np.allclose(np.asarray(out), _tags(n).sum()):
        raise ValueError("cp decode combine twin missed a contribution")
    return TwinProfile(FOLD, "all", True,
                       "one weighted partial folded per cp rank")


def _h_ragged_local(twin, n):
    # a per-rank function: no mesh/axis operand at all. Execute at the
    # registry's lint geometry on one device so path rot still fails
    # loudly, then assert finiteness — INCLUDING the per-row topology
    # operand with a full TREE row, so a twin that dropped or broke
    # the masked path fails the profile instead of silently agreeing
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM as g,
        causal_topologies,
        tree_topology_row,
    )

    pool = np.ones((g["npages"], g["hkv"], g["page"], g["d"]), np.float32)
    topo = causal_topologies(g["r"], g["topo_w"])
    # row 1: frontier + 7 nodes, two branches off the frontier — every
    # packed position occupied, so the tree row stays finite
    topo[1] = tree_topology_row([-1, 0, 0, 2, 3, 4, 5], g["topo_w"])
    out = twin(
        np.ones((g["hkv"], g["t"] * g["g"], g["d"]), np.float32),
        pool, pool,
        np.asarray([12, 8], np.int32), np.asarray([8, 8], np.int32),
        np.asarray([0, 8], np.int32),
        np.arange(g["r"] * g["pps"], dtype=np.int32)
        .reshape(g["r"], g["pps"]),
        group=g["g"], topologies=topo,
    )
    out, _lse = out                        # (attention out, per-row LSE)
    if not np.isfinite(np.asarray(out)).all():
        raise ValueError("ragged local twin produced non-finite output")
    return TwinProfile(LOCAL, None, True,
                       "per-rank function, no mesh operand")


#: harness key → runner. Keys are the DEGRADATION_TARGETS dotted paths,
#: except where one twin serves families of different classes (the
#: grouped GEMM) — those disambiguate through _twin_key.
_NATIVE = "triton_distributed_tpu.tools.native."
_HARNESSES = {
    "jax.lax.all_gather": _h_all_gather,
    "jax.lax.psum_scatter": _h_psum_scatter,
    "jax.lax.all_to_all": _h_all_to_all,
    _NATIVE + "xla_ag_gemm": _h_ag_gemm,
    _NATIVE + "xla_gemm_rs": _h_gemm_rs,
    _NATIVE + "xla_kv_ship": _h_kv_ship,
    "grouped_matmul_xla:ag": _h_grouped_ag,
    "grouped_matmul_xla:rs": _h_grouped_rs,
    "triton_distributed_tpu.kernels.ring_attention."
    "dense_attention_reference": _h_cp_attention,
    "triton_distributed_tpu.train.grad_wire.grad_allreduce_xla":
        _h_grad_ring,
    "triton_distributed_tpu.kernels.flash_decode.cp_lse_combine_xla":
        _h_cp_decode,
    "triton_distributed_tpu.kernels.ragged_paged_attention."
    "ragged_paged_attention_xla": _h_ragged_local,
}

#: fallback class table for hosts without n devices (profile marked
#: executed=False; realization and the SL012/SL013 diffs still run)
_STATIC_CLASS = {
    "jax.lax.all_gather": (SINGLE, "all"),
    "jax.lax.psum_scatter": (FOLD, "all"),
    "jax.lax.all_to_all": (SINGLE, "all"),
    _NATIVE + "xla_ag_gemm": (SINGLE, "all"),
    _NATIVE + "xla_gemm_rs": (FOLD, "all"),
    _NATIVE + "xla_kv_ship": (SINGLE, None),
    "grouped_matmul_xla:ag": (SINGLE, "all"),
    "grouped_matmul_xla:rs": (FOLD, "all"),
    "triton_distributed_tpu.kernels.ring_attention."
    "dense_attention_reference": (SINGLE, "all"),
    "triton_distributed_tpu.train.grad_wire.grad_allreduce_xla":
        (FOLD, "all"),
    "triton_distributed_tpu.kernels.flash_decode.cp_lse_combine_xla":
        (FOLD, "all"),
    "triton_distributed_tpu.kernels.ragged_paged_attention."
    "ragged_paged_attention_xla": (LOCAL, None),
}


def _twin_key(path: str, family_name: str | None) -> str:
    """The grouped GEMM backs both MoE pipeline stages; the degraded
    op path composed around it differs (gather-then-GEMM vs
    GEMM-then-scatter, ops/moe_tp.py), so the harness key carries the
    stage."""
    if path.endswith("group_gemm.grouped_matmul_xla"):
        stage = "rs" if "reduce_rs" in (family_name or "") else "ag"
        return f"grouped_matmul_xla:{stage}"
    return path


@functools.lru_cache(maxsize=None)
def _run_twin(key: str, n: int) -> TwinProfile:
    import jax

    from triton_distributed_tpu.kernels.registry import (
        resolve_degradation_target,
    )

    if key not in _HARNESSES:
        raise ValueError(
            f"no twin harness for degradation target {key!r} — contract "
            "inference cannot profile it (add a harness in "
            "analysis/contract_infer.py)"
        )
    path = key.split(":")[0]
    if ":" in key:
        path = ("triton_distributed_tpu.kernels.group_gemm."
                "grouped_matmul_xla")
    twin = resolve_degradation_target(path)   # existence proof either way
    if len(jax.devices()) < n:
        cls, sources = _STATIC_CLASS[key]
        return TwinProfile(
            cls, sources, False,
            f"{len(jax.devices())} device(s) < mesh {n}: static class "
            "table (twin resolved but not executed)",
        )
    return _HARNESSES[key](twin, n)


def twin_profile(degrades_to: str, n: int,
                 family_name: str | None = None) -> TwinProfile:
    """Execute (or table-classify) the twin behind a DEGRADATION_TARGETS
    dotted path on an ``n``-rank mesh with rank-tagged inputs."""
    return _run_twin(_twin_key(degrades_to, family_name), n)


# ----------------------------------------------------------- realization

def _observe_root(rec, state, root):
    """Per-rank classification of one root's contrib nibbles: exact
    per-source single-marker counts, full-fold counts, empties."""
    n = rec.n
    full_mask = sum(np.int64(1) << (_NIBBLE * s) for s in range(n))
    per_rank = []
    for rank in range(n):
        st = state.get(rank, root)
        c = st["contrib"]
        counts = {
            s: int((c == (np.int64(1) << (_NIBBLE * s))).sum())
            for s in range(n)
        }
        per_rank.append({
            "counts": counts,
            "fold": int((c == full_mask).sum()) if n > 1
            else int((c != 0).sum()),
            "empty": int((c == 0).sum()),
            "total": int(c.size),
        })
    return per_rank


def _class_mass(per_rank, cls, n) -> int:
    """How many elements of a root exhibit the twin class's delivery
    pattern, summed over ranks. ``single`` counts FOREIGN singles only
    (own-written compute buffers must not outscore the transport dst);
    ``local`` counts own singles on roots no foreign byte ever
    touched."""
    if cls == FOLD:
        return sum(o["fold"] for o in per_rank)
    if cls == SINGLE:
        return sum(
            c for rank, o in enumerate(per_rank)
            for s, c in o["counts"].items() if s != rank
        )
    for rank, o in enumerate(per_rank):
        if any(c for s, c in o["counts"].items() if s != rank):
            return 0
    return sum(o["counts"][rank] for rank, o in enumerate(per_rank))


def _modal(values):
    vals = [v for v in values if v > 0]
    if not vals:
        return 0
    uniq, counts = np.unique(np.asarray(vals), return_counts=True)
    return int(uniq[np.argmax(counts)])


def _realize(rec, state, profile, declared):
    """Pick the dst root that exhibits the twin class and read the
    concrete contract quantities off its provenance. Returns
    (dst_root or None, observation dict, dst-mismatch findings)."""
    n = rec.n
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    cands = [
        root for root, meta in rec.ref_meta.items()
        if meta.dtype is not None and not meta.is_input
        and int(np.prod(meta.shape)) > 0
    ]
    obs = {root: _observe_root(rec, state, root) for root in cands}
    declared_root = None
    if declared is not None:
        declared_root = dataflow._resolve_dst(rec, declared.dst)
        if declared_root not in obs:
            obs[declared_root] = _observe_root(rec, state, declared_root)
    scores = {
        root: _class_mass(per, profile.cls, n) for root, per in obs.items()
    }
    # ties broken toward the widest dtype: a quantized wire workspace
    # matches the delivery pattern element-for-element with the
    # dequantized destination, but the contract belongs to the latter
    best = max(
        (root for root in scores if scores[root] > 0),
        key=lambda r: (scores[r],
                       np.dtype(rec.ref_meta[r].dtype).itemsize, r),
        default=None,
    )
    # the declared dst wins as long as it realizes the class at all —
    # secondary roots (landed metadata, scale planes) can carry MORE
    # pattern-matching elements without being the payload destination
    if declared_root is not None and scores.get(declared_root, 0) > 0:
        dst = declared_root
    elif declared_root is not None and best is not None:
        dst = best
        findings.append(Finding(
            "SL012", kernel,
            f"declared contract dst {declared_root!r} exhibits none of "
            f"the twin's '{profile.cls}' delivery pattern, but "
            f"{best!r} does ({scores[best]} element(s)) — the declared "
            "destination is wrong"
            + ("" if profile.executed else " [twin class from static "
               "table; no devices to execute it]"),
            site=site,
        ))
    else:
        dst = best
    return dst, obs, findings


def _infer_single(rec, per_rank, dst, profile):
    """Concrete gather/permute quantities at the chosen dst."""
    n = rec.n
    dst_elems = int(np.prod(rec.ref_meta[dst].shape))
    senders = {
        rank: {s for s, c in o["counts"].items() if c > 0}
        for rank, o in enumerate(per_rank)
    }
    payload = _modal(
        c for o in per_rank for c in o["counts"].values()
    )
    own_absent = (
        all(o["counts"][rank] == 0 for rank, o in enumerate(per_rank))
        and any(senders.values())
    )
    full = all(
        o["empty"] == 0 or (own_absent and o["empty"] == payload)
        for o in per_rank
    )
    all_sources = all(
        senders[rank] >= (set(range(n)) - ({rank} if own_absent else set()))
        for rank in range(n)
    )
    kind = "gather" if (all_sources and full) else "permute"
    src_only = None
    if profile.sources is None:
        observed = {r: frozenset(s) for r, s in senders.items()}
        if any(s != set(range(n)) for s in senders.values()):
            src_only = (lambda m: lambda rank, n_: m[rank])(observed)
    payload_fn = None
    if payload and payload != dst_elems // n:
        payload_fn = (lambda v: lambda n_: v)(payload)
    contract = DeliveryContract(
        kind=kind, dst=dst, payload_per_src=payload_fn, full=full,
        own_absent_ok=own_absent, src_only=src_only,
    )
    return contract, {
        "senders": senders, "payload": payload,
        "own_absent": own_absent, "full": full,
    }


def _diff_single(rec, declared, per_rank, dst, profile, q):
    """SL012 facets of a single-class (gather/permute) realization
    against the declaration."""
    n = rec.n
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    tabled = ("" if profile.executed
              else " [twin class from static table]")
    dst_elems = int(np.prod(rec.ref_meta[dst].shape))
    expect = (
        declared.payload_per_src(n) if declared.payload_per_src
        else dst_elems // n
    )
    if q["payload"] and expect != q["payload"]:
        findings.append(Finding(
            "SL012", kernel,
            f"declared payload_per_src={expect} but the replay lands "
            f"{q['payload']} element(s) per (rank, source) in {dst} — "
            f"the contract {'over' if expect > q['payload'] else 'under'}"
            f"-declares each source's delivery{tabled}",
            site=site,
        ))
    for rank in range(n):
        declared_set = (
            set(declared.src_only(rank, n))
            if declared.src_only is not None else set(range(n))
        )
        got = q["senders"][rank]
        extra = got - declared_set
        allow_own = {rank} if (declared.own_absent_ok
                               or q["own_absent"]) else set()
        missing = declared_set - got - allow_own
        if extra:
            findings.append(Finding(
                "SL012", kernel,
                f"source rank(s) {sorted(extra)} deliver into rank "
                f"{rank}'s {dst} but sit OUTSIDE the declared source "
                f"topology {sorted(declared_set)}{tabled}",
                site=site, ranks=(rank,),
            ))
        if missing:
            findings.append(Finding(
                "SL012", kernel,
                f"declared source rank(s) {sorted(missing)} never "
                f"deliver into rank {rank}'s {dst} — the declared "
                f"topology over-promises{tabled}",
                site=site, ranks=(rank,),
            ))
    if declared.full != q["full"]:
        findings.append(Finding(
            "SL012", kernel,
            f"declared full={declared.full} but the replay shows "
            f"full={q['full']} coverage of {dst} "
            f"({'holes remain' if declared.full else 'every element is covered'})"
            f"{tabled}",
            site=site,
        ))
    # own-absence only drifts when the declared topology actually
    # expects own delivery — a src_only that already excludes the own
    # rank (kv_ship's disjoint pairs) declares the absence structurally,
    # which is exactly how SL008's want=0 branch reads it
    own_expected = any(
        rank in (set(declared.src_only(rank, n))
                 if declared.src_only is not None else {rank})
        for rank in range(n)
    )
    if q["own_absent"] and own_expected and not declared.own_absent_ok:
        findings.append(Finding(
            "SL012", kernel,
            f"no rank ever publishes its OWN chunk into {dst} yet the "
            "declared contract does not set own_absent_ok — the "
            f"declaration and the kernel disagree{tabled}",
            site=site,
        ))
    return findings


def _infer_topo_meta(rec) -> dict | None:
    """Detect a per-row attention-topology operand from the replay's
    input signature. The ragged family's scalar-prefetch block is a
    leading run of int32 inputs — table ``(R, pps)``, then the three
    per-row vectors ``kv_lens``/``q_lens``/``q_starts`` of length R.
    When a FIFTH leading int32 input follows with shape
    ``(R, 2 + 2W)``, it is the topology descriptor: the inferred LOCAL
    contract carries the masked-coverage facet so an UNDECLARED family
    still gets its descriptors value-checked."""
    metas = sorted(
        (m for m in rec.ref_meta.values() if m.is_input),
        key=lambda m: m.index,
    )
    if len(metas) < 5:
        return None
    lead = metas[:5]
    if not all(m.dtype == np.dtype(np.int32) for m in lead):
        return None
    if len(lead[1].shape) != 1:
        return None
    rows = lead[1].shape[0]
    tshape = lead[4].shape
    if len(tshape) != 2 or tshape[0] != rows:
        return None
    w = (tshape[1] - 2) // 2
    if w < 1 or tshape[1] != 2 + 2 * w:
        return None
    return {"ref": 4, "kv_lens": 1, "q_lens": 2, "width": int(w)}


def infer_from_replay(rec, sim, state, *, degrades_to,
                      declared=None) -> InferenceResult:
    """The core diff: profile the twin, realize the contract from the
    replayed provenance, and compare against the declaration (SL012) or
    synthesize the missing one (SL013)."""
    kernel, site = rec.info.kernel, rec.info.site
    profile = twin_profile(degrades_to, rec.n, family_name=kernel)
    tabled = "" if profile.executed else " [twin class from static table]"
    findings: list = []

    if declared is not None:
        declared_cls = _KIND_CLASS.get(declared.kind)
        if declared_cls != profile.cls:
            findings.append(Finding(
                "SL012", kernel,
                f"declared kind {declared.kind!r} is class "
                f"{declared_cls!r} but the XLA twin ({degrades_to}) "
                f"delivers class {profile.cls!r} ({profile.detail}) — "
                f"the declared contract checks the wrong shape{tabled}",
                site=site,
            ))
            # realize against the twin's class anyway: the synthesized
            # contract (not the wrong declaration) is what SL008 needs

    dst, obs, dst_findings = _realize(rec, state, profile, declared)
    findings += dst_findings
    if dst is None:
        findings.append(Finding(
            "SL012" if declared is not None else "SL013", kernel,
            f"no root buffer exhibits the twin's '{profile.cls}' "
            f"delivery pattern ({profile.detail}) — the kernel's replay "
            f"and its degradation target disagree entirely{tabled}",
            site=site,
        ))
        return InferenceResult(profile, None, None, findings, obs)

    per_rank = obs[dst]
    if profile.cls == FOLD:
        contract = DeliveryContract(kind="reduce", dst=dst)
        quantities = {}
    elif profile.cls == LOCAL:
        full = all(o["empty"] == 0 for o in per_rank)
        topo_meta = _infer_topo_meta(rec)
        contract = DeliveryContract(kind="local", dst=dst, full=full,
                                    topo=topo_meta)
        quantities = {"full": full, "topo": topo_meta}
        if declared is not None and _KIND_CLASS.get(declared.kind) == LOCAL:
            if declared.full != full:
                findings.append(Finding(
                    "SL012", kernel,
                    f"declared full={declared.full} but the replay shows "
                    f"full={full} own-write coverage of {dst}{tabled}",
                    site=site,
                ))
            dt = getattr(declared, "topo", None)
            if (dt is None) != (topo_meta is None):
                have = "a" if topo_meta else "no"
                want = "one" if dt else "none"
                findings.append(Finding(
                    "SL012", kernel,
                    f"the replay's input signature shows {have} per-row "
                    f"attention-topology operand but the declared "
                    f"contract carries {want} — the masked-coverage "
                    f"facet would check the wrong operand set{tabled}",
                    site=site,
                ))
            elif dt is not None and topo_meta is not None and \
                    int(dt.get("width", -1)) != topo_meta["width"]:
                findings.append(Finding(
                    "SL012", kernel,
                    f"declared topology width {dt.get('width')} drifted "
                    f"from the replay's descriptor width "
                    f"{topo_meta['width']}{tabled}",
                    site=site,
                ))
    else:
        contract, quantities = _infer_single(rec, per_rank, dst, profile)
        if declared is not None \
                and _KIND_CLASS.get(declared.kind) == SINGLE:
            findings += _diff_single(
                rec, declared, per_rank, dst, profile, quantities)

    if declared is None:
        findings.append(Finding(
            "SL013", kernel,
            f"family registered with NO declared DeliveryContract — "
            f"inferred a {contract.kind!r} contract on {dst!r} from the "
            f"XLA twin ({degrades_to}: {profile.detail}) so SL008 "
            "still runs; declare the contract in kernels/registry.py "
            f"to pin it{tabled}",
            site=site,
        ))
    return InferenceResult(profile, dst, contract, findings,
                           {"roots": obs, "quantities": quantities})


def infer_spec(rec, *, degrades_to, declared=None) -> InferenceResult:
    """Inference over an already-recorded symbolic run (fixtures and
    tests): simulate, replay provenance, then diff."""
    sim = checks.simulate(rec)
    if not sim.completed:
        # a wedged protocol has no terminal provenance to realize; the
        # SL002/SL003 findings from the protocol pass own this case
        profile = twin_profile(degrades_to, rec.n,
                               family_name=rec.info.kernel)
        return InferenceResult(profile, None, None, [], {})
    state, _puts, _wire = dataflow.replay_provenance(rec, sim)
    return infer_from_replay(
        rec, sim, state, degrades_to=degrades_to, declared=declared)


def infer_family(fam, n: int = 8, rec=None) -> InferenceResult:
    """Infer one registry family's contract at mesh ``n``. ``rec`` can
    reuse the recorder lint already produced; otherwise the family is
    re-analyzed symbolically."""
    if not fam.degrades_to:
        raise ValueError(
            f"family {fam.name!r} declares no degradation target — "
            "nothing to infer from (missing_degradation_targets() "
            "polices this)"
        )
    if rec is None:
        from triton_distributed_tpu.analysis import lint

        rec, _ = lint.analyze_family(fam, n)
    return infer_spec(rec, degrades_to=fam.degrades_to,
                      declared=fam.contract)


def verify_declared_contracts(n: int = 4, kernels=None) -> list:
    """Run inference over every registered family and return the
    SL012/SL013 findings — the TDTPU_LINT_STRICT registration gate and
    the ci/fast.sh smoke step both call this."""
    from triton_distributed_tpu.kernels.registry import families

    findings = []
    for name, fam in sorted(families().items()):
        if kernels and not any(k in name for k in kernels):
            continue
        findings += infer_family(fam, n).findings
    return findings
