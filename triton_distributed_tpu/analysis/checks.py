"""Checker passes over recorded per-rank SHMEM event traces.

The core is a cross-rank *replay*: all ranks' straight-line traces are
advanced together under TPU semaphore semantics — a DMA start delivers
its credits immediately (the transfer completes asynchronously
regardless of sender progress), a ``signal_op`` delivers when executed,
a wait blocks until enough credits are available and consumes them.
Replay to quiescence either completes (then the balance/hazard rules
run) or wedges (then the blocked waits are classified into
unsatisfiable waits and genuine cross-rank deadlock cycles).

Ordering is tracked with vector clocks:

* every executed event stamps the executing rank's clock;
* a credit carries the sender's clock at delivery;
* a *consuming wait* joins the clocks of the credits it can actually
  vouch for. TPU semaphores count, they don't tag: a wait for ``v``
  knows *which* transfers have landed only when the credit source is
  unambiguous — all credits on the slot come from one source rank
  (per-(src, dst) issue order is a hardware guarantee), or the wait has
  consumed *every* credit the slot will ever carry (the barrier
  pattern). Ambiguous consumption keeps the count but joins nothing —
  conservative in exactly the way slot-reuse bugs require.

The buffer-hazard rule then asks, for every remote DMA landing in a
symmetric buffer: is each local access to an overlapping region ordered
against the landing, either because the access happened-before the DMA
*start* (clock comparison) or because a wait that vouches for the
landing completed before the access (consumption order)? Neither ⇒ the
classic write-after-read/write-after-write over RDMA.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from triton_distributed_tpu.analysis import events as ev
from triton_distributed_tpu.analysis.findings import Finding


def _fmt_key(key) -> str:
    name, slot = key
    return name + (str(list(slot)) if slot else "")


# ---------------------------------------------------------------- simulation

@dataclass
class Credit:
    weight: int
    src: int
    src_idx: int
    clock: tuple
    kind: str                   # signal | dma_send | dma_recv | local_copy
    consumed: int = 0


@dataclass
class SimResult:
    completed: bool
    pcs: list
    credits: dict               # (owner, key) -> [Credit]
    delivered: dict             # (owner, key) -> int
    consumed: dict              # (owner, key) -> int
    total_ever: dict            # (owner, key) -> int (whole-trace count)
    sources: dict               # (owner, key) -> set of src ranks
    remote_writes: list         # PutEvents with cross-rank landings
    guarantee: dict             # (src, src_idx) -> (dst, wait_idx, wait_ctr)
    local_access: list          # per rank: [(idx, ctr, vc, region, kind)]
    blocked: list               # [(rank, WaitEvent)]
    schedule: list              # [(rank, event)] in replay execution order
                                # — one feasible cross-rank linearization
                                # (respects every wait); the dataflow
                                # pass replays provenance along it


def _deliveries(e):
    """Static (owner, key, weight, kind, src_idx) deliveries of one event."""
    out = []
    if isinstance(e, ev.PutEvent):
        kind = "local_copy" if e.local else "dma_send"
        out.append((e.rank, e.send_key, 1, kind))
        if not e.local and e.recv_key is not None:
            out.append((e.dst_rank, e.recv_key, 1, "dma_recv"))
    elif isinstance(e, ev.SignalEvent):
        out.append((e.target, e.key, e.inc, "signal"))
    return out


def simulate(rec: ev.Recorder) -> SimResult:
    n = rec.n
    traces = rec.traces
    credits: dict = {}
    delivered: dict = {}
    consumed: dict = {}
    total_ever: dict = {}
    sources: dict = {}
    for r in range(n):
        for e in traces[r]:
            for owner, key, w, kind in _deliveries(e):
                k = (owner, key)
                total_ever[k] = total_ever.get(k, 0) + w
                sources.setdefault(k, set()).add(e.rank)

    clocks = [[0] * n for _ in range(n)]
    pcs = [0] * n
    remote_writes: list = []
    guarantee: dict = {}
    local_access: list = [[] for _ in range(n)]
    schedule: list = []

    def access(r, e, region, kind):
        if region is not None:
            local_access[r].append((e.idx, e.ctr, e.vc, region, kind))

    def execute(r, e):
        clocks[r][r] += 1
        e.vc = tuple(clocks[r])
        e.ctr = clocks[r][r]
        if isinstance(e, ev.PutEvent):
            # the RDMA reads its source and (locally) writes its
            # destination; modeled at start time for hazard purposes
            local_access[r].append((e.idx, e.ctr, e.vc, e.src_region, "r"))
            if e.local:
                local_access[r].append(
                    (e.idx, e.ctr, e.vc, e.dst_region, "w"))
            else:
                remote_writes.append(e)
            for owner, key, w, kind in _deliveries(e):
                k = (owner, key)
                credits.setdefault(k, []).append(
                    Credit(w, r, e.idx, e.vc, kind))
                delivered[k] = delivered.get(k, 0) + w
        elif isinstance(e, ev.SignalEvent):
            k = (e.target, e.key)
            credits.setdefault(k, []).append(
                Credit(e.inc, r, e.idx, e.vc, "signal"))
            delivered[k] = delivered.get(k, 0) + e.inc
        elif isinstance(e, (ev.ReadEvent, ev.WriteEvent)):
            kind = "r" if isinstance(e, ev.ReadEvent) else "w"
            local_access[r].append((e.idx, e.ctr, e.vc, e.region, kind))
        elif isinstance(e, ev.QuantEvent):
            # the wire events stand in for the pipeline's hull accesses
            # (lang.wire skips the value-level pipeline under a recorder)
            access(r, e, e.src_region, "r")
            access(r, e, e.q_region, "w")
            access(r, e, e.s_region, "w")
        elif isinstance(e, ev.DequantEvent):
            access(r, e, e.q_region, "r")
            access(r, e, e.s_region, "r")
            access(r, e, e.add_region, "r")
            access(r, e, e.dst_region, "w")
        elif isinstance(e, ev.AddEvent):
            access(r, e, e.a_region, "r")
            access(r, e, e.b_region, "r")
            access(r, e, e.dst_region, "w")

    def try_wait(r, e) -> bool:
        k = (r, e.key)
        avail = delivered.get(k, 0) - consumed.get(k, 0)
        if avail < e.value:
            return False
        clocks[r][r] += 1
        e.vc = None  # assigned below after joins
        e.ctr = clocks[r][r]
        pool = credits.get(k, [])
        cum_before = consumed.get(k, 0)
        cum = cum_before + e.value
        consumed[k] = cum
        # consume the earliest-delivered credits
        need = e.value
        taken = []
        for c in pool:
            if need == 0:
                break
            free = c.weight - c.consumed
            if free == 0:
                continue
            take = min(free, need)
            c.consumed += take
            need -= take
            taken.append(c)
        # which credits can this wait vouch for? (see module docstring)
        single_src = len(sources.get(k, set())) <= 1
        all_ever = cum >= total_ever.get(k, 0)
        if single_src or all_ever:
            vouched = [c for c in pool if c.consumed == c.weight]
            for c in vouched:
                for d in range(n):
                    clocks[r][d] = max(clocks[r][d], c.clock[d])
                if c.kind == "dma_recv":
                    guarantee.setdefault(
                        (c.src, c.src_idx), (r, e.idx, e.ctr))
        e.vc = tuple(clocks[r])
        return True

    progress = True
    while progress:
        progress = False
        for r in range(n):
            while pcs[r] < len(traces[r]):
                e = traces[r][pcs[r]]
                if isinstance(e, ev.WaitEvent):
                    if not try_wait(r, e):
                        break
                else:
                    execute(r, e)
                schedule.append((r, e))
                pcs[r] += 1
                progress = True

    blocked = [
        (r, traces[r][pcs[r]])
        for r in range(n)
        if pcs[r] < len(traces[r])
    ]
    return SimResult(
        completed=not blocked,
        pcs=pcs,
        credits=credits,
        delivered=delivered,
        consumed=consumed,
        total_ever=total_ever,
        sources=sources,
        remote_writes=remote_writes,
        guarantee=guarantee,
        local_access=local_access,
        blocked=blocked,
        schedule=schedule,
    )


# ------------------------------------------------------------------- checks

def _check_blocked(rec, sim) -> list:
    """Classify a wedged replay: waits whose credits never come (SL002)
    vs genuine cross-rank wait-for cycles (SL003)."""
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    providers: dict = {}
    for r, w in sim.blocked:
        k = (r, w.key)
        provs = set()
        future = 0
        for s in range(rec.n):
            for e in rec.traces[s][sim.pcs[s]:]:
                for owner, key, wt, kind in _deliveries(e):
                    if (owner, key) == k:
                        provs.add(s)
                        future += wt
        avail = sim.delivered.get(k, 0) - sim.consumed.get(k, 0)
        if avail + future < w.value:
            findings.append(Finding(
                "SL002", kernel,
                f"rank {r} waits for {w.value} credit(s) on "
                f"{_fmt_key(w.key)} but only {avail} are available and "
                f"{future} more can ever arrive (all ranks' remaining "
                "events considered) — this is a hang at runtime",
                site=site, ranks=(r,), sem=_fmt_key(w.key), phase=w.phase,
            ))
        else:
            providers[r] = provs
    # cycle hunt over ranks blocked purely on other blocked ranks
    seen_cycles = set()
    for start in providers:
        path, node = [], start
        on_path = {}
        while node in providers and node not in on_path:
            on_path[node] = len(path)
            path.append(node)
            nxts = [s for s in providers[node] if s in providers]
            if not nxts:
                path = []
                break
            node = min(nxts)
        if path and node in on_path:
            cycle = tuple(path[on_path[node]:])
            canon = tuple(sorted(cycle))
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            chain = " -> ".join(
                f"rank {r} [waits {_fmt_key(dict(sim.blocked)[r].key)}]"
                for r in cycle
            ) + f" -> rank {cycle[0]}"
            findings.append(Finding(
                "SL003", kernel,
                f"cross-rank wait-for cycle: {chain}; every rank's "
                "missing credit sits behind another parked wait",
                site=site, ranks=canon,
                sem=_fmt_key(dict(sim.blocked)[cycle[0]].key),
                phase=dict(sim.blocked)[cycle[0]].phase,
            ))
    if not findings and sim.blocked:
        # blocked on providers that are themselves SL002/..-stuck
        ranks = tuple(sorted(r for r, _ in sim.blocked))
        r0, w0 = sim.blocked[0]
        findings.append(Finding(
            "SL002", kernel,
            f"ranks {list(ranks)} are transitively wedged behind an "
            "unsatisfiable wait",
            site=site, ranks=ranks, sem=_fmt_key(w0.key), phase=w0.phase,
        ))
    return findings


def _check_balance(rec, sim) -> list:
    """SL001/SL007: credits left on semaphores after a clean run."""
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    leftovers: dict = {}
    for (owner, key), total in sim.delivered.items():
        used = sim.consumed.get((owner, key), 0)
        if total > used:
            kinds = {
                c.kind for c in sim.credits[(owner, key)]
                if c.consumed < c.weight
            }
            leftovers.setdefault((key, frozenset(kinds)), []).append(
                (owner, total - used))
    for (key, kinds), owners in sorted(
        leftovers.items(), key=lambda kv: str(kv[0])
    ):
        ranks = tuple(r for r, _ in owners)
        excess = {r: x for r, x in owners}
        if kinds <= {"dma_send", "local_copy"}:
            findings.append(Finding(
                "SL007", kernel,
                f"{sum(excess.values())} started DMA(s) never locally "
                f"drained on {_fmt_key(key)} (missing quiet()/"
                f"wait_send()); per-rank excess {excess}",
                site=site, ranks=ranks, sem=_fmt_key(key),
            ))
        else:
            findings.append(Finding(
                "SL001", kernel,
                f"credit imbalance on {_fmt_key(key)}: "
                f"{sum(excess.values())} credit(s) signaled but never "
                f"consumed by a wait (per-rank excess {excess}) — a "
                "missing signal_wait_until / off-by-one in the wait "
                "value; the next launch reusing this semaphore is "
                "released early",
                site=site, ranks=ranks, sem=_fmt_key(key),
            ))
    return findings


def _check_hazards(rec, sim) -> list:
    """SL004: remote DMA landings unordered against local accesses."""
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    reported = set()
    for w in sim.remote_writes:
        d = w.dst_rank
        if not (0 <= d < rec.n):
            continue
        g = sim.guarantee.get((w.rank, w.idx))
        for idx, ctr, vc, region, kind in sim.local_access[d]:
            if region is None or not w.dst_region.overlaps(region):
                continue
            # access happened-before the DMA start?
            if w.vc[d] >= ctr:
                continue
            # a wait vouching for the landing completed before the access?
            if g is not None and g[0] == d and g[1] < idx:
                continue
            sig = ("local", w.send_key, region.ref, d, kind)
            if sig in reported:
                continue
            reported.add(sig)
            findings.append(Finding(
                "SL004", kernel,
                f"put from rank {w.rank} lands in {w.dst_region} on rank "
                f"{d} while rank {d} {'reads' if kind == 'r' else 'writes'}"
                f" {region} with no ordering wait/fence between them "
                "(write-after-read over RDMA)",
                site=site, ranks=(w.rank, d), sem=_fmt_key(w.recv_key),
                phase=w.phase,
            ))
        # unordered overlapping landings from two different sources
        for w2 in sim.remote_writes:
            if w2 is w or w2.dst_rank != d or w2.rank == w.rank:
                continue
            if not w.dst_region.overlaps(w2.dst_region):
                continue
            if (w.rank, w.idx) > (w2.rank, w2.idx):
                continue
            g1 = sim.guarantee.get((w.rank, w.idx))
            g2 = sim.guarantee.get((w2.rank, w2.idx))
            ordered = (
                (g1 is not None and g1[0] == d and w2.vc[d] >= g1[2])
                or (g2 is not None and g2[0] == d and w.vc[d] >= g2[2])
            )
            if ordered:
                continue
            sig = ("waw", d, w.dst_region.ref,
                   tuple(sorted((w.rank, w2.rank))))
            if sig in reported:
                continue
            reported.add(sig)
            findings.append(Finding(
                "SL004", kernel,
                f"unordered overlapping RDMA landings on rank {d}: "
                f"{w.dst_region} from rank {w.rank} vs {w2.dst_region} "
                f"from rank {w2.rank} (write-after-write over RDMA)",
                site=site, ranks=(w.rank, w2.rank, d),
                sem=_fmt_key(w.recv_key), phase=w.phase,
            ))
    return findings


def _check_barriers(rec) -> list:
    """SL005 (per family): barrier use without a collective_id; ranks
    disagreeing on the barrier sequence. (Cross-family collective_id
    uniqueness lives in lint.py where all families are visible.)"""
    findings = []
    kernel, site = rec.info.kernel, rec.info.site
    if rec.barrier_sem_used and rec.info.collective_id is None:
        findings.append(Finding(
            "SL005", kernel,
            "kernel touches the global barrier semaphore but its launch "
            "sets no collective_id (Mosaic rejects this at compile time; "
            "two such kernels would share one unkeyed rendezvous)",
            site=site,
        ))

    def seq(r):
        out = []
        for e in rec.traces[r]:
            if isinstance(e, ev.BarrierEvent):
                out.append(("barrier", e.collective_id))
            elif isinstance(e, ev.WaitEvent) and e.key[0] == "barrier_sem":
                out.append(("wait", e.value))
        return out

    ref = seq(0)
    bad = tuple(r for r in range(1, rec.n) if seq(r) != ref)
    if bad:
        findings.append(Finding(
            "SL005", kernel,
            f"ranks {list(bad)} execute a different barrier sequence "
            f"than rank 0 ({seq(bad[0])} vs {ref}) — collective "
            "rendezvous diverges across ranks",
            site=site, ranks=bad,
        ))
    return findings


def _check_vmem(rec) -> list:
    """SL006: VMEM-resident working set vs the per-core budget."""
    from triton_distributed_tpu.config import fused_vmem_budget

    limit = rec.info.vmem_limit_bytes or fused_vmem_budget()
    if rec.info.vmem_bytes <= limit:
        return []
    top = sorted(rec.info.vmem_breakdown, key=lambda kv: -kv[1])[:4]
    detail = ", ".join(f"{n}={b // 1024}KiB" for n, b in top)
    return [Finding(
        "SL006", rec.info.kernel,
        f"VMEM working set {rec.info.vmem_bytes // 1024}KiB exceeds the "
        f"budget {limit // 1024}KiB (largest: {detail})",
        site=rec.info.site,
    )]


def check_family(rec: ev.Recorder, contract=None,
                 fallback_contract=None) -> list:
    """All per-family passes over one recorded kernel family.

    ``contract`` (a :class:`~triton_distributed_tpu.analysis.dataflow.
    DeliveryContract`, usually from the kernel registry) additionally
    runs the data-correctness passes: SL008 delivery completeness
    against the contract, SL009/SL010 wire-rail consistency. The wire
    passes run whenever the traces carry a quantized rail, contract or
    not — a protocol can be semaphore-clean and still deliver the wrong
    bytes, which is exactly what these passes exist to catch.

    ``fallback_contract`` is used only when ``contract`` is None: an
    obligation *inferred* from the family's XLA twin
    (:mod:`.contract_infer`) so SL008 never goes blind on a family
    registered without a declaration — the gap itself is surfaced as
    SL013 by the inference pass."""
    from triton_distributed_tpu.analysis import dataflow

    if contract is None:
        contract = fallback_contract
    sim = simulate(rec)
    findings = _check_barriers(rec) + _check_vmem(rec)
    if sim.completed:
        findings += _check_balance(rec, sim)
        findings += _check_hazards(rec, sim)
        findings += dataflow.check_dataflow(rec, sim, contract)
    else:
        findings += _check_blocked(rec, sim)
    return findings
