"""shmemlint — static semaphore-protocol and deadlock analysis (L6).

The dynamic correctness evidence for the SHMEM kernel family (chaos
delays + the TPU interpreter's race detector) is probabilistic and
environment-bound: ``tests/test_races.py`` documents a deliberately
removed wait the detector missed under ``dma_execution_mode="on_wait"``,
and on a jax without the TPU-simulation interpreter the dynamic passes
cannot run at all. This package closes that gap *statically* (the
ML-Triton argument — compiler passes over kernel IR instead of runtime
luck, arxiv 2503.14985): each kernel family is symbolically executed
once per rank on an abstract N-rank mesh, every ``lang.shmem`` event
(puts, signal increments, consuming waits, fences, barriers) is
recorded into per-rank traces, and checker passes verify the cross-rank
protocol — credit balance, deadlock freedom, barrier hygiene, RDMA
buffer hazards, VMEM budget.

Layout:

* :mod:`events`    — the event/trace model + the active recorder that
  the ``lang.shmem`` hook layer feeds.
* :mod:`abstract`  — the abstract evaluator: fake refs/semaphores/DMA
  handles and the patched Pallas environment kernels run under.
* :mod:`checks`    — the checker passes (cross-rank replay simulation
  with vector clocks, then the SL-rule checks over the result).
* :mod:`findings`  — finding model, severities, the SL001… rule catalog.
* :mod:`dataflow`  — symbolic payload-provenance replay: delivery
  contracts (SL008), wire-rail consistency (SL009), stale-scale reads
  (SL010) — a schedule can be semaphore-clean and still deliver wrong
  bytes; this pass is what catches that.
* :mod:`contract_infer` — contract inference: run each family's XLA
  twin (``degrades_to``) on rank-tagged inputs and realize the concrete
  delivery contract from the replay's provenance arrays; declared
  contracts become assertions (SL012 on drift, SL013 on a missing
  declaration — inference supplies the contract so SL008 never goes
  blind).
* :mod:`mosaic_compat` — the seconds-fast Mosaic pre-flight (MC001–
  MC003): each family's kernel jaxpr, built for hardware, scanned for
  constructs this toolchain's Mosaic backend rejects.
* :mod:`lint`      — public API (:func:`lint.lint_family`,
  :func:`lint.lint_all`) and the CLI
  (``python -m triton_distributed_tpu.analysis.lint``).
* :mod:`fixtures`  — deliberately broken kernels (missing wait, credit
  imbalance, deadlock, barrier misuse, skipped/dup delivery, mispaired
  wire rails, Mosaic-rejected constructs) pinning each rule forever.

The kernel families under analysis are declared in
:mod:`triton_distributed_tpu.kernels.registry`.
"""

from triton_distributed_tpu.analysis.findings import (
    RULES,
    SCHEMA_VERSION,
    Finding,
    Severity,
)

__all__ = [
    "Finding",
    "Severity",
    "RULES",
    "SCHEMA_VERSION",
    "DeliveryContract",
    "lint_all",
    "lint_family",
    "lint_mesh",
    "preflight_all",
    "infer_family",
    "verify_declared_contracts",
]


def __getattr__(name):
    # lint is imported lazily so `python -m ...analysis.lint` does not
    # re-execute a module already bound by this package import (runpy
    # double-import warning)
    if name in ("lint_all", "lint_family", "lint_mesh"):
        from triton_distributed_tpu.analysis import lint

        return getattr(lint, name)
    if name == "preflight_all":
        from triton_distributed_tpu.analysis import mosaic_compat

        return mosaic_compat.preflight_all
    if name == "DeliveryContract":
        from triton_distributed_tpu.analysis import dataflow

        return dataflow.DeliveryContract
    if name in ("infer_family", "verify_declared_contracts"):
        from triton_distributed_tpu.analysis import contract_infer

        return getattr(contract_infer, name)
    raise AttributeError(name)
