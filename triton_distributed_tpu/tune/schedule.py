"""Ring schedules as data — the schedule IR, its enumerator/mutator,
the shmemlint legality oracle, and the persisted winner store.

Every fused engine used to hand-pick exactly one ring schedule
(``kernels/ring.py``: unidirectional forward/reduce rings, fixed chunk
order, fixed double-buffer depth 2, one rail assignment). This module
makes the choice a VALUE:

* :class:`RingSchedule` — per-hop chunk order, traversal direction,
  bidirectional split ratio, double-buffer depth, payload/scale rail
  assignment and eager-vs-epilogue dequant placement. The rings in
  ``kernels/ring.py`` (and the inline bidirectional AG) *execute* a
  schedule; :data:`DEFAULT` reproduces today's behavior byte-
  identically (test-pinned).
* :func:`enumerate_schedules` / :func:`mutate` — the candidate
  generator over each family's declared freedom set. Mutations include
  deliberately ILLEGAL values (a skipped hop, a scale rail on the
  payload's semaphore): the generator proposes, the oracle disposes.
* :func:`check_schedule` — the legality gate: every candidate is built
  through the real kernel builder over an abstract mesh, abstractly
  replayed through shmemlint (SL001–SL011 against the family's declared
  ``DeliveryContract``) and Mosaic-preflighted (MC001–MC006). A
  candidate may be timed or cached ONLY with zero findings; rejections
  carry their rule IDs.
* :func:`store_schedule` / :func:`load_schedule` — the flock'd winner
  store keyed by ``(family, shape, mesh, wire_dtype)``. Resolve paths
  load with zero search cost; only the autotuner search modes
  (``tune.autotuner.search_ring_schedule`` /
  ``search_grid_schedule``) ever write.

Alongside the ring IR lives :class:`GridSchedule` — the same
schedule-as-data discipline for the GRID kernels that are not rings:
the ragged paged-attention walk (``block_q`` ladder, page-walk
double-buffer depth, GQA packing granularity), the kv_ship page
transport (per-tick coalescing width, scale-rail placement) and the
GEMM-RS int8-MXU producer epilogue (quantize-off-accumulator vs
readback requantize, partial-tile demotion policy). The grid families
share the enumerator, the oracle, the pricer dispatch and the store;
:data:`GRID_DEFAULT` replays today's baked-in kernels byte-identically
(test-pinned), and the serving engines resolve traffic-tuned grid
winners through the very same :func:`resolve_schedule` hook.

No devices are required anywhere here: the gate runs on an
``AbstractMesh`` exactly like ``analysis.lint``.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import time
from dataclasses import asdict, dataclass, replace

import numpy as np

_F32 = np.dtype(np.float32)
_I8 = np.dtype(np.int8)
_I32 = np.dtype(np.int32)

#: schema version of the persisted schedule store. v1 stored ring-only
#: entries under a "v" header; v2 writes a "schema_version" header and
#: tags every entry with its schedule ``kind`` ("ring" | "grid") so the
#: loader can pick the right IR class. v1 stores are migrated on read
#: (every pre-grid entry IS a ring entry); unknown versions are ignored
#: cleanly rather than KeyError-ing on a schedule kind they predate.
_STORE_VERSION = 2

#: fields a schedule serializes (stable order for the store)
_FIELDS = ("chunk_order", "direction", "split8", "depth", "scale_rail",
           "dequant")


@dataclass(frozen=True)
class RingSchedule:
    """One executable ring schedule.

    ``chunk_order``
        ``"ring"`` — every hop of the standard ring traversal;
        ``"skip_last"`` — the final hop dropped entirely (start, wait
        AND consume), a protocol-clean mutation only the delivery
        contract can reject (SL008).
    ``direction``
        ``"fwd"`` (chunks flow to the right neighbor) or ``"rev"``
        (leftward; the consumed source walks ``me+s`` instead of
        ``me-s``) — both legal, identical on the perf model.
    ``split8``
        Bidirectional-AG column split in eighths: the clockwise ring
        carries ``split8/8`` of the columns, the counter-clockwise ring
        the rest. 4 is today's even ``k // 2``.
    ``depth``
        Reduce-ring buffer depth (work/recv slab count and DMA-semaphore
        lanes). 2 is today's double buffer; 3 adds one in-flight hop of
        slack against a slow folder.
    ``scale_rail``
        ``"own"`` — the quantized wire's scale planes ride their own
        DMA semaphores (legal); ``"payload"`` — scales signal the
        payload's recv semaphore, so a payload wait can be released by
        a scale arrival while the 1-byte slab is still in flight.
        Credits balance; SL009 is the only thing that can see it.
    ``dequant``
        ``"eager"`` — each wire arrival is dequantized into the bf16
        workspace before the MXU consumes it; ``"epilogue"`` — the MXU
        consumes the quantized slab directly and folds the scale in its
        accumulator epilogue (legal only for int8 wires with an
        s8×s8-capable consumer; resolve maps it to the ``int8-mxu``
        kernel twin).
    """

    #: schedule-kind tag (a class attr, not a field — never serialized;
    #: kernels duck-type on this so a schedule built when this module
    #: runs as ``__main__`` still dispatches correctly)
    kind = "ring"

    chunk_order: str = "ring"
    direction: str = "fwd"
    split8: int = 4
    depth: int = 2
    scale_rail: str = "own"
    dequant: str = "eager"

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RingSchedule":
        return cls(**{k: d[k] for k in _FIELDS if k in d})

    def is_default(self) -> bool:
        return self == DEFAULT


#: the canonical default — byte-identical to the pre-schedule rings
DEFAULT = RingSchedule()


#: fields a grid schedule serializes (stable order for the store)
_GRID_FIELDS = ("block_q", "n_bufs", "pack_rows", "coalesce", "rail",
                "epilogue", "demote", "tree_pack", "prefix_run_len")


@dataclass(frozen=True)
class GridSchedule:
    """One executable grid-kernel schedule — the non-ring families'
    schedule IR (ragged paged attention, kv_ship, the GEMM-RS int8-MXU
    epilogue). One dataclass covers all three; each family's freedom
    set only varies its own knobs and leaves the rest at the default.

    ``block_q``
        Ragged-attention query block rows. 0 means the runtime
        ``auto_block_q`` ladder (today's behavior); an explicit value
        pins the block (the engine applies it as a FLOOR, capped at
        the chunk-derived parking-zone width). An over-wide pin makes
        the out-DMA overrun the packed span's parking zone — only the
        local delivery contract can see it (SL008, via the evaluator's
        out-of-bounds events).
    ``n_bufs``
        Page-walk double-buffer depth: VMEM page landing slots the KV
        fetch rotates through. 2 is today's double buffer; 3 hides one
        more page fetch behind the flash inner loop.
    ``pack_rows``
        GQA packing granularity — the row alignment the engine packs
        request spans to. Gate-geometry knob: widening it moves the
        lint packing off the zero-slack layout, so its interaction
        with ``block_q`` is exactly what the oracle must re-check.
    ``coalesce``
        kv_ship pages per tick descriptor: 1 is the classic per-page
        dual-rail ship; wider ticks amortize descriptor issue but are
        only legal when the landing table gives each tick a contiguous
        slot run (``kv_ship.coalesced_landing_ok``).
    ``rail``
        kv_ship scale-plane placement: ``"paired"`` — own semaphores
        (legal); ``"shared"`` — the payload's semaphores (torn-scale
        hazard, SL009); ``"drop"`` — no scale rail at all (landed
        pages stay raw quantized bytes, SL009).
    ``epilogue``
        GEMM-RS int8-MXU producer epilogue: ``"accumulator"`` folds
        the wire quantization off the s32 accumulator (today's fused
        epilogue); ``"readback"`` writes the dequantized partial tile
        and re-quantizes it through the generic wire pipeline — an
        extra VMEM pass the pricer charges per reduce hop.
    ``demote``
        Partial-tile policy when the int8-MXU layout does not divide
        the local geometry: ``"auto"`` demotes to the eager int8 wire
        (today's behavior), ``"strict"`` refuses to build instead.
    ``tree_pack``
        Ragged-attention tree-verify packing: 0 gates the all-CAUSAL
        row mix; > 0 makes the gate geometry carry a branchy TREE
        topology row of that many packed positions, so the oracle
        re-checks the schedule against the ancestor-bitmask mask path
        the speculative engine's tree rows actually execute.
    ``prefix_run_len``
        SHARED_PREFIX run length (pages) the engine's batch dedup is
        expected to alias — a pricing term (deduped page reads), not a
        kernel-build knob: the kernel masks SHARED_PREFIX rows as
        causal either way.
    """

    #: schedule-kind tag (class attr — see :class:`RingSchedule`)
    kind = "grid"

    block_q: int = 0
    n_bufs: int = 2
    pack_rows: int = 8
    coalesce: int = 1
    rail: str = "paired"
    epilogue: str = "accumulator"
    demote: str = "auto"
    tree_pack: int = 0
    prefix_run_len: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "GridSchedule":
        return cls(**{k: d[k] for k in _GRID_FIELDS if k in d})

    def is_default(self) -> bool:
        return self == GRID_DEFAULT


#: the canonical grid default — byte-identical to the baked-in kernels
GRID_DEFAULT = GridSchedule()


# ------------------------------------------------------------ freedom sets
#
# What each searchable family may vary. Values outside these sets are
# MUTATIONS — enumerable on request so the oracle has something to
# reject, never timed, never cached.

_FREEDOMS: dict = {
    "ag_gemm.fused": dict(
        direction=("fwd", "rev"),
        dequant=("eager", "epilogue"),
    ),
    "gemm_rs.fused": dict(
        scale_rail=("own",),          # rail is load-bearing; depth pinned
    ),
    "allgather.ring_1d": dict(
        direction=("fwd", "rev"),
    ),
    "allgather.ring_bidir": dict(
        split8=(2, 3, 4, 5, 6),
    ),
    "reduce_scatter.stream": dict(
        depth=(2, 3),
    ),
    # training: the ring-attention KV rotation may traverse either way;
    # the gradient ring's depth generalizes like the streaming RS it is
    # built on (kernels/cp_ring.py)
    "cp.ring_attention": dict(
        direction=("fwd", "rev"),
    ),
    "grad_ring.stream_int8w": dict(
        depth=(2, 3),
    ),
}

#: one-field illegal mutations per family — the oracle's test diet
_MUTATIONS: dict = {
    "ag_gemm.fused": (dict(chunk_order="skip_last"),
                      dict(scale_rail="payload")),
    "gemm_rs.fused": (dict(scale_rail="payload"),),
    "allgather.ring_1d": (dict(chunk_order="skip_last"),
                          dict(scale_rail="payload")),
    "allgather.ring_bidir": (),
    "reduce_scatter.stream": (dict(scale_rail="payload"),),
    # skip_last drops one KV block — one attention step never sees one
    # sequence block; only the gather contract can tell (SL008)
    "cp.ring_attention": (dict(chunk_order="skip_last"),),
    # scales on the payload's semaphore — the torn-scale hazard (SL009)
    "grad_ring.stream_int8w": (dict(scale_rail="payload"),),
}

#: grid-family freedom sets — same proposer/oracle split as the rings.
#: block_q=0 is the auto ladder; 8/16 pin the block. The (block_q=8,
#: pack_rows=16) combo is a LEGITIMATE oracle rejection (a 16-row pack
#: with an 8-row block leaves coverage holes — SL008): the freedom
#: product is allowed to contain illegal corners; the gate prunes them.
_GRID_FREEDOMS: dict = {
    "flash_decode.ragged_paged": dict(
        block_q=(0, 8, 16),
        n_bufs=(2, 3),
        pack_rows=(8, 16),
        tree_pack=(0, 8),
    ),
    "kv_ship.pages": dict(
        coalesce=(1, 2),
    ),
    "gemm_rs.mx_epilogue": dict(
        epilogue=("accumulator", "readback"),
    ),
}

#: deliberately illegal grid mutations — the oracle's test diet
_GRID_MUTATIONS: dict = {
    # block wider than the parking zone: the out-DMA runs past the
    # packed span's tail pad — OOB events → SL008
    "flash_decode.ragged_paged": (dict(block_q=32),),
    # a coalesced tick that ships no scale rail (raw-bytes install),
    # and the scale rail signalling the payload's semaphores — SL009
    "kv_ship.pages": (dict(coalesce=2, rail="drop"),
                      dict(rail="shared")),
    # the producer's wire scales on the payload semaphore — SL009
    "gemm_rs.mx_epilogue": (dict(rail="shared"),),
}


def searchable_families() -> tuple:
    return tuple(sorted(set(_FREEDOMS) | set(_GRID_FREEDOMS)))


def grid_families() -> tuple:
    return tuple(sorted(_GRID_FREEDOMS))


def is_grid_family(family: str) -> bool:
    return family in _GRID_FREEDOMS


def default_for(family: str):
    """The family's canonical default schedule value."""
    return GRID_DEFAULT if family in _GRID_FREEDOMS else DEFAULT


def enumerate_schedules(family: str, *, include_mutations: bool = False):
    """All candidate schedules in ``family``'s freedom set (the default
    always first), optionally extended with the family's deliberately
    illegal one-field mutations. Dispatches on the family kind: grid
    families enumerate :class:`GridSchedule` values off
    :data:`GRID_DEFAULT`, ring families :class:`RingSchedule` values."""
    grid = family in _GRID_FREEDOMS
    free = _GRID_FREEDOMS[family] if grid else _FREEDOMS[family]
    base = GRID_DEFAULT if grid else DEFAULT
    muts = _GRID_MUTATIONS if grid else _MUTATIONS
    keys = sorted(free)
    seen, out = set(), []
    for combo in itertools.product(*(free[k] for k in keys)):
        s = replace(base, **dict(zip(keys, combo)))
        if s not in seen:
            seen.add(s)
            out.append(s)
    out.sort(key=lambda s: not s.is_default())   # default first
    if include_mutations:
        for m in muts[family]:
            s = replace(base, **m)
            if s not in seen:
                seen.add(s)
                out.append(s)
    return out


def mutate(schedule, family: str):
    """The family's illegal one-field mutations of ``schedule`` — what
    the search feeds the oracle to prove the gate is alive."""
    muts = _GRID_MUTATIONS if family in _GRID_FREEDOMS else _MUTATIONS
    return [replace(schedule, **m) for m in muts[family]]


# ------------------------------------------------------------ legality gate
#
# Each searchable family maps to a gate builder: construct the REAL
# kernel (over an AbstractMesh, nothing executes) with the candidate
# schedule threaded through, read the captured LaunchSpec back, and
# replay it through shmemlint + the Mosaic pre-flight.

_TOKENS = itertools.count()


def _gate_ag_gemm(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ag_gemm import _build_fused

    import jax.numpy as jnp

    if schedule.dequant == "epilogue":
        wire, launch = "int8-mxu", "ag_gemm_fused_int8mxw"
        shapes = [((16, 128), _I8), ((1, 128), _F32),
                  ((128, 64), _I8), ((1, 64), _F32)]
        contract = DeliveryContract(kind="gather", dst="agq_hbm",
                                    own_absent_ok=True)
    else:
        # int8 eager wire: portable across Mosaic versions (fp8 in-kernel
        # casts trip MC001 on toolchains without f8 extensions — the gate
        # must test the schedule, not the toolchain)
        wire, launch = "int8", "ag_gemm_fused_int8w"
        shapes = [((16, 128), _F32), ((16, 128), _I8),
                  ((1, 128), _F32), ((128, 64), _F32)]
        contract = DeliveryContract(kind="gather", dst="ag_hbm")
    _build_fused(
        mesh, "x", (), (16 * n, 128), (128, 64 * n),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 5,
        ("schedule-gate", next(_TOKENS)), return_gathered=True, wire=wire,
        schedule=schedule,
    )
    return launch, (lambda _n: shapes), contract, "ag_gemm"


def _gate_gemm_rs(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    import jax.numpy as jnp

    _build_fused(
        mesh, "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6,
        ("schedule-gate", next(_TOKENS)), wire="int8", schedule=schedule,
    )
    shapes = [((16 * n, 128), _F32), ((128, 64), _F32)]
    return ("gemm_rs_fused_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "gemm_rs")


def _gate_ag_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    import jax.numpy as jnp

    _build_all_gather(
        mesh, "x", AllGatherMethod.RING_1D, (8 * n, 2048),
        jnp.dtype(jnp.float32), 2, ("schedule-gate", next(_TOKENS)),
        wire="int8", schedule=schedule,
    )
    shapes = [((8, 2048), _F32), ((8, 2048), _I8), ((8, 128), _F32)]
    return ("ag_ring_1d_int8w", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="out_ref"), "allgather")


def _gate_ag_bidir(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    import jax.numpy as jnp

    _build_all_gather(
        mesh, "x", AllGatherMethod.RING_BIDIR, (8 * n, 1024),
        jnp.dtype(jnp.float32), 2, ("schedule-gate", next(_TOKENS)),
        schedule=schedule,
    )
    shapes = [((8, 1024), _F32)]
    return ("ag_ring_bidir", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="out_ref"), "allgather")


def _gate_rs_stream(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream_w,
    )

    import jax.numpy as jnp

    _build_rs_stream_w(
        mesh, "x", 8 * n, 2048, jnp.dtype(jnp.float32), False, 3,
        ("schedule-gate", next(_TOKENS)), "int8", schedule=schedule,
    )
    shapes = [((8 * n, 2048), _F32)]
    return ("rs_ring_stream_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "reduce_scatter")


def _gate_cp_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.cp_ring import build_kv_rotate_lint

    build_kv_rotate_lint(
        mesh, n, token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    shapes = [((8, 128), _F32)]
    return ("cp_ring_kv_rotate", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="ag_ref",
                             own_absent_ok=True), "cp_ring")


def _gate_grad_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.cp_ring import build_grad_ring_lint

    build_grad_ring_lint(
        mesh, n, token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    shapes = [((8 * n, 2048), _F32)]
    return ("grad_ring_stream_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "grad_ring")


def _gate_ragged_grid(schedule, n, mesh):
    """The ragged paged-attention grid gate: build through the real
    ``_build_ragged`` at the schedule-derived lint geometry (the packed
    span tracks ``pack_rows``/``block_q`` so zero-slack coverage is
    preserved for every LEGAL combo, and an over-wide block overruns
    the parking zone — SL008 via the evaluator's OOB events). A LOCAL
    family: the mesh only sets how many identical ranks replay."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        build_grid_lint_kernel,
    )

    del mesh
    gm = build_grid_lint_kernel(
        token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    pool = (gm["npages"], gm["hkv"], gm["page"], gm["d"])
    shapes = [
        ((gm["r"], gm["pps"]), _I32),                   # block table
        ((gm["r"],), _I32),                             # kv_lens
        ((gm["r"],), _I32),                             # q_lens
        ((gm["r"],), _I32),                             # q_starts
        ((gm["r"], 2 + 2 * gm["topo_w"]), _I32),        # topologies
        ((gm["hkv"], gm["t"] * gm["g"], gm["d"]), _F32),  # packed q
        (pool, _I8),                                    # k pool
        (pool, _I8),                                    # v pool
        ((gm["npages"], gm["hkv"], 1, gm["page"]), _F32),  # k scales
        ((gm["npages"], gm["hkv"], 1, gm["page"]), _F32),  # v scales
    ]
    init = {
        0: np.arange(gm["r"] * gm["pps"], dtype=np.int32).reshape(
            gm["r"], gm["pps"]
        ),
        1: np.asarray(gm["kv_lens"], np.int32),
        2: np.asarray(gm["q_lens"], np.int32),
        3: np.asarray(gm["q_starts"], np.int32),
        4: np.asarray(gm["topo"], np.int32),
    }
    return ("ragged_paged_attention_q8", (lambda _n: shapes),
            DeliveryContract(
                kind="local", dst=10,
                topo={"ref": 4, "kv_lens": 1, "q_lens": 2,
                      "width": gm["topo_w"]},
            ), "ragged_paged", init)


def _gate_kv_ship_grid(schedule, n, mesh):
    """The kv_ship grid gate: the real page-ship builder with the
    candidate's coalescing width and rail placement, against the
    registry's pairwise permute contract — the landing table is the
    coalesce-legal permutation (contiguous slot run per tick)."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.kv_ship import (
        KV_SHIP_GEOM,
        build_lint_kernel,
        coalesced_landing_table,
    )

    g = KV_SHIP_GEOM
    build_lint_kernel(
        mesh, n, token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    rows = g["pages"] * g["rows"]
    shapes = [
        ((g["pages"],), _I32),               # landing page table (SMEM)
        ((rows, g["cols"]), _I8),            # staged page payload
        ((rows, 128), _F32),                 # per-row scale planes
    ]
    init = {0: np.asarray(
        coalesced_landing_table(g["pages"], int(schedule.coalesce)),
        np.int32,
    )}
    elems = g["pages"] * g["rows"] * g["cols"]
    return ("kv_ship_pages", (lambda _n: shapes),
            DeliveryContract(
                kind="permute", dst="dst_q",
                payload_per_src=lambda _n: elems,
                src_only=lambda rank, nn: {(rank - nn // 2) % nn},
            ), "kv_ship", init)


def _gate_gemm_rs_mx(schedule, n, mesh):
    """The GEMM-RS int8-MXU epilogue gate: the real fused builder on
    the MXU wire with the candidate's epilogue placement threaded
    through — accumulator-fold and readback-requantize both launch
    under the same name, so one gate covers the whole freedom axis."""
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused
    from triton_distributed_tpu.lang import wire as wirelib

    import jax.numpy as jnp

    _build_fused(
        mesh, "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6,
        ("schedule-gate", next(_TOKENS)), wire="int8-mxu",
        schedule=schedule,
    )
    # per-rank quantized operands: a column-sharded → aq (16n, 128)
    # with one scale row per 16-row chunk; b row-sharded → bq (128, 64)
    shapes = [((16 * n, 128), _I8), ((n, wirelib.SCALE_LANES), _F32),
              ((128, 64), _I8), ((1, 64), _F32)]
    return ("gemm_rs_fused_int8mxw", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "gemm_rs")


_GATES: dict = {
    "ag_gemm.fused": _gate_ag_gemm,
    "gemm_rs.fused": _gate_gemm_rs,
    "allgather.ring_1d": _gate_ag_ring,
    "allgather.ring_bidir": _gate_ag_bidir,
    "reduce_scatter.stream": _gate_rs_stream,
    "cp.ring_attention": _gate_cp_ring,
    "grad_ring.stream_int8w": _gate_grad_ring,
    "flash_decode.ragged_paged": _gate_ragged_grid,
    "kv_ship.pages": _gate_kv_ship_grid,
    "gemm_rs.mx_epilogue": _gate_gemm_rs_mx,
}


def check_schedule(family: str, schedule, n: int = 8,
                   *, mosaic: bool = True):
    """The oracle: build ``family`` with ``schedule`` over an abstract
    ``n``-rank mesh, replay through shmemlint against the family's
    delivery contract, and (when the protocol is clean) Mosaic-preflight
    the trace. Returns the finding list — empty means the candidate may
    be timed/cached; otherwise ``findings[i].rule`` names why not.

    Gates return ``(launch, in_shapes, contract, site)`` — grid gates
    whose replay needs concrete scalar-prefetch values (landing tables,
    block tables, lengths) append a 5th ``init`` element, forwarded to
    the analyzer exactly like the registry families' ``init`` hook."""
    from triton_distributed_tpu.analysis import lint, mosaic_compat
    from triton_distributed_tpu.analysis.findings import has_errors
    from triton_distributed_tpu.lang.launch import captured_launch

    mesh = lint.lint_mesh(n)
    gate = _GATES[family](schedule, n, mesh)
    launch, in_shapes, contract, site = gate[:4]
    init = gate[4] if len(gate) > 4 else None
    spec = captured_launch(launch)
    if spec is None:
        raise RuntimeError(
            f"schedule gate for {family!r}: builder did not construct a "
            f"shmem_call named {launch!r}"
        )
    name = f"{family}[{schedule.to_dict()}]"
    _, findings = lint.analyze_spec(
        spec, in_shapes(n), n, kernel_name=name, site=site,
        contract=contract, init=init,
    )
    if mosaic and not has_errors(findings):
        findings = findings + mosaic_compat.preflight_spec(
            spec, in_shapes(n), n, kernel_name=name, site=site,
        )
    return findings


# ------------------------------------------------------------ perf pricing

#: default pricing shapes per grid family, used when the caller has no
#: observed traffic key (the CI smoke): ragged (r, t, hkv, g, d, page);
#: kv_ship (pages, page, hkv, d, n_layers); gemm_rs (m, k, n_out)
_GRID_SMOKE_SHAPES: dict = {
    "flash_decode.ragged_paged": (8, 128, 2, 4, 128, 16),
    "kv_ship.pages": (16, 16, 2, 128, 4),
    "gemm_rs.mx_epilogue": (2048, 1024, 1024),
}


def price_grid_schedule(family: str, schedule: GridSchedule, *, shape,
                        n: int = 8, wire: str | None = None,
                        spec=None) -> float:
    """Perf-model price (ms) of a grid schedule on a traffic shape key.

    The terms mirror what each knob actually buys: deeper page-walk
    double buffering divides the per-page descriptor-issue stall the
    flash loop cannot hide (``n_bufs - 1`` fetches in flight); an
    explicit ``block_q``/``pack_rows`` pays its tail-pad token traffic;
    kv_ship coalescing divides the per-tick issue count; the readback
    epilogue pays one extra requantize VMEM pass per reduce hop."""
    from triton_distributed_tpu.tune import perf_model as pm

    del wire
    spec = spec or pm.detect_spec()
    shape = tuple(int(x) for x in shape)
    if family == "flash_decode.ragged_paged":
        r, t, hkv, g, d, page = shape[:6]
        kv = [t] * r
        bytes_ms = pm.ragged_page_walk_ms(kv, page, hkv, d, spec=spec,
                                          quant=True, issue_ms=0.0)
        pages = r * max(-(-t // page), 1)
        issue = pm.measured_page_issue_ms()
        ms = bytes_ms + pages * issue / max(1, int(schedule.n_bufs) - 1)
        # a pinned block or a coarser pack pays its tail pad: wasted q
        # rows are read, attended and written back (3 touches, bf16)
        waste = r * g * (max(0, int(schedule.block_q) - 8)
                         + max(0, int(schedule.pack_rows) - 8))
        ms += waste * d * 2 * 3 / (spec.hbm_gbps * 1e9) * 1e3
        # serving traffic keys (engine ``_grid_key``) carry the prefill
        # CHUNK after the geometry: a prefill row packs ``chunk``
        # tokens through ceil(chunk/block_q) q blocks, so the block's
        # tail pad is paid once per prefill row — the term that makes
        # the same geometry at a different chunking a DIFFERENT hot
        # shape, tuned to its own block_q winner
        if len(shape) >= 7 and int(shape[6]) > 0:
            chunk = int(shape[6])
            bq = max(int(schedule.block_q), 8)
            pad = -(-chunk // bq) * bq - chunk
            ms += r * g * pad * d * 2 * 3 / (spec.hbm_gbps * 1e9) * 1e3
        # tree-packed verify rows widen the q block the row occupies
        # (1 + tree_pack positions attend the row's whole prefix) —
        # extra q/out traffic, paid back upstream by accepted tokens
        tp = int(getattr(schedule, "tree_pack", 0))
        if tp:
            ms += r * g * tp * d * 2 * 3 / (spec.hbm_gbps * 1e9) * 1e3
        # a shared-prefix run aliases its page reads across the batch:
        # (r - 1) rows skip prefix_run_len pages of KV traffic
        run = int(getattr(schedule, "prefix_run_len", 0))
        if run and r > 1:
            per_page = page * hkv * d * (1 + 1)     # int8 K + V bytes
            ms -= min(run, max(-(-t // page), 1)) * (r - 1) * per_page \
                / (spec.hbm_gbps * 1e9) * 1e3
        return max(ms, 0.0)
    if family == "kv_ship.pages":
        pages, page, hkv, d, layers = shape[:5]
        ms = pm.kv_ship_ms(pages, page, hkv, d, layers, quant=True,
                           spec=spec)
        ticks = -(-pages // max(1, int(schedule.coalesce)))
        ms += layers * 2 * ticks * pm.measured_page_issue_ms()
        return ms
    if family == "gemm_rs.mx_epilogue":
        m, k, n_out = shape[:3]
        m_local = max(m // n, 1)
        ms = pm.estimate_s8_gemm_ms(m_local, max(k // n, 1), n_out, spec)
        if schedule.epilogue == "readback":
            # the partial tile leaves the accumulator dequantized and is
            # re-quantized through the generic wire pipeline — one extra
            # VMEM pass per reduce hop rides the critical path
            ms += (n - 1) * pm.dequant_pass_ms(m_local, n_out, 2, spec)
        return ms
    raise KeyError(family)


def price_schedule(family: str, schedule, *, rows: int,
                   cols: int, itemsize: int = 4, n: int = 8,
                   wire: str | None = None, spec=None,
                   shape=None) -> float:
    """Perf-model price (ms) of running ``family`` under ``schedule`` on
    an (rows, cols) per-rank ring slab: the hop-critical-path wire term
    plus the dequant-placement term. Legality is NOT checked here — the
    search gates first, prices second. Grid families dispatch to
    :func:`price_grid_schedule` on their traffic shape key (``shape``;
    the family's smoke shape when the caller has none)."""
    from triton_distributed_tpu.tune import perf_model as pm

    if family in _GRID_FREEDOMS:
        return price_grid_schedule(
            family, schedule,
            shape=shape if shape is not None else _GRID_SMOKE_SHAPES[family],
            n=n, wire=wire, spec=spec,
        )
    spec = spec or pm.detect_spec()
    hops = n - 1
    if family == "allgather.ring_bidir":
        # each direction carries its column share the full n-1 hops; the
        # critical path is the heavier direction
        frac = max(schedule.split8, 8 - schedule.split8) / 8.0
        hop_bytes = int(rows * cols * itemsize * frac)
        return pm.hop_critical_path_ms(hops, hop_bytes, spec)
    hop_bytes = pm.ring_wire_bytes(rows, cols, itemsize, wire)
    ms = pm.hop_critical_path_ms(hops, hop_bytes, spec)
    if wire not in (None, "bf16") and schedule.dequant == "eager":
        # one dequant pass per arrival rides the critical path unless
        # the epilogue consumer folds the scale off the accumulator
        ms += hops * pm.dequant_pass_ms(rows, cols, 2, spec)
    return ms


# ------------------------------------------------------------ winner store
#
# Same discipline as the autotuner cache: flock'd read-modify-write,
# atomic replace, validated on load. Keys are
# repr((family, shape, mesh, wire_dtype)).

def _store_path() -> str:
    import pathlib

    # beside the autotuner cache: same env knob, same default dir
    d = pathlib.Path(
        os.environ.get("TDTPU_AUTOTUNE_LOG_DIR", ".autotune_logs")
    )
    d.mkdir(parents=True, exist_ok=True)
    return str(d / "schedules.json")


def schedule_key(family: str, shape, mesh_shape, wire_dtype) -> str:
    return repr((
        str(family),
        tuple(int(x) for x in shape),
        tuple(int(x) for x in mesh_shape),
        None if wire_dtype is None else str(wire_dtype),
    ))


def _read_store(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict):
        return {}
    version = data.get("schema_version", data.get("v"))
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return {}
    if version == 1:
        # pre-grid ring-only store: every entry is a ring schedule
        return {k: dict(e, kind="ring") for k, e in entries.items()
                if isinstance(e, dict)}
    if version != _STORE_VERSION:
        return {}
    return entries


def store_schedule(family: str, shape, mesh_shape, wire_dtype,
                   schedule: RingSchedule, *, price_ms: float | None = None,
                   default_ms: float | None = None) -> str:
    """Persist a searched winner; returns the store key."""
    import fcntl

    key = schedule_key(family, shape, mesh_shape, wire_dtype)
    path = _store_path()
    lock = path + ".lock"
    with open(lock, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        entries = _read_store(path)
        entries[key] = {
            "family": family,
            "kind": getattr(schedule, "kind", "ring"),
            "schedule": schedule.to_dict(),
            "price_ms": price_ms,
            "default_ms": default_ms,
            "ts": time.time(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"schema_version": _STORE_VERSION,
                       "entries": entries}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)
    load_schedule.cache_clear()
    return key


def schedule_from_entry(entry: dict):
    """Rebuild a store entry's schedule value through its ``kind``
    discriminator (ring by default — every v1 entry), or None when the
    entry doesn't validate. bench --lint re-gates through this so grid
    winners replay as :class:`GridSchedule`, not a ring shadow."""
    if not isinstance(entry, dict):
        return None
    sched = entry.get("schedule")
    if not isinstance(sched, dict):
        return None
    cls = GridSchedule if entry.get("kind") == "grid" else RingSchedule
    try:
        return cls.from_dict(sched)
    except TypeError:
        return None


def _load_entry(key: str) -> dict | None:
    entry = _read_store(_store_path()).get(key)
    if not isinstance(entry, dict):
        return None
    if schedule_from_entry(entry) is None:
        return None
    return entry


def stored_entries() -> dict:
    """Snapshot of the persisted store (key → entry) — bench --lint
    walks this to re-gate every cached schedule."""
    return _read_store(_store_path())


@functools.lru_cache(maxsize=256)
def load_schedule(family: str, shape, mesh_shape, wire_dtype):
    """The zero-search-cost resolve hook: the persisted winner for this
    ``(family, shape, mesh, wire_dtype)`` (a :class:`RingSchedule` or
    :class:`GridSchedule` per the entry's kind), or None. Cached per
    process — the second build never touches the disk either."""
    entry = _load_entry(schedule_key(family, shape, mesh_shape, wire_dtype))
    if entry is None or entry.get("family") != family:
        return None
    return schedule_from_entry(entry)


def resolve_schedule(family: str, shape, mesh_shape, wire_dtype,
                     explicit=None):
    """What an op entry should run: the caller's explicit schedule if
    given, else the persisted searched winner, else None (the canonical
    default paths — bit-for-bit today's rings and grid kernels)."""
    if explicit is not None:
        return explicit
    try:
        return load_schedule(
            family,
            tuple(int(x) for x in shape),
            tuple(int(x) for x in mesh_shape),
            None if wire_dtype is None else str(wire_dtype),
        )
    except Exception:
        return None


# ------------------------------------------------------------- CI smoke

def search_smoke(family: str = "ag_gemm.fused", n: int = 8) -> dict:
    """The bounded enumerate → lint-reject → pick loop ci/fast.sh runs:
    every legal candidate gates clean, every mutation is rejected with a
    stable rule ID, and the pick is the cheapest legal candidate."""
    legal, rejected = [], []
    for s in enumerate_schedules(family, include_mutations=True):
        findings = check_schedule(family, s, n)
        if findings:
            rejected.append((s, sorted({f.rule for f in findings})))
        else:
            legal.append(s)
    priced = sorted(
        legal,
        key=lambda s: price_schedule(family, s, rows=128, cols=2048,
                                     n=n, wire="int8"),
    )
    return {
        "family": family,
        "legal": len(legal),
        "rejected": [(s.to_dict(), rules) for s, rules in rejected],
        "pick": priced[0].to_dict() if priced else None,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m triton_distributed_tpu.tune.schedule",
        description="schedule-space smoke: enumerate ring/grid kernel "
        "schedules, reject illegal mutations through shmemlint, pick "
        "the cheapest legal candidate",
    )
    ap.add_argument("--family", default="ag_gemm.fused",
                    choices=sorted(_GATES))
    ap.add_argument("--mesh", type=int, default=8)
    args = ap.parse_args(argv)

    out = search_smoke(args.family, args.mesh)
    print(json.dumps(out))
    if not out["rejected"]:
        print("schedule smoke: no mutation was rejected — the oracle "
              "is not gating", flush=True)
        return 2
    if out["pick"] is None:
        print("schedule smoke: no legal candidate survived", flush=True)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
