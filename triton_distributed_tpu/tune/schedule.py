"""Ring schedules as data — the schedule IR, its enumerator/mutator,
the shmemlint legality oracle, and the persisted winner store.

Every fused engine used to hand-pick exactly one ring schedule
(``kernels/ring.py``: unidirectional forward/reduce rings, fixed chunk
order, fixed double-buffer depth 2, one rail assignment). This module
makes the choice a VALUE:

* :class:`RingSchedule` — per-hop chunk order, traversal direction,
  bidirectional split ratio, double-buffer depth, payload/scale rail
  assignment and eager-vs-epilogue dequant placement. The rings in
  ``kernels/ring.py`` (and the inline bidirectional AG) *execute* a
  schedule; :data:`DEFAULT` reproduces today's behavior byte-
  identically (test-pinned).
* :func:`enumerate_schedules` / :func:`mutate` — the candidate
  generator over each family's declared freedom set. Mutations include
  deliberately ILLEGAL values (a skipped hop, a scale rail on the
  payload's semaphore): the generator proposes, the oracle disposes.
* :func:`check_schedule` — the legality gate: every candidate is built
  through the real kernel builder over an abstract mesh, abstractly
  replayed through shmemlint (SL001–SL011 against the family's declared
  ``DeliveryContract``) and Mosaic-preflighted (MC001–MC005). A
  candidate may be timed or cached ONLY with zero findings; rejections
  carry their rule IDs.
* :func:`store_schedule` / :func:`load_schedule` — the flock'd winner
  store keyed by ``(family, shape, mesh, wire_dtype)``. Resolve paths
  load with zero search cost; only the autotuner search mode
  (``tune.autotuner.search_ring_schedule``) ever writes.

No devices are required anywhere here: the gate runs on an
``AbstractMesh`` exactly like ``analysis.lint``.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import time
from dataclasses import asdict, dataclass, replace

import numpy as np

_F32 = np.dtype(np.float32)
_I8 = np.dtype(np.int8)

#: schema version of the persisted schedule store
_STORE_VERSION = 1

#: fields a schedule serializes (stable order for the store)
_FIELDS = ("chunk_order", "direction", "split8", "depth", "scale_rail",
           "dequant")


@dataclass(frozen=True)
class RingSchedule:
    """One executable ring schedule.

    ``chunk_order``
        ``"ring"`` — every hop of the standard ring traversal;
        ``"skip_last"`` — the final hop dropped entirely (start, wait
        AND consume), a protocol-clean mutation only the delivery
        contract can reject (SL008).
    ``direction``
        ``"fwd"`` (chunks flow to the right neighbor) or ``"rev"``
        (leftward; the consumed source walks ``me+s`` instead of
        ``me-s``) — both legal, identical on the perf model.
    ``split8``
        Bidirectional-AG column split in eighths: the clockwise ring
        carries ``split8/8`` of the columns, the counter-clockwise ring
        the rest. 4 is today's even ``k // 2``.
    ``depth``
        Reduce-ring buffer depth (work/recv slab count and DMA-semaphore
        lanes). 2 is today's double buffer; 3 adds one in-flight hop of
        slack against a slow folder.
    ``scale_rail``
        ``"own"`` — the quantized wire's scale planes ride their own
        DMA semaphores (legal); ``"payload"`` — scales signal the
        payload's recv semaphore, so a payload wait can be released by
        a scale arrival while the 1-byte slab is still in flight.
        Credits balance; SL009 is the only thing that can see it.
    ``dequant``
        ``"eager"`` — each wire arrival is dequantized into the bf16
        workspace before the MXU consumes it; ``"epilogue"`` — the MXU
        consumes the quantized slab directly and folds the scale in its
        accumulator epilogue (legal only for int8 wires with an
        s8×s8-capable consumer; resolve maps it to the ``int8-mxu``
        kernel twin).
    """

    chunk_order: str = "ring"
    direction: str = "fwd"
    split8: int = 4
    depth: int = 2
    scale_rail: str = "own"
    dequant: str = "eager"

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RingSchedule":
        return cls(**{k: d[k] for k in _FIELDS if k in d})

    def is_default(self) -> bool:
        return self == DEFAULT


#: the canonical default — byte-identical to the pre-schedule rings
DEFAULT = RingSchedule()


# ------------------------------------------------------------ freedom sets
#
# What each searchable family may vary. Values outside these sets are
# MUTATIONS — enumerable on request so the oracle has something to
# reject, never timed, never cached.

_FREEDOMS: dict = {
    "ag_gemm.fused": dict(
        direction=("fwd", "rev"),
        dequant=("eager", "epilogue"),
    ),
    "gemm_rs.fused": dict(
        scale_rail=("own",),          # rail is load-bearing; depth pinned
    ),
    "allgather.ring_1d": dict(
        direction=("fwd", "rev"),
    ),
    "allgather.ring_bidir": dict(
        split8=(2, 3, 4, 5, 6),
    ),
    "reduce_scatter.stream": dict(
        depth=(2, 3),
    ),
    # training: the ring-attention KV rotation may traverse either way;
    # the gradient ring's depth generalizes like the streaming RS it is
    # built on (kernels/cp_ring.py)
    "cp.ring_attention": dict(
        direction=("fwd", "rev"),
    ),
    "grad_ring.stream_int8w": dict(
        depth=(2, 3),
    ),
}

#: one-field illegal mutations per family — the oracle's test diet
_MUTATIONS: dict = {
    "ag_gemm.fused": (dict(chunk_order="skip_last"),
                      dict(scale_rail="payload")),
    "gemm_rs.fused": (dict(scale_rail="payload"),),
    "allgather.ring_1d": (dict(chunk_order="skip_last"),
                          dict(scale_rail="payload")),
    "allgather.ring_bidir": (),
    "reduce_scatter.stream": (dict(scale_rail="payload"),),
    # skip_last drops one KV block — one attention step never sees one
    # sequence block; only the gather contract can tell (SL008)
    "cp.ring_attention": (dict(chunk_order="skip_last"),),
    # scales on the payload's semaphore — the torn-scale hazard (SL009)
    "grad_ring.stream_int8w": (dict(scale_rail="payload"),),
}


def searchable_families() -> tuple:
    return tuple(sorted(_FREEDOMS))


def enumerate_schedules(family: str, *, include_mutations: bool = False):
    """All candidate schedules in ``family``'s freedom set (the default
    always first), optionally extended with the family's deliberately
    illegal one-field mutations."""
    free = _FREEDOMS[family]
    keys = sorted(free)
    seen, out = set(), []
    for combo in itertools.product(*(free[k] for k in keys)):
        s = replace(DEFAULT, **dict(zip(keys, combo)))
        if s not in seen:
            seen.add(s)
            out.append(s)
    out.sort(key=lambda s: not s.is_default())   # default first
    if include_mutations:
        for m in _MUTATIONS[family]:
            s = replace(DEFAULT, **m)
            if s not in seen:
                seen.add(s)
                out.append(s)
    return out


def mutate(schedule: RingSchedule, family: str):
    """The family's illegal one-field mutations of ``schedule`` — what
    the search feeds the oracle to prove the gate is alive."""
    return [replace(schedule, **m) for m in _MUTATIONS[family]]


# ------------------------------------------------------------ legality gate
#
# Each searchable family maps to a gate builder: construct the REAL
# kernel (over an AbstractMesh, nothing executes) with the candidate
# schedule threaded through, read the captured LaunchSpec back, and
# replay it through shmemlint + the Mosaic pre-flight.

_TOKENS = itertools.count()


def _gate_ag_gemm(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.ag_gemm import _build_fused

    import jax.numpy as jnp

    if schedule.dequant == "epilogue":
        wire, launch = "int8-mxu", "ag_gemm_fused_int8mxw"
        shapes = [((16, 128), _I8), ((1, 128), _F32),
                  ((128, 64), _I8), ((1, 64), _F32)]
        contract = DeliveryContract(kind="gather", dst="agq_hbm",
                                    own_absent_ok=True)
    else:
        # int8 eager wire: portable across Mosaic versions (fp8 in-kernel
        # casts trip MC001 on toolchains without f8 extensions — the gate
        # must test the schedule, not the toolchain)
        wire, launch = "int8", "ag_gemm_fused_int8w"
        shapes = [((16, 128), _F32), ((16, 128), _I8),
                  ((1, 128), _F32), ((128, 64), _F32)]
        contract = DeliveryContract(kind="gather", dst="ag_hbm")
    _build_fused(
        mesh, "x", (), (16 * n, 128), (128, 64 * n),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 5,
        ("schedule-gate", next(_TOKENS)), return_gathered=True, wire=wire,
        schedule=schedule,
    )
    return launch, (lambda _n: shapes), contract, "ag_gemm"


def _gate_gemm_rs(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    import jax.numpy as jnp

    _build_fused(
        mesh, "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6,
        ("schedule-gate", next(_TOKENS)), wire="int8", schedule=schedule,
    )
    shapes = [((16 * n, 128), _F32), ((128, 64), _F32)]
    return ("gemm_rs_fused_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "gemm_rs")


def _gate_ag_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    import jax.numpy as jnp

    _build_all_gather(
        mesh, "x", AllGatherMethod.RING_1D, (8 * n, 2048),
        jnp.dtype(jnp.float32), 2, ("schedule-gate", next(_TOKENS)),
        wire="int8", schedule=schedule,
    )
    shapes = [((8, 2048), _F32), ((8, 2048), _I8), ((8, 128), _F32)]
    return ("ag_ring_1d_int8w", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="out_ref"), "allgather")


def _gate_ag_bidir(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    import jax.numpy as jnp

    _build_all_gather(
        mesh, "x", AllGatherMethod.RING_BIDIR, (8 * n, 1024),
        jnp.dtype(jnp.float32), 2, ("schedule-gate", next(_TOKENS)),
        schedule=schedule,
    )
    shapes = [((8, 1024), _F32)]
    return ("ag_ring_bidir", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="out_ref"), "allgather")


def _gate_rs_stream(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream_w,
    )

    import jax.numpy as jnp

    _build_rs_stream_w(
        mesh, "x", 8 * n, 2048, jnp.dtype(jnp.float32), False, 3,
        ("schedule-gate", next(_TOKENS)), "int8", schedule=schedule,
    )
    shapes = [((8 * n, 2048), _F32)]
    return ("rs_ring_stream_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "reduce_scatter")


def _gate_cp_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.cp_ring import build_kv_rotate_lint

    build_kv_rotate_lint(
        mesh, n, token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    shapes = [((8, 128), _F32)]
    return ("cp_ring_kv_rotate", (lambda _n: shapes),
            DeliveryContract(kind="gather", dst="ag_ref",
                             own_absent_ok=True), "cp_ring")


def _gate_grad_ring(schedule, n, mesh):
    from triton_distributed_tpu.analysis.dataflow import DeliveryContract
    from triton_distributed_tpu.kernels.cp_ring import build_grad_ring_lint

    build_grad_ring_lint(
        mesh, n, token=("schedule-gate", next(_TOKENS)), schedule=schedule,
    )
    shapes = [((8 * n, 2048), _F32)]
    return ("grad_ring_stream_int8w", (lambda _n: shapes),
            DeliveryContract(kind="reduce", dst="out_hbm"), "grad_ring")


_GATES: dict = {
    "ag_gemm.fused": _gate_ag_gemm,
    "gemm_rs.fused": _gate_gemm_rs,
    "allgather.ring_1d": _gate_ag_ring,
    "allgather.ring_bidir": _gate_ag_bidir,
    "reduce_scatter.stream": _gate_rs_stream,
    "cp.ring_attention": _gate_cp_ring,
    "grad_ring.stream_int8w": _gate_grad_ring,
}


def check_schedule(family: str, schedule: RingSchedule, n: int = 8,
                   *, mosaic: bool = True):
    """The oracle: build ``family`` with ``schedule`` over an abstract
    ``n``-rank mesh, replay through shmemlint against the family's
    delivery contract, and (when the protocol is clean) Mosaic-preflight
    the trace. Returns the finding list — empty means the candidate may
    be timed/cached; otherwise ``findings[i].rule`` names why not."""
    from triton_distributed_tpu.analysis import lint, mosaic_compat
    from triton_distributed_tpu.analysis.findings import has_errors
    from triton_distributed_tpu.lang.launch import captured_launch

    mesh = lint.lint_mesh(n)
    launch, in_shapes, contract, site = _GATES[family](schedule, n, mesh)
    spec = captured_launch(launch)
    if spec is None:
        raise RuntimeError(
            f"schedule gate for {family!r}: builder did not construct a "
            f"shmem_call named {launch!r}"
        )
    name = f"{family}[{schedule.to_dict()}]"
    _, findings = lint.analyze_spec(
        spec, in_shapes(n), n, kernel_name=name, site=site,
        contract=contract,
    )
    if mosaic and not has_errors(findings):
        findings = findings + mosaic_compat.preflight_spec(
            spec, in_shapes(n), n, kernel_name=name, site=site,
        )
    return findings


# ------------------------------------------------------------ perf pricing

def price_schedule(family: str, schedule: RingSchedule, *, rows: int,
                   cols: int, itemsize: int = 4, n: int = 8,
                   wire: str | None = None, spec=None) -> float:
    """Perf-model price (ms) of running ``family`` under ``schedule`` on
    an (rows, cols) per-rank ring slab: the hop-critical-path wire term
    plus the dequant-placement term. Legality is NOT checked here — the
    search gates first, prices second."""
    from triton_distributed_tpu.tune import perf_model as pm

    spec = spec or pm.detect_spec()
    hops = n - 1
    if family == "allgather.ring_bidir":
        # each direction carries its column share the full n-1 hops; the
        # critical path is the heavier direction
        frac = max(schedule.split8, 8 - schedule.split8) / 8.0
        hop_bytes = int(rows * cols * itemsize * frac)
        return pm.hop_critical_path_ms(hops, hop_bytes, spec)
    hop_bytes = pm.ring_wire_bytes(rows, cols, itemsize, wire)
    ms = pm.hop_critical_path_ms(hops, hop_bytes, spec)
    if wire not in (None, "bf16") and schedule.dequant == "eager":
        # one dequant pass per arrival rides the critical path unless
        # the epilogue consumer folds the scale off the accumulator
        ms += hops * pm.dequant_pass_ms(rows, cols, 2, spec)
    return ms


# ------------------------------------------------------------ winner store
#
# Same discipline as the autotuner cache: flock'd read-modify-write,
# atomic replace, validated on load. Keys are
# repr((family, shape, mesh, wire_dtype)).

def _store_path() -> str:
    import pathlib

    # beside the autotuner cache: same env knob, same default dir
    d = pathlib.Path(
        os.environ.get("TDTPU_AUTOTUNE_LOG_DIR", ".autotune_logs")
    )
    d.mkdir(parents=True, exist_ok=True)
    return str(d / "schedules.json")


def schedule_key(family: str, shape, mesh_shape, wire_dtype) -> str:
    return repr((
        str(family),
        tuple(int(x) for x in shape),
        tuple(int(x) for x in mesh_shape),
        None if wire_dtype is None else str(wire_dtype),
    ))


def _read_store(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("v") != _STORE_VERSION:
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def store_schedule(family: str, shape, mesh_shape, wire_dtype,
                   schedule: RingSchedule, *, price_ms: float | None = None,
                   default_ms: float | None = None) -> str:
    """Persist a searched winner; returns the store key."""
    import fcntl

    key = schedule_key(family, shape, mesh_shape, wire_dtype)
    path = _store_path()
    lock = path + ".lock"
    with open(lock, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        entries = _read_store(path)
        entries[key] = {
            "family": family,
            "schedule": schedule.to_dict(),
            "price_ms": price_ms,
            "default_ms": default_ms,
            "ts": time.time(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"v": _STORE_VERSION, "entries": entries}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)
    load_schedule.cache_clear()
    return key


def _load_entry(key: str) -> dict | None:
    entry = _read_store(_store_path()).get(key)
    if not isinstance(entry, dict):
        return None
    sched = entry.get("schedule")
    if not isinstance(sched, dict):
        return None
    try:
        RingSchedule.from_dict(sched)
    except TypeError:
        return None
    return entry


def stored_entries() -> dict:
    """Snapshot of the persisted store (key → entry) — bench --lint
    walks this to re-gate every cached schedule."""
    return _read_store(_store_path())


@functools.lru_cache(maxsize=256)
def load_schedule(family: str, shape, mesh_shape,
                  wire_dtype) -> RingSchedule | None:
    """The zero-search-cost resolve hook: the persisted winner for this
    ``(family, shape, mesh, wire_dtype)``, or None. Cached per process —
    the second build never touches the disk either."""
    entry = _load_entry(schedule_key(family, shape, mesh_shape, wire_dtype))
    if entry is None or entry.get("family") != family:
        return None
    return RingSchedule.from_dict(entry["schedule"])


def resolve_schedule(family: str, shape, mesh_shape, wire_dtype,
                     explicit: RingSchedule | None = None):
    """What an op entry should run: the caller's explicit schedule if
    given, else the persisted searched winner, else None (the canonical
    default paths, bit-for-bit today's rings)."""
    if explicit is not None:
        return explicit
    try:
        return load_schedule(
            family,
            tuple(int(x) for x in shape),
            tuple(int(x) for x in mesh_shape),
            None if wire_dtype is None else str(wire_dtype),
        )
    except Exception:
        return None


# ------------------------------------------------------------- CI smoke

def search_smoke(family: str = "ag_gemm.fused", n: int = 8) -> dict:
    """The bounded enumerate → lint-reject → pick loop ci/fast.sh runs:
    every legal candidate gates clean, every mutation is rejected with a
    stable rule ID, and the pick is the cheapest legal candidate."""
    legal, rejected = [], []
    for s in enumerate_schedules(family, include_mutations=True):
        findings = check_schedule(family, s, n)
        if findings:
            rejected.append((s, sorted({f.rule for f in findings})))
        else:
            legal.append(s)
    priced = sorted(
        legal,
        key=lambda s: price_schedule(family, s, rows=128, cols=2048,
                                     n=n, wire="int8"),
    )
    return {
        "family": family,
        "legal": len(legal),
        "rejected": [(s.to_dict(), rules) for s, rules in rejected],
        "pick": priced[0].to_dict() if priced else None,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m triton_distributed_tpu.tune.schedule",
        description="schedule-space smoke: enumerate ring schedules, "
        "reject illegal mutations through shmemlint, pick the cheapest "
        "legal candidate",
    )
    ap.add_argument("--family", default="ag_gemm.fused",
                    choices=sorted(_GATES))
    ap.add_argument("--mesh", type=int, default=8)
    args = ap.parse_args(argv)

    out = search_smoke(args.family, args.mesh)
    print(json.dumps(out))
    if not out["rejected"]:
        print("schedule smoke: no mutation was rejected — the oracle "
              "is not gating", flush=True)
        return 2
    if out["pick"] is None:
        print("schedule smoke: no legal candidate survived", flush=True)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
