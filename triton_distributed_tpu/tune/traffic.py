"""Traffic-driven grid-schedule retuning (closes the serving loop).

A serving run leaves an :class:`EngineStats` behind whose
``shape_ledger`` maps each grid-schedule traffic key
``(slots, t_pad, hkv, g, d, page, chunk, spec_k, spec_tree)`` to the
step time spent in it. ``chunk`` is the engine's prefill chunk: the
same geometry re-chunked packs a different q-row histogram, so it
re-searches under its own key and the pricer's chunk tail-pad term
picks the block_q that fits the chunking. The trailing pair is the
engine's speculation signature (``(0, 0)`` for a plain engine): a
schedule searched for 1-token decode rows is the wrong answer for
draft-k verify rows or tree-packed rows, so hot SPECULATIVE shapes
re-search under their own key (the pricer's ``tree_pack`` term sees
the wider rows). This
module turns that ledger into persisted schedule winners: rank the hot
keys, run :func:`search_grid_schedule` for each (oracle-gated,
perf-model priced), persist the winners in the flock'd store — and the
NEXT engine build resolves them through
``resolve_schedule("flash_decode.ragged_paged", key, ...)`` without
paying any search cost on the serving path.

The pass is deliberately OFF the hot path: run it synchronously after
a serving run (:func:`retune_hot_shapes`) or fire-and-forget it on a
background thread while the process drains
(:func:`background_retune` — join the returned thread to collect the
reports). ``dryrun=True`` (the default) skips hardware timing and
keeps the whole pass perf-model-only, which is exactly what the bench
and tests want.
"""

from __future__ import annotations

import threading
import traceback

RAGGED_FAMILY = "flash_decode.ragged_paged"


def retune_hot_shapes(stats, *, mesh_shape, wire=None, top: int = 4,
                      dryrun: bool = True, force: bool = False,
                      time_fn=None, family: str = RAGGED_FAMILY) -> list:
    """Search + persist grid schedules for the ledger's hot shape keys.

    ``stats``: an :class:`EngineStats` (anything with
    ``hot_shape_keys(top)``); ``mesh_shape``: the TP mesh the engine
    ran on (e.g. ``(model.tp,)``); ``wire``: the KV wire dtype key
    (``"int8"`` under kv_quant, else None) — together these reproduce
    the exact store key the next engine build resolves. Returns one
    search report per hot key (``cached=True`` entries cost nothing).
    A key whose search fails (an oracle bug is LOUD by design) is
    reported as ``{"key": ..., "error": ...}`` rather than aborting
    the remaining keys.
    """
    from triton_distributed_tpu.tune.autotuner import search_grid_schedule

    reports = []
    for key in stats.hot_shape_keys(top=top):
        try:
            rep = search_grid_schedule(
                family, shape=key, mesh_shape=mesh_shape, wire=wire,
                dryrun=dryrun, force=force, time_fn=time_fn,
            )
        except Exception as e:             # noqa: BLE001 — report, keep going
            traceback.print_exc()
            reports.append({"family": family, "key": tuple(key),
                            "error": f"{type(e).__name__}: {e}"})
            continue
        reports.append(rep)
    return reports


def background_retune(stats, *, mesh_shape, wire=None, top: int = 4,
                      dryrun: bool = True, force: bool = False,
                      time_fn=None,
                      family: str = RAGGED_FAMILY) -> threading.Thread:
    """:func:`retune_hot_shapes` on a daemon thread. The thread object
    carries the reports at ``thread.reports`` once joined — the store
    write itself is flock'd, so a concurrent engine build reading the
    store mid-pass sees either the old winner or the new one, never a
    torn file."""

    def run():
        t.reports = retune_hot_shapes(
            stats, mesh_shape=mesh_shape, wire=wire, top=top,
            dryrun=dryrun, force=force, time_fn=time_fn, family=family,
        )

    t = threading.Thread(target=run, name="grid-retune", daemon=True)
    t.reports = []
    t.start()
    return t


def retune_engine(engine, *, top: int = 4, dryrun: bool = True,
                  force: bool = False, time_fn=None) -> list:
    """Convenience: retune from a live :class:`ServingEngine` — pulls
    the mesh shape and wire key from the engine's model so the store
    keys match what its next build will resolve."""
    c = engine.model.config
    return retune_hot_shapes(
        engine.stats, mesh_shape=(engine.model.tp,),
        wire="int8" if c.kv_quant is not None else None,
        top=top, dryrun=dryrun, force=force, time_fn=time_fn,
    )
