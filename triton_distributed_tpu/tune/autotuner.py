"""Contextual autotuner with distributed consensus.

Reference: python/triton_dist/autotuner.py — ``ContextualAutoTuner`` /
``contextual_autotune(is_dist=True)`` (:97-253): tunes whole *thunks*
(not single kernels) because distributed kernels are not side-effect
free; resumable iterator-based benching across failing configs
(:78-94); per-rank logs (:57-67); and the load-bearing trick —
**distributed consensus: all-reduce MAX of per-config times so every
rank picks the same config** (:225-238), without which ranks deadlock
inside mismatched collectives.

TPU re-design: a decorator that benchmarks each config by running the
wrapped callable end to end (``perf_func``), skipping configs that fail
to compile or run (the reference's KernelError retry loop). Consensus
runs the same MAX-reduction across *processes* via
``multihost_utils.process_allgather`` — on a single process it is a
no-op, exactly like the reference's single-rank path. Winning configs
are cached in memory per (name, shape-key) and appended to a JSONL log
(``TDTPU_AUTOTUNE_LOG_DIR``, default ``.autotune_logs/``), one file per
process like the reference's ``.autotune_logs/rank-N.log``.
"""

from __future__ import annotations

import functools
import json
import os
import pathlib
import time
import traceback
import zlib

import jax
import numpy as np

from triton_distributed_tpu.utils.timing import perf_func


def _shape_key(args, kwargs):
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (tuple(x.shape), str(x.dtype))
        if isinstance(x, (int, float, str, bool, type(None))):
            return x
        return type(x).__name__
    return (
        tuple(one(a) for a in args),
        tuple(sorted((k, one(v)) for k, v in kwargs.items())),
    )


def _consensus_times(times: np.ndarray) -> np.ndarray:
    """MAX of per-config timings across processes (≡ the all-reduce at
    autotuner.py:225-238): every process then argmins the same vector,
    so collective code paths stay aligned. Failed configs carry +inf and
    stay +inf for everyone."""
    if jax.process_count() == 1:
        return times
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(times)   # (procs, cfgs)
    return np.max(np.asarray(gathered), axis=0)


class ContextualAutoTuner:
    """Tune ``fn(*args, **cfg)`` over ``configs`` (list of kwarg dicts).

    ``persist=True`` backs the in-memory winner cache with an on-disk
    JSON store (one per log dir), so a redeploy skips re-benching — the
    reference keeps the same state in its ``.autotune_logs``. Every
    process derives the identical winner from the MAX consensus, so
    concurrent writers race to write identical content (atomic replace).
    """

    def __init__(self, fn, configs, *, name=None, warmup=1, iters=5,
                 log=True, persist=True, rounds=1, revalidate_margin=0.25,
                 ttl_s=30 * 86400):
        self.fn = fn
        self.configs = list(configs)
        self.name = name or getattr(fn, "__name__", "thunk")
        self.warmup = warmup
        self.iters = iters
        self.log = log
        self.persist = persist
        # rounds > 1: bench configs round-robin in SNAKE order and rank
        # by the mean of within-round-normalized times (see _bench) —
        # slowly-varying interference on a time-shared chip cancels
        # inside each round's comparison, and the alternating order
        # symmetrizes any monotonic drift across a round.
        self.rounds = rounds
        # A persisted winner is re-validated on the first use per
        # process: winner and recorded runner-up are re-benched, and a
        # winner slower than (1+margin)·runner_up triggers a full
        # re-tune (a sticky wrong winner from a noisy sweep heals).
        self.revalidate_margin = revalidate_margin
        # Entries older than ttl_s re-bench outright (None disables).
        self.ttl_s = ttl_s
        self.cache: dict = {}
        functools.update_wrapper(self, fn)

    def _log_dir(self):
        d = pathlib.Path(os.environ.get("TDTPU_AUTOTUNE_LOG_DIR", ".autotune_logs"))
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _log_path(self):
        return self._log_dir() / f"process-{jax.process_index()}.jsonl"

    def _store_path(self):
        return self._log_dir() / "cache.json"

    def _disk_load(self) -> dict:
        try:
            return json.loads(self._store_path().read_text())
        except (OSError, ValueError):
            return {}

    def _disk_get(self, key):
        """Disk entry for ``key`` as a v2 record
        ``{"v": 2, "best": cfg, "runner_up": cfg|None, "ts": float}``, or
        None (miss / stale / schema drift → re-bench)."""
        entry = self._disk_load().get(repr(key))
        if entry is None:
            return None
        if not (isinstance(entry, dict) and entry.get("v") == 2):
            # pre-v2 store (a bare config dict): re-bench once and
            # rewrite in the validated schema
            return None
        best = entry.get("best")
        runner = entry.get("runner_up")
        # stale-cache self-healing: a winner from an older code version
        # (renamed kwarg, dropped candidate) must re-bench, not be
        # applied blindly
        if best not in self.configs:
            return None
        if runner is not None and runner not in self.configs:
            entry = dict(entry, runner_up=None)
        if self.ttl_s is not None and time.time() - entry.get("ts", 0) > self.ttl_s:
            return None
        return entry

    def _disk_put(self, key, best, runner_up=None):
        # flock'd read-modify-write: different tuners (ag_gemm/gemm_rs/
        # all_gather) and processes share one store; without the lock the
        # second writer's replace would drop the first writer's key
        path = self._store_path()
        lock = path.with_suffix(".lock")
        with open(lock, "w") as lf:
            try:
                import fcntl

                fcntl.flock(lf, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # best effort on exotic filesystems
            store = self._disk_load()
            store[repr(key)] = {
                "v": 2, "best": best, "runner_up": runner_up,
                "ts": time.time(),
            }
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(store, indent=1, sort_keys=True))
            os.replace(tmp, path)

    def _consensus_disk_hit(self, best):
        """A disk hit is usable only if EVERY process has the same one:
        a process that hit would skip the benching collectives a missing
        process is blocked in — the exact mismatched-collective deadlock
        the MAX consensus exists to prevent. Disagreement (including a
        partial hit) degrades to a miss for everyone. Only the
        decision-relevant fields are compared — per-host stores record
        their own ``ts``, which must not defeat agreement."""
        if jax.process_count() == 1:
            return best
        from jax.experimental import multihost_utils

        decision = (
            {"best": best.get("best"), "runner_up": best.get("runner_up")}
            if isinstance(best, dict) else best
        )
        blob = json.dumps(decision, sort_keys=True) if best is not None else ""
        sig = np.array(
            [1 if best is not None else 0, zlib.crc32(blob.encode())],
            np.uint32,
        )
        all_sigs = np.asarray(multihost_utils.process_allgather(sig))
        same = (all_sigs == all_sigs[0]).all() and all_sigs[0, 0] == 1
        return best if same else None

    def _bench(self, args, kwargs, configs=None):
        """PAIRED benching (the bench.py methodology, applied to config
        ranking): configs run round-robin in SNAKE order (forward, then
        reversed — a config measured late in one round is measured early
        in the next, so a monotonic drift in background interference
        biases each config symmetrically), and ranking uses the mean of
        WITHIN-ROUND-normalized times (each round's vector divided by
        its own finite mean) — slowly-varying common-mode interference
        cancels inside each round's comparison instead of shifting the
        per-config medians independently. Returned magnitudes are
        rescaled by the median round level so logged ms stay physical;
        ratios (all any caller compares) are the normalized ones."""
        configs = self.configs if configs is None else configs
        per_round = np.full((self.rounds, len(configs)), np.inf)
        dead = [False] * len(configs)
        for r in range(self.rounds):
            idx_order = range(len(configs))
            if r % 2:
                idx_order = reversed(list(idx_order))
            for i in idx_order:
                cfg = configs[i]
                if dead[i]:
                    continue
                try:
                    _, ms = perf_func(
                        lambda: self.fn(*args, **kwargs, **cfg),
                        # warmup only needs to happen once per config
                        warmup=self.warmup if r == 0 else 0,
                        iters=self.iters,
                    )
                    per_round[r, i] = ms
                except Exception:
                    # a config that fails anywhere must fail everywhere —
                    # +inf survives the MAX consensus (≡ KernelError
                    # skip, autotuner.py:78-94)
                    dead[i] = True
                    if self.log:
                        with open(self._log_path(), "a") as f:
                            f.write(json.dumps({
                                "name": self.name, "config": cfg,
                                "error": traceback.format_exc(limit=1),
                            }) + "\n")
        finite = np.isfinite(per_round)
        scales = np.array([
            row[ok].mean() if ok.any() else np.nan
            for row, ok in zip(per_round, finite)
        ])
        ok_rows = np.isfinite(scales) & (scales > 0)
        if ok_rows.any():
            norm = per_round[ok_rows] / scales[ok_rows, None]
            with np.errstate(invalid="ignore"):
                # mean over rounds of within-round relative time; inf
                # rows (config died mid-sweep) stay inf via the mask
                times = np.where(
                    np.isfinite(norm).all(axis=0),
                    np.where(np.isfinite(norm), norm, 0).mean(axis=0),
                    np.inf,
                ) * float(np.median(scales[ok_rows]))
        else:
            times = np.full(len(configs), np.inf)
        times[dead] = np.inf
        return _consensus_times(times)

    def _validate_entry(self, entry, args, kwargs):
        """Re-validate a persisted winner against its recorded runner-up
        on a fresh (cheap, 2-config) bench: a winner that measures
        > (1+margin)× the runner-up was a noise artifact — discard so
        the caller re-tunes from scratch. Runs under the same MAX
        consensus, so every process reaches the same verdict."""
        best, runner = entry["best"], entry.get("runner_up")
        if runner is None or not self.revalidate_margin:
            return best
        times = self._bench(args, kwargs, configs=[best, runner])
        if not np.isfinite(times[0]):
            return None  # persisted winner no longer even runs
        if np.isfinite(times[1]) and (
            times[0] > (1 + self.revalidate_margin) * times[1]
        ):
            if self.log:
                with open(self._log_path(), "a") as f:
                    f.write(json.dumps({
                        "name": self.name, "stale_winner": best,
                        "runner_up": runner,
                        "ms": [float(times[0]), float(times[1])],
                    }) + "\n")
            return None
        return best

    def pick(self, *args, **kwargs) -> dict:
        """Winning config for these (shapes of) arguments: memory cache →
        disk cache (TTL'd + re-validated) → measure-with-consensus."""
        key = (self.name, _shape_key(args, kwargs))
        best = self.cache.get(key)
        if best is None and self.persist:
            entry = self._consensus_disk_hit(self._disk_get(key))
            if entry is not None:
                best = self._validate_entry(entry, args, kwargs)
            if best is not None:
                self.cache[key] = best
        if best is None:
            times = self._bench(args, kwargs)
            order = np.argsort(times, kind="stable")
            idx = int(order[0])
            if not np.isfinite(times[idx]):
                raise RuntimeError(
                    f"autotune({self.name}): every config failed"
                )
            best = self.configs[idx]
            runner = None
            if len(order) > 1 and np.isfinite(times[order[1]]):
                runner = self.configs[int(order[1])]
            self.cache[key] = best
            if self.persist:
                self._disk_put(key, best, runner)
            if self.log:
                with open(self._log_path(), "a") as f:
                    f.write(json.dumps({
                        "name": self.name, "key": str(key[1]),
                        "best": best, "ms": float(times[idx]),
                        "times": [None if not np.isfinite(t) else float(t)
                                  for t in times],
                        "ts": time.time(),
                    }) + "\n")
        return best

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs, **self.pick(*args, **kwargs))


def method_tuner(name, run, methods, *, warmup=1, iters=3, rounds=3):
    """Engine-selection tuner: candidates are ``{"method": m.value}`` for
    each member of the ``methods`` enum (the shared shape behind the
    ag_gemm/gemm_rs/all_gather ``method=None`` wiring).

    Engine gaps are a few percent — the same order as the time-shared
    chip's run-to-run spread — so selection benches ``rounds``
    round-robin passes and ranks per-config medians (and persisted
    winners are re-validated against the recorded runner-up on first
    use, healing noise-artifact winners)."""
    return ContextualAutoTuner(
        run, [{"method": m.value} for m in methods],
        name=name, warmup=warmup, iters=iters, rounds=rounds,
    )


def wire_tuner(name, run, *, warmup=1, iters=3, rounds=3, mxu=False):
    """Wire-dtype selection tuner for ``wire_dtype='auto'``: the raw
    bf16 wire vs the fp8 wire, benched end to end with the same paired
    snake-order methodology as :func:`method_tuner` (wire gains on
    comm-bound shapes are tens of percent, but on compute-bound shapes
    the two are within the run-to-run spread — the rounds protocol is
    what keeps a noise artifact from pinning the lossy wire). The plain
    int8 wire is deliberately NOT a candidate: it is never faster than
    fp8 (same byte count) and strictly worse numerically — it stays an
    explicit opt-in. ``mxu=True`` (the caller declared int8 weight
    numerics, ``wq='int8'``) adds the dequant-free 'int8-mxu' candidate,
    which CAN beat fp8: same wire bytes, no per-arrival dequant pass,
    and the shard matmul at the s8×s8 MXU rate."""
    configs = [{"wire_dtype": "bf16"}, {"wire_dtype": "fp8"}]
    if mxu:
        configs.append({"wire_dtype": "int8-mxu"})
    return ContextualAutoTuner(
        run, configs,
        name=name, warmup=warmup, iters=iters, rounds=rounds,
    )


def tuned_method_or_none(tuner_factory, *args, key="method"):
    """The ``method=None`` dispatch shared by the op entries: consult the
    measured tuner when tuning is enabled AND the call carries concrete
    arrays (args[0] is probed: benching needs real execution, and inside
    a larger jit the args are tracers so the caller's static heuristic
    applies). Returns the winning config's ``key`` entry or None
    (``key='wire_dtype'`` reuses the same dispatch for the wire tuners)."""
    from triton_distributed_tpu.config import autotune_enabled

    if autotune_enabled() and not isinstance(args[0], jax.core.Tracer):
        return tuner_factory().pick(*args)[key]
    return None


def contextual_autotune(configs, **opts):
    """Decorator form (≡ contextual_autotune, autotuner.py:97)::

        @contextual_autotune(configs=[{"block_m": 128}, {"block_m": 256}])
        def step(x, w, *, block_m):
            return grouped_matmul(x, w, ..., block_m=block_m)
    """

    def wrap(fn):
        return ContextualAutoTuner(fn, configs, **opts)

    return wrap


def search_ring_schedule(
    family: str,
    *,
    rows: int,
    cols: int,
    mesh_shape,
    wire: str | None = None,
    shape=None,
    n: int | None = None,
    itemsize: int = 4,
    dryrun: bool = False,
    top_k: int = 2,
    time_fn=None,
    force: bool = False,
):
    """Schedule-space search for one ring family (the tune.schedule IR).

    enumerate (freedoms + illegal mutations) → LEGALITY GATE (every
    candidate abstractly replayed through shmemlint against the family's
    DeliveryContract, then Mosaic-preflighted; rejections carry rule
    IDs) → perf-model pricing of the lint-clean survivors (hop critical
    path + wire bytes + dequant placement) → optionally time the top-k
    on hardware (``time_fn(schedule) -> ms``; skipped under ``dryrun``
    or when no timer is supplied) → persist the winner keyed by
    ``(family, shape, mesh, wire_dtype)``.

    Reload is ZERO-COST: a persisted winner short-circuits the whole
    search (``cached=True`` in the report) — op resolve paths never pay
    for enumeration, and neither does a second search call.
    Mutated candidates are rejected by the oracle, never timed, never
    cached; the search fails loudly if the oracle rejected nothing
    (a gate that cannot reject is not a gate).
    """
    from triton_distributed_tpu.tune import schedule as sched_lib

    n = int(n if n is not None else int(np.prod(mesh_shape)))
    shape = tuple(shape) if shape is not None else (rows, cols)

    if not force:
        cached = sched_lib.load_schedule(
            family, tuple(int(x) for x in shape),
            tuple(int(x) for x in mesh_shape),
            None if wire is None else str(wire),
        )
        if cached is not None:
            return {
                "family": family, "cached": True,
                "winner": cached.to_dict(),
                "winner_ms": sched_lib.price_schedule(
                    family, cached, rows=rows, cols=cols,
                    itemsize=itemsize, n=n, wire=wire,
                ),
                "default_ms": sched_lib.price_schedule(
                    family, sched_lib.DEFAULT, rows=rows, cols=cols,
                    itemsize=itemsize, n=n, wire=wire,
                ),
                "rejected": [], "timed": 0, "candidates": 0,
            }

    legal, rejected = [], []
    for cand in sched_lib.enumerate_schedules(family, include_mutations=True):
        findings = sched_lib.check_schedule(family, cand, n)
        if findings:
            rejected.append(
                (cand.to_dict(), sorted({f.rule for f in findings}))
            )
        else:
            legal.append(cand)
    if not legal:
        raise RuntimeError(
            f"schedule search {family!r}: no lint-clean candidate "
            f"(rejections: {[r for _, r in rejected]})"
        )
    if not rejected:
        raise RuntimeError(
            f"schedule search {family!r}: the oracle rejected nothing — "
            "the legality gate is not wired"
        )

    priced = sorted(
        legal,
        key=lambda s: sched_lib.price_schedule(
            family, s, rows=rows, cols=cols, itemsize=itemsize, n=n,
            wire=wire,
        ),
    )
    timed = 0
    winner = priced[0]
    if time_fn is not None and not dryrun:
        best_ms, best = float("inf"), None
        for cand in priced[:max(1, int(top_k))]:
            try:
                ms = float(time_fn(cand))
            except Exception:
                traceback.print_exc()
                continue
            timed += 1
            if ms < best_ms:
                best_ms, best = ms, cand
        if best is not None:
            winner = best

    default_ms = sched_lib.price_schedule(
        family, sched_lib.DEFAULT, rows=rows, cols=cols,
        itemsize=itemsize, n=n, wire=wire,
    )
    winner_ms = sched_lib.price_schedule(
        family, winner, rows=rows, cols=cols, itemsize=itemsize, n=n,
        wire=wire,
    )
    key = sched_lib.store_schedule(
        family, shape, mesh_shape, wire, winner,
        price_ms=winner_ms, default_ms=default_ms,
    )
    return {
        "family": family, "cached": False, "key": key,
        "winner": winner.to_dict(), "winner_ms": winner_ms,
        "default_ms": default_ms, "rejected": rejected,
        "timed": timed, "candidates": len(legal) + len(rejected),
    }


def search_grid_schedule(
    family: str,
    *,
    shape,
    mesh_shape,
    wire: str | None = None,
    n: int | None = None,
    dryrun: bool = False,
    top_k: int = 2,
    time_fn=None,
    force: bool = False,
):
    """Schedule-space search for one grid family (``GridSchedule`` IR).

    Same oracle discipline as :func:`search_ring_schedule` — enumerate
    the family's freedom set plus its known-illegal mutations, reject
    through shmemlint + Mosaic preflight, price the clean survivors on
    the family's traffic shape key, optionally time the top-k, persist
    the winner under ``(family, shape, mesh, wire_dtype)``. The search
    RAISES if the oracle rejected nothing: a gate that cannot reject is
    not a gate, and a dead gate silently blesses every candidate.

    ``shape`` is the grid family's traffic key, not a ring slab:
    ragged ``(slots, t_pad, hkv, g, d, page)``, kv_ship
    ``(layers, pages, hkv, d, page)``, gemm_rs ``(m, k, n_out)``.
    A persisted winner short-circuits with ``cached=True`` at zero
    search cost.
    """
    from triton_distributed_tpu.tune import schedule as sched_lib

    if not sched_lib.is_grid_family(family):
        raise ValueError(
            f"{family!r} is not a grid family "
            f"(grid families: {sched_lib.grid_families()})"
        )
    n = int(n if n is not None else int(np.prod(mesh_shape)))
    shape = tuple(int(x) for x in shape)

    def _price(s):
        return sched_lib.price_schedule(
            family, s, rows=shape[0], cols=shape[-1], n=n, wire=wire,
            shape=shape,
        )

    if not force:
        cached = sched_lib.load_schedule(
            family, shape, tuple(int(x) for x in mesh_shape),
            None if wire is None else str(wire),
        )
        if cached is not None and getattr(cached, "kind", "ring") == "grid":
            return {
                "family": family, "cached": True,
                "winner": cached.to_dict(),
                "winner_ms": _price(cached),
                "default_ms": _price(sched_lib.GRID_DEFAULT),
                "rejected": [], "timed": 0, "candidates": 0,
            }

    legal, rejected = [], []
    for cand in sched_lib.enumerate_schedules(family, include_mutations=True):
        findings = sched_lib.check_schedule(family, cand, n)
        if findings:
            rejected.append(
                (cand.to_dict(), sorted({f.rule for f in findings}))
            )
        else:
            legal.append(cand)
    if not legal:
        raise RuntimeError(
            f"schedule search {family!r}: no lint-clean candidate "
            f"(rejections: {[r for _, r in rejected]})"
        )
    if not rejected:
        raise RuntimeError(
            f"schedule search {family!r}: the oracle rejected nothing — "
            "the legality gate is not wired"
        )

    priced = sorted(legal, key=_price)
    timed = 0
    winner = priced[0]
    if time_fn is not None and not dryrun:
        best_ms, best = float("inf"), None
        for cand in priced[:max(1, int(top_k))]:
            try:
                ms = float(time_fn(cand))
            except Exception:
                traceback.print_exc()
                continue
            timed += 1
            if ms < best_ms:
                best_ms, best = ms, cand
        if best is not None:
            winner = best

    default_ms = _price(sched_lib.GRID_DEFAULT)
    winner_ms = _price(winner)
    key = sched_lib.store_schedule(
        family, shape, mesh_shape, wire, winner,
        price_ms=winner_ms, default_ms=default_ms,
    )
    return {
        "family": family, "cached": False, "key": key,
        "winner": winner.to_dict(), "winner_ms": winner_ms,
        "default_ms": default_ms, "rejected": rejected,
        "timed": timed, "candidates": len(legal) + len(rejected),
    }
