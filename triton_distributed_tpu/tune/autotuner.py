"""Contextual autotuner with distributed consensus.

Reference: python/triton_dist/autotuner.py — ``ContextualAutoTuner`` /
``contextual_autotune(is_dist=True)`` (:97-253): tunes whole *thunks*
(not single kernels) because distributed kernels are not side-effect
free; resumable iterator-based benching across failing configs
(:78-94); per-rank logs (:57-67); and the load-bearing trick —
**distributed consensus: all-reduce MAX of per-config times so every
rank picks the same config** (:225-238), without which ranks deadlock
inside mismatched collectives.

TPU re-design: a decorator that benchmarks each config by running the
wrapped callable end to end (``perf_func``), skipping configs that fail
to compile or run (the reference's KernelError retry loop). Consensus
runs the same MAX-reduction across *processes* via
``multihost_utils.process_allgather`` — on a single process it is a
no-op, exactly like the reference's single-rank path. Winning configs
are cached in memory per (name, shape-key) and appended to a JSONL log
(``TDTPU_AUTOTUNE_LOG_DIR``, default ``.autotune_logs/``), one file per
process like the reference's ``.autotune_logs/rank-N.log``.
"""

from __future__ import annotations

import functools
import json
import os
import pathlib
import time
import traceback
import zlib

import jax
import numpy as np

from triton_distributed_tpu.utils.timing import perf_func


def _shape_key(args, kwargs):
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (tuple(x.shape), str(x.dtype))
        if isinstance(x, (int, float, str, bool, type(None))):
            return x
        return type(x).__name__
    return (
        tuple(one(a) for a in args),
        tuple(sorted((k, one(v)) for k, v in kwargs.items())),
    )


def _consensus_times(times: np.ndarray) -> np.ndarray:
    """MAX of per-config timings across processes (≡ the all-reduce at
    autotuner.py:225-238): every process then argmins the same vector,
    so collective code paths stay aligned. Failed configs carry +inf and
    stay +inf for everyone."""
    if jax.process_count() == 1:
        return times
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(times)   # (procs, cfgs)
    return np.max(np.asarray(gathered), axis=0)


class ContextualAutoTuner:
    """Tune ``fn(*args, **cfg)`` over ``configs`` (list of kwarg dicts).

    ``persist=True`` backs the in-memory winner cache with an on-disk
    JSON store (one per log dir), so a redeploy skips re-benching — the
    reference keeps the same state in its ``.autotune_logs``. Every
    process derives the identical winner from the MAX consensus, so
    concurrent writers race to write identical content (atomic replace).
    """

    def __init__(self, fn, configs, *, name=None, warmup=1, iters=5,
                 log=True, persist=True):
        self.fn = fn
        self.configs = list(configs)
        self.name = name or getattr(fn, "__name__", "thunk")
        self.warmup = warmup
        self.iters = iters
        self.log = log
        self.persist = persist
        self.cache: dict = {}
        functools.update_wrapper(self, fn)

    def _log_dir(self):
        d = pathlib.Path(os.environ.get("TDTPU_AUTOTUNE_LOG_DIR", ".autotune_logs"))
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _log_path(self):
        return self._log_dir() / f"process-{jax.process_index()}.jsonl"

    def _store_path(self):
        return self._log_dir() / "cache.json"

    def _disk_load(self) -> dict:
        try:
            return json.loads(self._store_path().read_text())
        except (OSError, ValueError):
            return {}

    def _disk_get(self, key):
        best = self._disk_load().get(repr(key))
        # stale-cache self-healing: a winner from an older code version
        # (renamed kwarg, dropped candidate) must re-bench, not be
        # applied blindly
        if best is not None and best not in self.configs:
            return None
        return best

    def _disk_put(self, key, best):
        # flock'd read-modify-write: different tuners (ag_gemm/gemm_rs/
        # all_gather) and processes share one store; without the lock the
        # second writer's replace would drop the first writer's key
        path = self._store_path()
        lock = path.with_suffix(".lock")
        with open(lock, "w") as lf:
            try:
                import fcntl

                fcntl.flock(lf, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # best effort on exotic filesystems
            store = self._disk_load()
            store[repr(key)] = best
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(store, indent=1, sort_keys=True))
            os.replace(tmp, path)

    def _consensus_disk_hit(self, best):
        """A disk hit is usable only if EVERY process has the same one:
        a process that hit would skip the benching collectives a missing
        process is blocked in — the exact mismatched-collective deadlock
        the MAX consensus exists to prevent. Disagreement (including a
        partial hit) degrades to a miss for everyone."""
        if jax.process_count() == 1:
            return best
        from jax.experimental import multihost_utils

        blob = json.dumps(best, sort_keys=True) if best is not None else ""
        sig = np.array(
            [1 if best is not None else 0, zlib.crc32(blob.encode())],
            np.uint32,
        )
        all_sigs = np.asarray(multihost_utils.process_allgather(sig))
        same = (all_sigs == all_sigs[0]).all() and all_sigs[0, 0] == 1
        return best if same else None

    def _bench(self, args, kwargs):
        times = np.full((len(self.configs),), np.inf)
        for i, cfg in enumerate(self.configs):
            try:
                _, ms = perf_func(
                    lambda: self.fn(*args, **kwargs, **cfg),
                    warmup=self.warmup, iters=self.iters,
                )
                times[i] = ms
            except Exception:
                # a config that fails anywhere must fail everywhere —
                # +inf survives the MAX consensus (≡ KernelError skip,
                # autotuner.py:78-94)
                if self.log:
                    with open(self._log_path(), "a") as f:
                        f.write(json.dumps({
                            "name": self.name, "config": self.configs[i],
                            "error": traceback.format_exc(limit=1),
                        }) + "\n")
        return _consensus_times(times)

    def pick(self, *args, **kwargs) -> dict:
        """Winning config for these (shapes of) arguments: memory cache →
        disk cache → measure-with-consensus."""
        key = (self.name, _shape_key(args, kwargs))
        best = self.cache.get(key)
        if best is None and self.persist:
            best = self._consensus_disk_hit(self._disk_get(key))
            if best is not None:
                self.cache[key] = best
        if best is None:
            times = self._bench(args, kwargs)
            idx = int(np.argmin(times))
            if not np.isfinite(times[idx]):
                raise RuntimeError(
                    f"autotune({self.name}): every config failed"
                )
            best = self.configs[idx]
            self.cache[key] = best
            if self.persist:
                self._disk_put(key, best)
            if self.log:
                with open(self._log_path(), "a") as f:
                    f.write(json.dumps({
                        "name": self.name, "key": str(key[1]),
                        "best": best, "ms": float(times[idx]),
                        "times": [None if not np.isfinite(t) else float(t)
                                  for t in times],
                        "ts": time.time(),
                    }) + "\n")
        return best

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs, **self.pick(*args, **kwargs))


def method_tuner(name, run, methods, *, warmup=1, iters=3):
    """Engine-selection tuner: candidates are ``{"method": m.value}`` for
    each member of the ``methods`` enum (the shared shape behind the
    ag_gemm/gemm_rs/all_gather ``method=None`` wiring)."""
    return ContextualAutoTuner(
        run, [{"method": m.value} for m in methods],
        name=name, warmup=warmup, iters=iters,
    )


def tuned_method_or_none(tuner_factory, *args):
    """The ``method=None`` dispatch shared by the op entries: consult the
    measured tuner when tuning is enabled AND the call carries concrete
    arrays (args[0] is probed: benching needs real execution, and inside
    a larger jit the args are tracers so the caller's static heuristic
    applies). Returns the winning method string or None."""
    from triton_distributed_tpu.config import autotune_enabled

    if autotune_enabled() and not isinstance(args[0], jax.core.Tracer):
        return tuner_factory().pick(*args)["method"]
    return None


def contextual_autotune(configs, **opts):
    """Decorator form (≡ contextual_autotune, autotuner.py:97)::

        @contextual_autotune(configs=[{"block_m": 128}, {"block_m": 256}])
        def step(x, w, *, block_m):
            return grouped_matmul(x, w, ..., block_m=block_m)
    """

    def wrap(fn):
        return ContextualAutoTuner(fn, configs, **opts)

    return wrap
