"""Contextual autotuner with distributed consensus.

Reference: python/triton_dist/autotuner.py — ``ContextualAutoTuner`` /
``contextual_autotune(is_dist=True)`` (:97-253): tunes whole *thunks*
(not single kernels) because distributed kernels are not side-effect
free; resumable iterator-based benching across failing configs
(:78-94); per-rank logs (:57-67); and the load-bearing trick —
**distributed consensus: all-reduce MAX of per-config times so every
rank picks the same config** (:225-238), without which ranks deadlock
inside mismatched collectives.

TPU re-design: a decorator that benchmarks each config by running the
wrapped callable end to end (``perf_func``), skipping configs that fail
to compile or run (the reference's KernelError retry loop). Consensus
runs the same MAX-reduction across *processes* via
``multihost_utils.process_allgather`` — on a single process it is a
no-op, exactly like the reference's single-rank path. Winning configs
are cached in memory per (name, shape-key) and appended to a JSONL log
(``TDTPU_AUTOTUNE_LOG_DIR``, default ``.autotune_logs/``), one file per
process like the reference's ``.autotune_logs/rank-N.log``.
"""

from __future__ import annotations

import functools
import json
import os
import pathlib
import time
import traceback

import jax
import numpy as np

from triton_distributed_tpu.utils.timing import perf_func


def _shape_key(args, kwargs):
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (tuple(x.shape), str(x.dtype))
        if isinstance(x, (int, float, str, bool, type(None))):
            return x
        return type(x).__name__
    return (
        tuple(one(a) for a in args),
        tuple(sorted((k, one(v)) for k, v in kwargs.items())),
    )


def _consensus_times(times: np.ndarray) -> np.ndarray:
    """MAX of per-config timings across processes (≡ the all-reduce at
    autotuner.py:225-238): every process then argmins the same vector,
    so collective code paths stay aligned. Failed configs carry +inf and
    stay +inf for everyone."""
    if jax.process_count() == 1:
        return times
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(times)   # (procs, cfgs)
    return np.max(np.asarray(gathered), axis=0)


class ContextualAutoTuner:
    """Tune ``fn(*args, **cfg)`` over ``configs`` (list of kwarg dicts)."""

    def __init__(self, fn, configs, *, name=None, warmup=1, iters=5, log=True):
        self.fn = fn
        self.configs = list(configs)
        self.name = name or getattr(fn, "__name__", "thunk")
        self.warmup = warmup
        self.iters = iters
        self.log = log
        self.cache: dict = {}
        functools.update_wrapper(self, fn)

    def _log_path(self):
        d = pathlib.Path(os.environ.get("TDTPU_AUTOTUNE_LOG_DIR", ".autotune_logs"))
        d.mkdir(parents=True, exist_ok=True)
        return d / f"process-{jax.process_index()}.jsonl"

    def _bench(self, args, kwargs):
        times = np.full((len(self.configs),), np.inf)
        for i, cfg in enumerate(self.configs):
            try:
                _, ms = perf_func(
                    lambda: self.fn(*args, **kwargs, **cfg),
                    warmup=self.warmup, iters=self.iters,
                )
                times[i] = ms
            except Exception:
                # a config that fails anywhere must fail everywhere —
                # +inf survives the MAX consensus (≡ KernelError skip,
                # autotuner.py:78-94)
                if self.log:
                    with open(self._log_path(), "a") as f:
                        f.write(json.dumps({
                            "name": self.name, "config": self.configs[i],
                            "error": traceback.format_exc(limit=1),
                        }) + "\n")
        return _consensus_times(times)

    def __call__(self, *args, **kwargs):
        key = (self.name, _shape_key(args, kwargs))
        best = self.cache.get(key)
        if best is None:
            times = self._bench(args, kwargs)
            idx = int(np.argmin(times))
            if not np.isfinite(times[idx]):
                raise RuntimeError(
                    f"autotune({self.name}): every config failed"
                )
            best = self.configs[idx]
            self.cache[key] = best
            if self.log:
                with open(self._log_path(), "a") as f:
                    f.write(json.dumps({
                        "name": self.name, "key": str(key[1]),
                        "best": best, "ms": float(times[idx]),
                        "times": [None if not np.isfinite(t) else float(t)
                                  for t in times],
                        "ts": time.time(),
                    }) + "\n")
        return self.fn(*args, **kwargs, **best)


def contextual_autotune(configs, **opts):
    """Decorator form (≡ contextual_autotune, autotuner.py:97)::

        @contextual_autotune(configs=[{"block_m": 128}, {"block_m": 256}])
        def step(x, w, *, block_m):
            return grouped_matmul(x, w, ..., block_m=block_m)
    """

    def wrap(fn):
        return ContextualAutoTuner(fn, configs, **opts)

    return wrap
