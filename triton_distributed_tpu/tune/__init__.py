"""Tuning package (L6): contextual autotuner + analytical perf models.

≡ python/triton_dist/autotuner.py (thunk-level distributed autotune
with cross-rank consensus) and kernels/nvidia/{comm,gemm}_perf_model.py
(speed-of-light estimators keyed by device generation).
"""

from triton_distributed_tpu.tune.autotuner import (
    ContextualAutoTuner,
    contextual_autotune,
)
from triton_distributed_tpu.tune.perf_model import (
    TPU_SPECS,
    TpuSpec,
    detect_spec,
    estimate_all_gather_ms,
    estimate_all_to_all_ms,
    estimate_gemm_ms,
    estimate_reduce_scatter_ms,
    overlap_efficiency,
)

__all__ = [
    "ContextualAutoTuner",
    "contextual_autotune",
    "TPU_SPECS",
    "TpuSpec",
    "detect_spec",
    "estimate_gemm_ms",
    "estimate_all_gather_ms",
    "estimate_reduce_scatter_ms",
    "estimate_all_to_all_ms",
    "overlap_efficiency",
]
