"""Analytical performance models for TPU generations.

Reference: python/triton_dist/kernels/nvidia/comm_perf_model.py (NIC /
NVLink / PCIe bandwidth discovery, ``estimate_reduce_scatter_time``
:91) and gemm_perf_model.py (tensor-core TFLOPS tables by device name,
``estimate_gemm_sol_time_ms`` :233) — used to pick SM budgets and
sanity-check measured numbers.

TPU re-design: per-generation datasheet tables (MXU TFLOPS, HBM GB/s,
ICI GB/s per link and links per chip) + speed-of-light estimators for
the collectives this framework ships (ring AG/RS, dense A2A, LL small
messages). The same two consumers: engine auto-selection thresholds and
"is this measurement sane" checks in benches.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax


@dataclass(frozen=True)
class TpuSpec:
    name: str
    bf16_tflops: float       # peak MXU, per chip
    hbm_gbps: float          # HBM bandwidth, per chip
    ici_gbps: float          # ICI bandwidth per link, per direction
    ici_links: int           # torus links per chip
    int8_tops: float = 0.0   # peak s8×s8→s32 MXU rate (0 = no speedup)
    # per-chip share of the inter-slice DCN fabric. Order-of-magnitude
    # deployment numbers (multi-NIC hosts divided by chips per host) —
    # DCN is 4-16× slower than one ICI link, which is exactly why the
    # KV-ship placement model must be able to REFUSE disaggregation.
    dcn_gbps: float = 6.25

    @property
    def s8_tops(self) -> float:
        """Effective int8 MXU rate: the native path where the datasheet
        lists one, else the bf16 rate (int8 then buys bytes, not
        FLOPs)."""
        return self.int8_tops or self.bf16_tflops


# Public datasheet numbers (cloud.google.com/tpu/docs/system-architecture).
# int8 TOPS: the native s8×s8→s32 path — ~2× the bf16 rate on v5e/v5p/
# v6e (the W8A8 grouped GEMM measured 320–350 TOP/s on a v5e against the
# 394 peak, kernels/group_gemm.py); v4 has no separate int8 path.
TPU_SPECS = {
    "v4": TpuSpec("v4", 275.0, 1228.0, 50.0, 6, dcn_gbps=6.25),
    "v5e": TpuSpec("v5e", 197.0, 819.0, 50.0, 4, int8_tops=394.0,
                   dcn_gbps=12.5),
    "v5p": TpuSpec("v5p", 459.0, 2765.0, 100.0, 6, int8_tops=918.0,
                   dcn_gbps=25.0),
    "v6e": TpuSpec("v6e", 918.0, 1640.0, 100.0, 4, int8_tops=1836.0,
                   dcn_gbps=25.0),
}
_DEFAULT = TPU_SPECS["v5e"]


def detect_spec(device=None) -> TpuSpec:
    """Map jax's device_kind onto a spec row (≡ get_device_name-keyed
    tables, gemm_perf_model.py). Unknown kinds fall back to v5e."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, spec in TPU_SPECS.items():
        if key in kind.replace(" ", "").replace("lite", "e"):
            return spec
    if "v5" in kind:
        return TPU_SPECS["v5e" if "lite" in kind else "v5p"]
    return _DEFAULT


def estimate_gemm_ms(m: int, k: int, n: int, spec: TpuSpec | None = None,
                     efficiency: float = 0.75) -> float:
    """Speed-of-light matmul time (≡ estimate_gemm_sol_time_ms,
    gemm_perf_model.py:233): max of MXU flops time and HBM traffic time."""
    spec = spec or detect_spec()
    flops_ms = (2 * m * k * n) / (spec.bf16_tflops * 1e12 * efficiency) * 1e3
    bytes_moved = 2 * (m * k + k * n + m * n)
    mem_ms = bytes_moved / (spec.hbm_gbps * 1e9) * 1e3
    return max(flops_ms, mem_ms)


def estimate_s8_gemm_ms(m: int, k: int, n: int, spec: TpuSpec | None = None,
                        efficiency: float = 0.75) -> float:
    """Speed-of-light s8×s8→s32 matmul time: the int8-MXU twin of
    :func:`estimate_gemm_ms` — 1-byte operands halve the HBM traffic
    and the native int8 path runs at ``spec.s8_tops``."""
    spec = spec or detect_spec()
    flops_ms = (2 * m * k * n) / (spec.s8_tops * 1e12 * efficiency) * 1e3
    bytes_moved = (m * k + k * n) + 2 * m * n   # s8 in, bf16-ish out
    mem_ms = bytes_moved / (spec.hbm_gbps * 1e9) * 1e3
    return max(flops_ms, mem_ms)


def dequant_pass_ms(rows: int, cols: int, out_itemsize: int = 2,
                    spec: TpuSpec | None = None) -> float:
    """Cost of one per-arrival dequant pass over a wire slab: read the
    1-byte payload (+ scale plane, negligible), write the widened copy —
    pure HBM traffic, the VPU multiply is free under it. This is the
    SKIPPED-PASS term of the int8→MXU model: the epilogue-folded
    consumer never runs this pass (and never re-reads the widened copy
    either, which :func:`estimate_gemm_ms`'s A-term would charge)."""
    spec = spec or detect_spec()
    return rows * cols * (1 + out_itemsize) / (spec.hbm_gbps * 1e9) * 1e3


def int8_mxu_step_ratio(slab_rows: int, k: int, n_cols: int,
                        spec: TpuSpec | None = None) -> float:
    """Projected per-ring-step speedup of the dequant-free int8→MXU
    consumer over dequant-then-matmul on the same int8 wire:
    (dequant pass + bf16 shard matmul) / s8×s8 shard matmul. > 1 means
    the perf model projects the epilogue path as a win."""
    spec = spec or detect_spec()
    legacy = dequant_pass_ms(slab_rows, k, 2, spec) + estimate_gemm_ms(
        slab_rows, k, n_cols, spec
    )
    return legacy / estimate_s8_gemm_ms(slab_rows, k, n_cols, spec)


def estimate_all_gather_ms(shard_bytes: int, n: int,
                           spec: TpuSpec | None = None) -> float:
    """Bidirectional-ring AG over ICI: each chip receives (n-1) shards
    across 2 directions (≡ estimate_allgather in comm_perf_model)."""
    spec = spec or detect_spec()
    wire = shard_bytes * (n - 1) / 2
    return wire / (spec.ici_gbps * 1e9) * 1e3


def estimate_reduce_scatter_ms(shard_bytes: int, n: int,
                               spec: TpuSpec | None = None) -> float:
    """Ring RS moves the same wire bytes as ring AG
    (≡ estimate_reduce_scatter_time, comm_perf_model.py:91)."""
    return estimate_all_gather_ms(shard_bytes, n, spec)


def estimate_all_to_all_ms(local_bytes: int, n: int,
                           spec: TpuSpec | None = None) -> float:
    """Dense A2A: (n-1)/n of the local buffer crosses the bisection;
    on a torus every chip drives ici_links links concurrently."""
    spec = spec or detect_spec()
    wire = local_bytes * (n - 1) / n
    return wire / (spec.ici_gbps * spec.ici_links * 1e9) * 1e3


def overlap_efficiency(compute_ms: float, comm_ms: float) -> float:
    """Fraction of comm hidden if perfectly pipelined under compute —
    the 'overlap %' north-star metric (BASELINE.json)."""
    if comm_ms <= 0:
        return 1.0
    return min(compute_ms, comm_ms) / comm_ms


# ------------------------------------------------------- wire-bytes term
#
# The streaming rings can ship fp8/int8 payloads with per-chunk f32
# scales (lang.wire): the model needs the true wire byte count (payload
# + scale planes) and a comm-bound test so the op entries can pick the
# wire dtype analytically when no measured winner exists.

def ring_wire_bytes(rows: int, cols: int, itemsize: int,
                    wire: str | None = None, chunk_rows: int = 64) -> int:
    """Bytes ONE ring slab puts on the wire: the raw (rows, cols)
    payload at ``itemsize``, or the compressed lang.wire layout — 1-byte
    elements plus one (128·4 B) scale row per ``chunk_rows`` rows."""
    if wire in (None, "bf16"):
        return rows * cols * itemsize
    chunks = -(-rows // max(1, chunk_rows))
    return rows * cols + chunks * 128 * 4


def ring_wire_ms(slab_bytes: int, spec: TpuSpec | None = None) -> float:
    """One unidirectional ring-step transfer over a single ICI link."""
    spec = spec or detect_spec()
    return slab_bytes / (spec.ici_gbps * 1e9) * 1e3


def auto_wire_dtype(slab_rows: int, k: int, n_cols: int, itemsize: int,
                    *, slab_bytes: int | None = None,
                    spec: TpuSpec | None = None,
                    consumer_wq: str | None = None) -> str:
    """'fp8' when the ring is comm-bound at these per-step shapes —
    i.e. the bf16 slab transfer (``slab_bytes``, default the A slab
    rows×k) outlasts the per-step shard matmul the ring hides it under
    — else 'bf16'. Compressing a compute-bound ring buys nothing
    (overlap is already 100%) and costs accuracy, so the selector only
    reaches for the 1-byte wire where it widens the overlap range.

    ``consumer_wq='int8'``: the consumer has declared int8 weight
    numerics, so on comm-bound shapes the selector picks the
    DEQUANT-FREE 'int8-mxu' wire instead of fp8 — same wire bytes, but
    the per-arrival dequant pass disappears and the shard matmul runs
    at the s8×s8 MXU rate (both terms the step-ratio model above
    projects as a win exactly where the wire engages)."""
    spec = spec or detect_spec()
    compute_ms = estimate_gemm_ms(slab_rows, k, n_cols, spec)
    if slab_bytes is None:
        slab_bytes = slab_rows * k * itemsize
    if ring_wire_ms(slab_bytes, spec) <= compute_ms:
        return "bf16"
    return "int8-mxu" if consumer_wq == "int8" else "fp8"


# ------------------------------------------------- ragged serving term
#
# The continuous-batching engine's step cost is dominated by the ragged
# paged-attention page walk (per-row TRUE lengths — the whole point of
# the ragged kernel) plus the packed batch's weight-HBM-bound
# projection reads. The bench (serving_continuous) reports this model
# term next to the measurement so regressions are explainable as
# %-of-speed-of-light, like every other bench row.

#: fixed per-page DMA-issue/loop overhead of the dynamic page walk,
#: MEASURED per backend (the ROADMAP "fold the measured per-page issue
#: cost" follow-on). Keys are coarse backend kinds:
#:
#: * ``"tpu"`` — the round-5 v5e serving-attention measurement
#:   (~0.17 µs/block at 1024-row blocks); refresh on the next
#:   multi-chip run from the serving_disaggregated bench's
#:   ``measured_page_issue_ms`` field.
#: * ``"cpu-interp"`` — the dev-box measurement backing the bench's
#:   model row off-TPU: derived from ``bench.py --dryrun``'s
#:   serving_disaggregated decode-role p50 (the pure-decode steps —
#:   the cleanest per-page signal: ~6 ms over ~6 rows × ~8 walked
#:   pages on the XLA-twin path; the bench re-derives and reports it
#:   as ``measured_page_issue_ms`` every run). Coarse by nature — the
#:   interpreter's cost is partly per-dispatch, not per-page — but 3
#:   orders closer to what the dev box pays than the TPU constant.
RAGGED_PAGE_ISSUE_MS_MEASURED = {
    "tpu": 0.17e-3,
    "cpu-interp": 0.13,
}

RAGGED_PAGE_ISSUE_MS = RAGGED_PAGE_ISSUE_MS_MEASURED["tpu"]


def measured_page_issue_ms(backend: str | None = None) -> float:
    """The measured per-page issue cost for ``backend`` (default: the
    current jax backend — 'tpu' on hardware, the dev-box row
    otherwise)."""
    if backend is None:
        backend = "tpu" if jax.default_backend() == "tpu" else "cpu-interp"
    return RAGGED_PAGE_ISSUE_MS_MEASURED.get(
        backend, RAGGED_PAGE_ISSUE_MS
    )


def ragged_page_walk_ms(kv_lens, page: int, hkv: int, d: int,
                        spec: TpuSpec | None = None,
                        quant: bool = True,
                        issue_ms: float | None = None) -> float:
    """HBM time of one ragged step's KV walk: every row reads
    ``ceil(kv_len/page)`` pages of K AND V (+ the f32 scale planes
    under int8), plus the fixed per-page issue cost — proportional to
    the step's TRUE KV volume, never the slot capacity (the quantity a
    rectangle batch cannot avoid paying). ``issue_ms`` overrides the
    per-page issue constant (pass
    :func:`measured_page_issue_ms` to use the backend's measured row —
    the bench does, so its model term tracks the machine it ran on)."""
    spec = spec or detect_spec()
    if issue_ms is None:
        issue_ms = RAGGED_PAGE_ISSUE_MS
    pages = sum(max(-(-int(l) // page), 1) for l in kv_lens if int(l) > 0)
    per_page = 2 * hkv * page * d * (1 if quant else 2)
    if quant:
        per_page += 2 * hkv * page * 4
    return (pages * per_page / (spec.hbm_gbps * 1e9) * 1e3
            + pages * issue_ms)


def ragged_serving_step_ms(kv_lens, q_lens, *, page: int, hkv: int,
                           g: int, d: int, hidden: int,
                           weight_bytes_per_token_layer: float = 0.0,
                           n_layers: int = 1,
                           spec: TpuSpec | None = None,
                           quant: bool = True,
                           issue_ms: float | None = None) -> float:
    """Analytic one-step model for the continuous engine: the per-layer
    ragged attention walk plus the packed batch's projection/expert
    weight reads (``weight_bytes_per_token_layer`` — serving GEMMs are
    weight-HBM-bound at batch-scale M, so the weight fetch, not the
    FLOPs, is the projection term) and the q/out token traffic."""
    spec = spec or detect_spec()
    t = sum(int(x) for x in q_lens)
    attn = ragged_page_walk_ms(kv_lens, page, hkv, d, spec, quant,
                               issue_ms)
    tok_bytes = 3 * t * hkv * g * d * 2          # q in, out, lse-ish
    w_ms = (weight_bytes_per_token_layer
            / (spec.hbm_gbps * 1e9) * 1e3)
    return n_layers * (
        attn + tok_bytes / (spec.hbm_gbps * 1e9) * 1e3 + w_ms
    )


# ---------------------------------------------------- speculation term
#
# Speculative decoding (serving/spec.py) changes WHAT a decode step is:
# a verify row packs 1 + k tokens and emits 1..k+1 of them, so the
# per-step cost rises a little (wider q traffic, k extra provisional KV
# appends) while the per-TOKEN cost falls by the accepted-tokens-per-
# step factor. Both the fleet router's load term and the
# disaggregation placement gate consume these: speculation SHRINKS the
# decode window a KV ship must hide under, so a split that was priced
# viable at 1 token/step can stop being viable at 2.

#: analytic prior for the per-draft acceptance probability before any
#: verify row has run — deliberately conservative (the n-gram drafter
#: measured ~0.5 on motif-heavy greedy traffic, near zero on
#: incompressible random tokens; the prior sits where under-promising
#: only makes the router/placement err toward the plain engine).
DEFAULT_SPEC_ACCEPTANCE = 0.3


def expected_accepted_per_step(spec_k: int, acceptance_rate: float) -> float:
    """Expected tokens EMITTED by one draft-k verify row under an
    i.i.d. per-draft acceptance probability ``p``:
    ``1 + p + p² + … + p^k`` (truncated geometric — every emitted token
    is an accepted draft or the final correction/bonus draw). Bounded
    in ``[1, k+1]``; the analytic prior where no measured
    ``EngineStats.accepted_tokens_per_step`` exists yet."""
    p = min(max(float(acceptance_rate), 0.0), 1.0)
    if p >= 1.0:
        return float(spec_k + 1)
    return (1.0 - p ** (spec_k + 1)) / (1.0 - p)


def expected_accepted_per_step_tree(spec_tree: int,
                                    acceptance_rate: float,
                                    branches: int = 2) -> float:
    """Expected tokens emitted by one TREE verify row of ``spec_tree``
    nodes hedged ``branches`` ways per level. Where the linear row must
    match ONE proposed token per level, a tree level escapes with any
    of its ``b`` siblings: ``q = 1 - (1-p)^b`` per level, and the node
    budget buys ``spec_tree // b`` levels —
    ``1 + q + q² + … + q^levels``. ``branches=1`` degenerates to
    :func:`expected_accepted_per_step` exactly; wider hedging trades
    depth for per-level escape probability, which wins when the
    traffic's continuations are genuinely ambiguous (branchy motifs)
    and loses on incompressible or single-path streams — the term the
    tune layer prices ``GridSchedule.tree_pack`` against."""
    p = min(max(float(acceptance_rate), 0.0), 1.0)
    b = max(int(branches), 1)
    levels = max(int(spec_tree) // b, 0)
    q = 1.0 - (1.0 - p) ** b
    if q >= 1.0:
        return float(levels + 1)
    return (1.0 - q ** (levels + 1)) / (1.0 - q)


def spec_step_ms(kv_lens, *, spec_k: int, page: int, hkv: int, g: int,
                 d: int, hidden: int, n_layers: int = 1,
                 spec_tree: int = 0,
                 spec: TpuSpec | None = None, quant: bool = True,
                 issue_ms: float | None = None) -> float:
    """Analytic cost of one speculative VERIFY step: the plain ragged
    step with every decode row widened to ``q_len = 1 + spec_k`` (the
    frontier token plus k provisional drafts; ``spec_tree > 0`` widens
    to the tree pack instead — a tree row costs exactly what a linear
    row of the same node count costs, since the ancestor-bitmask mask
    changes which scores survive, not which pages are walked). The
    page walk reads the extra appended pages' worth of KV; the token
    traffic term scales with the widened pack. Divide by
    :func:`expected_accepted_per_step` (or the ``_tree`` variant) for
    the per-emitted-token clock."""
    k = max(int(spec_k), int(spec_tree))
    wide = [int(l) + k for l in kv_lens]
    return ragged_serving_step_ms(
        wide, [1 + k] * len(kv_lens), page=page, hkv=hkv, g=g,
        d=d, hidden=hidden, n_layers=n_layers, spec=spec, quant=quant,
        issue_ms=issue_ms,
    )


def replica_step_ms(engine, *, spec: TpuSpec | None = None) -> float:
    """Analytic time of one engine step at the CURRENT resident
    occupancy (:func:`ragged_serving_step_ms` over the active slots'
    kv/cursor state): a slot still prefilling contributes its next
    chunk of prompt tokens, a decoding slot one token. Duck-typed over
    ``ServingEngine`` and either half of a disaggregated pair:
    anything with ``slot_req``, ``cfg``/``model.config``-shaped knobs.
    Cheap (no kernel runs) and deterministic — this is the modeled
    step clock the fleet accumulates for reproducible goodput, and the
    base of the router's :func:`replica_load_ms` perf term."""
    spec = spec or detect_spec()
    mc = engine.model.config
    # a speculative engine's decode rows are ``1 + spec_k`` wide (the
    # verify pack; tree mode packs its node budget instead) — price
    # the step it actually launches
    k = max(int(getattr(engine, "spec_k", 0)),
            int(getattr(engine, "spec_tree", 0)))
    active = [r for r in engine.slot_req if r is not None]
    kv_lens = [max(r.cursor, 1) + (k if r.cursor >= len(r.prompt) else 0)
               for r in active] or [1]
    q_lens = [
        max(1, min(engine.cfg.chunk, len(r.prompt) - r.cursor))
        if r.cursor < len(r.prompt) else 1 + k
        for r in active
    ] or [1]
    hkv = mc.n_kv_heads
    return ragged_serving_step_ms(
        kv_lens, q_lens, page=engine.cfg.page, hkv=hkv,
        g=mc.n_heads // max(hkv, 1), d=mc.head_dim, hidden=mc.hidden,
        n_layers=mc.n_layers, spec=spec,
        quant=getattr(mc, "kv_quant", None) is not None,
    )


def _spec_accept_factor(engine) -> float:
    """Tokens a speculative engine's verify step EMITS per step run —
    the measured engine rate once verify rows exist, the geometric
    prior before, 1.0 on plain engines. Divides the step clock
    wherever per-token throughput is being priced."""
    k = int(getattr(engine, "spec_k", 0))
    if not k:
        return 1.0
    st = getattr(engine, "stats", None)
    if st is not None and getattr(st, "spec_rows", 0) > 0:
        return max(st.accepted_tokens_per_step, 1.0)
    return expected_accepted_per_step(k, DEFAULT_SPEC_ACCEPTANCE)


def tiered_replica_load_ms(engine, queued_ahead: int, *,
                           spec: TpuSpec | None = None) -> float:
    """:func:`replica_load_ms` with an EXPLICIT queued-ahead count —
    the admission wait a PRIORITIZED arrival pays. Tier-r work
    re-enters admission ahead of every lower tier (the multi-tenant
    priority sort), so a tier-r arrival waits only on the queued
    requests at rank <= r; the caller passes that tier-filtered depth
    and the fleet's per-tenant retry-after prices by the tenant's own
    tier instead of the fleet-blind full queue."""
    step = replica_step_ms(engine, spec=spec) / _spec_accept_factor(engine)
    return step * (1.0 + max(int(queued_ahead), 0))


def replica_load_ms(engine, *, spec: TpuSpec | None = None) -> float:
    """Queue-depth load estimate for one fleet replica: the analytic
    :func:`replica_step_ms` scaled by how many admissions are already
    queued ahead — the router's perf term. A speculative replica's
    step EMITS more than one token, so its effective per-token clock is
    the step divided by accepted-tokens-per-step (the measured engine
    rate once verify rows have run, the geometric prior before) — a
    replica that drains its queue k× faster must price k× cheaper, or
    the router under-routes exactly the replicas speculation sped
    up."""
    queued = len(engine.waiting) + len(engine.pending)
    return tiered_replica_load_ms(engine, queued, spec=spec)


def request_service_ms(engine, req, *,
                       spec: TpuSpec | None = None) -> float:
    """Modeled time to serve ``req`` ITSELF at this engine's current
    occupancy clock: remaining prefill chunks plus remaining decode
    steps (speculation divides the decode part by accepted-tokens-per-
    step), each billed one :func:`replica_step_ms`. The own-work term
    of the router's deadline slack."""
    step = replica_step_ms(engine, spec=spec)
    remaining = max(len(req.seq) - req.cursor, 0)
    chunks = -(-remaining // max(int(engine.cfg.chunk), 1))
    decode = max(int(req.max_new) - len(req.generated), 0)
    return (chunks + decode / _spec_accept_factor(engine)) * step


def request_slack_ms(engine, req, slo_ms: float, *,
                     spec: TpuSpec | None = None) -> float:
    """Deadline slack of routing ``req`` to ``engine``:
    ``slo_ms − modeled completion``, where modeled completion is the
    queue already ahead (:func:`replica_load_ms`) plus the request's
    own remaining work (:func:`request_service_ms`). Negative slack
    means this placement is MODELED to miss the tenant's SLO — the
    fleet router lets that outrank prefix affinity."""
    return (float(slo_ms) - replica_load_ms(engine, spec=spec)
            - request_service_ms(engine, req, spec=spec))


# ------------------------------------------------ hop critical-path term
#
# The dataflow pass (analysis/dataflow.py) counts, per element of every
# contract destination, how many remote DMAs the bytes rode. Feeding
# that histogram back here turns it into a pre-hardware critical-path
# check: a ring of n ranks delivers every chunk in ≤ n-1 hops, so a
# schedule whose max hop count exceeds that has serialized (or detoured)
# its transfers — visible as wall-clock before any chip run (ROADMAP
# PR-4 follow-on, closed round 8: lint rule SL011).

def hop_critical_path_ms(max_hop: int, hop_bytes: int,
                         spec: TpuSpec | None = None) -> float:
    """Wire time of the LONGEST delivery chain: ``max_hop`` sequential
    ring-step transfers of ``hop_bytes`` each (hops on one chain cannot
    overlap each other — each forwards what the previous delivered)."""
    return max_hop * ring_wire_ms(hop_bytes, spec)


def ring_depth_regression(max_hop: int, n: int, hop_bytes: int,
                          spec: TpuSpec | None = None):
    """None when the observed max hop count is within the ring-optimal
    n-1; else (excess_hops, excess_ms) — the critical-path regression a
    serialized/detoured schedule pays per collective."""
    if max_hop <= max(n - 1, 1):
        return None
    excess = max_hop - (n - 1)
    return excess, hop_critical_path_ms(excess, hop_bytes, spec)


# --------------------------------------------------- KV-ship (DCN) term
#
# Disaggregated prefill/decode moves every finished request's KV cache
# slice→slice over DCN — the slowest fabric in the system. The split
# only wins when that transfer hides under the decode work the request
# buys (max_new decode steps); when prompts are long and generations
# short the wire DOMINATES and disaggregation makes latency worse.
# These terms price the ship so `auto` placement can refuse it
# analytically, before any hardware run.

def kv_ship_ms(n_pages: int, page: int, hkv: int, d: int, n_layers: int,
               quant: bool = True, spec: TpuSpec | None = None) -> float:
    """DCN time of ONE request's KV ship: K and V pages for every
    layer in the wire layout (1 B/elem int8 payload + the per-row f32
    scale planes under ``kv_quant``, else raw 2 B/elem pages) across
    the per-chip DCN share. Matches
    ``kernels.kv_ship.ship_wire_bytes`` by construction."""
    from triton_distributed_tpu.kernels.kv_ship import ship_wire_bytes

    spec = spec or detect_spec()
    return (ship_wire_bytes(n_pages, page, hkv, d, n_layers, quant)
            / (spec.dcn_gbps * 1e9) * 1e3)


def migrate_vs_reprefill_ms(n_pages: int, *, page: int, hkv: int, g: int,
                            d: int, hidden: int, n_layers: int = 1,
                            chunk: int = 16, quant: bool = True,
                            spec: TpuSpec | None = None,
                            issue_ms: float | None = None) -> tuple:
    """Price a cross-replica KV-page migration against recomputing the
    same prefix at the new home. Returns ``(migrate_ms, reprefill_ms)``:
    the DCN wire time of shipping ``n_pages`` in native quantized pool
    form (:func:`kv_ship_ms` — the bytes never widen) vs the chunked
    prefill steps that would rebuild the same ``n_pages · page`` tokens
    from scratch (:func:`ragged_serving_step_ms` per chunk, each chunk
    attending everything already rebuilt). The fleet migrates only when
    the wire beats the recompute — long committed prefixes ship, short
    ones re-prefill, and the crossover moves with ``dcn_gbps`` exactly
    like the disaggregation gate's."""
    spec = spec or detect_spec()
    migrate = kv_ship_ms(n_pages, page, hkv, d, n_layers, quant, spec)
    tokens = n_pages * page
    reprefill, done = 0.0, 0
    while done < tokens:
        take = min(chunk, tokens - done)
        done += take
        reprefill += ragged_serving_step_ms(
            [done], [take], page=page, hkv=hkv, g=g, d=d, hidden=hidden,
            n_layers=n_layers, spec=spec, quant=quant, issue_ms=issue_ms)
    return migrate, reprefill


def refuse_disaggregation(model_cfg, page: int, traffic: dict,
                          spec: TpuSpec | None = None, *,
                          ledger=None) -> str | None:
    """The `auto` placement gate: None when the expected per-request KV
    ship hides under the decode window it buys, else a human-readable
    refusal reason. ``traffic``: expected request shape —
    ``prompt_len`` (tokens whose pages ship) and ``max_new`` (decode
    steps the ship can overlap with); optional ``decode_step_ms``
    overrides the analytic steady-step estimate. ``spec_k`` (plus
    optional ``spec_acceptance``) prices speculative decode on the
    decode role: each verify step costs a little more
    (:func:`spec_step_ms`) but emits
    :func:`expected_accepted_per_step` tokens, so the request's decode
    WINDOW shrinks — a ship that hid under ``max_new`` plain steps may
    not hide under ``max_new / accepted`` verify steps, and the gate
    must refuse what speculation made unviable. ``ledger`` (a
    ``runtime.health.HealthLedger``) adds the health gate: a split
    topology is refused while a slice is condemned or the kv_ship wire
    itself is unhealthy — placement consults health, not just perf."""
    if ledger is not None:
        bad_slices = ledger.unhealthy_slices()
        if bad_slices:
            return (
                f"health ledger marks slice(s) {bad_slices} unhealthy — "
                "a split topology cannot place a role on a condemned "
                "slice"
            )
        from triton_distributed_tpu.runtime.health import PeerState

        if ledger.state("site:kv_ship") is PeerState.UNHEALTHY:
            return (
                "health ledger marks the kv_ship wire unhealthy — the "
                "split topology's transport is the thing that is broken"
            )
    spec = spec or detect_spec()
    prompt = int(traffic.get("prompt_len", 1024))
    max_new = int(traffic.get("max_new", 32))
    hkv = model_cfg.n_kv_heads
    d = model_cfg.head_dim
    quant = getattr(model_cfg, "kv_quant", None) is not None
    n_pages = max(-(-prompt // page), 1)
    ship = kv_ship_ms(
        n_pages, page, hkv, d, model_cfg.n_layers, quant, spec
    )
    spec_k = int(traffic.get("spec_k", 0))
    accepted = 1.0
    g = model_cfg.n_heads // max(hkv, 1)
    step_ms = traffic.get("decode_step_ms")
    if step_ms is None:
        if spec_k:
            step_ms = spec_step_ms(
                [prompt], spec_k=spec_k, page=page, hkv=hkv, g=g, d=d,
                hidden=model_cfg.hidden, n_layers=model_cfg.n_layers,
                spec=spec, quant=quant,
            )
        else:
            step_ms = ragged_serving_step_ms(
                [prompt], [1], page=page, hkv=hkv, g=g, d=d,
                hidden=model_cfg.hidden, n_layers=model_cfg.n_layers,
                spec=spec, quant=quant,
            )
    if spec_k:
        # a measured decode_step_ms is taken as the verify-step cost as
        # given (measurements outrank the analytic widening); the
        # window still shrinks by the emission rate
        accepted = expected_accepted_per_step(
            spec_k, float(traffic.get("spec_acceptance",
                                      DEFAULT_SPEC_ACCEPTANCE)))
    n_steps = max_new / accepted
    window = n_steps * float(step_ms)
    if ship <= window:
        return None
    spec_note = (
        f" (speculative decode spec_k={spec_k} emits {accepted:.2f} "
        f"tokens/step — the window shrank to {n_steps:.1f} steps)"
        if spec_k else ""
    )
    return (
        f"kv_ship_ms={ship:.3f} exceeds the decode window "
        f"{window:.3f} ms ({n_steps:.1f} steps x {float(step_ms):.3f} "
        f"ms){spec_note} — "
        f"shipping {n_pages} pages over {spec.dcn_gbps} GB/s DCN "
        "dominates the decode work it buys; keep prefill and decode "
        "colocated for this traffic"
    )


# --------------------------------------- context-parallel decode term
#
# Long-context serving shards one request's page walk across a cp axis
# (kernels/ragged_paged_attention.py TOPO_CP + the cp_decode.lse_combine
# ring): each rank reads only its ~1/cp share of the KV pages, then the
# per-rank (out, lse) partials merge over a cp-1-hop ring. The walk
# term shrinks by cp while the combine term is kv-length-INDEPENDENT,
# so long contexts win and short ones pay a fixed hop tax — these
# terms price that crossover so the fleet router can place long
# requests (and refuse them with numbers) before any hardware run.

def cp_decode_step_ms(kv_len: int, *, cp: int, page: int, hkv: int,
                      g: int, d: int, hidden: int, n_layers: int = 1,
                      spec: TpuSpec | None = None, quant: bool = True,
                      issue_ms: float | None = None) -> float:
    """Per-step decode cost of ONE ``kv_len``-token request on a
    ``cp``-sharded replica: the per-rank ragged walk over
    ``ceil(kv_len/cp)`` tokens (ranks walk their shards concurrently —
    the step pays the slowest, which under an even split is the 1/cp
    share) plus the cross-rank LSE-combine ring — ``cp-1`` sequential
    hops of the f32 ``(out, lse)`` partial slab per layer
    (:func:`hop_critical_path_ms`; hops on one delivery chain cannot
    overlap). ``cp=1`` degenerates to the single-slice walk exactly."""
    spec = spec or detect_spec()
    cp = max(int(cp), 1)
    local = max(-(-int(kv_len) // cp), 1)
    walk = ragged_serving_step_ms(
        [local], [1], page=page, hkv=hkv, g=g, d=d, hidden=hidden,
        n_layers=n_layers, spec=spec, quant=quant, issue_ms=issue_ms)
    if cp == 1:
        return walk
    slab = 4 * hkv * g * (d + 1)       # one row's f32 (out, lse) partial
    combine = n_layers * hop_critical_path_ms(cp - 1, slab, spec)
    return walk + combine


def refuse_long_context(model_cfg, page: int, need_pages: int, *,
                        pool_pages: int, pages_per_seq: int,
                        cp: int = 1,
                        spec: TpuSpec | None = None) -> str | None:
    """The long-context placement gate (the
    :func:`refuse_disaggregation` shape): None when ``need_pages`` —
    the request's END-TO-END KV, prompt plus every token it may
    generate — fits this replica's page pool AND its per-slot table
    width; else the priced refusal reason. Unlike an overload bounce,
    no retry-after can make pool capacity appear, so the reason names
    the missing capability and its price: the cp factor that WOULD
    hold the request and the modeled per-step cost of serving it there
    (:func:`cp_decode_step_ms` — the sharded walk plus the LSE-combine
    ring) against the single-slice HBM walk it replaces."""
    need = int(need_pages)
    cap = min(int(pool_pages), int(pages_per_seq))
    if need <= cap:
        return None
    spec = spec or detect_spec()
    hkv = model_cfg.n_kv_heads
    g = model_cfg.n_heads // max(hkv, 1)
    d = model_cfg.head_dim
    quant = getattr(model_cfg, "kv_quant", None) is not None
    kv = need * page
    # the smallest cp multiple of THIS replica's per-shard capacity
    # that holds the request (its shards are the fleet's pool unit)
    shard_cap = max(cap // max(int(cp), 1), 1)
    want_cp = max(-(-need // shard_cap), 2)
    cp_ms = cp_decode_step_ms(
        kv, cp=want_cp, page=page, hkv=hkv, g=g, d=d,
        hidden=model_cfg.hidden, n_layers=model_cfg.n_layers,
        spec=spec, quant=quant)
    flat_ms = ragged_serving_step_ms(
        [kv], [1], page=page, hkv=hkv, g=g, d=d,
        hidden=model_cfg.hidden, n_layers=model_cfg.n_layers,
        spec=spec, quant=quant)
    return (
        f"request needs {need} KV pages but this replica holds "
        f"{cap} (cp={max(int(cp), 1)}) — a cp={want_cp} replica would "
        f"serve it at ~{cp_ms:.3f} ms/step (sharded walk + "
        f"{want_cp - 1}-hop LSE-combine ring) vs the {flat_ms:.3f} ms "
        "single-slice HBM walk it replaces; route long contexts to a "
        "cp-capable replica"
    )
