"""Continuous-batching serving runtime on the ragged paged-attention
kernel: the explicit :class:`ServingState` (page pools + block table +
per-request cursors, donated and shard-resident) and the
:class:`ServingEngine` request scheduler (admission and eviction over
the page pool, chunked prefill interleaved into decode batches), with
:class:`ServingFleet` aggregating N engine replicas behind the health-
and cache-aware :class:`FleetRouter`. :class:`SpeculativeEngine` adds
draft-k speculative decoding as a ragged-batch scenario (verify pass =
one ``q_len=k+1`` row, token-exact accept via the request-keyed
sampler); ``spec_tree`` + :class:`TreeDrafter` pack a branchy draft
TREE into that row under the kernel's per-row topology operand.

See docs/SERVING.md for the lifecycle and knob catalog.
"""

from triton_distributed_tpu.serving.engine import (  # noqa: F401
    TIERS,
    DisaggregatedEngine,
    DisaggStats,
    EngineConfig,
    EngineStats,
    Request,
    ServingEngine,
    TenantConfig,
    effective_rank,
    poisson_trace,
    tier_rank,
)
from triton_distributed_tpu.serving.fleet import (  # noqa: F401
    BROWNOUT_LEVELS,
    FLEET_ENGINE_FAMILIES,
    MIGRATION_ENGINE_FAMILIES,
    AutoscalerConfig,
    BrownoutConfig,
    BrownoutController,
    FleetAutoscaler,
    FleetRouter,
    FleetStats,
    Replica,
    RouterConfig,
    ServingFleet,
)
from triton_distributed_tpu.serving.spec import (  # noqa: F401
    SPEC_ENGINE_FAMILIES,
    DraftModelDrafter,
    Drafter,
    NGramDrafter,
    SpeculativeEngine,
    TreeDrafter,
    make_drafter,
)
from triton_distributed_tpu.serving.state import (  # noqa: F401
    PagePool,
    ServingState,
)
