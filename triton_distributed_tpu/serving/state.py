"""ServingState: the explicit, donated, page-table-addressed decode state.

Before this module, the serving decode state was an ad-hoc tuple spread
across call sites: per-layer ``(k, v)`` cache tuples from
``init_paged_cache``/``paginate_caches``, a separate block table, and a
separate ``kv_lens`` vector, each threaded (and donated) individually.
The continuous-batching engine needs them as ONE object with one
placement story:

* **page pools** per layer — ``(npages, Hkv, page, D)`` (int8
  ``{"q","scale"}`` dicts under ``kv_quant``), sharded over the KV-HEAD
  dim on the tp axis. Head sharding (not the decode path's sequence
  sharding) is the serving layout: GQA heads are independent, so ranks
  never exchange LSE partials, and a request's pages live wholly in the
  shared pool — any rank can serve any mix of requests, which is what
  admission/eviction over one free list requires.
* **block table** ``(slots, pages_per_seq)`` int32 — pool page ids per
  request slot, replicated (it is scheduler metadata, bytes-tiny).
* **kv_lens** ``(slots,)`` int32 — per-slot lengths *including* the
  step currently in flight (the ragged kernel attends append-then-
  attend).
* **cursors** ``(slots,)`` int32 — per-request progress (prompt tokens
  consumed + tokens generated); the device-side mirror of the
  scheduler's cursor so an evicted request's resume point travels with
  the state object.

The object is a pytree (``jax.tree_util``): the serving-step jit
donates it whole, and with the pool placements pinned the per-step
append aliases in place — no pool-sized copy per step.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as _dc_replace

import jax
import numpy as np


@dataclass(frozen=True)
class ServingState:
    """One engine's device-resident serving state (see module docs)."""

    layers: tuple       # per-layer (k_pool, v_pool); dicts under kv_quant
    block_table: object  # (slots, pages_per_seq) int32
    kv_lens: object      # (slots,) int32 — includes the in-flight step
    cursors: object      # (slots,) int32
    page: int = 0        # static: rows per page

    def replace(self, **kw) -> "ServingState":
        return _dc_replace(self, **kw)

    @property
    def slots(self) -> int:
        return int(self.block_table.shape[0])

    @property
    def pages_per_seq(self) -> int:
        return int(self.block_table.shape[1])

    @property
    def npages(self) -> int:
        k0 = self.layers[0][0]
        return int((k0["q"] if isinstance(k0, dict) else k0).shape[0])

    @property
    def capacity(self) -> int:
        """Max sequence positions one slot can hold."""
        return self.pages_per_seq * self.page


def _flatten(s: ServingState):
    return (
        (s.layers, s.block_table, s.kv_lens, s.cursors),
        (s.page,),
    )


def _unflatten(aux, children):
    layers, table, lens, cursors = children
    return ServingState(
        layers=layers, block_table=table, kv_lens=lens, cursors=cursors,
        page=aux[0],
    )


jax.tree_util.register_pytree_node(ServingState, _flatten, _unflatten)


def fresh_table(slots: int, pages_per_seq: int) -> np.ndarray:
    """Host-side table template (-1 = unallocated; device consumers
    clamp, the allocator never reads a -1 back)."""
    return np.full((slots, pages_per_seq), -1, np.int32)
