"""ServingState: the explicit, donated, page-table-addressed decode state.

Before this module, the serving decode state was an ad-hoc tuple spread
across call sites: per-layer ``(k, v)`` cache tuples from
``init_paged_cache``/``paginate_caches``, a separate block table, and a
separate ``kv_lens`` vector, each threaded (and donated) individually.
The continuous-batching engine needs them as ONE object with one
placement story:

* **page pools** per layer — ``(npages, Hkv, page, D)`` (int8
  ``{"q","scale"}`` dicts under ``kv_quant``), sharded over the KV-HEAD
  dim on the tp axis. Head sharding (not the decode path's sequence
  sharding) is the serving layout: GQA heads are independent, so ranks
  never exchange LSE partials, and a request's pages live wholly in the
  shared pool — any rank can serve any mix of requests, which is what
  admission/eviction over one free list requires.
* **block table** ``(slots, pages_per_seq)`` int32 — pool page ids per
  request slot, replicated (it is scheduler metadata, bytes-tiny).
* **kv_lens** ``(slots,)`` int32 — per-slot lengths *including* the
  step currently in flight (the ragged kernel attends append-then-
  attend).
* **cursors** ``(slots,)`` int32 — per-request progress (prompt tokens
  consumed + tokens generated); the device-side mirror of the
  scheduler's cursor so an evicted request's resume point travels with
  the state object.

The object is a pytree (``jax.tree_util``): the serving-step jit
donates it whole, and with the pool placements pinned the per-step
append aliases in place — no pool-sized copy per step.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, replace as _dc_replace

import jax
import numpy as np


@dataclass(frozen=True)
class ServingState:
    """One engine's device-resident serving state (see module docs)."""

    layers: tuple       # per-layer (k_pool, v_pool); dicts under kv_quant
    block_table: object  # (slots, pages_per_seq) int32
    kv_lens: object      # (slots,) int32 — includes the in-flight step
    cursors: object      # (slots,) int32
    page: int = 0        # static: rows per page
    # static: context-parallel shards of the pool. Under cp > 1 the
    # pool rows are ONE stacked allocation of cp per-shard pools (shard
    # r owns global page ids [r·npages/cp, (r+1)·npages/cp)) and the
    # block-table columns split the same way: logical page index p of a
    # sequence lives in shard min(p // (pages_per_seq/cp), cp-1), so a
    # long request's KV spreads over every shard while the table keeps
    # GLOBAL ids and the scatter-append stays shard-oblivious.
    cp: int = 1

    def replace(self, **kw) -> "ServingState":
        return _dc_replace(self, **kw)

    @property
    def slots(self) -> int:
        return int(self.block_table.shape[0])

    @property
    def pages_per_seq(self) -> int:
        return int(self.block_table.shape[1])

    @property
    def pages_per_shard(self) -> int:
        """Block-table columns owned by one cp shard."""
        return self.pages_per_seq // max(self.cp, 1)

    @property
    def npages(self) -> int:
        k0 = self.layers[0][0]
        return int((k0["q"] if isinstance(k0, dict) else k0).shape[0])

    @property
    def capacity(self) -> int:
        """Max sequence positions one slot can hold."""
        return self.pages_per_seq * self.page


def _flatten(s: ServingState):
    return (
        (s.layers, s.block_table, s.kv_lens, s.cursors),
        (s.page, s.cp),
    )


def _unflatten(aux, children):
    layers, table, lens, cursors = children
    return ServingState(
        layers=layers, block_table=table, kv_lens=lens, cursors=cursors,
        page=aux[0], cp=aux[1],
    )


jax.tree_util.register_pytree_node(ServingState, _flatten, _unflatten)


def fresh_table(slots: int, pages_per_seq: int) -> np.ndarray:
    """Host-side table template (-1 = unallocated; device consumers
    clamp, the allocator never reads a -1 back)."""
    return np.full((slots, pages_per_seq), -1, np.int32)


class PagePool:
    """Host-side page allocator with PER-PAGE REFCOUNTS and an optional
    prefix cache (the PR-6 follow-on the block tables already made
    expressible).

    Three page states:

    * **free** — on the free list, content garbage;
    * **held** — ``refs[pg] >= 1``: referenced by that many block-table
      rows (shared-prefix pages are held by several slots at once; the
      engine's eviction *decrements* instead of freeing);
    * **cached** — ``refs[pg] == 0`` but the page is registered in the
      prefix cache: its KV content (a pure function of the token prefix
      it froze under — the chain hash) stays resident so a re-admitted
      evicted request, or a new request sharing the prefix, can reattach
      it instead of recomputing. Cached pages are *reclaimable*: when
      the free list runs dry the least-recently-released cached page is
      unregistered and reused, so the cache never shrinks the pool.

    Only FULL pages are ever registered (a page's content is frozen the
    moment the owning request's cursor crosses its end — nothing writes
    a page below the cursor), so a cached page's bytes can never change
    while it sits in the cache.
    """

    def __init__(self, npages: int, page: int, *, prefix_cache: bool = False):
        self.npages = int(npages)
        self.page = int(page)
        self.prefix_cache = bool(prefix_cache)
        self.refs = np.zeros((npages,), np.int32)
        self.free: list = list(range(npages - 1, -1, -1))
        self._by_hash: dict = {}              # chain hash -> page id
        self._hash_of: dict = {}              # page id -> chain hash
        self._reclaim: OrderedDict = OrderedDict()   # refcount-0 cached, LRU

    @property
    def available(self) -> int:
        """Pages an allocation may claim: free + reclaimable-cached."""
        return len(self.free) + len(self._reclaim)

    @property
    def held_pages(self) -> int:
        """Pages some block-table row still references (refs >= 1).
        On an IDLE engine this must be 0 — anything else is a leak
        (the preemption/eviction invariant the multi-tenant chaos
        matrix pins: ``pool.held_pages == 0`` once every stream has
        completed, whatever was preempted mid-draft on the way)."""
        return int((self.refs >= 1).sum())

    def alloc(self, idx: int | None = None) -> int | None:
        """Claim one page (refcount 1), reclaiming the LRU cached page
        when the free list is dry. None when genuinely exhausted.
        ``idx`` — the logical page index within the owning sequence —
        is the cp routing key; a flat pool ignores it."""
        del idx
        if self.free:
            pg = self.free.pop()
        elif self._reclaim:
            pg, _ = self._reclaim.popitem(last=False)
            h = self._hash_of.pop(pg)
            if self._by_hash.get(h) == pg:
                del self._by_hash[h]
        else:
            return None
        assert self.refs[pg] == 0, (pg, self.refs[pg])
        self.refs[pg] = 1
        return pg

    def retain(self, pg: int) -> None:
        """One more block-table row references ``pg`` (prefix share, or
        resurrection of a cached page)."""
        if pg in self._reclaim:
            del self._reclaim[pg]
        self.refs[pg] += 1

    def release(self, pg: int) -> None:
        """Drop one reference; the page frees (or parks in the cache)
        only when the LAST reference drops — shared-prefix pages survive
        their co-holders' evictions."""
        assert self.refs[pg] >= 1, (pg, self.refs[pg])
        self.refs[pg] -= 1
        if self.refs[pg] == 0:
            if pg in self._hash_of:
                self._reclaim[pg] = None
            else:
                self.free.append(pg)

    def register(self, pg: int, chain_hash) -> None:
        """Publish a FROZEN full page under its prefix-chain hash. First
        registration wins; a second page with identical content simply
        stays private (no post-hoc dedup — the bytes are already paid)."""
        if not self.prefix_cache or chain_hash in self._by_hash:
            return
        self._by_hash[chain_hash] = pg
        self._hash_of[pg] = chain_hash

    def lookup(self, chain_hash, idx: int | None = None) -> int | None:
        """The resident page holding this prefix page, or None. ``idx``
        routes the probe to the owning cp shard; a flat pool ignores
        it."""
        del idx
        return self._by_hash.get(chain_hash)

    def can_hold(self, held: int, need: int) -> bool:
        """Whether growing a sequence from ``held`` to ``need`` pages
        can be satisfied — the allocation gate the protocol's ``alloc``
        verb asks before claiming anything (a cp pool answers per
        owning shard; a flat pool is a simple headroom check)."""
        return need - held <= self.available

    def clone(self) -> "PagePool":
        """Deep-copy the allocator state (servlint world forking)."""
        q = PagePool.__new__(PagePool)
        q.npages = self.npages
        q.page = self.page
        q.prefix_cache = self.prefix_cache
        q.refs = self.refs.copy()
        q.free = list(self.free)
        q._by_hash = dict(self._by_hash)
        q._hash_of = dict(self._hash_of)
        q._reclaim = OrderedDict(self._reclaim)
        return q


class CpPagePool:
    """Context-parallel page allocator: ``cp`` per-shard
    :class:`PagePool` instances behind ONE global page-id namespace.

    Shard ``s`` owns global page ids ``[s·npages_shard,
    (s+1)·npages_shard)`` — the same rows of the stacked device pool —
    and logical page index ``idx`` of any sequence is owned by shard
    ``min(idx // pages_per_shard, cp-1)``, mirroring the block-table
    column split. Appends therefore always land on the owning shard
    (``alloc`` routes by ``idx``), releases route by the global id's
    shard, and the prefix cache registers/looks up within the owning
    shard (a prefix page at logical index p re-attaches on the shard
    that held it — position determines owner, so the probe is exact).

    The combined read-only views (``refs``/``free``/``_reclaim``/
    ``_hash_of``/``_by_hash``, all in GLOBAL ids) exist for the
    invariant checkers (servlint SV001/SV002 and the engine's leak
    asserts), which see one coherent allocator regardless of cp.
    """

    def __init__(self, cp: int, npages: int, page: int,
                 pages_per_shard: int, *, prefix_cache: bool = False):
        assert cp >= 2, cp
        self.cp = int(cp)
        self.npages_shard = int(npages)
        self.npages = int(cp) * int(npages)     # TOTAL pages
        self.page = int(page)
        self.pages_per_shard = int(pages_per_shard)
        self.prefix_cache = bool(prefix_cache)
        self.shards = tuple(
            PagePool(npages, page, prefix_cache=prefix_cache)
            for _ in range(self.cp)
        )

    # ---- routing

    def owner_of(self, idx: int) -> int:
        """Logical page index within a sequence → owning shard."""
        return min(int(idx) // self.pages_per_shard, self.cp - 1)

    def shard_of(self, pg: int) -> int:
        """Global page id → owning shard."""
        return int(pg) // self.npages_shard

    # ---- combined views (global ids)

    @property
    def refs(self):
        return np.concatenate([s.refs for s in self.shards])

    @property
    def free(self) -> list:
        return [
            i * self.npages_shard + lp
            for i, s in enumerate(self.shards) for lp in s.free
        ]

    @property
    def _reclaim(self) -> OrderedDict:
        out = OrderedDict()
        for i, s in enumerate(self.shards):
            for lp in s._reclaim:
                out[i * self.npages_shard + lp] = None
        return out

    @property
    def _hash_of(self) -> dict:
        return {
            i * self.npages_shard + lp: h
            for i, s in enumerate(self.shards)
            for lp, h in s._hash_of.items()
        }

    @property
    def _by_hash(self) -> dict:
        return {
            h: i * self.npages_shard + lp
            for i, s in enumerate(self.shards)
            for h, lp in s._by_hash.items()
        }

    @property
    def available(self) -> int:
        """Total claimable pages across shards — an UPPER bound for any
        one sequence (growth routes to owners; :meth:`can_hold` is the
        exact per-shard gate)."""
        return sum(s.available for s in self.shards)

    @property
    def held_pages(self) -> int:
        return sum(s.held_pages for s in self.shards)

    # ---- allocator verbs

    def alloc(self, idx: int | None = None) -> int | None:
        """Claim one page ON THE SHARD OWNING logical index ``idx``
        (None routes to shard 0 — only correct for idx-agnostic
        callers that never coexist with cp, asserted away)."""
        assert idx is not None, "cp pool allocation needs the page index"
        s = self.owner_of(idx)
        lp = self.shards[s].alloc()
        return None if lp is None else s * self.npages_shard + lp

    def retain(self, pg: int) -> None:
        s = self.shard_of(pg)
        self.shards[s].retain(pg - s * self.npages_shard)

    def release(self, pg: int) -> None:
        s = self.shard_of(pg)
        self.shards[s].release(pg - s * self.npages_shard)

    def register(self, pg: int, chain_hash) -> None:
        s = self.shard_of(pg)
        self.shards[s].register(pg - s * self.npages_shard, chain_hash)

    def lookup(self, chain_hash, idx: int | None = None) -> int | None:
        assert idx is not None, "cp pool lookup needs the page index"
        s = self.owner_of(idx)
        lp = self.shards[s].lookup(chain_hash)
        return None if lp is None else s * self.npages_shard + lp

    def can_hold(self, held: int, need: int) -> bool:
        """Exact per-shard gate: pages ``held..need-1`` route to their
        owners; every owner must have the headroom."""
        want = [0] * self.cp
        for p in range(held, need):
            want[self.owner_of(p)] += 1
        return all(
            w <= s.available for w, s in zip(want, self.shards)
        )

    def clone(self) -> "CpPagePool":
        q = CpPagePool.__new__(CpPagePool)
        q.cp = self.cp
        q.npages_shard = self.npages_shard
        q.npages = self.npages
        q.page = self.page
        q.pages_per_shard = self.pages_per_shard
        q.prefix_cache = self.prefix_cache
        q.shards = tuple(s.clone() for s in self.shards)
        return q


def page_chain_hash(prev_hash, tokens) -> int:
    """The prefix-cache key of one FULL page: chains the previous
    page's hash with this page's token ids. KV content of page ``p`` is
    a function of the ENTIRE prefix up to its end (attention mixes every
    earlier token into the residual stream), which is exactly what the
    chain covers."""
    return hash((prev_hash, tuple(int(t) for t in tokens)))
