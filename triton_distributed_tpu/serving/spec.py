"""Speculative decoding as a ragged-batch scenario.

Decode pays a full model step per emitted token; the ragged
paged-attention kernel already runs mixed ``q_len`` rows in one launch,
so the verify pass of draft-k speculation is literally a ``q_len=k+1``
row in the normal mixed batch — no new kernel (the Ragged Paged
Attention paper's stated point).

The pieces:

``Drafter``
    Proposes up to ``k`` provisional next tokens for one request from
    information the engine already holds. Implementations must be
    DETERMINISTIC functions of the request's token history — the accept
    rule below only preserves token-exactness because a draft can never
    inject randomness into the stream (wrong drafts are rejected, right
    drafts emit exactly what the keyed sampler would have drawn anyway).

``NGramDrafter``
    Prompt-lookup drafting: match the longest recent n-gram suffix of
    the request's own token history (prompt + generated) against an
    earlier occurrence and propose its continuation. Free (no extra
    model weights, no device work) and strong on motif-heavy traffic.

``DraftModelDrafter``
    A shared-weights TRUNCATED-DEPTH draft model: the target's own
    embedding, first ``depth`` decoder blocks, final norm and lm_head
    (parameter views — no second checkpoint) run as a real greedy
    autoregressive forward. Acceptance now measures how much of the
    target the early layers already determine, which is what makes
    acceptance rates and the adaptive-k budget meaningful.

``SpeculativeEngine``
    A :class:`~triton_distributed_tpu.serving.engine.ServingEngine`
    mode. Steady decode rows (one remaining sequence token) are widened
    to ``[frontier, d_1 .. d_k]`` — the drafts are appended as
    PROVISIONAL page content, verified by the same jitted step as every
    other row (the all-positions-logits twin), and accepted via the
    request-keyed sampler draws:

    for ``j = 0..nd``: sample ``t_j`` from the logits at packed index
    ``q_starts[s] + j`` with the request's draw key
    ``(seed, rid, n0 + j)`` (``n0`` = tokens generated before the
    step); emit ``t_j``; accept draft ``j+1`` iff ``t_j == d_{j+1}``,
    else stop — ``t_j`` is the correction. All drafts accepted → the
    last draw is the bonus token. Because the engine's sampler draws
    are deterministic keyed functions of (seed, rid, position), this
    exact-match rule IS the rejection-sampling identity: every emitted
    token is byte-identical to what the non-speculative engine would
    have produced at that position, so streams stay token-exact across
    chunking, eviction, tp sharding and disaggregation.

    Rejected drafts roll back through the recompute-eviction
    discipline: the cursor rewinds to the surviving prefix and pages
    past it return to the pool. KV above the cursor is garbage the
    same way post-eviction pool pages are — ``kv_lens`` is recomputed
    from host cursors every step, so it is never attended and is
    overwritten by the next append.
"""
from __future__ import annotations

import numpy as np

from triton_distributed_tpu.serving.engine import ServingEngine

# kernel families the speculative engine launches — identical to the
# plain engine's (the verify pass is the SAME ragged kernel; that is
# the point). bench --lint gates that each resolves a degradation
# target so a speculative fleet degrades exactly like a plain one.
SPEC_ENGINE_FAMILIES = ("flash_decode.ragged_paged",)


# ===================================================================
# Drafters
# ===================================================================

class Drafter:
    """Proposes provisional next tokens for one request.

    Contract: ``draft(req, k)`` returns an ``int32`` array of length
    ``<= k`` (empty is always legal — the row degrades to a plain
    decode step). The result must be a deterministic pure function of
    ``req.seq`` (prompt + generated so far): no RNG, no mutable state
    that scheduling order could perturb. ``observe`` is optional
    feedback (accepted/rejected counts) for adaptive drafters; the
    built-ins ignore it."""

    name = "null"

    def draft(self, req, k: int) -> np.ndarray:
        raise NotImplementedError

    def observe(self, req, accepted: int, rejected: int) -> None:
        pass


class NGramDrafter(Drafter):
    """Prompt-lookup drafting over the request's OWN token history.

    Matches the longest suffix n-gram (``max_ngram`` down to
    ``min_ngram``) of ``req.seq`` against its most recent earlier
    occurrence and proposes the tokens that followed it. Rightmost
    match wins — recency beats primacy on repetitive traffic, and the
    tie-break keeps the proposal deterministic."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError((min_ngram, max_ngram))
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, req, k: int) -> np.ndarray:
        seq = [int(t) for t in req.seq]
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(seq) <= n:
                continue
            tail = seq[-n:]
            for i in range(len(seq) - n - 1, -1, -1):
                if seq[i:i + n] == tail:
                    cont = seq[i + n:i + n + k]
                    if cont:
                        return np.asarray(cont, np.int32)
                    break               # suffix matched itself only
        return np.zeros((0,), np.int32)


class TreeDrafter(NGramDrafter):
    """Tree drafting over the request's OWN token history — the
    Medusa-style multi-path proposal the ragged kernel's TREE attention
    topology verifies in ONE row.

    The TRUNK is exactly :class:`NGramDrafter`'s proposal (the most
    recent matching continuation), packed first as a parent chain — so
    a tree row can never accept fewer trunk tokens than the linear
    drafter would have. DIVERGENT continuations from OLDER occurrences
    of the same suffix n-gram then graft sibling branches at their
    divergence points: where the history continues the motif more than
    one way, the tree hedges instead of committing, and the verify walk
    follows whichever child the keyed sample actually draws (the
    "sibling rescue" that beats linear draft-k on branchy traffic).

    ``draft_tree(req, budget)`` returns ``(tokens, parents)`` int32
    arrays of equal length ``<= budget``: ``tokens[i]`` is draft node
    ``i``'s token, ``parents[i]`` its parent NODE index (< i; -1 = the
    frontier). Trunk-first packing (node ``i`` of the trunk has parent
    ``i - 1``) is part of the contract — the engine rewinds the cursor
    to the accepted IN-PLACE prefix, and only trunk nodes sit at their
    true sequence offsets in the pool. Deterministic pure function of
    ``req.seq``, like every drafter."""

    name = "tree"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 branches: int = 2, branch_len: int = 2):
        super().__init__(max_ngram, min_ngram)
        if branches < 0 or branch_len < 1:
            raise ValueError((branches, branch_len))
        self.branches = branches
        self.branch_len = branch_len

    def _continuations(self, seq: list, k: int) -> list:
        """Continuations of the longest matched suffix n-gram, most
        recent occurrence first (the same scan order as
        :meth:`NGramDrafter.draft`, collecting every occurrence)."""
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(seq) <= n:
                continue
            tail = seq[-n:]
            conts = []
            for i in range(len(seq) - n - 1, -1, -1):
                if seq[i:i + n] == tail:
                    cont = seq[i + n:i + n + k]
                    if cont:
                        conts.append(cont)
            if conts:
                return conts
        return []

    def draft_tree(self, req, budget: int):
        empty = (np.zeros((0,), np.int32), np.zeros((0,), np.int32))
        if budget <= 0:
            return empty
        seq = [int(t) for t in req.seq]
        conts = self._continuations(seq, budget)
        if not conts:
            return empty
        trunk = conts[0][:budget]
        tokens = list(trunk)
        parents = [-1] + list(range(len(trunk) - 1))
        grafted = 0
        for cont in conts[1:]:
            if grafted >= self.branches or len(tokens) >= budget:
                break
            dv = next(
                (d for d in range(min(len(cont), len(trunk)))
                 if cont[d] != trunk[d]),
                None,
            )
            if dv is None:
                continue               # same path — nothing to hedge
            if any(parents[t] == dv - 1 and tokens[t] == cont[dv]
                   for t in range(len(tokens))):
                continue               # this sibling already exists
            par = dv - 1               # divergence hangs off trunk[dv-1]
            added = False
            for tok in cont[dv:dv + self.branch_len]:
                if len(tokens) >= budget:
                    break
                tokens.append(tok)
                parents.append(par)
                par = len(tokens) - 1
                added = True
            grafted += int(added)
        return (np.asarray(tokens, np.int32),
                np.asarray(parents, np.int32))


class DraftModelDrafter(Drafter):
    """A genuinely smaller shared-weights draft model: the target's own
    embedding, its FIRST ``depth`` decoder blocks, final norm and
    lm_head — all parameter VIEWS into the target checkpoint (shared
    embeddings, truncated depth; no second checkpoint shipped) — run as
    a real autoregressive forward. Drafting k tokens is k greedy steps
    of that truncated model, so acceptance tracks how much of the
    target's computation the early layers already determine (the
    adaptive-k budget then has a real signal to walk), instead of the
    fixed bigram table this class used to be.

    Sequences are right-padded to a ``BUCKET``-aligned length so the
    jitted forward compiles once per bucket, not per length; causal
    attention keeps the padding out of every position that is read.
    Deterministic pure function of ``req.seq`` — the drafter contract
    token-exactness rests on."""

    name = "draft_model"

    BUCKET = 16

    def __init__(self, model, params, depth: int | None = None):
        n = len(params["blocks"])
        if depth is None:
            depth = max(1, n // 2)
        if not 1 <= depth <= n:
            raise ValueError(
                f"draft depth must be in [1, {n}], got {depth}")
        self.depth = int(depth)
        self._model = model
        # views, not copies: the draft checkpoint IS the target's
        self._params = {
            "embed": params["embed"],
            "norm_f": params["norm_f"],
            "lm_head": params["lm_head"],
            "blocks": list(params["blocks"][:depth]),
        }
        self._fwd = None

    def _forward(self):
        if self._fwd is None:
            import jax

            self._fwd = jax.jit(self._model.forward)
        return self._fwd

    def _next_token(self, seq: list) -> int:
        ln = len(seq)
        pad = -(-ln // self.BUCKET) * self.BUCKET
        toks = np.zeros((1, pad), np.int32)
        toks[0, :ln] = seq
        logits = np.asarray(self._forward()(self._params, toks))
        return int(np.argmax(logits[ln - 1]))

    def draft(self, req, k: int) -> np.ndarray:
        seq = [int(t) for t in req.seq]
        out = []
        for _ in range(k):
            tok = self._next_token(seq)
            out.append(tok)
            seq.append(tok)
        return np.asarray(out, np.int32)


def make_drafter(kind: str, model=None, params=None, **kw) -> Drafter:
    """Build a drafter by name (``"ngram"`` / ``"tree"`` /
    ``"draft_model"``) — the bench/CI entry point. ``draft_model``
    accepts ``depth`` (the truncated layer count; default half the
    target's); ``tree`` accepts ``branches``/``branch_len``."""
    if kind == "ngram":
        return NGramDrafter(**kw)
    if kind == "tree":
        return TreeDrafter(**kw)
    if kind == "draft_model":
        if model is None or params is None:
            raise ValueError("draft_model drafter needs model + params")
        return DraftModelDrafter(model, params, **kw)
    raise ValueError(f"unknown drafter kind: {kind!r}")


# ===================================================================
# SpeculativeEngine
# ===================================================================

class SpeculativeEngine(ServingEngine):
    """:class:`ServingEngine` with draft-k speculative decode rows.

    Scheduling, admission, eviction, prefix caching, health/probation
    and degradation are all inherited untouched — speculation only
    changes what a steady decode row PACKS (``1 + k`` tokens instead of
    1) and how its logits are consumed (the verify/accept loop in
    :meth:`_advance_row`). With ``spec_k <= 7`` the widened row costs
    no extra packed budget: ``_ceil8(k+1) == _ceil8(1)``.

    ``spec_tree > 0`` switches steady decode rows to TREE verification:
    the drafter's ``draft_tree`` packs up to ``spec_tree`` nodes of a
    draft TREE into one verify row, the row carries a TREE attention-
    topology descriptor (kernels/ragged_paged_attention.py) so sibling
    branches never attend each other, and the accept walk descends the
    tree by the same request-keyed draws — each emitted token is the
    keyed sample at its PATH-conditioned distribution, so streams stay
    byte-identical to the plain engine while branchy traffic accepts
    more tokens per step than any single linear path could."""

    def __init__(self, model, params, cfg, *, drafter: Drafter | None = None,
                 spec_k: int = 4, spec_tree: int = 0,
                 adaptive_k: bool = False, **kw):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if spec_k + 1 > cfg.chunk:
            # the chunk bound sizes the kernel's block_q cap and the
            # packed array's parking zone — a verify row wider than a
            # prefill chunk would invalidate both
            raise ValueError(
                f"spec_k={spec_k} verify row exceeds chunk={cfg.chunk}")
        if spec_tree:
            from triton_distributed_tpu.kernels.ragged_paged_attention \
                import TOPO_MAX_NODES

            if spec_tree + 1 > cfg.chunk:
                raise ValueError(
                    f"spec_tree={spec_tree} verify row exceeds "
                    f"chunk={cfg.chunk}")
            if spec_tree + 1 > TOPO_MAX_NODES:
                raise ValueError(
                    f"spec_tree={spec_tree} exceeds the topology "
                    f"descriptor's {TOPO_MAX_NODES - 1}-node bound")
        self.spec_k = int(spec_k)
        self.spec_tree = int(spec_tree)
        # set before super().__init__: the traffic key (_spec_key) is
        # derived during the base constructor
        super().__init__(model, params, cfg, **kw)
        if drafter is None:
            drafter = TreeDrafter() if spec_tree else NGramDrafter()
        if spec_tree and not hasattr(drafter, "draft_tree"):
            raise ValueError(
                "spec_tree needs a drafter with draft_tree (TreeDrafter)")
        self.drafter = drafter
        # adaptive per-request draft budget: consume the observe()
        # feedback to walk each request's k inside [1, spec_k] — AIMD
        # over the verify outcomes (grow +1 on a clean sweep, shrink to
        # what the row actually earned on a rejection). A deterministic
        # pure function of the request's accept history, so two replays
        # of a trace budget identically; ``spec_k`` stays the admission
        # headroom bound (``_row_take_bound`` must assume the widest
        # row a request may ever pack).
        self.adaptive_k = bool(adaptive_k)
        self._req_k: dict = {}             # rid -> current draft budget
        # slot -> this step's proposed draft tail (cleared every
        # assembly: a deferred row's entry must not leak into a later
        # step where the slot packs something else)
        self._step_drafts: dict = {}
        # slot -> (tokens, parents) of this step's draft TREE (tree
        # mode only; cleared alongside _step_drafts)
        self._step_trees: dict = {}

    def _spec_key(self) -> tuple:
        # extends the engine's traffic-tuning key: a schedule searched
        # for draft-k=4 rows is the wrong answer for tree-packed rows
        return (self.spec_k, self.spec_tree)

    # ------------------------------------------------------- planning

    def _row_take_bound(self, req) -> int:
        take = super()._row_take_bound(req)
        if len(req.seq) - req.cursor == 1:
            # steady decode row: may widen by the draft budget —
            # admission headroom must assume the widest case
            take = min(1 + max(self.spec_k, self.spec_tree),
                       self.state.capacity - req.cursor)
        return take

    def _plan_row(self, req) -> np.ndarray:
        if len(req.seq) - req.cursor != 1:
            return super()._plan_row(req)     # prefill/chunk row
        if self.spec_tree:
            return self._plan_tree_row(req)
        # steady decode row: widen to [frontier, d_1 .. d_nd]. Drafting
        # past the request's remaining emission target is pure rollback
        # work, so nd is also capped by (max_new - generated - 1).
        budget = self.spec_k
        if self.adaptive_k:
            budget = self._req_k.setdefault(req.rid, self.spec_k)
            st = self.stats
            st.adaptive_k_rows[budget] = (
                st.adaptive_k_rows.get(budget, 0) + 1)
        if self.throttled_tiers:
            # brownout squeeze: a throttled tier drafts at most one
            # token — speculation's rollback work is the first compute
            # the fleet reclaims from batch traffic under overload
            pr = getattr(req, "priority", None)
            if pr is None:
                pr = self._tenant(req).priority
            if pr in self.throttled_tiers:
                budget = min(budget, 1)
        nd = min(budget,
                 self.state.capacity - (req.cursor + 1),
                 req.max_new - len(req.generated) - 1)
        drafts = (self.drafter.draft(req, nd) if nd > 0
                  else np.zeros((0,), np.int32))
        drafts = np.asarray(drafts, np.int32)[:max(nd, 0)]
        self._step_drafts[req.slot] = drafts
        return np.concatenate(
            [np.asarray(req.seq[req.cursor:], np.int32), drafts])

    def _plan_tree_row(self, req) -> np.ndarray:
        """Steady decode row, tree mode: pack [frontier, node_1 ..
        node_nd] where the nodes are a draft TREE in index order
        (``parents[t] < t``, ``-1`` = the frontier). The row's TREE
        topology descriptor (emitted by :meth:`_row_topology`) masks
        each node to attend only its root-to-node ancestry, so
        ``logits[base + t]`` is the PATH-conditioned next-token
        distribution — sibling branches never contaminate each other."""
        budget = self.spec_tree
        if self.throttled_tiers:
            pr = getattr(req, "priority", None)
            if pr is None:
                pr = self._tenant(req).priority
            if pr in self.throttled_tiers:
                budget = 1            # brownout: shed speculation first
        nd = min(budget,
                 self.state.capacity - (req.cursor + 1),
                 req.max_new - len(req.generated) - 1)
        if nd > 0:
            tokens, parents = self.drafter.draft_tree(req, nd)
            # parents[t] < t, so truncating the tail keeps a valid tree
            tokens = np.asarray(tokens, np.int32)[:nd]
            parents = np.asarray(parents, np.int32)[: len(tokens)]
        else:
            tokens = np.zeros((0,), np.int32)
            parents = np.zeros((0,), np.int32)
        self._step_trees[req.slot] = (tokens, parents)
        self._step_drafts[req.slot] = tokens
        return np.concatenate(
            [np.asarray(req.seq[req.cursor:], np.int32), tokens])

    def _row_topology(self, s: int, req, take: int):
        tree = self._step_trees.get(s)
        if tree is None or len(tree[0]) == 0:
            return None               # plain row stays CAUSAL
        from triton_distributed_tpu.kernels.ragged_paged_attention \
            import topo_width, tree_topology_row

        _, parents = tree
        return tree_topology_row(
            [int(p) for p in parents], topo_width(self._block_q_cap))

    def _assemble(self):
        self._step_drafts = {}
        self._step_trees = {}
        return super()._assemble()

    # ------------------------------------------------------- verify

    def _step_jit(self):
        # same batch contract, but logits at EVERY packed position —
        # the accept loop needs the next-token distribution after each
        # draft token, not just each slot's frontier
        return self.model._serving_all_logits_jit

    def _advance_row(self, s: int, req, take: int, logits,
                     q_starts, q_lens) -> tuple:
        drafts = self._step_drafts.get(s)
        base = int(q_starts[s])
        tree = self._step_trees.get(s)
        if tree is not None and len(tree[0]) > 0:
            return self._advance_tree_row(s, req, take, logits, base, tree)
        if drafts is None or len(drafts) == 0:
            # plain chunk/decode row — base bookkeeping, but the
            # frontier distribution lives at the row's LAST packed
            # index (logits here are per-token, not per-slot)
            self.ops.advance_cursor(self, s, req, take)
            if req.cursor == len(req.seq):
                tok = self._sample(logits[base + take - 1], req)
                req.generated.append(tok)
                self._maybe_complete(req, s)
                return 1, take - 1
            return 0, take
        # verify row: [frontier, d_1 .. d_nd] at positions
        # cursor .. cursor+nd. logits[base + j] is the next-token
        # distribution given seq[:cursor+1] + d_1..d_j — valid exactly
        # while every earlier draft was accepted, which is exactly how
        # far the loop below reads.
        nd = len(drafts)
        assert take == nd + 1, (take, nd)
        old_cursor = req.cursor
        emitted = accepted = 0
        for j in range(nd + 1):
            tok = self._sample(logits[base + j], req)
            req.generated.append(tok)
            emitted += 1
            if len(req.generated) >= req.max_new:
                break                  # stream length must match exactly
            if j < nd and tok == int(drafts[j]):
                accepted += 1          # draft j's provisional KV is real
                continue
            break                      # tok is the correction (j < nd)
            # ... or the bonus draw after a full accept (j == nd)
        # rollback: rewind to the surviving prefix and free the pages
        # the rejected tail claimed at assembly. Garbage KV above the
        # cursor is never attended (kv_lens is recomputed from host
        # cursors) and the next append overwrites it in place.
        self.ops.rollback_draft(self, s, req, old_cursor, take, accepted)
        st = self.stats
        st.spec_rows += 1
        st.draft_tokens += nd
        st.accepted_draft_tokens += accepted
        st.spec_tokens_out += emitted
        st.rolled_back_tokens += nd - accepted
        self.drafter.observe(req, accepted, nd - accepted)
        if self.adaptive_k:
            self._observe_k(req, accepted, nd - accepted, nd)
        self._maybe_complete(req, s)
        return emitted, 0

    def _advance_tree_row(self, s: int, req, take: int, logits,
                          base: int, tree) -> tuple:
        """Tree verify: walk the draft tree from the frontier, at each
        node drawing the request-keyed sample from that node's
        PATH-conditioned logits (the TREE mask guarantees position
        ``t+1`` attended exactly prefix + node ``t``'s ancestry).
        Accepting means descending to the child whose draft token
        matches the draw; the walk ends on a mismatch (the draw IS the
        correction) or at a leaf (the draw is the bonus token). Every
        draw keys on (seed, rid, generated-so-far) exactly as the plain
        engine's sequential draws would, so the stream is
        byte-identical to non-speculative decode.

        Only the leading IN-PLACE segment of the accepted path — nodes
        whose q position equals their linear packed position, i.e. the
        trunk — advances the cursor: off-trunk accepted tokens were
        written to the wrong pool offsets, so they are emitted into the
        stream now but re-packed (and their KV rewritten in place) as a
        chunk row next step."""
        tokens, parents = tree
        nd = len(tokens)
        assert take == nd + 1, (take, nd)
        old_cursor = req.cursor
        emitted = 0
        path = []                 # q positions of accepted nodes, root->leaf
        cur = 0                   # current q position (0 = frontier)
        while True:
            tok = self._sample(logits[base + cur], req)
            req.generated.append(tok)
            emitted += 1
            if len(req.generated) >= req.max_new:
                break             # stream length must match exactly
            nxt = -1
            for t in range(nd):   # child of cur whose draft matches the draw
                if int(parents[t]) + 1 == cur and int(tokens[t]) == tok:
                    nxt = t + 1
                    break
            if nxt < 0:
                break             # correction draw, or bonus past a leaf
            path.append(nxt)
            cur = nxt
        in_place = 0
        for i, qp in enumerate(path):
            if qp == i + 1:       # trunk: packed position == path position
                in_place += 1
            else:
                break
        self.ops.rollback_draft(self, s, req, old_cursor, take, in_place)
        st = self.stats
        st.spec_rows += 1
        st.draft_tokens += nd
        accepted = len(path)
        st.accepted_draft_tokens += accepted
        st.spec_tokens_out += emitted
        st.rolled_back_tokens += nd - in_place
        self.drafter.observe(req, accepted, nd - accepted)
        self._maybe_complete(req, s)
        return emitted, 0

    def _observe_k(self, req, accepted: int, rejected: int,
                   nd: int) -> None:
        """Walk the request's draft budget on one verify outcome:
        a clean sweep earns +1 (additive growth, capped at ``spec_k``),
        a rejection shrinks the budget to ``accepted + 1`` (what the
        row proved it could use, floor 1) — rejected drafts are pure
        rollback work, so the budget tracks the stream's measured
        compressibility instead of paying ``spec_k`` everywhere."""
        k = self._req_k.get(req.rid, self.spec_k)
        if rejected > 0:
            k = max(1, accepted + 1)
        elif nd > 0:
            k = min(self.spec_k, k + 1)
        self._req_k[req.rid] = k
