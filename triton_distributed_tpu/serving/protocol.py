"""ProtocolOps: the serving/fleet protocol's transition functions
behind one narrow seam.

Everything that moves a request or a page through the serving state
machine — admission, allocation, eviction, preemption, the
transactional reserve/commit/abort KV ship, speculative rollback, and
the fleet's failover/drain requeue discipline — lives here as a verb on
:class:`ProtocolOps`. The production engines delegate
(``ServingEngine``, ``DisaggregatedEngine``, ``SpeculativeEngine``,
``ServingFleet`` each hold an ``ops`` instance), so there is exactly
ONE implementation of each transition.

The point of the seam is :mod:`triton_distributed_tpu.analysis.
servlint`: the bounded model checker drives THESE verbs — the real
scheduling/pool logic, not a re-implementation — over an abstract
2-replica fleet, and its seeded true-positive fixtures are built by
subclassing :class:`ProtocolOps` with one deliberate bug per rule
(mutated ops through the production seam). Every verb is pure host
bookkeeping: numpy tables, the :class:`~triton_distributed_tpu.serving.
state.PagePool` refcounts, request fields and deques — no device work,
which is what makes exhaustive interleaving exploration affordable.

Behavior contract: each verb's body IS the pre-seam engine/fleet method
body (PR 19 moved them verbatim); the trace-equality pin in
tests/test_fleet.py holds ``FleetStats.events`` byte-identical across
the refactor.
"""

from __future__ import annotations

from collections import deque


class ProtocolOps:
    """The serving protocol's transition verbs. Engine-scoped verbs
    take the engine as their first argument (one stateless ops instance
    can serve every role engine of a deployment); fleet-scoped verbs
    take the pieces they move. Subclass and override a verb to build a
    deliberately-broken protocol for servlint's fixtures."""

    #: fixture metadata: the servlint rule a mutated subclass seeds
    #: (None on the production ops)
    seeds_rule: str | None = None

    # ---------------------------------------------------- page allocator

    def alloc(self, eng, slot: int, held: int, need: int) -> bool:
        """Grow ``slot``'s table from ``held`` to ``need`` pages;
        all-or-nothing (no partial growth to unwind). The logical page
        index rides into the pool so a cp-sharded pool can land each
        page on its owning shard (``can_hold`` is the matching exact
        per-shard gate; a flat pool degenerates both to the old
        headroom check)."""
        if not eng.pool.can_hold(held, need):
            return False
        for pg in range(held, need):
            eng.table[slot, pg] = eng.pool.alloc(pg)
        return True

    def free_slot(self, eng, slot: int) -> None:
        """Release the slot's page references — shared-prefix pages
        only truly free when their LAST holder lets go (the refcount
        discipline); privately-held pages return to the free list."""
        for pg in eng.table[slot]:
            if pg >= 0:
                eng.pool.release(int(pg))
        eng.table[slot] = -1
        eng.slot_req[slot] = None

    def ensure_pages(self, eng, slot: int, held: int, need: int,
                     batched: set) -> bool:
        """Batch assembly's allocation loop: claim the row's pages,
        evicting (priority-aware LIFO) until they fit or nothing
        evictable remains. False = the row defers this step."""
        while not self.alloc(eng, slot, held, need):
            if not self.evict_one(eng, batched | {slot}):
                return False
        return True

    # ------------------------------------------------ eviction/preemption

    def evict_one(self, eng, batched: set) -> bool:
        """Evict the lowest-tier, latest-arrived active request not
        already in this step's batch (priority-aware LIFO preemption);
        its pages return to the free list and the request re-queues AT
        THE FRONT with cursor 0 — the recompute prefix (prompt +
        generated) resumes it exactly. Parked requests (pages pinned by
        an in-flight KV ship) and already-completed holders are never
        victims."""
        victims = [
            (eng._rank(req), req.arrival, s)
            for s, req in enumerate(eng.slot_req)
            if req is not None and s not in batched
            and not req.parked and not req.done
        ]
        if not victims:
            return False
        _, _, s = max(victims)
        req = eng.slot_req[s]
        req.cursor = 0
        req.evictions += 1
        req.slot = None
        self.free_slot(eng, s)
        eng.waiting.appendleft(req)
        eng.stats.evictions += 1
        return True

    def preempt_for(self, eng, by_req) -> bool:
        """Priority preemption: evict the LOWEST-tier resident row
        strictly below ``by_req``'s effective rank through the
        recompute-eviction discipline (token-exact, cursor-resumable).
        False = no strictly-lower victim. Runs under the ``preempt``
        chaos site so a fault-plan Stall can wedge it visibly."""
        rank = eng._eff_rank(by_req)
        victims = [
            (eng._eff_rank(req), -int((eng.table[s] >= 0).sum()),
             req.arrival, s)
            for s, req in enumerate(eng.slot_req)
            if req is not None and not req.parked and not req.done
            and eng._eff_rank(req) > rank
        ]
        if not victims:
            return False
        from triton_distributed_tpu.lang.launch import maybe_instrument

        _, _, _, s = max(victims)

        def body():
            victim = eng.slot_req[s]
            victim.cursor = 0
            victim.evictions += 1
            victim.slot = None
            self.free_slot(eng, s)
            eng.waiting.append(victim)
            eng.stats.evictions += 1
            eng.stats.preemptions += 1
            t = getattr(victim, "tenant", "default")
            eng.stats.tenant_preemptions[t] = (
                eng.stats.tenant_preemptions.get(t, 0) + 1)
            if eng.on_preempt is not None:
                eng.on_preempt(by_req, victim)
            return True

        return maybe_instrument(
            body, axis=None, site="preempt",
            collective_id=("preempt", eng.step_count), n=1,
            step=eng.step_count,
        )()

    # ----------------------------------------------------------- admission

    def admit(self, eng) -> None:
        """Priority admission over the free slots: effective tier rank
        (tenant tier minus the aging bump), then FIFO, with preemption
        when a higher tier finds no slot or no page headroom and
        per-tenant fair-share deferrals."""
        while eng.pending and eng.pending[0].arrival <= eng.step_count:
            eng.waiting.append(eng.pending.popleft())
        if not eng.waiting:
            return
        eng.waiting = deque(sorted(
            eng.waiting,
            key=lambda r: (eng._eff_rank(r), r.arrival, r.rid)))
        deferred: list = []
        while eng.waiting:
            req = eng.waiting[0]
            free = [s for s, r in enumerate(eng.slot_req) if r is None]
            if not free:
                if not self.preempt_for(eng, req):
                    break                  # no slot, no lower-tier victim
                free = [s for s, r in enumerate(eng.slot_req)
                        if r is None]
            first = min(eng._chunk_for(req), len(req.seq))
            if (eng._pages_held(first)
                    > eng.pool.available - eng._committed_pages()):
                # pool exhausted: a higher tier may still claim pages
                # by preempting the lowest-tier resident
                if self.preempt_for(eng, req):
                    continue
                break                      # hold the queue
            if not eng._fair_share_ok(req, first):
                eng.waiting.popleft()
                deferred.append(req)
                t = getattr(req, "tenant", "default")
                eng.stats.fair_share_deferrals[t] = (
                    eng.stats.fair_share_deferrals.get(t, 0) + 1)
                continue
            eng.waiting.popleft()
            s = free[0]
            req.slot = s
            eng.slot_req[s] = req
            if len(req.seq) > eng.state.capacity:
                # cannot ever fit — fail it loudly rather than wedging
                req.done = True
                self.free_slot(eng, s)
                raise ValueError(
                    f"request {req.rid}: sequence {len(req.seq)} exceeds "
                    f"slot capacity {eng.state.capacity}"
                )
            if eng.pool.prefix_cache and req.cursor == 0:
                eng._attach_prefix(req, s)
        for req in deferred:               # over-share: retry next step
            eng.waiting.append(req)

    # ------------------------------------------------------- row advance

    def advance_cursor(self, eng, s: int, req, take: int) -> int:
        """Move one batched row's cursor past its packed tokens and
        publish newly-frozen pages to the prefix cache. Returns the
        pre-advance cursor."""
        old_cursor = req.cursor
        req.cursor += take
        if eng.pool.prefix_cache:
            eng._register_frozen(req, s, old_cursor)
        return old_cursor

    def complete(self, eng, req, s: int) -> None:
        """Completion check after a row emitted into ``req.generated``;
        frees (or parks, via ``on_complete``) the slot when the request
        reaches its target."""
        target = 1 if eng.cfg.prefill_only else req.max_new
        if len(req.generated) >= target:
            req.completion_step = eng.step_count
            eng.stats.completed += 1
            eng.stats.generated_tokens += len(req.generated)
            if not eng.cfg.prefill_only:
                req.done = True
            if eng.on_complete is None or eng.on_complete(req, s):
                self.free_slot(eng, s)

    def rollback_draft(self, eng, s: int, req, old_cursor: int,
                       take: int, accepted: int) -> None:
        """Speculative rollback: rewind the cursor to the surviving
        prefix (frontier + accepted drafts) and free the pages the
        rejected tail claimed at assembly. Garbage KV above the cursor
        is never attended (kv_lens is recomputed from host cursors) and
        the next append overwrites it in place."""
        req.cursor = old_cursor + 1 + accepted
        keep = eng._pages_held(req.cursor)
        got = eng._pages_held(old_cursor + take)
        for pg in range(keep, got):
            if eng.table[s, pg] >= 0:
                eng.pool.release(int(eng.table[s, pg]))
                eng.table[s, pg] = -1
        if eng.pool.prefix_cache:
            # register AFTER the rewind — only pages below the FINAL
            # cursor are frozen (pure functions of the chained prefix)
            eng._register_frozen(req, s, old_cursor)

    # --------------------------------------------- transactional KV ship

    def reserve_shipped(self, eng, req) -> tuple | None:
        """Claim a slot + landing pages for a request whose first
        ``req.cursor`` tokens of KV will arrive by transfer. Returns
        (slot, page_ids) or None (no slot / pool pressure — the caller
        retries, leaving the source pages pinned)."""
        free = [s for s, r in enumerate(eng.slot_req) if r is None]
        if not free:
            return None
        if len(req.seq) > eng.state.capacity:
            raise ValueError(
                f"request {req.rid}: sequence {len(req.seq)} exceeds "
                f"slot capacity {eng.state.capacity}"
            )
        need = eng._pages_held(req.cursor)
        if (need > eng.pool.available - eng._committed_pages()
                or not eng.pool.can_hold(0, need)):
            return None
        s = free[0]
        pids = []
        for p in range(need):
            pg = eng.pool.alloc(p)
            eng.table[s, p] = pg
            pids.append(int(pg))
        req.slot = s
        req.parked = True
        eng.slot_req[s] = req
        return s, pids

    def commit_shipped(self, eng, req) -> None:
        """The transfer into this request's reserved pages has landed:
        the row becomes schedulable (and evictable) like any other."""
        req.parked = False

    def release_parked(self, eng, slot: int) -> None:
        """Free a parked slot (source-side handoff after its pages have
        shipped, or an abandoned reservation)."""
        req = eng.slot_req[slot]
        assert req is not None and req.parked, (slot, req)
        req.parked = False
        self.free_slot(eng, slot)

    def ship_commit(self, src_eng, pslot: int, dst_eng, req) -> None:
        """Land one ship/migration: handoff order matters (the
        ``_commit_ships`` discipline) — the SOURCE frees its pinned
        pages FIRST, then the row becomes schedulable at the
        destination. The reverse order would leave a window where both
        pools claim the request's KV."""
        self.release_parked(src_eng, pslot)
        self.commit_shipped(dst_eng, req)

    def ship_abort(self, dst_eng, dslot: int, req, pslot: int) -> None:
        """Transport exhausted: roll the destination reservation back
        (landing pages return to the pool) and restore the request to
        its source slot, schedulable in place — the degradation target
        of every ship is finish-where-you-are / re-prefill."""
        self.release_parked(dst_eng, dslot)
        req.slot = pslot
        req.parked = False

    def migrate_live_core(self, req, src_role, dst_role, pslot: int,
                          npg: int, transport):
        """The transactional core of a replica→replica live migration:
        reserve landing pages at the destination, gather+transport the
        committed pages, then commit (source releases first) — or roll
        back on transport exhaustion. Returns None (no reservation —
        try another destination), False (transport failed, rolled
        back), or ``(dslot, dpids)`` on success."""
        got = self.reserve_shipped(dst_role, req)
        if got is None:
            return None                # no slot/pages there; try next
        dslot, dpids = got
        src_pids = [int(p) for p in src_role.table[pslot, :npg]]
        payload = src_role.gather_pages(src_pids)
        shipped = transport(payload)
        if shipped is None:
            # roll the reservation back; the row stays at src and
            # can still finish in place (or requeue on a kill)
            self.ship_abort(dst_role, dslot, req, pslot)
            return False
        dst_role.land_pages(dpids, *shipped)
        self.ship_commit(src_role, pslot, dst_role, req)
        return dslot, dpids

    # ------------------------------------------------- fleet requeue verbs

    def failover_requeue(self, held: list, queue, stats=None) -> list:
        """The ReplicaDeath drain discipline: everything the dead
        replica held re-enters the fleet queue at cursor 0 (the
        recompute-eviction discipline — re-prefilling prompt+generated
        resumes the exact cursor), arrival-ordered at the FRONT — zero
        lost requests, and the request-keyed sampler keeps the streams
        byte-identical."""
        drained = sorted(held, key=lambda r: r.arrival)
        for req in drained:
            if stats is not None:
                stats.failover_re_prefill_tokens += req.cursor
            if req.cursor > 0:
                req.evictions += 1
            req.cursor = 0
            req.slot = None
            req.parked = False
        for req in reversed(drained):
            queue.appendleft(req)
        return drained

    def drain_requeue(self, role, queue) -> list:
        """Planned-drain requeue: one role's queued-but-not-resident
        work re-enters the fleet queue now (residents migrate or finish
        in place)."""
        moved = [r for r in list(role.waiting) + list(role.pending)
                 if not r.done]
        role.waiting.clear()
        role.pending.clear()
        for req in moved:
            req.slot = None
            queue.append(req)
        return moved
