"""Continuous-batching request scheduler over the ragged serving step.

The engine turns the repo's serving ingredients (paged int8 KV pools,
the EP-MoE decode step, the ragged paged-attention kernel) into a
traffic-serving runtime: requests arrive on a trace, are ADMITTED into
slots when the page pool can hold their first chunk, their prompts are
prefilled in CHUNKS interleaved with other requests' decode tokens
(one ragged mixed batch per step — no prefill stall, no rectangle),
and when the pool runs dry mid-decode the lowest-priority request is
EVICTED (pages freed, request re-queued; on re-admission its prompt
*plus everything generated so far* is re-prefilled, so generation
resumes from the exact cursor — the recompute-eviction discipline).

Scheduling model (all host-side, numpy; the device work is ONE jitted
``Transformer.serving_step`` per engine step):

* a step's batch is assembled slot-by-slot under a static
  ``token_budget``: each active slot contributes
  ``min(chunk, remaining_sequence)`` tokens — 1 in steady decode, up
  to ``chunk`` while prefilling — packed at 8-aligned offsets;
* pages for the new tokens are allocated from one shared free list;
  allocation failure triggers eviction (victims: the latest-arrived
  active request not already in this step's batch — LIFO preemption),
  and a row that still cannot get pages is deferred one step;
* per-slot device ``kv_lens`` are zeroed for slots outside the batch,
  so the kernel never walks a deferred row's pages.

Degradation: the first device failure of the Pallas kernel path flips
the engine onto the XLA twin (``use_pallas=False``) and retries — the
``tools/native``-style graceful-degradation story at engine level, so
a fault-plan replay (bench.py --dryrun --faults) exercises scheduling
under chaos without hardware.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np


@dataclass
class Request:
    """One serving request. ``arrival`` is in engine-step units (the
    deterministic clock the tests and the Poisson trace share)."""

    rid: int
    prompt: np.ndarray                 # (L,) int32 token ids
    max_new: int = 8
    arrival: float = 0.0

    # runtime (engine-owned)
    generated: list = field(default_factory=list)
    cursor: int = 0                    # tokens of `seq` already in KV
    slot: int | None = None
    evictions: int = 0
    done: bool = False
    completion_step: int | None = None

    @property
    def seq(self) -> np.ndarray:
        """Every known token of the sequence: prompt + generated. The
        recompute prefix after an eviction IS this — re-prefilling it
        resumes generation from the exact cursor."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)]
        )


@dataclass(frozen=True)
class EngineConfig:
    slots: int = 8                     # concurrent requests (R)
    token_budget: int = 64             # static packed tokens per step (T)
    chunk: int = 16                    # max prefill tokens per row-step
    page: int = 16
    npages: int = 64
    max_steps: int = 10_000


@dataclass
class EngineStats:
    step_times: list = field(default_factory=list)
    step_tokens: list = field(default_factory=list)
    completed: int = 0
    generated_tokens: int = 0
    prefill_tokens: int = 0
    evictions: int = 0
    deferrals: int = 0
    degraded: bool = False

    @property
    def total_time(self) -> float:
        return float(sum(self.step_times))

    @property
    def sustained_tok_per_s(self) -> float:
        t = self.total_time
        return (sum(self.step_tokens) / t) if t > 0 else 0.0

    @property
    def goodput_tok_per_s(self) -> float:
        """GENERATED tokens of completed requests per wall second — the
        metric padding cannot inflate (prefill re-computation after an
        eviction, padded rectangle slots, and abandoned work all count
        against it)."""
        t = self.total_time
        return (self.generated_tokens / t) if t > 0 else 0.0

    @property
    def p99_step_ms(self) -> float:
        if not self.step_times:
            return 0.0
        return float(np.percentile(np.asarray(self.step_times), 99) * 1e3)

    @property
    def p50_step_ms(self) -> float:
        if not self.step_times:
            return 0.0
        return float(np.percentile(np.asarray(self.step_times), 50) * 1e3)


def poisson_trace(seed: int, n_requests: int, mean_interarrival: float,
                  len_lo: int, len_hi: int, max_new_lo: int,
                  max_new_hi: int, vocab: int) -> list:
    """Seeded Poisson arrival trace: exponential inter-arrival gaps (in
    engine-step units), prompt lengths ~ U[len_lo, len_hi) — the
    ISSUE-6 traffic shape (lengths ~U[S/8, 3S/4]) — and uniform
    max_new. Deterministic under ``seed``."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for i in range(n_requests):
        t += float(rng.exponential(mean_interarrival))
        ln = int(rng.integers(len_lo, max(len_hi, len_lo + 1)))
        out.append(Request(
            rid=i,
            prompt=rng.integers(0, vocab, (ln,)).astype(np.int32),
            max_new=int(rng.integers(max_new_lo, max(max_new_hi,
                                                     max_new_lo + 1))),
            arrival=t,
        ))
    return out


def _ceil8(x: int) -> int:
    return -(-x // 8) * 8


class ServingEngine:
    """The scheduler. Owns the host mirrors (free list, block table,
    lengths, cursors) and the device :class:`ServingState`; every
    :meth:`step` assembles one ragged batch and runs one jitted
    ``model.serving_step``."""

    def __init__(self, model, params, cfg: EngineConfig, *,
                 moe_state="auto", use_pallas: bool = True):
        import jax.numpy as jnp

        self.model = model
        self.params = params
        self.cfg = cfg
        self.use_pallas = use_pallas
        self.state = model.init_serving_state(
            cfg.slots, cfg.npages, cfg.page
        )
        self._jnp = jnp
        pps = self.state.pages_per_seq
        self.table = np.full((cfg.slots, pps), -1, np.int32)
        self.free_pages = list(range(cfg.npages - 1, -1, -1))
        self.slot_req: list = [None] * cfg.slots
        self.pending: deque = deque()      # not yet arrived (by time)
        self.waiting: deque = deque()      # arrived, not admitted
        self.stats = EngineStats()
        self.step_count = 0
        g = model.config.n_heads // model.config.n_kv_heads
        self._g = g
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            auto_block_q,
        )

        self._block_q_cap = auto_block_q(cfg.chunk, g)
        # the packed array carries a PARKING zone of block_q_cap tokens
        # past the budget: rows outside the batch (q_len == 0) park
        # their garbage writes there, where no valid span can be
        # clobbered by the kernel's sequential out DMAs
        self._t_pad = cfg.token_budget + self._block_q_cap
        # LL MoE workspaces sized to the PACKED step width (None when
        # the model has no fused-transport EP layers)
        self.moe_state = (
            model.init_decode_state(self._t_pad)
            if moe_state == "auto" else moe_state
        )
        if cfg.token_budget % 8:
            raise ValueError("token_budget must be 8-aligned")
        if cfg.chunk > cfg.token_budget:
            raise ValueError(
                f"chunk={cfg.chunk} exceeds token_budget="
                f"{cfg.token_budget}"
            )

    # ------------------------------------------------------------ requests

    def submit(self, req: Request) -> None:
        self.pending.append(req)

    def submit_trace(self, trace) -> None:
        for r in sorted(trace, key=lambda r: r.arrival):
            self.submit(r)

    @property
    def idle(self) -> bool:
        return (not self.pending and not self.waiting
                and all(r is None for r in self.slot_req))

    # ----------------------------------------------------------- allocator

    def _pages_held(self, cursor: int) -> int:
        return -(-cursor // self.cfg.page)

    def _alloc(self, slot: int, held: int, need: int) -> bool:
        """Grow slot's table from ``held`` to ``need`` pages; all-or-
        nothing (no partial growth to unwind)."""
        if need - held > len(self.free_pages):
            return False
        for pg in range(held, need):
            self.table[slot, pg] = self.free_pages.pop()
        return True

    def _free_slot(self, slot: int) -> None:
        for pg in self.table[slot]:
            if pg >= 0:
                self.free_pages.append(int(pg))
        self.table[slot] = -1
        self.slot_req[slot] = None

    def _evict_one(self, batched: set) -> bool:
        """Evict the latest-arrived active request not already in this
        step's batch (LIFO preemption); its pages return to the free
        list and the request re-queues AT THE FRONT with cursor 0 — the
        recompute prefix (prompt + generated) resumes it exactly."""
        victims = [
            (req.arrival, s) for s, req in enumerate(self.slot_req)
            if req is not None and s not in batched
        ]
        if not victims:
            return False
        _, s = max(victims)
        req = self.slot_req[s]
        req.cursor = 0
        req.evictions += 1
        req.slot = None
        self._free_slot(s)
        self.waiting.appendleft(req)
        self.stats.evictions += 1
        return True

    # ---------------------------------------------------------------- step

    def _committed_pages(self) -> int:
        """Pages the already-admitted slots will claim for their NEXT
        chunk but have not allocated yet — admission must not promise
        them away (allocation happens at batch assembly)."""
        tot = 0
        for req in self.slot_req:
            if req is None:
                continue
            take = min(self.cfg.chunk, len(req.seq) - req.cursor)
            tot += max(
                self._pages_held(req.cursor + take)
                - self._pages_held(req.cursor), 0,
            )
        return tot

    def _admit(self) -> None:
        while self.pending and self.pending[0].arrival <= self.step_count:
            self.waiting.append(self.pending.popleft())
        while self.waiting:
            free = [s for s, r in enumerate(self.slot_req) if r is None]
            if not free:
                return
            req = self.waiting[0]
            first = min(self.cfg.chunk, len(req.seq))
            if (self._pages_held(first)
                    > len(self.free_pages) - self._committed_pages()):
                return                     # pool exhausted — hold the queue
            self.waiting.popleft()
            s = free[0]
            req.slot = s
            self.slot_req[s] = req
            if len(req.seq) > self.state.capacity:
                # cannot ever fit — fail it loudly rather than wedging
                req.done = True
                self._free_slot(s)
                raise ValueError(
                    f"request {req.rid}: sequence {len(req.seq)} exceeds "
                    f"slot capacity {self.state.capacity}"
                )

    def _assemble(self):
        cfg = self.cfg
        R, T = cfg.slots, self._t_pad
        tokens = np.zeros((T,), np.int32)
        token_rows = np.zeros((T,), np.int32)
        token_pos = np.full((T,), -1, np.int32)
        # inactive slots PARK their garbage output block past the
        # budget (see __init__) — never over another row's valid span
        q_starts = np.full((R,), cfg.token_budget, np.int32)
        q_lens = np.zeros((R,), np.int32)
        kv_dev = np.zeros((R,), np.int32)
        next_start = 0
        batched: set = set()
        takes: dict = {}
        for s in range(R):
            req = self.slot_req[s]
            if req is None:
                continue
            seq = req.seq
            take = min(cfg.chunk, len(seq) - req.cursor)
            if take <= 0:
                continue
            if next_start + _ceil8(take) > cfg.token_budget:
                self.stats.deferrals += 1
                continue                   # token budget spent
            held = self._pages_held(req.cursor)
            need = self._pages_held(req.cursor + take)
            while not self._alloc(s, held, need):
                if not self._evict_one(batched | {s}):
                    break
            else:
                # allocation succeeded
                span = slice(next_start, next_start + take)
                tokens[span] = seq[req.cursor:req.cursor + take]
                token_rows[span] = s
                token_pos[span] = np.arange(
                    req.cursor, req.cursor + take, dtype=np.int32
                )
                q_starts[s] = next_start
                q_lens[s] = take
                kv_dev[s] = req.cursor + take
                next_start += _ceil8(take)
                batched.add(s)
                takes[s] = take
                continue
            # page allocation failed even after eviction: defer the row
            self.stats.deferrals += 1
        return (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
                batched, takes)

    def _run_device(self, arrays, block_q):
        jnp = self._jnp
        tokens, token_rows, token_pos, q_starts, q_lens, kv_dev = arrays
        state = self.state.replace(
            block_table=jnp.asarray(self.table),
            kv_lens=jnp.asarray(kv_dev),
            cursors=jnp.asarray(
                [0 if r is None else r.cursor for r in self.slot_req],
                dtype=jnp.int32,
            ),
        )
        out = self.model._serving_jit(
            self.params, state, jnp.asarray(tokens),
            jnp.asarray(token_rows), jnp.asarray(token_pos),
            jnp.asarray(q_starts), jnp.asarray(q_lens),
            self.moe_state, block_q, self.use_pallas,
        )
        if self.moe_state is None:
            logits, self.state = out
        else:
            logits, self.state, self.moe_state = out
        return np.asarray(logits)          # host fetch = the fence

    def step(self) -> dict:
        """One engine step: admit → assemble → device step → advance
        cursors/completions. Returns a small per-step report."""
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            auto_block_q,
        )

        self._admit()
        (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
         batched, takes) = self._assemble()
        report = {"step": self.step_count, "batched": len(batched),
                  "tokens": int(q_lens.sum())}
        if not batched:
            self.step_count += 1
            return report
        block_q = auto_block_q(int(q_lens.max()), self._g)
        t0 = time.perf_counter()
        arrays = (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev)
        try:
            logits = self._run_device(arrays, block_q)
        except Exception:
            if not self.use_pallas:
                raise
            # degradation: fall back to the XLA twin for the rest of
            # the session (the op-level with_fallback story at engine
            # level) — scheduling state is untouched, re-run the batch
            self.use_pallas = False
            self.stats.degraded = True
            logits = self._run_device(arrays, block_q)
        dt = time.perf_counter() - t0
        nxt = np.argmax(logits, axis=-1).astype(np.int32)
        gen_this_step = 0
        for s in sorted(batched):
            req = self.slot_req[s]
            take = takes[s]
            req.cursor += take
            if req.cursor == len(req.seq):
                # the row's last packed token was its sequence frontier:
                # the logits row is the next-token distribution
                tok = int(nxt[s])
                req.generated.append(tok)
                gen_this_step += 1
                if len(req.generated) >= req.max_new:
                    req.done = True
                    req.completion_step = self.step_count
                    self.stats.completed += 1
                    self.stats.generated_tokens += len(req.generated)
                    self._free_slot(s)
        self.stats.step_times.append(dt)
        self.stats.step_tokens.append(int(q_lens.sum()))
        self.stats.prefill_tokens += int(q_lens.sum()) - gen_this_step
        report.update(
            ms=round(dt * 1e3, 3), generated=gen_this_step,
            free_pages=len(self.free_pages),
            waiting=len(self.waiting) + len(self.pending),
        )
        self.step_count += 1
        return report

    def run(self, trace=None, max_steps: int | None = None) -> EngineStats:
        """Drive the engine until the trace drains (or ``max_steps``)."""
        if trace is not None:
            self.submit_trace(trace)
        max_steps = max_steps or self.cfg.max_steps
        for _ in range(max_steps):
            if self.idle:
                break
            self.step()
        return self.stats
